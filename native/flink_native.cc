// flink_tpu native runtime layer (C ABI, loaded via ctypes).
//
// TPU-native equivalents of the reference's native-performance components
// (SURVEY §2.6): the Cython fast coders (pyflink/fn_execution/*_fast.pyx)
// become the varint/block codec here; the JNI LZ4 buffer compression
// (runtime/io/compression/BufferCompressor.java) becomes the FLZ block
// compressor; the RocksDB JNI keyed-state spill tier
// (flink-state-backends/flink-statebackend-rocksdb) becomes SpillStore — an
// in-memory hash index over an append-only value log with a memory budget,
// eviction to disk, manifest-based persistence and compaction; the Netty
// off-heap buffer ring becomes the SPSC byte ring buffer used by host infeed.
//
// Everything is original code written for this framework; formats are custom
// ("FLZ1" block format, "FSP1" manifest) — no wire compatibility with the
// reference is intended or needed.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#if defined(_WIN32)
#error "POSIX only"
#endif
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#define API extern "C" __attribute__((visibility("default")))

typedef int64_t i64;
typedef uint64_t u64;
typedef int32_t i32;
typedef uint32_t u32;
typedef uint8_t u8;

// ---------------------------------------------------------------------------
// varint / zigzag (delta codec for sorted int64 columns: timestamps, keys)
// ---------------------------------------------------------------------------

static inline u64 zigzag_enc(i64 v) { return ((u64)v << 1) ^ (u64)(v >> 63); }
static inline i64 zigzag_dec(u64 v) { return (i64)(v >> 1) ^ -(i64)(v & 1); }

static inline size_t varint_put(u8* out, u64 v) {
  size_t i = 0;
  while (v >= 0x80) { out[i++] = (u8)(v | 0x80); v >>= 7; }
  out[i++] = (u8)v;
  return i;
}

static inline size_t varint_get(const u8* in, const u8* end, u64* v) {
  u64 r = 0; int shift = 0; size_t i = 0;
  while (in + i < end) {
    u8 b = in[i++];
    r |= (u64)(b & 0x7f) << shift;
    if (!(b & 0x80)) { *v = r; return i; }
    shift += 7;
    if (shift > 63) break;
  }
  return 0;  // malformed
}

// Delta + zigzag + varint encode. Returns bytes written, or -1 if cap too
// small. Worst case 10 bytes/value.
API i64 fn_delta_varint_encode_i64(const i64* vals, i64 n, u8* out, i64 cap) {
  i64 w = 0, prev = 0;
  for (i64 i = 0; i < n; i++) {
    if (w + 10 > cap) return -1;
    w += (i64)varint_put(out + w, zigzag_enc(vals[i] - prev));
    prev = vals[i];
  }
  return w;
}

// Returns bytes consumed, or -1 on malformed input.
API i64 fn_delta_varint_decode_i64(const u8* in, i64 nbytes, i64 n, i64* out) {
  const u8* end = in + nbytes;
  i64 r = 0, prev = 0;
  for (i64 i = 0; i < n; i++) {
    u64 v;
    size_t c = varint_get(in + r, end, &v);
    if (c == 0) return -1;
    r += (i64)c;
    prev += zigzag_dec(v);
    out[i] = prev;
  }
  return r;
}

// ---------------------------------------------------------------------------
// FLZ block compression (LZ77, byte-oriented, format "FLZ1")
//
// Sequence = token byte (hi nibble literal-run len, lo nibble match len - 4,
// 15 => varint extension follows), literals, u16le offset, [ext match len].
// Final sequence carries literals only (match nibble unused, no offset).
// ---------------------------------------------------------------------------

static const int FLZ_HASH_LOG = 15;
static const u32 FLZ_MIN_MATCH = 4;

static inline u32 flz_hash(u32 seq) {
  return (seq * 2654435761u) >> (32 - FLZ_HASH_LOG);
}

static inline u32 read32(const u8* p) { u32 v; memcpy(&v, p, 4); return v; }

API i64 fn_lz_bound(i64 n) { return n + n / 255 + 80; }

// Compress src[0..n) into dst (cap >= fn_lz_bound(n)). Returns compressed
// size, or -1 on cap overflow.
API i64 fn_lz_compress(const u8* src, i64 n, u8* dst, i64 cap) {
  std::vector<i64> table((size_t)1 << FLZ_HASH_LOG, -1);
  i64 ip = 0, anchor = 0, op = 0;
  const i64 mflimit = n - (i64)FLZ_MIN_MATCH;

  auto emit = [&](i64 lit_len, i64 match_len, i64 offset, bool final_seq) -> bool {
    // worst-case bytes for this sequence (varint extensions are <= 10 bytes)
    i64 need = 1 + lit_len + (lit_len >= 15 ? 10 : 0) + 12;
    if (op + need > cap) return false;
    u8* token = dst + op++;
    i64 ml = final_seq ? 0 : match_len - FLZ_MIN_MATCH;
    *token = (u8)(((lit_len < 15 ? lit_len : 15) << 4) |
                  (ml < 15 ? ml : 15));
    if (lit_len >= 15) op += (i64)varint_put(dst + op, (u64)(lit_len - 15));
    memcpy(dst + op, src + anchor, (size_t)lit_len);
    op += lit_len;
    if (!final_seq) {
      dst[op++] = (u8)(offset & 0xff);
      dst[op++] = (u8)(offset >> 8);
      if (ml >= 15) op += (i64)varint_put(dst + op, (u64)(ml - 15));
    }
    return true;
  };

  while (ip <= mflimit) {
    u32 h = flz_hash(read32(src + ip));
    i64 cand = table[h];
    table[h] = ip;
    if (cand >= 0 && ip - cand <= 0xffff && read32(src + cand) == read32(src + ip)) {
      // extend match
      i64 ml = FLZ_MIN_MATCH;
      while (ip + ml < n && src[cand + ml] == src[ip + ml]) ml++;
      if (!emit(ip - anchor, ml, ip - cand, false)) return -1;
      // index interior positions sparsely for better ratio on long matches
      for (i64 p = ip + 1; p + 4 <= ip + ml && p <= mflimit; p += 3)
        table[flz_hash(read32(src + p))] = p;
      ip += ml;
      anchor = ip;
    } else {
      ip++;
    }
  }
  if (!emit(n - anchor, 0, 0, true)) return -1;
  return op;
}

// Decompress into dst of exactly orig_n bytes. Returns orig_n, or -1 on
// malformed input.
API i64 fn_lz_decompress(const u8* src, i64 n, u8* dst, i64 orig_n) {
  const u8* end = src + n;
  i64 ip = 0, op = 0;
  while (ip < n) {
    u8 token = src[ip++];
    i64 lit = token >> 4;
    if (lit == 15) {
      u64 ext; size_t c = varint_get(src + ip, end, &ext);
      if (!c) return -1;
      ip += (i64)c; lit = 15 + (i64)ext;
    }
    if (ip + lit > n || op + lit > orig_n) return -1;
    memcpy(dst + op, src + ip, (size_t)lit);
    ip += lit; op += lit;
    if (ip >= n) break;  // final literals-only sequence
    if (ip + 2 > n) return -1;
    i64 offset = src[ip] | ((i64)src[ip + 1] << 8);
    ip += 2;
    i64 ml = (token & 0x0f);
    if (ml == 15) {
      u64 ext; size_t c = varint_get(src + ip, end, &ext);
      if (!c) return -1;
      ip += (i64)c; ml = 15 + (i64)ext;
    }
    ml += FLZ_MIN_MATCH;
    if (offset == 0 || offset > op || op + ml > orig_n) return -1;
    // byte-wise copy: overlapping matches are the RLE case and must copy fwd
    for (i64 k = 0; k < ml; k++) dst[op + k] = dst[op + k - offset];
    op += ml;
  }
  return op == orig_n ? orig_n : -1;
}

// ---------------------------------------------------------------------------
// CRC32 (IEEE, table-driven) — checkpoint/log record integrity
// ---------------------------------------------------------------------------

static u32 crc_table[256];
static std::once_flag crc_once;

static void crc_init() {
  for (u32 i = 0; i < 256; i++) {
    u32 c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xedb88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
}

API u32 fn_crc32(const u8* data, i64 n, u32 seed) {
  std::call_once(crc_once, crc_init);
  u32 c = seed ^ 0xffffffffu;
  for (i64 i = 0; i < n; i++) c = crc_table[(c ^ data[i]) & 0xff] ^ (c >> 8);
  return c ^ 0xffffffffu;
}

// CRC32C (Castagnoli, reflected poly 0x82F63B78) — the checksum of Kafka's
// v2 record batches; slice-by-4 tables.
static u32 crc32c_table[4][256];
static std::once_flag crc32c_once;

static void crc32c_init() {
  for (u32 i = 0; i < 256; i++) {
    u32 c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0x82F63B78u ^ (c >> 1) : c >> 1;
    crc32c_table[0][i] = c;
  }
  for (u32 i = 0; i < 256; i++)
    for (int t = 1; t < 4; t++)
      crc32c_table[t][i] =
          crc32c_table[t - 1][i] >> 8 ^
          crc32c_table[0][crc32c_table[t - 1][i] & 0xff];
}

API u32 fn_crc32c(const u8* data, i64 n, u32 seed) {
  std::call_once(crc32c_once, crc32c_init);
  u32 c = seed ^ 0xffffffffu;
  i64 i = 0;
  for (; i + 4 <= n; i += 4) {
    c ^= (u32)data[i] | ((u32)data[i + 1] << 8) | ((u32)data[i + 2] << 16) |
         ((u32)data[i + 3] << 24);
    c = crc32c_table[3][c & 0xff] ^ crc32c_table[2][(c >> 8) & 0xff] ^
        crc32c_table[1][(c >> 16) & 0xff] ^ crc32c_table[0][c >> 24];
  }
  for (; i < n; i++)
    c = crc32c_table[0][(c ^ data[i]) & 0xff] ^ (c >> 8);
  return c ^ 0xffffffffu;
}

// ---------------------------------------------------------------------------
// SpillStore — memory-budgeted KV tier with append-only disk log
// (RocksDB-analog behind the keyed-state spill interface)
// ---------------------------------------------------------------------------

namespace {

struct Entry {
  std::string val;   // when resident
  bool in_mem;
  i64 off;           // log offset of the record's value payload (when spilled)
  u32 len;           // value length
};

struct SpillStore {
  std::string dir;
  i64 mem_budget;
  i64 mem_used = 0;       // resident value bytes
  i64 log_end = 0;        // append position
  i64 log_garbage = 0;    // dead value bytes in log
  FILE* log = nullptr;
  std::unordered_map<std::string, Entry> map;
  std::mutex mu;
  // insertion clock for eviction (approx-LRU: evict oldest-written first)
  std::vector<std::string> write_order;
  size_t evict_cursor = 0;

  std::string log_path() const { return dir + "/spill.log"; }
  std::string manifest_path() const { return dir + "/manifest.fsp"; }
};

// log record: [crc u32][klen u32][vlen u32][key][value]
static bool log_append(SpillStore* s, const std::string& key,
                       const std::string& val, i64* val_off) {
  u32 klen = (u32)key.size(), vlen = (u32)val.size();
  u32 crc = fn_crc32((const u8*)key.data(), klen, 0);
  crc = fn_crc32((const u8*)val.data(), vlen, crc);
  if (fseeko(s->log, s->log_end, SEEK_SET) != 0) return false;
  if (fwrite(&crc, 4, 1, s->log) != 1) return false;
  if (fwrite(&klen, 4, 1, s->log) != 1) return false;
  if (fwrite(&vlen, 4, 1, s->log) != 1) return false;
  if (klen && fwrite(key.data(), 1, klen, s->log) != klen) return false;
  if (vlen && fwrite(val.data(), 1, vlen, s->log) != vlen) return false;
  *val_off = s->log_end + 12 + klen;
  s->log_end += 12 + klen + vlen;
  return true;
}

// Read a spilled value and verify the record CRC (record layout puts the crc
// at off - 12 - klen; the crc covers key bytes then value bytes).
static bool log_read(SpillStore* s, i64 off, u32 len, const std::string& key,
                     std::string* out) {
  out->resize(len);
  fflush(s->log);
  FILE* f = fopen(s->log_path().c_str(), "rb");
  if (!f) return false;
  i64 rec_start = off - 12 - (i64)key.size();
  u32 stored_crc = 0;
  bool ok = rec_start >= 0 && fseeko(f, rec_start, SEEK_SET) == 0 &&
            fread(&stored_crc, 4, 1, f) == 1 &&
            fseeko(f, off, SEEK_SET) == 0 &&
            (len == 0 || fread(&(*out)[0], 1, len, f) == len);
  fclose(f);
  if (!ok) return false;
  u32 crc = fn_crc32((const u8*)key.data(), (i64)key.size(), 0);
  crc = fn_crc32((const u8*)out->data(), len, crc);
  return crc == stored_crc;
}

static void maybe_evict(SpillStore* s) {
  while (s->mem_used > s->mem_budget) {
    if (s->evict_cursor >= s->write_order.size()) {
      // Updated keys re-enter residency without re-entering write_order, so
      // one pass is not enough: rebuild the queue from currently-resident
      // keys. Empty rebuild == nothing evictable -> stop.
      s->write_order.clear();
      for (auto& kv : s->map)
        if (kv.second.in_mem) s->write_order.push_back(kv.first);
      s->evict_cursor = 0;
      if (s->write_order.empty()) return;
    }
    const std::string& k = s->write_order[s->evict_cursor++];
    auto it = s->map.find(k);
    if (it == s->map.end() || !it->second.in_mem) continue;
    i64 off;
    if (!log_append(s, k, it->second.val, &off)) return;
    s->mem_used -= (i64)it->second.val.size();
    it->second.in_mem = false;
    it->second.off = off;
    it->second.len = (u32)it->second.val.size();
    it->second.val.clear();
    it->second.val.shrink_to_fit();
  }
}

}  // namespace

API void* spill_open(const char* dir, i64 mem_budget) {
  auto* s = new SpillStore();
  s->dir = dir;
  s->mem_budget = mem_budget;
  mkdir(dir, 0755);
  // load manifest if present (reopen after flush)
  FILE* mf = fopen(s->manifest_path().c_str(), "rb");
  if (mf) {
    char magic[4];
    u64 n = 0;
    if (fread(magic, 1, 4, mf) == 4 && memcmp(magic, "FSP1", 4) == 0 &&
        fread(&n, 8, 1, mf) == 1) {
      for (u64 i = 0; i < n; i++) {
        u32 klen; u8 flag;
        if (fread(&klen, 4, 1, mf) != 1 || fread(&flag, 1, 1, mf) != 1) break;
        std::string key(klen, '\0');
        if (klen && fread(&key[0], 1, klen, mf) != klen) break;
        Entry e;
        if (flag) {  // resident in manifest
          u32 vlen;
          if (fread(&vlen, 4, 1, mf) != 1) break;
          e.val.resize(vlen);
          if (vlen && fread(&e.val[0], 1, vlen, mf) != vlen) break;
          e.in_mem = true; e.off = 0; e.len = vlen;
          s->mem_used += vlen;
        } else {
          i64 off; u32 vlen;
          if (fread(&off, 8, 1, mf) != 1 || fread(&vlen, 4, 1, mf) != 1) break;
          e.in_mem = false; e.off = off; e.len = vlen;
        }
        s->write_order.push_back(key);
        s->map.emplace(std::move(key), std::move(e));
      }
    }
    fclose(mf);
  }
  s->log = fopen(s->log_path().c_str(), "ab+");
  if (!s->log) { delete s; return nullptr; }
  fseeko(s->log, 0, SEEK_END);
  s->log_end = ftello(s->log);
  return s;
}

API int spill_put(void* h, const u8* key, i64 klen, const u8* val, i64 vlen) {
  auto* s = (SpillStore*)h;
  std::lock_guard<std::mutex> g(s->mu);
  std::string k((const char*)key, (size_t)klen);
  auto it = s->map.find(k);
  if (it != s->map.end()) {
    if (it->second.in_mem) s->mem_used -= (i64)it->second.val.size();
    else s->log_garbage += it->second.len;
    it->second.val.assign((const char*)val, (size_t)vlen);
    it->second.in_mem = true;
    it->second.len = (u32)vlen;
  } else {
    Entry e;
    e.val.assign((const char*)val, (size_t)vlen);
    e.in_mem = true; e.off = 0; e.len = (u32)vlen;
    s->map.emplace(k, std::move(e));
    s->write_order.push_back(k);
  }
  s->mem_used += vlen;
  maybe_evict(s);
  return 0;
}

// Returns value length (copy into out up to cap), or -1 if absent, -2 on IO
// error. Call with cap=0 to size-probe.
API i64 spill_get(void* h, const u8* key, i64 klen, u8* out, i64 cap) {
  auto* s = (SpillStore*)h;
  std::lock_guard<std::mutex> g(s->mu);
  std::string k((const char*)key, (size_t)klen);
  auto it = s->map.find(k);
  if (it == s->map.end()) return -1;
  if (it->second.in_mem) {
    i64 n = (i64)it->second.val.size();
    if (out && cap >= n) memcpy(out, it->second.val.data(), (size_t)n);
    return n;
  }
  if (out == nullptr || cap < (i64)it->second.len) return it->second.len;
  std::string v;
  if (!log_read(s, it->second.off, it->second.len, k, &v)) return -2;
  memcpy(out, v.data(), v.size());
  return (i64)v.size();
}

API int spill_delete(void* h, const u8* key, i64 klen) {
  auto* s = (SpillStore*)h;
  std::lock_guard<std::mutex> g(s->mu);
  std::string k((const char*)key, (size_t)klen);
  auto it = s->map.find(k);
  if (it == s->map.end()) return 0;
  if (it->second.in_mem) s->mem_used -= (i64)it->second.val.size();
  else s->log_garbage += it->second.len;
  s->map.erase(it);
  return 1;
}

API i64 spill_count(void* h) {
  auto* s = (SpillStore*)h;
  std::lock_guard<std::mutex> g(s->mu);
  return (i64)s->map.size();
}

API i64 spill_mem_used(void* h) { return ((SpillStore*)h)->mem_used; }
API i64 spill_log_bytes(void* h) { return ((SpillStore*)h)->log_end; }
API i64 spill_log_garbage(void* h) { return ((SpillStore*)h)->log_garbage; }

// Iteration: caller passes cursor index; returns key length and fills key
// buffer. Cursor walks the hash map snapshot taken at iter_begin.
struct SpillIter {
  std::vector<std::string> keys;
  size_t pos = 0;
};

API void* spill_iter_begin(void* h) {
  auto* s = (SpillStore*)h;
  std::lock_guard<std::mutex> g(s->mu);
  auto* it = new SpillIter();
  it->keys.reserve(s->map.size());
  for (auto& kv : s->map) it->keys.push_back(kv.first);
  return it;
}

API i64 spill_iter_next(void* hi, u8* key_out, i64 cap) {
  auto* it = (SpillIter*)hi;
  if (it->pos >= it->keys.size()) return -1;
  const std::string& k = it->keys[it->pos];
  if ((i64)k.size() > cap) return (i64)k.size();  // probe: not advanced
  memcpy(key_out, k.data(), k.size());
  it->pos++;
  return (i64)k.size();
}

API void spill_iter_end(void* hi) { delete (SpillIter*)hi; }

// Durably persist: fsync log + write manifest atomically. The manifest holds
// resident values inline and spilled values as (off, len) into the log.
// Caller must hold s->mu.
static int flush_locked(SpillStore* s) {
  fflush(s->log);
  fsync(fileno(s->log));
  std::string tmp = s->manifest_path() + ".tmp";
  FILE* mf = fopen(tmp.c_str(), "wb");
  if (!mf) return -1;
  u64 n = s->map.size();
  fwrite("FSP1", 1, 4, mf);
  fwrite(&n, 8, 1, mf);
  for (auto& kv : s->map) {
    u32 klen = (u32)kv.first.size();
    u8 flag = kv.second.in_mem ? 1 : 0;
    fwrite(&klen, 4, 1, mf);
    fwrite(&flag, 1, 1, mf);
    fwrite(kv.first.data(), 1, klen, mf);
    if (flag) {
      u32 vlen = (u32)kv.second.val.size();
      fwrite(&vlen, 4, 1, mf);
      fwrite(kv.second.val.data(), 1, vlen, mf);
    } else {
      fwrite(&kv.second.off, 8, 1, mf);
      fwrite(&kv.second.len, 4, 1, mf);
    }
  }
  fflush(mf);
  fsync(fileno(mf));
  fclose(mf);
  return rename(tmp.c_str(), s->manifest_path().c_str());
}

API int spill_flush(void* h) {
  auto* s = (SpillStore*)h;
  std::lock_guard<std::mutex> g(s->mu);
  return flush_locked(s);
}

// Rewrite the log keeping only live spilled values (incremental-checkpoint
// hygiene, the RocksDB-compaction analog). Returns reclaimed bytes.
API i64 spill_compact(void* h) {
  auto* s = (SpillStore*)h;
  std::lock_guard<std::mutex> g(s->mu);
  std::string tmp = s->log_path() + ".tmp";
  FILE* nf = fopen(tmp.c_str(), "wb");
  if (!nf) return -1;
  i64 old_end = s->log_end;
  fflush(s->log);
  i64 new_end = 0;
  bool ok = true;
  // collect new offsets first; commit them only after the rename succeeds
  std::vector<std::pair<Entry*, i64>> new_offs;
  for (auto& kv : s->map) {
    if (kv.second.in_mem) continue;
    std::string v;
    if (!log_read(s, kv.second.off, kv.second.len, kv.first, &v)) {
      ok = false;
      break;
    }
    u32 klen = (u32)kv.first.size(), vlen = (u32)v.size();
    u32 crc = fn_crc32((const u8*)kv.first.data(), klen, 0);
    crc = fn_crc32((const u8*)v.data(), vlen, crc);
    fwrite(&crc, 4, 1, nf);
    fwrite(&klen, 4, 1, nf);
    fwrite(&vlen, 4, 1, nf);
    fwrite(kv.first.data(), 1, klen, nf);
    fwrite(v.data(), 1, vlen, nf);
    new_offs.emplace_back(&kv.second, new_end + 12 + klen);
    new_end += 12 + klen + vlen;
  }
  fflush(nf);
  fclose(nf);
  if (!ok) { remove(tmp.c_str()); return -1; }
  fclose(s->log);
  s->log = nullptr;
  if (rename(tmp.c_str(), s->log_path().c_str()) != 0) {
    // old log file is still in place and offsets unchanged: reopen and bail
    s->log = fopen(s->log_path().c_str(), "ab+");
    if (s->log) fseeko(s->log, 0, SEEK_END);
    remove(tmp.c_str());
    return -1;
  }
  for (auto& [entry, off] : new_offs) entry->off = off;
  s->log = fopen(s->log_path().c_str(), "ab+");
  fseeko(s->log, 0, SEEK_END);
  s->log_end = new_end;
  s->log_garbage = 0;
  // eviction bookkeeping restarts over current keys
  s->write_order.clear();
  for (auto& kv : s->map)
    if (kv.second.in_mem) s->write_order.push_back(kv.first);
  s->evict_cursor = 0;
  // the on-disk manifest (if any) points at pre-compaction offsets — rewrite
  // it, or a reopen after crash would read wrong values from the new log
  if (access(s->manifest_path().c_str(), F_OK) == 0) flush_locked(s);
  return old_end - new_end;
}

API void spill_close(void* h) {
  auto* s = (SpillStore*)h;
  if (s->log) fclose(s->log);
  delete s;
}

// ---------------------------------------------------------------------------
// SPSC byte ring buffer — host infeed path (Netty buffer-pool analog)
// ---------------------------------------------------------------------------

namespace {
struct Ring {
  std::vector<u8> buf;
  std::atomic<u64> head{0};  // producer position (bytes written)
  std::atomic<u64> tail{0};  // consumer position (bytes read)
  u64 cap;
};

static void ring_copy_in(Ring* r, u64 pos, const u8* src, u64 n) {
  u64 off = pos % r->cap;
  u64 first = std::min(n, r->cap - off);
  memcpy(r->buf.data() + off, src, first);
  if (n > first) memcpy(r->buf.data(), src + first, n - first);
}

static void ring_copy_out(Ring* r, u64 pos, u8* dst, u64 n) {
  u64 off = pos % r->cap;
  u64 first = std::min(n, r->cap - off);
  memcpy(dst, r->buf.data() + off, first);
  if (n > first) memcpy(dst + first, r->buf.data(), n - first);
}
}  // namespace

API void* ring_create(i64 capacity) {
  auto* r = new Ring();
  r->cap = (u64)capacity;
  r->buf.resize(r->cap);
  return r;
}

API i64 ring_free_space(void* h) {
  auto* r = (Ring*)h;
  return (i64)(r->cap - (r->head.load(std::memory_order_acquire) -
                         r->tail.load(std::memory_order_acquire)));
}

// Push one length-prefixed message. Returns 1 on success, 0 if no room.
API int ring_push(void* h, const u8* data, i64 n) {
  auto* r = (Ring*)h;
  u64 need = (u64)n + 4;
  u64 head = r->head.load(std::memory_order_relaxed);
  u64 tail = r->tail.load(std::memory_order_acquire);
  if (r->cap - (head - tail) < need) return 0;
  u32 len = (u32)n;
  ring_copy_in(r, head, (const u8*)&len, 4);
  ring_copy_in(r, head + 4, data, (u64)n);
  r->head.store(head + need, std::memory_order_release);
  return 1;
}

// Pop one message into out (cap bytes). Returns message length, -1 if empty,
// or required length if cap too small (message left in place).
API i64 ring_pop(void* h, u8* out, i64 cap) {
  auto* r = (Ring*)h;
  u64 tail = r->tail.load(std::memory_order_relaxed);
  u64 head = r->head.load(std::memory_order_acquire);
  if (head == tail) return -1;
  u32 len;
  ring_copy_out(r, tail, (u8*)&len, 4);
  if ((i64)len > cap) return (i64)len;
  ring_copy_out(r, tail + 4, out, len);
  r->tail.store(tail + 4 + len, std::memory_order_release);
  return (i64)len;
}

API void ring_destroy(void* h) { delete (Ring*)h; }

// ---------------------------------------------------------------------------
// keydict: vectorized int64 key -> dense int32 slot open-addressing table.
// The native drop-in for flink_tpu/state/keyindex.py (KeyIndex): the batched
// analog of the reference's per-record CopyOnWriteStateMap hash probe —
// one C call maps a whole micro-batch of keys to dense HBM row ids.
// ---------------------------------------------------------------------------

static inline u64 kd_mix64(u64 x) {
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

// mmap-backed buffer advised onto 2MB transparent huge pages.  Random access
// into multi-MB tables (the key dict, the mirror panes) is TLB-bound with 4K
// pages — every probe is a TLB miss on top of the cache miss; 2MB pages cut
// the working set to a handful of TLB entries.  Memory is NOT pre-touched:
// anonymous mmap reads as zero, so untouched regions stay unbacked.
struct HugeBuf {
  u8* p = nullptr;
  size_t mapped = 0;  // 0 => malloc fallback (zero-filled manually)

  HugeBuf() = default;
  HugeBuf(const HugeBuf&) = delete;
  HugeBuf& operator=(const HugeBuf&) = delete;
  HugeBuf(HugeBuf&& o) noexcept { *this = static_cast<HugeBuf&&>(o); }
  HugeBuf& operator=(HugeBuf&& o) noexcept {
    release();
    p = o.p; mapped = o.mapped;
    o.p = nullptr; o.mapped = 0;
    return *this;
  }
  ~HugeBuf() { release(); }

  void release() {
    if (!p) return;
    if (mapped) munmap(p, mapped);
    else free(p);
    p = nullptr;
    mapped = 0;
  }

  // fresh zero-filled allocation (drops previous contents)
  void alloc(size_t bytes) {
    release();
    size_t rounded = (bytes + ((size_t)1 << 21) - 1) & ~((((size_t)1 << 21)) - 1);
    void* m = mmap(nullptr, rounded, PROT_READ | PROT_WRITE,
                   MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    if (m != MAP_FAILED) {
      madvise(m, rounded, MADV_HUGEPAGE);
      p = (u8*)m;
      mapped = rounded;
    } else {
      p = (u8*)calloc(1, bytes);
      mapped = 0;
    }
  }
};

struct KeyDict {
  // Interleaved bucket layout: key + slot share a cache line, so a probe
  // costs ONE memory access instead of two parallel-array misses, and the
  // +1 linear-probe neighbour is usually already resident.  slot1 stores
  // slot + 1 so the zero-page state of a fresh HugeBuf IS the empty table.
  struct Bucket { i64 key; i32 slot1; };  // slot1 0 = empty (16B padded)
  u64 cap = 0, mask = 0;
  HugeBuf tabbuf;
  Bucket* tab = nullptr;
  std::vector<i64> reverse; // slot -> key
  i64 n = 0;

  void init(u64 c) {
    cap = 1;
    while (cap < c) cap <<= 1;
    mask = cap - 1;
    tabbuf.alloc(cap * sizeof(Bucket));
    tab = (Bucket*)tabbuf.p;
  }

  inline i32 find_or_insert(i64 key) {
    u64 b = kd_mix64((u64)key) & mask;
    for (;;) {
      Bucket& bk = tab[b];
      if (bk.slot1 == 0) {
        bk.slot1 = (i32)n + 1;
        bk.key = key;
        reverse.push_back(key);
        return (i32)n++;
      }
      if (bk.key == key) return bk.slot1 - 1;
      b = (b + 1) & mask;
    }
  }

  inline i32 find(i64 key) const {
    u64 b = kd_mix64((u64)key) & mask;
    for (;;) {
      const Bucket& bk = tab[b];
      if (bk.slot1 == 0) return -1;
      if (bk.key == key) return bk.slot1 - 1;
      b = (b + 1) & mask;
    }
  }

  void grow_to(u64 c) {
    init(c);
    for (i64 i = 0; i < n; i++) {
      u64 b = kd_mix64((u64)reverse[i]) & mask;
      while (tab[b].slot1 != 0) b = (b + 1) & mask;
      tab[b].slot1 = (i32)i + 1;
      tab[b].key = reverse[i];
    }
  }

  inline void reserve(i64 incoming) {
    // worst case every incoming key is new; keep load factor <= 0.5
    if ((u64)(n + incoming) * 2 > cap) {
      u64 c = cap;
      while ((u64)(n + incoming) * 2 > c) c <<= 1;
      grow_to(c);
    }
  }

  inline void prefetch(i64 key) const {
    __builtin_prefetch(&tab[kd_mix64((u64)key) & mask]);
  }
};

API void* keydict_create(i64 initial_cap) {
  KeyDict* d = new KeyDict();
  d->init((u64)(initial_cap > 16 ? initial_cap : 16));
  // pre-size reverse to the load-factor bound so a hinted run avoids
  // push_back's amortized doubling copies
  d->reverse.reserve(d->cap / 2);
  return d;
}

API void keydict_destroy(void* h) { delete (KeyDict*)h; }

API i64 keydict_size(void* h) { return ((KeyDict*)h)->n; }

// Probe distance for software pipelining: random hash probes are
// memory-latency bound on one core; issuing the (i + PF)-th bucket's
// prefetch while resolving the i-th keeps ~PF misses in flight.
static const i64 KD_PF = 12;

API void keydict_lookup_or_insert(void* h, const i64* ks, i64 m, i32* out) {
  KeyDict* d = (KeyDict*)h;
  d->reserve(m);
  for (i64 i = 0; i < m; i++) {
    if (i + KD_PF < m) d->prefetch(ks[i + KD_PF]);
    out[i] = d->find_or_insert(ks[i]);
  }
}

API void keydict_lookup(void* h, const i64* ks, i64 m, i32* out) {
  KeyDict* d = (KeyDict*)h;
  for (i64 i = 0; i < m; i++) {
    if (i + KD_PF < m) d->prefetch(ks[i + KD_PF]);
    out[i] = d->find(ks[i]);
  }
}

API void keydict_reverse(void* h, i64* out) {
  KeyDict* d = (KeyDict*)h;
  std::memcpy(out, d->reverse.data(), (size_t)d->n * sizeof(i64));
}

// ---------------------------------------------------------------------------
// ShardPool: a small persistent worker pool for the sharded probe/mirror
// pass.  The hot path is memory-latency bound on one core (every random
// probe is a cache+TLB miss); a second/third core doubles the number of
// misses in flight, which is the only parallelism this workload has.  The
// CALLING thread executes shard 0 inline, pool workers cover shards
// 1..S-1, so a serial call (S=1) never touches the pool at all.  The pool
// is process-wide and intentionally leaked (daemon-style threads park on
// the condvar forever): joining at static destruction would deadlock
// interpreters that unload the library mid-exit.
// ---------------------------------------------------------------------------

namespace {

struct ShardPool {
  std::vector<std::thread> workers;
  std::mutex mu;
  // serializes whole waves: the pool is process-wide, so two threads
  // sharding concurrently (parallel subtasks in one MiniCluster process)
  // must not clobber each other's job/active/pending — without this the
  // second caller rebinds `job` while the first wave's workers still
  // reference it (use-after-free of the wave lambda).  Concurrent callers
  // degrade to serialized waves, which is also the honest schedule: they
  // would be contending for the same cores anyway.
  std::mutex run_mu;
  std::condition_variable cv_work, cv_done;
  std::function<void(int)> job;
  u64 gen = 0;
  int active = 0;   // shards in the current wave (including the caller)
  int pending = 0;  // participating workers not yet finished

  void loop(int tid) {
    u64 seen = 0;
    std::unique_lock<std::mutex> lk(mu);
    for (;;) {
      cv_work.wait(lk, [&] { return gen != seen; });
      seen = gen;
      if (tid < active) {
        auto f = job;  // copy: `job` is rebound by the next wave
        lk.unlock();
        f(tid);
        lk.lock();
        if (--pending == 0) cv_done.notify_all();
      }
    }
  }

  // Run f(tid) for tid in [0, nshards); blocks until every shard returns.
  void run(int nshards, const std::function<void(int)>& f) {
    if (nshards <= 1) {
      f(0);
      return;
    }
    std::lock_guard<std::mutex> wave(run_mu);
    {
      std::unique_lock<std::mutex> lk(mu);
      while ((int)workers.size() < nshards - 1) {
        int tid = (int)workers.size() + 1;  // caller is shard 0
        workers.emplace_back([this, tid] { loop(tid); });
      }
      job = f;
      active = nshards;
      pending = nshards - 1;
      gen++;
      cv_work.notify_all();
    }
    f(0);
    std::unique_lock<std::mutex> lk(mu);
    cv_done.wait(lk, [&] { return pending == 0; });
  }
};

ShardPool* shard_pool() {
  static ShardPool* p = new ShardPool();  // leaked by design, see above
  return p;
}

// below this the parallel path costs more than the misses it hides
static const i64 WM_MIN_PARALLEL = 1 << 14;

}  // namespace

API i32 fn_hw_threads() { return (i32)std::thread::hardware_concurrency(); }

// ---------------------------------------------------------------------------
// WinMirror: write-through host value mirror of windowed ACC cells.
//
// The native fire/mirror/probe hot path of the window operator's HOST emit
// tier (operators/window_agg.py): the batched analog of the reference's
// per-record WindowOperator.processElement -> HeapAggregatingState.add loop
// and its emitWindowContents fire path
// (flink-streaming-java/.../windowing/WindowOperator.java:300,574), with the
// same make-the-inner-loop-native role as the reference's Cython fast coders
// (pyflink/fn_execution/table/window_aggregate_fast.pyx:51).
//
// Layout: one entry per live pane, rows interleaved as
// [count i64][leaf_0 8B][leaf_1 8B]... so a record update touches ONE cache
// line; leaves are f64 (float accumulators) or i64 (integer accumulators) —
// the higher-precision twins of the device's f32/i32 cells.  The key dict is
// SHARED with the Python KeyIndex (same handle), so slot ids agree with the
// device state rows by construction.
//
// wm_probe_update fuses the key probe and the mirror write-through into one
// pass (the (slot, pane, value) triples are computed once and consumed
// twice); wm_fire is one sequential pass over slots that combines panes,
// compacts non-empty rows, and resolves keys — fire cost is memory
// bandwidth, not Python.
// ---------------------------------------------------------------------------

namespace {

struct MirrorPane {
  HugeBuf rows;  // interleaved rows, `cap` of them
  i64 cap = 0;
};

struct WinMirror {
  KeyDict* dict = nullptr;  // shared with the Python KeyIndex; NOT owned
  int nl = 0;               // number of accumulator leaves (scalar each)
  u8 kind[16];              // per leaf: 0 add, 1 min, 2 max
  u8 lt[16];                // per leaf storage: 0 f64, 1 i64
  u64 init_bits[16];        // identity value bits (storage dtype)
  i64 stride = 0;           // 8 * (1 + nl) bytes per row
  bool zero_init = true;    // all identities are 0 bits: zero pages suffice
  std::unordered_map<i64, MirrorPane> panes;

  void grow(MirrorPane& mp, i64 min_rows) {
    i64 nc = mp.cap ? mp.cap : 1024;
    while (nc < min_rows) nc <<= 1;
    HugeBuf fresh;
    fresh.alloc((size_t)(nc * stride));
    if (!zero_init) {
      // min/max identities are non-zero bit patterns: stamp the template
      // into the grown region (add identities are 0, the mmap default,
      // so sum/count panes skip this and stay zero-page-backed)
      u8 tmpl[8 * 17];
      i64 zero = 0;
      memcpy(tmpl, &zero, 8);
      for (int j = 0; j < nl; j++) memcpy(tmpl + 8 + 8 * j, &init_bits[j], 8);
      for (i64 r = mp.cap; r < nc; r++)
        memcpy(fresh.p + r * stride, tmpl, (size_t)stride);
    }
    if (mp.cap) memcpy(fresh.p, mp.rows.p, (size_t)(mp.cap * stride));
    mp.rows = static_cast<HugeBuf&&>(fresh);
    mp.cap = nc;
  }

  inline MirrorPane* ensure_pane(i64 p, i64 min_rows) {
    MirrorPane& mp = panes[p];
    if (mp.cap < min_rows) grow(mp, min_rows);
    return &mp;
  }
};

// value load: input leaf arrays keep their numpy dtype (no Python-side cast)
enum VDt { VF64 = 0, VF32 = 1, VI64 = 2, VI32 = 3 };

}  // namespace

API void* wm_create(void* dict_handle, i32 n_leaves, const u8* kinds,
                    const u8* ltypes, const u64* init_bits) {
  if (n_leaves < 1 || n_leaves > 16) return nullptr;
  auto* w = new WinMirror();
  w->dict = (KeyDict*)dict_handle;
  w->nl = n_leaves;
  memcpy(w->kind, kinds, (size_t)n_leaves);
  memcpy(w->lt, ltypes, (size_t)n_leaves);
  memcpy(w->init_bits, init_bits, (size_t)n_leaves * 8);
  w->stride = 8 * (1 + n_leaves);
  w->zero_init = true;
  for (i32 j = 0; j < n_leaves; j++)
    if (init_bits[j] != 0) w->zero_init = false;
  return w;
}

API void wm_destroy(void* h) { delete (WinMirror*)h; }

API void wm_drop_pane(void* h, i64 pane) { ((WinMirror*)h)->panes.erase(pane); }

API i64 wm_pane_count(void* h) { return (i64)((WinMirror*)h)->panes.size(); }

API void wm_live_panes(void* h, i64* out) {
  auto* w = (WinMirror*)h;
  i64 i = 0;
  for (auto& kv : w->panes) out[i++] = kv.first;
}

namespace {

// One record's fold into its mirror row (generic path, any leaf mix).
static inline void wm_fold_one(WinMirror* w, u8* row, const void* const* vals,
                               const u8* vdt, i64 k) {
  (*(i64*)row)++;
  for (int l = 0; l < w->nl; l++) {
    u8* cell = row + 8 + 8 * l;
    if (w->lt[l] == 0) {
      double x;
      switch (vdt[l]) {
        case VF64: x = ((const double*)vals[l])[k]; break;
        case VF32: x = (double)((const float*)vals[l])[k]; break;
        case VI64: x = (double)((const i64*)vals[l])[k]; break;
        default:   x = (double)((const i32*)vals[l])[k]; break;
      }
      double* c = (double*)cell;
      if (w->kind[l] == 0) *c += x;
      else if (w->kind[l] == 1) { if (x < *c) *c = x; }
      else { if (x > *c) *c = x; }
    } else {
      i64 x;
      switch (vdt[l]) {
        case VF64: x = (i64)((const double*)vals[l])[k]; break;
        case VF32: x = (i64)((const float*)vals[l])[k]; break;
        case VI64: x = ((const i64*)vals[l])[k]; break;
        default:   x = (i64)((const i32*)vals[l])[k]; break;
      }
      i64* c = (i64*)cell;
      if (w->kind[l] == 0) *c += x;
      else if (w->kind[l] == 1) { if (x < *c) *c = x; }
      else { if (x > *c) *c = x; }
    }
  }
}

static void wm_probe_serial(WinMirror* w, const i64* keys,
                            const i64* pane_ids, i64 n,
                            const void* const* vals, const u8* vdt,
                            i32* slots_out, i64 pane_mod, i32* flat_out) {
  KeyDict* d = w->dict;
  d->reserve(n);
  for (i64 i = 0; i < n; i++) {
    if (i + KD_PF < n) d->prefetch(keys[i + KD_PF]);
    slots_out[i] = d->find_or_insert(keys[i]);
  }
  const i64 need = d->n;  // fixed for the scatter: all inserts done above
  const i64 stride = w->stride;
  const i64 PF = 16;
  // timestamps arrive roughly sorted, so panes form long runs: segment the
  // batch by pane once and keep the inner loops free of per-record checks
  i64 i = 0;
  while (i < n) {
    const i64 p = pane_ids[i];
    i64 j = i + 1;
    while (j < n && pane_ids[j] == p) j++;
    MirrorPane* mp = w->ensure_pane(p, need);
    u8* base = mp->rows.p;
    if (flat_out) {
      const i32 ps = (i32)(((p % pane_mod) + pane_mod) % pane_mod);
      const i32 mul = (i32)pane_mod;
      for (i64 k = i; k < j; k++) flat_out[k] = slots_out[k] * mul + ps;
    }
    // fast path: single f64 add leaf fed by f32 values (sum over floats —
    // the dominant shape).  Direct prefetched scatter: an LSD-radix
    // sort-then-sweep variant measured SLOWER here (the bucket-placement
    // passes cost more than the locality buys on this single-core box).
    if (w->nl == 1 && w->kind[0] == 0 && w->lt[0] == 0 && vdt[0] == VF32) {
      const float* v = (const float*)vals[0];
      for (i64 k = i; k < j; k++) {
        if (k + PF < j)
          __builtin_prefetch(base + (i64)slots_out[k + PF] * stride, 1);
        u8* row = base + (i64)slots_out[k] * stride;
        (*(i64*)row)++;
        *(double*)(row + 8) += (double)v[k];
      }
      i = j;
      continue;
    }
    for (i64 k = i; k < j; k++) {
      if (k + PF < j)
        __builtin_prefetch(base + (i64)slots_out[k + PF] * stride, 1);
      wm_fold_one(w, base + (i64)slots_out[k] * stride, vals, vdt, k);
    }
    i = j;
  }
}

// Sharded probe+fold: bitwise identical to the serial pass at ANY shard
// count.  Phase 1 partitions the batch into contiguous record ranges and
// runs READ-ONLY dict lookups in parallel (no inserts -> the table is
// immutable during the scan).  Phase 2 inserts the misses serially in
// batch order, so new keys get exactly the slot ids the serial pass would
// assign.  Phase 3 folds in parallel with slot-ownership partitioning:
// by default shard t owns slots with slot %% S == t; with shard_div > 0
// shard t instead owns the CONTIGUOUS slot range
// [t * shard_div, (t+1) * shard_div) — the key-group-range ownership the
// mesh runtime uses, so probe shard t maintains exactly the mirror rows
// whose device state block lives on mesh device t.  Either way every
// mirror cell has exactly ONE writer and sees its updates in batch order —
// no locks, no atomics, and the result is bit-identical, not just
// equivalent.  shard_ns (nullable, length >= S) receives each shard's
// phase-3 fold wall time in nanoseconds (the per-shard probe breakdown).
static void wm_probe_sharded(WinMirror* w, const i64* keys,
                             const i64* pane_ids, i64 n,
                             const void* const* vals, const u8* vdt,
                             i32* slots_out, i64 pane_mod, i32* flat_out,
                             i64 flat_cap, i32 flat_pad, int S,
                             i64 shard_div, i64* shard_ns) {
  KeyDict* d = w->dict;
  d->reserve(n);  // up front: phase 1 must not observe a rehash
  ShardPool* pool = shard_pool();
  std::vector<std::vector<i64>> misses((size_t)S);
  pool->run(S, [&](int t) {
    const i64 lo = n * t / S, hi = n * (t + 1) / S;
    auto& miss = misses[(size_t)t];
    for (i64 i = lo; i < hi; i++) {
      if (i + KD_PF < hi) d->prefetch(keys[i + KD_PF]);
      i32 s = d->find(keys[i]);
      slots_out[i] = s;
      if (s < 0) miss.push_back(i);
    }
  });
  // serial insert in batch order (ranges are contiguous and ordered, so
  // concatenating the per-shard miss lists IS the original record order);
  // duplicate new keys resolve to their first occurrence's slot, exactly
  // like the serial pass
  for (int t = 0; t < S; t++)
    for (i64 i : misses[(size_t)t])
      slots_out[i] = d->find_or_insert(keys[i]);
  const i64 need = d->n;
  // pre-grow every pane this batch touches: the parallel fold must not
  // mutate the pane map (iterating pane runs costs one sequential scan)
  {
    i64 i = 0;
    while (i < n) {
      const i64 p = pane_ids[i];
      w->ensure_pane(p, need);
      i64 j = i + 1;
      while (j < n && pane_ids[j] == p) j++;
      i = j;
    }
  }
  const i64 stride = w->stride;
  const i64 PF = 16;
  pool->run(S, [&](int t) {
    const auto t0 = std::chrono::steady_clock::now();
    if (flat_out) {
      // flat device-scatter ids partition by record range (no sharing)
      const i64 lo = n * t / S, hi = n * (t + 1) / S;
      for (i64 k = lo; k < hi; k++) {
        const i64 p = pane_ids[k];
        const i32 ps = (i32)(((p % pane_mod) + pane_mod) % pane_mod);
        flat_out[k] = slots_out[k] * (i32)pane_mod + ps;
      }
      if (t == S - 1)
        for (i64 k = n; k < flat_cap; k++) flat_out[k] = flat_pad;
    }
    const u32 uS = (u32)S, ut = (u32)t;
    const bool by_range = shard_div > 0;
    const i64 own_lo = by_range ? (i64)t * shard_div : 0;
    // the LAST range is open-ended: slots past shard_div * S (a caller
    // whose capacity grew under it) must still have exactly one owner
    const i64 own_hi = !by_range ? 0
        : (t == S - 1 ? INT64_MAX : own_lo + shard_div);
    // mine(s): does this shard own slot s?  Range ownership compares
    // against [own_lo, own_hi); modulo ownership hashes slot classes.
#define WM_MINE(s) (by_range ? ((i64)(s) >= own_lo && (i64)(s) < own_hi) \
                             : ((u32)(s) % uS == ut))
    i64 i = 0;
    while (i < n) {
      const i64 p = pane_ids[i];
      i64 j = i + 1;
      while (j < n && pane_ids[j] == p) j++;
      u8* base = w->panes.find(p)->second.rows.p;  // pre-grown above
      if (w->nl == 1 && w->kind[0] == 0 && w->lt[0] == 0 && vdt[0] == VF32) {
        const float* v = (const float*)vals[0];
        for (i64 k = i; k < j; k++) {
          const i32 s = slots_out[k];
          if (!WM_MINE(s)) continue;
          const i64 kp = k + PF;
          if (kp < j && WM_MINE(slots_out[kp]))
            __builtin_prefetch(base + (i64)slots_out[kp] * stride, 1);
          u8* row = base + (i64)s * stride;
          (*(i64*)row)++;
          *(double*)(row + 8) += (double)v[k];
        }
      } else {
        for (i64 k = i; k < j; k++) {
          const i32 s = slots_out[k];
          if (!WM_MINE(s)) continue;
          const i64 kp = k + PF;
          if (kp < j && WM_MINE(slots_out[kp]))
            __builtin_prefetch(base + (i64)slots_out[kp] * stride, 1);
          wm_fold_one(w, base + (i64)s * stride, vals, vdt, k);
        }
      }
      i = j;
    }
#undef WM_MINE
    if (shard_ns)
      shard_ns[t] = (i64)std::chrono::duration_cast<std::chrono::nanoseconds>(
          std::chrono::steady_clock::now() - t0).count();
  });
}

}  // namespace

// Fused probe + mirror write-through: one pass maps keys -> slots (shared
// dict; new keys insert) and folds each record into its pane's row.  Pane
// pointers are cached across the usual within-batch runs (timestamps arrive
// roughly sorted), and both the hash probe and the mirror row are
// software-prefetched — the loop keeps ~8-12 cache misses in flight, which
// is all the parallelism a single core offers; ``nshards`` > 1 multiplies
// it across cores (see wm_probe_sharded — bit-identical at any count).
// ``pane_mod``/``flat_out``: when flat_out is non-null, also emit the device
// scatter ids flat = slot * pane_mod + pane %% pane_mod (int32) — the ids
// the jitted update step consumes — saving three numpy passes per batch;
// flat_out[n..flat_cap) is filled with ``flat_pad`` (the dropped-padding
// id), so the caller's pow2-padded staging buffer is ready to dispatch.
API void wm_probe_update2(void* h, const i64* keys, const i64* pane_ids,
                          i64 n, const void* const* vals, const u8* vdt,
                          i32* slots_out, i64 pane_mod, i32* flat_out,
                          i64 flat_cap, i32 flat_pad, i32 nshards,
                          i64 shard_div, i64* shard_ns);

API void wm_probe_update(void* h, const i64* keys, const i64* pane_ids, i64 n,
                         const void* const* vals, const u8* vdt,
                         i32* slots_out, i64 pane_mod, i32* flat_out,
                         i64 flat_cap, i32 flat_pad, i32 nshards) {
  wm_probe_update2(h, keys, pane_ids, n, vals, vdt, slots_out, pane_mod,
                   flat_out, flat_cap, flat_pad, nshards, 0, nullptr);
}

// Extended probe entry: ``shard_div`` > 0 switches shard ownership from
// slot %% S classes to contiguous slot ranges [t*shard_div, (t+1)*shard_div)
// — the mesh runtime passes K_cap / n_devices so probe shard t owns exactly
// the key-group range whose device state block lives on mesh device t.
// ``shard_ns`` (nullable, i64[nshards]) receives per-shard fold wall nanos
// (serial pass: total in shard_ns[0]).
API void wm_probe_update2(void* h, const i64* keys, const i64* pane_ids,
                          i64 n, const void* const* vals, const u8* vdt,
                          i32* slots_out, i64 pane_mod, i32* flat_out,
                          i64 flat_cap, i32 flat_pad, i32 nshards,
                          i64 shard_div, i64* shard_ns) {
  auto* w = (WinMirror*)h;
  int S = nshards;
  if (S > 16) S = 16;
  // range ownership must cover every slot: with fewer ranges than shards
  // the tail shards simply own nothing (their ranges sit past shard_div*S)
  if (S > 1 && n >= WM_MIN_PARALLEL) {
    wm_probe_sharded(w, keys, pane_ids, n, vals, vdt, slots_out, pane_mod,
                     flat_out, flat_cap, flat_pad, S, shard_div, shard_ns);
    return;
  }
  const auto t0 = std::chrono::steady_clock::now();
  wm_probe_serial(w, keys, pane_ids, n, vals, vdt, slots_out, pane_mod,
                  flat_out);
  if (flat_out)
    for (i64 k = n; k < flat_cap; k++) flat_out[k] = flat_pad;
  if (shard_ns && nshards >= 1) {
    for (i32 t = 1; t < nshards && t < 16; t++) shard_ns[t] = 0;
    shard_ns[0] = (i64)std::chrono::duration_cast<std::chrono::nanoseconds>(
        std::chrono::steady_clock::now() - t0).count();
  }
}

// Window fire: combine the window's panes per slot, compact non-empty rows
// (ascending slot order), resolve raw keys from the shared dict's reverse
// table.  Outputs are caller-allocated with capacity >= dict->n rows.
// Returns the number of emitted rows.  Slots beyond a pane's capacity hold
// the identity by construction, so clamping is sufficient.
API i64 wm_fire(void* h, const i64* pane_ids, i32 npanes, i64* out_keys,
                i64* out_counts, void* const* out_leaves) {
  auto* w = (WinMirror*)h;
  const i64 n = w->dict->n;
  std::vector<const u8*> bases_v;
  std::vector<i64> caps_v;
  bases_v.reserve((size_t)npanes);
  caps_v.reserve((size_t)npanes);
  for (i32 i = 0; i < npanes; i++) {
    auto it = w->panes.find(pane_ids[i]);
    if (it == w->panes.end() || it->second.cap == 0) continue;
    bases_v.push_back(it->second.rows.p);
    caps_v.push_back(it->second.cap);
  }
  const int np = (int)bases_v.size();
  if (np == 0 || n == 0) return 0;
  const u8* const* bases = bases_v.data();
  const i64* caps = caps_v.data();
  const i64 stride = w->stride;
  const i64* rev = w->dict->reverse.data();
  i64 m = 0;
  // fast path: tumbling (single pane), one f64 leaf — one sequential sweep
  if (np == 1 && w->nl == 1 && w->lt[0] == 0) {
    const u8* base = bases[0];
    const i64 lim = n < caps[0] ? n : caps[0];
    double* ol = (double*)out_leaves[0];
    for (i64 s = 0; s < lim; s++) {
      const u8* row = base + s * stride;
      const i64 c = *(const i64*)row;
      if (c > 0) {
        out_keys[m] = rev[s];
        out_counts[m] = c;
        ol[m] = *(const double*)(row + 8);
        m++;
      }
    }
    return m;
  }
  for (i64 s = 0; s < n; s++) {
    i64 total = 0;
    for (int q = 0; q < np; q++)
      if (s < caps[q]) total += *(const i64*)(bases[q] + s * stride);
    if (total <= 0) continue;
    out_keys[m] = rev[s];
    out_counts[m] = total;
    // seed the combine from the FIRST present pane's cell (total > 0
    // guarantees one exists) — seeding from the identity instead would
    // double-count a nonzero 'add' identity relative to the numpy mirror
    for (int j = 0; j < w->nl; j++) {
      if (w->lt[j] == 0) {
        double acc = 0;
        bool first = true;
        for (int q = 0; q < np; q++) {
          if (s >= caps[q]) continue;
          double v = *(const double*)(bases[q] + s * stride + 8 + 8 * j);
          if (first) { acc = v; first = false; }
          else if (w->kind[j] == 0) acc += v;
          else if (w->kind[j] == 1) acc = v < acc ? v : acc;
          else acc = v > acc ? v : acc;
        }
        ((double*)out_leaves[j])[m] = acc;
      } else {
        i64 acc = 0;
        bool first = true;
        for (int q = 0; q < np; q++) {
          if (s >= caps[q]) continue;
          i64 v = *(const i64*)(bases[q] + s * stride + 8 + 8 * j);
          if (first) { acc = v; first = false; }
          else if (w->kind[j] == 0) acc += v;
          else if (w->kind[j] == 1) acc = v < acc ? v : acc;
          else acc = v > acc ? v : acc;
        }
        ((i64*)out_leaves[j])[m] = acc;
      }
    }
    m++;
  }
  return m;
}

// Fold a pane-granular DELTA into the mirror (the device-resident key
// probe's catch-up path, state/device_keyindex.py): ``counts`` adds into the
// per-row element counts, each leaf column combines by its kind.  The delta
// columns are identity-initialized on device, so folding an untouched row
// is a no-op by construction (add identity 0, min/max identities compare
// away) — no mask is needed.  Rows past the pane's current capacity grow it
// first, like wm_import_pane.
API void wm_apply_delta(void* h, i64 pane, i64 nrows, const i64* counts,
                        const void* const* vals, const u8* vdt) {
  auto* w = (WinMirror*)h;
  i64 need = nrows > w->dict->n ? nrows : w->dict->n;
  MirrorPane* mp = w->ensure_pane(pane, need);
  u8* base = mp->rows.p;
  const i64 stride = w->stride;
  for (i64 s = 0; s < nrows; s++) {
    u8* row = base + s * stride;
    *(i64*)row += counts[s];
    for (int l = 0; l < w->nl; l++) {
      u8* cell = row + 8 + 8 * l;
      if (w->lt[l] == 0) {
        double x;
        switch (vdt[l]) {
          case VF64: x = ((const double*)vals[l])[s]; break;
          case VF32: x = (double)((const float*)vals[l])[s]; break;
          case VI64: x = (double)((const i64*)vals[l])[s]; break;
          default:   x = (double)((const i32*)vals[l])[s]; break;
        }
        double* c = (double*)cell;
        if (w->kind[l] == 0) *c += x;
        else if (w->kind[l] == 1) { if (x < *c) *c = x; }
        else { if (x > *c) *c = x; }
      } else {
        i64 x;
        switch (vdt[l]) {
          case VF64: x = (i64)((const double*)vals[l])[s]; break;
          case VF32: x = (i64)((const float*)vals[l])[s]; break;
          case VI64: x = ((const i64*)vals[l])[s]; break;
          default:   x = (i64)((const i32*)vals[l])[s]; break;
        }
        i64* c = (i64*)cell;
        if (w->kind[l] == 0) *c += x;
        else if (w->kind[l] == 1) { if (x < *c) *c = x; }
        else { if (x > *c) *c = x; }
      }
    }
  }
}

// De-interleave one pane's first `nrows` rows into columnar buffers
// (snapshots, verification).  Rows beyond the pane's capacity export as
// count 0 / identity.  Returns 1 if the pane exists, else 0 (buffers are
// still filled with identity rows).
API i32 wm_export_pane(void* h, i64 pane, i64 nrows, i64* counts_out,
                       void* const* leaves_out) {
  auto* w = (WinMirror*)h;
  auto it = w->panes.find(pane);
  const u8* base = nullptr;
  i64 cap = 0;
  if (it != w->panes.end()) {
    base = it->second.rows.p;
    cap = it->second.cap;
  }
  const i64 stride = w->stride;
  for (i64 s = 0; s < nrows; s++) {
    if (s < cap) {
      const u8* row = base + s * stride;
      counts_out[s] = *(const i64*)row;
      for (int j = 0; j < w->nl; j++)
        memcpy((u8*)leaves_out[j] + 8 * s, row + 8 + 8 * j, 8);
    } else {
      counts_out[s] = 0;
      for (int j = 0; j < w->nl; j++)
        memcpy((u8*)leaves_out[j] + 8 * s, &w->init_bits[j], 8);
    }
  }
  return it != w->panes.end() ? 1 : 0;
}

// Interleave columnar buffers into one pane's rows (snapshot restore).
API void wm_import_pane(void* h, i64 pane, i64 nrows, const i64* counts,
                        const void* const* leaves) {
  auto* w = (WinMirror*)h;
  MirrorPane* mp = w->ensure_pane(pane, nrows);
  u8* base = mp->rows.p;
  const i64 stride = w->stride;
  for (i64 s = 0; s < nrows; s++) {
    u8* row = base + s * stride;
    *(i64*)row = counts[s];
    for (int j = 0; j < w->nl; j++)
      memcpy(row + 8 + 8 * j, (const u8*)leaves[j] + 8 * s, 8);
  }
}
