"""Pipelined hot-path equivalence (the ISSUE 3 tentpole contract).

``WindowAggOperator`` with ``pipeline_depth > 0`` runs its hot stage (fused
probe/mirror + paging + device dispatch) on a background worker, and
``native_shards > 1`` hash-partitions the fused C probe across the native
worker pool.  Both are pure scheduling changes: fire digests, snapshots,
and counters must be BIT-identical to the serial single-shard path — at any
depth, any shard count, on every tier (host mirror / device / deferred /
paged), and under chaos.  These tests compare exact bytes, not tolerances.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from flink_tpu.core.batch import RecordBatch, Watermark
from flink_tpu.core.functions import RuntimeContext, SumAggregator
from flink_tpu.native import native_available
from flink_tpu.operators.base import StreamOperator
from flink_tpu.operators.window_agg import WindowAggOperator
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


def _mk_op(pipeline_depth=0, native_shards=1, native=True, paging=None,
           emit_tier="host", device_sync="scatter", window_ms=100, **kw):
    if paging is not None:
        emit_tier = "device"
    op = WindowAggOperator(
        TumblingEventTimeWindows.of(window_ms), SumAggregator(jnp.float32),
        key_column="k", value_column="v", emit_tier=emit_tier,
        snapshot_source="mirror" if emit_tier == "host" else "device",
        device_sync=device_sync if emit_tier == "host" else "scatter",
        native_emit=native, pipeline_depth=pipeline_depth,
        native_shards=native_shards, paging=paging, **kw)
    op.open(RuntimeContext())
    return op


def _digests(out):
    """Exact per-fired-batch fingerprint: window, row count, and the raw
    BYTES of the emitted key and result columns (order included)."""
    return [(int(np.asarray(b.column("window_start"))[0]), len(b),
             np.asarray(b.column("k")).tobytes(),
             np.asarray(b.column("result")).tobytes())
            for b in out if hasattr(b, "columns") and "result" in b.columns]


def _counters(op):
    return {
        "late_dropped": op.late_dropped,
        "num_keys": op.key_index.num_keys if op.key_index else 0,
        "watermark": op.watermark,
        "last_fired_window": op.last_fired_window,
    }


def _assert_snap_equal(a, b):
    assert set(a) == set(b), set(a) ^ set(b)
    for k in sorted(a):
        va, vb = a[k], b[k]
        if isinstance(va, np.ndarray):
            assert np.array_equal(va, np.asarray(vb)), k
        elif isinstance(va, (list, tuple)):
            for x, y in zip(va, vb):
                assert np.array_equal(np.asarray(x), np.asarray(y)), k
        elif isinstance(va, dict):
            continue  # key_index internals: covered by digest equality
        else:
            assert va == vb, k


def _seeded_run(op, n_batches=12, nk=1500, b=4000, seed=11, snap_at=7,
                late_every=4):
    """Seeded feed with per-batch watermarks, a mid-run snapshot, and
    periodic LATE records (exercising the refire flush path), ending with
    end_input.  Returns (digests, mid-run snapshot, counters)."""
    rng = np.random.default_rng(seed)
    out, snap = [], None
    for i in range(n_batches):
        keys = rng.integers(0, nk, b).astype(np.int64)
        vals = rng.random(b).astype(np.float32)
        ts = i * 50 + np.sort(rng.integers(0, 50, b)).astype(np.int64)
        if late_every and i % late_every == late_every - 1 and i > 0:
            # a slice of records one window behind (late within lateness 0:
            # dropped — or refired when still retained)
            ts[: b // 8] = max(0, (i - 3) * 50)
        out += op.process_batch(RecordBatch({"k": keys, "v": vals},
                                            timestamps=ts))
        out += op.process_watermark(Watermark(int(ts.max()) - 1))
        if i == snap_at:
            op.prepare_snapshot_pre_barrier()
            snap = op.snapshot_state()
    out += op.end_input()
    counters = _counters(op)
    op.close()
    return _digests(out), snap, counters


# ---------------------------------------------------------------------------
# pipelining on vs off: bit-identical digests, snapshots, counters
# ---------------------------------------------------------------------------

def test_pipeline_on_off_bit_identical_host_tier():
    ref = _seeded_run(_mk_op(pipeline_depth=0))
    for depth in (1, 3):
        got = _seeded_run(_mk_op(pipeline_depth=depth))
        assert got[0] == ref[0], f"fire digests diverged at depth {depth}"
        _assert_snap_equal(got[1], ref[1])
        assert got[2] == ref[2]


def test_pipeline_on_off_bit_identical_device_tier():
    ref = _seeded_run(_mk_op(pipeline_depth=0, emit_tier="device"))
    got = _seeded_run(_mk_op(pipeline_depth=1, emit_tier="device"))
    assert got[0] == ref[0]
    _assert_snap_equal(got[1], ref[1])
    assert got[2] == ref[2]


def test_pipeline_on_off_bit_identical_deferred_sync():
    ref = _seeded_run(_mk_op(pipeline_depth=0, device_sync="deferred"))
    got = _seeded_run(_mk_op(pipeline_depth=2, device_sync="deferred"))
    assert got[0] == ref[0]
    _assert_snap_equal(got[1], ref[1])
    assert got[2] == ref[2]


def test_pipeline_numpy_mirror_fallback_identical():
    ref = _seeded_run(_mk_op(pipeline_depth=0, native=False))
    got = _seeded_run(_mk_op(pipeline_depth=1, native=False))
    assert got[0] == ref[0]
    _assert_snap_equal(got[1], ref[1])
    assert got[2] == ref[2]


# ---------------------------------------------------------------------------
# native probe sharding: bit-identical at any shard count
# ---------------------------------------------------------------------------

@pytest.mark.skipif(not native_available(),
                    reason="native library unavailable")
def test_native_shards_bit_identical():
    """Batches above the native parallel threshold (2^14), so the sharded
    lookup/insert/fold phases actually run.  Slot assignment, mirror cell
    contents, and fire compaction order must all match shard count 1."""
    kw = dict(n_batches=6, nk=4096, b=1 << 15, late_every=0, snap_at=3)
    ref = _seeded_run(_mk_op(pipeline_depth=0, native_shards=1), **kw)
    for shards in (2, 3):
        got = _seeded_run(_mk_op(pipeline_depth=0, native_shards=shards),
                          **kw)
        assert got[0] == ref[0], f"digests diverged at {shards} shards"
        _assert_snap_equal(got[1], ref[1])
        assert got[2] == ref[2]
    # sharded AND pipelined together
    both = _seeded_run(_mk_op(pipeline_depth=2, native_shards=3), **kw)
    assert both[0] == ref[0]
    _assert_snap_equal(both[1], ref[1])
    assert both[2] == ref[2]


@pytest.mark.skipif(not native_available(),
                    reason="native library unavailable")
def test_native_shards_new_key_insert_order():
    """Duplicate NEW keys inside one sharded batch must get the slot ids
    the serial pass would assign (first occurrence in batch order), even
    when the occurrences land in different shard ranges."""
    b = 1 << 15
    keys = np.arange(b, dtype=np.int64) % 977          # heavy duplication
    keys = np.concatenate([keys, keys[::-1]])          # cross-range dups
    vals = np.arange(keys.size, dtype=np.float32)
    ts = np.zeros(keys.size, np.int64)

    def run(shards):
        op = _mk_op(native_shards=shards)
        out = op.process_batch(RecordBatch({"k": keys, "v": vals},
                                           timestamps=ts))
        out += op.process_watermark(Watermark(99))
        d = _digests(out)
        op.close()
        return d

    assert run(4) == run(1)


@pytest.mark.skipif(not native_available(),
                    reason="native library unavailable")
def test_native_shards_concurrent_callers_safe():
    """The shard pool is process-wide: two subtask threads sharding their
    OWN mirrors at once must serialize waves, not clobber each other
    (regression: the unserialized pool raced job/pending across callers —
    use-after-free of the wave closure, observed as a segfault)."""
    import threading

    from flink_tpu.state.keyindex import make_key_index
    from flink_tpu.state.native_mirror import NativeWindowMirror

    agg = SumAggregator(jnp.float32)
    results = [None] * 3

    def worker(seed, i):
        rng = np.random.default_rng(seed)
        idx = make_key_index(np.int64(0), capacity_hint=1 << 15)
        nm = NativeWindowMirror.try_create(
            idx, agg.acc_spec(), agg.scatter_kind_leaves(), (np.float64,))
        B = 1 << 15
        total = 0.0
        count = 0
        for _ in range(8):
            k = rng.integers(0, 1 << 15, B).astype(np.int64)
            v = rng.random(B).astype(np.float32)
            flat = np.empty(B, np.int32)
            nm.probe_update(k, np.zeros(B, np.int64), [v], pane_mod=16,
                            flat_out=flat, flat_fill=2 ** 31 - 1, shards=3)
            total += float(v.astype(np.float64).sum())
            count += B
        _keys, counts, leaves = nm.fire(np.array([0]))
        results[i] = (float(np.asarray(leaves[0]).sum()), total,
                      int(np.asarray(counts).sum()), count)

    threads = [threading.Thread(target=worker, args=(s, i))
               for i, s in enumerate((1, 2, 3))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for got, want, cnt, n in results:
        assert abs(got - want) < 1e-6 * max(want, 1.0)
        assert cnt == n


# ---------------------------------------------------------------------------
# paging: 64k cap / 256k keys, pipelined vs serial
# ---------------------------------------------------------------------------

def _paged_run(pipeline_depth, n_keys=256 * 1024, cap=64 * 1024, seed=13):
    from flink_tpu.state.paging import PagingConfig
    op = _mk_op(pipeline_depth=pipeline_depth, paging=PagingConfig(cap),
                window_ms=1000, initial_key_capacity=1 << 10)
    rng = np.random.default_rng(seed)
    out = []
    for w in range(2):
        keys = rng.permutation(n_keys).astype(np.int64)
        for lo in range(0, n_keys, 1 << 15):
            k = keys[lo: lo + (1 << 15)]
            v = (k % 17 + 1).astype(np.float32)
            out += op.process_batch(RecordBatch(
                {"k": k, "v": v},
                timestamps=np.full(k.size, w * 1000 + 10, np.int64)))
        out += op.process_watermark(Watermark(w * 1000 + 999))
    out += op.end_input()
    snap = op.snapshot_state()
    stats = op.paging_stats()
    op.close()
    return _digests(out), snap, stats


def test_pipeline_with_paging_64k_cap_256k_keys():
    """The tentpole acceptance at the paging scale: K_cap 64k under 256k
    live keys, pipelined vs serial — identical fire digests (every spilled
    key fires), identical snapshots, identical occupancy counters.  The
    pager sees each batch's slots before any later batch can influence
    eviction decisions (stages are strictly ordered on the worker)."""
    ref_d, ref_s, ref_st = _paged_run(0)
    got_d, got_s, got_st = _paged_run(2)
    assert got_d == ref_d
    _assert_snap_equal(got_s, ref_s)
    assert got_st == ref_st
    assert ref_st["spilled_keys"] == 256 * 1024 - 64 * 1024


# ---------------------------------------------------------------------------
# chaos: SlowDisk on checkpoint storage must not perturb pipelined results
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_pipeline_under_slowdisk_identical_results_and_job_status():
    """Cluster-level equivalence under the SlowDisk nemesis: a windowed
    job with pipelining on vs off, checkpointing against a stalling store,
    must produce identical result rows AND identical job_status() record
    counters (records_in/out per vertex) — the pipeline barriers at every
    snapshot, so a stalled checkpoint can neither lose nor duplicate a
    stage."""
    from flink_tpu.datastream.api import StreamExecutionEnvironment
    from flink_tpu.runtime.checkpoint.storage import InMemoryCheckpointStorage
    from flink_tpu.testing import chaos
    from flink_tpu.testing.chaos import FaultInjector, SlowDisk
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows as T

    rng = np.random.default_rng(29)
    n = 40_000
    keys = rng.integers(0, 101, n).astype(np.int64)
    vals = rng.random(n)
    ts = np.sort(rng.integers(0, 5000, n)).astype(np.int64)

    def run(pipeline_depth):
        inj = FaultInjector(seed=7)
        inj.inject("checkpoint.store",
                   SlowDisk(max_s=0.03, min_s=0.01, p=0.5, times=10))
        env = StreamExecutionEnvironment()
        env.set_parallelism(2)
        sink = (env.from_collection(
                    columns={"k": keys, "v": vals, "t": ts}, batch_size=2048)
                .assign_timestamps_and_watermarks(0, timestamp_column="t")
                .key_by("k")
                .window(T.of(500))
                .aggregate(SumAggregator(np.float64), value_column="v",
                           pipeline_depth=pipeline_depth)
                .collect())
        with chaos.installed(inj):
            res = env.execute_cluster(storage=InMemoryCheckpointStorage(),
                                      checkpoint_interval_ms=5,
                                      tolerable_failed_checkpoints=0)
        rows = sorted(
            (int(r["k"]), int(r["window_start"]), float(r["result"]))
            for r in sink.rows())
        status = env._last_cluster.job_status()
        records = sorted(
            (v["name"], sum(s["records_in"] for s in v["subtasks"]),
             sum(s["records_out"] for s in v["subtasks"]))
            for v in status["vertices"])
        return rows, records, res.state

    rows0, rec0, state0 = run(0)
    rows1, rec1, state1 = run(1)
    assert state0 == state1
    assert rows1 == rows0
    assert rec1 == rec0


# ---------------------------------------------------------------------------
# barrier/driver-hook semantics
# ---------------------------------------------------------------------------

def test_flush_pipeline_base_noop_and_idempotent():
    assert StreamOperator().flush_pipeline() == []
    op = _mk_op(pipeline_depth=1)
    assert op.flush_pipeline() == []          # nothing in flight: no-op
    keys = np.arange(256, dtype=np.int64)
    op.process_batch(RecordBatch(
        {"k": keys, "v": np.ones(256, np.float32)},
        timestamps=np.zeros(256, np.int64)))
    op.flush_pipeline()
    op.flush_pipeline()                       # idempotent
    assert op.key_index.num_keys == 256       # stage completed at barrier
    op.close()


def test_pipeline_stage_error_surfaces_at_barrier():
    """A stage failure must re-raise at the next barrier, not vanish."""
    op = _mk_op(pipeline_depth=1)

    def boom(*a, **kw):
        raise RuntimeError("stage exploded")

    op._hot_stage = boom
    keys = np.arange(64, dtype=np.int64)
    op.process_batch(RecordBatch(
        {"k": keys, "v": np.ones(64, np.float32)},
        timestamps=np.zeros(64, np.int64)))
    with pytest.raises(RuntimeError, match="stage exploded"):
        op.flush_pipeline()
    # STICKY: a foreign-thread flush (metrics poller via job_status ->
    # paging_stats) must not consume the error — the task thread's own
    # next barrier still has to fail the task
    with pytest.raises(RuntimeError, match="stage exploded"):
        op.flush_pipeline()
    with pytest.raises(RuntimeError, match="stage exploded"):
        op.close()  # teardown surfaces the failure once more, then clears
    assert op.flush_pipeline() == []


def test_watermark_fast_path_never_defers_due_fires():
    """The pipelined watermark fast path may only skip the barrier when NO
    window newly passed: a watermark that crosses a window end must fire
    immediately, with the just-submitted stage's records included."""
    op = _mk_op(pipeline_depth=3)
    out = []
    for w in range(4):
        keys = np.arange(100, dtype=np.int64)
        ts = np.full(100, w * 100 + 50, np.int64)
        out += op.process_batch(RecordBatch(
            {"k": keys, "v": np.ones(100, np.float32)}, timestamps=ts))
        out += op.process_watermark(Watermark(w * 100 + 99))
    fired = _digests(out)
    assert len(fired) == 4
    assert all(n == 100 for _w, n, _k, _r in fired)
    op.close()
