"""Mesh-sharded job runtime (VERDICT r1 #1): env.execute() runs keyed
pipelines whose window state shards over a device mesh and whose records
ride the all_to_all device exchange — no __graft_entry__ special-casing.

Reference anchors: the keyed exchange as the runtime
(``KeyGroupStreamPartitioner.java``, ``NettyMessage.java:254``), key-group
rescaling (``StateAssignmentOperation.reDistributeKeyedStates``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flink_tpu.core.batch import RecordBatch, Watermark
from flink_tpu.core.functions import RuntimeContext, SumAggregator
from flink_tpu.datastream.api import StreamExecutionEnvironment
from flink_tpu.parallel.mesh import make_mesh
from flink_tpu.parallel.mesh_runtime import MeshWindowAggOperator
from flink_tpu.testing.harness import KeyedOneInputOperatorHarness
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


def _wordcount_env(n=5000, n_keys=37, mesh_devices=8):
    env = StreamExecutionEnvironment().set_mesh(n_devices=mesh_devices)
    words = (np.arange(n) % n_keys).astype(np.int64)
    sink = (env.from_collection(
                columns={"word": words, "one": np.ones(n, np.float32)},
                batch_size=512)
            .assign_timestamps_and_watermarks(0, timestamp_column="word")
            .key_by("word")
            .window(TumblingEventTimeWindows.of(10_000))
            .sum("one").collect())
    want = {k: float(np.sum(words == k)) for k in range(n_keys)}
    return env, sink, want


def test_mesh_job_through_env_execute():
    """A socket_window_word_count-class job runs end-to-end on the 8-device
    mesh through the NORMAL DataStream path."""
    env, sink, want = _wordcount_env()
    env.execute()
    got = {int(r["word"]): float(r["one"]) for r in sink.rows()}
    assert got == want


def test_mesh_operator_state_is_sharded_and_exchange_runs():
    mesh = make_mesh(8)
    op = MeshWindowAggOperator(
        TumblingEventTimeWindows.of(1000), SumAggregator(jnp.float32),
        key_column="k", value_column="v", mesh=mesh)
    h = KeyedOneInputOperatorHarness(op)
    rng = np.random.default_rng(5)
    keys = rng.integers(0, 500, 2000).astype(np.int64)
    vals = rng.random(2000).astype(np.float32)
    h.process_batch(RecordBatch({"k": keys, "v": vals},
                                timestamps=np.zeros(2000, np.int64)))
    # state physically lives on all 8 devices
    assert len(op._leaves[0].sharding.device_set) == 8
    h.process_watermark(1000 - 1)
    rows = h.extract_output_rows()
    got = {r["k"]: r["result"] for r in rows}
    want = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        want[k] = want.get(k, 0.0) + v
    assert set(got) == set(want)
    np.testing.assert_allclose(
        [got[k] for k in sorted(got)], [want[k] for k in sorted(want)],
        rtol=1e-4)


def test_mesh_sharded_checkpoint_restore():
    """Snapshot of mesh-sharded state restores and resumes correctly."""
    mesh = make_mesh(8)
    op = MeshWindowAggOperator(
        TumblingEventTimeWindows.of(1000), SumAggregator(jnp.float32),
        key_column="k", value_column="v", mesh=mesh)
    op.open(RuntimeContext())
    keys = np.arange(100, dtype=np.int64)
    op.process_batch(RecordBatch(
        {"k": keys, "v": np.full(100, 2.0, np.float32)},
        timestamps=np.zeros(100, np.int64)))
    snap = op.snapshot_state()

    op2 = MeshWindowAggOperator(
        TumblingEventTimeWindows.of(1000), SumAggregator(jnp.float32),
        key_column="k", value_column="v", mesh=mesh)
    op2.open(RuntimeContext())
    op2.restore_state(snap)
    assert len(op2._leaves[0].sharding.device_set) == 8
    op2.process_batch(RecordBatch(
        {"k": keys, "v": np.full(100, 3.0, np.float32)},
        timestamps=np.full(100, 10, np.int64)))
    out = op2.process_watermark(Watermark(999))
    rows = [r for b in out for r in b.to_rows()]
    assert len(rows) == 100
    assert all(abs(r["result"] - 5.0) < 1e-5 for r in rows)


@pytest.mark.parametrize("new_devices", [4, 1])
def test_mesh_rescale_restore(new_devices):
    """A snapshot taken on 8 devices restores onto a smaller mesh (and onto
    a single chip): key-group ranges re-slice, results unchanged — the
    reference's rescaling story (``StateAssignmentOperation``)."""
    mesh8 = make_mesh(8)
    op = MeshWindowAggOperator(
        TumblingEventTimeWindows.of(1000), SumAggregator(jnp.float32),
        key_column="k", value_column="v", mesh=mesh8)
    op.open(RuntimeContext())
    keys = np.arange(256, dtype=np.int64)
    op.process_batch(RecordBatch(
        {"k": keys, "v": np.full(256, 1.5, np.float32)},
        timestamps=np.zeros(256, np.int64)))
    snap = op.snapshot_state()

    if new_devices == 1:
        from flink_tpu.operators.window_agg import WindowAggOperator
        op2 = WindowAggOperator(
            TumblingEventTimeWindows.of(1000), SumAggregator(jnp.float32),
            key_column="k", value_column="v")
    else:
        op2 = MeshWindowAggOperator(
            TumblingEventTimeWindows.of(1000), SumAggregator(jnp.float32),
            key_column="k", value_column="v", mesh=make_mesh(new_devices))
    op2.open(RuntimeContext())
    op2.restore_state(snap)
    out = op2.process_watermark(Watermark(999))
    rows = [r for b in out for r in b.to_rows()]
    assert len(rows) == 256
    assert all(abs(r["result"] - 1.5) < 1e-5 for r in rows)


def test_mesh_job_with_checkpoint_through_env():
    """env-level checkpointing of a mesh job: snapshot mid-stream, restore
    into a fresh env, results complete."""
    from flink_tpu.runtime.checkpoint.storage import InMemoryCheckpointStorage

    storage = InMemoryCheckpointStorage()
    env, sink, want = _wordcount_env()
    env.enable_checkpointing(1, storage=storage)
    env.execute()
    got = {int(r["word"]): float(r["one"]) for r in sink.rows()}
    assert got == want
    # at least one checkpoint completed and holds the mesh operator's state
    assert storage.checkpoint_ids()

    def _has_leaves(tree):
        if isinstance(tree, dict):
            return "leaves" in tree or any(_has_leaves(v)
                                           for v in tree.values())
        if isinstance(tree, (list, tuple)):
            return any(_has_leaves(v) for v in tree)
        return False

    assert _has_leaves(storage.load_latest())


def test_mesh_zipf_skew_correctness():
    """Skewed (Zipf) keys: bucket capacities renegotiate host-side, no loss."""
    mesh = make_mesh(8)
    op = MeshWindowAggOperator(
        TumblingEventTimeWindows.of(1000), SumAggregator(jnp.float32),
        key_column="k", value_column="v", mesh=mesh)
    h = KeyedOneInputOperatorHarness(op)
    rng = np.random.default_rng(11)
    keys = rng.zipf(1.5, 4000).astype(np.int64) % 1000
    vals = np.ones(4000, np.float32)
    h.process_batch(RecordBatch({"k": keys, "v": vals},
                                timestamps=np.zeros(4000, np.int64)))
    h.process_watermark(999)
    rows = h.extract_output_rows()
    assert sum(r["result"] for r in rows) == 4000.0


def test_mesh_non_pow2_device_count():
    """D=6: key capacity rounds to lcm(pow2, 6); rows still split evenly."""
    op = MeshWindowAggOperator(
        TumblingEventTimeWindows.of(1000), SumAggregator(jnp.float32),
        key_column="k", value_column="v", mesh=make_mesh(6),
        initial_key_capacity=64)
    op.open(RuntimeContext())
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 100, 777).astype(np.int64)
    op.process_batch(RecordBatch({"k": keys, "v": np.ones(777, np.float32)},
                                 timestamps=np.zeros(777, np.int64)))
    assert op._K % 6 == 0
    out = op.process_watermark(Watermark(999))
    total = sum(float(np.asarray(b.column("result")).sum()) for b in out)
    assert total == 777.0


# ---------------------------------------------------------------------------
# Mesh sessions (VERDICT r2 #2): baseline config #4 shape — Zipf keys,
# gap sessions, device segment fold, checkpoint/restore/rescale.
# ---------------------------------------------------------------------------

def _zipf_session_batches(n_batches=6, batch=512, n_keys=200, seed=5):
    rng = np.random.default_rng(seed)
    t = 0
    out = []
    for _ in range(n_batches):
        keys = np.minimum(rng.zipf(1.6, batch), n_keys).astype(np.int64)
        vals = rng.integers(0, 50, batch).astype(np.float32)
        ts = t + np.sort(rng.integers(0, 400, batch)).astype(np.int64)
        t += 400
        out.append((keys, vals, ts))
    return out


def _drive_sessions(op, batches, snapshot_at=None):
    """Feed batches + watermarks; returns (emitted tuples, snapshot)."""
    rows, snap = [], None
    for i, (keys, vals, ts) in enumerate(batches):
        out = op.process_batch(RecordBatch({"k": keys, "v": vals},
                                           timestamps=ts))
        out += op.process_watermark(Watermark(int(ts.max()) - 1))
        for b in out:
            if hasattr(b, "columns"):
                rows.extend(b.to_rows())
        if snapshot_at == i:
            snap = op.snapshot_state()
    out = op.process_watermark(Watermark(1 << 40))
    for b in out:
        if hasattr(b, "columns"):
            rows.extend(b.to_rows())
    return sorted((int(r["k"]), int(r["window_start"]), int(r["window_end"]),
                   round(float(r["result"]), 2)) for r in rows), snap


def test_mesh_sessions_zipf_matches_single_device():
    """The mesh session operator (device segment fold over the all_to_all
    exchange) produces byte-identical sessions to the single-device one
    under Zipf skew."""
    from flink_tpu.operators.session_window import SessionWindowOperator
    from flink_tpu.parallel.mesh_runtime import MeshSessionWindowOperator
    from flink_tpu.windowing.assigners import EventTimeSessionWindows

    batches = _zipf_session_batches()
    single = SessionWindowOperator(
        EventTimeSessionWindows(120), SumAggregator(jnp.float32),
        key_column="k", value_column="v")
    single.open(RuntimeContext())
    mesh_op = MeshSessionWindowOperator(
        EventTimeSessionWindows(120), SumAggregator(jnp.float32),
        key_column="k", value_column="v", mesh=make_mesh(8))
    mesh_op.open(RuntimeContext())
    ref, _ = _drive_sessions(single, batches)
    got, _ = _drive_sessions(mesh_op, batches)
    assert got == ref and len(ref) > 50


@pytest.mark.parametrize("restore_devices", [8, 4, 1])
def test_mesh_sessions_checkpoint_restore_rescale(restore_devices):
    """Mid-run session snapshot restores onto a different mesh size (and
    onto the single-device operator for 1) and finishes identically."""
    from flink_tpu.operators.session_window import SessionWindowOperator
    from flink_tpu.parallel.mesh_runtime import MeshSessionWindowOperator
    from flink_tpu.windowing.assigners import EventTimeSessionWindows

    batches = _zipf_session_batches()
    mk = lambda d: MeshSessionWindowOperator(  # noqa: E731
        EventTimeSessionWindows(120), SumAggregator(jnp.float32),
        key_column="k", value_column="v", mesh=make_mesh(d))
    ref_op = mk(8)
    ref_op.open(RuntimeContext())
    ref, snap = _drive_sessions(ref_op, batches, snapshot_at=2)
    assert snap is not None and len(snap["session_keys"]) > 0

    if restore_devices == 1:
        op2 = SessionWindowOperator(
            EventTimeSessionWindows(120), SumAggregator(jnp.float32),
            key_column="k", value_column="v")
    else:
        op2 = mk(restore_devices)
    op2.open(RuntimeContext())
    op2.restore_state(snap)
    tail, _ = _drive_sessions(op2, batches[3:])
    # the tail must reproduce every session the reference emitted after the
    # checkpoint (pre-checkpoint emissions excluded)
    pre, _ = _drive_sessions_upto(batches, 3)
    expect = sorted(set(ref) - set(pre) | set())
    assert sorted(set(tail)) == expect


def _drive_sessions_upto(batches, k):
    """Reference emissions for the first k batches only (fresh op)."""
    from flink_tpu.operators.session_window import SessionWindowOperator
    from flink_tpu.windowing.assigners import EventTimeSessionWindows

    op = SessionWindowOperator(
        EventTimeSessionWindows(120), SumAggregator(jnp.float32),
        key_column="k", value_column="v")
    op.open(RuntimeContext())
    rows = []
    for keys, vals, ts in batches[:k]:
        out = op.process_batch(RecordBatch({"k": keys, "v": vals},
                                           timestamps=ts))
        out += op.process_watermark(Watermark(int(ts.max()) - 1))
        for b in out:
            if hasattr(b, "columns"):
                rows.extend(b.to_rows())
    return sorted((int(r["k"]), int(r["window_start"]), int(r["window_end"]),
                   round(float(r["result"]), 2)) for r in rows), None
