"""Production-QPS serving tier (ISSUE-13): binary columnar wire protocol,
client-side key-group routing, hot-key response caching, N-replica
fan-out, and per-worker serving in ProcessCluster.

The PR-9 suite (test_queryable_serving.py) covers the read tiers'
semantics; THIS suite covers the throughput rebuild on top of them —
codec round trips at the dtype edges, routing-table agreement with the
operators' own key-group assignment, cache invalidation on checkpoint
complete, protocol negotiation between old and new peers, replica
failover under a scoped partition, and the stale-endpoint-map retry the
routed client self-heals with.
"""

import json
import socket
import socketserver
import struct
import sys
import textwrap
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from flink_tpu.core.batch import RecordBatch, Watermark
from flink_tpu.core.functions import RuntimeContext, SumAggregator
from flink_tpu.operators.window_agg import WindowAggOperator
from flink_tpu.queryable import (QueryableStateClientPool,
                                 QueryableStateService, QueryableStateSpec,
                                 WindowReadView, wire)
from flink_tpu.queryable.replica import (REPLICA_FETCH_POINT,
                                         CheckpointReplica, ReplicaGroup)
from flink_tpu.queryable.view import route_keys
from flink_tpu.runtime.checkpoint.storage import InMemoryCheckpointStorage
from flink_tpu.state.shard_layout import ShardLayout
from flink_tpu.testing import chaos
from flink_tpu.testing.chaos import FaultInjector, Partition
from flink_tpu.windowing.assigners import TumblingEventTimeWindows

WINDOW_MS = 1000

_LEN = struct.Struct("<I")


# ---------------------------------------------------------------------------
# helpers (the PR-9 suite's drain/expect idiom)
# ---------------------------------------------------------------------------

def _build_op(queryable="agg", **kw):
    kw.setdefault("snapshot_source", "mirror")
    op = WindowAggOperator(TumblingEventTimeWindows.of(WINDOW_MS),
                           SumAggregator(jnp.float32), key_column="k",
                           value_column="v", emit_tier="host",
                           queryable=queryable, **kw)
    op.open(RuntimeContext())
    return op


def _batches(n=6, b=512, keys=61, seed=9, t0=0):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        k = rng.integers(0, keys, b)
        v = rng.integers(1, 8, b).astype(np.float32)
        ts = t0 + i * (WINDOW_MS // 2) + np.sort(
            rng.integers(0, WINDOW_MS // 2, b)).astype(np.int64)
        out.append((k, v, ts))
    return out


def _drain(op, batches):
    out = []
    for k, v, ts in batches:
        out += op.process_batch(RecordBatch({"k": k, "v": v},
                                            timestamps=ts))
        out += op.process_watermark(Watermark(int(ts.max()) - 1))
    return out


def _assembled_from(op, cid, uid="win"):
    op.prepare_snapshot_pre_barrier()
    return {uid: {"subtasks": [{"operator": {"op0": op.snapshot_state()}}]},
            "__job__": {"checkpoint_id": cid}}


def _expected_sums(batches):
    exp = {}
    for k, v, _ts in batches:
        for kk, vv in zip(k.tolist(), v.tolist()):
            exp[kk] = exp.get(kk, 0.0) + vv
    return exp


# ---------------------------------------------------------------------------
# binary columnar codec
# ---------------------------------------------------------------------------

def test_wire_codec_round_trip_edge_values():
    """NaN/±inf float payloads and int64 extremes must survive the wire
    bit-exactly — raw column bytes, not a decimal text path."""
    found = np.array([1, 0, 1, 1, 1], bool)
    i64 = np.array([np.iinfo(np.int64).min, 0, -1,
                    np.iinfo(np.int64).max, 7], np.int64)
    f64 = np.array([float("nan"), 0.0, float("inf"),
                    float("-inf"), -0.0], np.float64)
    f32 = np.array([1.5, 2.5, 3.5, 4.5, 5.5], np.float32)
    obj = np.array(["a", None, "c", "", "e"], object)
    tags = {"consistency": "checkpoint", "checkpoint_id": 12,
            "replica_lag_checkpoints": 0}
    payload = wire.encode_response(
        found, {"cnt": i64, "val": f64, "f": f32, "tag": obj}, tags)
    assert wire.is_binary(payload)
    f2, cols, t2 = wire.decode_response(payload)
    assert f2.tolist() == found.tolist()
    assert t2 == tags
    assert cols["cnt"].dtype == np.int64
    assert cols["cnt"].tolist() == i64.tolist()
    # bit-exact floats: compare raw bytes (NaN != NaN)
    assert cols["val"].tobytes() == f64.tobytes()
    assert np.signbit(cols["val"][4])          # -0.0 preserved
    assert cols["f"].dtype == np.float32
    assert cols["f"].tolist() == f32.tolist()
    assert cols["tag"].tolist() == obj.tolist()


def test_wire_request_round_trip_and_negotiation():
    req = wire.encode_request("agg", np.arange(9, dtype=np.int64),
                              "checkpoint")
    assert wire.is_binary(req)
    state, keys, cons = wire.decode_request(req)
    assert state == "agg" and cons == "checkpoint"
    assert isinstance(keys, np.ndarray) and keys.dtype == np.int64
    # python int lists take the raw-int64 fast path too
    _s, k2, _c = wire.decode_request(
        wire.encode_request("agg", [5, 6, 7], "live"))
    assert isinstance(k2, np.ndarray) and k2.tolist() == [5, 6, 7]
    # object keys ride as JSON
    _s, k3, _c = wire.decode_request(
        wire.encode_request("agg", ["x", 3, True], "live"))
    assert k3 == ["x", 3, True]
    # a JSON request can never read as binary (0xFB is not valid JSON)
    assert not wire.is_binary(json.dumps({"state": "agg"}).encode())
    # unknown versions fail loudly, never silently misparse
    bad = bytearray(req)
    bad[1] = 99
    with pytest.raises(wire.WireError):
        wire.decode_request(bytes(bad))
    with pytest.raises(RuntimeError, match="boom"):
        wire.decode_response(wire.encode_error("boom"))


def test_columnar_lookup_equals_dict_lookup():
    """The two encodings of one contract: the columnar path's answers,
    converted back to per-key dicts, must equal the dict path's."""
    op = _build_op()
    batches = _batches()
    _drain(op, batches)
    view = op.queryable_view()
    rng = np.random.default_rng(3)
    q = rng.integers(0, 80, 64).astype(np.int64)     # some keys missing
    f_d, v_d, t_d = view.lookup_batch(q)
    f_c, cols, t_c = view.lookup_batch_columnar(q)
    assert f_c.tolist() == f_d.tolist()
    assert t_c == t_d
    assert wire.values_from_columnar(f_c, cols) == v_d
    # replica twin
    rep = CheckpointReplica(QueryableStateSpec("agg", "win", "k", op.agg))
    assert rep.ingest_assembled(1, _assembled_from(op, 1))
    f_d, v_d, _ = rep.lookup_batch(q)
    f_c, cols, _ = rep.lookup_batch_columnar(q)
    assert f_c.tolist() == f_d.tolist()
    assert wire.values_from_columnar(f_c, cols) == v_d


# ---------------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------------

def test_routing_table_matches_shard_layout_route_keys():
    """One assignment, three call sites: the client's batch partitioning,
    the view's per-subtask routing, and ``ShardLayout.route_keys`` must
    agree key for key — otherwise a routed lookup lands on a server that
    does not own the key's state."""
    rng = np.random.default_rng(11)
    keys = rng.integers(0, 1 << 30, 4096).astype(np.int64)
    for p in (1, 2, 3, 4, 7):
        layout = ShardLayout(n_shards=p, K=p * 8)
        a = layout.route_keys(keys, max_parallelism=128)
        b = route_keys(keys, p, 128)
        assert (a == b).all(), f"parallelism {p}"


def test_client_fanout_covers_every_key_exactly_once():
    svc = QueryableStateService()
    views = [WindowReadView("k") for _ in range(3)]
    svc.register_views("agg", views, 3, 128)
    server = svc.start_server()
    try:
        pool = QueryableStateClientPool(server.host, server.port,
                                        protocol="binary", routing=True)
        keys = np.arange(333, dtype=np.int64)
        groups = pool._split_by_endpoint("agg", keys)
        assert groups is not None
        seen = np.concatenate(list(groups.values()))
        assert sorted(seen.tolist()) == list(range(333))
        owner = route_keys(keys, 3, 128)
        for _ep, sel in groups.items():
            subs = set(owner[sel].tolist())
            # every endpoint group is a union of whole subtasks
            for s in subs:
                assert set(np.flatnonzero(owner == s).tolist()) \
                    <= set(sel.tolist())
        pool.close()
    finally:
        svc.close()


def test_per_subtask_registry_skips_foreign_views():
    """A per-worker registry holds only its own subtasks' views (None
    elsewhere): lookups answer local keys and leave foreign keys
    not-found instead of crashing."""
    op = _build_op()
    _drain(op, _batches())
    view = op.queryable_view()
    svc = QueryableStateService()
    svc.register_views("agg", [view, None], 2, 128)
    keys = np.arange(61, dtype=np.int64)
    owner = route_keys(keys, 2, 128)
    status, got = svc.lookup_batch("agg", keys.tolist())
    assert status == "ok"
    for i, sub in enumerate(owner.tolist()):
        if sub == 1:
            assert not got["found"][i]       # foreign subtask: not here


# ---------------------------------------------------------------------------
# hot-key response cache
# ---------------------------------------------------------------------------

def test_cache_invalidation_on_checkpoint_complete():
    """A cached answer row dies the moment a newer checkpoint is
    ingested: the second read of a hot key after an ingest must return
    the NEW value, and the cache must count the invalidation."""
    op = _build_op(allowed_lateness_ms=60_000)
    b1 = _batches(n=3, seed=20)
    _drain(op, b1)
    svc = QueryableStateService()
    svc.add_replica("agg", QueryableStateSpec("agg", "win", "k", op.agg))
    svc.on_checkpoint_complete(1, _assembled_from(op, 1))
    assert svc.drain_feed()
    exp1 = _expected_sums(b1)
    key = sorted(exp1)[0]
    _status, got1 = svc.lookup_batch("agg", [key], "checkpoint")
    assert got1["found"][0]
    v1 = got1["values"][0]["result"]
    _status, got1b = svc.lookup_batch("agg", [key], "checkpoint")
    assert got1b["values"][0]["result"] == v1
    assert svc.cache.hits >= 1                 # second read was cached
    # new data (later windows) + new checkpoint -> the cached row
    # must NOT survive
    b2 = _batches(n=3, seed=21, t0=10_000)
    _drain(op, b2)
    svc.on_checkpoint_complete(2, _assembled_from(op, 2))
    assert svc.drain_feed()
    _status, got2 = svc.lookup_batch("agg", [key], "checkpoint")
    exp_all = _expected_sums(b1 + b2)
    assert abs(got2["values"][0]["result"] - exp_all[key]) \
        <= 2e-2 + 1e-4 * abs(exp_all[key])
    assert got2["values"][0]["result"] != v1 or exp_all[key] == exp1[key]
    assert svc.cache.invalidations >= 1
    assert svc.stats()["cache"]["entries"] >= 1


def test_cache_invalidation_on_live_publish():
    op = _build_op()
    b1 = _batches(n=2, seed=30)
    _drain(op, b1)
    svc = QueryableStateService()
    svc.register_views("agg", [op.queryable_view()], 1, 128)
    key = int(b1[0][0][0])
    _s, got1 = svc.lookup_batch("agg", [key], "live")
    _s, got1b = svc.lookup_batch("agg", [key], "live")
    assert got1b["values"] == got1["values"]
    hits_before = svc.cache.hits
    assert hits_before >= 1
    # another fired window bumps the view epoch: cache re-misses
    _drain(op, _batches(n=2, seed=31, t0=10_000))
    _s, got2 = svc.lookup_batch("agg", [key], "live")
    assert svc.cache.invalidations >= 1
    assert got2["found"][0]


# ---------------------------------------------------------------------------
# protocol negotiation (mixed old/new peers)
# ---------------------------------------------------------------------------

class _Pr9JsonOnlyServer:
    """A PR-9-era server: length-prefixed JSON only — a binary frame
    reads as malformed.  The negotiation target for new clients."""

    def __init__(self, registry):
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        hdr = self._recv(_LEN.size)
                        if hdr is None:
                            return
                        (n,) = _LEN.unpack(hdr)
                        payload = self._recv(n)
                        if payload is None:
                            return
                        try:
                            req = json.loads(payload)
                            resp = registry.lookup_batch(
                                req["state"], req["keys"],
                                req.get("consistency", "live"))
                        except (ValueError, TypeError, KeyError,
                                UnicodeDecodeError):
                            resp = ("err", "malformed request")
                        data = json.dumps(
                            resp, default=outer._safe).encode()
                        self.request.sendall(_LEN.pack(len(data)) + data)
                except (ConnectionError, OSError):
                    return

            def _recv(self, n):
                buf = b""
                while len(buf) < n:
                    chunk = self.request.recv(n - len(buf))
                    if not chunk:
                        return None
                    buf += chunk
                return buf

        self._srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0),
                                                    Handler)
        self._srv.daemon_threads = True
        self.host, self.port = self._srv.server_address
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()

    @staticmethod
    def _safe(v):
        return v.item() if isinstance(v, np.generic) else (
            v.tolist() if isinstance(v, np.ndarray) else str(v))

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()


def test_protocol_negotiation_mixed_old_new():
    """Old JSON client against the new server AND new auto client against
    an old JSON-only server: both keep working, and both return the same
    answers the binary path returns."""
    op = _build_op()
    _drain(op, _batches())
    svc = QueryableStateService()
    svc.register_views("agg", [op.queryable_view()], 1, 128)
    new_server = svc.start_server()
    old_server = _Pr9JsonOnlyServer(svc.registry)
    keys = np.arange(40, dtype=np.int64)
    try:
        # new client, binary, new server: the reference answer
        bpool = QueryableStateClientPool(new_server.host, new_server.port,
                                         protocol="binary")
        bf, bc, _bt = bpool.get_batch_columnar("agg", keys)
        ref = {"found": bf.tolist(),
               "values": wire.values_from_columnar(bf, bc)}
        # old client (pure JSON), new server
        jpool = QueryableStateClientPool(new_server.host, new_server.port)
        jgot = jpool.get_batch("agg", keys.tolist())
        assert jgot["found"] == ref["found"]
        assert jgot["values"] == ref["values"]
        # new auto client, OLD server: negotiates down to JSON
        apool = QueryableStateClientPool(old_server.host, old_server.port,
                                         protocol="auto")
        af, ac, _at = apool.get_batch_columnar("agg", keys)
        assert apool.stats["json_fallbacks"] >= 1
        assert af.tolist() == ref["found"]
        assert wire.values_from_columnar(af, ac) == ref["values"]
        # forced-binary client against the old server fails LOUDLY
        fpool = QueryableStateClientPool(old_server.host, old_server.port,
                                         protocol="binary")
        with pytest.raises(RuntimeError, match="binary"):
            fpool.get_batch_columnar("agg", keys)
        for p in (bpool, jpool, apool, fpool):
            p.close()
    finally:
        old_server.stop()
        svc.close()


# ---------------------------------------------------------------------------
# replica fan-out + failover
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_replica_fanout_failover_partitioned_member():
    """Partition ONE member of a 2-replica group from the checkpoint
    stream mid-read-storm: every read keeps answering (via the fresh
    sibling) with zero errors, and the staleness stats NAME the dead
    member.  Heal -> the member re-converges and leaves the laggard
    list."""
    storage = InMemoryCheckpointStorage(retain=5)
    op = _build_op(queryable=None, allowed_lateness_ms=60_000)
    b1 = _batches(n=2, seed=40)
    _drain(op, b1)
    storage.store(1, _assembled_from(op, 1))
    svc = QueryableStateService()
    group = svc.add_replica("agg",
                            QueryableStateSpec("agg", "win", "k", op.agg),
                            storage=storage, replicas=2)
    assert isinstance(group, ReplicaGroup)
    assert [m.name for m in group.members] == ["agg#r0", "agg#r1"]
    for m in group.members:
        assert m.poll_once()
    exp1 = _expected_sums(b1)
    q = np.asarray(sorted(exp1), np.int64)

    inj = FaultInjector(seed=3)
    part = inj.inject(REPLICA_FETCH_POINT, Partition(replica="agg#r1"))
    b2 = _batches(n=2, seed=41)
    _drain(op, b2)
    storage.store(2, _assembled_from(op, 2))
    storage.store(3, _assembled_from(op, 3))
    exp_all = _expected_sums(b1 + b2)
    with chaos.installed(inj):
        assert group.members[0].poll_once()      # healthy sibling advances
        assert not group.members[1].poll_once()  # partitioned: stays at 1
        # read storm THROUGH the group: every answer fresh, zero errors
        for _ in range(32):
            found, values, tags = group.lookup_batch(q)
            assert found.all()
            assert tags["checkpoint_id"] == 3
            for i, k in enumerate(q.tolist()):
                assert abs(values[i]["result"] - exp_all[k]) \
                    <= 2e-2 + 1e-4 * abs(exp_all[k])
        st = group.stats()
        assert st["laggards"] == ["agg#r1"]       # the gauge NAMES it
        assert st["members"]["agg#r1"]["serving_checkpoint_id"] == 1
        assert st["serving_checkpoint_id"] == 3   # reads see the head
        # the service-level lag stats ride the group's serving view
        assert svc.stats()["per_state"]["agg"]["replica"][
            "laggards"] == ["agg#r1"]
        part.heal()
        assert group.members[1].poll_once()       # re-converges
    st2 = group.stats()
    assert st2["laggards"] == []


def test_replica_group_load_balances_across_fresh_members():
    op = _build_op(queryable=None)
    _drain(op, _batches(n=2, seed=50))
    spec = QueryableStateSpec("agg", "win", "k", op.agg)
    group = ReplicaGroup([CheckpointReplica(spec, name=f"agg#r{i}")
                          for i in range(2)])
    assembled = _assembled_from(op, 1)
    group.ingest_assembled(1, assembled)
    picks = {id(group._pick()) for _ in range(8)}
    assert len(picks) == 2                       # both members take reads


# ---------------------------------------------------------------------------
# stale endpoint map: evict -> refresh -> retry
# ---------------------------------------------------------------------------

def test_stale_endpoint_map_refreshes_and_succeeds():
    """A worker restarted on a NEW port: the routed client's first send
    hits the dead endpoint, evicts the socket, refreshes the map from the
    bootstrap server, and the retry lands on the new endpoint — no caller
    -visible error."""
    op = _build_op()
    _drain(op, _batches())
    view = op.queryable_view()
    # "worker" server 1
    w1 = QueryableStateService()
    w1.register_views("agg", [view], 1, 128)
    s1 = w1.start_server()
    # bootstrap: advertises the worker endpoint, serves no views itself
    boot = QueryableStateService()
    boot.set_state_endpoints("agg", {0: (s1.host, s1.port)},
                             parallelism=1, max_parallelism=128)
    bs = boot.start_server()
    pool = QueryableStateClientPool(bs.host, bs.port, protocol="binary",
                                    routing=True, backoff_s=0.01)
    keys = np.arange(16, dtype=np.int64)
    f, _c, _t = pool.get_batch_columnar("agg", keys)
    assert f.any()
    refreshes_before = pool.stats["routing_refreshes"]
    # the worker dies and comes back on a NEW port
    w1.close()
    w2 = QueryableStateService()
    w2.register_views("agg", [view], 1, 128)
    s2 = w2.start_server()
    assert (s2.host, s2.port) != (s1.host, s1.port)
    boot.set_state_endpoints("agg", {0: (s2.host, s2.port)},
                             parallelism=1, max_parallelism=128)
    # stale map in hand: the lookup must still succeed via evict ->
    # refresh -> retry (never reusing the dead pooled socket)
    f2, c2, _t2 = pool.get_batch_columnar("agg", keys)
    assert f2.tolist() == f.tolist()
    assert pool.stats["routing_refreshes"] > refreshes_before
    assert pool.stats["retries"] >= 1
    pool.close()
    w2.close()
    boot.close()


# ---------------------------------------------------------------------------
# serve-path observability
# ---------------------------------------------------------------------------

def test_serve_spans_and_server_side_histogram():
    from flink_tpu.observability import tracing
    op = _build_op()
    _drain(op, _batches())
    svc = QueryableStateService()
    svc.register_views("agg", [op.queryable_view()], 1, 128)
    svc.add_replica("agg", QueryableStateSpec("agg", "win", "k", op.agg))
    journal = tracing.install(capacity=4096)
    try:
        svc.on_checkpoint_complete(1, _assembled_from(op, 1))
        assert svc.drain_feed()
        server = svc.start_server()
        pool = QueryableStateClientPool(server.host, server.port,
                                        protocol="binary")
        jpool = QueryableStateClientPool(server.host, server.port)
        keys = np.arange(8, dtype=np.int64)
        pool.get_batch_columnar("agg", keys, "live")
        jpool.get_batch("agg", keys.tolist(), "checkpoint")
        pool.close()
        jpool.close()
        names = [s[3] for s in journal.spans()]
        assert "queryable.serve" in names
        assert "queryable.replica_ingest" in names
        serve = next(s for s in journal.spans()
                     if s[3] == "queryable.serve")
        assert serve[6]["protocol"] in ("binary", "json")
        st = svc.stats()
        # the server-side service-time ring (lookup + serialization,
        # recorded by the TCP handler) sits NEXT TO the lookup numbers
        assert st["served_requests"] >= 2
        assert st["serve_p99_ms"] is not None
        assert st["protocols"]["binary"] >= 1
        assert st["protocols"]["json"] >= 1
    finally:
        tracing.uninstall()
        svc.close()


# ---------------------------------------------------------------------------
# per-worker serving e2e in ProcessCluster
# ---------------------------------------------------------------------------

QSERVE_JOB = textwrap.dedent('''
    """Deterministic queryable window job: keyed sum, parallelism 2."""
    import numpy as np
    from flink_tpu.core.functions import SumAggregator
    from flink_tpu.datastream.api import StreamExecutionEnvironment
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    N = 60_000
    K = 64

    def build():
        env = StreamExecutionEnvironment()
        env.set_parallelism(2)
        keys = (np.arange(N) % K).astype(np.int64)
        vals = np.ones(N)
        ts = (np.arange(N) * 2).astype(np.int64)
        (env.from_collection(columns={"k": keys, "v": vals, "t": ts},
                             timestamp_column="t", batch_size=512)
            .key_by("k")
            .window(TumblingEventTimeWindows.of(5_000))
            .aggregate(SumAggregator(), value_column="v",
                       queryable="agg")
            .collect())
        return env.get_stream_graph("qserve-job")
''')


def test_per_worker_serving_e2e_process_cluster(tmp_path):
    """Each worker stands up its own QueryableStateServer fronting its
    local live views + replica shards; the coordinator aggregates the
    endpoint map; a routed client fans live AND checkpoint reads straight
    to the owning workers."""
    from flink_tpu.cluster.distributed import ProcessCluster

    mod = tmp_path / "qserve_job_mod.py"
    mod.write_text(QSERVE_JOB)
    sys.path.insert(0, str(tmp_path))
    pc = None
    pool = None
    try:
        pc = ProcessCluster("qserve_job_mod:build", n_workers=2,
                            checkpoint_storage=InMemoryCheckpointStorage(),
                            checkpoint_interval_ms=300,
                            extra_sys_path=(str(tmp_path),))
        res = {}
        th = threading.Thread(
            target=lambda: res.update(pc.run(timeout_s=120)))
        th.start()
        deadline = time.monotonic() + 90
        eps = {}
        while time.monotonic() < deadline:
            eps = pc.queryable_endpoints()
            if len(set((eps.get("agg") or {}).values())) >= 2:
                break
            time.sleep(0.1)
        assert len(set(eps["agg"].values())) >= 2, \
            f"per-worker endpoints not registered: {eps}"
        srv = pc.start_queryable_server()
        pool = QueryableStateClientPool(srv.host, srv.port,
                                        protocol="binary", routing=True)
        keys = np.arange(64, dtype=np.int64)
        live = ckpt = None
        while time.monotonic() < deadline and (live is None
                                               or ckpt is None):
            try:
                f, c, _t = pool.get_batch_columnar("agg", keys, "live")
                if f.any() and live is None:
                    live = (f, c)
                f, c, t = pool.get_batch_columnar("agg", keys,
                                                  "checkpoint")
                if f.any() and ckpt is None:
                    ckpt = (f, c, t)
            except (RuntimeError, ConnectionError):
                pass
            time.sleep(0.1)
        assert live is not None, "no live values served by the workers"
        assert ckpt is not None, "no checkpoint values served"
        # live reads were FANNED OUT to per-worker endpoints: more than
        # one distinct server answered
        assert pool.stats["routed_batches"] >= 1
        assert pool.stats["fanout_requests"] > \
            pool.stats["routed_batches"], \
            "reads never fanned out past one endpoint"
        f, c = live
        # tumbling 5s windows over 2-ms-spaced records: each fired
        # window holds 2500 records spread over 64 keys
        vals = c["result"][f]
        assert ((vals >= 30) & (vals <= 50)).all(), vals
        th.join(timeout=120)
        assert res.get("state") == "FINISHED", res
        assert res.get("completed_checkpoints")
    finally:
        if pool is not None:
            pool.close()
        sys.path.remove(str(tmp_path))
        sys.modules.pop("qserve_job_mod", None)
