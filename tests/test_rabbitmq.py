"""RabbitMQ connector (RMQSource/RMQSink analogs): AMQP 0-9-1 wire broker
+ client + source/sink."""

import json

import numpy as np
import pytest

from flink_tpu.connectors.rabbitmq import (AmqpBroker, AmqpClient,
                                           PROTOCOL_HEADER, RmqSink,
                                           RmqSource)
from flink_tpu.core.batch import RecordBatch


@pytest.fixture
def broker():
    b = AmqpBroker()
    yield b
    b.stop()


class TestWire:
    def test_handshake_declare_publish_get_ack(self, broker):
        c = AmqpClient(broker.host, broker.port)
        assert c.queue_declare("q1") == 0
        c.publish("q1", b'{"x": 1}')
        c.publish("q1", b'{"x": 2}')
        assert c.queue_declare("q1") == 2
        tag1, body1 = c.get("q1")
        assert json.loads(body1) == {"x": 1}
        tag2, body2 = c.get("q1")
        assert json.loads(body2) == {"x": 2}
        assert c.get("q1") is None            # empty
        c.ack(tag2, multiple=True)            # acks tag1 too
        c.close()
        # acked messages are gone for the next consumer
        c2 = AmqpClient(broker.host, broker.port)
        assert c2.get("q1") is None
        c2.close()

    def test_unacked_messages_redeliver_on_connection_drop(self, broker):
        c = AmqpClient(broker.host, broker.port)
        c.queue_declare("q2")
        c.publish("q2", b"a")
        c.publish("q2", b"b")
        c2 = AmqpClient(broker.host, broker.port)
        assert c2.get("q2")[1] == b"a"        # fetched, NOT acked
        c2.sock.close()                       # hard drop (no Connection.Close)
        import time
        time.sleep(0.2)                       # broker notices the EOF
        got = []
        while True:
            m = c.get("q2")
            if m is None:
                break
            got.append(m[1])
            c.ack(m[0])
        assert sorted(got) == [b"a", b"b"]    # nothing lost
        c.close()

    def test_bad_protocol_header_rejected(self, broker):
        import socket as _socket
        s = _socket.create_connection((broker.host, broker.port), timeout=5)
        s.sendall(b"HTTP/1.1 GET /\r\n")
        got = s.recv(16)
        assert got == PROTOCOL_HEADER         # spec: answer header + close
        assert s.recv(16) == b""
        s.close()

    def test_empty_body_and_large_body(self, broker):
        c = AmqpClient(broker.host, broker.port)
        c.queue_declare("q3")
        c.publish("q3", b"")
        big = bytes(range(256)) * 2048        # 512 KiB
        c.publish("q3", big)
        assert c.get("q3", no_ack=True)[1] == b""
        assert c.get("q3", no_ack=True)[1] == big
        c.close()


class TestConnector:
    def test_sink_to_source_round_trip(self, broker):
        sink = RmqSink(broker.host, broker.port, "events")
        sink.open(None)
        sink.write_batch(RecordBatch(
            {"k": np.asarray([1, 2, 3], np.int64),
             "v": np.asarray([1.5, 2.5, 3.5])}))
        sink.close()
        src = RmqSource(broker.host, broker.port, "events")
        (split,) = src.create_splits(1)
        rows = [r for b in split.read() for r in b.to_rows()]
        assert sorted((r["k"], r["v"]) for r in rows) == \
            [(1, 1.5), (2, 2.5), (3, 3.5)]
        # drained and acked: a second read sees nothing
        (split2,) = src.create_splits(1)
        assert list(split2.read()) == []

    def test_source_in_pipeline(self, broker):
        from flink_tpu.datastream.api import StreamExecutionEnvironment

        sink = RmqSink(broker.host, broker.port, "nums")
        sink.open(None)
        sink.write_batch(RecordBatch(
            {"k": np.asarray([0, 1, 0, 1], np.int64),
             "v": np.asarray([1.0, 2.0, 3.0, 4.0])}))
        sink.close()
        env = StreamExecutionEnvironment()
        rows = (env.from_source(
            RmqSource(broker.host, broker.port, "nums"))
            .key_by("k").sum("v", output_column="total")
            .execute_and_collect())
        finals = {}
        for r in rows:
            finals[r["k"]] = max(r["total"], finals.get(r["k"], 0.0))
        assert finals == {0: 4.0, 1: 6.0}


def test_crash_before_drain_completion_redelivers_everything(broker):
    """The at-least-once contract: acks land only at FULL drain
    completion, so a consumer dying mid-drain (even after yielding
    batches) loses nothing."""
    sink = RmqSink(broker.host, broker.port, "alo")
    sink.open(None)
    sink.write_batch(RecordBatch({"k": np.arange(10, dtype=np.int64)}))
    sink.close()
    src = RmqSource(broker.host, broker.port, "alo", batch_rows=3)
    (split,) = src.create_splits(1)
    g = split.read()
    next(g)                               # one batch yielded, NOT acked
    g.close()                             # crash mid-drain
    import time
    time.sleep(0.2)                       # broker requeues unacked
    (split2,) = src.create_splits(1)
    rows = [r for b in split2.read() for r in b.to_rows()]
    assert sorted(r["k"] for r in rows) == list(range(10))


def test_heterogeneous_rows_union_columns(broker):
    c = AmqpClient(broker.host, broker.port)
    c.queue_declare("het")
    c.publish("het", b'{"k": 1}')
    c.publish("het", b'{"k": 2, "v": 3.5}')
    c.close()
    src = RmqSource(broker.host, broker.port, "het")
    (split,) = src.create_splits(1)
    rows = [r for b in split.read() for r in b.to_rows()]
    assert rows[0]["k"] == 1 and np.isnan(rows[0]["v"])  # missing -> NaN
    assert rows[1] == {"k": 2, "v": 3.5}
