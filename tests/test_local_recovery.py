"""Task-local state store (TaskLocalStateStoreImpl.java:54 analog):
secondary worker-local snapshot copies; restore prefers local over the
coordinator-shipped remote state.
"""

import numpy as np

from flink_tpu.runtime.checkpoint.local import TaskLocalStateStore


def test_store_load_roundtrip(tmp_path):
    s = TaskLocalStateStore(str(tmp_path), worker_index=0)
    snap = {"operator": {"total": 3.5}, "arr": np.arange(4)}
    s.store(7, "v1", 0, snap)
    got = s.load(7, "v1", 0)
    assert got["operator"] == {"total": 3.5}
    assert np.array_equal(got["arr"], np.arange(4))
    assert s.load(7, "v1", 1) is None          # other subtask absent
    assert s.load(8, "v1", 0) is None          # other checkpoint absent


def test_confirm_prunes_older_checkpoints(tmp_path):
    s = TaskLocalStateStore(str(tmp_path), worker_index=1)
    for cid in (1, 2, 3):
        s.store(cid, "v1", 0, {"cid": cid})
    s.confirm(3)
    assert s.checkpoint_ids() == [3]
    assert s.load(3, "v1", 0) == {"cid": 3}
    assert s.load(2, "v1", 0) is None


def test_workers_are_isolated(tmp_path):
    a = TaskLocalStateStore(str(tmp_path), worker_index=0)
    b = TaskLocalStateStore(str(tmp_path), worker_index=1)
    a.store(1, "v", 0, {"w": 0})
    assert b.load(1, "v", 0) is None


def test_corrupt_entry_falls_back_to_none(tmp_path):
    s = TaskLocalStateStore(str(tmp_path), worker_index=0)
    s.store(1, "v", 0, {"x": 1})
    with open(s._path(1, "v", 0), "wb") as f:
        f.write(b"not a pickle")
    assert s.load(1, "v", 0) is None           # silent remote fallback


def test_uid_quoting(tmp_path):
    s = TaskLocalStateStore(str(tmp_path), worker_index=0)
    uid = "map/with:odd chars?"
    s.store(1, uid, 3, {"ok": True})
    assert s.load(1, uid, 3) == {"ok": True}
