"""Sharded execution tests on the 8-device virtual CPU mesh (conftest forces
``--xla_force_host_platform_device_count=8`` — the MiniCluster analog)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flink_tpu.core.batch import RecordBatch
from flink_tpu.core.functions import SumAggregator
from flink_tpu.parallel.exchange import make_all_to_all_exchange
from flink_tpu.parallel.mesh import KeyGroupSharding, make_mesh, state_sharding
from flink_tpu.parallel.window_shard import sharded_window_operator
from flink_tpu.testing.harness import KeyedOneInputOperatorHarness
from flink_tpu.windowing import TumblingEventTimeWindows


def test_mesh_and_sharding_specs():
    mesh = make_mesh(8)
    assert mesh.devices.size == 8
    sh = KeyGroupSharding(max_parallelism=128, num_shards=8)
    kg = np.arange(128)
    shards = sh.shard_of_key_group(kg)
    # contiguous ranges, all shards used, monotone
    assert shards.min() == 0 and shards.max() == 7
    assert (np.diff(shards) >= 0).all()
    counts = np.bincount(shards, minlength=8)
    assert counts.min() >= 128 // 8 - 1


def test_sharded_window_agg_matches_single_device():
    rng = np.random.default_rng(0)
    n = 5000
    keys = rng.integers(0, 257, n)
    vals = rng.random(n).astype(np.float32)
    ts = np.sort(rng.integers(0, 5000, n))

    def run(op):
        h = KeyedOneInputOperatorHarness(op)
        for lo in range(0, n, 512):
            hi = min(lo + 512, n)
            h.process_batch(RecordBatch({"k": keys[lo:hi], "v": vals[lo:hi]},
                                        timestamps=ts[lo:hi]))
        h.process_watermark(10_000)
        return {(r["k"], r["window_start"]): r["result"]
                for r in h.extract_output_rows()}

    from flink_tpu.operators.window_agg import WindowAggOperator
    single = run(WindowAggOperator(TumblingEventTimeWindows.of(1000),
                                   SumAggregator(jnp.float32),
                                   key_column="k", value_column="v"))
    mesh = make_mesh(8)
    sharded = run(sharded_window_operator(
        mesh, assigner=TumblingEventTimeWindows.of(1000),
        agg=SumAggregator(jnp.float32), key_column="k", value_column="v"))
    assert set(single) == set(sharded)
    for kk in single:
        assert abs(single[kk] - sharded[kk]) < 1e-3


def test_sharded_state_is_actually_distributed():
    mesh = make_mesh(8)
    op = sharded_window_operator(
        mesh, assigner=TumblingEventTimeWindows.of(100),
        agg=SumAggregator(jnp.float32), key_column="k", value_column="v")
    h = KeyedOneInputOperatorHarness(op)
    h.process_batch(RecordBatch({"k": np.arange(100), "v": np.ones(100, np.float32)},
                                timestamps=np.zeros(100, np.int64)))
    leaf = op._leaves[0]
    assert len(leaf.sharding.device_set) == 8


def test_all_to_all_exchange_routes_by_shard():
    mesh = make_mesh(8)
    D, B, cap = 8, 16, 32
    ex = make_all_to_all_exchange(mesh, num_leaves=2, cap=cap)
    rng = np.random.default_rng(3)
    # [D*B] records scattered over devices; dest = key % D
    keys = rng.integers(0, 1000, D * B).astype(np.int32)
    vals = rng.random(D * B).astype(np.float32)
    dest = (keys % D).astype(np.int32)
    rx_leaves, rx_valid, overflow = ex(jnp.asarray(dest),
                                       jnp.asarray(keys), jnp.asarray(vals))
    assert int(np.sum(np.asarray(overflow))) == 0
    rx_keys = np.asarray(rx_leaves[0])
    rx_vals = np.asarray(rx_leaves[1])
    valid = np.asarray(rx_valid)
    # every record arrives exactly once, on the device owning its key
    assert valid.sum() == D * B
    got = sorted(zip(rx_keys[valid].tolist(), rx_vals[valid].tolist()))
    want = sorted(zip(keys.tolist(), vals.tolist()))
    assert got == want
    # placement: received row i on shard s must satisfy key % D == s
    per_dev = valid.reshape(D, D * cap)
    keys_dev = rx_keys.reshape(D, D * cap)
    for s in range(D):
        assert (keys_dev[s][per_dev[s]] % D == s).all()


def test_exchange_overflow_reported():
    mesh = make_mesh(8)
    cap = 2
    ex = make_all_to_all_exchange(mesh, num_leaves=1, cap=cap)
    # all records on every device target shard 0 -> overflow beyond cap
    dest = jnp.zeros(8 * 20, jnp.int32)
    vals = jnp.arange(8 * 20, dtype=jnp.float32)
    _, rx_valid, overflow = ex(dest, vals)
    assert int(np.asarray(overflow).sum()) == 8 * 20 - 8 * cap
    assert int(np.asarray(rx_valid).sum()) == 8 * cap


def test_resizing_exchange_forced_overflow_zero_loss():
    """VERDICT r1 #2: overflow must block/resend, never drop.  Every record
    lands on every device targeting ONE shard at a tiny initial capacity;
    the resizing exchange must deliver all of them exactly once."""
    from flink_tpu.parallel.exchange import ResizingExchange

    mesh = make_mesh(8)
    D, B = 8, 20
    ex = ResizingExchange(mesh, num_leaves=1, cap=2)
    dest = jnp.zeros(D * B, jnp.int32)          # extreme skew: all -> shard 0
    vals = jnp.arange(D * B, dtype=jnp.float32)
    rx_leaves, rx_valid, cap_used = ex(dest, vals)
    valid = np.asarray(rx_valid)
    got = sorted(np.asarray(rx_leaves[0])[valid].tolist())
    assert got == sorted(np.asarray(vals).tolist())   # zero loss, no dupes
    assert cap_used >= B                              # capacity renegotiated
    # steady state at the grown capacity: next call needs no further resize
    rx2, rv2, cap2 = ex(dest, vals)
    assert cap2 == cap_used
    assert int(np.asarray(rv2).sum()) == D * B


def test_resizing_exchange_max_cap_guard():
    from flink_tpu.parallel.exchange import ResizingExchange

    mesh = make_mesh(8)
    ex = ResizingExchange(mesh, num_leaves=1, cap=2, max_cap=4)
    dest = jnp.zeros(8 * 20, jnp.int32)
    vals = jnp.ones(8 * 20, jnp.float32)
    with pytest.raises(RuntimeError, match="overflow at max capacity"):
        ex(dest, vals)
