"""SQL depth: joins, changelog aggregation with retraction, Top-N,
deduplication, mini-batch bundling."""

import numpy as np
import pytest

from flink_tpu.sql.table_env import TableEnvironment


@pytest.fixture
def tenv():
    te = TableEnvironment()
    te.register_collection("orders", columns={
        "oid": np.arange(6, dtype=np.int64),
        "cust": np.array([1, 2, 1, 3, 2, 9], np.int64),
        "amount": np.array([10., 20., 30., 40., 50., 60.])})
    te.register_collection("customers", columns={
        "cust": np.array([1, 2, 3], np.int64),
        "name": np.asarray(["alice", "bob", "carol"], object)})
    return te


def test_inner_join_sql(tenv):
    rows = tenv.execute_sql(
        "SELECT o.oid, c.name, o.amount FROM orders o "
        "JOIN customers c ON o.cust = c.cust").collect()
    assert len(rows) == 5               # oid 5 (cust 9) unmatched
    by_oid = {r["oid"]: r["name"] for r in rows}
    assert by_oid[0] == "alice" and by_oid[1] == "bob" and by_oid[3] == "carol"


def test_left_join_sql(tenv):
    rows = tenv.execute_sql(
        "SELECT o.oid, c.name FROM orders o "
        "LEFT JOIN customers c ON o.cust = c.cust").collect()
    assert len(rows) == 6
    assert next(r for r in rows if r["oid"] == 5)["name"] is None


def test_join_then_group_by(tenv):
    rows = tenv.execute_sql(
        "SELECT c.name, SUM(o.amount) AS total FROM orders o "
        "JOIN customers c ON o.cust = c.cust "
        "GROUP BY c.name ORDER BY total DESC").collect()
    assert [(r["name"], r["total"]) for r in rows] == \
        [("bob", 70.0), ("alice", 40.0), ("carol", 40.0)]


def test_join_where_and_ambiguity(tenv):
    rows = tenv.execute_sql(
        "SELECT o.oid FROM orders o JOIN customers c ON o.cust = c.cust "
        "WHERE o.amount > 25").collect()
    assert sorted(r["oid"] for r in rows) == [2, 3, 4]
    from flink_tpu.sql.planner import PlanError
    with pytest.raises(PlanError, match="ambiguous"):
        tenv.execute_sql("SELECT oid FROM orders o "
                         "JOIN customers c ON cust = cust").collect()


def test_join_clashing_columns_renamed(tenv):
    rows = tenv.execute_sql(
        "SELECT o.cust, c.cust FROM orders o "
        "JOIN customers c ON o.cust = c.cust").collect()
    # both sides selectable; right side got a distinct physical name
    assert all(list(r.values())[0] == list(r.values())[1] for r in rows)


def test_changelog_group_agg_retraction(tenv):
    res = (tenv.sql_query("SELECT * FROM orders").group_by("cust")
           .select_changelog("cust, SUM(amount) AS total, COUNT(*) AS n"))
    rows = res.collect()
    ops = [r["op"] for r in rows]
    assert "+I" in ops
    # final accumulated value per key = last +I/+U row
    final = {}
    for r in rows:
        if r["op"] in ("+I", "+U"):
            final[r["cust"]] = (r["total"], r["n"])
        elif r["op"] == "-U":
            pass
    assert final[1] == (40.0, 2.0)
    assert final[2] == (70.0, 2.0)


def test_changelog_retraction_pairs():
    te = TableEnvironment()
    te.register_collection("t", columns={
        "k": np.array([7, 7], np.int64),
        "v": np.array([1., 2.])}, batch_size=1)   # two batches -> an update
    rows = (te.sql_query("SELECT * FROM t").group_by("k")
            .select_changelog("k, SUM(v) AS s").collect())
    assert [r["op"] for r in rows] == ["+I", "-U", "+U"]
    assert rows[1]["s"] == 1.0 and rows[2]["s"] == 3.0


def test_top_n(tenv):
    rows = tenv.sql_query("SELECT * FROM orders").top_n(
        2, partition_by="cust", order_by="amount").collect()
    got = {(r["cust"], r["rank"]): r["amount"] for r in rows}
    assert got[(1, 1)] == 30.0 and got[(1, 2)] == 10.0
    assert got[(2, 1)] == 50.0
    assert (9, 1) in got


def test_top_n_global():
    te = TableEnvironment()
    te.register_collection("t", columns={"x": np.array([5., 1., 9., 7.])})
    rows = te.sql_query("SELECT * FROM t").top_n(
        2, partition_by=None, order_by="x").collect()
    assert [r["x"] for r in rows] == [9.0, 7.0]
    assert [r["rank"] for r in rows] == [1, 2]


def test_deduplicate_first_and_last():
    te = TableEnvironment()
    te.register_collection("t", columns={
        "k": np.array([1, 2, 1, 2], np.int64),
        "v": np.array([10., 20., 30., 40.]),
        "seq": np.array([0, 1, 2, 3], np.int64)})
    first = te.sql_query("SELECT * FROM t").deduplicate("k", keep="first").collect()
    assert {r["k"]: r["v"] for r in first} == {1: 10.0, 2: 20.0}
    last = (te.sql_query("SELECT * FROM t")
            .deduplicate("k", keep="last", order_by="seq").collect())
    assert {r["k"]: r["v"] for r in last} == {1: 30.0, 2: 40.0}


def test_mini_batch_bundles_before_agg():
    te = TableEnvironment(mini_batch_rows=1000)
    n = 2000
    te.register_collection("t", columns={
        "k": np.arange(n) % 3, "v": np.ones(n)}, batch_size=10)
    rows = te.execute_sql(
        "SELECT k, SUM(v) AS s FROM t GROUP BY k ORDER BY k").collect()
    assert [r["s"] for r in rows] == [667.0, 667.0, 666.0]


def test_qualified_single_table(tenv):
    rows = tenv.execute_sql(
        "SELECT o.amount FROM orders o WHERE o.amount >= 50").collect()
    assert sorted(r["amount"] for r in rows) == [50.0, 60.0]


def test_unqualified_ambiguous_select_raises(tenv):
    from flink_tpu.sql.planner import PlanError
    with pytest.raises(PlanError, match="ambiguous"):
        tenv.execute_sql("SELECT cust FROM orders o "
                         "JOIN customers c ON o.cust = c.cust").collect()


def test_table_where_survives_topn_dedup():
    te = TableEnvironment()
    te.register_collection("t", columns={
        "k": np.array([1, 1, 2, 2], np.int64),
        "v": np.array([5., 50., 7., 70.])})
    rows = te.sql_query("SELECT * FROM t").where("v < 10").top_n(
        5, partition_by=None, order_by="v").collect()
    assert sorted(r["v"] for r in rows) == [5.0, 7.0]


def test_left_join_empty_right_side():
    """Regression: an EMPTY right side must still produce null-filled right
    columns on a LEFT JOIN."""
    te = TableEnvironment()
    te.register_collection("l", columns={"k": np.array([1, 2], np.int64),
                                         "lv": np.array([10., 20.])})
    te.register_collection("r", columns={"k": np.zeros(0, np.int64),
                                         "name": np.zeros(0, object)})
    rows = te.execute_sql(
        "SELECT l.lv, r.name FROM l LEFT JOIN r ON l.k = r.k").collect()
    assert len(rows) == 2 and all(r["name"] is None for r in rows)


def test_dedup_parallel_correct():
    """Regression: deduplicate must hash-route by key so parallelism > 1
    cannot emit a key twice."""
    te = TableEnvironment(parallelism=2)
    n = 2000
    te.register_collection("t", columns={
        "k": np.arange(n) % 50, "v": np.arange(n, dtype=np.float64)},
        batch_size=64)
    rows = te.sql_query("SELECT * FROM t").deduplicate("k").collect()
    ks = [r["k"] for r in rows]
    assert sorted(ks) == sorted(set(ks)) and len(set(ks)) == 50


def test_row_number_over_topn_sql(tenv):
    rows = tenv.execute_sql(
        "SELECT * FROM (SELECT cust, amount, "
        "ROW_NUMBER() OVER (PARTITION BY cust ORDER BY amount DESC) AS rn "
        "FROM orders) WHERE rn <= 2").collect()
    got = {(r["cust"], r["rn"]): r["amount"] for r in rows}
    assert got[(1, 1)] == 30.0 and got[(1, 2)] == 10.0
    assert got[(2, 1)] == 50.0 and got[(2, 2)] == 20.0
    assert got[(3, 1)] == 40.0


def test_row_number_global_topn_sql(tenv):
    rows = tenv.execute_sql(
        "SELECT oid, rn FROM (SELECT oid, amount, "
        "ROW_NUMBER() OVER (ORDER BY amount DESC) AS rn FROM orders) "
        "WHERE rn <= 3 ORDER BY rn").collect()
    assert [r["oid"] for r in rows] == [5, 4, 3]


def test_plain_derived_table(tenv):
    rows = tenv.execute_sql(
        "SELECT big_cust, SUM(amount) AS total FROM "
        "(SELECT cust AS big_cust, amount FROM orders WHERE amount > 15) "
        "GROUP BY big_cust ORDER BY big_cust").collect()
    assert [(r["big_cust"], r["total"]) for r in rows] == \
        [(1, 30.0), (2, 70.0), (3, 40.0), (9, 60.0)]


def test_over_needs_time_attribute(tenv):
    # top-level OVER is supported, but only ordered by a rowtime — "orders"
    # has no time attribute, so the planner must reject the order column
    from flink_tpu.sql.planner import PlanError
    with pytest.raises(PlanError, match="time attribute"):
        tenv.execute_sql(
            "SELECT ROW_NUMBER() OVER (ORDER BY amount) FROM orders").collect()


def test_subquery_order_limit_respected(tenv):
    """Regression: a subquery's ORDER BY/LIMIT bound ITS result set."""
    rows = tenv.execute_sql(
        "SELECT SUM(amount) AS s FROM "
        "(SELECT amount FROM orders ORDER BY amount DESC LIMIT 2)").collect()
    assert rows[0]["s"] == 110.0    # 60 + 50


def test_derived_table_join_not_dropped(tenv):
    rows = tenv.execute_sql(
        "SELECT c.name, o.amount FROM "
        "(SELECT cust, amount FROM orders WHERE amount > 45) o "
        "JOIN customers c ON o.cust = c.cust").collect()
    assert sorted((r["name"], r["amount"]) for r in rows) == [("bob", 50.0)]


def test_count_distinct(tenv):
    rows = tenv.execute_sql(
        "SELECT cust, COUNT(DISTINCT amount) AS n FROM orders "
        "GROUP BY cust ORDER BY cust").collect()
    # every amount is unique in the fixture -> same as COUNT(*)
    assert [(r["cust"], r["n"]) for r in rows] == \
        [(1, 2), (2, 2), (3, 1), (9, 1)]


def test_sum_distinct_dedups_values():
    te = TableEnvironment()
    te.register_collection("t", columns={
        "k": np.array([1, 1, 1, 2], np.int64),
        "v": np.array([5., 5., 7., 5.])})
    rows = te.execute_sql(
        "SELECT k, SUM(DISTINCT v) AS s FROM t GROUP BY k ORDER BY k").collect()
    assert [(r["k"], r["s"]) for r in rows] == [(1, 12.0), (2, 5.0)]
    # global (no GROUP BY): distinct per whole table
    rows = te.execute_sql("SELECT COUNT(DISTINCT v) AS n FROM t").collect()
    assert rows[0]["n"] == 2


def test_mixed_distinct_plain_aggregates(tenv):
    # one query, both kinds: planned as two branches re-merged on the key
    rows = tenv.execute_sql(
        "SELECT cust, COUNT(DISTINCT amount) AS d, SUM(amount) AS s, "
        "COUNT(*) AS n FROM orders GROUP BY cust ORDER BY cust").collect()
    assert [(r["cust"], r["d"], r["s"], r["n"]) for r in rows] == \
        [(1, 2, 40.0, 2), (2, 2, 70.0, 2), (3, 1, 40.0, 1), (9, 1, 60.0, 1)]


def test_mixed_distinct_plain_global(tenv):
    rows = tenv.execute_sql(
        "SELECT COUNT(DISTINCT cust) AS d, SUM(amount) AS s "
        "FROM orders").collect()
    assert (rows[0]["d"], rows[0]["s"]) == (4, 210.0)


def test_distinct_in_tumble_window():
    te = TableEnvironment()
    te.register_collection("e", columns={
        "k": np.array([1, 1, 1, 1, 2], np.int64),
        "ts": np.array([1000, 2000, 6000, 7000, 1500], np.int64),
        "v": np.array([5., 5., 5., 7., 5.])}, rowtime="ts")
    rows = te.execute_sql(
        "SELECT k, COUNT(DISTINCT v) AS d FROM e "
        "GROUP BY k, TUMBLE(ts, INTERVAL '5' SECOND) ORDER BY k").collect()
    # key 1: window [0,5s) has {5} -> 1; window [5s,10s) has {5,7} -> 2;
    # the 5.0 recurring in the SECOND window must still count there
    assert sorted((r["k"], r["d"]) for r in rows) == [(1, 1), (1, 2), (2, 1)]


def test_mixed_distinct_plain_in_tumble_window():
    te = TableEnvironment()
    te.register_collection("e", columns={
        "k": np.array([1, 1, 1, 1], np.int64),
        "ts": np.array([1000, 2000, 6000, 7000], np.int64),
        "v": np.array([5., 5., 5., 7.])}, rowtime="ts")
    rows = te.execute_sql(
        "SELECT k, COUNT(DISTINCT v) AS d, SUM(v) AS s, "
        "TUMBLE_START(ts, INTERVAL '5' SECOND) AS ws FROM e "
        "GROUP BY k, TUMBLE(ts, INTERVAL '5' SECOND) ORDER BY ws").collect()
    assert [(r["d"], r["s"]) for r in rows] == [(1, 10.0), (2, 12.0)]


def test_count_distinct_parallel_cluster():
    """Regression: the DISTINCT dedup stage must hash-route by the
    (key, value) pair so parallel subtasks cannot each count a duplicate."""
    from flink_tpu.cluster.task import TaskStates

    te = TableEnvironment(parallelism=2)
    te.register_collection("t", columns={
        "k": np.ones(8, np.int64), "v": np.full(8, 5.0)}, batch_size=1)
    table = te.sql_query("SELECT k, COUNT(DISTINCT v) AS n FROM t GROUP BY k")
    env, plan = te._plan(table._stmt if table._stmt.items else table._stmt)
    # execute on the MiniCluster (real parallelism)
    sink = plan.stream.collect()
    res = env.execute_cluster()
    assert res.state == TaskStates.FINISHED
    rows = [r for r in sink.rows()]
    final = {r["k"]: r["__agg0"] for r in rows if "__agg0" in r}
    if not final:   # post-projection naming
        final = {r["k"]: r["n"] for r in rows}
    assert final == {1: 1.0} or final == {1: 1}


# ---------------------------------------------------------------------------
# UNION / UNION ALL
# ---------------------------------------------------------------------------

def test_union_all(tenv):
    rows = tenv.execute_sql(
        "SELECT oid, amount FROM orders WHERE amount < 25 "
        "UNION ALL SELECT oid, amount FROM orders WHERE amount >= 25 "
        "ORDER BY oid").collect()
    assert [r["oid"] for r in rows] == [0, 1, 2, 3, 4, 5]


def test_union_distinct_dedups():
    te = TableEnvironment()
    te.register_collection("a", columns={"x": np.array([1, 2, 3], np.int64)})
    te.register_collection("b", columns={"x": np.array([2, 3, 4], np.int64)})
    rows = te.execute_sql(
        "SELECT x FROM a UNION SELECT x FROM b ORDER BY x").collect()
    assert [r["x"] for r in rows] == [1, 2, 3, 4]


def test_union_positional_column_alignment():
    te = TableEnvironment()
    te.register_collection("a", columns={"x": np.array([1], np.int64),
                                         "y": np.array([10.0])})
    te.register_collection("b", columns={"p": np.array([2], np.int64),
                                         "q": np.array([20.0])})
    rows = te.execute_sql(
        "SELECT x, y FROM a UNION ALL SELECT p, q FROM b "
        "ORDER BY x").collect()
    assert [(r["x"], r["y"]) for r in rows] == [(1, 10.0), (2, 20.0)]


def test_union_aggregated_branches(tenv):
    rows = tenv.execute_sql(
        "SELECT cust, SUM(amount) AS s FROM orders GROUP BY cust "
        "UNION ALL SELECT cust, COUNT(*) AS c FROM orders GROUP BY cust "
        "ORDER BY cust").collect()
    assert len(rows) == 8   # 4 custs x 2 branches


def test_union_errors(tenv):
    from flink_tpu.sql.parser import SqlParseError
    from flink_tpu.sql.planner import PlanError
    with pytest.raises(PlanError, match="column count"):
        tenv.execute_sql("SELECT oid FROM orders UNION ALL "
                         "SELECT oid, amount FROM orders").collect()
    with pytest.raises(SqlParseError, match="UNION branch"):
        tenv.execute_sql("SELECT oid FROM orders ORDER BY oid "
                         "UNION ALL SELECT oid FROM orders").collect()

def test_union_mixed_all_chain(tenv):
    """Mixed UNION/UNION ALL chains bind left-associatively (SQL standard):
    A UNION B UNION ALL C = (A dedup B) followed by all of C — the
    union_associativity rewrite rule nests the chain before lowering."""
    rows = tenv.execute_sql(
        "SELECT oid FROM orders UNION "
        "SELECT oid FROM orders UNION ALL "
        "SELECT oid FROM orders").collect()
    oids = sorted(int(r["oid"]) for r in rows)
    # (orders UNION orders) = each oid once; UNION ALL appends all rows
    single = sorted(int(r["oid"]) for r in
                    tenv.execute_sql("SELECT oid FROM orders").collect())
    assert oids == sorted(list(set(single)) + single)


def test_union_in_derived_table():
    te = TableEnvironment()
    te.register_collection("a", columns={"x": np.array([1, 5], np.int64)})
    te.register_collection("b", columns={"x": np.array([2, 6], np.int64)})
    rows = te.execute_sql(
        "SELECT SUM(x) AS s FROM "
        "(SELECT x FROM a UNION ALL SELECT x FROM b)").collect()
    assert rows[0]["s"] == 14


def test_union_order_by_ordinal_checked(tenv):
    from flink_tpu.sql.planner import PlanError
    rows = tenv.execute_sql(
        "SELECT oid FROM orders UNION ALL SELECT oid FROM orders "
        "ORDER BY 1 LIMIT 3").collect()
    assert [r["oid"] for r in rows] == [0, 0, 1]
    with pytest.raises(PlanError, match="out of range"):
        tenv.execute_sql("SELECT oid FROM orders UNION ALL "
                         "SELECT oid FROM orders ORDER BY 0").collect()


def test_union_fluent_table_rejected():
    from flink_tpu.sql.planner import PlanError
    te = TableEnvironment()
    te.register_collection("a", columns={"x": np.array([1], np.int64)})
    t = te.sql_query("SELECT x FROM a UNION ALL SELECT x FROM a")
    with pytest.raises(PlanError, match="UNION"):
        t.where("x > 0")


def test_explain_sql(tenv):
    res = tenv.execute_sql(
        "EXPLAIN SELECT cust, SUM(amount) AS s FROM orders GROUP BY cust")
    text = res.collect()[0]["plan"]
    assert "Physical Execution Plan" in text
    assert "sql-group-agg" in text and "hash" in text
    assert "Output columns: ['cust', 's']" in text


def test_insert_into_sink_table(tenv, tmp_path):
    out = str(tmp_path / "totals.csv")
    tenv.register_sink_table("totals", out)
    res = tenv.execute_sql(
        "INSERT INTO totals SELECT cust, SUM(amount) AS total FROM orders "
        "GROUP BY cust ORDER BY cust")
    assert res.collect()[0]["rows_written"] == 4
    from flink_tpu.core.batch import RecordBatch
    from flink_tpu.formats import reader_for
    got = RecordBatch.concat(list(reader_for("csv")(out)))
    assert len(got) == 4
    from flink_tpu.sql.planner import PlanError
    with pytest.raises(PlanError, match="unknown sink"):
        tenv.execute_sql("INSERT INTO nope SELECT * FROM orders")


# ---------------------------------------------------------------------------
# device join kernel (ops/join_kernels): same pair set as the numpy join
# ---------------------------------------------------------------------------

def test_device_join_pairs_matches_numpy():
    from flink_tpu.operators.joins import _join_pairs
    from flink_tpu.ops.join_kernels import device_join_pairs

    rng = np.random.default_rng(3)
    lk = rng.integers(0, 50, 300).astype(np.int64)
    rk = rng.integers(0, 50, 200).astype(np.int64)
    li_n, ri_n = _join_pairs(lk, rk)
    li_d, ri_d = device_join_pairs(lk, rk)
    want = sorted(zip(lk[li_n].tolist(), li_n.tolist(), ri_n.tolist()))
    got = sorted(zip(lk[li_d].tolist(), li_d.tolist(), ri_d.tolist()))
    assert got == want
    # pair keys really are equal
    assert (lk[li_d] == rk[ri_d]).all()


def test_device_join_pairs_object_keys_and_empties():
    from flink_tpu.ops.join_kernels import device_join_pairs

    lk = np.asarray(["a", "b", "a", "c"], dtype=object)
    rk = np.asarray(["a", "z", "b", "a"], dtype=object)
    li, ri = device_join_pairs(lk, rk)
    pairs = sorted(zip(li.tolist(), ri.tolist()))
    assert pairs == [(0, 0), (0, 3), (1, 2), (2, 0), (2, 3)]
    li, ri = device_join_pairs(np.zeros(0, np.int64), rk)
    assert li.size == 0


def test_sql_join_via_device_kernel(tenv, monkeypatch):
    """End-to-end SQL join with the device kernel switched on."""
    monkeypatch.setenv("FLINK_TPU_DEVICE_JOIN", "1")
    rows = tenv.execute_sql(
        "SELECT o.cust, c.name, o.amount FROM orders o "
        "JOIN customers c ON o.cust = c.cust").collect()
    assert len(rows) >= 1
    monkeypatch.delenv("FLINK_TPU_DEVICE_JOIN")
    rows2 = tenv.execute_sql(
        "SELECT o.cust, c.name, o.amount FROM orders o "
        "JOIN customers c ON o.cust = c.cust").collect()
    key = lambda r: tuple(sorted(r.items()))  # noqa: E731
    assert sorted(map(key, rows)) == sorted(map(key, rows2))


def test_changelog_agg_device_state_and_snapshot_roundtrip():
    """The changelog group-agg is device-resident (StreamExecGroupAggregate
    analog): state is a dense jax array; snapshots roundtrip in the new
    columnar format and keep accumulating."""
    import jax

    from flink_tpu.operators.sql_ops import ChangelogGroupAggOperator

    op = ChangelogGroupAggOperator("k", {"s": ("v", "sum"),
                                         "mn": ("v", "min"),
                                         "mx": ("v", "max"),
                                         "n": (None, "count")})
    from flink_tpu.core.batch import RecordBatch
    out = op.process_batch(RecordBatch({
        "k": np.array([1, 2, 1], np.int64),
        "v": np.array([3., 5., 7.], np.float64)}))
    assert isinstance(op._state[0], jax.Array)
    rows = [r for b in out for r in b.to_rows()]
    byk = {r["k"]: r for r in rows}
    assert byk[1]["op"] == "+I" and byk[1]["s"] == 10.0
    assert byk[1]["mn"] == 3.0 and byk[1]["mx"] == 7.0 and byk[1]["n"] == 2.0

    snap = op.snapshot_state()
    op2 = ChangelogGroupAggOperator("k", {"s": ("v", "sum"),
                                          "mn": ("v", "min"),
                                          "mx": ("v", "max"),
                                          "n": (None, "count")})
    op2.restore_state(snap)
    out2 = op2.process_batch(RecordBatch({
        "k": np.array([1], np.int64), "v": np.array([1.], np.float64)}))
    rows2 = [r for b in out2 for r in b.to_rows()]
    assert [r["op"] for r in rows2] == ["-U", "+U"]
    assert rows2[1]["s"] == 11.0 and rows2[1]["mn"] == 1.0


def test_changelog_count_exact_past_f32_precision():
    """Double-single accumulation: counts/sums stay exact far past 2^24,
    where a plain f32 accumulator would freeze."""
    from flink_tpu.core.batch import RecordBatch
    from flink_tpu.operators.sql_ops import ChangelogGroupAggOperator

    op = ChangelogGroupAggOperator("k", {"n": (None, "count")})
    total = 0
    for _ in range(20):
        b = 1 << 20
        op.process_batch(RecordBatch({"k": np.zeros(b, np.int64)}))
        total += b
    out = op.process_batch(RecordBatch({"k": np.zeros(3, np.int64)}))
    rows = [r for bt in out for r in bt.to_rows()]
    assert rows[-1]["n"] == total + 3


def test_changelog_minmax_exact_past_f32_precision():
    """min/max carry Dekker (hi, lo) pairs: integer-valued inputs above
    2^24 — where plain f32 collapses adjacent integers — stay exact, so
    change detection never misses or fabricates -U/+U pairs."""
    from flink_tpu.core.batch import RecordBatch
    from flink_tpu.operators.sql_ops import ChangelogGroupAggOperator

    big = (1 << 24)  # 16777216: f32(big) == f32(big + 1)
    op = ChangelogGroupAggOperator("k", {"mn": ("v", "min"),
                                         "mx": ("v", "max")})
    out = op.process_batch(RecordBatch({
        "k": np.zeros(2, np.int64),
        "v": np.array([big + 1, big + 3], np.int64)}))
    rows = [r for b in out for r in b.to_rows()]
    assert rows[-1]["mn"] == big + 1 and rows[-1]["mx"] == big + 3

    # a new min one integer below: f32 cannot represent the difference,
    # the pair can — the -U/+U change must be emitted with exact values
    out = op.process_batch(RecordBatch({
        "k": np.zeros(1, np.int64), "v": np.array([big], np.int64)}))
    rows = [r for b in out for r in b.to_rows()]
    assert [r["op"] for r in rows] == ["-U", "+U"]
    assert rows[1]["mn"] == big and rows[1]["mx"] == big + 3

    # equal-to-current-min arrival: NO change rows (f32 ties broken by the
    # low word must not fabricate updates)
    out = op.process_batch(RecordBatch({
        "k": np.zeros(1, np.int64), "v": np.array([big], np.int64)}))
    assert out == []


def test_dedup_keep_last_arrival_order_across_batches():
    """keep='last' without an order column: a later BATCH's row must beat an
    earlier batch's row regardless of in-batch position."""
    from flink_tpu.core.batch import RecordBatch
    from flink_tpu.operators.sql_ops import DeduplicateOperator

    op = DeduplicateOperator("k", keep="last")
    op.process_batch(RecordBatch({
        "k": np.array([5, 5, 7], np.int64),
        "v": np.array([1., 2., 3.])}))          # key 5 last row in batch 1: v=2
    op.process_batch(RecordBatch({
        "k": np.array([5], np.int64), "v": np.array([9.])}))  # position 0!
    out = op.end_input()
    rows = {r["k"]: r["v"] for b in out for r in b.to_rows()}
    assert rows == {5: 9.0, 7: 3.0}
    # emitted column is numeric, not object (device-consumable downstream)
    assert out[0].column("v").dtype.kind == "f"


# ---------------------------------------------------------------------------
# DISTINCT aggregates in HOP windows (was an explicit known gap): rows expand
# to per-covering-window copies so the dedup key can name the window
# ---------------------------------------------------------------------------

def _hop_distinct_env():
    te = TableEnvironment()
    te.register_collection("t", columns={
        "k": np.array([1, 1, 1, 1], np.int64),
        "v": np.array([5, 7, 5, 7], np.int64),
        "ts": np.array([0, 1000, 1500, 2000], np.int64)}, rowtime="ts")
    return te


def test_hop_count_distinct():
    rows = _hop_distinct_env().execute_sql(
        "SELECT k, COUNT(DISTINCT v) AS dc, "
        "HOP_START(ts, INTERVAL '1' SECOND, INTERVAL '2' SECOND) AS ws "
        "FROM t GROUP BY k, HOP(ts, INTERVAL '1' SECOND, "
        "INTERVAL '2' SECOND)").collect()
    got = sorted((int(r["ws"]), int(r["dc"])) for r in rows)
    # windows: [-1000,1000):{5}  [0,2000):{5,7}  [1000,3000):{7,5}
    #          [2000,4000):{7}
    assert got == [(-1000, 1), (0, 2), (1000, 2), (2000, 1)]


def test_hop_sum_distinct_mixed_with_plain():
    """Mixed plain + DISTINCT aggregates over HOP: the plain branch runs the
    native sliding assigner, the distinct branch the expanded path; fired
    rows re-merge on (key, REAL window bounds)."""
    rows = _hop_distinct_env().execute_sql(
        "SELECT k, COUNT(*) AS n, SUM(DISTINCT v) AS sd, "
        "HOP_START(ts, INTERVAL '1' SECOND, INTERVAL '2' SECOND) AS ws "
        "FROM t GROUP BY k, HOP(ts, INTERVAL '1' SECOND, "
        "INTERVAL '2' SECOND)").collect()
    got = {int(r["ws"]): (int(r["n"]), int(r["sd"])) for r in rows}
    assert got == {-1000: (1, 5), 0: (3, 12), 1000: (3, 12), 2000: (1, 7)}


def test_session_distinct_aggregates():
    """DISTINCT aggregates over SESSION windows: per-session value SETS
    merge with the session intervals (closes the PARITY r2 gap)."""
    te = TableEnvironment()
    te.register_collection("t", columns={
        "k":  np.array([1, 1, 1, 1, 2], np.int64),
        "ts": np.array([0, 400, 800, 5000, 100], np.int64),
        "v":  np.array([5., 5., 7., 9., 5.])}, rowtime="ts")
    rows = te.execute_sql(
        "SELECT k, COUNT(DISTINCT v) AS dc, SUM(DISTINCT v) AS ds, "
        "COUNT(*) AS n, "
        "SESSION_START(ts, INTERVAL '1' SECOND) AS ws "
        "FROM t GROUP BY k, SESSION(ts, INTERVAL '1' SECOND)").collect()
    got = sorted((int(r["k"]), int(r["ws"]), int(r["dc"]), float(r["ds"]),
                  int(r["n"])) for r in rows)
    # key 1 session [0,1800): values {5,5,7} -> 2 distinct, sum 12, 3 rows
    # key 1 session [5000,6000): {9};  key 2 session [100,1100): {5}
    assert got == [(1, 0, 2, 12.0, 3), (1, 5000, 1, 9.0, 1),
                   (2, 100, 1, 5.0, 1)]


def test_session_distinct_merging_sessions_union_sets():
    """A late-ish batch that MERGES two sessions must union their distinct
    sets (the MergingWindowSet + distinct-MapView interaction)."""
    from flink_tpu.core.batch import RecordBatch, Watermark
    from flink_tpu.core.functions import CountAggregator, RuntimeContext, TupleAggregator
    from flink_tpu.operators.session_window import SessionWindowOperator
    from flink_tpu.windowing.assigners import EventTimeSessionWindows

    op = SessionWindowOperator(
        EventTimeSessionWindows(100),
        TupleAggregator({"n": ("v", CountAggregator())}),
        key_column="k", value_selector=lambda c: c,
        distinct_specs={"dc": "COUNT", "ds": "SUM"}, distinct_column="v")
    op.open(RuntimeContext())
    # two separate sessions for key 1: [0,100) {5}, [180,280) {5,7}
    op.process_batch(RecordBatch(
        {"k": np.array([1, 1, 1]), "v": np.array([5., 5., 7.])},
        timestamps=np.array([0, 180, 190])))
    # bridging row at t=90 merges them; distinct set must be {5,7,9}
    op.process_batch(RecordBatch(
        {"k": np.array([1]), "v": np.array([9.])},
        timestamps=np.array([90])))
    out = op.process_watermark(Watermark(10_000))
    rows = [r for b in out if hasattr(b, "columns") for r in b.to_rows()]
    assert len(rows) == 1
    assert rows[0]["dc"] == 3 and rows[0]["ds"] == 21.0 and rows[0]["n"] == 4

    # snapshot/restore keeps the sets
    op.process_batch(RecordBatch(
        {"k": np.array([3, 3]), "v": np.array([2., 2.])},
        timestamps=np.array([20_000, 20_010])))
    snap = op.snapshot_state()
    op2 = SessionWindowOperator(
        EventTimeSessionWindows(100),
        TupleAggregator({"n": ("v", CountAggregator())}),
        key_column="k", value_selector=lambda c: c,
        distinct_specs={"dc": "COUNT", "ds": "SUM"}, distinct_column="v")
    op2.open(RuntimeContext())
    op2.restore_state(snap)
    out = op2.process_watermark(Watermark(50_000))
    rows = [r for b in out if hasattr(b, "columns") for r in b.to_rows()]
    assert [(r["k"], r["dc"], r["ds"]) for r in rows] == [(3, 1, 2.0)]


def test_hop_distinct_non_divisible_size_late_rule_matches_plain():
    """size % slide != 0: the synthetic bucket must close EXACTLY at the
    real window close, so late rows drop identically in both branches —
    never COUNT(DISTINCT) > COUNT(*)."""
    te = TableEnvironment()
    te.register_collection("t", columns={
        "k": np.array([1, 1, 1], np.int64),
        "v": np.array([5, 9, 7], np.int64),
        # watermark reaches 2600 (closing real window [0,2500)), THEN a
        # late row at 2400 arrives
        "ts": np.array([0, 2600, 2400], np.int64)},
        batch_size=2, rowtime="ts", watermark_delay_ms=0)
    rows = te.execute_sql(
        "SELECT k, COUNT(*) AS n, COUNT(DISTINCT v) AS dc, "
        "HOP_START(ts, INTERVAL '1' SECOND, INTERVAL '2.5' SECOND) AS ws "
        "FROM t GROUP BY k, HOP(ts, INTERVAL '1' SECOND, "
        "INTERVAL '2.5' SECOND)").collect()
    for r in rows:
        assert int(r["dc"]) <= int(r["n"]), dict(r)


def test_explain_diff_shows_pushdown(tenv):
    """EXPLAIN diff (VERDICT r2 #3 'done' criterion): the rewrite stage's
    filter pushdown and projection pruning are visible in the physical
    plan — a pre-join filter vertex appears, the post-join WHERE vanishes,
    and the scan is pruned to referenced columns."""
    join_q = ("SELECT o.oid, c.name FROM orders o JOIN customers c "
              "ON o.cust = c.cust WHERE c.name = 'alice' AND o.amount > 15")
    txt = tenv.explain_sql(join_q)
    assert "Logical Rewrites Applied" in txt and "filter_pushdown" in txt
    # both single-side conjuncts ran BEFORE the join
    assert "sql-prejoin-filter:customers" in txt
    assert "sql-prejoin-filter:orders" in txt
    assert "sql-where" not in txt           # nothing left post-join

    # scan pruning on a plain select: only referenced columns survive
    txt2 = tenv.explain_sql("SELECT oid FROM orders WHERE amount > 15")
    assert "projection_prune" in txt2
    assert "sql-scan-prune[oid,amount]" in txt2

    # and the rewritten plans still compute the right answers
    rows = tenv.execute_sql(join_q).collect()
    assert sorted((int(r["oid"]), r["name"]) for r in rows) == \
        [(2, "alice")]
    rows2 = tenv.execute_sql(
        "SELECT oid FROM orders WHERE amount > 15").collect()
    assert sorted(int(r["oid"]) for r in rows2) == [1, 2, 3, 4, 5]


def test_filter_pushdown_outer_join_semantics(tenv):
    """Pushdown must not change LEFT JOIN results: a right-side predicate
    pre-filters the right input, turning unmatched rows into NULL-extended
    output exactly as the post-join filter... does NOT — so the rule must
    keep right-side conjuncts of outer joins un-pushed."""
    rows = tenv.execute_sql(
        "SELECT o.oid, c.name FROM orders o LEFT JOIN customers c "
        "ON o.cust = c.cust WHERE c.name = 'alice'").collect()
    assert sorted(int(r["oid"]) for r in rows) == [0, 2]


def test_composite_key_hasher_locks_representation():
    """The int64 hash fast path decides hash-vs-tuple ONCE per query: a
    key column whose dtype drifts mid-stream (a None turning int64 into
    object) must raise, never silently split one logical key into two
    __key representations."""
    import numpy as np
    import pytest
    from flink_tpu.sql.planner import (KeyHashCollisionError,
                                       _CompositeKeyHasher)

    h = _CompositeKeyHasher(keep_components=True)
    a = np.arange(4, dtype=np.int64)
    b = np.ones(4, np.float64)
    assert h.combine([a, b], 4) is not None          # locks in "hash"
    drift = np.asarray([1, None, 3, 4], object)      # nullable batch
    with pytest.raises(KeyHashCollisionError, match="non-numeric"):
        h.combine([a, drift], 4)
    # first-batch-ineligible locks in "tuple" and STAYS tuple even when a
    # later batch would be hashable (consistent representation, no error)
    h2 = _CompositeKeyHasher()
    assert h2.combine([np.asarray(["x", "y"], object)], 2) is None
    assert h2.combine([np.arange(2, dtype=np.int64)], 2) is None


def test_composite_key_hash_negative_zero_groups_with_zero():
    """Regression: 0.0 and -0.0 are one SQL group — the hash fast path
    must canonicalize the float bit pattern, matching the tuple path."""
    import numpy as np
    from flink_tpu.sql.table_env import TableEnvironment

    cols = {"a": np.ones(4, np.int64),
            "b": np.asarray([0.0, -0.0, 0.0, -0.0]),
            "v": np.asarray([1.0, 2.0, 3.0, 4.0])}
    rows_by_flag = {}
    for flag in (True, False):
        tenv = TableEnvironment(hash_composite_keys=flag)
        tenv.register_collection("t", columns=cols)
        out = tenv.execute_sql(
            "SELECT a, b, SUM(v) AS s FROM t GROUP BY a, b").collect()
        out = out.rows() if hasattr(out, "rows") else out
        rows_by_flag[flag] = sorted(
            (int(r["a"]), float(r["s"])) for r in out)
    assert rows_by_flag[True] == rows_by_flag[False] == [(1, 10.0)]
