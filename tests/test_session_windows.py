"""Session (merging) window tests — modeled on the session cases of the
reference's WindowOperatorTest (flink-streaming-java/.../windowing/
WindowOperatorTest.java: testSessionWindows / testSessionWindowsWithLateness /
merging snapshot cases)."""

import numpy as np
import pytest

from flink_tpu.core.batch import RecordBatch
from flink_tpu.core.functions import SumAggregator
from flink_tpu.operators.session_window import SessionWindowOperator
from flink_tpu.testing.harness import KeyedOneInputOperatorHarness
from flink_tpu.windowing.assigners import EventTimeSessionWindows


def make_op(gap=10, lateness=0):
    import jax.numpy as jnp
    return SessionWindowOperator(
        EventTimeSessionWindows(gap), SumAggregator(jnp.float64),
        key_column="k", value_column="v", output_column="v",
        allowed_lateness_ms=lateness)


def _batch(keys, vals, ts):
    return RecordBatch({"k": np.asarray(keys, np.int64),
                        "v": np.asarray(vals, np.float64)},
                       timestamps=np.asarray(ts, np.int64))


def fired(h):
    rows = h.extract_output_rows()
    return sorted(((r["k"], r["window_start"], r["window_end"], r["v"])
                   for r in rows))


def test_single_session_fires_after_gap():
    h = KeyedOneInputOperatorHarness(make_op(gap=10))
    h.process_batch(_batch([1, 1, 1], [1, 2, 3], [0, 5, 8]))
    h.process_watermark(17)  # session end = 8+10 = 18 > 17: not yet
    assert fired(h) == []
    h.process_watermark(18)
    assert fired(h) == [(1, 0, 18, 6.0)]


def test_gap_splits_sessions():
    h = KeyedOneInputOperatorHarness(make_op(gap=10))
    h.process_batch(_batch([1, 1], [1, 2], [0, 30]))  # gap 30 > 10: two sessions
    h.process_watermark(100)
    assert fired(h) == [(1, 0, 10, 1.0), (1, 30, 40, 2.0)]


def test_cross_batch_merge_extends_session():
    h = KeyedOneInputOperatorHarness(make_op(gap=10))
    h.process_batch(_batch([1], [1], [0]))
    h.process_batch(_batch([1], [2], [8]))   # within gap of [0,10): merge
    h.process_watermark(100)
    assert fired(h) == [(1, 0, 18, 3.0)]


def test_bridging_record_merges_two_stored_sessions():
    h = KeyedOneInputOperatorHarness(make_op(gap=10))
    # two disjoint sessions: [0,10) and [18,28)
    h.process_batch(_batch([1, 1], [1, 2], [0, 18]))
    # bridging record at 9: [9,19) overlaps both -> one merged session
    h.process_batch(_batch([1], [10], [9]))
    h.process_watermark(100)
    assert fired(h) == [(1, 0, 28, 13.0)]


def test_keys_are_isolated():
    h = KeyedOneInputOperatorHarness(make_op(gap=10))
    h.process_batch(_batch([1, 2], [1, 5], [0, 3]))
    h.process_watermark(100)
    assert fired(h) == [(1, 0, 10, 1.0), (2, 3, 13, 5.0)]


def test_late_record_within_lateness_merges_and_refires():
    h = KeyedOneInputOperatorHarness(make_op(gap=10, lateness=100))
    h.process_batch(_batch([1], [1], [0]))
    h.process_watermark(50)  # fires [0,10) -> 1.0
    assert fired(h) == [(1, 0, 10, 1.0)]
    h.clear_output()
    # late record at ts=5 (watermark 50, within lateness horizon 110)
    h.process_batch(_batch([1], [2], [5]))
    assert fired(h) == [(1, 0, 15, 3.0)]  # re-fired enlarged session


def test_beyond_lateness_dropped():
    op = make_op(gap=10, lateness=0)
    h = KeyedOneInputOperatorHarness(op)
    h.process_batch(_batch([1], [1], [0]))
    h.process_watermark(50)
    h.clear_output()
    h.process_batch(_batch([1], [2], [5]))  # end 15 + lateness 0 <= 50: drop
    h.process_watermark(100)
    assert fired(h) == []
    assert op.late_dropped == 1


def test_snapshot_restore_continues_sessions():
    h = KeyedOneInputOperatorHarness(make_op(gap=10))
    h.process_batch(_batch([1, 2], [1, 2], [0, 3]))
    snap = h.snapshot()
    h2 = KeyedOneInputOperatorHarness.restored(make_op(gap=10), snap)
    h2.process_batch(_batch([1], [10], [8]))  # merges into restored session
    h2.process_watermark(100)
    assert fired(h2) == [(1, 0, 18, 11.0), (2, 3, 13, 2.0)]


def test_rescale_split_and_merge_roundtrip():
    h = KeyedOneInputOperatorHarness(make_op(gap=10))
    keys = np.arange(50, dtype=np.int64)
    h.process_batch(_batch(keys, np.ones(50), np.zeros(50)))
    snap = h.snapshot()
    parts = SessionWindowOperator.split_snapshot(snap, 128, 4)
    assert sum(len(p["session_keys"]) for p in parts) == 50
    # each part restores and fires only its keys
    seen = []
    for i, p in enumerate(parts):
        hp = KeyedOneInputOperatorHarness.restored(make_op(gap=10), p)
        hp.process_watermark(100)
        seen.extend(k for k, *_ in fired(hp))
    assert sorted(seen) == list(range(50))
    # merge back
    merged = SessionWindowOperator.merge_snapshots(parts)
    hm = KeyedOneInputOperatorHarness.restored(make_op(gap=10), merged)
    hm.process_watermark(100)
    assert len(fired(hm)) == 50


def test_session_multiple_batch_sessions_same_batch_merge_with_store():
    h = KeyedOneInputOperatorHarness(make_op(gap=5))
    h.process_batch(_batch([1], [1], [10]))          # stored [10,15)
    # batch contains two local sessions for key 1: [0,5) and [13,18)
    h.process_batch(_batch([1, 1], [2, 3], [0, 13]))
    h.process_watermark(100)
    # [13,18) merges with [10,15) -> [10,18); [0,5) stays separate
    assert fired(h) == [(1, 0, 5, 2.0), (1, 10, 18, 4.0)]


def test_session_end_to_end_datastream():
    from flink_tpu.datastream.api import StreamExecutionEnvironment

    env = StreamExecutionEnvironment()
    rows = [{"k": 1, "v": 1.0, "t": 0}, {"k": 1, "v": 2.0, "t": 4},
            {"k": 1, "v": 4.0, "t": 50}, {"k": 2, "v": 8.0, "t": 2}]
    out = (env.from_collection(rows)
           .assign_timestamps_and_watermarks(0, timestamp_column="t")
           .key_by("k")
           .window(EventTimeSessionWindows(10))
           .sum("v")
           .execute_and_collect())
    got = sorted((r["k"], r["window_start"], r["window_end"], r["v"])
                 for r in out)
    assert got == [(1, 0, 14, 3.0), (1, 50, 60, 4.0), (2, 2, 12, 8.0)]


def test_session_avg_nontrivial_acc():
    import jax.numpy as jnp
    from flink_tpu.core.functions import AvgAggregator

    op = SessionWindowOperator(
        EventTimeSessionWindows(10), AvgAggregator(jnp.float64),
        key_column="k", value_column="v")
    h = KeyedOneInputOperatorHarness(op)
    h.process_batch(_batch([1, 1], [2.0, 4.0], [0, 5]))
    h.process_watermark(100)
    rows = h.extract_output_rows()
    assert len(rows) == 1 and rows[0]["result"] == pytest.approx(3.0)


def test_no_duplicate_emission_after_late_refire():
    """A re-fired session must be marked fired — the next watermark advance
    must not emit it again."""
    h = KeyedOneInputOperatorHarness(make_op(gap=10, lateness=100))
    h.process_batch(_batch([1], [1], [0]))
    h.process_watermark(50)
    h.clear_output()
    h.process_batch(_batch([1], [2], [5]))  # late merge -> immediate re-fire
    assert fired(h) == [(1, 0, 15, 3.0)]
    h.clear_output()
    h.process_watermark(60)  # must NOT re-emit
    assert fired(h) == []


def test_batch_boundary_does_not_change_sessionization():
    """Records exactly `gap` apart must split the same way whether they
    arrive in one batch or two (merge-boundary consistency)."""
    h1 = KeyedOneInputOperatorHarness(make_op(gap=100))
    h1.process_batch(_batch([1, 1], [1, 2], [0, 100]))
    h1.process_watermark(1000)
    h2 = KeyedOneInputOperatorHarness(make_op(gap=100))
    h2.process_batch(_batch([1], [1], [0]))
    h2.process_batch(_batch([1], [2], [100]))
    h2.process_watermark(1000)
    assert fired(h1) == fired(h2) == [(1, 0, 100, 1.0), (1, 100, 200, 2.0)]


def test_late_record_overlapping_retained_session_survives():
    """Lateness is judged on the post-merge window: a record whose own
    window would be late still merges into a retained session."""
    op = make_op(gap=40, lateness=100)
    h = KeyedOneInputOperatorHarness(op)
    h.process_batch(_batch([1], [1], [60]))   # session [60,100)
    h.process_watermark(151)                  # fired; retained until 200
    h.clear_output()
    h.process_batch(_batch([1], [2], [70]))   # own end 110+100=210>151? 70+40+100=210>151 not late anyway
    h.clear_output()
    # ts=10: own window [10,50)+lateness=150 <= 151 -> late alone, but
    # [10,50) does NOT overlap [60,100): dropped
    h.process_batch(_batch([1], [4], [10]))
    assert op.late_dropped == 1
    # ts=25: own cleanup 25+40+100=165 > 151 -> not late, merges nothing
    h.clear_output()
    op2 = make_op(gap=40, lateness=100)
    h3 = KeyedOneInputOperatorHarness(op2)
    h3.process_batch(_batch([1], [1], [60]))
    h3.process_watermark(151)
    h3.clear_output()
    # ts=30: own cleanup 30+40+100=170 > 151: not late; [30,70) overlaps
    # [60,100) -> merges and re-fires enlarged session
    h3.process_batch(_batch([1], [8], [30]))
    assert fired(h3) == [(1, 30, 100, 9.0)]
    assert op2.late_dropped == 0


def test_late_record_that_merges_is_not_dropped_even_if_own_window_late():
    op = make_op(gap=40, lateness=100)
    h = KeyedOneInputOperatorHarness(op)
    h.process_batch(_batch([1], [1], [100]))  # session [100,140)
    h.process_watermark(235)                  # fired; retained until 240
    h.clear_output()
    # ts=90: own cleanup 90+40+100=230 <= 235 -> late alone, BUT [90,130)
    # overlaps retained [100,140): must merge + re-fire, not drop
    h.process_batch(_batch([1], [2], [90]))
    assert fired(h) == [(1, 90, 140, 3.0)]
    assert op.late_dropped == 0


def test_trigger_on_session_raises():
    from flink_tpu.datastream.api import StreamExecutionEnvironment
    from flink_tpu.windowing.triggers import CountTrigger

    env = StreamExecutionEnvironment()
    with pytest.raises(ValueError, match="session"):
        (env.from_collection([{"k": 1, "v": 1.0}])
         .key_by("k").window(EventTimeSessionWindows(10))
         .trigger(CountTrigger(2)).sum("v"))


def test_split_zeroes_counter_in_all_but_first_part():
    op = make_op(gap=10, lateness=0)
    h = KeyedOneInputOperatorHarness(op)
    h.process_batch(_batch([1], [1], [0]))
    h.process_watermark(50)
    h.process_batch(_batch([1], [2], [5]))  # dropped
    assert op.late_dropped == 1
    snap = h.snapshot()
    parts = SessionWindowOperator.split_snapshot(snap, 128, 4)
    total = sum(p.get("late_dropped", 0) for p in parts)
    assert total == 1


def test_session_side_output_late_data():
    """Beyond-lateness session records route to a side output instead of
    dropping (sideOutputLateData on merging windows)."""
    import numpy as np

    from flink_tpu.core.batch import OutputTag
    from flink_tpu.datastream.api import StreamExecutionEnvironment
    from flink_tpu.windowing.assigners import EventTimeSessionWindows

    env = StreamExecutionEnvironment()
    tag = OutputTag("late-sessions")
    ks = np.zeros(6, np.int64)
    vs = np.ones(6)
    # session gap 1000; watermark sails past 50_000; then a straggler at 10
    ts = np.array([100, 300, 20_000, 20_300, 50_000, 10], np.int64)
    win = (env.from_collection(columns={"k": ks, "v": vs, "t": ts},
                               batch_size=2)
           .assign_timestamps_and_watermarks(0, timestamp_column="t")
           .key_by("k")
           .window(EventTimeSessionWindows(1000)))
    agg = win.side_output_late_data(tag).sum("v")
    late_sink = agg.get_side_output(tag).collect()
    main_sink = agg.collect()
    env.execute("late-session")
    lr = late_sink.rows()
    assert len(lr) == 1 and lr[0]["t"] == 10
    assert sum(r["v"] for r in main_sink.rows()) >= 4.0
