"""Device fast lane for evicting windows (VERDICT r3 next #10).

Tier-equivalence: the device lane (columnar elements, mask eviction,
segment combine) must match the host lane (EvictingWindowOperator with a
row-level apply) for CountEvictor/TimeEvictor + built-in aggregates."""

import numpy as np
import pytest

from flink_tpu.core.batch import RecordBatch, Watermark
from flink_tpu.core.functions import (AvgAggregator, MaxAggregator,
                                      RuntimeContext, SumAggregator)
from flink_tpu.operators.evicting_device import (
    DeviceEvictingWindowOperator, device_evictor_supported)
from flink_tpu.operators.evicting_window import EvictingWindowOperator
from flink_tpu.windowing.assigners import (SlidingEventTimeWindows,
                                           TumblingEventTimeWindows)
from flink_tpu.windowing.evictors import (CountEvictor, DeltaEvictor,
                                          TimeEvictor)


def _run(op, batches, wm_each=True):
    out = []
    for keys, vals, ts in batches:
        out += op.process_batch(RecordBatch(
            {"k": np.asarray(keys, np.int64),
             "v": np.asarray(vals, np.float32)},
            timestamps=np.asarray(ts, np.int64)))
        if wm_each:
            out += op.process_watermark(Watermark(int(np.max(ts)) - 1))
    out += op.end_input()
    rows = []
    for b in out:
        if hasattr(b, "columns"):
            for i in range(len(b)):
                rows.append((int(np.asarray(b.column("k"))[i]),
                             int(np.asarray(b.column("window_start"))[i]),
                             round(float(np.asarray(b.column("result"))[i]),
                                   4)))
    return sorted(rows)


def _host_sum_apply(key, window, rows):
    return {"k": key, "result": float(sum(r["v"] for r in rows)),
            "window_start": window.start, "window_end": window.end}


def _mk_device(evictor, agg=None, assigner=None):
    op = DeviceEvictingWindowOperator(
        assigner or TumblingEventTimeWindows.of(100), evictor,
        agg or SumAggregator(np.float32), key_column="k", value_column="v")
    op.open(RuntimeContext())
    return op


def _mk_host(evictor, assigner=None):
    op = EvictingWindowOperator(
        assigner or TumblingEventTimeWindows.of(100), evictor,
        key_column="k", apply_fn=_host_sum_apply)
    op.open(RuntimeContext())
    return op


def _batches(seed=0, nb=6, n=400, keys=23, span=120):
    rng = np.random.default_rng(seed)
    out = []
    t = 0
    for _ in range(nb):
        ts = t + np.sort(rng.integers(0, span, n))
        out.append((rng.integers(0, keys, n), rng.random(n), ts))
        t += span
    return out


def _assert_equivalent(dev, host):
    """Same (key, window) sets; results equal to f32 summation-order noise."""
    dk = [(k, w) for k, w, _ in dev]
    hk = [(k, w) for k, w, _ in host]
    assert dk == hk and dk
    np.testing.assert_allclose([v for _, _, v in dev],
                               [v for _, _, v in host],
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("evictor", [CountEvictor.of(5), TimeEvictor.of(30)])
def test_tier_equivalence_tumbling(evictor):
    import copy
    batches = _batches()
    dev = _run(_mk_device(copy.deepcopy(evictor)), batches)
    host = _run(_mk_host(copy.deepcopy(evictor)), batches)
    _assert_equivalent(dev, host)


def test_tier_equivalence_sliding_panes():
    import copy
    ev = CountEvictor.of(3)
    a = SlidingEventTimeWindows.of(200, 100)
    batches = _batches(seed=2)
    dev = _run(_mk_device(copy.deepcopy(ev), assigner=a), batches)
    host = _run(_mk_host(copy.deepcopy(ev), assigner=a), batches)
    _assert_equivalent(dev, host)


def test_count_evictor_keeps_last_n():
    # key 1 gets values 1..6 in arrival order; CountEvictor(2) keeps 5,6
    op = _mk_device(CountEvictor.of(2))
    out = _run(op, [([1] * 6, [1, 2, 3, 4, 5, 6], [10, 20, 30, 40, 50, 60])])
    assert out == [(1, 0, 11.0)]


def test_time_evictor_trailing_span():
    # keep rows within 15ms of the key's newest: ts 40,50 survive
    op = _mk_device(TimeEvictor.of(15))
    out = _run(op, [([7] * 4, [1, 2, 3, 4], [10, 20, 40, 50])])
    assert out == [(7, 0, 7.0)]


def test_avg_and_max_aggregates():
    op = _mk_device(CountEvictor.of(3), agg=AvgAggregator(np.float32))
    out = _run(op, [([1] * 5, [10, 20, 30, 40, 50], [1, 2, 3, 4, 5])])
    assert out == [(1, 0, 40.0)]            # mean of last 3
    op2 = _mk_device(TimeEvictor.of(100), agg=MaxAggregator(np.float32))
    out2 = _run(op2, [([1, 1], [5, 3], [1, 2])])
    assert out2 == [(1, 0, 5.0)]


def test_snapshot_restore_mid_window():
    import copy
    ev = CountEvictor.of(4)
    batches = _batches(seed=5, nb=4)
    full = _run(_mk_device(copy.deepcopy(ev)), batches)
    op = _mk_device(copy.deepcopy(ev))
    out = []
    for keys, vals, ts in batches[:2]:
        out += op.process_batch(RecordBatch(
            {"k": np.asarray(keys, np.int64),
             "v": np.asarray(vals, np.float32)},
            timestamps=np.asarray(ts, np.int64)))
        out += op.process_watermark(Watermark(int(np.max(ts)) - 1))
    snap = op.snapshot_state()
    op2 = _mk_device(copy.deepcopy(ev))
    op2.restore_state(snap)
    rest = []
    for keys, vals, ts in batches[2:]:
        rest += op2.process_batch(RecordBatch(
            {"k": np.asarray(keys, np.int64),
             "v": np.asarray(vals, np.float32)},
            timestamps=np.asarray(ts, np.int64)))
        rest += op2.process_watermark(Watermark(int(np.max(ts)) - 1))
    rest += op2.end_input()

    def rows(elems):
        rws = []
        for b in elems:
            if hasattr(b, "columns"):
                for i in range(len(b)):
                    rws.append((int(np.asarray(b.column("k"))[i]),
                                int(np.asarray(b.column("window_start"))[i]),
                                round(float(
                                    np.asarray(b.column("result"))[i]), 4)))
        return sorted(rws)

    assert rows(out) + rows(rest) and sorted(rows(out) + rows(rest)) == full


def test_buffer_compaction_bounds_growth():
    op = DeviceEvictingWindowOperator(
        TumblingEventTimeWindows.of(100), CountEvictor.of(2),
        SumAggregator(np.float32), key_column="k", value_column="v",
        initial_capacity=256)
    op.open(RuntimeContext())
    t = 0
    for i in range(40):                     # 40 * 64 rows >> 256
        ts = t + np.sort(np.random.default_rng(i).integers(0, 100, 64))
        op.process_batch(RecordBatch(
            {"k": np.arange(64, dtype=np.int64) % 5,
             "v": np.ones(64, np.float32)},
            timestamps=np.asarray(ts, np.int64)))
        op.process_watermark(Watermark(t + 99))
        t += 100
    assert op._C <= 4096                    # compaction kept it bounded


def test_api_routing_and_unsupported():
    from flink_tpu.datastream import StreamExecutionEnvironment

    env = StreamExecutionEnvironment()
    n = 3000
    rng = np.random.default_rng(1)
    src = (env.from_collection(columns={
        "k": rng.integers(0, 9, n), "v": rng.random(n),
        "t": np.sort(rng.integers(0, 1000, n))})
        .assign_timestamps_and_watermarks(0, timestamp_column="t"))
    rows = (src.key_by("k").window(TumblingEventTimeWindows.of(250))
            .evictor(CountEvictor.of(3))
            .aggregate(SumAggregator(np.float32), value_column="v")
            .execute_and_collect())
    assert rows and all(float(r["result"]) <= 3.0 for r in rows)
    # unsupported evictor directs to apply()
    with pytest.raises(ValueError, match="device lane"):
        (src.key_by("k").window(TumblingEventTimeWindows.of(250))
            .evictor(DeltaEvictor(1.0, lambda r: r))
            .aggregate(SumAggregator(np.float32), value_column="v"))
    assert not device_evictor_supported(DeltaEvictor(1.0, lambda r: r),
                                        SumAggregator(np.float32))


def test_evictor_count_and_session_guard():
    from flink_tpu.core.functions import CountAggregator
    from flink_tpu.datastream import StreamExecutionEnvironment
    from flink_tpu.windowing.assigners import SessionGap

    env = StreamExecutionEnvironment()
    n = 2000
    rng = np.random.default_rng(4)
    src = (env.from_collection(columns={
        "k": rng.integers(0, 5, n), "v": rng.random(n),
        "t": np.sort(rng.integers(0, 1000, n))})
        .assign_timestamps_and_watermarks(0, timestamp_column="t"))
    # count() with an evictor: capped at the evictor's n
    rows = (src.key_by("k").window(TumblingEventTimeWindows.of(500))
            .evictor(CountEvictor.of(7))
            .aggregate(CountAggregator())
            .execute_and_collect())
    assert rows and all(int(r["result"]) <= 7 for r in rows)
    # session windows reject evictors AT CALL TIME
    with pytest.raises(ValueError, match="session"):
        (src.key_by("k").window(SessionGap(100))
            .evictor(CountEvictor.of(2))
            .aggregate(CountAggregator()))
