"""End-to-end DataStream API tests — source → transform → keyBy → window → sink.

Modeled on the reference's ITCase style (MiniCluster jobs asserting collected
output, e.g. ``flink-tests`` window ITCases and
``SocketWindowWordCount.java:69-84`` = baseline config #1 shape).
"""

import numpy as np
import pytest

from flink_tpu.datastream import StreamExecutionEnvironment
from flink_tpu.windowing import TumblingEventTimeWindows, SlidingEventTimeWindows


def rows_by(rows, *cols):
    return sorted(rows, key=lambda r: tuple(r[c] for c in cols))


def test_map_filter_pipeline():
    env = StreamExecutionEnvironment.get_execution_environment()
    rows = env.from_collection(columns={"x": np.arange(10, dtype=np.int64)}) \
        .map(lambda c: {"x": c["x"], "y": c["x"] * 2}) \
        .filter(lambda c: c["x"] % 2 == 0) \
        .execute_and_collect()
    assert [r["y"] for r in rows_by(rows, "x")] == [0, 4, 8, 12, 16]


def test_flat_map():
    env = StreamExecutionEnvironment.get_execution_environment()

    def explode(cols):
        # duplicate each row k times where k = x % 3
        reps = np.asarray(cols["x"]) % 3
        src = np.repeat(np.arange(len(reps)), reps)
        return {"x": np.asarray(cols["x"])[src]}, src

    rows = env.from_collection(columns={"x": np.arange(6, dtype=np.int64)}) \
        .flat_map(explode).execute_and_collect()
    xs = sorted(r["x"] for r in rows)
    assert xs == [1, 2, 2, 4, 5, 5]


def test_keyed_reduce_running_sum():
    env = StreamExecutionEnvironment.get_execution_environment()
    keys = np.asarray([1, 2, 1, 1, 2], dtype=np.int64)
    vals = np.asarray([10.0, 1.0, 20.0, 30.0, 2.0])
    rows = env.from_collection(columns={"k": keys, "v": vals}) \
        .key_by("k").sum("v").execute_and_collect()
    # running per-key sums, one output per input record
    assert len(rows) == 5
    got = {}
    for r in rows:
        got.setdefault(r["k"], []).append(r["v"])
    assert got[1] == [10.0, 30.0, 60.0]
    assert got[2] == [1.0, 3.0]


def test_keyed_reduce_across_batches():
    env = StreamExecutionEnvironment.get_execution_environment()
    n = 1000
    keys = np.arange(n, dtype=np.int64) % 7
    vals = np.ones(n)
    rows = env.from_collection(columns={"k": keys, "v": vals}, batch_size=64) \
        .key_by("k").sum("v").execute_and_collect()
    assert len(rows) == n
    finals = {}
    for r in rows:
        finals[r["k"]] = r["v"]  # last wins = running total
    for k in range(7):
        assert finals[k] == np.sum(keys == k)


def test_tumbling_window_sum_e2e():
    env = StreamExecutionEnvironment.get_execution_environment()
    # 2 keys, events at t=100..900, 500ms tumbling windows
    ts = np.asarray([100, 200, 600, 700, 100, 900], dtype=np.int64)
    keys = np.asarray([1, 1, 1, 1, 2, 2], dtype=np.int64)
    vals = np.asarray([1.0, 2.0, 3.0, 4.0, 10.0, 20.0])
    rows = (env.from_collection(columns={"k": keys, "v": vals, "t": ts})
            .assign_timestamps_and_watermarks(0, timestamp_column="t")
            .key_by("k")
            .window(TumblingEventTimeWindows.of(500))
            .sum("v")
            .execute_and_collect())
    got = rows_by([{k: r[k] for k in ("k", "v", "window_start")} for r in rows],
                  "k", "window_start")
    assert got == [
        {"k": 1, "v": 3.0, "window_start": 0},
        {"k": 1, "v": 7.0, "window_start": 500},
        {"k": 2, "v": 10.0, "window_start": 0},
        {"k": 2, "v": 20.0, "window_start": 500},
    ]


def test_sliding_window_e2e():
    env = StreamExecutionEnvironment.get_execution_environment()
    ts = np.asarray([0, 100, 250, 400], dtype=np.int64)
    vals = np.ones(4)
    keys = np.zeros(4, dtype=np.int64)
    rows = (env.from_collection(columns={"k": keys, "v": vals, "t": ts})
            .assign_timestamps_and_watermarks(0, timestamp_column="t")
            .key_by("k")
            .window(SlidingEventTimeWindows.of(200, 100))
            .sum("v")
            .execute_and_collect())
    by_start = {r["window_start"]: r["v"] for r in rows}
    # windows: [-100,100)=1, [0,200)=2, [100,300)=2, [200,400)=1, [300,500)=1, [400,600)=1
    assert by_start[0] == 2.0
    assert by_start[100] == 2.0
    assert by_start[300] == 1.0


def test_wordcount_string_keys():
    """Baseline config #1 shape: text → words → keyBy(word) → tumbling count."""
    env = StreamExecutionEnvironment.get_execution_environment()
    words = np.asarray(["to", "be", "or", "not", "to", "be"], dtype=object)
    ts = np.asarray([0, 0, 0, 0, 1, 1], dtype=np.int64)
    rows = (env.from_collection(columns={"word": words, "t": ts})
            .assign_timestamps_and_watermarks(0, timestamp_column="t")
            .key_by("word")
            .window(TumblingEventTimeWindows.of(5000))
            .count()
            .execute_and_collect())
    counts = {r["word"]: r["count"] for r in rows}
    assert counts == {"to": 2, "be": 2, "or": 1, "not": 1}


def test_union():
    env = StreamExecutionEnvironment.get_execution_environment()
    a = env.from_collection(columns={"x": np.asarray([1, 2], np.int64)})
    b = env.from_collection(columns={"x": np.asarray([3, 4], np.int64)})
    rows = a.union(b).execute_and_collect()
    assert sorted(r["x"] for r in rows) == [1, 2, 3, 4]


def test_chaining_fuses_forward_ops():
    env = StreamExecutionEnvironment.get_execution_environment()
    s = env.from_collection(columns={"x": np.arange(4, dtype=np.int64)}) \
        .map(lambda c: {"x": c["x"] + 1}) \
        .map(lambda c: {"x": c["x"] * 2})
    s.collect()
    plan = env.get_stream_graph().to_plan()
    # source + 2 maps + sink chain into ONE vertex
    assert len(plan.vertices) == 1


def test_keyby_breaks_chain():
    env = StreamExecutionEnvironment.get_execution_environment()
    s = env.from_collection(columns={"k": np.asarray([1], np.int64),
                                     "v": np.asarray([1.0])}) \
        .key_by("k").sum("v")
    s.collect()
    plan = env.get_stream_graph().to_plan()
    assert len(plan.vertices) == 2  # [source+key-by] -> [reduce+sink]


def test_generator_source_unbounded_budget():
    from flink_tpu.connectors import GeneratorSource
    env = StreamExecutionEnvironment.get_execution_environment()

    def make(split, b, n):
        return {"v": np.full(n, b, dtype=np.int64)}

    rows = env.from_source(GeneratorSource(make, num_batches=3, batch_size=4)) \
        .execute_and_collect()
    assert len(rows) == 12


def test_watermarks_flow_to_sink():
    from flink_tpu.connectors import CollectSink
    env = StreamExecutionEnvironment.get_execution_environment()
    ts = np.asarray([100, 900], dtype=np.int64)
    sink = CollectSink()
    wms = []
    sink.on_watermark = lambda t: wms.append(t)
    env.from_collection(columns={"t": ts}) \
        .assign_timestamps_and_watermarks(0, timestamp_column="t") \
        .add_sink(sink)
    env.execute()
    assert 899 in wms  # batch watermark: max_ts - ooo - 1
    assert wms[-1] > 10 ** 15  # MAX_WATERMARK at end of input


def test_count_window():
    """countWindow(n): fires every n elements per key with that batch's
    aggregate, then purges (GlobalWindows + purging CountTrigger)."""
    env = StreamExecutionEnvironment()
    n = 10
    rows = (env.from_collection(
        columns={"k": np.zeros(n, np.int64),
                 "v": np.arange(1, n + 1, dtype=np.float64)}, batch_size=5)
        .key_by("k").count_window(5).sum("v").execute_and_collect())
    assert [r["v"] for r in rows] == [15.0, 40.0]   # 1..5, 6..10
    # the sliding form is implemented since round 4 (its own suite:
    # tests/test_count_window_slide.py)
    env2 = StreamExecutionEnvironment()
    rows2 = (env2.from_collection(
        columns={"k": np.zeros(n, np.int64),
                 "v": np.arange(1, n + 1, dtype=np.float64)}, batch_size=2)
        .key_by("k").count_window(4, 2).sum("v").execute_and_collect())
    # fires at counts 2,4,6,8,10 over the last min(count,4) values
    assert [r["v"] for r in rows2] == [3.0, 10.0, 18.0, 26.0, 34.0]


def test_explicit_partitioning_methods():
    env = StreamExecutionEnvironment()
    n = 100
    for maker in ("shuffle", "rescale", "global_"):
        s = env.from_collection(columns={"v": np.arange(n, dtype=np.float64)},
                                batch_size=16)
        s = getattr(s, maker)()
        total = sum(r["v"] for r in s.execute_and_collect(f"{maker}-job"))
        assert total == float(n * (n - 1) / 2), maker
        env = StreamExecutionEnvironment()


def test_side_output_late_data():
    """Beyond-lateness records route to a side output (sideOutputLateData)
    instead of being silently dropped."""
    from flink_tpu.core.batch import OutputTag
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    env = StreamExecutionEnvironment()
    tag = OutputTag("late")
    # main: ts 0..9 then watermark advances past window 0's cleanup;
    # a straggler at ts=1 afterwards is beyond lateness
    ks = np.zeros(12, np.int64)
    vs = np.ones(12)
    ts = np.array([100, 200, 300, 400, 5100, 5200, 5300, 5400,
                   11_000, 12_000, 13_000, 1], np.int64)   # last row LATE
    win = (env.from_collection(columns={"k": ks, "v": vs, "t": ts},
                               batch_size=4)
           .assign_timestamps_and_watermarks(0, timestamp_column="t")
           .key_by("k")
           .window(TumblingEventTimeWindows.of(5000)))
    agg = win.side_output_late_data(tag).sum("v")
    late_rows = agg.get_side_output(tag)
    late_sink = late_rows.collect()
    main_sink = agg.collect()
    env.execute("late-side-output")
    lr = late_sink.rows()
    assert len(lr) == 1 and lr[0]["t"] == 1
    # the main output still fired the on-time windows
    assert sum(r["v"] for r in main_sink.rows()) >= 8.0


def test_min_by_max_by():
    """minBy/maxBy keep the FULL ROW of the extreme element (ties keep the
    first arrival)."""
    env = StreamExecutionEnvironment()
    rows = (env.from_collection(columns={
        "k": np.array([1, 1, 1, 2, 2], np.int64),
        "v": np.array([5., 2., 2., 9., 1.]),
        "tag": np.asarray(["a", "b", "c", "d", "e"], object)}, batch_size=2)
        .key_by("k").min_by("v").execute_and_collect())
    final = {}
    for r in rows:
        final[r["k"]] = (r["v"], r["tag"])
    # key 1's min is 2.0 first seen with tag "b" (tie with "c" keeps first)
    assert final[1] == (2.0, "b") and final[2] == (1.0, "e")

    env2 = StreamExecutionEnvironment()
    rows = (env2.from_collection(columns={
        "k": np.zeros(4, np.int64),
        "v": np.array([3., 7., 7., 1.]),
        "tag": np.asarray(["p", "q", "r", "s"], object)}, batch_size=1)
        .key_by("k").max_by("v").execute_and_collect())
    assert rows[-1]["tag"] == "q"   # max 7.0, first arrival wins the tie


def test_min_by_keyed_snapshot_rescale():
    """min_by state follows the keyed-snapshot convention: rescale split
    routes each key's extreme to its key-group owner."""
    from flink_tpu.core.batch import RecordBatch
    from flink_tpu.operators.basic import ExtremumByOperator
    from flink_tpu.state.redistribute import split_keyed_snapshot

    op = ExtremumByOperator("k", "v", is_min=True)
    op.process_batch(RecordBatch({
        "k": np.array([1, 2, 3, 1], np.int64),
        "v": np.array([5., 7., 2., 1.]),
        "tag": np.asarray(["a", "b", "c", "d"], object)}))
    snap = op.snapshot_state()
    parts = split_keyed_snapshot(
        snap, [f for f in snap if f.startswith("state.")], 128, 2)
    # every key's extreme lands in exactly one part, values intact
    found = {}
    for p in parts:
        op2 = ExtremumByOperator("k", "v", is_min=True)
        op2.restore_state(p)
        out = op2.process_batch(RecordBatch({
            "k": np.array([1, 2, 3], np.int64),
            "v": np.array([99., 99., 99.]),
            "tag": np.asarray(["x", "x", "x"], object)}))
        for r in out[0].to_rows():
            if r["tag"] != "x":
                found[r["k"]] = (r["v"], r["tag"])
    assert found == {1: (1.0, "d"), 2: (7.0, "b"), 3: (2.0, "c")}


def test_min_by_emits_triggering_timestamp():
    """Emission carries the TRIGGERING record's timestamp (the stored
    extreme may be far behind the watermark)."""
    from flink_tpu.core.batch import RecordBatch
    from flink_tpu.operators.basic import ExtremumByOperator

    op = ExtremumByOperator("k", "v", is_min=True)
    op.process_batch(RecordBatch({"k": np.zeros(1, np.int64),
                                  "v": np.array([1.])},
                                 timestamps=np.array([100], np.int64)))
    out = op.process_batch(RecordBatch({"k": np.zeros(1, np.int64),
                                        "v": np.array([9.])},
                                       timestamps=np.array([50_000],
                                                           np.int64)))
    assert np.asarray(out[0].timestamps)[0] == 50_000
    assert out[0].to_rows()[0]["v"] == 1.0
