"""Spill keyed-state backend: parity with the heap backend on the State API,
eviction beyond memory budget, snapshot/restore and key-group rescale."""

import numpy as np
import pytest

from flink_tpu.core.functions import AvgAggregator, SumAggregator
from flink_tpu.state.heap import HeapKeyedStateBackend
from flink_tpu.state.redistribute import (merge_keyed_snapshots,
                                          split_keyed_snapshot)
from flink_tpu.state.spill import SpillKeyedStateBackend


@pytest.fixture
def backend(tmp_path):
    b = SpillKeyedStateBackend(str(tmp_path / "spill"), mem_budget=1 << 20)
    yield b
    b.close()


def test_value_state(backend):
    st = backend.value_state("v", default=0)
    backend.set_current_key("alice")
    assert st.value() == 0
    st.update(42)
    assert st.value() == 42
    backend.set_current_key("bob")
    assert st.value() == 0
    backend.set_current_key("alice")
    assert st.value() == 42
    st.clear()
    assert st.value() == 0


def test_rows_api(backend):
    st = backend.value_state("v", default=None)
    slots = backend.key_slots(np.array([10, 20, 30], np.int64))
    st.put_rows(slots, ["a", "b", "c"])
    got = st.get_rows(slots)
    assert list(got) == ["a", "b", "c"]
    st.clear_rows(slots[:1])
    assert list(st.get_rows(slots)) == [None, "b", "c"]


def test_list_map_state(backend):
    ls = backend.list_state("l")
    ms = backend.map_state("m")
    backend.set_current_key(7)
    ls.add(1)
    ls.add(2)
    assert ls.get() == [1, 2]
    ls.update([9])
    assert ls.get() == [9]
    ms.put("x", 1)
    ms.put_all({"y": 2})
    assert ms.get("x") == 1 and ms.contains("y") and not ms.is_empty()
    assert sorted(ms.keys()) == ["x", "y"]
    ms.remove("x")
    assert ms.get("x") is None


def test_reducing_aggregating_state(backend):
    import jax.numpy as jnp

    rs = backend.reducing_state("r", reduce_fn=SumAggregator(jnp.float64))
    backend.set_current_key(1)
    rs.add(5.0)
    rs.add(7.0)
    assert float(rs.get()) == 12.0

    ag = backend.aggregating_state("a", agg=AvgAggregator(jnp.float64))
    ag.add(10.0)
    ag.add(20.0)
    assert float(ag.get()) == 15.0


def test_spill_beyond_budget(tmp_path):
    # 2MB of values with a 100KB budget: state must keep working off disk.
    b = SpillKeyedStateBackend(str(tmp_path / "s"), mem_budget=100_000)
    st = b.value_state("v")
    keys = np.arange(200, dtype=np.int64)
    slots = b.key_slots(keys)
    payload = [bytes(10_000) + str(i).encode() for i in range(200)]
    st.put_rows(slots, payload)
    assert b.store.mem_used() <= 100_000
    got = st.get_rows(slots)
    assert list(got) == payload
    b.close()


def test_snapshot_restore(tmp_path):
    b = SpillKeyedStateBackend(str(tmp_path / "a"), mem_budget=1 << 20)
    st = b.value_state("v", default=0)
    ls = b.list_state("l")
    slots = b.key_slots(np.array([1, 2, 3], np.int64))
    st.put_rows(slots, [10, 20, 30])
    b.set_current_key(2)
    ls.add("x")
    snap = b.snapshot()
    b.close()

    b2 = SpillKeyedStateBackend(str(tmp_path / "b"), mem_budget=1 << 20)
    b2.restore(snap)
    st2 = b2.value_state("v", default=0)
    slots2 = b2.key_slots(np.array([1, 2, 3], np.int64))
    assert list(st2.get_rows(slots2)) == [10, 20, 30]
    b2.set_current_key(2)
    assert b2.list_state("l").get() == ["x"]
    b2.close()


def test_rescale_split_merge(tmp_path):
    """Spill snapshots go through the same key-group redistribute path as
    heap snapshots (StateAssignmentOperation analog)."""
    b = SpillKeyedStateBackend(str(tmp_path / "a"), max_parallelism=8,
                               mem_budget=1 << 20)
    st = b.value_state("v", default=-1)
    keys = np.arange(64, dtype=np.int64)
    st.put_rows(b.key_slots(keys), [int(k) * 2 for k in keys])
    snap = b.snapshot()
    fields = SpillKeyedStateBackend.row_fields(snap)

    parts = split_keyed_snapshot(snap, fields, max_parallelism=8,
                                 new_parallelism=2)
    merged = merge_keyed_snapshots(parts, fields)

    b2 = SpillKeyedStateBackend(str(tmp_path / "b"), max_parallelism=8,
                                mem_budget=1 << 20)
    b2.restore(merged)
    st2 = b2.value_state("v", default=-1)
    got = st2.get_rows(b2.key_slots(keys))
    assert list(got) == [int(k) * 2 for k in keys]
    b.close()
    b2.close()


def test_ttl_expiry(tmp_path):
    from flink_tpu.state.api import StateTtlConfig
    now = [1000]
    b = SpillKeyedStateBackend(str(tmp_path / "s"), clock=lambda: now[0])
    st = b.get_state(
        __import__("flink_tpu.state.api", fromlist=["x"]).ValueStateDescriptor(
            "v", default="dead", ttl=StateTtlConfig.new_builder(100).build()))
    b.set_current_key("k")
    st.update("alive")
    assert st.value() == "alive"
    now[0] += 99
    assert st.value() == "alive"
    now[0] += 2
    assert st.value() == "dead"
    b.close()


def test_parity_with_heap_backend(tmp_path):
    """Same operation sequence on both backends -> same observable state."""
    import jax.numpy as jnp

    heap = HeapKeyedStateBackend(max_parallelism=16)
    spill = SpillKeyedStateBackend(str(tmp_path / "s"), max_parallelism=16)
    rng = np.random.default_rng(3)
    keys = rng.integers(0, 20, 100)
    vals = rng.integers(0, 1000, 100).astype(np.float64)
    for be in (heap, spill):
        rs = be.reducing_state("sum", reduce_fn=SumAggregator(jnp.float64))
        for k, v in zip(keys.tolist(), vals.tolist()):
            be.set_current_key(k)
            rs.add(v)
    for k in np.unique(keys).tolist():
        heap.set_current_key(k)
        spill.set_current_key(k)
        hv = heap._states["sum"].get()
        sv = spill._states["sum"].get()
        assert float(hv) == float(sv), f"key {k}"
    spill.close()
