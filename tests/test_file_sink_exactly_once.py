"""Exactly-once FileSink (VERDICT r3 next #4): checkpoint-id-bound part
lifecycle (in-progress file -> pending-{ckpt} -> committed), rolling
policies, buckets, and the S3 committer path — kill-and-restore proofs
that committed output has no duplicates and no loss.  Reference:
``flink-connector-files/.../sink/FileSink.java:1``."""

import os

import numpy as np
import pytest

from flink_tpu import formats
from flink_tpu.connectors.file_source import (DateTimeBucketAssigner,
                                              FileSink, RollingPolicy)
from flink_tpu.core.batch import RecordBatch
from flink_tpu.operators.base import snapshot_scope


def _mkbatch(lo, hi, ts=None):
    v = np.arange(lo, hi, dtype=np.float64)
    return RecordBatch({"v": v},
                       timestamps=None if ts is None
                       else np.full(len(v), ts, np.int64))


def _rows(paths):
    out = []
    for p in paths:
        for b in formats.read_csv(p):
            out.extend(np.asarray(b.column("v")).tolist())
    return sorted(out)


def test_inprogress_is_a_real_file(tmp_path):
    """Row formats stream to an actual .inprogress file (bounded memory),
    not a Python buffer."""
    d = str(tmp_path / "out")
    sink = FileSink(d, format="csv")
    sink.write_batch(_mkbatch(0, 10))
    inprog = [f for f in os.listdir(d) if f.endswith(".inprogress")]
    assert len(inprog) == 1
    # data streams through the OS file (buffered); after the roll the
    # finalized pending part holds every byte
    with snapshot_scope(1):
        sink.snapshot_state()
    assert not any(f.endswith(".inprogress") for f in os.listdir(d))
    pend = [f for f in os.listdir(d) if f.endswith(".pending")]
    assert len(pend) == 1
    assert os.path.getsize(os.path.join(d, pend[0])) > 0


def test_pending_bound_to_checkpoint_id(tmp_path):
    """A part pended for checkpoint 2 must NOT be committed by checkpoint
    1's notification — a restore to 1 after 2 fails would double it."""
    d = str(tmp_path / "out")
    sink = FileSink(d, format="csv")
    sink.write_batch(_mkbatch(0, 5))
    with snapshot_scope(1):
        sink.snapshot_state()
    sink.write_batch(_mkbatch(5, 9))
    with snapshot_scope(2):
        sink.snapshot_state()
    sink.notify_checkpoint_complete(1)
    assert _rows(sink.committed_files()) == list(map(float, range(5)))
    sink.notify_checkpoint_complete(2)
    assert _rows(sink.committed_files()) == list(map(float, range(9)))


def test_kill_and_restore_no_dupes_no_loss(tmp_path):
    """The VERDICT's done-criterion: write across checkpoints, crash after
    an uncommitted epoch, restore from the completed checkpoint, replay —
    committed output equals the logical stream exactly once."""
    d = str(tmp_path / "out")
    sink = FileSink(d, format="csv")
    sink.write_batch(_mkbatch(0, 50))
    with snapshot_scope(1):
        snap1 = sink.snapshot_state()
    sink.notify_checkpoint_complete(1)
    sink.write_batch(_mkbatch(50, 80))
    with snapshot_scope(2):
        snap2 = sink.snapshot_state()
    # checkpoint 2 completed, but the notification never arrived (crash
    # window between complete and notify) — plus an uncheckpointed epoch
    sink.write_batch(_mkbatch(80, 95))
    sink._roll()
    del sink
    # restore from checkpoint 2: its pending parts commit, the orphaned
    # epoch-3 parts are discarded; the source replays from 80
    sink2 = FileSink(d, format="csv")
    sink2.restore_state(snap2)
    sink2.write_batch(_mkbatch(80, 95))
    with snapshot_scope(3):
        sink2.snapshot_state()
    sink2.notify_checkpoint_complete(3)
    assert _rows(sink2.committed_files()) == list(map(float, range(95)))
    assert not any(f.endswith((".pending", ".inprogress"))
                   for f in os.listdir(d))
    # restore-to-1 variant: snap1's parts commit exactly once even though
    # they were already committed (idempotent re-commit)
    sink3 = FileSink(d, format="csv")
    sink3.restore_state(snap1)
    assert _rows(sink3.committed_files()) == list(map(float, range(95)))


def test_rolling_policy_bytes_and_rows(tmp_path):
    d = str(tmp_path / "out")
    sink = FileSink(d, format="csv",
                    rolling_policy=RollingPolicy(max_rows=10,
                                                 max_bytes=1 << 30))
    for lo in range(0, 25, 5):             # policy checked per batch
        sink.write_batch(_mkbatch(lo, lo + 5))
    with snapshot_scope(1):
        sink.snapshot_state()
    sink.notify_checkpoint_complete(1)
    files = sink.committed_files()
    assert len(files) >= 2                 # rolled before the checkpoint
    assert _rows(files) == list(map(float, range(25)))
    # bytes policy
    sink2 = FileSink(d, format="csv", prefix="b",
                     rolling_policy=RollingPolicy(max_rows=1 << 20,
                                                  max_bytes=64))
    for lo in range(0, 30, 5):
        sink2.write_batch(_mkbatch(lo, lo + 5))
    with snapshot_scope(1):
        sink2.snapshot_state()
    sink2.notify_checkpoint_complete(1)
    assert len(sink2.committed_files()) >= 2


def test_datetime_buckets(tmp_path):
    d = str(tmp_path / "out")
    sink = FileSink(d, format="csv",
                    bucket_assigner=DateTimeBucketAssigner("%Y-%m-%d"))
    day0 = 0                   # 1970-01-01
    day1 = 86_400_000          # 1970-01-02
    sink.write_batch(RecordBatch(
        {"v": np.asarray([1.0, 2.0, 3.0])},
        timestamps=np.asarray([day0, day1, day0], np.int64)))
    with snapshot_scope(1):
        sink.snapshot_state()
    sink.notify_checkpoint_complete(1)
    files = sink.committed_files()
    dirs = {os.path.basename(os.path.dirname(f)) for f in files}
    assert dirs == {"1970-01-01", "1970-01-02"}
    assert _rows(files) == [1.0, 2.0, 3.0]


def test_bulk_format_roundtrip(tmp_path):
    """Bulk formats (ftb) buffer and materialize at roll; committed files
    read back exactly."""
    d = str(tmp_path / "out")
    sink = FileSink(d, format="ftb")
    sink.write_batch(_mkbatch(0, 100))
    with snapshot_scope(1):
        sink.snapshot_state()
    sink.notify_checkpoint_complete(1)
    [f] = sink.committed_files()
    got = np.concatenate([np.asarray(b.column("v"))
                          for b in formats.reader_for("ftb")(f)])
    np.testing.assert_array_equal(got, np.arange(100, dtype=np.float64))


@pytest.fixture()
def s3(tmp_path):
    from flink_tpu.filesystems.s3 import S3CompatibleServer

    srv = S3CompatibleServer(str(tmp_path / "s3data"), access_key="AK",
                             secret_key="SK").start()
    try:
        yield srv.client("sink-bucket")
    finally:
        srv.stop()


def test_s3_commit_and_kill_restore(tmp_path, s3):
    """S3 committer pattern: parts stage locally, commit uploads to the
    object store (no rename on S3); kill-and-restore keeps exactly-once."""
    d = str(tmp_path / "stage")
    sink = FileSink(d, format="csv", filesystem=s3)
    sink.write_batch(_mkbatch(0, 20))
    with snapshot_scope(1):
        snap = sink.snapshot_state()
    assert sink.committed_files() == []    # staged, not uploaded
    del sink                               # crash before notify
    sink2 = FileSink(d, format="csv", filesystem=s3)
    sink2.restore_state(snap)              # re-commit uploads to S3
    [key] = sink2.committed_files()
    data = s3.get_object(key).decode()
    vals = sorted(float(line.split(",")[0])
                  for line in data.splitlines()[1:])
    assert vals == list(map(float, range(20)))
    # staging dir fully drained
    assert not any(f.endswith((".pending", ".inprogress"))
                   for f in os.listdir(d))
