"""History server: terminal jobs archived by the Dispatcher and served
after the cluster is gone (``HistoryServer`` + ``FsJobArchivist`` analog)."""

import json
import time
import urllib.request

import numpy as np

from flink_tpu.cluster.coordination import StandaloneSessionCluster
from flink_tpu.datastream.api import StreamExecutionEnvironment
from flink_tpu.rest.history import HistoryServer, archive_job, list_archived


def _plan(n=5_000, keys=7, name="hist-job"):
    env = StreamExecutionEnvironment()
    env.set_parallelism(1)
    (env.from_collection(columns={"k": np.arange(n) % keys,
                                  "v": np.ones(n)}, batch_size=256)
        .key_by("k").sum("v").collect())
    return env.get_stream_graph(name).to_plan()


def test_archive_and_list(tmp_path):
    d = str(tmp_path / "archive")
    archive_job(d, "job-0001", {"state": "FINISHED", "name": "a"})
    archive_job(d, "job-0002", {"state": "FAILED", "name": "b"})
    jobs = list_archived(d)
    assert {j["id"] for j in jobs} == {"job-0001", "job-0002"}
    assert all("archived_at" in j for j in jobs)


def test_dispatcher_archives_finished_jobs(tmp_path):
    d = str(tmp_path / "archive")
    cluster = StandaloneSessionCluster(num_task_executors=1,
                                       slots_per_executor=1, history_dir=d)
    try:
        client = cluster.client()
        job_id = client.submit(_plan(), parallelism=1)
        client.wait_for_completion(job_id, timeout_s=120)
        # archiving runs async on the dispatcher main thread
        deadline = time.time() + 10
        while time.time() < deadline and not list_archived(d):
            time.sleep(0.05)
        jobs = list_archived(d)
        assert len(jobs) == 1 and jobs[0]["id"] == job_id
    finally:
        cluster.shutdown()

    # the cluster is GONE; the history server still answers
    hs = HistoryServer(d).start()
    try:
        with urllib.request.urlopen(f"{hs.url}/jobs", timeout=10) as r:
            listing = json.loads(r.read())
        assert listing["jobs"][0]["id"] == job_id
        with urllib.request.urlopen(f"{hs.url}/jobs/{job_id}",
                                    timeout=10) as r:
            detail = json.loads(r.read())
        assert detail["id"] == job_id
        with urllib.request.urlopen(f"{hs.url}/overview", timeout=10) as r:
            ov = json.loads(r.read())
        assert ov["jobs_total"] == 1
    finally:
        hs.stop()
