"""Savepoint cross-round compatibility (VERDICT r1 #9).

``tests/fixtures/savepoint_v1`` is a CHECKED-IN snapshot written by an
earlier build (``gen_savepoint_fixture.py``).  These tests assert the
current code still restores it — the analog of the reference's
cross-version snapshot files (``OperatorSnapshotUtil.java``,
``flink-end-to-end-tests/flink-stream-stateful-job-upgrade-test``).

If a test here fails, the checkpoint FORMAT broke: either restore the
compatibility path or document a deliberate format-version bump (and only
then regenerate the fixture).
"""

import os

import jax.numpy as jnp
import numpy as np

from flink_tpu.core.batch import RecordBatch, Watermark
from flink_tpu.core.functions import AvgAggregator, RuntimeContext, SumAggregator
from flink_tpu.operators.session_window import SessionWindowOperator
from flink_tpu.operators.window_agg import WindowAggOperator
from flink_tpu.runtime.checkpoint.storage import read_savepoint
from flink_tpu.windowing.assigners import SessionGap, TumblingEventTimeWindows

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fixtures", "savepoint_v1")


def _load():
    return read_savepoint(FIXTURE)


def test_fixture_restores_tumbling_sum_and_fires_correct_totals():
    snap = _load()
    fx = snap["__fixture__"]
    op = WindowAggOperator(
        TumblingEventTimeWindows.of(10_000), SumAggregator(jnp.float32),
        key_column="k", value_column="v")
    op.open(RuntimeContext())
    op.restore_state(snap["tumbling-sum"])
    out = op.process_watermark(Watermark(10_000 - 1))
    rows = [r for b in out for r in b.to_rows()]
    total = sum(r["result"] for r in rows)
    assert abs(total - fx["expected_sum_total"]) < 1e-3
    # per-key totals must match a host recomputation of the fixture inputs
    want = {}
    for k, v in zip(fx["keys"].tolist(), fx["vals"].tolist()):
        want[k] = want.get(k, 0.0) + v
    got = {r["k"]: r["result"] for r in rows}
    assert set(got) == set(want)
    for k in want:
        assert abs(got[k] - want[k]) < 1e-3


def test_fixture_restores_avg_accumulator_pair():
    snap = _load()
    fx = snap["__fixture__"]
    op = WindowAggOperator(
        TumblingEventTimeWindows.of(10_000), AvgAggregator(jnp.float32),
        key_column="k", value_column="v", output_column="avg")
    op.open(RuntimeContext())
    op.restore_state(snap["tumbling-avg"])
    out = op.process_watermark(Watermark(10_000 - 1))
    rows = [r for b in out for r in b.to_rows()]
    want_sum, want_n = {}, {}
    for k, v in zip(fx["keys"].tolist(), fx["vals"].tolist()):
        want_sum[k] = want_sum.get(k, 0.0) + v
        want_n[k] = want_n.get(k, 0) + 1
    for r in rows:
        assert abs(r["avg"] - want_sum[r["k"]] / want_n[r["k"]]) < 1e-3


def test_fixture_restores_session_state():
    snap = _load()
    op = SessionWindowOperator(
        SessionGap(500), SumAggregator(jnp.float32),
        key_column="k", value_column="v")
    op.open(RuntimeContext())
    op.restore_state(snap["session-sum"])
    out = op.process_watermark(Watermark(1 << 40))
    rows = [r for b in out for r in b.to_rows()]
    fx = snap["__fixture__"]
    total = sum(r["result"] for r in rows)
    assert abs(total - float(fx["vals"][:100].sum())) < 1e-3


def test_fixture_restores_after_resume_with_more_data():
    """Restore + keep processing: late-arriving records fold into restored
    panes (the stateful-job-upgrade flow: stop, upgrade, resume)."""
    snap = _load()
    fx = snap["__fixture__"]
    op = WindowAggOperator(
        TumblingEventTimeWindows.of(10_000), SumAggregator(jnp.float32),
        key_column="k", value_column="v")
    op.open(RuntimeContext())
    op.restore_state(snap["tumbling-sum"])
    op.process_batch(RecordBatch(
        {"k": np.array([1, 2], np.int64),
         "v": np.array([10.0, 20.0], np.float32)},
        timestamps=np.array([6000, 6001], np.int64)))
    out = op.process_watermark(Watermark(10_000 - 1))
    rows = [r for b in out for r in b.to_rows()]
    total = sum(r["result"] for r in rows)
    assert abs(total - (fx["expected_sum_total"] + 30.0)) < 1e-3


def test_fixture_rescales_to_four_subtasks():
    """The checked-in snapshot splits across key-group ranges (restore at a
    different parallelism — the savepoint rescaling contract)."""
    snap = _load()
    fx = snap["__fixture__"]
    parts = WindowAggOperator.split_snapshot(snap["tumbling-sum"], 128, 4)
    total = 0.0
    for part in parts:
        op = WindowAggOperator(
            TumblingEventTimeWindows.of(10_000), SumAggregator(jnp.float32),
            key_column="k", value_column="v")
        op.open(RuntimeContext())
        op.restore_state(part)
        out = op.process_watermark(Watermark(10_000 - 1))
        total += sum(r["result"] for b in out for r in b.to_rows())
    assert abs(total - fx["expected_sum_total"]) < 1e-3
