"""Schema registry + Confluent Avro wire format
(ConfluentRegistryAvroDeserializationSchema analog)."""

import json
import struct
import urllib.request

import numpy as np
import pytest

from flink_tpu.formats.registry import (AvroRegistrySerializer,
                                        SchemaRegistryClient,
                                        SchemaRegistryError,
                                        SchemaRegistryServer)


@pytest.fixture
def reg():
    s = SchemaRegistryServer()
    yield s
    s.close()


V1 = {"type": "record", "name": "Ev", "fields": [
    {"name": "id", "type": "long"},
    {"name": "v", "type": "double"}]}
V2 = {"type": "record", "name": "Ev", "fields": [
    {"name": "id", "type": "long"},
    {"name": "v", "type": "double"},
    {"name": "tag", "type": ["null", "string"]}]}
BAD = {"type": "record", "name": "Ev", "fields": [
    {"name": "id", "type": "string"}]}


class TestRegistry:
    def test_register_dedupe_and_fetch(self, reg):
        c = SchemaRegistryClient(reg.url)
        sid = c.register("ev-value", V1)
        assert c.register("ev-value", V1) == sid      # identical dedupes
        assert c.get_by_id(sid)["fields"][0]["name"] == "id"
        lid, latest = c.latest("ev-value")
        assert lid == sid and latest == c.get_by_id(sid)
        assert c.subjects() == ["ev-value"]

    def test_backward_compatibility_enforced(self, reg):
        c = SchemaRegistryClient(reg.url)
        c.register("ev-value", V1)
        v2 = c.register("ev-value", V2)               # nullable add: OK
        assert c.latest("ev-value")[0] == v2
        with pytest.raises(SchemaRegistryError, match="incompatible"):
            c.register("ev-value", BAD)               # type change: 409
        with pytest.raises(SchemaRegistryError, match="must be nullable"):
            c.register("ev-value", {
                "type": "record", "name": "Ev", "fields":
                V2["fields"] + [{"name": "req", "type": "long"}]})

    def test_rest_shapes_for_foreign_clients(self, reg):
        canon = json.dumps(V1, sort_keys=True, separators=(",", ":"))
        req = urllib.request.Request(
            f"{reg.url}/subjects/s/versions",
            data=json.dumps({"schema": canon}).encode(), method="POST")
        sid = json.loads(urllib.request.urlopen(req, timeout=5).read())["id"]
        got = json.loads(urllib.request.urlopen(
            f"{reg.url}/schemas/ids/{sid}", timeout=5).read())
        assert json.loads(got["schema"]) == V1


class TestWireFormat:
    def test_magic_id_framing_round_trip(self, reg):
        ser = AvroRegistrySerializer(reg.url, "ev-value", schema=V1)
        payload = ser.encode({"id": 7, "v": 2.5})
        assert payload[0] == 0                        # magic byte
        (sid,) = struct.unpack_from(">I", payload, 1)
        assert sid >= 1
        assert ser.decode(payload) == {"id": 7, "v": 2.5}
        with pytest.raises(SchemaRegistryError, match="magic"):
            ser.decode(b"\x01garbage")

    def test_old_consumer_reads_new_producer(self, reg):
        """Schema evolution through the registry: a consumer holding NO
        compiled schema decodes whatever writer schema the id names."""
        old = AvroRegistrySerializer(reg.url, "ev-value", schema=V1)
        old_payload = old.encode({"id": 1, "v": 1.0})
        new = AvroRegistrySerializer(reg.url, "ev-value", schema=V2)
        new_payload = new.encode({"id": 2, "v": 2.0, "tag": "x"})
        consumer = AvroRegistrySerializer(reg.url, "ev-value")
        assert consumer.decode(old_payload) == {"id": 1, "v": 1.0}
        assert consumer.decode(new_payload) == {"id": 2, "v": 2.0,
                                                "tag": "x"}

    def test_kafka_end_to_end(self, reg, tmp_path):
        from flink_tpu.connectors.kafka import (KafkaWireBroker,
                                                KafkaWireSink,
                                                KafkaWireSource)
        from flink_tpu.core.batch import RecordBatch

        broker = KafkaWireBroker(directory=str(tmp_path / "k")).start()
        try:
            broker.create_topic("ev", partitions=1)
            ser = AvroRegistrySerializer(reg.url, "ev-value", schema=V1)
            sink = KafkaWireSink(broker.host, broker.port, "ev",
                                 value_encoder=ser.encoder())
            sink.open(None)
            sink.write_batch(RecordBatch(
                {"id": np.asarray([1, 2], np.int64),
                 "v": np.asarray([1.5, 2.5])}))
            sink.close()
            # fresh consumer: schemas come FROM the registry by id
            deser = AvroRegistrySerializer(reg.url, "ev-value")
            src = KafkaWireSource(broker.host, broker.port, "ev",
                                  value_decoder=deser.decoder())
            rows = [r for sp in src.create_splits(1)
                    for b in sp.read() for r in b.to_rows()]
            assert sorted((r["id"], r["v"]) for r in rows) == \
                [(1, 1.5), (2, 2.5)]
        finally:
            broker.stop()


def test_scram_username_with_comma_and_equals(tmp_path):
    """RFC 5802 saslname escaping: ',' and '=' in usernames transit as
    =2C/=3D and authenticate the same as under PLAIN."""
    from flink_tpu.connectors.kafka import KafkaWireBroker, KafkaWireClient

    b = KafkaWireBroker(directory=str(tmp_path / "k"),
                        users={"a,b=c": "pw"}).start()
    try:
        b.create_topic("t", partitions=1)
        c = KafkaWireClient(b.host, b.port, username="a,b=c",
                            password="pw",
                            sasl_mechanism="SCRAM-SHA-256")
        c.produce("t", 0, [(None, b"x")])
        assert c.latest_offset("t", 0) == 1
        c.close()
    finally:
        b.stop()


def test_inference_refuses_null_first_row(reg):
    ser = AvroRegistrySerializer(reg.url, "nulls-value")
    with pytest.raises(SchemaRegistryError, match="cannot infer"):
        ser.encode({"x": None})
    # short/garbage payloads raise the documented error type
    with pytest.raises(SchemaRegistryError, match="wire format"):
        ser.decode(b"\x00\x01")
