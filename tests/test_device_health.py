"""Device-lane health: watchdog, classification, quarantine, degraded
tier, background healing, checkpoint-aligned re-promotion.

The ISSUE-4 tentpole suite: the accelerator is a failure domain — a
wedged dispatch must be detected (sacrificial watcher, bounded deadline),
the device tier quarantined, the operator degraded MID-JOB onto the
host/numpy tier bit-exactly, and healed back at a checkpoint boundary.
All on CPU, via the deterministic ``WedgedDevice`` chaos schedule hanging
the ``device.dispatch`` fault point.
"""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from flink_tpu.core.batch import RecordBatch, Watermark
from flink_tpu.core.functions import RuntimeContext, SumAggregator
from flink_tpu.operators.window_agg import WindowAggOperator
from flink_tpu.runtime import device_health as dh
from flink_tpu.runtime.device_health import (DeviceHealthMonitor,
                                             DeviceQuarantinedError,
                                             WatchdogConfig, classify_failure)
from flink_tpu.testing import chaos
from flink_tpu.testing.chaos import FailTimes, FaultInjector, WedgedDevice
from flink_tpu.windowing.assigners import TumblingEventTimeWindows

pytestmark = pytest.mark.chaos

WINDOW_MS = 1000


@pytest.fixture(autouse=True)
def _clean_monitor_and_injector():
    """Neither a quarantined monitor nor an injector may leak across
    tests (the monitor is process-wide by design)."""
    prev = dh.get_monitor(create=False)
    yield
    dh.set_monitor(prev if prev is not None and prev.healthy else None)
    chaos.uninstall()


def _fast_monitor(**kw):
    # the first-dispatch grace stays generous by default: operator tests'
    # first dispatch carries an XLA compile, which must not read as a
    # wedge even under the test-sized deadline floor
    cfg = WatchdogConfig(deadline_floor_s=kw.pop("deadline_floor_s", 0.25),
                         first_dispatch_grace_s=kw.pop(
                             "first_dispatch_grace_s", 30.0),
                         backoff_initial_s=0.001, backoff_max_s=0.01,
                         probe_backoff_initial_s=0.02,
                         probe_backoff_max_s=0.1)
    mon = DeviceHealthMonitor(cfg, **kw)
    dh.set_monitor(mon)
    return mon


def _build_op(emit_tier="device", paging_cap=0, **kw):
    paging = None
    if paging_cap:
        from flink_tpu.state.paging import PagingConfig
        paging = PagingConfig(capacity=paging_cap)
    op = WindowAggOperator(
        TumblingEventTimeWindows.of(WINDOW_MS), SumAggregator(jnp.float32),
        key_column="k", value_column="v", emit_tier=emit_tier,
        paging=paging, **kw)
    op.open(RuntimeContext())
    return op


def _batches(n=20, b=256, keys=37, seed=5):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        k = rng.integers(0, keys, b)
        v = np.ones(b, np.float32)
        ts = i * (WINDOW_MS // 2) + np.sort(
            rng.integers(0, WINDOW_MS // 2, b)).astype(np.int64)
        out.append((k, v, ts))
    return out


def _digests(elements):
    """(rows, sum) per fired window — merged per window id, because a
    paged fire legitimately emits resident and spilled keys as separate
    batches of the same window."""
    out = {}
    for b in elements:
        if hasattr(b, "columns") and "result" in b.columns:
            w = int(np.asarray(b.column("window_start"))[0])
            rows, total = out.get(w, (0, 0.0))
            out[w] = (rows + len(b),
                      total + float(np.asarray(b.column("result"),
                                               np.float64).sum()))
    return sorted((w, r, s) for w, (r, s) in out.items())


# ---------------------------------------------------------------------------
# monitor unit tests
# ---------------------------------------------------------------------------

def test_classifier_conservative():
    assert classify_failure(RuntimeError(
        "RESOURCE_EXHAUSTED: out of memory")) == dh.OOM
    assert classify_failure(RuntimeError("UNAVAILABLE: socket closed")) \
        == dh.TRANSIENT
    assert classify_failure(RuntimeError("INTERNAL: stream terminated")) \
        == dh.TRANSIENT
    assert classify_failure(chaos.InjectedFault("boom")) == dh.TRANSIENT
    # programming errors must surface unchanged, never retried — absl
    # status codes match as UPPERCASE words, not prose substrings
    assert classify_failure(TypeError("bad operand shape")) == dh.FATAL
    assert classify_failure(ValueError("shapes (3,) and (4,)")) == dh.FATAL
    assert classify_failure(ValueError("unknown key column x")) == dh.FATAL
    assert classify_failure(ValueError("operation aborted by user")) \
        == dh.FATAL
    assert classify_failure(KeyError("internal_field")) == dh.FATAL


def test_watchdog_fires_under_wedged_device():
    """A dispatch hung by WedgedDevice misses its deadline: the lane is
    sacrificed, the tier quarantined, the caller unblocked with
    DeviceQuarantinedError — the task mailbox never hangs."""
    mon = _fast_monitor(heal_async=False, first_dispatch_grace_s=0.25)
    inj = FaultInjector(seed=1)
    sched = inj.inject("device.dispatch", WedgedDevice(at=1))
    ran = []
    with chaos.installed(inj):
        t0 = time.monotonic()
        with pytest.raises(DeviceQuarantinedError):
            mon.run_guarded(lambda: ran.append(1))
        assert time.monotonic() - t0 < 5.0   # bounded, not forever
    assert mon.quarantined
    assert mon.counters["watchdog_timeouts"] == 1
    assert mon.counters["quarantines"] == 1
    # the abandoned lane must NOT run the thunk once the schedule heals
    sched.heal()
    time.sleep(0.1)
    assert ran == []
    # further dispatches refuse fast (no deadline wait) while quarantined
    t0 = time.monotonic()
    with pytest.raises(DeviceQuarantinedError):
        mon.run_guarded(lambda: 1)
    assert time.monotonic() - t0 < 0.1


def test_transient_retry_succeeds_without_quarantine():
    mon = _fast_monitor(heal_async=False)
    inj = FaultInjector(seed=2)
    inj.inject("device.dispatch", FailTimes(2))
    with chaos.installed(inj):
        assert mon.run_guarded(lambda: "ok") == "ok"
    assert mon.healthy
    assert mon.counters["transient_retries"] == 2
    assert mon.counters["quarantines"] == 0


def test_exhausted_transient_retries_quarantine():
    mon = _fast_monitor(heal_async=False)
    inj = FaultInjector(seed=3)
    inj.inject("device.dispatch", FailTimes(50))
    with chaos.installed(inj):
        with pytest.raises(DeviceQuarantinedError):
            mon.run_guarded(lambda: "ok")
    assert mon.quarantined


def test_background_healer_heals_on_schedule_heal():
    """The healer probes under backoff (chaos-aware probe: the wedge
    schedule IS the device state) and flips the tier back to HEALTHY
    exactly once after heal()."""
    mon = _fast_monitor(heal_async=True, first_dispatch_grace_s=0.25)
    inj = FaultInjector(seed=4)
    sched = inj.inject("device.dispatch", WedgedDevice(at=1))
    with chaos.installed(inj):
        with pytest.raises(DeviceQuarantinedError):
            mon.run_guarded(lambda: 1)
        time.sleep(0.15)
        assert mon.quarantined, "probe must fail while wedged"
        sched.heal()
        deadline = time.monotonic() + 5.0
        while mon.quarantined and time.monotonic() < deadline:
            time.sleep(0.01)
    assert mon.healthy
    assert mon.counters["heals"] == 1
    assert mon.counters["quarantines"] == 1


def test_deadline_scales_with_measured_dispatch_cost():
    from flink_tpu.utils import transport
    mon = DeviceHealthMonitor(WatchdogConfig(deadline_floor_s=1.0,
                                             deadline_multiplier=10.0))
    assert mon.deadline_s(100.0) == 1.0         # unmeasured: floor
    saved = transport._samples, transport._verdict
    try:
        transport.reset()
        for _ in range(3):
            transport.record_dispatch_cost(1.0, 0.05)   # 50 ms/MB
        # 100 MB * 50 ms/MB * 10x = 50 s > floor
        assert mon.deadline_s(100.0) == pytest.approx(50.0)
        assert mon.deadline_s(0.001) == 1.0     # tiny upload: floor rules
    finally:
        transport._samples, transport._verdict = saved


# ---------------------------------------------------------------------------
# operator-level: degradation, OOM page-out, quarantine->heal digests
# ---------------------------------------------------------------------------

def _run_operator(op, batches, wedge_at=None, heal_at=None, snap_at=None,
                  repromote_at=None, seed=1):
    """Drive an operator through batches + per-batch watermarks under an
    optional WedgedDevice schedule; returns (digests, mid snapshot)."""
    inj = FaultInjector(seed=seed)
    sched = (inj.inject("device.dispatch", WedgedDevice(at=wedge_at))
             if wedge_at else None)
    out, snap = [], None
    with chaos.installed(inj):
        for i, (k, v, ts) in enumerate(batches):
            out += op.process_batch(RecordBatch({"k": k, "v": v},
                                                timestamps=ts))
            out += op.process_watermark(Watermark(int(ts.max()) - 1))
            if snap_at is not None and i == snap_at:
                op.prepare_snapshot_pre_barrier()
                snap = op.snapshot_state()
            if heal_at is not None and i == heal_at:
                sched.heal()
                assert dh.get_monitor().probe_now()
            if repromote_at is not None and i == repromote_at:
                out += op.prepare_snapshot_pre_barrier()
        out += op.end_input()
    return _digests(out), snap


def test_quarantine_heal_cycle_digest_identical_device_tier():
    """The acceptance cycle at operator level: wedge mid-stream ->
    degrade (no dropped records) -> checkpoint DURING quarantine ->
    heal -> re-promote at the next safe point; digests equal an unfaulted
    run, and the mid-quarantine checkpoint restores on BOTH tiers."""
    batches = _batches()
    _fast_monitor(heal_async=False)
    clean, _ = _run_operator(_build_op(), batches)

    mon = _fast_monitor(heal_async=False)
    op = _build_op()
    wedged, snap = _run_operator(op, batches, wedge_at=8, snap_at=10,
                                 heal_at=11, repromote_at=14)
    assert wedged == clean
    assert snap is not None
    st = op.device_health_stats()
    assert st == {"degraded": 0, "quarantine_migrations": 1,
                  "repromotions": 1}
    assert mon.counters["quarantines"] == 1 and mon.counters["heals"] == 1

    # suffix digests of the clean run, for the replay comparison
    op_ref = _build_op()
    ref_out = []
    for i, (k, v, ts) in enumerate(batches):
        els = op_ref.process_batch(RecordBatch({"k": k, "v": v},
                                               timestamps=ts))
        els += op_ref.process_watermark(Watermark(int(ts.max()) - 1))
        if i > 10:
            ref_out += els
    ref_out += op_ref.end_input()
    suffix = _digests(ref_out)

    def replay(snapshot, monitor):
        dh.set_monitor(monitor)
        op2 = _build_op()
        op2.restore_state(snapshot)
        out = []
        for i, (k, v, ts) in enumerate(batches):
            if i <= 10:
                continue
            out += op2.process_batch(RecordBatch({"k": k, "v": v},
                                                 timestamps=ts))
            out += op2.process_watermark(Watermark(int(ts.max()) - 1))
        out += op2.end_input()
        return _digests(out), op2

    # tier A: healthy device tier
    healthy, op_a = replay(snap, _fast_monitor(heal_async=False))
    assert not op_a._degraded
    assert healthy == suffix
    # tier B: monitor still quarantined -> the first dispatch migrates
    # and the whole replay runs degraded, same digests
    qmon = _fast_monitor(heal_async=False)
    qmon.quarantine("test: still wedged")
    degraded, op_b = replay(snap, qmon)
    assert op_b._degraded
    assert degraded == suffix


def test_quarantine_heal_cycle_host_tier():
    """Host emit tier: the mirror is already authoritative — degrading
    just stops the replica dispatch; fires stay identical, and the
    re-promotion refresh restores device/mirror equality."""
    batches = _batches(seed=9)
    _fast_monitor(heal_async=False)
    clean, _ = _run_operator(_build_op(emit_tier="host"), batches)

    mon = _fast_monitor(heal_async=False)
    op = _build_op(emit_tier="host")
    wedged, _ = _run_operator(op, batches, wedge_at=6, heal_at=10,
                              repromote_at=12)
    assert wedged == clean
    assert op.device_health_stats()["repromotions"] == 1
    assert mon.counters["quarantines"] == 1 and mon.counters["heals"] == 1
    assert op.verify_mirror(), "re-promoted replica must equal the mirror"


def test_oom_forces_pageout_and_digests_survive():
    """A RESOURCE_EXHAUSTED dispatch triggers the DevicePager pressure
    valve (forced page-out of cold rows), then the retry succeeds — no
    quarantine, and fire digests equal an un-faulted paged run."""
    def paged_batches():
        out = []
        for i in range(6):
            # rotating key ranges: batch i touches keys [i*64, i*64+128)
            k = (np.arange(256) % 128) + (i * 64)
            v = np.ones(256, np.float32)
            ts = i * (WINDOW_MS // 2) + np.sort(
                np.arange(256) % (WINDOW_MS // 2)).astype(np.int64)
            out.append((k, v, ts))
        return out

    _fast_monitor(heal_async=False)
    clean, _ = _run_operator(_build_op(paging_cap=512), paged_batches())

    mon = _fast_monitor(heal_async=False)
    inj = FaultInjector(seed=7)
    # OOM at the THIRD dispatch: by then resident rows beyond the current
    # batch's (protected) working set exist, so the valve has victims
    inj.inject("device.dispatch",
               chaos.ActionSequence(
                   ["ok", "ok",
                    ("fail", "RESOURCE_EXHAUSTED: out of memory "
                             "allocating 1.0G")]))
    op = _build_op(paging_cap=512)
    out = []
    with chaos.installed(inj):
        for k, v, ts in paged_batches():
            out += op.process_batch(RecordBatch({"k": k, "v": v},
                                                timestamps=ts))
            out += op.process_watermark(Watermark(int(ts.max()) - 1))
        out += op.end_input()
    assert mon.counters["oom_pageouts"] == 1
    assert mon.counters["quarantines"] == 0
    assert op.paging_stats()["evictions"] > 0, "valve never paged out"
    assert _digests(out) == clean


def test_unsupported_tier_fails_task_instead_of_degrading():
    """No host twin tier (count trigger): the wedge surfaces as an error
    — the normal restart path owns recovery, not a silent wrong tier."""
    from flink_tpu.windowing.assigners import GlobalWindows
    from flink_tpu.windowing.triggers import CountTrigger
    mon = _fast_monitor(heal_async=False, first_dispatch_grace_s=0.3)
    op = WindowAggOperator(GlobalWindows(), SumAggregator(jnp.float32),
                           key_column="k", value_column="v",
                           trigger=CountTrigger.of(4))
    op.open(RuntimeContext())
    inj = FaultInjector(seed=8)
    inj.inject("device.dispatch", WedgedDevice(at=1))
    with chaos.installed(inj):
        with pytest.raises(DeviceQuarantinedError):
            op.process_batch(RecordBatch(
                {"k": np.arange(8) % 3,
                 "v": np.ones(8, np.float32)},
                timestamps=np.arange(8, dtype=np.int64)))
    assert mon.quarantined


def test_degraded_key_growth_keeps_all_panes_consistent():
    """Keys that first appear DURING quarantine, touching only some
    panes: every live mirror pane must still serve fires, snapshots and
    re-promotion at the new key count (the _grow_keys all-pane invariant
    carried into degraded mode)."""
    mon = _fast_monitor(heal_async=False)
    op = _build_op(initial_key_capacity=16)
    inj = FaultInjector(seed=12)
    sched = inj.inject("device.dispatch", WedgedDevice(at=2))
    out = []
    with chaos.installed(inj):
        # batch 1 (healthy): 16 keys into window 0's pane
        k = np.arange(16)
        ts = np.zeros(16, np.int64)
        out += op.process_batch(RecordBatch(
            {"k": k, "v": np.ones(16, np.float32)}, timestamps=ts))
        # batch 2 wedges -> degrade (same window-0 pane)
        out += op.process_batch(RecordBatch(
            {"k": k, "v": np.ones(16, np.float32)}, timestamps=ts))
        assert op._degraded
        # batch 3 (degraded): 200 NEW keys touch ONLY window 1's pane —
        # window 0's pane entry must still grow with the key count
        k2 = np.arange(16, 216)
        ts2 = np.full(200, 1500, np.int64)
        out += op.process_batch(RecordBatch(
            {"k": k2, "v": np.ones(200, np.float32)}, timestamps=ts2))
        # fire both windows + snapshot DURING quarantine at the grown count
        out += op.process_watermark(Watermark(2100))
        op.prepare_snapshot_pre_barrier()
        snap = op.snapshot_state()
        assert snap["counts"].shape[0] == 216
        # heal + re-promote at the grown key count
        sched.heal()
        assert mon.probe_now()
        op.prepare_snapshot_pre_barrier()
        assert not op._degraded
        out += op.end_input()
    d = dict((w, (r, s)) for w, r, s in _digests(out))
    assert d[0] == (16, 32.0)       # both window-0 batches counted
    assert d[1000] == (200, 200.0)  # degraded-only keys all fired


def test_salvage_read_is_deadline_bounded():
    """A device that cannot serve the migration's state download within
    the salvage deadline must not hang the task thread: the salvage
    raises and the caller falls back to checkpoint recovery."""
    import threading as _th
    mon = _fast_monitor(heal_async=False)
    hang = _th.Event()
    with pytest.raises(DeviceQuarantinedError, match="salvage"):
        mon.run_salvage(hang.wait, deadline_s=0.2, label="migration")
    hang.set()  # release the sacrificed lane thread


def test_lane_threads_pruned_when_task_threads_die():
    """Per-task-thread lanes are pruned once their owning thread exits —
    no thread/memory leak across many short-lived jobs."""
    import threading as _th
    mon = _fast_monitor(heal_async=False)

    def _dispatch():
        mon.run_guarded(lambda: 1)

    for _ in range(5):
        t = _th.Thread(target=_dispatch)
        t.start()
        t.join()
    mon.run_guarded(lambda: 1)   # lookup prunes the dead threads' lanes
    assert len(mon._lanes) == 1


# ---------------------------------------------------------------------------
# surface area: job_status, metrics, REST panel
# ---------------------------------------------------------------------------

def test_job_status_reports_device_health_defaults():
    from flink_tpu.cluster.minicluster import MiniCluster
    dh.reset_monitor()
    status = MiniCluster().job_status()["device_health"]
    assert status["state"] == "healthy"
    assert status["quarantines"] == 0 and status["heals"] == 0
    assert status["degraded_operators"] == 0


def test_device_health_metrics_registered():
    from flink_tpu.cluster.minicluster import MiniCluster
    from flink_tpu.metrics.groups import (DEVICE_HEALTH_HEALS,
                                          DEVICE_HEALTH_QUARANTINES,
                                          DEVICE_HEALTH_STATE)
    cluster = MiniCluster()
    names = set(cluster.metrics_registry.all_metrics())
    for suffix in (DEVICE_HEALTH_STATE, DEVICE_HEALTH_QUARANTINES,
                   DEVICE_HEALTH_HEALS):
        assert any(k.endswith(suffix) for k in names), suffix
    mon = _fast_monitor(heal_async=False)
    mon.quarantine("test")
    metrics = cluster.metrics_registry.all_metrics()
    state = next(m for k, m in metrics.items()
                 if k.endswith(DEVICE_HEALTH_STATE))
    assert state.get_value() == 1


def test_device_health_html_panel():
    from flink_tpu.rest.views import device_health_html
    frag = device_health_html({"state": "quarantined", "quarantines": 1,
                               "heals": 0, "watchdog_timeouts": 1,
                               "degraded_operators": 2,
                               "last_failure": "update_step wedged"})
    assert 'data-state="quarantined"' in frag
    assert "dh-quarantined" in frag
    assert 'data-metric="quarantines"' in frag
    assert "update_step wedged" in frag
    healthy = device_health_html({"state": "healthy"})
    assert 'data-state="healthy"' in healthy and "dh-healthy" in healthy


# ---------------------------------------------------------------------------
# cluster acceptance: wedge mid-stream, degrade, heal at a checkpoint
# ---------------------------------------------------------------------------

def _run_cluster_job(inject: bool, seed=31):
    from flink_tpu.datastream.api import StreamExecutionEnvironment
    from flink_tpu.runtime.checkpoint.storage import InMemoryCheckpointStorage

    # a generous deadline floor: under cluster load a HEALTHY dispatch can
    # take hundreds of ms on a shared vCPU — the watchdog must only catch
    # the injected wedge (which hangs far past any real dispatch)
    mon = _fast_monitor(heal_async=True, deadline_floor_s=2.0)
    rng = np.random.default_rng(seed)
    n = 30_000
    keys = rng.integers(0, 23, n)
    vals = np.ones(n, dtype=np.float64)
    ts = np.sort(rng.integers(0, 4000, n))
    env = StreamExecutionEnvironment()
    env.set_parallelism(1)
    sink = (env.from_collection(columns={"k": keys, "v": vals, "t": ts},
                                batch_size=128)
            .assign_timestamps_and_watermarks(0, timestamp_column="t")
            .key_by("k")
            .window(TumblingEventTimeWindows.of(1000))
            .sum("v").collect())
    inj = FaultInjector(seed=seed)
    healer = None
    if inject:
        sched = inj.inject("device.dispatch", WedgedDevice(at=40))

        def _heal_once_quarantined():
            deadline = time.monotonic() + 60
            while not mon.quarantined and time.monotonic() < deadline:
                time.sleep(0.005)
            time.sleep(0.1)      # degraded batches + a checkpoint pass
            # pause the sources so the job cannot finish before the heal
            # and the checkpoint-aligned re-promotion have happened (the
            # paused sources keep serving checkpoint barriers)
            cluster = env._last_cluster
            for t in cluster._source_tasks:
                t._paused.set()
            try:
                sched.heal()     # background healer probes it healthy
                while mon.quarantined and time.monotonic() < deadline:
                    time.sleep(0.005)
                while (cluster.device_health_status()["repromotions"] < 1
                       and time.monotonic() < deadline):
                    time.sleep(0.005)
            finally:
                for t in cluster._source_tasks:
                    t._paused.clear()

        healer = threading.Thread(target=_heal_once_quarantined,
                                  daemon=True)
        healer.start()
    with chaos.installed(inj):
        res = env.execute_cluster(storage=InMemoryCheckpointStorage(
            retain=10), checkpoint_interval_ms=20,
            tolerable_failed_checkpoints=0)
    if healer is not None:
        healer.join(timeout=10)
    status = env._last_cluster.job_status()
    rows = sorted((int(r["k"]), int(r["window_start"]), float(r["v"]))
                  for r in sink.rows())
    return res, rows, status


@pytest.mark.slow
def test_acceptance_wedge_degrade_heal_cluster_exactly_once():
    """ISSUE-4 acceptance: a windowed job wedges mid-stream, degrades to
    the host tier without dropping records, heals back to the device tier
    at a checkpoint boundary; fire digests + exactly-once counters equal
    an unfaulted run; job_status() records exactly one quarantine and one
    heal."""
    from flink_tpu.cluster.task import TaskStates

    res0, rows0, status0 = _run_cluster_job(inject=False)
    assert res0.state == TaskStates.FINISHED
    assert status0["device_health"]["quarantines"] == 0

    res1, rows1, status1 = _run_cluster_job(inject=True)
    assert res1.state == TaskStates.FINISHED
    assert res1.restarts == 0, "degradation must not cost a restart"
    assert rows1 == rows0, "fire digests diverged from the unfaulted run"
    hs = status1["device_health"]
    assert hs["quarantines"] == 1 and hs["heals"] == 1
    assert hs["quarantine_migrations"] == 1
    assert hs["repromotions"] == 1
    assert hs["state"] == "healthy"
    assert hs["degraded_operators"] == 0
    assert status1["checkpoints"]["failed_checkpoints"] == \
        status0["checkpoints"]["failed_checkpoints"] == 0
    # records_in per vertex equal (no drops, no replays)
    recs0 = {v["name"]: v["records_in"] for v in status0["vertices"]}
    recs1 = {v["name"]: v["records_in"] for v in status1["vertices"]}
    assert recs0 == recs1
