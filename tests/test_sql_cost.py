"""Cost-based join reordering (VERDICT r3 next #5).

The done-criterion test: a 3-way join whose cheapest order differs from
the written order and measurably beats it, plus the model/DP units and
the EXPLAIN cost section.  Reference: ``Optimizer.java:402``."""

import time

import numpy as np
import pytest

from flink_tpu.sql.cost import (TableStats, _best_order, _Edge, _Rel,
                                filter_selectivity, join_reorder)
from flink_tpu.sql.parser import parse
from flink_tpu.sql.table_env import TableEnvironment


def _env(big=20_000, tiny=50):
    """big_a x big_b share a LOW-NDV key (explosive join); tiny_c shrinks
    big_b first when joined early."""
    rng = np.random.default_rng(5)
    t = TableEnvironment()
    t.register_collection("big_a", columns={
        "x": rng.integers(0, 40, big), "va": np.arange(big)})
    t.register_collection("big_b", columns={
        "x": rng.integers(0, 40, big), "y": rng.integers(0, big, big),
        "vb": np.arange(big)})
    t.register_collection("tiny_c", columns={
        "y": np.arange(tiny), "vc": np.arange(tiny) * 10})
    return t


def test_stats_lazy_and_cached():
    t = _env()
    ct = t._catalog["tiny_c"]
    assert ct.stats is None               # registration pays nothing
    st = ct.get_stats()
    assert st.row_count == 50 and st.ndv["y"] == 50
    assert ct.get_stats() is st           # cached


def test_derived_table_base_keeps_order():
    """Regression: a derived-table FROM base with two joins must plan (the
    rule bails instead of using an unhashable SelectStmt as a catalog key)."""
    t = _env(big=200, tiny=10)
    rows = t.sql_query(
        "SELECT d.va, tiny_c.vc FROM (SELECT x, va FROM big_a) d "
        "JOIN big_b ON d.x = big_b.x "
        "JOIN tiny_c ON big_b.y = tiny_c.y").execute().collect()
    assert rows  # planned and executed


def test_select_star_schema_stable():
    """SELECT * must keep the written column order — the rule must not
    rewrite queries whose OUTPUT depends on join order."""
    t = _env(big=500, tiny=10)
    res = t.sql_query(
        "SELECT * FROM big_a "
        "JOIN big_b ON big_a.x = big_b.x "
        "JOIN tiny_c ON big_b.y = tiny_c.y").execute()
    assert res.output_columns[:2] == ["x", "va"]   # big_a leads


def test_filter_selectivity_heuristics():
    from flink_tpu.sql.parser import Binary, Column, Literal
    st = TableStats(row_count=1000, ndv={"k": 100})
    eq = Binary("=", Column("k"), Literal(5))
    gt = Binary(">", Column("k"), Literal(5))
    assert filter_selectivity(eq, st) == pytest.approx(1 / 100)
    assert filter_selectivity(gt, st) == pytest.approx(0.3)
    assert filter_selectivity(Binary("AND", eq, gt), st) \
        == pytest.approx(0.3 / 100)


def test_dp_prefers_selective_edge_first():
    # A(1e5) -x- B(1e5) -y- C(10): best left-deep order starts from the
    # B-C edge, never materializing the A-B blowup first
    rels = [
        _Rel(0, "A", "A", None, 1e5, {"x": 10}),
        _Rel(1, "B", "B", None, 1e5, {"x": 10, "y": 1e5}),
        _Rel(2, "C", "C", None, 10, {"y": 10}),
    ]
    edges = [_Edge(0, 1, "x", "x", None), _Edge(1, 2, "y", "y", None)]
    order, cost = _best_order(rels, edges)
    assert order[0] in (1, 2) and set(order[:2]) == {1, 2}
    assert cost < 1e9


def test_three_way_join_reordered_and_faster():
    """The written order A JOIN B (x, 40 NDV -> ~10M rows) JOIN C must be
    replaced by one that joins tiny_c early; results identical; wall time
    measurably better."""
    sql = ("SELECT big_a.va, big_b.vb, tiny_c.vc FROM big_a "
           "JOIN big_b ON big_a.x = big_b.x "
           "JOIN tiny_c ON big_b.y = tiny_c.y")
    t = _env()
    plan = t.explain_sql(sql)
    assert "Join Order (cost-based)" in plan
    assert "order=['tiny_c'" in plan or "order=['big_b', 'tiny_c'" in plan, \
        plan
    # correctness: same rows as the syntactic plan (rule disabled)
    import flink_tpu.sql.rules as rules_mod
    rows_opt = t.sql_query(sql).execute().collect()
    saved = list(rules_mod.RULES)
    rules_mod.RULES = [r for r in saved if "join_reorder" not in r[0]]
    try:
        t2 = _env()
        t0 = time.perf_counter()
        rows_syn = t2.sql_query(sql).execute().collect()
        syn_s = time.perf_counter() - t0
    finally:
        rules_mod.RULES = saved
    t3 = _env()
    t0 = time.perf_counter()
    rows_opt2 = t3.sql_query(sql).execute().collect()
    opt_s = time.perf_counter() - t0

    def key(rows):
        return sorted((int(r["va"]), int(r["vb"]), int(r["vc"]))
                      for r in rows)

    assert key(rows_opt) == key(rows_syn) == key(rows_opt2)
    # the syntactic order materializes the ~10M-row A-B blowup; the chosen
    # order never does — demand a decisive wall-clock win despite host noise
    assert opt_s * 1.5 < syn_s, (opt_s, syn_s)


def test_outer_join_keeps_syntactic_order():
    t = _env()
    sql = ("SELECT big_a.va FROM big_a "
           "LEFT JOIN big_b ON big_a.x = big_b.x "
           "JOIN tiny_c ON big_b.y = tiny_c.y")
    stmt = parse(sql)
    from flink_tpu.sql.rules import apply_rules
    out = apply_rules(stmt, t._catalog)
    assert out.table == "big_a"            # untouched
    assert getattr(out, "join_order_cost", None) is None


def test_no_stats_keeps_syntactic_order():
    t = _env()
    # a source-backed table has no stats
    from flink_tpu.connectors.sources import IteratorSource
    t.register_source("ext", IteratorSource([]), ["y", "w"])
    stmt = parse("SELECT big_a.va FROM big_a "
                 "JOIN big_b ON big_a.x = big_b.x "
                 "JOIN ext ON big_b.y = ext.y")
    assert join_reorder(stmt, t._catalog) is None


def test_annotation_when_order_kept():
    """Even a kept order records its estimated cost for EXPLAIN."""
    t = TableEnvironment()
    t.register_collection("s1", columns={"k": np.arange(10)})
    t.register_collection("s2", columns={"k": np.arange(10),
                                         "j": np.arange(10)})
    t.register_collection("s3", columns={"j": np.arange(10)})
    plan = t.explain_sql(
        "SELECT s1.k FROM s1 JOIN s2 ON s1.k = s2.k "
        "JOIN s3 ON s2.j = s3.j")
    assert "est_cost=" in plan
