"""ORC from the spec (``formats/orc.py``).

RLEv2 decoding is validated against the worked byte examples in the
public ORC specification (short-repeat / direct / delta), RLEv1 and the
file layer by round trip and by hand-parsed structure — the same
methodology as the Parquet and Avro suites (no foreign implementation
exists in this image; the caveat rides PARITY.md)."""

from __future__ import annotations

import numpy as np
import pytest

from flink_tpu.core.batch import RecordBatch
from flink_tpu.formats.orc import (
    COMP_ZLIB, MAGIC, _bool_decode, _bool_encode, _byte_rle_decode,
    _byte_rle_encode, _compress_stream, _decompress_stream, _pb_decode,
    _rle1_decode, _rle1_encode, _rle2_decode, read_orc, write_orc)


class TestRleV2SpecVectors:
    """The ORC spec's own worked examples, byte for byte."""

    def test_short_repeat(self):
        # [10000] * 5 -> 0x0a 0x27 0x10 (width 2 bytes, count 5)
        got = _rle2_decode(bytes([0x0A, 0x27, 0x10]), 5, signed=False)
        assert got.tolist() == [10000] * 5

    def test_direct(self):
        # [23713, 43806, 57005, 48879] -> 5e 03 5c a1 ab 1e de ad be ef
        data = bytes([0x5E, 0x03, 0x5C, 0xA1, 0xAB, 0x1E,
                      0xDE, 0xAD, 0xBE, 0xEF])
        got = _rle2_decode(data, 4, signed=False)
        assert got.tolist() == [23713, 43806, 57005, 48879]

    def test_delta(self):
        # [2,3,5,7,11,13,17,19,23,29] -> c6 09 02 02 22 42 42 46
        data = bytes([0xC6, 0x09, 0x02, 0x02, 0x22, 0x42, 0x42, 0x46])
        got = _rle2_decode(data, 10, signed=False)
        assert got.tolist() == [2, 3, 5, 7, 11, 13, 17, 19, 23, 29]

    def test_delta_fixed_width_zero(self):
        # width code 0 = fixed delta: base 10, delta -2, length 4
        # header: 11 00000 0 -> 0xC0, len-1 = 3
        import flink_tpu.formats.orc as orc

        data = bytes([0xC0, 0x03]) + orc._uvarint(10) + orc._svarint(-2)
        got = _rle2_decode(data, 4, signed=False)
        assert got.tolist() == [10, 8, 6, 4]

    def test_patched_base_hand_built(self):
        """Hand-built per the spec's field layout: base 100, width 4 bits,
        one outlier patched with 8 extra bits at position 2."""
        vals = [1, 5, 3, 7]          # packed 4-bit values
        # outlier: position 2 gets patch 0x1 -> value 3 | (1 << 4) = 19
        header = bytes([0x80 | (3 << 1), 0x03])  # width code 3 (=4 bits), len 4
        bw_pw = bytes([(0 << 5) | 6])  # base width 1 byte, patch width code 6 (=7 bits)
        # -> patch width 7 bits, gap width 3 bits, patch list length 1
        pgw_pll = bytes([(2 << 5) | 1])
        base = bytes([100])
        packed = bytes([vals[0] << 4 | vals[1], vals[2] << 4 | vals[3]])
        # one patch entry: gap 2 (3 bits), patch 1 (7 bits) -> 10 bits,
        # padded to 2 bytes big-endian: 010 0000001 000000
        entry = (2 << 7) | 1
        patch_bytes = bytes([(entry >> 2) & 0xFF, (entry & 0x3) << 6])
        data = header + bw_pw + pgw_pll + base + packed + patch_bytes
        got = _rle2_decode(data, 4, signed=False)
        assert got.tolist() == [101, 105, 100 + 19, 107]

    def test_negative_base_sign_magnitude(self):
        # patched base with MSB-set base byte = negative base
        header = bytes([0x80 | (3 << 1), 0x01])  # 4-bit width, len 2
        meta = bytes([(0 << 5) | 0, (0 << 5) | 0])  # bw 1, pw 1bit, no patches
        base = bytes([0x80 | 10])    # sign-magnitude: -10
        packed = bytes([2 << 4 | 4])
        got = _rle2_decode(header + meta + base + packed, 2, signed=False)
        assert got.tolist() == [-8, -6]


class TestRleV1:
    def test_runs_and_literals_round_trip(self, rng):
        cases = [
            np.arange(1000, dtype=np.int64),              # one long run
            np.full(500, -7, np.int64),                   # constant
            rng.integers(-10**12, 10**12, 333),           # literals
            np.asarray([5], np.int64),
            np.asarray([], np.int64),
            np.repeat(np.arange(10), 40),                 # many runs
        ]
        for vals in cases:
            vals = vals.astype(np.int64)
            enc = _rle1_encode(vals, signed=True)
            assert np.array_equal(_rle1_decode(enc, len(vals), True), vals)

    def test_unsigned_lengths(self, rng):
        vals = rng.integers(0, 100, 777).astype(np.int64)
        enc = _rle1_encode(vals, signed=False)
        assert np.array_equal(_rle1_decode(enc, 777, False), vals)

    def test_run_compression_is_real(self):
        enc = _rle1_encode(np.arange(130, dtype=np.int64), signed=True)
        assert len(enc) <= 4          # one run record: ctrl, delta, base


class TestByteAndBoolRle:
    def test_byte_rle_round_trip(self, rng):
        for raw in (b"\x00" * 100, bytes(rng.integers(0, 256, 257)),
                    b"ab" * 3 + b"\x07" * 50, b""):
            assert _byte_rle_decode(_byte_rle_encode(raw), len(raw)) == raw

    def test_bool_round_trip(self, rng):
        for mask in (np.zeros(100, bool), np.ones(31, bool),
                     rng.integers(0, 2, 97).astype(bool)):
            assert np.array_equal(_bool_decode(_bool_encode(mask),
                                               len(mask)), mask)


class TestFileRoundTrip:
    def batch(self, rng, n=1000):
        return RecordBatch({
            "i64": rng.integers(-10**14, 10**14, n),
            "i32": rng.integers(-2**30, 2**30, n).astype(np.int32),
            "f64": rng.random(n),
            "f32": rng.random(n).astype(np.float32),
            "flag": rng.integers(0, 2, n).astype(bool),
            "name": np.asarray([f"row-{i}'s ünïcode" for i in range(n)],
                               object),
        })

    @pytest.mark.parametrize("compression", ["none", "zlib"])
    def test_round_trip(self, tmp_path, rng, compression):
        p = str(tmp_path / "t.orc")
        src = self.batch(rng)
        n = write_orc([src], p, compression=compression)
        assert n == 1000
        (got,) = read_orc(p)
        for c in src.columns:
            a, b = np.asarray(src.column(c)), np.asarray(got.column(c))
            if a.dtype.kind == "f":
                assert np.allclose(a, b) and a.dtype == b.dtype
            elif a.dtype == object:
                assert a.tolist() == b.tolist()
            else:
                assert np.array_equal(a, b) and a.dtype == b.dtype

    def test_multiple_stripes(self, tmp_path, rng):
        p = str(tmp_path / "s.orc")
        write_orc([self.batch(rng, 500) for _ in range(4)], p,
                  stripe_rows=800)
        stripes = list(read_orc(p))
        assert [len(s) for s in stripes] == [1000, 1000]
        assert sum(len(s) for s in stripes) == 2000

    def test_layout_bytes(self, tmp_path, rng):
        """Hand-parse the physical layout: magic, trailing postscript
        length byte, postscript fields, footer row count."""
        p = str(tmp_path / "l.orc")
        write_orc([self.batch(rng, 64)], p, compression="zlib")
        raw = open(p, "rb").read()
        assert raw.startswith(MAGIC)
        ps_len = raw[-1]
        ps = _pb_decode(raw[-1 - ps_len:-1])
        assert ps[8000][0] == b"ORC"          # postscript magic field
        assert ps[2][0] == COMP_ZLIB
        flen = ps[1][0]
        footer = _pb_decode(_decompress_stream(
            raw[-1 - ps_len - flen:-1 - ps_len], COMP_ZLIB))
        assert footer[6][0] == 64             # numberOfRows
        assert len(footer[3]) == 1            # one stripe

    def test_empty_input_writes_valid_file(self, tmp_path):
        p = str(tmp_path / "e.orc")
        empty = RecordBatch({"x": np.empty(0, np.int64)})
        assert write_orc([empty], p) == 0
        assert list(read_orc(p)) == []

    def test_compression_chunks_round_trip(self, rng):
        data = bytes(rng.integers(0, 8, 700_000))  # compressible, multi-chunk
        z = _compress_stream(data, COMP_ZLIB)
        assert len(z) < len(data)
        assert _decompress_stream(z, COMP_ZLIB) == data


class TestReaderForeignEncodings:
    """Streams a modern writer would emit (DIRECT_V2 / DICTIONARY_V2):
    hand-assembled stripes prove the reader handles them."""

    def test_direct_v2_and_dictionary_v2(self, tmp_path):
        """Assemble a whole single-stripe file by hand with RLEv2-coded
        integers (DIRECT_V2) and a DICTIONARY_V2 string column — the
        encodings a modern writer emits and our writer does not."""
        import flink_tpu.formats.orc as orc

        primes = [2, 3, 5, 7, 11, 13, 17, 19, 23, 29]
        # signed RLEv2 delta: zigzag base 2, delta base +1, 4-bit deltas
        int_data = bytes([0xC6, 0x09]) + orc._uvarint(4) \
            + orc._svarint(1) + bytes([0x22, 0x42, 0x42, 0x46])
        words = ["ab", "ab", "zz", "cd", "ab", "zz", "cd", "cd", "ab", "zz"]
        dict_sorted = ["ab", "cd", "zz"]
        idx = [dict_sorted.index(w) for w in words]
        # indexes: RLEv2 DIRECT, width 2 bits, 10 values (unsigned)
        packed = bytearray()
        acc = bits = 0
        for v in idx:
            acc = (acc << 2) | v
            bits += 2
            while bits >= 8:
                packed.append((acc >> (bits - 8)) & 0xFF)
                bits -= 8
        if bits:
            packed.append((acc << (8 - bits)) & 0xFF)
        idx_data = bytes([0x40 | (1 << 1), 0x09]) + bytes(packed)
        dict_blob = "".join(dict_sorted).encode()
        # dict entry lengths [2,2,2]: RLEv2 short repeat, width 1, count 3
        len_data = bytes([0x00, 0x02])

        streams = [(orc.STREAM_DATA, 1, int_data),
                   (orc.STREAM_DATA, 2, idx_data),
                   (orc.STREAM_DICT_DATA, 2, dict_blob),
                   (orc.STREAM_LENGTH, 2, len_data)]
        sfoot = orc._Msg()
        body = b"".join(s[2] for s in streams)
        for skind, col, blob in streams:
            sfoot.msg(1, orc._Msg().varint(1, skind).varint(2, col)
                      .varint(3, len(blob)))
        sfoot.msg(2, orc._Msg().varint(1, orc.ENC_DIRECT))      # root
        sfoot.msg(2, orc._Msg().varint(1, orc.ENC_DIRECT_V2))   # ints
        sfoot.msg(2, orc._Msg().varint(1, orc.ENC_DICTIONARY_V2)
                  .varint(2, len(dict_sorted)))                 # strings
        sf = sfoot.encode()

        footer = orc._Msg()
        footer.varint(1, 3).varint(2, 3 + len(body) + len(sf))
        footer.msg(3, orc._Msg().varint(1, 3).varint(2, 0)
                   .varint(3, len(body)).varint(4, len(sf)).varint(5, 10))
        root = orc._Msg().varint(1, orc.K_STRUCT)
        root.varint(2, 1).varint(2, 2)
        root.string(3, "x").string(3, "w")
        footer.msg(4, root)
        footer.msg(4, orc._Msg().varint(1, orc.K_LONG))
        footer.msg(4, orc._Msg().varint(1, orc.K_STRING))
        footer.varint(6, 10).varint(8, 0)
        fb = footer.encode()
        ps = orc._Msg().varint(1, len(fb)).varint(2, orc.COMP_NONE) \
            .varint(3, orc._CHUNK).varint(4, 0).varint(4, 12) \
            .string(8000, "ORC").encode()
        p = str(tmp_path / "v2.orc")
        with open(p, "wb") as f:
            f.write(MAGIC + body + sf + fb + ps + bytes([len(ps)]))

        (got,) = read_orc(p)
        assert np.asarray(got.column("x")).tolist() == primes
        assert np.asarray(got.column("w")).tolist() == words
