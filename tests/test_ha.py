"""Coordinator high availability (ISSUE-20): leader lease, epoch fencing,
job recovery from the HA store.

Five layers under test:

1. **Lease + epoch** — ``FileHaStore`` acquisition at ``epoch + 1``,
   exclusivity while live, takeover after TTL expiry, renew's
   verify-back, and epoch monotonicity surviving a torn lease record
   (the separate ``epoch.json`` counter publishes first).
2. **Fencing** — the store-side zombie fence
   (``set_completed_checkpoint`` under a stale epoch), the worker-side
   control-plane fence (``_admit_epoch``), the data-plane HELLO fence
   (``ChannelServer.min_epoch``), the MiniCluster commit gate, and the
   two-phase-commit sink's ``fence_epoch``.
3. **Recovery** — ``resolve_restore``: the HA completed-checkpoint
   pointer is TRUTH over ``load_latest``; scan is a logged fallback
   only; chain-aware retention (``pin_provider``) never evicts the
   pointed cut — full snapshots AND increment chains.
4. **Chaos** — the ``ha.lease`` fault point: ``TruncatedWrite`` tears a
   renewal into a loud ``LeaseLostError`` demotion; ``KillCoordinator``
   deterministically fails the n-th renewal and composes with
   ``KillDuringRescale`` on independent points.
5. **Acceptance** — the scenario harness's ``run_ha_kill``: leader
   killed at the diurnal peak while running on as a zombie, standby
   takes over at ``epoch + 1``, the zombie's completions and 2PC
   commits are fenced, and the committed output is exactly-once and
   digest-identical to the unfaulted control.
"""

import os
import threading
import time

import numpy as np
import pytest

from flink_tpu.runtime.ha import (FileHaStore, Lease, LeaseLostError,
                                  LeaseRenewer, StaleEpochError, job_id_for,
                                  resolve_restore)
from flink_tpu.testing import chaos
from flink_tpu.testing.chaos import (FaultInjector, InjectedFault,
                                     KillCoordinator, KillDuringRescale,
                                     TruncatedWrite, installed)

# ---------------------------------------------------------------------------
# lease + epoch
# ---------------------------------------------------------------------------


def test_acquire_is_exclusive_and_epochs_are_monotone(tmp_path):
    store = FileHaStore(str(tmp_path))
    a = store.try_acquire("coord-a", ttl_s=30.0)
    assert a is not None and a.epoch == 1 and a.holder == "coord-a"
    # a live foreign lease blocks acquisition
    assert store.try_acquire("coord-b", ttl_s=30.0) is None
    # the incumbent may re-acquire (epoch still advances — a new grant)
    a2 = store.try_acquire("coord-a", ttl_s=30.0)
    assert a2 is not None and a2.epoch == 2
    assert store.current_epoch() == 2


def test_standby_takes_over_after_ttl_and_old_lease_is_fenced(tmp_path):
    t = [1000.0]
    store = FileHaStore(str(tmp_path), clock=lambda: t[0])
    a = store.try_acquire("coord-a", ttl_s=2.0)
    assert a.epoch == 1
    assert store.try_acquire("coord-b", ttl_s=2.0) is None
    t[0] += 5.0                                 # a's lease ages out
    b = store.try_acquire("coord-b", ttl_s=2.0)
    assert b is not None and b.epoch == 2
    # the deposed leader's renew demotes loudly, never extends
    with pytest.raises(LeaseLostError):
        store.renew(a, ttl_s=2.0)
    assert not store.is_current(a)
    assert store.is_current(b)


def test_renew_extends_and_verifies_back(tmp_path):
    t = [0.0]
    store = FileHaStore(str(tmp_path), clock=lambda: t[0])
    a = store.acquire("coord-a", ttl_s=1.0, timeout_s=1.0)
    t[0] = 0.5
    renewed = store.renew(a, ttl_s=1.0)
    assert renewed.deadline == 1.5
    assert store.read_lease().deadline == 1.5


def test_epoch_counter_survives_a_torn_lease_record(tmp_path):
    """A lease torn by a crash reads as ABSENT (CRC gate) — but the
    separately-published epoch counter still fences: two leaders can
    never be handed the same epoch."""
    store = FileHaStore(str(tmp_path))
    a = store.try_acquire("coord-a", ttl_s=30.0)
    assert a.epoch == 1
    with open(os.path.join(str(tmp_path), FileHaStore.LEASE_FILE), "wb") as f:
        f.write(b'{"record": {"epoch": 1, "holder')     # torn mid-write
    assert store.read_lease() is None                   # absent, not wrong
    assert store.current_epoch() == 1                   # counter intact
    b = store.try_acquire("coord-b", ttl_s=30.0)
    assert b.epoch == 2                                 # never 1 again


def test_release_only_drops_the_holders_own_lease(tmp_path):
    t = [0.0]
    store = FileHaStore(str(tmp_path), clock=lambda: t[0])
    a = store.try_acquire("coord-a", ttl_s=1.0)
    t[0] += 5.0
    b = store.try_acquire("coord-b", ttl_s=10.0)
    store.release(a)                        # stale release: b's lease stays
    assert store.read_lease().holder == "coord-b"
    store.release(b)
    assert store.read_lease() is None


def test_acquire_times_out_against_a_live_lease(tmp_path):
    store = FileHaStore(str(tmp_path))
    store.try_acquire("coord-a", ttl_s=60.0)
    with pytest.raises(TimeoutError):
        store.acquire("coord-b", ttl_s=1.0, timeout_s=0.2, poll_s=0.05)


# ---------------------------------------------------------------------------
# chaos: the ha.lease fault point
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_torn_renewal_demotes_loudly_and_successor_epoch_advances(tmp_path):
    store = FileHaStore(str(tmp_path))
    a = store.try_acquire("coord-a", ttl_s=0.2)
    inj = FaultInjector(seed=3)
    inj.inject("ha.lease", TruncatedWrite(at=1, frac=0.4))
    with installed(inj):
        with pytest.raises(LeaseLostError):
            store.renew(a, ttl_s=0.2)       # verify-back caught the tear
    time.sleep(0.25)                        # torn lease ages out (absent)
    b = store.try_acquire("coord-b", ttl_s=30.0)
    assert b is not None and b.epoch == a.epoch + 1


@pytest.mark.chaos
def test_kill_coordinator_fails_the_nth_renewal_deterministically():
    sched = KillCoordinator(at=2, times=2)
    acts = [sched.action(n, None) for n in range(1, 6)]
    assert acts[0] == chaos.OK
    assert acts[1][0] == chaos.FAIL and acts[2][0] == chaos.FAIL
    assert acts[3] == chaos.OK and acts[4] == chaos.OK
    with pytest.raises(ValueError):
        KillCoordinator(times=0)


@pytest.mark.chaos
def test_kill_coordinator_fires_at_the_lease_point(tmp_path):
    store = FileHaStore(str(tmp_path))
    a = store.try_acquire("coord-a", ttl_s=30.0)
    inj = FaultInjector(seed=0)
    inj.inject("ha.lease", KillCoordinator(at=1))
    with installed(inj):
        with pytest.raises(InjectedFault):
            store.renew(a, ttl_s=30.0)


@pytest.mark.chaos
def test_kill_coordinator_composes_with_kill_during_rescale():
    """Per-point counters are independent: arming both nemeses never
    cross-fires (the scenario harness composes them at the peak)."""
    inj = FaultInjector(seed=1)
    inj.inject("ha.lease", KillCoordinator(at=2))
    inj.inject("rescale.redistribute", KillDuringRescale(at=1))
    with installed(inj):
        assert chaos.fire("ha.lease")               # renewal 1 survives
        with pytest.raises(InjectedFault):
            chaos.fire("rescale.redistribute")      # rescale 1 dies
        with pytest.raises(InjectedFault):
            chaos.fire("ha.lease")                  # renewal 2 dies
        assert chaos.fire("rescale.redistribute")   # rescale 2 proceeds


def test_lease_renewer_demotes_once_via_on_lost(tmp_path):
    store = FileHaStore(str(tmp_path))
    a = store.try_acquire("coord-a", ttl_s=5.0)
    lost, demoted = [], threading.Event()

    def on_lost(exc):
        lost.append(exc)
        demoted.set()

    renewer = LeaseRenewer(store, a, ttl_s=5.0, interval_s=0.05,
                           on_lost=on_lost).start()
    # supersede the lease out from under the renewer
    os.remove(os.path.join(str(tmp_path), FileHaStore.LEASE_FILE))
    assert demoted.wait(5.0), "renewer never demoted"
    renewer.join()
    assert len(lost) == 1 and isinstance(renewer.lost, LeaseLostError)


# ---------------------------------------------------------------------------
# job registry + completed-checkpoint pointer (the store-side zombie fence)
# ---------------------------------------------------------------------------


def test_job_registry_roundtrip_and_stale_epoch_rejection(tmp_path):
    store = FileHaStore(str(tmp_path))
    a = store.try_acquire("coord-a", ttl_s=30.0)
    payload = {"plan": "fraud", "parallelism": 2,
               "weights": np.arange(4).tolist()}
    store.register_job("job-1", payload, a.epoch)
    assert store.load_job("job-1") == payload
    assert store.job_ids() == ["job-1"]
    b = store.try_acquire("coord-a", ttl_s=30.0)        # epoch 2
    store.register_job("job-1", {"plan": "v2"}, b.epoch)
    with pytest.raises(StaleEpochError):
        store.register_job("job-1", {"plan": "zombie"}, a.epoch)
    assert store.load_job("job-1") == {"plan": "v2"}
    with pytest.raises(KeyError):
        store.load_job("no-such-job")


def test_completed_checkpoint_pointer_is_monotone_and_epoch_fenced(tmp_path):
    store = FileHaStore(str(tmp_path))
    a = store.try_acquire("coord-a", ttl_s=30.0)        # epoch 1
    store.set_completed_checkpoint("j", 5, a.epoch)
    store.set_completed_checkpoint("j", 3, a.epoch)     # older cut: kept out
    assert store.completed_checkpoint("j") == {"checkpoint_id": 5,
                                               "epoch": 1}
    b = store.try_acquire("coord-a", ttl_s=30.0)        # epoch 2
    store.set_completed_checkpoint("j", 1_000_001, b.epoch)
    # THE zombie fence: the ex-leader's completion fails at the store,
    # before any notify-complete could fan out
    with pytest.raises(StaleEpochError):
        store.set_completed_checkpoint("j", 99, a.epoch)
    assert store.completed_checkpoint("j") == {"checkpoint_id": 1_000_001,
                                               "epoch": 2}
    with pytest.raises(StaleEpochError):
        store.check_epoch(a.epoch)
    store.check_epoch(b.epoch)                          # current: admitted


def test_job_id_for_sanitizes_module_refs():
    assert job_id_for("examples.fraud:main") == "examples_fraud_main"
    assert job_id_for("ok-name_2") == "ok-name_2"


# ---------------------------------------------------------------------------
# recovery: resolve_restore + chain-aware pinned retention
# ---------------------------------------------------------------------------


def _full_storage(tmp_path, cids):
    from flink_tpu.runtime.checkpoint.storage import FileCheckpointStorage
    storage = FileCheckpointStorage(str(tmp_path), retain=100)
    for cid in cids:
        storage.store(cid, {"op": {"cid": np.array([cid])}})
    return storage


def test_resolve_restore_pointer_beats_directory_scan(tmp_path):
    """The split-brain fix: the HA pointer is TRUTH even when a newer
    (possibly an unfenced zombie's) cut sits in the same directory."""
    store = FileHaStore(str(tmp_path / "ha"))
    a = store.try_acquire("coord-a", ttl_s=30.0)
    storage = _full_storage(tmp_path / "ckpt", [1, 2, 3])
    store.set_completed_checkpoint("j", 2, a.epoch)
    snap, source = resolve_restore(store, "j", storage)
    assert source == "ha-pointer"
    assert int(snap["op"]["cid"][0]) == 2               # not the newest (3)


def test_resolve_restore_falls_back_to_scan_and_logs(tmp_path):
    store = FileHaStore(str(tmp_path / "ha"))
    a = store.try_acquire("coord-a", ttl_s=30.0)
    storage = _full_storage(tmp_path / "ckpt", [1, 2])
    # no pointer at all -> scan
    snap, source = resolve_restore(store, "j", storage)
    assert source == "scan-fallback" and int(snap["op"]["cid"][0]) == 2
    # pointer to a missing cut -> logged scan fallback
    store.set_completed_checkpoint("j", 99, a.epoch)
    said = []
    snap, source = resolve_restore(store, "j", storage, log=said.append)
    assert source == "scan-fallback" and int(snap["op"]["cid"][0]) == 2
    assert any("99" in msg for msg in said)


def test_resolve_restore_none_when_nothing_exists(tmp_path):
    from flink_tpu.runtime.checkpoint.storage import FileCheckpointStorage
    store = FileHaStore(str(tmp_path / "ha"))
    storage = FileCheckpointStorage(str(tmp_path / "ckpt"))
    assert resolve_restore(store, "j", storage) == (None, "none")
    assert resolve_restore(None, "j", None) == (None, "none")


def test_retention_never_evicts_the_pinned_full_cut(tmp_path):
    from flink_tpu.runtime.checkpoint.storage import FileCheckpointStorage
    storage = FileCheckpointStorage(str(tmp_path), retain=2)
    storage.pin_provider = lambda: 1
    for cid in range(1, 6):
        storage.store(cid, {"op": {"cid": np.array([cid])}})
    ids = storage.checkpoint_ids()
    assert 1 in ids, "HA-pinned cut evicted by retention"
    assert ids[-2:] == [4, 5]
    assert int(storage.load(1)["op"]["cid"][0]) == 1


def _increment_chain(tmp_path, n_cuts, **storage_kw):
    """Real window-operator cuts driven into IncrementalCheckpointStorage:
    cid 1 is a full base, later cids append increments (compaction may
    re-base per ``max_increments_per_base``)."""
    import jax.numpy as jnp

    from flink_tpu.core.batch import RecordBatch
    from flink_tpu.core.functions import RuntimeContext, SumAggregator
    from flink_tpu.operators.base import snapshot_scope
    from flink_tpu.operators.window_agg import WindowAggOperator
    from flink_tpu.runtime.checkpoint.incremental import \
        IncrementalCheckpointStorage
    from flink_tpu.windowing import TumblingEventTimeWindows

    storage = IncrementalCheckpointStorage(str(tmp_path), **storage_kw)
    op = WindowAggOperator(TumblingEventTimeWindows.of(1000),
                           SumAggregator(jnp.float32),
                           key_column="k", value_column="v")
    op.open(RuntimeContext())
    op.incremental_state = True

    def feed(n):
        # a wide base (2000 keys) with narrow per-cut churn (50 keys) so
        # the delta tracker stays in increment mode instead of re-basing
        op.process_batch(RecordBatch(
            {"k": np.arange(n), "v": np.ones(n, np.float32)},
            timestamps=np.full(n, 100, np.int64)))

    feed(2000)
    for cid in range(1, n_cuts + 1):
        if cid > 1:
            feed(50)
        with snapshot_scope(cid, incremental=True):
            storage.store(cid, {"w": op.snapshot_state()})
        op.notify_checkpoint_complete(cid)
    return storage


def test_retention_keeps_the_pinned_cuts_whole_increment_chain(tmp_path):
    """A full cut at cid 6 starts a fresh base, so retain=1 owes nothing
    to the old chain — yet the HA-pinned increment (cid 2) AND the base
    it resolves through (cid 1) must survive eviction, keeping the
    pointer loadable.  Unpinned, the whole old chain drops."""
    storage = _increment_chain(tmp_path / "pinned", 5, retain=10,
                               max_increments_per_base=10)
    storage.retain = 1
    storage.pin_provider = lambda: 2        # an increment off base 1
    # a full (non-increment) cut: new base; eviction runs with the pin
    storage.store(6, {"w": {"note": np.array([6])}})
    ids = storage.checkpoint_ids()
    assert 2 in ids, "pinned increment evicted"
    assert 1 in ids, "pinned cut's chain base evicted"
    assert not {3, 4, 5} & set(ids), "unpinned chain tail not evicted"
    assert storage.load(2) is not None      # chain still resolves
    # control: without the pin the same shape drops the old chain
    bare = _increment_chain(tmp_path / "bare", 5, retain=10,
                            max_increments_per_base=10)
    bare.retain = 1
    bare.store(6, {"w": {"note": np.array([6])}})
    assert bare.checkpoint_ids() == [6]


# ---------------------------------------------------------------------------
# fencing: worker control plane, data plane, commit gate, 2PC sink
# ---------------------------------------------------------------------------


def _worker_shim():
    from flink_tpu.cluster.distributed import _WorkerRuntime

    class Shim:
        _admit_epoch = _WorkerRuntime._admit_epoch

    w = Shim()
    w.index = 3
    w._leader_epoch = 0
    w._fenced_msgs = 0
    w.sent = []
    w._send = w.sent.append
    return w


def test_worker_admits_higher_epochs_and_fences_lower_ones():
    w = _worker_shim()
    assert w._admit_epoch(0, "deploy")      # epoch 0 = HA off: admit all
    assert w._admit_epoch(2, "deploy")      # new leader: adopt
    assert w._leader_epoch == 2
    assert w._admit_epoch(2, "barrier")     # same leader: admit
    assert not w._admit_epoch(1, "barrier")  # zombie: reject + report
    assert w._fenced_msgs == 1
    assert w.sent == [("fenced", 3, "barrier", 1)]
    assert w._leader_epoch == 2


def test_worker_epoch_adoption_raises_the_data_plane_fence():
    w = _worker_shim()

    class FakeServer:
        min_epoch = 0

    w.server = FakeServer()
    assert w._admit_epoch(5, "deploy")
    assert w.server.min_epoch == 5          # HELLO fence follows control


def test_channel_server_rejects_stale_epoch_hellos():
    from flink_tpu.cluster.net import ChannelServer, RemoteChannel
    from flink_tpu.core.batch import RecordBatch

    server = ChannelServer()
    server.min_epoch = 3
    try:
        stale = RemoteChannel(server.host, server.port, "ha-ch", epoch=2)
        fresh = RemoteChannel(server.host, server.port, "ha-ch", epoch=3)
        batch = RecordBatch({"x": np.array([1])})
        # the zombie incarnation's writer never attaches: its put times out
        # against a closed connection instead of delivering
        assert not stale.put(batch, timeout_s=1.0)
        assert fresh.put(batch, timeout_s=5.0)
        got = server.channel("ha-ch").poll(timeout_s=5)
        assert got is not None
        assert server.channel("ha-ch").poll(timeout_s=0.2) is None
        stale.close()
        fresh.close()
    finally:
        server.stop()


def test_two_phase_sink_fences_stale_epoch_commits():
    from flink_tpu.connectors.sinks import TwoPhaseCommitSink

    class Rec(TwoPhaseCommitSink):
        def __init__(self):
            super().__init__(sink_id="rec")
            self.committed = []

        def begin_transaction(self, txn_name):
            return ("t", txn_name)

        def write_rows(self, handle, rows):
            pass

        def commit_transaction(self, handle):
            self.committed.append(handle)

        def abort_transaction(self, handle):
            pass

    sink = Rec()
    sink._staged = [(("t", "rec-s0-0"), 1)]
    sink.fence_epoch = 2                    # new leader restored this sink
    sink.notify_checkpoint_complete(1, epoch=1)     # zombie's notify round
    assert sink.committed == [] and sink.fenced_commits == 1
    assert sink._staged, "fenced notify must leave the stage for replay"
    sink.notify_checkpoint_complete(1, epoch=2)     # rightful leader
    assert sink.committed == [("t", "rec-s0-0")]
    # back-compat: an un-stamped notify (single-coordinator mode) commits
    sink._staged = [(("t", "rec-s0-1"), 2)]
    sink.notify_checkpoint_complete(2)
    assert len(sink.committed) == 2


@pytest.mark.slow
def test_minicluster_commit_gate_fences_every_completion():
    """A gate that always refuses (the store fenced this epoch): the job
    still finishes, but no checkpoint completes, nothing lands in
    storage, and no notify-complete ever fans out."""
    from flink_tpu.cluster.minicluster import MiniCluster
    from flink_tpu.cluster.task import TaskStates
    from flink_tpu.datastream.api import StreamExecutionEnvironment
    from flink_tpu.runtime.checkpoint.storage import InMemoryCheckpointStorage

    n = 40_000
    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    sink = (env.from_collection(columns={"k": np.arange(n) % 11,
                                         "v": np.ones(n)}, batch_size=256)
            .key_by("k").sum("v").collect())
    plan = env.get_stream_graph("ha-gate").to_plan()
    storage = InMemoryCheckpointStorage(retain=10)
    cluster = MiniCluster(checkpoint_storage=storage,
                          checkpoint_interval_ms=10,
                          tolerable_failed_checkpoints=1_000_000)
    cluster.ha_commit_gate = lambda cid: False
    res = cluster.execute(plan, timeout_s=120.0)
    assert res.state == TaskStates.FINISHED
    assert cluster.ha_fenced_completions > 0
    assert res.completed_checkpoints == []
    assert storage.load_latest() is None


@pytest.mark.slow
def test_minicluster_commit_gate_admits_and_records_epoch_pointer(tmp_path):
    """The harness wiring end-to-end in miniature: the gate advances the
    HA pointer under the acting epoch, so completed cuts and the pointer
    stay in lockstep."""
    from flink_tpu.cluster.minicluster import MiniCluster
    from flink_tpu.cluster.task import TaskStates
    from flink_tpu.datastream.api import StreamExecutionEnvironment
    from flink_tpu.runtime.checkpoint.storage import InMemoryCheckpointStorage

    store = FileHaStore(str(tmp_path))
    lease = store.try_acquire("coord-a", ttl_s=30.0)
    n = 40_000
    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    (env.from_collection(columns={"k": np.arange(n) % 11,
                                  "v": np.ones(n)}, batch_size=256)
     .key_by("k").sum("v").collect())
    plan = env.get_stream_graph("ha-gate2").to_plan()
    cluster = MiniCluster(checkpoint_storage=InMemoryCheckpointStorage(),
                          checkpoint_interval_ms=10)

    def gate(cid):
        try:
            store.set_completed_checkpoint("j", cid, lease.epoch)
            return True
        except StaleEpochError:
            return False

    cluster.ha_commit_gate = gate
    res = cluster.execute(plan, timeout_s=120.0)
    assert res.state == TaskStates.FINISHED
    assert res.completed_checkpoints, "no checkpoint completed"
    pointer = store.completed_checkpoint("j")
    assert pointer is not None and pointer["epoch"] == lease.epoch
    assert cluster.ha_fenced_completions == 0


# ---------------------------------------------------------------------------
# acceptance: coordinator killed at the peak, zombie fenced, exactly-once
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_coordinator_kill_at_peak_recovers_exactly_once():
    """The full ISSUE-20 story on the fraud scenario: leader A is killed
    at its lease renewal during the diurnal peak and runs on as a
    zombie; standby B takes over at epoch + 1, the zombie's checkpoint
    completions AND a 2PC commit under the stale epoch are provably
    fenced, B restores from the HA pointer (increment chains included)
    and finishes — zero lost, zero duplicated, digest-identical to the
    unfaulted control."""
    from flink_tpu.scenarios import ScenarioHarness, get_scenario

    harness = ScenarioHarness(get_scenario("fraud_detection"), smoke=True)
    res = harness.run_ha_kill()
    assert res["state"] == "FINISHED", res
    assert res["control_state"] == "Finished", res["control_error"]
    assert res["leader_epochs"] == sorted(res["leader_epochs"])
    assert len(res["leader_epochs"]) == 2
    assert res["leader_epochs"][1] == res["leader_epochs"][0] + 1
    assert res["stale_pointer_rejected"], res
    assert res["stale_commit_fenced"], res
    assert res["fenced_completions"] > 0, res
    assert res["restore_source"] == "ha-pointer", res
    assert res["records_lost"] == 0, res
    assert res["records_duplicated"] == 0, res
    assert res["digest_match"], res
    assert sum(res["committed_rows"].values()) > 0
    assert res["ok"], res
