import numpy as np
import pytest

from flink_tpu.core.keygroups import (KeyGroupRange, assign_to_key_group,
                                      assign_key_to_parallel_operator,
                                      compute_key_group_range,
                                      compute_operator_index_for_key_group,
                                      java_int_hash, key_group_ranges,
                                      murmur_hash)


def _java_murmur(code: int) -> int:
    """Scalar reference implementation transcribed from MathUtils.java:137."""
    def i32(x):
        x &= 0xFFFFFFFF
        return x - (1 << 32) if x >= (1 << 31) else x

    def rotl(x, r):
        x &= 0xFFFFFFFF
        return ((x << r) | (x >> (32 - r))) & 0xFFFFFFFF

    code = i32(code * 0xCC9E2D51)
    code = i32(rotl(code, 15))
    code = i32(code * 0x1B873593)
    code = i32(rotl(code, 13))
    code = i32(code * 5 + 0xE6546B64)
    code = i32(code ^ 4)
    u = code & 0xFFFFFFFF
    u ^= u >> 16
    u = (u * 0x85EBCA6B) & 0xFFFFFFFF
    u ^= u >> 13
    u = (u * 0xC2B2AE35) & 0xFFFFFFFF
    u ^= u >> 16
    code = i32(u)
    if code >= 0:
        return code
    if code != -(1 << 31):
        return -code
    return 0


@pytest.mark.parametrize("val", [0, 1, -1, 42, 123456789, -987654321,
                                 2**31 - 1, -(2**31), 7, 1000000])
def test_murmur_matches_reference_scalar(val):
    assert int(murmur_hash(val)) == _java_murmur(val)


def test_murmur_vectorized_batch():
    vals = np.arange(-5000, 5000, dtype=np.int32)
    got = murmur_hash(vals)
    assert got.dtype == np.int32
    for v in (-5000, -1, 0, 1, 4999):
        assert got[v + 5000] == _java_murmur(v)
    assert (got >= 0).all()


def test_assign_to_key_group_range():
    keys = np.arange(100000, dtype=np.int32)
    kg = assign_to_key_group(keys, 128)
    assert kg.min() >= 0 and kg.max() < 128
    # roughly uniform
    counts = np.bincount(kg, minlength=128)
    assert counts.min() > 400


def test_key_group_ranges_partition_exactly():
    max_p, par = 128, 6
    ranges = key_group_ranges(max_p, par)
    covered = sorted(g for r in ranges for g in r)
    assert covered == list(range(max_p))
    for i, r in enumerate(ranges):
        for g in r:
            assert compute_operator_index_for_key_group(max_p, par, g) == i


def test_assign_key_to_parallel_operator_consistent():
    keys = np.arange(10000, dtype=np.int64)
    hashes = java_int_hash(keys)
    ops = assign_key_to_parallel_operator(hashes, 128, 4)
    kg = assign_to_key_group(hashes, 128)
    ranges = key_group_ranges(128, 4)
    for i, r in enumerate(ranges):
        mask = ops == i
        assert set(np.unique(kg[mask])).issubset(set(range(r.start, r.end + 1)))


def test_key_group_range_intersection():
    a = KeyGroupRange(0, 63)
    b = KeyGroupRange(32, 100)
    assert a.intersection(b) == KeyGroupRange(32, 63)
    assert KeyGroupRange(0, 10).intersection(KeyGroupRange(20, 30)).num_key_groups == 0


def test_java_long_hash():
    v = np.array([0, 1, -1, 2**40], np.int64)
    h = java_int_hash(v)
    # Long.hashCode(x) = (int)(x ^ (x >>> 32))
    assert h[0] == 0 and h[1] == 1
    assert h[2] == 0  # -1 ^ (0xFFFFFFFF) = 0 ... (-1 >>> 32 == 0xFFFFFFFF)
    assert h[3] == int(np.int32((2**40 ^ (2**40 >> 32)) & 0xFFFFFFFF))
