"""MATCH_RECOGNIZE → CEP NFA lowering (StreamExecMatch.java:90 analog).

The canonical V-shape (falling-then-rising price) query and its variants:
PREV navigation, greedy quantifiers, AFTER MATCH SKIP strategies, MEASURES
(FIRST/LAST/aggregates), partitioning, and equivalence with the direct
DataStream CEP path.
"""

import numpy as np
import pytest

from flink_tpu.sql.parser import SqlParseError, parse
from flink_tpu.sql.planner import PlanError
from flink_tpu.sql.table_env import TableEnvironment


def ticker_env(**kw):
    tenv = TableEnvironment()
    # symbol A: 12 10 9 11 13 8 7 10  (two V shapes)
    # symbol B: 5 6 4 8               (one V shape: 6->4 down, 8 up)
    rows = [("A", 0, 12.0), ("B", 0, 5.0), ("A", 1, 10.0), ("B", 1, 6.0),
            ("A", 2, 9.0), ("B", 2, 4.0), ("A", 3, 11.0), ("B", 3, 8.0),
            ("A", 4, 13.0), ("A", 5, 8.0), ("A", 6, 7.0), ("A", 7, 10.0)]
    tenv.register_collection(
        "ticker",
        columns={"symbol": np.asarray([r[0] for r in rows], object),
                 "ts": np.asarray([r[1] for r in rows], np.int64),
                 "price": np.asarray([r[2] for r in rows])},
        batch_size=3, **kw)
    return tenv


V_QUERY = """
SELECT * FROM ticker MATCH_RECOGNIZE (
  PARTITION BY symbol
  ORDER BY ts
  MEASURES
    FIRST(DOWN.price) AS start_price,
    MIN(DOWN.price) AS bottom_price,
    LAST(UP.price) AS end_price,
    COUNT(DOWN.price) AS down_ticks
  ONE ROW PER MATCH
  AFTER MATCH SKIP PAST LAST ROW
  PATTERN (DOWN+ UP)
  DEFINE
    DOWN AS price < PREV(price),
    UP AS price > PREV(price)
) AS T
"""


def test_parse_shape():
    stmt = parse(V_QUERY)
    mr = stmt.match
    assert mr is not None
    assert mr.partition_by == ["symbol"]
    assert mr.order_by == "ts"
    assert [s.var for s in mr.pattern] == ["DOWN", "UP"]
    assert mr.pattern[0].quant_max is None       # DOWN+
    assert mr.after_match == "skip_past_last"
    assert set(mr.defines) == {"DOWN", "UP"}
    assert mr.alias == "T"


def test_v_shape_canonical():
    rows = ticker_env().execute_sql(V_QUERY).collect()
    got = sorted((r["symbol"], r["start_price"], r["bottom_price"],
                  r["end_price"], r["down_ticks"]) for r in rows)
    assert got == [
        ("A", 10.0, 9.0, 11.0, 2),   # 12 >10 >9 then 11
        ("A", 8.0, 7.0, 10.0, 2),    # 13 >8 >7 then 10
        ("B", 4.0, 4.0, 8.0, 1),     # 6 >4 then 8
    ] or got == sorted([
        ("A", 10.0, 9.0, 11.0, 2),
        ("A", 8.0, 7.0, 10.0, 2),
        ("B", 4.0, 4.0, 8.0, 1)])


def test_skip_to_next_row_overlapping():
    """SKIP TO NEXT ROW: a match may start at EVERY row, so the nested V
    (starting one tick later) also emits."""
    q = V_QUERY.replace("SKIP PAST LAST ROW", "SKIP TO NEXT ROW")
    rows = ticker_env().execute_sql(q).collect()
    a_starts = sorted(r["start_price"] for r in rows if r["symbol"] == "A")
    # matches starting at 10 (full V) AND at 9 (inner V), etc.
    assert 9.0 in a_starts and 10.0 in a_starts
    assert len(rows) > 3


def test_quantifier_bounds():
    q = """
    SELECT * FROM ticker MATCH_RECOGNIZE (
      PARTITION BY symbol
      ORDER BY ts
      MEASURES COUNT(DOWN.price) AS n
      AFTER MATCH SKIP PAST LAST ROW
      PATTERN (DOWN{2} UP)
      DEFINE DOWN AS price < PREV(price), UP AS price > PREV(price)
    )
    """
    rows = ticker_env().execute_sql(q).collect()
    # B has only a single down tick: no match; A's two Vs have exactly 2
    assert sorted(r["symbol"] for r in rows) == ["A", "A"]
    assert all(r["n"] == 2 for r in rows)


def test_optional_and_star():
    q = """
    SELECT * FROM ticker MATCH_RECOGNIZE (
      PARTITION BY symbol
      ORDER BY ts
      MEASURES LAST(UP.price) AS end_price, COUNT(DOWN.price) AS downs
      AFTER MATCH SKIP PAST LAST ROW
      PATTERN (DOWN* UP)
      DEFINE DOWN AS price < PREV(price), UP AS price > PREV(price)
    )
    """
    rows = ticker_env().execute_sql(q).collect()
    # DOWN* allows zero downs: a bare up-tick matches too
    assert any(r["downs"] == 0 for r in rows)
    assert any(r["downs"] >= 1 for r in rows)


def test_unpartitioned_and_no_prev():
    tenv = TableEnvironment()
    tenv.register_collection(
        "events",
        columns={"ts": np.asarray([0, 1, 2, 3, 4], np.int64),
                 "kind": np.asarray(["a", "b", "c", "a", "b"], object)})
    q = """
    SELECT * FROM events MATCH_RECOGNIZE (
      ORDER BY ts
      MEASURES FIRST(A.ts) AS a_ts, LAST(B.ts) AS b_ts
      AFTER MATCH SKIP PAST LAST ROW
      PATTERN (A B)
      DEFINE A AS kind = 'a', B AS kind = 'b'
    )
    """
    rows = tenv.execute_sql(q).collect()
    assert sorted((r["a_ts"], r["b_ts"]) for r in rows) == [(0, 1), (3, 4)]


def test_strict_contiguity_kills_gaps():
    """Unlike CEP followedBy, MATCH_RECOGNIZE rows must be contiguous:
    a non-matching row between A and B kills the attempt."""
    tenv = TableEnvironment()
    tenv.register_collection(
        "events",
        columns={"ts": np.asarray([0, 1, 2], np.int64),
                 "kind": np.asarray(["a", "x", "b"], object)})
    q = """
    SELECT * FROM events MATCH_RECOGNIZE (
      ORDER BY ts
      MEASURES FIRST(A.ts) AS a_ts
      PATTERN (A B)
      DEFINE A AS kind = 'a', B AS kind = 'b'
    )
    """
    assert tenv.execute_sql(q).collect() == []


def test_measure_arithmetic_and_sum():
    q = """
    SELECT * FROM ticker MATCH_RECOGNIZE (
      PARTITION BY symbol
      ORDER BY ts
      MEASURES
        LAST(UP.price) - MIN(DOWN.price) AS rebound,
        SUM(DOWN.price) AS down_total
      AFTER MATCH SKIP PAST LAST ROW
      PATTERN (DOWN+ UP)
      DEFINE DOWN AS price < PREV(price), UP AS price > PREV(price)
    )
    """
    rows = ticker_env().execute_sql(q).collect()
    b = [r for r in rows if r["symbol"] == "B"][0]
    assert b["rebound"] == 4.0 and b["down_total"] == 4.0


def test_matches_direct_cep_path():
    """The SQL lowering and a hand-built CEP pattern find the same episodes
    (same count and same partition keys) for an A-then-B pattern."""
    from flink_tpu.cep import CEP, Pattern
    from flink_tpu.datastream.api import StreamExecutionEnvironment

    cols = {"k": np.asarray(["x", "x", "y", "x", "y"], object),
            "ts": np.asarray([0, 1, 2, 3, 4], np.int64),
            "kind": np.asarray(["a", "b", "a", "a", "b"], object)}
    # SQL path
    tenv = TableEnvironment()
    tenv.register_collection("ev", columns=cols)
    q = """
    SELECT * FROM ev MATCH_RECOGNIZE (
      PARTITION BY k
      ORDER BY ts
      MEASURES FIRST(A.ts) AS a_ts
      AFTER MATCH SKIP PAST LAST ROW
      PATTERN (A B)
      DEFINE A AS kind = 'a', B AS kind = 'b'
    )
    """
    sql_rows = tenv.execute_sql(q).collect()
    # direct CEP path (relaxed contiguity is equivalent here: no gaps)
    env = StreamExecutionEnvironment(parallelism=1)
    pat = (Pattern.begin("A")
           .where(lambda c: np.asarray(c["kind"]) == "a")
           .next("B")
           .where(lambda c: np.asarray(c["kind"]) == "b"))
    stream = (env.from_collection(columns=cols, timestamp_column="ts")
              .assign_timestamps_and_watermarks(0, timestamp_column="ts")
              .key_by("k"))
    res = CEP.pattern(stream, pat).select(
        lambda m: {"k": m["A"][0]["k"], "a_ts": m["A"][0]["ts"]})
    cep_rows = res.execute_and_collect()
    assert sorted((r["k"], r["a_ts"]) for r in sql_rows) == \
        sorted((r["k"], r["a_ts"]) for r in cep_rows)


def test_snapshot_restore_mid_pattern():
    """Operator-level: snapshot between the DOWN run and the UP tick; the
    restored operator completes the match (PREV continuity included)."""
    from flink_tpu.cep.operator import CepOperator
    from flink_tpu.cep.pattern import Pattern, Stage
    from flink_tpu.core.batch import RecordBatch, Watermark

    def mk():
        stages = [
            Stage("DOWN", condition=lambda c: np.asarray(
                c["price"]) < np.asarray(c["__prev_price"]),
                contiguity="strict", times_min=1, times_max=None,
                greedy=True),
            Stage("UP", condition=lambda c: np.asarray(
                c["price"]) > np.asarray(c["__prev_price"]),
                contiguity="strict"),
        ]
        pat = Pattern(stages)
        return CepOperator(
            pat, "symbol",
            lambda m: {"symbol": m["DOWN"][0]["symbol"],
                       "bottom": min(r["price"] for r in m["DOWN"])},
            prev_columns=["price"], leftmost_order_column="ts")

    def batch(ts, price):
        return RecordBatch(
            {"symbol": np.asarray(["A"], object),
             "ts": np.asarray([ts], np.int64),
             "price": np.asarray([price])},
            timestamps=np.asarray([ts], np.int64))

    op = mk()
    out = []
    out += op.process_batch(batch(0, 12.0))
    out += op.process_batch(batch(1, 10.0))
    out += op.process_watermark(Watermark(1))     # drain the down ticks
    snap = op.snapshot_state()

    op2 = mk()
    op2.restore_state(snap)
    out += op2.process_batch(batch(2, 9.0))
    out += op2.process_batch(batch(3, 11.0))
    out += op2.process_watermark(Watermark(3))
    rows = [dict(zip(b.columns, vals))
            for b in out
            for vals in zip(*[np.asarray(b.column(c)) for c in b.columns])]
    assert any(r["bottom"] == 9.0 for r in rows)


def test_zero_min_quantifier_is_optional():
    """PATTERN (A B{0,2} C): B may match ZERO rows — {0,n} must not be
    silently clamped to at-least-once."""
    q = """
    SELECT * FROM ev MATCH_RECOGNIZE (
      ORDER BY ts
      MEASURES FIRST(A.ts) AS a_ts, LAST(C.ts) AS c_ts, COUNT(B.ts) AS nb
      AFTER MATCH SKIP PAST LAST ROW
      PATTERN (A B{0,2} C)
      DEFINE A AS v = 1, B AS v > 3, C AS v = 1
    )
    """

    def run(vals):
        tenv = TableEnvironment()
        tenv.register_collection(
            "ev", columns={"ts": np.arange(len(vals), dtype=np.int64),
                           "v": np.asarray(vals, np.int64)})
        return sorted((r["a_ts"], r["c_ts"], r["nb"])
                      for r in tenv.execute_sql(q).collect())

    assert run([1, 1]) == [(0, 1, 0)]        # zero-B match
    assert run([1, 5, 1]) == [(0, 2, 1)]     # one-B match
    assert run([1, 5, 5, 1]) == [(0, 3, 2)]  # two-B match (greedy)


def test_match_recognize_over_changelog_rejected():
    tenv = TableEnvironment()
    tenv.register_collection("l", columns={"k": np.asarray([1, 2]),
                                           "ts": np.asarray([0, 1])},
                             bounded=False)
    tenv.register_collection("r", columns={"k2": np.asarray([1, 3])},
                             bounded=False)
    tenv.create_temporary_view(
        "joined", tenv.sql_query("SELECT l.k, l.ts FROM l "
                                 "JOIN r ON l.k = r.k2"))
    with pytest.raises(PlanError, match="changelog"):
        tenv.execute_sql("""
        SELECT * FROM joined MATCH_RECOGNIZE (
          ORDER BY ts MEASURES FIRST(A.k) AS k
          PATTERN (A) DEFINE A AS k > 0 )
        """).collect()


def test_errors():
    tenv = ticker_env()
    with pytest.raises(SqlParseError):
        tenv.execute_sql("SELECT * FROM ticker MATCH_RECOGNIZE ( "
                         "MEASURES 1 AS x PATTERN (A) DEFINE A AS TRUE )")
    with pytest.raises(PlanError, match="PREV with offset"):
        tenv.execute_sql("""
        SELECT * FROM ticker MATCH_RECOGNIZE (
          PARTITION BY symbol ORDER BY ts
          MEASURES LAST(A.price) AS p
          PATTERN (A) DEFINE A AS price < PREV(price, 2) )
        """)
    with pytest.raises(PlanError, match="unknown pattern variable"):
        tenv.execute_sql("""
        SELECT * FROM ticker MATCH_RECOGNIZE (
          PARTITION BY symbol ORDER BY ts
          MEASURES LAST(Z.price) AS p
          PATTERN (A) DEFINE A AS price > 0 )
        """)
