"""One-dispatch fused megastep (ISSUE 11 tentpole contract).

``WindowAggOperator(superbatch=N)`` stages up to N micro-batches and
advances them in ONE pass — a device-side ``lax.scan`` over donated state
buffers when the device-resident probe is active, a single concatenated
fused C probe+fold on the host tier otherwise.  Staging is a pure
scheduling change: fire digests, snapshot bytes, and counters must be
BIT-identical fused on vs off — on the host tier under both sync
cadences, with the numpy-mirror fallback, at mesh 1 vs 2, and through a
mid-scan WedgedDevice quarantine (the scan is one transactional
``guarded_dispatch``).  Geometry must be sticky: exactly one XLA compile
of the scan megastep per (table capacity, K_cap, P, depth, step width,
value spec).  Paging keeps the lane structurally off, like the probe.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from flink_tpu.core.batch import RecordBatch, Watermark
from flink_tpu.core.functions import RuntimeContext, SumAggregator
from flink_tpu.operators import fused_step
from flink_tpu.operators.window_agg import WindowAggOperator
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


def _mk_op(superbatch=0, device_probe="off", emit_tier="host",
           device_sync="deferred", native=True, paging=None,
           pipeline_depth=0, **kw):
    if paging is not None:
        emit_tier = "device"
    op = WindowAggOperator(
        TumblingEventTimeWindows.of(100), SumAggregator(jnp.float32),
        key_column="k", value_column="v", emit_tier=emit_tier,
        snapshot_source="mirror" if emit_tier == "host" else "device",
        device_sync=device_sync if emit_tier == "host" else "scatter",
        native_emit=native, paging=paging, device_probe=device_probe,
        superbatch=superbatch, pipeline_depth=pipeline_depth, **kw)
    op.open(RuntimeContext())
    return op


def _digests(out):
    return [(int(np.asarray(b.column("window_start"))[0]), len(b),
             np.asarray(b.column("k")).tobytes(),
             np.asarray(b.column("result")).tobytes())
            for b in out if hasattr(b, "columns") and "result" in b.columns]


def _counters(op):
    return {
        "late_dropped": op.late_dropped,
        "num_keys": op.key_index.num_keys if op.key_index else 0,
        "watermark": op.watermark,
        "last_fired_window": op.last_fired_window,
    }


def _snap_bytes(snap):
    return (snap["counts"].tobytes(),
            tuple(np.asarray(l).tobytes() for l in snap["leaves"]))


def _seeded_run(op, n_batches=12, nk=1500, b=4000, seed=11, snap_at=6,
                close=True):
    rng = np.random.default_rng(seed)
    out, snap = [], None
    for i in range(n_batches):
        keys = rng.integers(0, nk, b).astype(np.int64)
        vals = rng.random(b).astype(np.float32)
        ts = i * 50 + np.sort(rng.integers(0, 50, b)).astype(np.int64)
        out += op.process_batch(RecordBatch({"k": keys, "v": vals},
                                            timestamps=ts))
        out += op.process_watermark(Watermark(int(ts.max()) - 1))
        if i == snap_at:
            op.prepare_snapshot_pre_barrier()
            snap = op.snapshot_state()
    out += op.end_input()
    counters = _counters(op)
    if close:
        op.close()
    return _digests(out), snap, counters


# ---------------------------------------------------------------------------
# bit-identity: fused on/off across tiers and lanes
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("sync", ["deferred", "scatter"])
def test_host_tier_bit_identical_fused_on_off(sync):
    ref = _seeded_run(_mk_op(1, device_sync=sync))
    got = _seeded_run(_mk_op(4, device_sync=sync))
    assert got[0] == ref[0], "fire digests diverged"
    assert _snap_bytes(got[1]) == _snap_bytes(ref[1]), "snapshot diverged"
    assert got[2] == ref[2], "counters diverged"


@pytest.mark.parametrize("sync", ["deferred", "scatter"])
def test_scan_lane_bit_identical(sync):
    """The forced scan lane (device probe ON + superbatch) must match the
    fully-unfused path — and must actually have scanned."""
    ref = _seeded_run(_mk_op(1, device_probe="off", device_sync=sync))
    op = _mk_op(4, device_probe="on", device_sync=sync)
    got_d, got_s, got_c = _seeded_run(op, close=False)
    fu = op.fused_stats()
    op.close()
    assert got_d == ref[0] and got_c == ref[2]
    assert _snap_bytes(got_s) == _snap_bytes(ref[1])
    assert fu["scan_dispatches"] > 0, "scan lane never dispatched"
    assert fu["scan_steps"] > fu["scan_dispatches"], \
        "scan dispatches did not amortize multiple staged steps"


def test_numpy_mirror_fallback_bit_identical():
    ref = _seeded_run(_mk_op(1, native=False))
    got = _seeded_run(_mk_op(4, native=False))
    assert got[0] == ref[0] and got[2] == ref[2]
    assert _snap_bytes(got[1]) == _snap_bytes(ref[1])


def test_pipelined_fused_bit_identical():
    ref = _seeded_run(_mk_op(1))
    got = _seeded_run(_mk_op(4, pipeline_depth=1))
    assert got[0] == ref[0] and got[2] == ref[2]
    assert _snap_bytes(got[1]) == _snap_bytes(ref[1])


def test_mesh_1v2_bit_identical_fused_on_off():
    from flink_tpu.parallel.mesh import make_mesh
    from flink_tpu.parallel.mesh_runtime import MeshWindowAggOperator

    def mk(superbatch, D):
        op = MeshWindowAggOperator(
            TumblingEventTimeWindows.of(100), SumAggregator(jnp.float32),
            key_column="k", value_column="v", emit_tier="host",
            snapshot_source="mirror", device_sync="deferred",
            superbatch=superbatch, mesh=make_mesh(D),
            initial_key_capacity=2048)
        op.open(RuntimeContext(max_parallelism=128))
        return op

    ref = _seeded_run(mk(1, 1), n_batches=6)
    for D in (1, 2):
        got = _seeded_run(mk(4, D), n_batches=6)
        assert got[0] == ref[0], f"mesh x{D} fire digests diverged"
        assert got[2] == ref[2]


def test_paging_keeps_fused_lane_structurally_off():
    """Paging pins the device emit tier, and the fused lane stages the
    HOST tier only — a superbatch request on a paged operator degrades
    gracefully to off (like the device probe), digests unchanged."""
    from flink_tpu.state.paging import PagingConfig

    def run(superbatch):
        op = _mk_op(superbatch, paging=PagingConfig(capacity=1024))
        res = _seeded_run(op, nk=2000, close=False)
        fu = op.fused_stats()
        op.close()
        return res, fu

    (ref, fu1), (got, fu4) = run(1), run(4)
    assert got[0] == ref[0]
    assert fu4["enabled"] == 0 and fu4["staged_batches"] == 0
    assert fu1["enabled"] == 0


# ---------------------------------------------------------------------------
# staging semantics: fire boundaries flush, plain watermarks stage
# ---------------------------------------------------------------------------

def test_watermark_fast_path_keeps_batches_staged():
    """A watermark that passes no window end must leave the stage parked
    (the amortization source); the one that crosses a fire boundary must
    flush and fire — and a snapshot must flush too."""
    op = _mk_op(8)
    rng = np.random.default_rng(5)
    out = []
    # first window fires so last_fired_window is set (fast-path arming)
    k = rng.integers(0, 64, 512).astype(np.int64)
    v = np.ones(512, np.float32)
    out += op.process_batch(RecordBatch(
        {"k": k, "v": v}, timestamps=np.full(512, 50, np.int64)))
    out += op.process_watermark(Watermark(99))
    assert _digests(out), "first window did not fire"
    staged_seen = 0
    for i in range(3):   # all inside window [100, 200): no boundary
        ts = 100 + i * 20 + np.sort(
            rng.integers(0, 20, 512)).astype(np.int64)
        op.process_batch(RecordBatch({"k": k, "v": v}, timestamps=ts))
        got = op.process_watermark(Watermark(int(ts.max()) - 1))
        assert got == []
        staged_seen = max(staged_seen, op.fused_stats()["staged_pending"])
    assert staged_seen >= 2, "watermarks flushed the stage prematurely"
    fired = op.process_watermark(Watermark(199))   # boundary: flush + fire
    assert _digests(fired), "boundary watermark did not fire"
    assert op.fused_stats()["staged_pending"] == 0
    # snapshot flushes staged rows: state must contain them
    op.process_batch(RecordBatch(
        {"k": k, "v": v}, timestamps=np.full(512, 250, np.int64)))
    assert op.fused_stats()["staged_pending"] == 1
    op.prepare_snapshot_pre_barrier()
    snap = op.snapshot_state()
    assert op.fused_stats()["staged_pending"] == 0
    assert snap["counts"].sum() >= 512, "snapshot missed staged rows"
    op.close()


def test_restore_fused_into_unfused_and_back():
    """A snapshot written mid-stream by either lane restores into the
    other, and the replayed tail produces identical digests."""
    rng = np.random.default_rng(13)
    batches = []
    for i in range(12):
        keys = rng.integers(0, 900, 3000).astype(np.int64)
        vals = rng.random(3000).astype(np.float32)
        ts = i * 50 + np.sort(rng.integers(0, 50, 3000)).astype(np.int64)
        batches.append((keys, vals, ts))

    def drain(op, subset):
        out = []
        for keys, vals, ts in subset:
            out += op.process_batch(RecordBatch({"k": keys, "v": vals},
                                                timestamps=ts))
            out += op.process_watermark(Watermark(int(ts.max()) - 1))
        out += op.end_input()
        return _digests(out)

    def snapshot_from(src_sb):
        src = _mk_op(src_sb)
        for keys, vals, ts in batches[:6]:
            src.process_batch(RecordBatch({"k": keys, "v": vals},
                                          timestamps=ts))
            src.process_watermark(Watermark(int(ts.max()) - 1))
        src.prepare_snapshot_pre_barrier()
        snap = src.snapshot_state()
        src.close()
        return snap

    snaps = {sb: snapshot_from(sb) for sb in (1, 4)}
    # the fused writer's snapshot is byte-identical to the unfused one
    assert _snap_bytes(snaps[4]) == _snap_bytes(snaps[1])
    ref = None
    for src_sb, dst_sb in ((1, 1), (4, 1), (1, 4), (4, 4)):
        dst = _mk_op(dst_sb)
        dst.restore_state(snaps[src_sb])
        got = drain(dst, batches[6:])
        dst.close()
        if ref is None:
            ref = got
        assert got == ref, f"restore {src_sb}->{dst_sb} diverged"


# ---------------------------------------------------------------------------
# compile discipline: sticky [N, B] geometry
# ---------------------------------------------------------------------------

def test_scan_compiles_once_per_sticky_geometry(rng):
    op = _mk_op(4, device_probe="on", initial_key_capacity=4096)
    nk = 1000
    keys0 = rng.integers(0, nk, 2048).astype(np.int64)
    op.process_batch(RecordBatch(
        {"k": keys0, "v": np.ones(2048, np.float32)},
        timestamps=np.zeros(2048, np.int64)))
    op.flush_pipeline()   # table capacity settles before the smoke
    base = op.fused_step_cache_size()["_fused_scan_delta_step"]
    if base < 0:
        pytest.skip("jax build without the jit cache-size probe")
    # wobbling batch sizes UNDER the sticky high-waters must not recompile
    for i in range(1, 9):
        b = 2048 - 64 * i
        keys = rng.integers(0, nk, b).astype(np.int64)
        ts = np.full(b, i * 10, np.int64)
        op.process_batch(RecordBatch(
            {"k": keys, "v": np.ones(b, np.float32)}, timestamps=ts))
    op.flush_pipeline()
    got = op.fused_step_cache_size()["_fused_scan_delta_step"]
    assert got <= base + 1, \
        f"scan step recompiled per batch: {base} -> {got}"
    op.close()


# ---------------------------------------------------------------------------
# quarantine: a wedged scan is transactional; donated buffers stay safe
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_mid_scan_wedge_quarantine_digest_identical():
    from flink_tpu.runtime import device_health as dh
    from flink_tpu.testing import chaos

    rng = np.random.default_rng(7)
    batches = []
    for i in range(20):
        k = rng.integers(0, 64, 512).astype(np.int64)
        v = np.ones(512, np.float32)
        ts = i * 50 + np.sort(rng.integers(0, 50, 512)).astype(np.int64)
        batches.append((k, v, ts))

    def one_pass(superbatch, device_probe, inject):
        prev = dh.get_monitor(create=False)
        dh.set_monitor(dh.DeviceHealthMonitor(
            dh.WatchdogConfig(deadline_floor_s=0.5), heal_async=False))
        inj = chaos.FaultInjector(seed=3)
        sched = (inj.inject("device.dispatch", chaos.WedgedDevice(at=3))
                 if inject else None)
        op = _mk_op(superbatch, device_probe=device_probe)
        out = []
        snap_degraded = False
        try:
            with chaos.installed(inj):
                for i, (k, v, ts) in enumerate(batches):
                    out += op.process_batch(
                        RecordBatch({"k": k, "v": v}, timestamps=ts))
                    out += op.process_watermark(Watermark(int(ts.max()) - 1))
                    if inject and i == 12:
                        op.prepare_snapshot_pre_barrier()
                        op.snapshot_state()   # checkpoint DURING quarantine
                        snap_degraded = op._degraded
                        sched.heal()
                        dh.get_monitor().probe_now()
                    if inject and i == 16:
                        out += op.prepare_snapshot_pre_barrier()
                out += op.end_input()
            stats = op.device_health_stats()
            held_deleted = any(
                getattr(a, "is_deleted", lambda: False)()
                for a in ((op._delta_counts,) + (op._delta_leaves or ()))
                if a is not None)
            op.close()
        finally:
            dh.set_monitor(prev)
        return _digests(out), stats, snap_degraded, held_deleted

    clean, _s, _d, _h = one_pass(1, "off", False)
    wedged, stats, snap_degraded, held = one_pass(4, "on", True)
    assert wedged == clean, "wedged scan run diverged from clean run"
    assert stats["quarantine_migrations"] == 1
    assert stats["repromotions"] == 1 and stats["degraded"] == 0
    assert snap_degraded, "snapshot did not run during quarantine"
    assert not held, "operator still holds deleted (donated) delta arrays"


def test_donated_delta_consumed_takes_restart_path():
    """PR-4's donated-buffer guard, extended to the scan lane's delta
    planes: when a genuinely timed-out dispatch already CONSUMED the
    donated delta arrays, the degrade path must refuse in-process salvage
    (a use-after-free) and surface the original error — the restart path
    — instead of limping on with deleted arrays."""
    op = _mk_op(4, device_probe="on")
    rng = np.random.default_rng(3)
    for i in range(8):
        k = rng.integers(0, 64, 256).astype(np.int64)
        ts = i * 50 + np.sort(rng.integers(0, 50, 256)).astype(np.int64)
        op.process_batch(RecordBatch(
            {"k": k, "v": np.ones(256, np.float32)}, timestamps=ts))
        op.process_watermark(Watermark(int(ts.max()) - 1))
    op.flush_pipeline()
    assert op._delta_counts is not None and op._delta_panes, \
        "test setup: scan lane left no unsynced delta"
    # simulate the donated-consumed state a real watchdog timeout leaves
    for a in (op._delta_counts, *op._delta_leaves):
        a.delete()
    from flink_tpu.runtime.device_health import DeviceQuarantinedError
    err = DeviceQuarantinedError("wedged (test)")
    with pytest.raises(DeviceQuarantinedError) as ei:
        op._devprobe_degrade(err)
    assert ei.value is err, "restart path must surface the ORIGINAL error"
    assert "consumed" in str(ei.value.__cause__ or "").lower() \
        or isinstance(ei.value.__cause__, RuntimeError)
    op.close()


# ---------------------------------------------------------------------------
# resolution / calibration plumbing
# ---------------------------------------------------------------------------

def test_superbatch_zero_resolves_via_calibration(monkeypatch):
    calls = []
    monkeypatch.setattr(fused_step, "calibrated_superbatch",
                        lambda: calls.append(1) or 6)
    op = _mk_op(0)
    res = _seeded_run(op, n_batches=6, close=False)
    fu = op.fused_stats()
    op.close()
    assert calls, "auto superbatch never consulted the calibration"
    assert fu["depth"] == 6 and fu["enabled"] == 1
    ref = _seeded_run(_mk_op(1), n_batches=6)
    assert res[0] == ref[0], "auto-resolved staging diverged"


def test_superbatch_env_override(monkeypatch):
    monkeypatch.setenv("FLINK_TPU_SUPERBATCH", "3")
    fused_step._reset_calibration_for_tests()
    try:
        assert fused_step.calibrated_superbatch() == 3
    finally:
        fused_step._reset_calibration_for_tests()


def test_single_batch_flush_is_not_a_super_pass():
    """A fire boundary draining ONE staged batch runs the plain per-batch
    path: ``host_super_passes`` must count genuine multi-batch passes
    only (the mesh amortization story reads this counter), while
    ``flushes`` counts every drain."""
    op = _mk_op(4)
    rng = np.random.default_rng(3)
    for i in range(5):
        keys = rng.integers(0, 512, 1024).astype(np.int64)
        vals = rng.random(1024).astype(np.float32)
        # each batch spans a whole window: every watermark fires, so the
        # stage never accumulates past one batch
        ts = np.full(1024, i * 100 + 50, np.int64)
        op.process_batch(RecordBatch({"k": keys, "v": vals},
                                     timestamps=ts))
        op.process_watermark(Watermark(i * 100 + 99))
    fu = op.fused_stats()
    op.close()
    assert fu["flushes"] >= 5
    assert fu["host_super_passes"] == 0, \
        "single-batch drains must not count as super passes"


def test_count_trigger_pins_unfused():
    """Count triggers read device counts inside process_batch: they must
    never stage (the per-batch read IS the semantics)."""
    from flink_tpu.windowing.assigners import GlobalWindows
    from flink_tpu.windowing.triggers import CountTrigger

    op = WindowAggOperator(
        GlobalWindows(), SumAggregator(jnp.float32), key_column="k",
        value_column="v", trigger=CountTrigger.of(4), superbatch=8)
    op.open(RuntimeContext())
    k = np.arange(16, dtype=np.int64) % 4
    out = []
    for i in range(4):
        out += op.process_batch(RecordBatch(
            {"k": k, "v": np.ones(16, np.float32)},
            timestamps=np.full(16, i * 10, np.int64)))
    assert op.fused_stats()["enabled"] == 0
    assert any(hasattr(b, "columns") for b in out), "count fire missing"
    op.close()


def test_pallas_fold_gate_off_on_cpu():
    from flink_tpu.state.device_keyindex import pallas_probe_fold_available

    assert not pallas_probe_fold_available(1 << 12, 1 << 14, ("add",)), \
        "fused Pallas kernel must be gated off on the CPU backend"
    # non-single-add shapes are ineligible everywhere
    assert not pallas_probe_fold_available(1 << 12, 1 << 14,
                                           ("add", "min"))
    assert not pallas_probe_fold_available(1 << 12, 1 << 14, None)


def test_fused_scan_phase_and_span_names():
    """The --profile/tracing contract under fusion: scan-lane time lands
    in a 'fused_scan' phase whose hot_stage spans ride the journal with
    the same name (the test_bench_gate vocabulary scrape sees the literal
    in window_agg.py)."""
    from flink_tpu.observability import tracing

    j = tracing.install(tracing.SpanJournal(capacity=4096))
    try:
        op = _mk_op(4, device_probe="on")
        _seeded_run(op, n_batches=6)
    finally:
        tracing.uninstall()
    assert op.phase_ns.get("fused_scan", 0) > 0, \
        "scan-lane time not attributed to the fused_scan phase"
    names = {s[3] for s in j.snapshot()["spans"] if s[4] == "hot_stage"}
    assert "fused_scan" in names, "no fused_scan hot_stage spans emitted"
