"""Kafka v2 record batches + consumer groups (VERDICT r3 next #3).

Byte-level checks mirror the v0 suite's approach: frames are hand-built in
the tests with independent struct packing, so the codec is validated
against the spec, not against itself.  Group tests drive the real broker
over TCP: join/sync/range assignment, a two-consumer rebalance, generation
fencing, and committed offsets surviving both consumer restarts and broker
restarts.  Reference: flink-connector-kafka KafkaSource (reader/enumerator
built on exactly these APIs)."""

import json
import struct
import threading
import time

import numpy as np
import pytest

from flink_tpu.connectors.kafka import KafkaWireBroker, KafkaWireClient
from flink_tpu.connectors.kafka_v2 import (
    KafkaGroupConsumer, KafkaGroupSource, decode_assignment,
    decode_record_batches, decode_subscription, encode_assignment,
    encode_record_batch, encode_subscription, fetch_v2, produce_v2,
    range_assign, read_varint, write_varint)
from flink_tpu.native import crc32c


# ---------------------------------------------------------------------------
# codec, byte-level
# ---------------------------------------------------------------------------

def test_crc32c_known_answer():
    # the Castagnoli check value from the CRC catalogue
    assert crc32c(b"123456789") == 0xE3069283


def test_varint_zigzag():
    for v in (0, 1, -1, 63, -64, 64, 300, -300, 2 ** 31, -2 ** 31, 10 ** 15):
        buf = bytearray()
        write_varint(buf, v)
        got, pos = read_varint(bytes(buf), 0)
        assert got == v and pos == len(buf)
    # zigzag makes small magnitudes short
    one = bytearray(); write_varint(one, -1)
    assert len(one) == 1


def test_record_batch_golden_bytes():
    """Hand-assemble a one-record magic-2 batch per the spec and require
    byte equality with the codec."""
    key, value, ts = b"k", b"hello", 1234
    # record: attrs(0) tsDelta(0) offDelta(0) klen(1) key vlen(5) value nh(0)
    rec = bytes([0]) + bytes([0]) + bytes([0]) \
        + bytes([1 << 1]) + key + bytes([5 << 1]) + value + bytes([0])
    rec = bytes([len(rec) << 1]) + rec          # length varint (zigzag)
    after_crc = struct.pack(">hiqqqhii", 0, 0, ts, ts, -1, -1, -1, 1) + rec
    crc = crc32c(after_crc)
    expected = (struct.pack(">qi", 7, 9 + len(after_crc))
                + struct.pack(">ibI", 0, 2, crc) + after_crc)
    got = encode_record_batch(7, [(ts, key, value, [])])
    assert got == expected
    [(off, rts, rk, rv, hdrs)] = decode_record_batches(expected)
    assert (off, rts, rk, rv, hdrs) == (7, ts, key, value, [])


def test_record_batch_roundtrip_edge_cases():
    records = [
        (100, None, b"v0", []),
        (105, b"key", None, [("h1", b"x"), ("h2", None)]),
        (99, b"" , b"", []),                     # empty (not null) key/value
        (100 + 10 ** 7, b"late", b"\x00" * 300, []),
    ]
    data = encode_record_batch(42, records)
    out = decode_record_batches(data)
    assert [(o, t, k, v, h) for o, t, k, v, h in out] == [
        (42 + i, t, k, v, h) for i, (t, k, v, h) in enumerate(records)]


def test_record_batch_crc_rejects_corruption():
    data = bytearray(encode_record_batch(0, [(1, b"a", b"b", [])]))
    data[-1] ^= 0x40
    with pytest.raises(ValueError, match="CRC32C"):
        decode_record_batches(bytes(data))


def test_partial_trailing_batch_skipped():
    full = encode_record_batch(0, [(1, b"a", b"b", [])])
    two = full + encode_record_batch(1, [(2, b"c", b"d", [])])
    assert len(decode_record_batches(two[:len(full) + 10])) == 1


def test_subscription_assignment_codec():
    sub = encode_subscription(["t1", "t2"])
    assert decode_subscription(sub) == ["t1", "t2"]
    a = encode_assignment({"t1": [0, 2], "t2": [1]})
    assert decode_assignment(a) == {"t1": [0, 2], "t2": [1]}


def test_range_assignor():
    plan = range_assign([("m1", ["t"]), ("m2", ["t"])], {"t": 5})
    assert plan["m1"]["t"] == [0, 1, 2] and plan["m2"]["t"] == [3, 4]
    # member not subscribed to a topic gets nothing from it
    plan = range_assign([("m1", ["t"]), ("m2", ["u"])], {"t": 2, "u": 2})
    assert plan["m1"] == {"t": [0, 1]} and plan["m2"] == {"u": [0, 1]}


# ---------------------------------------------------------------------------
# broker data plane (v2 over TCP) + cross-version interop
# ---------------------------------------------------------------------------

@pytest.fixture()
def broker():
    b = KafkaWireBroker().start()
    yield b
    b.stop()


def test_produce_fetch_v2(broker):
    broker.create_topic("t2", 1)
    c = KafkaWireClient(broker.host, broker.port)
    try:
        base = produce_v2(c, "t2", 0, [(111, b"k1", b"v1", []),
                                       (222, b"k2", b"v2", [("h", b"1")])])
        assert base == 0
        recs, hw = fetch_v2(c, "t2", 0, 0)
        assert hw == 2
        assert [(o, t, k, v) for o, t, k, v, _h in recs] == [
            (0, 111, b"k1", b"v1"), (1, 222, b"k2", b"v2")]
    finally:
        c.close()


def test_cross_version_interop(broker):
    """v0-produced records fetch via v4 (and vice versa): one log, two
    dialects — the broker re-encodes per request version."""
    broker.create_topic("x", 1)
    c = KafkaWireClient(broker.host, broker.port)
    try:
        c.produce("x", 0, [(b"a", b"old")])            # v0 produce
        produce_v2(c, "x", 0, [(5, b"b", b"new", [])])  # v3 produce
        msgs, hw = c.fetch("x", 0, 0)                   # v0 fetch
        assert hw == 2 and [v for _o, _k, v in msgs] == [b"old", b"new"]
        recs, hw = fetch_v2(c, "x", 0, 0)               # v4 fetch
        assert hw == 2 and [v for _o, _t, _k, v, _h in recs] == [b"old",
                                                                 b"new"]
    finally:
        c.close()


def test_v2_persistence_across_broker_restart(tmp_path, broker):
    d = str(tmp_path / "logs")
    b1 = KafkaWireBroker(directory=d).start()
    try:
        b1.create_topic("p", 1)
        c = KafkaWireClient(b1.host, b1.port)
        produce_v2(c, "p", 0, [(77, b"k", b"v", [])])
        c.close()
    finally:
        b1.stop()
    b2 = KafkaWireBroker(directory=d).start()
    try:
        c = KafkaWireClient(b2.host, b2.port)
        recs, hw = fetch_v2(c, "p", 0, 0)
        assert hw == 1 and recs[0][1] == 77 and recs[0][3] == b"v"
        c.close()
    finally:
        b2.stop()


# ---------------------------------------------------------------------------
# consumer groups
# ---------------------------------------------------------------------------

def test_find_coordinator(broker):
    c = KafkaGroupConsumer(broker.host, broker.port, "g0", ["t"])
    try:
        node, host, port = c.find_coordinator()
        assert (host, port) == (broker.host, broker.port)
    finally:
        c.close()


def test_single_consumer_gets_all_partitions(broker):
    broker.create_topic("t", 4)
    c = KafkaGroupConsumer(broker.host, broker.port, "g1", ["t"])
    try:
        assignment = c.join()
        assert assignment == {"t": [0, 1, 2, 3]}
        assert c.heartbeat()
    finally:
        c.leave()
        c.close()


def test_two_consumer_rebalance(broker):
    """c1 owns everything; c2 joins -> c1's heartbeat reports the rebalance
    -> both rejoin -> the partitions split; c2 leaves -> c1 reclaims all."""
    broker.create_topic("t", 4)
    c1 = KafkaGroupConsumer(broker.host, broker.port, "g2", ["t"],
                            client_id="c1")
    c2 = KafkaGroupConsumer(broker.host, broker.port, "g2", ["t"],
                            client_id="c2")
    try:
        assert c1.join() == {"t": [0, 1, 2, 3]}
        # c2's join blocks on the rebalance barrier until c1 rejoins: run
        # it in a thread while c1 heartbeats its way into the new round
        a2: dict = {}
        t = threading.Thread(target=lambda: a2.update(c2.join()))
        t.start()
        deadline = time.time() + 5
        while c1.heartbeat() and time.time() < deadline:
            time.sleep(0.02)
        assert time.time() < deadline, "c1 never saw the rebalance"
        a1 = c1.join()
        t.join(timeout=5)
        assert not t.is_alive()
        got = sorted(a1.get("t", []) + a2.get("t", []))
        assert got == [0, 1, 2, 3]
        assert a1["t"] and a2["t"]          # both hold a nonempty range
        assert c1.generation == c2.generation
        # c2 leaves: c1 discovers via heartbeat and reclaims everything
        c2.leave()
        deadline = time.time() + 5
        while c1.heartbeat() and time.time() < deadline:
            time.sleep(0.02)
        assert c1.join() == {"t": [0, 1, 2, 3]}
    finally:
        c1.close()
        c2.close()


def test_commit_fetch_offsets_with_generation_fencing(broker):
    broker.create_topic("t", 2)
    c = KafkaGroupConsumer(broker.host, broker.port, "g3", ["t"])
    try:
        c.join()
        c.commit({("t", 0): 41, ("t", 1): 7})
        got = c.committed([("t", 0), ("t", 1)])
        assert got == {("t", 0): 41, ("t", 1): 7}
        # a deposed generation's commit is fenced
        c.generation += 5
        with pytest.raises(ValueError, match="OffsetCommit"):
            c.commit({("t", 0): 99})
        c.generation -= 5
        assert c.committed([("t", 0)]) == {("t", 0): 41}
    finally:
        c.close()


def test_committed_offsets_survive_broker_restart(tmp_path):
    d = str(tmp_path / "logs")
    b1 = KafkaWireBroker(directory=d).start()
    try:
        b1.create_topic("t", 1)
        c = KafkaGroupConsumer(b1.host, b1.port, "gd", ["t"])
        c.join()
        c.commit({("t", 0): 123})
        c.close()
    finally:
        b1.stop()
    b2 = KafkaWireBroker(directory=d).start()
    try:
        c = KafkaGroupConsumer(b2.host, b2.port, "gd", ["t"])
        assert c.committed([("t", 0)]) == {("t", 0): 123}
        c.close()
    finally:
        b2.stop()


# ---------------------------------------------------------------------------
# group source: committed-offset restart
# ---------------------------------------------------------------------------

def _produce_rows(broker, topic, parts, rows_per_part):
    c = KafkaWireClient(broker.host, broker.port)
    try:
        for p in range(parts):
            recs = [(i, None,
                     json.dumps({"part": p, "i": i}).encode(), [])
                    for i in range(rows_per_part)]
            produce_v2(c, topic, p, recs)
    finally:
        c.close()


def _drain(source, parallelism: int = 1):
    rows = []
    for split in source.create_splits(parallelism):
        for el in split.read():
            if hasattr(el, "columns"):
                for i in range(len(el)):
                    rows.append({k: int(np.asarray(el.column(k))[i])
                                 for k in el.columns})
    return rows


def test_group_source_reads_and_resumes(broker):
    """First run drains everything and commits; a second run (same group)
    resumes at the committed offsets and sees ONLY newly produced rows —
    the committed-offset restart contract of the reference's
    OffsetsInitializer.committedOffsets."""
    broker.create_topic("s", 3)
    _produce_rows(broker, "s", 3, 50)
    src = KafkaGroupSource(broker.host, broker.port, "s", group_id="job1")
    rows = _drain(src)
    assert len(rows) == 150
    assert {(r["part"], r["i"]) for r in rows} == {
        (p, i) for p in range(3) for i in range(50)}
    # run 2, nothing new: resumes at committed offsets, reads nothing
    assert _drain(KafkaGroupSource(broker.host, broker.port, "s",
                                   group_id="job1")) == []
    # produce more, run 3: only the new rows
    c = KafkaWireClient(broker.host, broker.port)
    produce_v2(c, "s", 1, [(0, None, json.dumps({"part": 1, "i": 99}).encode(),
                            [])])
    c.close()
    rows3 = _drain(KafkaGroupSource(broker.host, broker.port, "s",
                                    group_id="job1"))
    assert rows3 == [{"part": 1, "i": 99}]
    # a FRESH group starts from earliest and sees everything
    assert len(_drain(KafkaGroupSource(broker.host, broker.port, "s",
                                       group_id="job2"))) == 151


def test_group_source_parallel_exactly_once(broker):
    """Two parallel splits partition the topic manually (p %% 2 == split
    index, the enumerator's round-robin): every record read exactly once."""
    broker.create_topic("par", 4)
    _produce_rows(broker, "par", 4, 25)
    rows = _drain(KafkaGroupSource(broker.host, broker.port, "par",
                                   group_id="jp"), parallelism=2)
    assert len(rows) == 100
    assert {(r["part"], r["i"]) for r in rows} == {
        (p, i) for p in range(4) for i in range(25)}
    # resume across BOTH splits: nothing left
    assert _drain(KafkaGroupSource(broker.host, broker.port, "par",
                                   group_id="jp"), parallelism=2) == []


def test_leave_during_join_barrier(broker):
    """A member leaving while another waits in the rebalance barrier must
    not expel the waiter (regression: the waiter's joined mark was erased
    by the leave, then min() crashed on an empty group)."""
    broker.create_topic("t", 2)
    c1 = KafkaGroupConsumer(broker.host, broker.port, "gl", ["t"],
                            client_id="c1")
    c2 = KafkaGroupConsumer(broker.host, broker.port, "gl", ["t"],
                            client_id="c2")
    try:
        c1.join()
        result: dict = {}
        t = threading.Thread(target=lambda: result.update(c2.join()))
        t.start()
        time.sleep(0.15)          # c2 is blocked in the barrier
        c1.leave()
        t.join(timeout=8)
        assert not t.is_alive()
        assert result == {"t": [0, 1]}   # c2 inherits everything
        assert c2.heartbeat()
    finally:
        c1.close()
        c2.close()


def test_mixed_v0_v2_log_survives_restart(tmp_path):
    """A pre-upgrade on-disk partition log (v0 message sets) continued with
    v2 batches must load after a restart — per-entry format sniffing."""
    from flink_tpu.connectors.kafka import encode_message_set

    d = str(tmp_path / "logs")
    b1 = KafkaWireBroker(directory=d).start()
    try:
        b1.create_topic("m", 1)
        path = b1._part_path("m", 0)
    finally:
        b1.stop()
    # simulate a pre-upgrade file: raw v0 message set on disk
    with open(path, "ab") as f:
        f.write(encode_message_set([(0, b"k0", b"old")]))
    b2 = KafkaWireBroker(directory=d).start()
    try:
        c = KafkaWireClient(b2.host, b2.port)
        produce_v2(c, "m", 0, [(9, b"k1", b"new", [])])  # appends v2
        c.close()
    finally:
        b2.stop()
    b3 = KafkaWireBroker(directory=d).start()   # loads the MIXED file
    try:
        c = KafkaWireClient(b3.host, b3.port)
        recs, hw = fetch_v2(c, "m", 0, 0)
        assert hw == 2
        assert [v for _o, _t, _k, v, _h in recs] == [b"old", b"new"]
        c.close()
    finally:
        b3.stop()
