"""Kinesis connector (FlinkKinesisConsumer/Producer analogs): JSON wire
service + SigV4 client + per-shard positioned source + batched sink."""

import json

import numpy as np
import pytest

from flink_tpu.connectors.kinesis import (KinesisClient, KinesisError,
                                          KinesisService, KinesisSink,
                                          KinesisSource)
from flink_tpu.core.batch import RecordBatch


@pytest.fixture
def svc():
    s = KinesisService()
    yield s
    s.close()


def client(s, **kw):
    return KinesisClient(f"http://{s.host}:{s.port}", **kw)


class TestWire:
    def test_create_put_get(self, svc):
        c = client(svc)
        c.create_stream("s1", shards=2)
        assert len(c.list_shards("s1")) == 2
        c.put_records("s1", [("a", b'{"x": 1}'), ("b", b'{"x": 2}'),
                             ("a", b'{"x": 3}')])
        got = []
        for sid in c.list_shards("s1"):
            it = c.shard_iterator("s1", sid)
            res = c.get_records(it)
            got += [json.loads(__import__("base64").b64decode(r["Data"]))
                    for r in res["Records"]]
            assert res["MillisBehindLatest"] == 0
        assert sorted(r["x"] for r in got) == [1, 2, 3]

    def test_same_partition_key_same_shard_ordered(self, svc):
        c = client(svc)
        c.create_stream("s2", shards=4)
        c.put_records("s2", [("k", json.dumps({"i": i}).encode())
                             for i in range(10)])
        non_empty = []
        for sid in c.list_shards("s2"):
            res = c.get_records(c.shard_iterator("s2", sid))
            if res["Records"]:
                non_empty.append(res["Records"])
        assert len(non_empty) == 1             # one shard owns the key
        seqs = [int(r["SequenceNumber"]) for r in non_empty[0]]
        assert seqs == sorted(seqs)            # per-shard order preserved

    def test_iterator_types_and_errors(self, svc):
        c = client(svc)
        c.create_stream("s3")
        c.put_records("s3", [("k", b"a"), ("k", b"b"), ("k", b"c")])
        (sid,) = c.list_shards("s3")
        after = c.call("GetShardIterator", {
            "StreamName": "s3", "ShardId": sid,
            "ShardIteratorType": "AFTER_SEQUENCE_NUMBER",
            "StartingSequenceNumber": "0"})["ShardIterator"]
        recs = c.get_records(after)["Records"]
        assert [r["SequenceNumber"] for r in recs] == ["1", "2"]
        latest = c.call("GetShardIterator", {
            "StreamName": "s3", "ShardId": sid,
            "ShardIteratorType": "LATEST"})["ShardIterator"]
        assert c.get_records(latest)["Records"] == []
        with pytest.raises(KinesisError, match="ResourceNotFound"):
            c.list_shards("nope")
        with pytest.raises(KinesisError, match="ResourceInUse"):
            c.create_stream("s3")

    def test_access_key_enforced(self):
        s = KinesisService(access_key="AKID", secret_key="sek")
        try:
            good = client(s, access_key="AKID", secret_key="sek")
            good.create_stream("auth")
            bad = client(s, access_key="WRONG", secret_key="sek")
            with pytest.raises(KinesisError, match="AccessDenied"):
                bad.list_shards("auth")
        finally:
            s.close()


class TestConnector:
    def test_sink_source_round_trip(self, svc):
        c = client(svc)
        c.create_stream("events", shards=3)
        ep = f"http://{svc.host}:{svc.port}"
        sink = KinesisSink(ep, "events", partition_key_column="k")
        sink.open(None)
        sink.write_batch(RecordBatch(
            {"k": np.asarray([1, 2, 3, 1], np.int64),
             "v": np.asarray([1.0, 2.0, 3.0, 4.0])}))
        sink.end_input()
        sink.close()
        src = KinesisSource(ep, "events")
        rows = [r for sp in src.create_splits(4)
                for b in sp.read() for r in b.to_rows()]
        assert sorted((r["k"], r["v"]) for r in rows) == \
            [(1, 1.0), (1, 4.0), (2, 2.0), (3, 3.0)]

    def test_positioned_reader_resumes_mid_shard(self, svc):
        c = client(svc)
        c.create_stream("resume")
        c.put_records("resume", [("k", json.dumps({"i": i}).encode())
                                 for i in range(20)])
        ep = f"http://{svc.host}:{svc.port}"
        src = KinesisSource(ep, "resume", batch_rows=8)
        (split,) = src.create_splits(1)
        reader = src.open_split(split, None)
        first = next(reader)
        assert reader.position == 8            # checkpointable position
        # resume a FRESH reader from the checkpointed position
        reader2 = src.open_split(split, reader.position)
        rest = [r["i"] for b in reader2 for r in b.to_rows()]
        assert [r["i"] for r in first.to_rows()] + rest == list(range(20))

    def test_source_in_pipeline(self, svc):
        from flink_tpu.datastream.api import StreamExecutionEnvironment

        c = client(svc)
        c.create_stream("nums", shards=2)
        ep = f"http://{svc.host}:{svc.port}"
        sink = KinesisSink(ep, "nums", partition_key_column="k")
        sink.open(None)
        sink.write_batch(RecordBatch(
            {"k": np.asarray([0, 1, 0, 1], np.int64),
             "v": np.asarray([1.0, 2.0, 3.0, 4.0])}))
        sink.close()
        env = StreamExecutionEnvironment()
        rows = (env.from_source(KinesisSource(ep, "nums"))
                .key_by("k").sum("v", output_column="total")
                .execute_and_collect())
        finals = {}
        for r in rows:
            finals[r["k"]] = max(r["total"], finals.get(r["k"], 0.0))
        assert finals == {0: 4.0, 1: 6.0}
