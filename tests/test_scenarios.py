"""Scenario suite (ISSUE-15): end-to-end exactly-once applications under
a diurnal load curve.

Four layers under test:

1. **Workload** — the promoted :class:`DiurnalSource` (one implementation
   for ``bench.py --autoscale`` AND the scenario harness): seeded
   determinism, replay fast-forward, peak accounting.
2. **Two-phase-commit sink base** — the reusable
   :class:`TwoPhaseCommitSink` lifecycle factored out of the Kafka EOS
   sink, plus its rescale union merge through the savepoint machinery.
3. **Rescale coverage for scenario operators** — CEP snapshots split by
   key group and merge with event-id remapping; session snapshots
   dispatch through ``_split_member``; merged watermarks take MIN.
4. **Acceptance** (chaos) — each scenario end-to-end: the autoscaler
   reacts to the diurnal curve, nemeses hit during the peak (worker
   kill, SlowConsumer bursts, KillDuringRescale), and the committed
   transactional output is exactly-once — zero lost, zero duplicated,
   digest-identical to an unfaulted control over the same stream;
   sessionized_analytics additionally cross-checks the datastream TUMBLE
   against the SQL planner, and feature_store serves routed binary
   queryable reads at a paced QPS while rescaling.
"""

import time

import numpy as np
import pytest

from flink_tpu.connectors.sinks import TwoPhaseCommitSink
from flink_tpu.scenarios import SCENARIOS, ScenarioHarness, get_scenario
from flink_tpu.scenarios.harness import (committed_digest, diff_committed)
from flink_tpu.testing.workload import DiurnalSource

# ---------------------------------------------------------------------------
# workload: the shared diurnal generator
# ---------------------------------------------------------------------------


def test_diurnal_source_is_seed_deterministic():
    a = DiurnalSource(4000, 97, 64, 5000, peak_s=0.0, trough_s=0.0, seed=9)
    b = DiurnalSource(4000, 97, 64, 5000, peak_s=0.0, trough_s=0.0, seed=9)
    for (ka, va, ta), (kb, vb, tb) in zip(a._data, b._data):
        assert np.array_equal(ka, kb)
        assert np.array_equal(va, vb)
        assert np.array_equal(ta, tb)
    c = DiurnalSource(4000, 97, 64, 5000, peak_s=0.0, trough_s=0.0, seed=10)
    assert not all(np.array_equal(x[0], y[0])
                   for x, y in zip(a._data, c._data))


def test_diurnal_expected_per_key_covers_all_records():
    src = DiurnalSource(4000, 97, 64, 5000, peak_s=0.0, trough_s=0.0,
                        seed=9)
    exp = src.expected_per_key()
    assert sum(c for c, _s in exp.values()) == src.total_records == 4000
    assert sum(s for _c, s in exp.values()) == 4000.0   # default ones


def test_diurnal_replay_fast_forwards_past_emitted_batches():
    """A rescale restore re-reads from batch 0: already-emitted batches
    must re-yield WITHOUT re-sleeping the pre-cut curve."""
    src = DiurnalSource(2048, 31, 64, 5000, peak_s=0.01, trough_s=0.01,
                        seed=3)
    first = list(src.read_split(0, 2))
    assert src._progress[0] == len(first)
    t0 = time.monotonic()
    replay = list(src.read_split(0, 2))
    fast = time.monotonic() - t0
    assert fast < 0.05, f"replay re-slept the curve ({fast:.3f}s)"
    assert len(replay) == len(first)
    for a, b in zip(first, replay):
        assert np.array_equal(np.asarray(a.column("k")),
                              np.asarray(b.column("k")))
    # and the emit log recorded each batch ONCE (peak accounting input)
    assert len(src._emit_log[0]) == len(first)


def test_diurnal_unpaced_control_leg_is_instant_and_identical():
    paced = DiurnalSource(2048, 31, 64, 5000, peak_s=0.002,
                          trough_s=0.004, seed=3)
    unpaced = DiurnalSource(2048, 31, 64, 5000, peak_s=0.002,
                            trough_s=0.004, seed=3, paced=False)
    t0 = time.monotonic()
    batches = list(unpaced.read_split(0, 2)) + list(unpaced.read_split(1, 2))
    assert time.monotonic() - t0 < 0.5
    assert sum(len(b) for b in batches) == sum(
        d[0].size for d in paced._data)
    for (ks, vs, ts), (ku, vu, tu) in zip(paced._data, unpaced._data):
        assert np.array_equal(ks, ku) and np.array_equal(ts, tu)


def test_diurnal_peak_stats_cover_middle_third():
    src = DiurnalSource(4096, 31, 64, 5000, peak_s=0.0, trough_s=0.0,
                        seed=3)
    list(src.read_split(0, 2))
    list(src.read_split(1, 2))
    stats = src.peak_stats()
    nb = src.n_batches
    expect = (2 * nb // 3 - nb // 3) * 64 * 2
    assert stats["peak_records"] == expect
    assert stats["peak_records_per_sec"] >= 0.0


# ---------------------------------------------------------------------------
# TwoPhaseCommitSink: the reusable 2PC lifecycle
# ---------------------------------------------------------------------------


class _MemoryTxnSink(TwoPhaseCommitSink):
    """Minimal transactional backend: rows become visible only on commit;
    commit replay is idempotent; dangling sweep aborts leftovers."""

    def __init__(self, store=None, **kw):
        super().__init__(**kw)
        self.store = store if store is not None else {
            "open": {}, "committed": {}, "log": []}

    def begin_transaction(self, txn_name):
        self.store["open"][txn_name] = []
        return (txn_name,)

    def write_rows(self, handle, rows):
        self.store["open"][handle[0]].extend(rows)

    def commit_transaction(self, handle):
        name = handle[0]
        if name in self.store["committed"]:
            return                          # idempotent replay
        self.store["committed"][name] = self.store["open"].pop(name, [])
        self.store["log"].append(("commit", name))

    def abort_transaction(self, handle):
        self.store["open"].pop(handle[0], None)
        self.store["log"].append(("abort", handle[0]))

    def sweep_dangling(self, committed):
        mine = f"{self.sink_id}-s{self._subtask_index}-"
        names = {h[0] for h in committed}
        for name in list(self.store["open"]):
            if name.startswith(mine) and name not in names:
                self.abort_transaction((name,))

    def visible_rows(self):
        return [r for rows in self.store["committed"].values()
                for r in rows]


def _batch(vals):
    from flink_tpu.core.batch import RecordBatch
    return RecordBatch({"v": np.asarray(vals, np.int64)})


def test_two_phase_sink_stages_and_commits_on_notify():
    from flink_tpu.operators.base import snapshot_scope

    s = _MemoryTxnSink(sink_id="m")
    s.open(type("Ctx", (), {"subtask_index": 0, "parallelism": 1})())
    s.write_batch(_batch([1, 2]))
    with snapshot_scope(1):
        snap = s.snapshot_state()
    assert snap["two_phase"] == "m" and snap["epoch"] == 1
    assert s.visible_rows() == []           # pre-commit: invisible
    s.write_batch(_batch([3]))
    with snapshot_scope(2):
        s.snapshot_state()
    s.notify_checkpoint_complete(1)
    assert [r["v"] for r in s.visible_rows()] == [1, 2]
    s.notify_checkpoint_complete(2)
    assert sorted(r["v"] for r in s.visible_rows()) == [1, 2, 3]


def test_two_phase_sink_end_input_commits_staged_and_current():
    """Graceful end of stream: the tail epoch AND any staged-but-never-
    notified epochs commit — the committed-output hole the scenario
    suite's gating first caught (SinkOperator now calls end_input)."""
    from flink_tpu.operators.base import snapshot_scope

    s = _MemoryTxnSink(sink_id="m2")
    s.open(type("Ctx", (), {"subtask_index": 0, "parallelism": 1})())
    s.write_batch(_batch([1]))
    with snapshot_scope(1):
        s.snapshot_state()                  # staged, notify never arrives
    s.write_batch(_batch([2]))
    s.end_input()
    assert sorted(r["v"] for r in s.visible_rows()) == [1, 2]
    assert s.store["open"] == {}


def test_two_phase_sink_restore_replays_and_sweeps():
    from flink_tpu.operators.base import snapshot_scope

    s = _MemoryTxnSink(sink_id="m3")
    s.open(type("Ctx", (), {"subtask_index": 0, "parallelism": 1})())
    s.write_batch(_batch([7]))
    with snapshot_scope(1):
        snap = s.snapshot_state()
    s.write_batch(_batch([8]))              # post-checkpoint epoch, open
    s._flush()
    store = s.store
    for _ in range(2):                      # double restore = idempotent
        r = _MemoryTxnSink(store=store, sink_id="m3")
        r.open(type("Ctx", (), {"subtask_index": 0, "parallelism": 1})())
        r.restore_state(snap)
    assert [x["v"] for x in r.visible_rows()] == [7]
    assert store["open"] == {}              # dangling epoch-1 txn aborted


def test_two_phase_sink_operator_end_input_drives_sink():
    """SinkOperator.end_input must call the sink's end_input (not just
    flush) — otherwise every bounded job aborts its tail transaction at
    close."""
    from flink_tpu.core.functions import RuntimeContext
    from flink_tpu.operators.basic import SinkOperator

    sink = _MemoryTxnSink(sink_id="m4")
    op = SinkOperator(sink)
    op.open(RuntimeContext())
    op.process_batch(_batch([5, 6]))
    op.end_input()
    inner = op.sink                         # clone_per_subtask deep-copies
    assert sorted(r["v"] for r in inner.visible_rows()) == [5, 6]


def test_two_phase_merge_unions_staged_across_subtasks():
    merged = TwoPhaseCommitSink.merge_snapshots([
        {"epoch": 3, "two_phase": "s",
         "staged": [("s-s0-2", 10, 0, 4)]},
        {"epoch": 5, "two_phase": "s",
         "staged": [("s-s1-3", 11, 0, 4), ("s-s1-4", 11, 0, 5)]},
        {},
    ])
    assert merged["epoch"] == 5
    assert len(merged["staged"]) == 3
    assert merged["two_phase"] == "s"


def test_two_phase_split_keeps_epoch_and_routes_staged_by_owner():
    """Rescale split: every part keeps the merged epoch (an empty part
    would restart at epoch 0 and reuse transaction names that may still
    be staged-open at the backend), and staged entries go back to their
    OWNING subtask so its own restore commits them before any sweep."""
    member = {"epoch": 7, "two_phase": "s", "staged": [
        ("s-s0-2", 10, 0, 4), ("s-s1-3", 11, 0, 4), ("s-s3-1", 13, 0, 2)]}
    parts = TwoPhaseCommitSink.split_snapshot(member, 128, 2)
    assert [p["epoch"] for p in parts] == [7, 7]
    # owner 0 -> part 0, owner 1 -> part 1, removed owner 3 -> part 0
    assert {t[0] for t in parts[0]["staged"]} == {"s-s0-2", "s-s3-1"}
    assert {t[0] for t in parts[1]["staged"]} == {"s-s1-3"}


def test_two_phase_commit_strict_vs_replay(tmp_path):
    """First-time commits (notify/end_input) must RAISE on an unknown
    transaction (the staged rows are gone — silent loss otherwise);
    restore replay tolerates it (commit aged out of retention)."""
    from flink_tpu.connectors.kafka import (KafkaError, KafkaWireBroker,
                                            KafkaExactlyOnceSink)
    from flink_tpu.operators.base import snapshot_scope

    b = KafkaWireBroker(directory=str(tmp_path / "kafka")).start()
    try:
        b.create_topic("t", partitions=1)
        s = KafkaExactlyOnceSink(b.host, b.port, "t", sink_id="strict")
        s.open(type("Ctx", (), {"subtask_index": 0})())
        s.write_batch(_batch([1]))
        with snapshot_scope(1):
            snap = s.snapshot_state()
        (tid, pid, ep, _cid) = snap["staged"][0]
        # the txn vanishes from under the sink (zombie sweep analog)
        s._cli().end_txn(tid, pid, ep, commit=False)
        with pytest.raises(KafkaError):
            s.notify_checkpoint_complete(1)     # strict: loss must raise
        # restore replay of a long-gone txn proceeds idempotently: an
        # abort leaves no committed-tid entry, so fake one having aged
        # out by replaying a commit of a NEVER-known tid
        r = KafkaExactlyOnceSink(b.host, b.port, "t", sink_id="strict")
        r.open(type("Ctx", (), {"subtask_index": 0})())
        with pytest.raises(KafkaError):
            r.commit_transaction(("strict-s0-99", 999, 0))
        r.replay_commit(("strict-s0-99", 999, 0))   # tolerated
        r.close()
        s.close()
    finally:
        b.stop()


def test_two_phase_merge_dispatches_in_savepoint_machinery():
    """A chained vertex with a 2PC sink member must UNION staged
    transactions on merge — keep-subtask-0 would strand subtask 1's
    pre-commits (records lost if the cancel raced the notify round)."""
    from flink_tpu.state_processor.savepoint import _merged_operator_snapshot

    entry = {"subtasks": [
        {"operator": {"op0": {"epoch": 1, "two_phase": "k",
                              "staged": [("k-s0-0", 1, 0, 1)]}}},
        {"operator": {"op0": {"epoch": 2, "two_phase": "k",
                              "staged": [("k-s1-0", 2, 0, 1)]}}},
    ]}
    merged = _merged_operator_snapshot(entry, strict=True)
    staged = merged["op0"]["staged"]
    assert {t[0] for t in staged} == {"k-s0-0", "k-s1-0"}
    assert merged["op0"]["epoch"] == 2


# ---------------------------------------------------------------------------
# CEP + session rescale coverage (the scenario operators)
# ---------------------------------------------------------------------------


def _cep_op(vectorized="off"):
    from flink_tpu.cep import CepOperator, Pattern

    pat = (Pattern.begin("small")
           .where(lambda c: np.asarray(c["v"]) < 0.2)
           .followed_by("large")
           .where(lambda c: np.asarray(c["v"]) > 0.8)
           .within(5000))
    return CepOperator(pat, "k",
                       lambda m: {"k": m["small"][0]["k"],
                                  "v": m["large"][0]["v"]},
                       vectorized=vectorized)


def _cep_drain(op, keys, vals, tss, wm):
    from flink_tpu.core.batch import RecordBatch, Watermark

    out = op.process_batch(RecordBatch({"k": keys, "v": vals},
                                       timestamps=tss))
    out += op.process_watermark(Watermark(wm))
    return sorted((int(np.asarray(b.column("k"))[i]),
                   round(float(np.asarray(b.column("v"))[i]), 9),
                   int(np.asarray(b.timestamps)[i]))
                  for b in out for i in range(len(b)))


def _cep_stream(seed=3, n=3000, keys=64):
    rng = np.random.default_rng(seed)
    ks = rng.integers(0, keys, n).astype(np.int64)
    vs = rng.random(n)
    ts = np.sort(rng.integers(0, 8000, n)).astype(np.int64)
    return ks, vs, ts


def test_cep_split_routes_partials_by_key_group():
    from flink_tpu.cep.operator import CepOperator
    from flink_tpu.core.keygroups import route_raw_keys

    ks, vs, ts = _cep_stream()
    half = len(ks) // 2
    ref = _cep_op()
    r1 = _cep_drain(ref, ks[:half], vs[:half], ts[:half], 3000)
    r2 = _cep_drain(ref, ks[half:], vs[half:], ts[half:], 1 << 40)

    op = _cep_op()
    assert _cep_drain(op, ks[:half], vs[:half], ts[:half], 3000) == r1
    parts = CepOperator.split_snapshot(op.snapshot_state(), 128, 2)
    own = route_raw_keys(ks[half:], 2, 128)
    cont = []
    for p in range(2):
        o = _cep_op()
        o.restore_state(parts[p])
        m = own == p
        cont += _cep_drain(o, ks[half:][m], vs[half:][m], ts[half:][m],
                           1 << 40)
    assert sorted(cont) == r2


@pytest.mark.parametrize("restore_engine", ["off", "on"])
def test_cep_merge_remaps_event_ids_and_matches(restore_engine):
    """Scale-down: two operators' snapshots share overlapping event-id
    ranges for DIFFERENT rows; the merge must remap ids so the single
    restored row store never aliases two events (and the merged operator
    restores on either engine)."""
    from flink_tpu.cep.operator import CepOperator
    from flink_tpu.core.keygroups import route_raw_keys

    ks, vs, ts = _cep_stream(seed=11)
    half = len(ks) // 2
    ref = _cep_op()
    r1 = _cep_drain(ref, ks[:half], vs[:half], ts[:half], 3000)
    r2 = _cep_drain(ref, ks[half:], vs[half:], ts[half:], 1 << 40)

    own = route_raw_keys(ks, 2, 128)
    ops = [_cep_op(), _cep_op()]
    halves = []
    for p in range(2):
        m = own[:half] == p
        halves += _cep_drain(ops[p], ks[:half][m], vs[:half][m],
                             ts[:half][m], 3000)
    assert sorted(halves) == r1
    merged = CepOperator.merge_snapshots(
        [ops[0].snapshot_state(), ops[1].snapshot_state()])
    om = _cep_op(vectorized=restore_engine)
    om.restore_state(merged)
    assert _cep_drain(om, ks[half:], vs[half:], ts[half:], 1 << 40) == r2


def test_cep_and_session_split_dispatch_in_rescale_machinery():
    """`_split_member` must route CEP (``nfas``) and session
    (``session_keys``) members through the operators' own split — the
    generic keyed split (or worse, keep-subtask-0) silently strands
    their per-key state on rescale."""
    from flink_tpu.cluster.adaptive import _split_member

    cep_member = {"buffers": {1: [], 130: []},
                  "nfas": {1: ([], 0, {}), 130: ([], 0, {})},
                  "last_rows": {}, "next_event_id": 5, "watermark": 7}
    parts = _split_member(cep_member, 128, 2)
    assert len(parts) == 2
    all_keys = sorted(k for p in parts for k in p["nfas"])
    assert all_keys == [1, 130]
    assert all(p["watermark"] == 7 for p in parts)

    sess_member = {"session_keys": np.asarray([1, 130], np.int64),
                   "start": np.asarray([0, 5]), "end": np.asarray([10, 15]),
                   "fired": np.asarray([False, False]),
                   "acc": (np.asarray([1.0, 2.0]),),
                   "watermark": 3, "late_dropped": 0}
    sparts = _split_member(sess_member, 128, 2)
    assert len(sparts) == 2
    assert sorted(int(k) for p in sparts
                  for k in p["session_keys"].tolist()) == [1, 130]


def test_session_merge_takes_min_watermark():
    """Unaligned-cut merge: the behind part's persisted in-flight
    elements replay with their own watermark progression, so the merged
    restart point is the MIN — a max would mark them late on arrival."""
    from flink_tpu.operators.session_window import SessionWindowOperator

    def part(wm, key):
        return {"session_keys": np.asarray([key], np.int64),
                "start": np.asarray([0]), "end": np.asarray([10]),
                "fired": np.asarray([False]),
                "acc": (np.asarray([1.0]),), "watermark": wm,
                "late_dropped": 0}

    merged = SessionWindowOperator.merge_snapshots([part(100, 1),
                                                    part(50, 2)])
    assert merged["watermark"] == 50


# ---------------------------------------------------------------------------
# harness units
# ---------------------------------------------------------------------------


def test_diff_committed_counts_lost_and_duplicated():
    control = {"t": [{"v": 1}, {"v": 2}, {"v": 2}]}
    assert diff_committed({"t": [{"v": 1}, {"v": 2}, {"v": 2}]},
                          control) == (0, 0)
    assert diff_committed({"t": [{"v": 1}, {"v": 2}]}, control) == (1, 0)
    assert diff_committed({"t": [{"v": 1}, {"v": 2}, {"v": 2}, {"v": 2}]},
                          control) == (0, 1)
    # digests are order-insensitive but content-exact
    assert committed_digest({"t": [{"v": 1}, {"v": 2}]}) == \
        committed_digest({"t": [{"v": 2}, {"v": 1}]})
    assert committed_digest({"t": [{"v": 1}]}) != \
        committed_digest({"t": [{"v": 3}]})


def test_scenario_registry_shapes():
    assert set(SCENARIOS) == {"fraud_detection", "sessionized_analytics",
                              "feature_store"}
    sections = set()
    for name in SCENARIOS:
        sc = get_scenario(name)
        for smoke in (True, False):
            spec = sc.spec(smoke)
            assert spec.records > 0 and spec.keys > 0
            assert spec.topics, f"{name}: no transactional topics"
            assert spec.queryable_state, f"{name}: no queryable state"
        sections.add(sc.budget_section)
    assert len(sections) == 3               # one budget section each
    with pytest.raises(ValueError):
        get_scenario("nope")


def test_sql_crosscheck_catches_divergence():
    sc = get_scenario("sessionized_analytics")
    spec = sc.spec(True, records=4000, keys=61)
    src = sc.make_source(spec, paced=False)
    exp = {}
    for ks, vs, ts in src._data:
        for k, v, w in zip(ks.tolist(), vs.tolist(),
                           ((ts // spec.window_ms)
                            * spec.window_ms).tolist()):
            exp[(int(k), int(w))] = exp.get((int(k), int(w)), 0.0) + v
    rows = [{"k": k, "window_start": w, "s": s}
            for (k, w), s in exp.items()]
    assert sc.cross_check({"tumble": rows}, src, spec) == []
    corrupt = [dict(r) for r in rows]
    corrupt[0]["s"] += 1.0
    assert sc.cross_check({"tumble": corrupt}, src, spec)


def test_fraud_example_rides_the_scenario_pattern():
    """Satellite: the shipped example imports the scenario's pattern +
    topology; smoke-run it and find exactly the planted alerts."""
    import os
    import runpy

    from flink_tpu.datastream.api import StreamExecutionEnvironment

    env = StreamExecutionEnvironment()
    example = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "examples", "fraud_detection.py")
    ns = runpy.run_path(example, init_globals={"env": env})
    sink = ns["main"](env)
    env.execute("fraud-example")
    rows = sink.rows()
    assert sorted(int(r["account"]) for r in rows) == [7, 21, 33]
    assert all(float(r["amount"]) == 900.0 for r in rows)


# ---------------------------------------------------------------------------
# acceptance: each scenario end-to-end, exactly-once under kill
# ---------------------------------------------------------------------------


def _accept(name, **kw):
    harness = ScenarioHarness(get_scenario(name), smoke=True,
                              records=30_000, keys=503, **kw)
    res = harness.run()
    assert res["state"] == "Finished", (res["state"], res["error"])
    assert res["control_state"] == "Finished", res["control_error"]
    assert res["records_lost"] == 0, res
    assert res["records_duplicated"] == 0, res
    assert res["digest_match"], res
    assert res["cross_check_violations"] == [], res
    assert res["rescales"] >= 1, res["parallelism_path"]
    assert sum(res["committed_rows"].values()) > 0
    assert {"worker_kill", "kill_during_rescale",
            "slow_consumer"} <= set(res["nemeses"])
    assert res["ok"], res
    return res


@pytest.mark.chaos
def test_fraud_detection_exactly_once_under_kill():
    """Diurnal transactions -> CEP -> transactional alerts: the
    autoscaler rescales the CEP job mid-stream (per-key NFA state splits
    by key group), a worker dies at the peak, a rescale's redistribute is
    killed and re-triggered — and the committed alert stream is
    exactly-once, digest-identical to the unfaulted control."""
    res = _accept("fraud_detection")
    assert res["committed_rows"]["alerts"] > 0
    # the alert totals were live-queryable while the job ran
    assert res["queryable"]["lookups"] > 0
    assert res["queryable"]["routed_batches"] > 0


@pytest.mark.chaos
def test_sessionized_analytics_exactly_once_and_sql_crosscheck():
    """Sessions + TUMBLE over one clickstream, both committed
    transactionally; the TUMBLE branch must equal the SQL planner's
    answer over the identical stream (cross-checked in ``_accept`` via
    cross_check_violations == [])."""
    res = _accept("sessionized_analytics")
    assert res["committed_rows"]["sessions"] > 0
    assert res["committed_rows"]["tumble"] > 0


@pytest.mark.chaos
def test_feature_store_exactly_once_with_routed_reads():
    """Windowed feature aggregates committed transactionally AND served
    to routed binary clients at a paced QPS while the job rescales; the
    committed sums also match the per-(key, window) ground truth."""
    res = _accept("feature_store")
    q = res["queryable"]
    assert q["lookups"] > 0 and q["batches"] > 0
    assert q["routed_batches"] > 0          # the PR-13 routing leg ran
    assert q["found"] > 0                   # live views answered
    assert q["json_fallbacks"] == 0         # binary wire end to end
