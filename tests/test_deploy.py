"""Deployment: externally-started workers (the k8s pod flow) and manifest
rendering (``flink-kubernetes`` analog)."""

import os
import subprocess
import sys
import textwrap
import threading

import numpy as np
import pytest

from flink_tpu.cluster.distributed import ProcessCluster
from flink_tpu.deploy import render_job_cluster
from flink_tpu.deploy.kubernetes import to_yaml


def test_manifest_rendering_shapes():
    ms = render_job_cluster("wordcount", "gcr.io/x/flink-tpu:1", "jobs:build",
                            n_workers=3, checkpoint_dir="/ckpt",
                            checkpoint_interval_ms=5000,
                            tpu_resource={"google.com/tpu": 8},
                            env={"EXTRA": "1"})
    kinds = [m["kind"] for m in ms]
    assert kinds == ["Service", "Service", "Job", "StatefulSet"]
    svc, wsvc, job, sts = ms
    assert svc["spec"]["selector"]["component"] == "coordinator"
    # governing headless Service of the StatefulSet (stable per-pod DNS)
    assert wsvc["metadata"]["name"] == sts["spec"]["serviceName"]
    assert wsvc["spec"]["clusterIP"] == "None"
    cmd = job["spec"]["template"]["spec"]["containers"][0]["command"]
    assert "--workers" in cmd and "3" in cmd and "--checkpoint-dir" in cmd
    worker = sts["spec"]["template"]["spec"]["containers"][0]
    assert sts["spec"]["replicas"] == 3
    assert worker["resources"]["limits"] == {"google.com/tpu": 8}
    assert "--advertise ${POD_IP}" in worker["command"][2]

    text = to_yaml(ms)
    import yaml
    docs = list(yaml.safe_load_all(text))
    assert len(docs) == 4 and docs[0]["kind"] == "Service"


def test_external_workers_register_and_run(tmp_path):
    """spawn=False: the coordinator only listens; workers are launched
    separately with the exact CLI a k8s pod would run."""
    mod = tmp_path / "ext_job_mod.py"
    mod.write_text(textwrap.dedent('''
        import numpy as np
        from flink_tpu.datastream.api import StreamExecutionEnvironment

        def build():
            env = StreamExecutionEnvironment()
            env.set_parallelism(2)
            n = 4000
            keys = (np.arange(n) % 3).astype(np.int64)
            (env.from_collection(columns={"k": keys, "v": np.ones(n)},
                                 batch_size=256)
                .key_by("k").sum("v").collect())
            return env.get_stream_graph("ext-job")
    '''))
    sys.path.insert(0, str(tmp_path))
    try:
        pc = ProcessCluster("ext_job_mod:build", n_workers=2, spawn=False,
                            extra_sys_path=(str(tmp_path),))
        result = {}

        def run():
            result.update(pc.run(timeout_s=120))

        th = threading.Thread(target=run, daemon=True)
        th.start()
        # wait for the coordinator to listen, then start the "pods"
        import time
        deadline = time.time() + 10
        while not hasattr(pc, "control_port") and time.time() < deadline:
            time.sleep(0.02)
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join((str(tmp_path), *sys.path))
        procs = [subprocess.Popen(
            [sys.executable, "-m", "flink_tpu", "worker",
             "--index", str(i), "--workers", "2",
             "--job", "ext_job_mod:build",
             "--coordinator", f"127.0.0.1:{pc.control_port}",
             "--bind", "127.0.0.1", "--advertise", "127.0.0.1"],
            env=env) for i in range(2)]
        th.join(timeout=120)
        for p in procs:
            p.wait(timeout=30)
        assert result.get("state") == "FINISHED", result.get("error")
        last = {}
        for r in result["rows"]:
            last[r["k"]] = r["v"]
        assert last == {0: 1334.0, 1: 1333.0, 2: 1333.0}
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("ext_job_mod", None)


def test_stray_connection_does_not_kill_registration(tmp_path):
    """A readiness-probe-style connect/close or garbage bytes on the
    coordinator port must not consume a worker slot or fail the job."""
    import socket
    import textwrap
    import time

    mod = tmp_path / "probe_job_mod.py"
    mod.write_text(textwrap.dedent('''
        import numpy as np
        from flink_tpu.datastream.api import StreamExecutionEnvironment

        def build():
            env = StreamExecutionEnvironment()
            env.set_parallelism(1)
            (env.from_collection(columns={"k": np.zeros(100, np.int64),
                                          "v": np.ones(100)}, batch_size=64)
                .key_by("k").sum("v").collect())
            return env.get_stream_graph("probe-job")
    '''))
    sys.path.insert(0, str(tmp_path))
    try:
        pc = ProcessCluster("probe_job_mod:build", n_workers=1, spawn=False,
                            extra_sys_path=(str(tmp_path),))
        result = {}
        th = threading.Thread(
            target=lambda: result.update(pc.run(timeout_s=120)), daemon=True)
        th.start()
        deadline = time.time() + 10
        while not hasattr(pc, "control_port") and time.time() < deadline:
            time.sleep(0.02)
        # probe 1: connect and close immediately
        s = socket.create_connection(("127.0.0.1", pc.control_port))
        s.close()
        # probe 2: garbage bytes
        s = socket.create_connection(("127.0.0.1", pc.control_port))
        s.sendall(b"GET / HTTP/1.1\r\n\r\n")
        s.close()
        # the real worker registers fine afterwards
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join((str(tmp_path), *sys.path))
        p = subprocess.Popen(
            [sys.executable, "-m", "flink_tpu", "worker",
             "--index", "0", "--workers", "1",
             "--job", "probe_job_mod:build",
             "--coordinator", f"127.0.0.1:{pc.control_port}"], env=env)
        th.join(timeout=120)
        p.wait(timeout=30)
        assert result.get("state") == "FINISHED", result.get("error")
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("probe_job_mod", None)
