"""Incremental + changelog checkpoints (ISSUE-16).

The acceptance contract: checkpoint bytes scale with the CHANGE RATE, not
the state size (at <=10% of keys churning an increment is <=25% of the
full snapshot), restore = base + ordered increment replay is bit-identical
to a full-snapshot restore — on every state tier (device / host-mirror /
paged), across savepoints (always full, never advancing the chain), under
lost notifies (union-of-unconfirmed dirt), through the content-addressed
storage's compaction, and past torn increment writes (CRC-gated fallback
to an older base).
"""

import os

import numpy as np
import jax.numpy as jnp
import pytest

from flink_tpu.core.batch import RecordBatch, Watermark
from flink_tpu.core.functions import RuntimeContext, SumAggregator
from flink_tpu.operators.base import snapshot_scope
from flink_tpu.operators.window_agg import WindowAggOperator
from flink_tpu.runtime.checkpoint import delta
from flink_tpu.runtime.checkpoint.incremental import \
    IncrementalCheckpointStorage
from flink_tpu.runtime.checkpoint.local import TaskLocalStateStore
from flink_tpu.runtime.checkpoint.storage import CorruptCheckpointError
from flink_tpu.state.changelog import ChangelogKeyedStateBackend
from flink_tpu.state.heap import HeapKeyedStateBackend
from flink_tpu.testing.chaos import (FailTimes, FaultInjector,
                                     TruncatedWrite, installed)
from flink_tpu.windowing import TumblingEventTimeWindows


def make_op(**kw):
    op = WindowAggOperator(TumblingEventTimeWindows.of(1000),
                           SumAggregator(jnp.float32),
                           key_column="k", value_column="v", **kw)
    op.open(RuntimeContext())
    op.incremental_state = True
    return op


def feed(op, keys, vals, ts, wm=None):
    out = op.process_batch(RecordBatch(
        {"k": np.asarray(keys), "v": np.asarray(vals, np.float32)},
        timestamps=np.asarray(ts, np.int64)))
    if wm is not None:
        out += op.process_watermark(Watermark(wm))
    return out


def collect(elements):
    rows = {}
    for b in elements:
        if not hasattr(b, "columns") or "result" not in b.columns:
            continue
        for i in range(len(b)):
            rows[(int(np.asarray(b.column("k"))[i]),
                  int(np.asarray(b.column("window_start"))[i]))] = float(
                np.asarray(b.column("result"))[i])
    return rows


def cut(op, cid, incremental=True):
    """One checkpoint cut as the runtime takes it (scoped snapshot)."""
    with snapshot_scope(cid, incremental=incremental):
        return op.snapshot_state()


def tree_equal(a, b, path="$"):
    """Bit-exact structural equality of two snapshot trees."""
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        a, b = np.asarray(a), np.asarray(b)
        assert a.dtype == b.dtype, f"{path}: dtype {a.dtype} != {b.dtype}"
        assert a.shape == b.shape, f"{path}: shape {a.shape} != {b.shape}"
        assert np.array_equal(a, b), f"{path}: values differ"
        return
    if isinstance(a, dict):
        assert isinstance(b, dict) and a.keys() == b.keys(), \
            f"{path}: keys {sorted(map(str, a))} != {sorted(map(str, b))}"
        for k in a:
            tree_equal(a[k], b[k], f"{path}.{k}")
        return
    if isinstance(a, (list, tuple)):
        assert isinstance(b, (list, tuple)) and len(a) == len(b), \
            f"{path}: length {len(a)} != {len(b)}"
        for i, (x, y) in enumerate(zip(a, b)):
            tree_equal(x, y, f"{path}[{i}]")
        return
    assert a == b, f"{path}: {a!r} != {b!r}"


def _traffic(seed=3, n_seed=3000, churn=120, rounds=3):
    """Seed a key population, then rounds of sparse churn batches."""
    rng = np.random.default_rng(seed)
    seed_keys = np.repeat(np.arange(n_seed), 1)
    batches = [(seed_keys, np.ones(seed_keys.size, np.float32),
                np.full(seed_keys.size, 100, np.int64))]
    for _ in range(rounds):
        k = rng.integers(0, churn, 400)
        batches.append((k, np.ones(400, np.float32),
                        np.full(400, 100, np.int64)))
    return batches


# ---------------------------------------------------------------------------
# window_delta increments: digest-identical restore
# ---------------------------------------------------------------------------

def _restore_digest_identical(op_kw):
    """Chain restore (base + increments) == full restore, bit-identical,
    and both continue to identical fires."""
    batches = _traffic()
    op = make_op(**op_kw)
    feed(op, *batches[0])
    base = cut(op, 1)
    assert not delta.is_increment(base), "first cut must be a full base"
    op.notify_checkpoint_complete(1)

    chain = [base]
    for i, b in enumerate(batches[1:], start=2):
        feed(op, *b)
        inc = cut(op, i)
        assert delta.is_increment(inc), f"cut {i} did not go incremental"
        op.notify_checkpoint_complete(i)
        chain.append(inc)
    full = op.snapshot_state()            # unscoped: always full

    resolved = delta.resolve_chain(chain)
    tree_equal(resolved, full)

    op_chain, op_full = make_op(**op_kw), make_op(**op_kw)
    op_chain.restore_state(resolved)
    op_full.restore_state(full)
    tree_equal(op_chain.snapshot_state(), op_full.snapshot_state())

    tail = (np.arange(50), np.ones(50, np.float32),
            np.full(50, 100, np.int64))
    got_a = collect(feed(op_chain, *tail, wm=5000))
    got_b = collect(feed(op_full, *tail, wm=5000))
    assert got_a == got_b and got_a, "continued fires diverged"


def test_device_tier_restore_digest_identical():
    _restore_digest_identical({})


def test_host_mirror_tier_restore_digest_identical():
    _restore_digest_identical({"emit_tier": "host"})


def test_paged_tier_restore_digest_identical():
    from flink_tpu.state.paging import PagingConfig
    _restore_digest_identical({"paging": PagingConfig(1 << 10),
                               "initial_key_capacity": 1 << 10,
                               "emit_tier": "device"})


def test_mesh_tier_restore_digest_identical():
    """Sharded mesh state: the increment is cut from the dense mirror and
    applies against the DENSIFIED shard-sliced base, so chain restore
    fires identically to a full-snapshot restore (the resolved tree is
    dense — also the rescale interchange; conftest forces host devices)."""
    from flink_tpu.parallel.mesh import make_mesh
    from flink_tpu.parallel.mesh_runtime import MeshWindowAggOperator

    def mk():
        op = MeshWindowAggOperator(TumblingEventTimeWindows.of(1000),
                                   SumAggregator(jnp.float32),
                                   key_column="k", value_column="v",
                                   mesh=make_mesh(2))
        op.open(RuntimeContext())
        op.incremental_state = True
        return op

    op = mk()
    feed(op, np.arange(500), np.ones(500, np.float32),
         np.full(500, 100, np.int64))
    base = cut(op, 1)
    op.notify_checkpoint_complete(1)
    feed(op, np.arange(40), np.ones(40, np.float32),
         np.full(40, 100, np.int64))
    inc = cut(op, 2)
    assert delta.is_increment(inc)
    full = op.snapshot_state()

    op_a, op_b = mk(), mk()
    op_a.restore_state(delta.resolve_chain([base, inc]))
    op_b.restore_state(full)
    got_a = collect(op_a.process_watermark(Watermark(5000)))
    got_b = collect(op_b.process_watermark(Watermark(5000)))
    assert got_a == got_b and len(got_a) == 500


@pytest.mark.chaos
def test_quarantine_then_incremental_cut_digest_identical():
    """A wedged device degrades the tier MID-CHAIN (the degrade path
    drains the device delta first), so the next increment never depends
    on salvaged device state: chain restore stays digest-identical."""
    from flink_tpu.runtime import device_health as dh
    from flink_tpu.runtime.device_health import (DeviceHealthMonitor,
                                                 WatchdogConfig)
    from flink_tpu.testing import chaos as chaos_mod
    from flink_tpu.testing.chaos import WedgedDevice

    prev = dh.get_monitor(create=False)
    cfg = WatchdogConfig(deadline_floor_s=0.25, first_dispatch_grace_s=30.0,
                         backoff_initial_s=0.001, backoff_max_s=0.01,
                         probe_backoff_initial_s=0.02,
                         probe_backoff_max_s=0.1)
    dh.set_monitor(DeviceHealthMonitor(cfg, heal_async=False))
    try:
        op = make_op(emit_tier="device")
        feed(op, np.arange(500), np.ones(500, np.float32),
             np.full(500, 100, np.int64))
        base = cut(op, 1)
        op.notify_checkpoint_complete(1)

        inj = FaultInjector(seed=9)
        inj.inject("device.dispatch", WedgedDevice(at=0))
        with installed(inj):
            feed(op, np.arange(40), np.ones(40, np.float32),
                 np.full(40, 100, np.int64))    # wedge -> degrade, no loss
        assert op._degraded, "the wedge did not degrade the tier"
        inc = cut(op, 2)                        # cut DURING quarantine
        assert delta.is_increment(inc)
        full = op.snapshot_state()
        tree_equal(delta.resolve_chain([base, inc]), full)
        op_r = make_op()
        op_r.restore_state(delta.resolve_chain([base, inc]))
        got = collect(op_r.process_watermark(Watermark(5000)))
        assert len(got) == 500 and got[(7, 0)] == 2.0
    finally:
        dh.set_monitor(prev if prev is not None and prev.healthy else None)
        chaos_mod.uninstall()


@pytest.mark.chaos
def test_slow_disk_on_increment_append_is_latency_only(tmp_path):
    """A SlowDisk schedule on the store path stalls the append but
    corrupts nothing: backpressure, not data loss — the persisted chain
    still resolves digest-identical."""
    from flink_tpu.testing.chaos import SlowDisk
    inj = FaultInjector(seed=5)
    inj.inject("checkpoint.store",
               SlowDisk(max_s=0.01, min_s=0.002, p=1.0, times=8))
    with installed(inj):
        storage, op, full = _op_chain(tmp_path, n_incs=2, retain=10,
                                      max_increments_per_base=10)
        tree_equal(storage.load_latest(), full)
    assert storage.chain_length(storage.checkpoint_ids()[-1]) == 3


def test_increment_covers_unconfirmed_dirt_after_lost_cut():
    """Crash consistency: a cut whose confirmation never arrives (aborted
    checkpoint, lost notify) stays covered — the NEXT increment ships the
    union of all unconfirmed dirt, so resolving base + inc_3 while
    skipping inc_2 entirely still lands on the exact state."""
    batches = _traffic(seed=11)
    op = make_op()
    feed(op, *batches[0])
    base = cut(op, 1)
    op.notify_checkpoint_complete(1)

    feed(op, *batches[1])
    inc2 = cut(op, 2)                     # frozen but NEVER confirmed
    assert delta.is_increment(inc2)
    feed(op, *batches[2])
    inc3 = cut(op, 3)
    assert delta.is_increment(inc3)
    full = op.snapshot_state()

    tree_equal(delta.resolve_chain([base, inc3]), full)    # 2 lost
    tree_equal(delta.resolve_chain([base, inc2, inc3]), full)  # 2 stored


def test_incremental_bytes_scale_with_change_rate():
    """<=10% of keys churning => increment <= 25% of the full snapshot
    (the acceptance budget; the real ratio is far smaller)."""
    n_keys = 20_000
    op = make_op()
    feed(op, np.arange(n_keys), np.ones(n_keys, np.float32),
         np.full(n_keys, 100, np.int64))
    cut(op, 1)
    op.notify_checkpoint_complete(1)
    churn = np.arange(n_keys // 10)       # 10% of the population
    feed(op, churn, np.ones(churn.size, np.float32),
         np.full(churn.size, 100, np.int64))
    inc = cut(op, 2)
    assert delta.is_increment(inc)
    full = op.snapshot_state()
    ratio = delta.state_size(inc) / delta.state_size(full)
    assert ratio <= 0.25, f"increment is {ratio:.1%} of full"


def test_savepoint_stays_full_and_never_advances_the_chain():
    """A savepoint cut mid-chain ships FULL state, and its notify must not
    advance the operator's confirmed base (the savepoint is out-of-band:
    the increment chain in primary storage never saw it)."""
    op = make_op()
    feed(op, np.arange(2000), np.ones(2000, np.float32),
         np.full(2000, 100, np.int64))
    base = cut(op, 1)
    op.notify_checkpoint_complete(1)
    feed(op, np.arange(100), np.ones(100, np.float32),
         np.full(100, 100, np.int64))
    sp = cut(op, 2, incremental=False)    # savepoint: full, self-contained
    assert not delta.is_increment(sp)
    op.notify_checkpoint_complete(2)      # must NOT re-base the chain
    feed(op, np.arange(100, 200), np.ones(100, np.float32),
         np.full(100, 100, np.int64))
    inc = cut(op, 3)
    assert delta.is_increment(inc)
    # inc still applies against checkpoint 1's base — covering the dirt
    # the savepoint cut saw — because confirmation of cid=2 didn't match
    # any frozen incremental cut
    tree_equal(delta.resolve_chain([base, inc]), op.snapshot_state())


def test_rebase_ratio_forces_a_full_cut():
    """Dirt beyond ``incr_rebase_ratio`` of the dense grid re-bases: the
    cut ships full state (an increment that big stops paying)."""
    op = make_op()
    op.incr_rebase_ratio = 0.5
    feed(op, np.arange(1000), np.ones(1000, np.float32),
         np.full(1000, 100, np.int64))
    cut(op, 1)
    op.notify_checkpoint_complete(1)
    feed(op, np.arange(900), np.ones(900, np.float32),
         np.full(900, 100, np.int64))     # 90% churn
    snap = cut(op, 2)
    assert not delta.is_increment(snap), "90% churn must re-base"


def test_resolved_chain_is_dense_rescale_interchange():
    """The resolved tree IS the dense gid-indexed interchange: key-group
    split/merge on it behaves exactly as on a full snapshot."""
    batches = _traffic(seed=23, n_seed=500, churn=60)
    op = make_op()
    feed(op, *batches[0])
    base = cut(op, 1)
    op.notify_checkpoint_complete(1)
    feed(op, *batches[1])
    inc = cut(op, 2)
    assert delta.is_increment(inc)
    resolved = delta.resolve_chain([base, inc])
    tree_equal(resolved, op.snapshot_state())

    parts = WindowAggOperator.split_snapshot(resolved, max_parallelism=128,
                                             new_parallelism=2)
    merged = WindowAggOperator.merge_snapshots(parts)
    op_m, op_w = make_op(), make_op()
    op_m.restore_state(merged)
    op_w.restore_state(resolved)
    tail = (np.arange(60), np.ones(60, np.float32),
            np.full(60, 100, np.int64))
    assert collect(feed(op_m, *tail, wm=5000)) == \
        collect(feed(op_w, *tail, wm=5000))


# ---------------------------------------------------------------------------
# changelog increments
# ---------------------------------------------------------------------------

def _changelog_backend():
    be = ChangelogKeyedStateBackend(HeapKeyedStateBackend(max_parallelism=16))
    st = be.value_state("v", default=0.0)
    return be, st


def test_changelog_suffix_restore_matches_full():
    """Restore(base + changelog-suffix replay) == restore(full snapshot):
    identical replayed backends, identical reads, identical next cut."""
    be, st = _changelog_backend()
    slots = be.key_slots(np.arange(50))
    st.put_rows(slots, np.arange(50.0))
    be.materialize()
    base = be.snapshot()
    be._unconfirmed.append((1, be._epoch, len(be._log)))
    be.notify_checkpoint_complete(1)

    be.set_current_key(7)
    st.update(700.0)
    inc = be.snapshot_increment(2)
    assert inc is not None and inc["kind"] == "changelog"
    be.notify_checkpoint_complete(2)
    be.set_current_key(9)
    st.update(900.0)
    inc3 = be.snapshot_increment(3)
    assert inc3 is not None and int(inc3["log_base"]) > 0
    full = be.snapshot()

    resolved = delta.resolve_chain([base, inc, inc3])
    # restored-vs-restored: replay the chain-resolved and the full
    # snapshot into twin backends and compare state + continued behavior
    be_a, st_a = _changelog_backend()
    be_a.restore(resolved)
    be_b, st_b = _changelog_backend()
    be_b.restore(full)
    for key, want in ((7, 700.0), (9, 900.0), (3, 3.0)):
        be_a.set_current_key(key)
        be_b.set_current_key(key)
        assert st_a.value() == st_b.value() == want
    tree_equal(be_a.snapshot(), be_b.snapshot())


def test_changelog_increment_spans_lost_cut():
    """The suffix is anchored at the CONFIRMED position: an unconfirmed
    cut in between stays covered by the next increment."""
    be, st = _changelog_backend()
    st_slots = be.key_slots(np.arange(10))
    st.put_rows(st_slots, np.zeros(10))
    base = be.snapshot()
    be._unconfirmed.append((1, be._epoch, len(be._log)))
    be.notify_checkpoint_complete(1)
    be.set_current_key(1)
    st.update(11.0)
    assert be.snapshot_increment(2) is not None    # cut 2: LOST (no notify)
    be.set_current_key(2)
    st.update(22.0)
    inc3 = be.snapshot_increment(3)
    resolved = delta.resolve_chain([base, inc3])   # skipping cut 2
    be_r, st_r = _changelog_backend()
    be_r.restore(resolved)
    be_r.set_current_key(1)
    assert st_r.value() == 11.0                    # cut-2 dirt included
    be_r.set_current_key(2)
    assert st_r.value() == 22.0


def test_changelog_materialization_rebases_the_chain():
    """Auto-materialization re-bases: the cut that crossed the threshold
    ships FULL state (epoch changed), and the chain resumes after."""
    be, st = _changelog_backend()
    be.materialize_threshold = 8
    slots = be.key_slots(np.arange(4))
    st.put_rows(slots, np.zeros(4))
    base = be.snapshot()
    be._unconfirmed.append((1, be._epoch, len(be._log)))
    be.notify_checkpoint_complete(1)
    for i in range(10):                    # outgrow the threshold
        be.set_current_key(i % 4)
        st.update(float(i))
    epoch_before = be._epoch
    assert be.snapshot_increment(2) is None        # re-based: full cut
    assert be._epoch == epoch_before + 1
    full2 = be.snapshot()
    be.notify_checkpoint_complete(2)
    be.set_current_key(0)
    st.update(123.0)
    inc3 = be.snapshot_increment(3)                # chain resumes
    assert inc3 is not None
    be_r, st_r = _changelog_backend()
    be_r.restore(delta.resolve_chain([full2, inc3]))
    be_r.set_current_key(0)
    assert st_r.value() == 123.0


# ---------------------------------------------------------------------------
# durable format: chains in IncrementalCheckpointStorage
# ---------------------------------------------------------------------------

def _op_chain(tmp_path, n_incs=3, **storage_kw):
    """An operator driving real cuts into the storage; returns
    (storage, op, full_snapshot_at_end)."""
    storage = IncrementalCheckpointStorage(str(tmp_path), **storage_kw)
    op = make_op()
    feed(op, np.arange(2000), np.ones(2000, np.float32),
         np.full(2000, 100, np.int64))
    storage.store(1, {"w": cut(op, 1)})
    op.notify_checkpoint_complete(1)
    for i in range(2, 2 + n_incs):
        feed(op, np.arange(50), np.ones(50, np.float32),
             np.full(50, 100, np.int64))
        storage.store(i, {"w": cut(op, i)})
        op.notify_checkpoint_complete(i)
    return storage, op, {"w": op.snapshot_state()}


def test_storage_resolves_increment_chains_on_load(tmp_path):
    storage, op, full = _op_chain(tmp_path, n_incs=3, retain=10,
                                  max_increments_per_base=10)
    last = storage.checkpoint_ids()[-1]
    assert storage.metadata(last)["delta"]
    assert storage.chain_length(last) == 4         # base + 3 increments
    tree_equal(storage.load(last), full)
    tree_equal(storage.load_latest(), full)


def test_storage_compaction_rebases_and_keeps_resolving(tmp_path):
    storage, op, full = _op_chain(tmp_path, n_incs=4, retain=10,
                                  max_increments_per_base=2,
                                  compact_in_background=False)
    ids = storage.checkpoint_ids()
    assert storage.compactions >= 1
    rebased = [i for i in ids if storage.metadata(i).get("compacted")]
    assert rebased, "no checkpoint was re-based in place"
    assert storage.chain_length(rebased[-1]) == 1
    # newer increments chain off the compacted base, not the original
    assert storage.chain_length(ids[-1]) <= 1 + (ids[-1] - rebased[-1])
    tree_equal(storage.load(ids[-1]), full)


def test_retention_never_evicts_a_live_chain_base(tmp_path):
    """retain=2 with a 4-long chain: the base and every link a retained
    head resolves through survive eviction."""
    storage, op, full = _op_chain(tmp_path, n_incs=3, retain=2,
                                  max_increments_per_base=10)
    ids = storage.checkpoint_ids()
    assert 1 in ids, "chain base evicted while increments still need it"
    tree_equal(storage.load(ids[-1]), full)


@pytest.mark.chaos
def test_crash_mid_compaction_restores_from_prior_base(tmp_path):
    """A fault at the compaction rewrite leaves the old chain fully
    intact: the atomic-rename publish never happened, restore still
    resolves base + replay."""
    inj = FaultInjector(seed=5)
    inj.inject("checkpoint.compact", FailTimes(1))
    with installed(inj):
        storage, op, full = _op_chain(tmp_path, n_incs=3, retain=10,
                                      max_increments_per_base=2,
                                      compact_in_background=False)
        last = storage.checkpoint_ids()[-1]
        assert storage.compactions == 0            # faulted attempt
        assert storage.metadata(last)["delta"]     # chain untouched
        tree_equal(storage.load(last), full)
        tree_equal(storage.load_latest(), full)


@pytest.mark.chaos
def test_torn_increment_write_falls_back_to_older_base(tmp_path):
    """TruncatedWrite on the increment append: the CRC/size gate detects
    the torn snapshot at load, and load_latest (the restart-recovery
    path) falls back past it to the newest intact checkpoint."""
    storage = IncrementalCheckpointStorage(str(tmp_path), retain=10,
                                           max_increments_per_base=10)
    op = make_op()
    feed(op, np.arange(2000), np.ones(2000, np.float32),
         np.full(2000, 100, np.int64))
    storage.store(1, {"w": cut(op, 1)})
    op.notify_checkpoint_complete(1)
    intact = {"w": op.snapshot_state()}

    inj = FaultInjector(seed=5)
    inj.inject("checkpoint.increment_append", TruncatedWrite(frac=0.4))
    with installed(inj):
        feed(op, np.arange(50), np.ones(50, np.float32),
             np.full(50, 100, np.int64))
        storage.store(2, {"w": cut(op, 2)})        # torn on disk
    with pytest.raises(CorruptCheckpointError):
        storage.load(2)
    tree_equal(storage.load_latest(), intact)      # fell back to cid 1


@pytest.mark.chaos
def test_materialize_fault_point_fires():
    """``checkpoint.materialize`` is a first-class fault point: a fault
    there fails the cut loudly instead of silently shipping a stale log."""
    from flink_tpu.testing.chaos import InjectedFault
    inj = FaultInjector(seed=5)
    inj.inject("checkpoint.materialize", FailTimes(1))
    be, st = _changelog_backend()
    be.materialize_threshold = 2
    be.key_slots(np.arange(4))
    with installed(inj):
        with pytest.raises(InjectedFault):
            be.snapshot_increment(1)               # auto-materialize faults
    assert inj.fired("checkpoint.materialize") == 1


# ---------------------------------------------------------------------------
# task-local state store: increment chains (local recovery)
# ---------------------------------------------------------------------------

def _local_chain(tmp_path):
    store = TaskLocalStateStore(str(tmp_path), worker_index=0)
    op = make_op()
    feed(op, np.arange(1000), np.ones(1000, np.float32),
         np.full(1000, 100, np.int64))
    store.store(1, "w", 0, cut(op, 1))
    op.notify_checkpoint_complete(1)
    feed(op, np.arange(40), np.ones(40, np.float32),
         np.full(40, 100, np.int64))
    inc = cut(op, 2)
    assert delta.is_increment(inc)
    store.store(2, "w", 0, inc)
    op.notify_checkpoint_complete(2)
    return store, op


def test_local_store_resolves_increment_chains(tmp_path):
    store, op = _local_chain(tmp_path)
    tree_equal(store.load(2, "w", 0), op.snapshot_state())


def test_local_store_confirm_keeps_live_chain_bases(tmp_path):
    """confirm(2) must NOT prune chk-1: checkpoint 2 is an increment whose
    chain still walks through 1.  A later full cut releases it."""
    store, op = _local_chain(tmp_path)
    store.confirm(2)
    assert store.checkpoint_ids() == [1, 2]        # base kept
    tree_equal(store.load(2, "w", 0), op.snapshot_state())
    store.store(3, "w", 0, op.snapshot_state())    # full: chain ends
    store.confirm(3)
    assert store.checkpoint_ids() == [3]


def test_local_store_chain_gap_falls_back_to_remote(tmp_path):
    """A pruned/missing link returns None — the restore silently reads
    the coordinator-shipped remote state instead of a wrong resolve."""
    store, op = _local_chain(tmp_path)
    import shutil
    shutil.rmtree(store._chk_dir(1))               # sever the chain
    assert store.load(2, "w", 0) is None


# ---------------------------------------------------------------------------
# end-to-end: MiniCluster under sub-second incremental cuts
# ---------------------------------------------------------------------------

def test_minicluster_incremental_end_to_end(tmp_path):
    """Sparse churn through the full cluster path: sub-second cuts go
    incremental (delta bytes << full bytes in checkpoint stats), chains
    land in the storage, background compaction re-bases, the restore
    interchange stays dense, and exactly-once totals hold."""
    from flink_tpu.cluster.task import TaskStates
    from flink_tpu.datastream.api import StreamExecutionEnvironment
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    rng = np.random.default_rng(7)
    keys = np.concatenate([np.repeat(np.arange(5000), 2),
                           rng.integers(0, 100, 50_000)])
    vals = np.ones(len(keys), np.float32)
    ts = np.full(len(keys), 100, np.int64)
    storage = IncrementalCheckpointStorage(str(tmp_path), retain=4,
                                           max_increments_per_base=4)
    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    sink = (env.from_collection(columns={"k": keys, "v": vals, "t": ts},
                                batch_size=128)
            .assign_timestamps_and_watermarks(0, timestamp_column="t")
            .key_by("k")
            .window(TumblingEventTimeWindows.of(1000))
            .sum("v").collect())
    res = env.execute_cluster(storage=storage, checkpoint_interval_ms=5,
                              incremental=True)
    assert res.state == TaskStates.FINISHED
    stats = env._last_cluster._checkpoint_stats
    incs = [s for s in stats if s.get("incremental")]
    assert incs, f"no incremental cuts in {len(stats)} checkpoints"
    steady = incs[-1]
    assert steady["delta_bytes"] <= 0.25 * steady["state_size_bytes"], \
        steady
    # the durable chain resolves to a dense, increment-free tree
    snap = storage.load_latest()
    assert snap is not None and not delta.tree_has_increment(snap)
    assert sum(r["v"] for r in sink.rows()) == len(keys)   # exactly-once


def test_minicluster_incremental_via_config(tmp_path):
    """``state.backend.incremental: true`` in the job Configuration flips
    the same wiring on (no explicit kwarg)."""
    from flink_tpu.cluster.minicluster import MiniCluster
    from flink_tpu.config.config_option import Configuration
    from flink_tpu.config.options import StateOptions

    config = Configuration()
    config.set(StateOptions.INCREMENTAL, True)
    mc = MiniCluster(config=config)
    assert mc.incremental


@pytest.mark.slow
def test_process_cluster_incremental_end_to_end(tmp_path):
    """The distributed coordinator: ckpt_opts ship the incremental policy
    with deploy, workers ack increment nodes over the wire, the
    coordinator resolves against the previous cut, increment-capable
    storage persists the raw chain."""
    import sys
    import textwrap

    from flink_tpu.cluster.distributed import ProcessCluster

    mod = tmp_path / "incr_job_mod.py"
    mod.write_text(textwrap.dedent('''
        import numpy as np
        from flink_tpu.datastream.api import StreamExecutionEnvironment
        from flink_tpu.windowing.assigners import TumblingEventTimeWindows

        def build():
            rng = np.random.default_rng(7)
            keys = np.concatenate([np.repeat(np.arange(5000), 2),
                                   rng.integers(0, 100, 50_000)])
            vals = np.ones(len(keys), np.float32)
            ts = np.full(len(keys), 100, np.int64)
            env = StreamExecutionEnvironment()
            env.set_parallelism(2)
            (env.from_collection(columns={"k": keys, "v": vals, "t": ts},
                                 batch_size=128)
                .assign_timestamps_and_watermarks(0, timestamp_column="t")
                .key_by("k")
                .window(TumblingEventTimeWindows.of(1000))
                .sum("v").collect())
            return env.get_stream_graph("incr-job")
    '''))
    sys.path.insert(0, str(tmp_path))
    try:
        storage = IncrementalCheckpointStorage(str(tmp_path / "ckpt"),
                                               retain=4,
                                               max_increments_per_base=4)
        pc = ProcessCluster("incr_job_mod:build", n_workers=2,
                            checkpoint_storage=storage,
                            checkpoint_interval_ms=30,
                            incremental=True,
                            extra_sys_path=(str(tmp_path),))
        res = pc.run(timeout_s=240)
        assert res["state"] == "FINISHED", res.get("error")
        incs = [s for s in pc._checkpoint_stats if s.get("incremental")]
        assert incs, pc._checkpoint_stats
        steady = incs[-1]
        assert steady["delta_bytes"] <= 0.25 * steady["state_size_bytes"]
        snap = storage.load_latest()
        assert snap is not None and not delta.tree_has_increment(snap)
        assert sum(r["v"] for r in res["rows"]) == 60_000
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("incr_job_mod", None)
