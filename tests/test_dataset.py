"""DataSet batch API: transforms, grouping, joins, optimizer strategies,
BSP iterations."""

import numpy as np
import pytest

from flink_tpu.dataset import DataSet, ExecutionEnvironment


@pytest.fixture
def env():
    return ExecutionEnvironment.get_execution_environment()


def test_map_filter_project(env):
    out = (env.generate_sequence(1, 10)
           .map(lambda c: {"value": c["value"], "sq": np.asarray(c["value"]) ** 2})
           .filter(lambda c: np.asarray(c["sq"]) % 2 == 0)
           .project("sq")
           .collect())
    assert [r["sq"] for r in out] == [4, 16, 36, 64, 100]


def test_group_by_aggregations(env):
    ds = env.from_columns({"k": [1, 2, 1, 2, 1], "v": [10., 20., 30., 40., 50.]})
    sums = {r["k"]: r["v"] for r in ds.group_by("k").sum("v").collect()}
    assert sums == {1: 90.0, 2: 60.0}
    mins = {r["k"]: r["v"] for r in ds.group_by("k").min("v").collect()}
    assert mins == {1: 10.0, 2: 20.0}
    counts = {r["k"]: r["count"] for r in ds.group_by("k").count().collect()}
    assert counts == {1: 3, 2: 2}


def test_group_by_composite_key(env):
    ds = env.from_columns({"a": [1, 1, 2], "b": [1, 1, 1], "v": [5., 6., 7.]})
    out = {(r["a"], r["b"]): r["v"]
           for r in ds.group_by("a", "b").sum("v").collect()}
    assert out == {(1, 1): 11.0, (2, 1): 7.0}


def test_group_reduce_and_first_n(env):
    ds = env.from_columns({"k": [1, 1, 1, 2], "v": [3., 1., 2., 9.]})
    out = (ds.group_by("k")
           .reduce_group(lambda k, rows: {"k": k, "n": len(rows),
                                          "tot": sum(r["v"] for r in rows)})
           .collect())
    got = {r["k"]: (r["n"], r["tot"]) for r in out}
    assert got == {1: (3, 6.0), 2: (1, 9.0)}
    topn = ds.sort_partition("v").group_by("k").first_n(2).collect()
    assert len(topn) == 3


def test_distinct_sort_first(env):
    ds = env.from_columns({"x": [3, 1, 2, 3, 1]})
    assert sorted(r["x"] for r in ds.distinct().collect()) == [1, 2, 3]
    assert [r["x"] for r in ds.sort_partition("x").first_n(2).collect()] == [1, 1]


def test_inner_join(env):
    users = env.from_columns({"uid": [1, 2, 3], "name": np.asarray(["a", "b", "c"], object)})
    orders = env.from_columns({"uid": [1, 1, 3], "amt": [10., 20., 30.]})
    out = (orders.join(users).where("uid").equal_to("uid").apply().collect())
    got = sorted((r["name"], r["amt"]) for r in out)
    assert got == [("a", 10.0), ("a", 20.0), ("c", 30.0)]


def test_outer_joins(env):
    l = env.from_columns({"k": [1, 2], "lv": [10., 20.]})
    r = env.from_columns({"k": [2, 3], "rv": [200., 300.]})
    left = (l.left_outer_join(r).where("k").equal_to("k").apply().collect())
    assert len(left) == 2
    unmatched = [x for x in left if x["lv"] == 10.0][0]
    assert unmatched["rv"] is None
    full = (l.full_outer_join(r).where("k").equal_to("k").apply().collect())
    assert len(full) == 3


def test_cogroup(env):
    l = env.from_columns({"k": [1, 1, 2], "v": [1., 2., 3.]})
    r = env.from_columns({"k": [2, 3], "w": [9., 8.]})
    out = (l.co_group(r).where("k").equal_to("k")
           .apply(lambda k, lr, rr: {"k": k, "nl": len(lr), "nr": len(rr)})
           .collect())
    got = {r["k"]: (r["nl"], r["nr"]) for r in out}
    assert got == {1: (2, 0), 2: (1, 1), 3: (0, 1)}


def test_cross_and_union(env):
    a = env.from_columns({"x": [1, 2]})
    b = env.from_columns({"y": [10, 20, 30]})
    assert len(a.cross(b).collect()) == 6
    assert len(a.union(a).collect()) == 4


def test_optimizer_broadcast_choice(env):
    big = env.from_columns({"k": np.arange(1000) % 10, "v": np.ones(1000)})
    small = env.from_columns({"k": np.arange(10), "name": np.arange(10)})
    joined = big.join(small).where("k").equal_to("k").apply()
    plan = joined.explain()
    assert "broadcast_hash_right" in plan
    # hint overrides
    hinted = (big.join(small).where("k").equal_to("k")
              .with_hint("sort_merge").apply())
    assert "sort_merge" in hinted.explain()
    assert len(joined.collect()) == 1000


def test_bulk_iteration_converges(env):
    # Newton iteration for sqrt(2) per row
    start = env.from_columns({"x": [1.0, 3.0]})

    def step(ds):
        return ds.map(lambda c: {"x": (np.asarray(c["x"]) + 2 / np.asarray(c["x"])) / 2})

    out = start.iterate(50, step,
                        termination=lambda prev, nxt: bool(
                            np.allclose(np.asarray(prev.column("x")),
                                        np.asarray(nxt.column("x"))))).collect()
    assert np.allclose([r["x"] for r in out], np.sqrt(2))


def test_delta_iteration_connected_components_style(env):
    # min-label propagation on a tiny chain graph 0-1-2, 3-4
    edges = [(0, 1), (1, 2), (3, 4)]
    neighbors = {n: set() for n in range(5)}
    for a, b in edges:
        neighbors[a].add(b)
        neighbors[b].add(a)

    solution = env.from_columns({"v": np.arange(5), "label": np.arange(5)})
    workset = env.from_columns({"v": np.arange(5), "label": np.arange(5)})

    def step(sol_ds, work_ds):
        sol = sol_ds.collect_batch()
        work = work_ds.collect_batch()
        labels = {int(v): int(l) for v, l in
                  zip(np.asarray(sol.column("v")), np.asarray(sol.column("label")))}
        changed = {}
        for v, l in zip(np.asarray(work.column("v")).tolist(),
                        np.asarray(work.column("label")).tolist()):
            for nb in neighbors[v]:
                if l < labels.get(nb, 1 << 30) and l < changed.get(nb, 1 << 30):
                    changed[nb] = l
        env2 = ExecutionEnvironment()
        delta = env2.from_columns(
            {"v": np.asarray(list(changed.keys()), np.int64),
             "label": np.asarray(list(changed.values()), np.int64)})
        return delta, delta

    out = solution.delta_iterate(workset, "v", 10, step).collect()
    labels = {r["v"]: r["label"] for r in out}
    assert labels == {0: 0, 1: 0, 2: 0, 3: 3, 4: 3}


def test_global_agg_and_reduce(env):
    ds = env.from_columns({"v": [1., 2., 3.]})
    assert ds.sum("v").collect()[0]["v"] == 6.0
    assert ds.max("v").collect()[0]["v"] == 3.0
    red = ds.reduce(lambda a, b: {"v": a["v"] + b["v"]}).collect()
    assert red[0]["v"] == 6.0


def test_file_roundtrip(env, tmp_path):
    p = str(tmp_path / "out.csv")
    env.from_columns({"a": [1, 2, 3], "b": [1., 2., 3.]}).write_file(p)
    back = env.read_file(p, format="csv").collect()
    assert [r["a"] for r in back] == [1, 2, 3]


def test_composite_key_no_collision_large_values():
    """Regression: radix packing must stay injective for values near 2^31."""
    env = ExecutionEnvironment()
    ds = env.from_columns({"a": np.array([0, 1], np.int64),
                           "b": np.array([2147483647, 0], np.int64),
                           "v": np.array([1.0, 1.0])})
    out = ds.group_by("a", "b").sum("v").collect()
    assert len(out) == 2     # the two rows are DIFFERENT groups


# ---------------------------------------------------------------------------
# Streamed (pipelined) plan execution — VERDICT r2 #5
# ---------------------------------------------------------------------------

def test_stream_plan_matches_materialized():
    """Every streamed driver must agree with the materialized executor on
    a plan mixing chunkwise ops, dams with streaming kernels (sort,
    distinct, grouped agg) and genuine dams (join)."""
    from flink_tpu.dataset import external

    env = ExecutionEnvironment()
    old = external.memory_budget_rows
    external.memory_budget_rows = lambda: 64   # force many chunks + spills
    try:
        n = 1000
        ds = (env.from_columns({"k": np.arange(n) % 17,
                                "v": np.arange(n, dtype=np.float64)})
              .filter(lambda c: np.asarray(c["v"]) % 3 != 0)
              .map(lambda c: {"k": c["k"], "v": np.asarray(c["v"]) * 2}))
        grouped = ds.group_by("k").sum("v")
        ref = sorted((r["k"], r["v"]) for r in grouped.collect())
        got = sorted((r["k"], r["v"]) for b in grouped.stream_batches()
                     for r in b.to_rows())
        assert got == ref

        cnt = ds.group_by("k").count()
        refc = sorted((r["k"], r["count"]) for r in cnt.collect())
        gotc = sorted((r["k"], r["count"]) for b in cnt.stream_batches()
                      for r in b.to_rows())
        assert gotc == refc

        srt = ds.sort_partition("v", ascending=False).first_n(10)
        assert [r["v"] for b in srt.stream_batches()
                for r in b.to_rows()] == [r["v"] for r in srt.collect()]

        dst = ds.map(lambda c: {"k": c["k"]}).distinct("k")
        assert sorted(r["k"] for b in dst.stream_batches()
                      for r in b.to_rows()) == \
            sorted(r["k"] for r in dst.collect())

        # count() is streaming end-to-end
        assert ds.count() == sum(1 for i in range(n) if i % 3 != 0)
    finally:
        external.memory_budget_rows = old


def test_stream_plan_shared_subplan_materializes_once():
    env = ExecutionEnvironment()
    calls = {"n": 0}

    def spy(cols):
        calls["n"] += 1
        return {"k": cols["k"], "v": cols["v"]}

    base = env.from_columns({"k": np.arange(100) % 5,
                             "v": np.ones(100)}).map(spy)
    joined = base.join(base).where("k").equal_to("k").apply()
    _ = [r for b in joined.stream_batches() for r in b.to_rows()]
    # the shared mapped subplan ran ONCE (diamond memoization), not per side
    assert calls["n"] == 1


@pytest.mark.slow
def test_stream_plan_peak_memory_bounded_by_budget(tmp_path):
    """A 3-operator pipeline over FAR more rows than the budget completes
    with peak RSS bounded: the plan never materializes its input or
    output (sequence -> map -> filter -> count, 40M rows ~ 320MB/column
    if materialized; chunks are budget-sized)."""
    import os
    import subprocess
    import sys
    import textwrap

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # NOTE: VmHWM (per-mm, reset at execve), NOT getrusage ru_maxrss — the
    # latter survives exec, so a child forked from a bloated pytest parent
    # inherits the parent's high-water mark and fails spuriously
    script = textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {root!r})
        from flink_tpu.dataset.api import ExecutionEnvironment
        import numpy as np

        n = 40_000_000
        env = ExecutionEnvironment()
        ds = (env.generate_sequence(1, n)
              .map(lambda c: {{"value": np.asarray(c["value"]) * 2}})
              .filter(lambda c: np.asarray(c["value"]) % 4 == 0))
        assert ds.count() == n // 2
        with open("/proc/self/status") as f:
            hwm_kb = next(int(line.split()[1]) for line in f
                          if line.startswith("VmHWM:"))
        print("PEAK_MB", hwm_kb / 1024)
    """)
    # hermetic child: CPU backend (a TPU client init would pollute the RSS
    # measurement) and an EXPLICIT row budget (another test's leaked
    # FLINK_TPU_BATCH_MEMORY_ROWS must not change what this test bounds)
    child_env = dict(os.environ, JAX_PLATFORMS="cpu",
                     FLINK_TPU_BATCH_MEMORY_ROWS=str(1 << 22))
    out = subprocess.run([sys.executable, "-c", script],
                         capture_output=True, text=True, timeout=300,
                         env=child_env)
    assert "PEAK_MB" in out.stdout, out.stderr
    peak_mb = float(out.stdout.split("PEAK_MB")[1].strip())
    # materialized execution holds >= 3 full int64 columns (~960MB on top
    # of the ~400MB interpreter+jax baseline => >=1.3GB); streamed
    # execution stays near the baseline + budget-sized chunks.  700MB
    # keeps allocator-arena headroom under load while still proving the
    # plan never materialized
    assert peak_mb < 700, peak_mb


def test_stream_plan_empty_result_keeps_schema():
    """Streamed and materialized execution agree on empty results: the
    stream yields one schema-carrying empty batch, count() matches
    len(collect()), and dams over empty inputs see their columns."""
    env = ExecutionEnvironment()
    ds = (env.from_columns({"v": np.arange(10.0)})
          .filter(lambda c: np.asarray(c["v"]) < 0))
    assert ds.count() == 0
    batches = list(ds.stream_batches())
    assert len(batches) == 1 and len(batches[0]) == 0
    assert list(batches[0].columns) == ["v"]
    # a global agg over the empty stream matches collect()
    s = ds.sum("v")
    assert [r for b in s.stream_batches() for r in b.to_rows()] == s.collect()
    # an outer join with an empty side keeps BOTH sides' columns
    right = env.from_columns({"v": np.arange(3.0), "b": np.ones(3)})
    j = ds.full_outer_join(right).where("v").equal_to("v").apply()
    got = sorted(tuple(sorted(r)) for b in j.stream_batches()
                 for r in b.to_rows())
    ref = sorted(tuple(sorted(r)) for r in j.collect())
    assert got == ref and len(ref) == 3
