"""Temporal (versioned-table) and lookup (dimension) joins —
``StreamExecTemporalJoin.java:67`` / ``StreamExecLookupJoin`` analogs.
"""

import numpy as np
import pytest

from flink_tpu.sql.planner import PlanError
from flink_tpu.sql.table_env import TableEnvironment


# ---------------------------------------------------------------------------
# temporal join
# ---------------------------------------------------------------------------


def rates_env(**orders_kw):
    tenv = TableEnvironment()
    tenv.register_collection(
        "orders",
        columns={"cur": np.asarray(["eur", "eur", "usd", "eur"], object),
                 "amount": np.asarray([10.0, 20.0, 30.0, 40.0]),
                 "ts": np.asarray([2, 5, 6, 9], np.int64)},
        batch_size=2, **orders_kw)
    # rate versions: eur 1.1@0, 1.2@4, 1.3@8 ; usd 1.0@0
    tenv.register_collection(
        "rates",
        columns={"cur2": np.asarray(["eur", "usd", "eur", "eur"], object),
                 "rate": np.asarray([1.1, 1.0, 1.2, 1.3]),
                 "rts": np.asarray([0, 0, 4, 8], np.int64)},
        rowtime="rts", batch_size=1)
    return tenv


TEMPORAL_SQL = ("SELECT o.cur, o.amount, r.rate FROM orders o "
                "JOIN rates FOR SYSTEM_TIME AS OF o.ts AS r "
                "ON o.cur = r.cur2")


def test_temporal_join_picks_version_at_rowtime():
    rows = rates_env().execute_sql(TEMPORAL_SQL).collect()
    got = sorted((r["cur"], r["amount"], r["rate"]) for r in rows)
    assert got == [("eur", 10.0, 1.1),   # ts 2 -> version @0
                   ("eur", 20.0, 1.2),   # ts 5 -> version @4
                   ("eur", 40.0, 1.3),   # ts 9 -> version @8
                   ("usd", 30.0, 1.0)]


def test_temporal_left_join_pads_missing_versions():
    tenv = rates_env()
    # an order before ANY version exists for its currency
    tenv.register_collection(
        "orders",
        columns={"cur": np.asarray(["gbp", "eur"], object),
                 "amount": np.asarray([5.0, 10.0]),
                 "ts": np.asarray([3, 3], np.int64)})
    sql = ("SELECT o.cur, o.amount, r.rate FROM orders o "
           "LEFT JOIN rates FOR SYSTEM_TIME AS OF o.ts AS r "
           "ON o.cur = r.cur2")
    rows = tenv.execute_sql(sql).collect()
    got = {(r["cur"], r["rate"]) for r in rows}
    assert got == {("gbp", None), ("eur", 1.1)}


def test_temporal_join_unbounded_is_append_not_changelog():
    tenv = rates_env(bounded=False)
    rows = tenv.execute_sql(TEMPORAL_SQL).collect()
    assert rows and all("op" not in r for r in rows)
    # append output: aggregates over it are legal
    agg = tenv.execute_sql(
        "SELECT SUM(o.amount * r.rate) AS total FROM orders o "
        "JOIN rates FOR SYSTEM_TIME AS OF o.ts AS r ON o.cur = r.cur2"
    ).collect()
    assert agg[0]["total"] == pytest.approx(
        10.0 * 1.1 + 20.0 * 1.2 + 40.0 * 1.3 + 30.0 * 1.0)


def test_temporal_operator_snapshot_restore():
    from flink_tpu.core.batch import RecordBatch, Watermark
    from flink_tpu.operators.sql_ops import TemporalJoinOperator

    def mk():
        return TemporalJoinOperator(
            "cur", "cur2", "ts", "rts", ["cur2", "rate", "rts"],
            {"cur2": "cur2", "rate": "rate", "rts": "rts"}, "inner")

    op = mk()
    op.process_batch2(RecordBatch(
        {"cur2": np.asarray(["eur"], object), "rate": np.asarray([1.1]),
         "rts": np.asarray([0], np.int64)}), 1)
    op.process_batch2(RecordBatch(
        {"cur": np.asarray(["eur"], object), "amount": np.asarray([10.0]),
         "ts": np.asarray([2], np.int64)}), 0)
    snap = op.snapshot_state()

    op2 = mk()
    op2.restore_state(snap)
    op2.process_batch2(RecordBatch(
        {"cur2": np.asarray(["eur"], object), "rate": np.asarray([1.2]),
         "rts": np.asarray([4], np.int64)}), 1)
    out = op2.process_watermark(Watermark(10))
    (b,) = out
    assert np.asarray(b.column("rate")).tolist() == [1.1]  # version @0 for ts2


def test_temporal_version_pruning():
    from flink_tpu.core.batch import RecordBatch, Watermark
    from flink_tpu.operators.sql_ops import TemporalJoinOperator

    op = TemporalJoinOperator("k", "k", "ts", "vts", ["k", "v", "vts"],
                              {}, "inner")
    for vts in (0, 2, 4, 6):
        op.process_batch2(RecordBatch(
            {"k": np.asarray(["a"], object), "v": np.asarray([vts]),
             "vts": np.asarray([vts], np.int64)}), 1)
    # pruning is lazy: probing the key at the watermark cleans its state
    op.process_batch2(RecordBatch(
        {"k": np.asarray(["a"], object), "ts": np.asarray([5], np.int64)}),
        0)
    op.process_watermark(Watermark(5))
    # versions @0 and @2 can never be joined again (valid-at-5 is @4)
    assert op._versions["a"][0] == [4, 6]


# ---------------------------------------------------------------------------
# lookup join
# ---------------------------------------------------------------------------


class CountingLookup:
    def __init__(self, data):
        self.data = data
        self.calls = 0

    def __call__(self, key):
        self.calls += 1
        return self.data.get(key, [])


def test_lookup_join_sql():
    tenv = TableEnvironment()
    tenv.register_collection(
        "orders",
        columns={"pid": np.asarray([1, 2, 1, 3], np.int64),
                 "qty": np.asarray([5, 6, 7, 8], np.int64)},
        batch_size=2)
    lk = CountingLookup({1: [{"id": 1, "label": "ant"}],
                         2: [{"id": 2, "label": "bee"}]})
    tenv.register_lookup_table("dim", lk, ["id", "label"], key_column="id")
    rows = tenv.execute_sql(
        "SELECT o.qty, d.label FROM orders o "
        "JOIN dim FOR SYSTEM_TIME AS OF o.pid AS d ON o.pid = d.id"
    ).collect()
    got = sorted((r["qty"], r["label"]) for r in rows)
    assert got == [(5, "ant"), (6, "bee"), (7, "ant")]   # pid 3: no match
    # cache: pid 1 probed once despite two rows... (distinct keys per batch)
    assert lk.calls <= 3


def test_lookup_left_join_pads():
    tenv = TableEnvironment()
    tenv.register_collection(
        "orders", columns={"pid": np.asarray([9], np.int64),
                           "qty": np.asarray([1], np.int64)})
    tenv.register_lookup_table("dim", CountingLookup({}), ["id", "label"],
                               key_column="id")
    rows = tenv.execute_sql(
        "SELECT o.qty, d.label FROM orders o "
        "LEFT JOIN dim FOR SYSTEM_TIME AS OF o.pid AS d ON o.pid = d.id"
    ).collect()
    assert rows == [{"qty": 1, "label": None}]


def test_lookup_cache_ttl_and_key_validation():
    from flink_tpu.operators.sql_ops import LookupJoinOperator
    from flink_tpu.core.batch import RecordBatch

    lk = CountingLookup({1: [{"id": 1, "v": "x"}]})
    op = LookupJoinOperator("k", lk, ["id", "v"], cache_ttl_ms=10_000)
    b = RecordBatch({"k": np.asarray([1, 1], np.int64)})
    op.process_batch(b)
    op.process_batch(b)
    assert lk.calls == 1                     # served from cache
    # expire the entry
    op._cache[1] = (op._cache[1][0] - 60_000, op._cache[1][1])
    op.process_batch(b)
    assert lk.calls == 2                     # TTL forced a re-probe

    tenv = TableEnvironment()
    tenv.register_collection("o", columns={"x": np.asarray([1], np.int64)})
    tenv.register_lookup_table("dim", lk, ["id", "v"], key_column="id")
    with pytest.raises(PlanError, match="keyed by"):
        tenv.execute_sql(
            "SELECT o.x FROM o "
            "JOIN dim FOR SYSTEM_TIME AS OF o.x AS d ON o.x = d.v").collect()


def test_lookup_table_cannot_be_scanned():
    tenv = TableEnvironment()
    tenv.register_lookup_table("dim", CountingLookup({}), ["id"],
                               key_column="id")
    with pytest.raises(PlanError, match="cannot be scanned"):
        tenv.execute_sql("SELECT id FROM dim").collect()


def test_postgres_lookup_function_end_to_end():
    from flink_tpu.connectors.postgres import (PostgresLookupFunction,
                                               PostgresWireClient,
                                               PostgresWireServer)

    srv = PostgresWireServer()
    try:
        with PostgresWireClient(srv.host, srv.port) as c:
            c.execute("CREATE TABLE products (id int8, label text)")
            c.execute("INSERT INTO products (id, label) VALUES "
                      "(1, 'ant'), (2, 'bee')")
        fn = PostgresLookupFunction(srv.host, srv.port, "products", "id",
                                    columns=["id", "label"])
        tenv = TableEnvironment()
        tenv.register_collection(
            "orders", columns={"pid": np.asarray([2, 1, 9], np.int64),
                               "qty": np.asarray([4, 5, 6], np.int64)})
        tenv.register_lookup_table("products", fn, ["id", "label"],
                                   key_column="id")
        rows = tenv.execute_sql(
            "SELECT o.qty, p.label FROM orders o "
            "LEFT JOIN products FOR SYSTEM_TIME AS OF o.pid AS p "
            "ON o.pid = p.id").collect()
        got = sorted((r["qty"], r["label"]) for r in rows)
        assert got == [(4, "bee"), (5, "ant"), (6, None)]
        fn.close()
    finally:
        srv.close()
