"""Cassandra connector (CassandraSink analog): CQL binary protocol v4
server + client + sink/source."""

import struct

import numpy as np
import pytest

from flink_tpu.connectors.cassandra import (CassandraError, CassandraSink,
                                            CassandraSource, CqlClient,
                                            CqlServer)
from flink_tpu.core.batch import RecordBatch


@pytest.fixture
def srv():
    s = CqlServer()
    yield s
    s.close()


def connect(s):
    return CqlClient(s.host, s.port)


class TestWire:
    def test_startup_create_insert_select(self, srv):
        with connect(srv) as c:
            c.execute("CREATE KEYSPACE ks")
            c.execute("USE ks")
            c.execute("CREATE TABLE t (id bigint PRIMARY KEY, "
                      "name text, score double, ok boolean)")
            c.execute("INSERT INTO t (id, name, score, ok) "
                      "VALUES (1, 'ada', 9.5, true)")
            c.execute("INSERT INTO t (id, name, score, ok) "
                      "VALUES (2, 'bob', 7.25, false)")
            cols, rows = c.execute("SELECT id, name, score, ok FROM t")
            assert [n for n, _t in cols] == ["id", "name", "score", "ok"]
            assert sorted(rows) == [[1, "ada", 9.5, True],
                                    [2, "bob", 7.25, False]]

    def test_upsert_by_primary_key(self, srv):
        with connect(srv) as c:
            c.execute("CREATE KEYSPACE ks")
            c.execute("CREATE TABLE ks.u (id int PRIMARY KEY, v text)")
            c.execute("INSERT INTO ks.u (id, v) VALUES (7, 'first')")
            c.execute("INSERT INTO ks.u (id, v) VALUES (7, 'second')")
            _, rows = c.execute("SELECT v FROM ks.u WHERE id = 7")
            assert rows == [["second"]]       # Cassandra INSERT = upsert
            _, rows = c.execute("SELECT id FROM ks.u")
            assert len(rows) == 1             # no duplicate rows

    def test_partial_insert_merges(self, srv):
        with connect(srv) as c:
            c.execute("CREATE KEYSPACE ks")
            c.execute("CREATE TABLE ks.p (id int PRIMARY KEY, "
                      "a text, b text)")
            c.execute("INSERT INTO ks.p (id, a, b) VALUES (1, 'x', 'y')")
            c.execute("INSERT INTO ks.p (id, b) VALUES (1, 'z')")
            _, rows = c.execute("SELECT a, b FROM ks.p WHERE id = 1")
            assert rows == [["x", "z"]]       # unset columns keep values

    def test_errors_ride_error_frames(self, srv):
        with connect(srv) as c:
            with pytest.raises(CassandraError, match="no keyspace"):
                c.execute("SELECT * FROM nope")
            c.execute("CREATE KEYSPACE ks")
            c.execute("USE ks")
            with pytest.raises(CassandraError, match="does not exist"):
                c.execute("SELECT * FROM nope")
            # the connection SURVIVES errors (stream-level, not fatal)
            c.execute("CREATE TABLE t (id int PRIMARY KEY, v text)")
            c.execute("INSERT INTO t (id, v) VALUES (1, 'a')")
            _, rows = c.execute("SELECT v FROM t")
            assert rows == [["a"]]

    def test_raw_frame_layout(self, srv):
        """A foreign driver's first bytes: v4 STARTUP gets READY with the
        response-direction bit set."""
        import socket as _socket
        from flink_tpu.connectors.cassandra import (OP_READY, OP_STARTUP,
                                                    _frame, _string)
        s = _socket.create_connection((srv.host, srv.port), timeout=5)
        opts = struct.pack(">H", 1) + _string("CQL_VERSION") \
            + _string("3.4.4")
        s.sendall(_frame(0x04, 42, OP_STARTUP, opts))
        hdr = s.recv(9)
        version, _fl, stream, opcode, length = struct.unpack(">BBhBI", hdr)
        assert version == 0x84                # response bit | v4
        assert stream == 42 and opcode == OP_READY and length == 0
        s.close()


class TestConnector:
    def test_sink_flush_on_checkpoint_and_idempotent_replay(self, srv):
        with connect(srv) as c:
            c.execute("CREATE KEYSPACE ks")
            c.execute("CREATE TABLE ks.out (id bigint PRIMARY KEY, "
                      "v double)")

        def run():
            sink = CassandraSink(srv.host, srv.port, "ks.out",
                                 columns=["id", "v"])
            sink.open(None)
            sink.write_batch(RecordBatch(
                {"id": np.asarray([1, 2, 3], np.int64),
                 "v": np.asarray([1.5, 2.5, 3.5])}))
            sink.snapshot_state()             # checkpoint flush
            sink.close()

        run()
        run()                                 # replay: upserts, no dups
        with connect(srv) as c:
            _, rows = c.execute("SELECT id, v FROM ks.out")
        assert sorted(rows) == [[1, 1.5], [2, 2.5], [3, 3.5]]

    def test_source_in_pipeline(self, srv):
        from flink_tpu.datastream.api import StreamExecutionEnvironment

        with connect(srv) as c:
            c.execute("CREATE KEYSPACE ks")
            c.execute("CREATE TABLE ks.n (id bigint PRIMARY KEY, "
                      "k bigint, v double)")
            for i, (k, v) in enumerate([(0, 1.0), (1, 2.0), (0, 3.0)]):
                c.execute(f"INSERT INTO ks.n (id, k, v) "
                          f"VALUES ({i}, {k}, {v})")
        env = StreamExecutionEnvironment()
        rows = (env.from_source(
            CassandraSource(srv.host, srv.port, "ks.n"))
            .key_by("k").sum("v", output_column="total")
            .execute_and_collect())
        finals = {}
        for r in rows:
            finals[r["k"]] = max(r["total"], finals.get(r["k"], 0.0))
        assert finals == {0: 4.0, 1: 2.0}
