"""Metrics system tests (MetricRegistryImplTest / reporter tests analogs)."""

import urllib.request

import numpy as np
import pytest

from flink_tpu.metrics import (Counter, Histogram, LoggingReporter, Meter,
                               MetricRegistry, PrometheusReporter,
                               task_metric_group)


def test_counter_and_group_identifier():
    reg = MetricRegistry()
    g = task_metric_group(reg, "jobA", "window-agg", 0)
    c = g.counter("numRecordsIn")
    c.inc(5)
    c.inc()
    assert c.get_count() == 6
    ident = g.metric_identifier("numRecordsIn")
    assert ident.endswith("jobA.window-agg.0.numRecordsIn")
    assert reg.all_metrics()[ident] is c


def test_group_reuse_and_idempotent_registration():
    reg = MetricRegistry()
    g = task_metric_group(reg, "j", "t", 0)
    assert g.counter("c") is g.counter("c")
    assert g.add_group("user") is g.add_group("user")


def test_meter_rate():
    t = [0.0]
    m = Meter(window_s=60, clock=lambda: t[0])
    m.mark_event(10)
    t[0] = 10.0
    m.mark_event(10)
    assert m.get_rate() == pytest.approx(1.0)
    assert m.get_count() == 20


def test_histogram_bulk_update_and_percentiles():
    h = Histogram(size=1000)
    h.update_all(np.arange(1, 101, dtype=np.float64))
    s = h.get_statistics()
    assert s["count"] == 100
    assert s["min"] == 1.0 and s["max"] == 100.0
    assert s["p50"] == pytest.approx(50.5, abs=1)
    # ring wrap: push more than capacity
    h.update_all(np.full(2000, 7.0))
    s = h.get_statistics()
    assert s["count"] == 2100 and s["max"] == 7.0


def test_reporter_notified_on_registration():
    seen = []

    class Spy(LoggingReporter):
        def notify_of_added_metric(self, metric, name, group):
            seen.append(name)

    reg = MetricRegistry(reporters=[Spy()])
    g = task_metric_group(reg, "j", "t", 0)
    g.counter("a")
    g.meter("b")
    assert seen == ["a", "b"]


def test_prometheus_scrape_text_format():
    reg = MetricRegistry()
    prom = PrometheusReporter(registry=reg)
    g = task_metric_group(reg, "j", "my task!", 0)
    g.counter("numRecordsIn").inc(3)
    g.gauge("watermark", lambda: 42)
    g.histogram("lat").update_all(np.array([1.0, 2.0, 3.0]))
    text = prom.scrape()
    assert "flink_tpu_taskmanager_tm_0_j_my_task__0_numRecordsIn 3" in text
    assert "watermark 42" in text
    assert 'quantile="0.99"' in text


def test_prometheus_http_endpoint():
    reg = MetricRegistry()
    prom = PrometheusReporter(registry=reg)
    g = task_metric_group(reg, "j", "t", 0)
    g.counter("c").inc(9)
    port = prom.start_server(0)
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        assert "_c 9" in body
    finally:
        prom.close()


def test_executor_populates_io_metrics():
    from flink_tpu.datastream.api import StreamExecutionEnvironment
    from flink_tpu.metrics import NUM_RECORDS_IN, NUM_RECORDS_OUT

    env = StreamExecutionEnvironment()
    rows = [{"k": i % 2, "v": float(i)} for i in range(10)]
    (env.from_collection(rows).key_by("k").sum("v").collect())
    env.execute("metrics-job")
    reg = env._last_executor.metric_registry
    all_m = reg.all_metrics()
    ins = {k: v.get_count() for k, v in all_m.items()
           if k.endswith(NUM_RECORDS_IN)}
    outs = {k: v.get_count() for k, v in all_m.items()
            if k.endswith(NUM_RECORDS_OUT)}
    # keyed-reduce vertex saw all 10 records in and emitted 10 running sums
    assert any(v == 10 for v in ins.values()), ins
    assert any(v == 10 for v in outs.values()), outs


def test_statsd_line_protocol_and_udp_push():
    import socket as _socket

    from flink_tpu.metrics import StatsDReporter

    reg = MetricRegistry()
    g = task_metric_group(reg, "j", "t", 0)
    g.counter("recs").inc(7)
    g.gauge("wm", lambda: 12.5)
    srv = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
    srv.bind(("127.0.0.1", 0))
    srv.settimeout(5)
    rep = StatsDReporter("127.0.0.1", srv.getsockname()[1])
    lines = rep.render(reg.all_metrics())
    assert any(l.endswith(".recs.count:7|g") for l in lines), lines
    assert any(l.endswith(".wm.value:12.5|g") for l in lines), lines
    rep.report(reg.all_metrics())
    got = {srv.recvfrom(4096)[0].decode() for _ in lines}
    assert got == set(lines)
    rep.close()
    srv.close()


def test_graphite_plaintext_over_tcp():
    import socket as _socket
    import threading as _threading

    from flink_tpu.metrics import GraphiteReporter

    reg = MetricRegistry()
    g = task_metric_group(reg, "j", "t", 1)
    g.counter("out").inc(3)
    srv = _socket.create_server(("127.0.0.1", 0))
    srv.settimeout(8)
    received = []

    def accept():
        try:
            conn, _ = srv.accept()
            conn.settimeout(5)
            received.append(conn.recv(65536).decode())
            conn.close()
        except OSError:
            pass

    th = _threading.Thread(target=accept, daemon=True)
    th.start()
    rep = GraphiteReporter("127.0.0.1", srv.getsockname()[1])
    lines = rep.render(reg.all_metrics(), now=1700000000)
    assert any(".out.count 3 1700000000" in l for l in lines), lines
    rep.report(reg.all_metrics())
    th.join(5)
    assert received and ".out.count 3 " in received[0]
    rep.close()
    srv.close()


def test_influxdb_line_protocol():
    from flink_tpu.metrics import InfluxDBReporter

    reg = MetricRegistry()
    g = task_metric_group(reg, "j", "my task", 0)
    g.counter("recs").inc(5)
    g.histogram("lat").update_all(np.array([1.0, 2.0, 3.0, 4.0]))
    rep = InfluxDBReporter(tags={"host": "tm 1"})
    lines = rep.render(reg.all_metrics(), now_ns=123)
    # measurement escapes spaces; tags attach; fields group per metric
    recs = [l for l in lines if ".recs," in l or ".recs " in l]
    assert recs and "host=tm\\ 1" in recs[0] and "count=5i" in recs[0]
    lat = [l for l in lines if ".lat" in l][0]
    assert "p99=" in lat and "count=4i" in lat and lat.endswith(" 123")
