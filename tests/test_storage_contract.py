"""Checkpoint-storage behavior contract, run against EVERY implementation.

The reference pins filesystem semantics with a shared behavior suite every
FS implementation must pass (``FileSystemBehaviorTestSuite.java``,
``AbstractHadoopFileSystemITTest``); checkpoint storages here have the
same need: memory, local-FS, object-store, and S3 storages must agree on
round-trip fidelity, ordering, retention, atomic publish, and
missing-checkpoint behavior — a job restored from any of them must see
identical state.  One parametrized suite, four backends.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from flink_tpu.runtime.checkpoint.storage import (FileCheckpointStorage,
                                                  InMemoryCheckpointStorage)


class _Impl:
    """One storage under contract: a factory plus an ``unpublish`` hook
    that destroys checkpoint ``cid``'s publish marker (simulating a
    writer that died mid-store) without touching its data artifacts."""

    name: str

    def make(self, retain: int):
        raise NotImplementedError

    def unpublish(self, storage, cid: int) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class _Memory(_Impl):
    name = "memory"

    def make(self, retain):
        return InMemoryCheckpointStorage(retain=retain)

    def unpublish(self, storage, cid):
        # memory stores publish atomically by dict assignment; the closest
        # analog of a half-written checkpoint is its absence
        storage._store.pop(cid, None)


class _File(_Impl):
    name = "file"

    def __init__(self, tmp):
        self.tmp = tmp

    def make(self, retain):
        return FileCheckpointStorage(str(self.tmp / "ckpt"), retain=retain)

    def unpublish(self, storage, cid):
        from flink_tpu.runtime.checkpoint.storage import METADATA_FILE

        os.remove(os.path.join(storage._dir(cid), METADATA_FILE))


class _ObjectStore(_Impl):
    name = "objectstore"

    def __init__(self, tmp):
        from flink_tpu.runtime.checkpoint.objectstore import ObjectStoreServer

        self.server = ObjectStoreServer(str(tmp / "os")).start()

    def make(self, retain):
        from flink_tpu.runtime.checkpoint.objectstore import (
            ObjectStoreCheckpointStorage)

        return ObjectStoreCheckpointStorage(self.server.url,
                                            prefix="contract/",
                                            retain=retain)

    def unpublish(self, storage, cid):
        storage.client.delete(f"contract/chk-{cid}/_metadata.json")

    def close(self):
        self.server.stop()


class _S3(_Impl):
    name = "s3"

    def __init__(self, tmp):
        from flink_tpu.filesystems.s3 import S3Client, S3CompatibleServer

        self.server = S3CompatibleServer(str(tmp / "s3"),
                                         access_key="AKIA_TEST",
                                         secret_key="secret123").start()
        self.client = S3Client(self.server.url, "ckpts", "AKIA_TEST",
                               "secret123")

    def make(self, retain):
        from flink_tpu.filesystems.s3 import S3CheckpointStorage

        return S3CheckpointStorage(self.server.url, "ckpts", "AKIA_TEST",
                                   "secret123", retain=retain)

    def unpublish(self, storage, cid):
        self.client.delete_object(f"chk-{cid}/_metadata.json")

    def close(self):
        self.server.stop()


@pytest.fixture(params=["memory", "file", "objectstore", "s3"])
def impl(request, tmp_path):
    made = {"memory": _Memory, "file": _File,
            "objectstore": _ObjectStore, "s3": _S3}[request.param]
    obj = made(tmp_path) if request.param != "memory" else made()
    yield obj
    obj.close()


def snap(cid: int):
    return {"op-a": {"x": np.arange(cid, dtype=np.int64),
                     "f": np.float32(cid) / 4},
            "op-b": {"nested": {"y": cid, "z": [cid, cid + 1]}}}


class TestStorageContract:
    def test_round_trip_preserves_numpy_trees(self, impl):
        st = impl.make(retain=3)
        st.store(1, snap(5))
        out = st.load(1)
        assert out["op-a"]["x"].dtype == np.int64
        assert np.array_equal(out["op-a"]["x"], np.arange(5))
        assert out["op-a"]["f"] == np.float32(1.25)
        assert out["op-b"]["nested"]["z"] == [5, 6]

    def test_ids_sorted_and_latest_wins(self, impl):
        st = impl.make(retain=10)
        for cid in (3, 1, 2):
            st.store(cid, snap(cid))
        assert st.checkpoint_ids() == [1, 2, 3]
        assert st.load_latest()["op-b"]["nested"]["y"] == 3

    def test_retention_drops_oldest(self, impl):
        st = impl.make(retain=2)
        for cid in (1, 2, 3):
            st.store(cid, snap(cid))
        assert st.checkpoint_ids() == [2, 3]

    def test_store_same_id_replaces(self, impl):
        st = impl.make(retain=3)
        st.store(1, snap(1))
        st.store(1, snap(9))
        assert np.array_equal(st.load(1)["op-a"]["x"], np.arange(9))
        assert st.checkpoint_ids() == [1]

    def test_empty_storage_has_no_latest(self, impl):
        st = impl.make(retain=3)
        assert st.checkpoint_ids() == []
        assert st.load_latest() is None

    def test_unpublished_checkpoint_is_invisible(self, impl):
        """Metadata-last atomic publish: a checkpoint whose publish marker
        is missing (writer died mid-store) must be invisible to ids and
        load_latest — restoring a half-written checkpoint is corruption."""
        st = impl.make(retain=5)
        st.store(1, snap(1))
        st.store(2, snap(2))
        impl.unpublish(st, 2)
        assert st.checkpoint_ids() == [1]
        assert st.load_latest()["op-b"]["nested"]["y"] == 1

    def test_fresh_instance_sees_published_checkpoints(self, impl):
        """Durability: a NEW storage instance over the same location reads
        what the old one stored (post-crash restore path)."""
        st = impl.make(retain=3)
        st.store(7, snap(7))
        st2 = impl.make(retain=3)
        if isinstance(st, InMemoryCheckpointStorage):
            pytest.skip("memory storage is process-local by design")
        assert st2.checkpoint_ids() == [7]
        assert np.array_equal(st2.load(7)["op-a"]["x"], np.arange(7))
