"""Native WinMirror (C++) vs numpy host-mirror equivalence.

The host emit tier has two implementations of the write-through value
mirror: the fused C++ kernels (``state/native_mirror.py`` over
``native/flink_native.cc`` WinMirror) and the numpy fallback inside
``operators/window_agg.py``.  They must be observationally identical —
same fires, same snapshots, same restore/replay behaviour — across
aggregates, growth, lateness, and sliding panes.  Reference role:
``WindowOperatorTest.java`` golden behaviour, plus the fast-coder
equivalence obligation of ``window_aggregate_fast.pyx``.
"""

import numpy as np
import pytest

from flink_tpu.core.batch import RecordBatch, Watermark
from flink_tpu.core.functions import (AvgAggregator, CountAggregator,
                                      MaxAggregator, MinAggregator,
                                      RuntimeContext, SumAggregator,
                                      TupleAggregator)
from flink_tpu.native import native_available
from flink_tpu.operators.window_agg import WindowAggOperator
from flink_tpu.windowing import (SlidingEventTimeWindows,
                                 TumblingEventTimeWindows)

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native library unavailable")


def _mk(agg, assigner=None, native=True, **kw):
    # TupleAggregator selects its own columns; everything else takes "v"
    vcol = None if isinstance(agg, TupleAggregator) else "v"
    op = WindowAggOperator(
        assigner or TumblingEventTimeWindows.of(100), agg,
        key_column="k", value_column=vcol, emit_tier="host",
        snapshot_source="mirror", native_emit=native, **kw)
    op.open(RuntimeContext())
    return op


def _feed(op, keys, vals, ts, wm=None):
    out = op.process_batch(
        RecordBatch({"k": np.asarray(keys, np.int64),
                     "v": np.asarray(vals, np.float32)},
                    timestamps=np.asarray(ts, np.int64)))
    if wm is not None:
        out += op.process_watermark(Watermark(wm))
    return out


def _rows(outs):
    rows = []
    for b in outs:
        if not hasattr(b, "columns"):
            continue
        cols = {c: np.asarray(b.column(c)) for c in b.columns}
        for i in range(len(b)):
            rows.append(tuple(sorted(
                (c, round(float(v[i]), 4)) for c, v in cols.items())))
    return sorted(rows)


def _random_run(op, seed=0, n_batches=6, n_keys=500, bsz=1000):
    rng = np.random.default_rng(seed)
    out = []
    t = 0
    for _ in range(n_batches):
        keys = rng.integers(0, n_keys, bsz)
        vals = rng.random(bsz).astype(np.float32)
        ts = t + np.sort(rng.integers(0, 120, bsz))
        t += 120
        out += _feed(op, keys, vals, ts, wm=int(ts.max()) - 1)
    out += op.end_input()
    return _rows(out)


AGGS = [
    lambda: SumAggregator(np.float32),
    lambda: MinAggregator(np.float32),
    lambda: MaxAggregator(np.float32),
    lambda: CountAggregator(),
    lambda: AvgAggregator(np.float32),
    lambda: TupleAggregator({"s": ("v", SumAggregator(np.float32)),
                             "m": ("v", MaxAggregator(np.float32))}),
]


@pytest.mark.parametrize("agg_f", AGGS)
def test_fire_equivalence_tumbling(agg_f):
    native = _mk(agg_f())
    assert native._nm is None  # binds on first batch
    fallback = _mk(agg_f(), native=False)
    r_n = _random_run(native)
    r_f = _random_run(fallback)
    assert native._nm is not None, "native mirror did not engage"
    assert fallback._nm is None
    assert r_n == r_f


@pytest.mark.parametrize("agg_f", [
    lambda: SumAggregator(np.float32),   # fast C path (1 f64 add leaf)
    lambda: AvgAggregator(np.float32),   # generic C path (2 leaves)
    lambda: MinAggregator(np.float32),   # non-zero identity across panes
])
def test_fire_equivalence_sliding_panes(agg_f):
    native = _mk(agg_f(), SlidingEventTimeWindows.of(300, 100))
    fallback = _mk(agg_f(), SlidingEventTimeWindows.of(300, 100),
                   native=False)
    assert _random_run(native) == _random_run(fallback)
    assert native._nm is not None


def test_wide_window_many_panes():
    """A window spanning >64 panes must combine EVERY pane (regression for
    a fixed-size pane-table cap in the C fire kernel)."""
    a = SlidingEventTimeWindows.of(1000, 10)  # 100 panes per window
    native = _mk(SumAggregator(np.float32), a)
    fallback = _mk(SumAggregator(np.float32), a, native=False)
    outs = []
    for op in (native, fallback):
        out = []
        # one record in each of 100 panes for key 1
        for i in range(100):
            out += _feed(op, [1], [1.0], [i * 10 + 5])
        out += op.process_watermark(Watermark(999))   # first full window
        outs.append(_rows(out))
    assert native._nm is not None
    assert outs[0] == outs[1]
    # the window [0, 1000) saw all 100 records
    full = [r for r in outs[0]
            if dict(r).get("window_start") == 0.0 and dict(r).get("window_end") == 1000.0]
    assert any(dict(r).get("result") == 100.0 for r in full), full


def test_key_capacity_growth():
    """Inserting far past the initial capacity keeps fires exact."""
    native = _mk(SumAggregator(np.float32), initial_key_capacity=64)
    fallback = _mk(SumAggregator(np.float32), initial_key_capacity=64,
                   native=False)
    r_n = _random_run(native, n_keys=5000, bsz=2000)
    r_f = _random_run(fallback, n_keys=5000, bsz=2000)
    assert r_n == r_f
    assert native.key_index.num_keys > 64


def test_lateness_refire_equivalence():
    kw = dict(allowed_lateness_ms=100)
    outs = []
    for native in (True, False):
        op = _mk(SumAggregator(np.float32), native=native, **kw)
        out = _feed(op, [1, 2], [1.0, 2.0], [10, 20], wm=99)   # fire w0
        out += _feed(op, [1], [5.0], [30], wm=150)             # late, refires
        out += op.process_watermark(Watermark(210))  # past cleanup (99+100)
        out += _feed(op, [1], [9.0], [15])           # beyond lateness: drop
        out += op.end_input()
        outs.append(_rows(out))
        assert op.late_dropped == 1
    assert outs[0] == outs[1]


def test_snapshot_restore_cross_implementation():
    """A mirror-sourced snapshot from the NATIVE path restores into the
    NUMPY path (and vice versa): the snapshot format is implementation-free."""
    for src_native, dst_native in ((True, False), (False, True)):
        src = _mk(SumAggregator(np.float32), native=src_native)
        _feed(src, [1, 2, 3], [1.0, 2.0, 3.0], [10, 20, 30], wm=50)
        _feed(src, [1, 4], [10.0, 4.0], [60, 130])
        snap = src.snapshot_state()
        dst = _mk(SumAggregator(np.float32), native=dst_native)
        dst.restore_state(snap)
        cont_src = _rows(_feed(src, [2], [7.0], [140], wm=2000)
                         + src.end_input())
        cont_dst = _rows(_feed(dst, [2], [7.0], [140], wm=2000)
                         + dst.end_input())
        assert cont_src == cont_dst, (src_native, dst_native)


def test_pane_expiry_drops_native_state():
    op = _mk(SumAggregator(np.float32))
    _feed(op, [1], [1.0], [10], wm=99)
    _feed(op, [1], [1.0], [110], wm=199)
    assert op._nm is not None
    live = op._nm.live_panes()
    assert 0 not in live.tolist()  # pane 0 expired after window 0 fired


def test_device_mirror_consistency_native():
    op = _mk(SumAggregator(np.float32))
    _random_run(op, n_batches=3)
    assert op._nm is not None
    assert op.verify_mirror()


def test_reset_state_unbinds():
    op = _mk(SumAggregator(np.float32))
    _feed(op, [1], [1.0], [10])
    assert op._nm is not None
    op.reset_state()
    assert op._nm is None
    _feed(op, [2], [2.0], [10])
    assert op._nm is not None  # rebinds to the fresh key index
