"""bench.py --check regression gate (VERDICT r3 next #2).

Unit-tests the budget comparison itself, and (slow tier) runs the real
smoke bench under --check so a structural perf regression fails the suite
before the driver sees it — the in-repo answer to the r1->r2 0.84M rec/s
surprise."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bench import check_budget  # noqa: E402


def _result(rps=10e6, p99=10.0, phases=None):
    return {"value": rps, "p99_fire_latency_ms": p99,
            "details": {"phases_ms": phases or {"probe_mirror": 100.0}}}


def _budget(**kw):
    b = {"min_rps": 5e6, "max_p99_ms": 30.0,
         "max_phase_ms": {"probe_mirror": 500.0}}
    b.update(kw)
    return b


def test_check_budget_pass():
    assert check_budget(_result(), _budget()) == []


def test_check_budget_rps_floor():
    viol = check_budget(_result(rps=1e6), _budget())
    assert len(viol) == 1 and "rec/s" in viol[0]


def test_check_budget_p99_ceiling():
    viol = check_budget(_result(p99=45.0), _budget())
    assert len(viol) == 1 and "p99" in viol[0]


def test_check_budget_phase_ceiling():
    viol = check_budget(_result(phases={"probe_mirror": 900.0}), _budget())
    assert len(viol) == 1 and "probe_mirror" in viol[0]


def test_check_budget_unknown_phase_ignored():
    """A budgeted phase absent from the run (e.g. numpy fallback reports
    'probe'+'mirror' instead of 'probe_mirror') is not a violation."""
    b = _budget(max_phase_ms={"probe_mirror": 500.0, "mirror": 400.0})
    assert check_budget(_result(), b) == []


def test_budget_file_shape():
    with open(os.path.join(REPO, "BENCH_BUDGET.json")) as f:
        budget = json.load(f)
    for tier in ("full", "smoke"):
        sec = budget[tier]
        assert sec["min_rps"] > 0
        assert sec["max_p99_ms"] > 0
        assert "probe_mirror" in sec["max_phase_ms"]


@pytest.mark.slow
def test_smoke_bench_passes_gate():
    """The committed budget must hold on this host: run the real smoke
    bench end-to-end under --check."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke",
         "--check"],
        capture_output=True, text=True, timeout=900, cwd=REPO)
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
