"""bench.py --check regression gate (VERDICT r3 next #2).

Unit-tests the budget comparison itself, and (slow tier) runs the real
smoke bench under --check so a structural perf regression fails the suite
before the driver sees it — the in-repo answer to the r1->r2 0.84M rec/s
surprise."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bench import check_budget  # noqa: E402


def _result(rps=10e6, p99=10.0, phases=None, vs_numpy=None, elapsed=None):
    r = {"value": rps, "p99_fire_latency_ms": p99,
         "details": {"phases_ms": phases or {"probe_mirror": 100.0}}}
    if vs_numpy is not None:
        r["vs_numpy_baseline"] = vs_numpy
    if elapsed is not None:
        r["details"]["elapsed_ms"] = elapsed
    return r


def _budget(**kw):
    b = {"min_rps": 5e6, "max_p99_ms": 30.0,
         "max_phase_ms": {"probe_mirror": 500.0}}
    b.update(kw)
    return b


def test_check_budget_pass():
    assert check_budget(_result(), _budget()) == []


def test_check_budget_rps_floor():
    viol = check_budget(_result(rps=1e6), _budget())
    assert len(viol) == 1 and "rec/s" in viol[0]


def test_check_budget_p99_ceiling():
    viol = check_budget(_result(p99=45.0), _budget())
    assert len(viol) == 1 and "p99" in viol[0]


def test_check_budget_phase_ceiling():
    viol = check_budget(_result(phases={"probe_mirror": 900.0}), _budget())
    assert len(viol) == 1 and "probe_mirror" in viol[0]


def test_check_budget_unknown_phase_ignored():
    """A budgeted phase absent from the run (e.g. numpy fallback reports
    'probe'+'mirror' instead of 'probe_mirror') is not a violation."""
    b = _budget(max_phase_ms={"probe_mirror": 500.0, "mirror": 400.0})
    assert check_budget(_result(), b) == []


def test_check_budget_vs_numpy_floor():
    """CPU-forced runs must not lose to flat single-core numpy (the
    acceptance floor of the pipelined hot path)."""
    b = _budget(min_vs_numpy=1.0)
    assert check_budget(_result(vs_numpy=2.05), b) == []
    viol = check_budget(_result(vs_numpy=0.6), b)
    assert len(viol) == 1 and "vs_numpy" in viol[0]
    # results without the field (configN runners) are not violations
    assert check_budget(_result(), b) == []


def test_check_budget_probe_mirror_frac():
    b = _budget(max_probe_mirror_frac=0.85, max_phase_ms={})
    ok = _result(phases={"probe_mirror": 700.0}, elapsed=1000.0)
    assert check_budget(ok, b) == []
    viol = check_budget(
        _result(phases={"probe_mirror": 950.0}, elapsed=1000.0), b)
    assert len(viol) == 1 and "probe_mirror" in viol[0]
    # no elapsed / no probe_mirror phase (numpy fallback): not a violation
    assert check_budget(_result(phases={"probe": 950.0},
                                elapsed=1000.0), b) == []
    assert check_budget(_result(phases={"probe_mirror": 950.0}), b) == []


def test_check_budget_probe_hit_rate_floor():
    """*_device sections gate the device-resident key probe: a hit rate
    under the floor (the table not absorbing the warm-key steady state)
    is a violation — but ONLY when the probe resolved on (auto
    calibration may legitimately pick it off)."""
    b = _budget(min_probe_hit_rate=0.8)
    ok = _result()
    ok["details"]["device_probe"] = "on"
    ok["details"]["probe_hit_rate"] = 0.97
    assert check_budget(ok, b) == []
    bad = _result()
    bad["details"]["device_probe"] = "on"
    bad["details"]["probe_hit_rate"] = 0.4
    viol = check_budget(bad, b)
    assert len(viol) == 1 and "probe_hit_rate" in viol[0]
    # probe calibrated OFF: the floor must not fire
    off = _result()
    off["details"]["device_probe"] = "off"
    off["details"]["probe_hit_rate"] = 0.0
    assert check_budget(off, b) == []
    # no probe fields at all (pre-probe result shapes): not a violation
    assert check_budget(_result(), b) == []


def _mesh_result(rps_pod=4e6, per_shard=(150.0, 120.0), phases=None,
                 ok=True):
    return {"records_per_sec_pod": rps_pod, "ok": ok,
            "details": {"phases_ms": phases or {"probe_mirror": 600.0},
                        "probe_mirror_shard_ms": list(per_shard)}}


def _mesh_budget(**kw):
    b = {"min_rps_pod": 1.5e6, "max_shard_probe_share": 0.85,
         "max_phase_ms": {"probe_mirror": 2000.0}}
    b.update(kw)
    return b


def test_check_mesh_budget_pass():
    from bench import check_mesh_budget
    assert check_mesh_budget(_mesh_result(), _mesh_budget()) == []


def test_check_mesh_budget_pod_floor():
    from bench import check_mesh_budget
    viol = check_mesh_budget(_mesh_result(rps_pod=1e5), _mesh_budget())
    assert len(viol) == 1 and "rec/s/pod" in viol[0]


def test_check_mesh_budget_shard_share_ceiling():
    """A 'sharded' probe whose whole fold sits on one shard is fictional
    sharding — the share ceiling catches it."""
    from bench import check_mesh_budget
    viol = check_mesh_budget(_mesh_result(per_shard=(600.0, 1.0)),
                             _mesh_budget())
    assert len(viol) == 1 and "not\ndecomposed".replace("\n", " ") \
        in viol[0].replace("\n", " ")
    # single-device / serial-probe runs (one live entry) are exempt
    assert check_mesh_budget(_mesh_result(per_shard=(600.0,)),
                             _mesh_budget()) == []
    assert check_mesh_budget(_mesh_result(per_shard=(600.0, 0.0)),
                             _mesh_budget()) == []


def test_check_mesh_budget_replay_and_phase():
    from bench import check_mesh_budget
    viol = check_mesh_budget(_mesh_result(ok=False), _mesh_budget())
    assert any("replay" in v for v in viol)
    viol = check_mesh_budget(
        _mesh_result(phases={"probe_mirror": 9000.0}), _mesh_budget())
    assert any("probe_mirror" in v for v in viol)


def test_mesh_bench_reports_pod_and_per_shard(tmp_path):
    """bench.py --mesh-devices N end-to-end on the forced-host CPU mesh:
    records/sec/pod + records/sec/chip reported, per-shard probe
    breakdown present, restore+replay digests hold, and the committed
    mesh_cpu gate passes at smoke size."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke",
         "--mesh-devices", "2", "--records", "65536", "--keys", "16384",
         "--batch-size", "16384", "--check"],
        capture_output=True, text=True, timeout=600, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["ok"]
    assert result["records_per_sec_pod"] > 0
    assert result["records_per_sec_chip"] * 2 == pytest.approx(
        result["records_per_sec_pod"], rel=1e-6)
    d = result["details"]
    assert d["mesh_devices"] == 2 and d["restore_replay_ok"]
    assert [m["shard"] for m in d["shard_manifest"]] == [0, 1]


def _cep_result(mps=500.0, speedup=10.0, eq=True, auto="vectorized"):
    return {"value": mps, "ok": eq,
            "details": {"speedup_vs_interpreted": speedup,
                        "equivalence_ok": eq, "auto_engine": auto}}


def _cep_budget(**kw):
    b = {"min_matches_per_sec": 150.0, "min_speedup_vs_interpreted": 3.0,
         "min_speedup_smoke": 1.5}
    b.update(kw)
    return b


def test_check_cep_budget_pass():
    from bench import check_cep_budget
    assert check_cep_budget(_cep_result(), _cep_budget()) == []


def test_check_cep_budget_matches_floor_full_only():
    """The matches/sec floor gates FULL runs; smoke is one batch of fixed
    costs and only the relaxed speedup floor applies there."""
    from bench import check_cep_budget
    viol = check_cep_budget(_cep_result(mps=10.0), _cep_budget())
    assert len(viol) == 1 and "matches/sec" in viol[0]
    assert check_cep_budget(_cep_result(mps=10.0), _cep_budget(),
                            smoke=True) == []


def test_check_cep_budget_speedup_floor():
    """The acceptance bar: the batched kernel must beat the interpreted
    NFA by the budgeted factor (3x full, relaxed at smoke)."""
    from bench import check_cep_budget
    viol = check_cep_budget(_cep_result(speedup=2.0), _cep_budget())
    assert len(viol) == 1 and "speedup" in viol[0]
    # the same 2.0x PASSES the relaxed smoke floor...
    assert check_cep_budget(_cep_result(speedup=2.0), _cep_budget(),
                            smoke=True) == []
    # ...but a kernel losing outright fails even at smoke
    viol = check_cep_budget(_cep_result(speedup=0.9), _cep_budget(),
                            smoke=True)
    assert len(viol) == 1 and "speedup" in viol[0]


def test_check_cep_budget_unmeasured_speedup_is_a_violation():
    """An interpreted leg that recorded zero matches leaves the speedup
    None — the acceptance bar must not silently pass as unmeasured."""
    from bench import check_cep_budget
    viol = check_cep_budget(_cep_result(speedup=None), _cep_budget())
    assert any("unmeasured" in v for v in viol)
    viol = check_cep_budget(_cep_result(speedup=None), _cep_budget(),
                            smoke=True)
    assert any("unmeasured" in v for v in viol)


def test_check_cep_budget_equivalence_always_gates():
    """Divergent vectorized-vs-interpreted matches must never exit 0 —
    even at smoke size, even with every perf floor met."""
    from bench import check_cep_budget
    viol = check_cep_budget(_cep_result(eq=False), _cep_budget(),
                            smoke=True)
    assert any("equivalence" in v for v in viol)


def test_cep_bench_smoke_passes_gate():
    """bench.py --cep --smoke --check end-to-end on CPU: the vectorized
    kernel beats the interpreted NFA, auto calibration resolves, matches
    are equivalence-checked, and the committed cep_cpu gate passes."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--cep",
         "--smoke", "--records", "65536", "--keys", "65536",
         "--batch-size", "16384", "--check"],
        capture_output=True, text=True, timeout=600, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    d = result["details"]
    assert result["ok"] and d["equivalence_ok"]
    assert d["auto_engine"] in ("vectorized", "interpreted")
    assert d["partials_high_water"] > 0
    assert d["speedup_vs_interpreted"] is not None
    assert d["degraded"] == 0


def _queryable_result(qps=148_000.0, p99=400.0, lag=1,
                      load_frac=0.94, live_eq=True, bin_eq=True,
                      errors=0, serve_p99=50.0):
    return {"value": qps,
            "details": {"lookups_per_sec": qps, "lookup_p50_ms": 4.5,
                        "lookup_p99_ms": p99,
                        "serve_p50_ms": 2.0, "serve_p99_ms": serve_p99,
                        "protocol": "binary", "routing": "client",
                        "max_replica_lag_checkpoints": lag,
                        "records_per_sec_under_load": 14_000_000.0,
                        "rps_under_load_frac": load_frac,
                        "live_equality_ok": live_eq,
                        "binary_json_equal_ok": bin_eq,
                        "lookup_errors": errors}}


def _queryable_budget():
    return {"min_lookups_per_sec": 100_000, "max_p99_ms": 2500,
            "max_replica_lag_checkpoints": 3,
            "min_rps_under_load_frac": 0.90}


def test_check_queryable_budget_pass():
    from bench import check_queryable_budget
    assert check_queryable_budget(_queryable_result(),
                                  _queryable_budget()) == []


def test_check_queryable_budget_floors_full_only():
    """qps + under-load-rps floors gate FULL runs (smoke is fixed-cost
    dominated); p99/lag ceilings and the equality check gate both."""
    from bench import check_queryable_budget
    viol = check_queryable_budget(_queryable_result(qps=100.0),
                                  _queryable_budget())
    assert len(viol) == 1 and "lookups/sec" in viol[0]
    assert check_queryable_budget(_queryable_result(qps=100.0),
                                  _queryable_budget(), smoke=True) == []
    viol = check_queryable_budget(_queryable_result(load_frac=0.7),
                                  _queryable_budget())
    assert len(viol) == 1 and "taxing the hot path" in viol[0]
    assert check_queryable_budget(_queryable_result(load_frac=0.7),
                                  _queryable_budget(), smoke=True) == []


def test_check_queryable_budget_p99_and_lag_ceilings():
    from bench import check_queryable_budget
    viol = check_queryable_budget(_queryable_result(p99=9000.0),
                                  _queryable_budget(), smoke=True)
    assert len(viol) == 1 and "p99" in viol[0]
    viol = check_queryable_budget(_queryable_result(lag=7),
                                  _queryable_budget(), smoke=True)
    assert len(viol) == 1 and "replica lag" in viol[0]


def test_check_queryable_budget_equality_and_errors_always_gate():
    """Wire values diverging from fire-time values, or lookups failing
    after pooled-client retries, must never exit 0 — even at smoke."""
    from bench import check_queryable_budget
    viol = check_queryable_budget(_queryable_result(live_eq=False),
                                  _queryable_budget(), smoke=True)
    assert any("diverge" in v for v in viol)
    viol = check_queryable_budget(_queryable_result(errors=3),
                                  _queryable_budget(), smoke=True)
    assert any("failed" in v for v in viol)
    # binary==JSON answer equality gates unconditionally too (ISSUE-13)
    viol = check_queryable_budget(_queryable_result(bin_eq=False),
                                  _queryable_budget(), smoke=True)
    assert any("binary" in v for v in viol)
    # an optional server-side serve-p99 ceiling is honored when present
    viol = check_queryable_budget(
        _queryable_result(serve_p99=9_000.0),
        {**_queryable_budget(), "max_serve_p99_ms": 1000}, smoke=True)
    assert any("serve p99" in v for v in viol)


def test_queryable_bench_smoke_passes_gate():
    """bench.py --queryable --smoke --check end-to-end on CPU: batched
    lookups over the real TCP protocol against the running window job,
    live values equal fire-time values, replica fed from the checkpoint
    stream, committed queryable_cpu gate passes."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--queryable",
         "--smoke", "--records", "65536", "--keys", "65536", "--check"],
        capture_output=True, text=True, timeout=600, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    d = result["details"]
    assert result["ok"] and d["live_equality_ok"]
    assert d["binary_json_equal_ok"]
    assert d["lookup_errors"] == 0
    assert d["lookups"] > 0
    assert d["protocol"] == "binary" and d["routing"] == "client"
    assert d["serve_p99_ms"] is not None
    assert d["checkpoints_fed"] >= 1
    assert d["records_per_sec_under_load"] > 0


def _trace_detail(ratio=0.99, hot=20, ckpt=4, lat=1):
    return {"throughput_ratio": ratio, "hot_stage_spans": hot,
            "checkpoint_spans": ckpt, "latency_summaries": lat,
            "spans": hot + ckpt, "dropped_spans": 0}


def test_check_trace_budget_pass():
    from bench import check_trace_budget
    assert check_trace_budget(_trace_detail(),
                              {"min_throughput_ratio": 0.95}) == []


def test_check_trace_budget_throughput_floor():
    """Tracing-on must keep >= the budgeted fraction of tracing-off
    throughput (the <5% overhead acceptance).  Smoke-size runs skip the
    ratio floor only — fixed per-pass costs (compile, first fire)
    dominate a smoke pass and the on/off ratio is pure noise there."""
    from bench import check_trace_budget
    viol = check_trace_budget(_trace_detail(ratio=0.80),
                              {"min_throughput_ratio": 0.95})
    assert len(viol) == 1 and "tracing-on" in viol[0]
    assert check_trace_budget(_trace_detail(ratio=0.80),
                              {"min_throughput_ratio": 0.95},
                              smoke=True) == []
    # structural gates stay on at smoke size
    assert any("hot-stage" in v
               for v in check_trace_budget(_trace_detail(ratio=0.80, hot=0),
                                           {}, smoke=True))


def test_check_trace_budget_structural_checks_always_gate():
    """An artifact without hot-stage spans, checkpoint lifecycle spans or
    a latency summary is not a usable trace — never exit 0 on one."""
    from bench import check_trace_budget
    b = {"min_throughput_ratio": 0.95}
    assert any("hot-stage" in v
               for v in check_trace_budget(_trace_detail(hot=0), b))
    assert any("checkpoint" in v
               for v in check_trace_budget(_trace_detail(ckpt=0), b))
    assert any("latency" in v
               for v in check_trace_budget(_trace_detail(lat=0), b))


def test_trace_artifact_smoke(tmp_path):
    """bench.py --trace end-to-end at smoke size: the artifact is
    Perfetto-shaped trace-event JSON with hot-stage phase spans (the
    operator's own ``_phase`` vocabulary), checkpoint lifecycle spans and
    a latency histogram summary, and the tracing-on/off ratio is
    reported.  (The trace_cpu ratio gate itself runs with --check on the
    full bench — one smoke batch is fixed-cost noise.)"""
    out = tmp_path / "trace.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke",
         "--records", "16384", "--keys", "2048", "--batch-size", "4096",
         "--checkpoint-every", "2", "--trace", str(out)],
        capture_output=True, text=True, timeout=600, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    t = result["details"]["trace"]
    assert t["hot_stage_spans"] > 0 and t["checkpoint_spans"] > 0
    assert t["latency_summaries"] == 1 and t["throughput_ratio"] > 0
    with open(out) as f:
        artifact = json.load(f)
    assert artifact["displayTimeUnit"] == "ms"
    evs = artifact["traceEvents"]
    hot = {e["name"] for e in evs if e.get("cat") == "hot_stage"}
    assert hot and hot <= _operator_phase_names()
    ckpt_names = {e["name"] for e in evs if e.get("cat") == "checkpoint"}
    assert {"checkpoint.trigger", "checkpoint.snapshot",
            "checkpoint"} <= ckpt_names
    assert artifact["otherData"]["latency_histograms"]["window_fire_ms"][
        "samples"] > 0
    # spans are the X/i/M trace-event dialect with µs timestamps
    assert all(e["ph"] in ("X", "i", "M") for e in evs)


# ---------------------------------------------------------------------------
# the fused-megastep gate (bench.py --superbatch --check, ISSUE-11)
# ---------------------------------------------------------------------------

def _fused_result(vs_numpy=2.0, dpb=0.5, eq=True):
    return {"value": 10e6, "vs_numpy_baseline": vs_numpy,
            "details": {"fused": {"enabled": True, "superbatch": 8,
                                  "dispatches_per_batch": dpb,
                                  "equivalence_ok": eq}}}


def _fused_budget(**kw):
    b = {"min_vs_numpy": 1.0, "max_dispatches_per_batch": 1.0}
    b.update(kw)
    return b


def test_check_fused_budget_pass():
    from bench import check_fused_budget
    assert check_fused_budget(_fused_result(), _fused_budget()) == []


def test_check_fused_budget_equivalence_always_gates():
    """Divergent fused-on/off digests must never exit 0 — smoke size,
    missing floors, nothing exempts it."""
    from bench import check_fused_budget
    viol = check_fused_budget(_fused_result(eq=False), {}, smoke=True)
    assert viol and "equivalence" in viol[0]


def test_check_fused_budget_dispatch_ceiling():
    from bench import check_fused_budget
    viol = check_fused_budget(_fused_result(dpb=2.5), _fused_budget())
    assert any("dispatches/batch" in v for v in viol)
    assert check_fused_budget(_fused_result(dpb=1.0), _fused_budget()) == []


def test_check_fused_budget_ceiling_needs_enabled_lane():
    """A run whose fused lane resolved (or was forced) OFF never claimed
    one-dispatch amortization: the per-batch device-probe scatter path is
    structurally 2 dispatches/batch on cold keys (probe + miss update),
    and --superbatch 1 --check must not fail it.  The digest equivalence
    still gates."""
    from bench import check_fused_budget
    r = _fused_result(dpb=2.0)
    r["details"]["fused"]["enabled"] = False
    assert check_fused_budget(r, _fused_budget()) == []
    r["details"]["fused"]["equivalence_ok"] = False
    assert any("equivalence" in v
               for v in check_fused_budget(r, _fused_budget()))


def test_check_fused_budget_vs_numpy_floor_full_only():
    from bench import check_fused_budget
    r = _fused_result(vs_numpy=0.5)
    assert any("vs_numpy" in v
               for v in check_fused_budget(r, _fused_budget()))
    # smoke runs are one batch of fixed costs: the ratio floor is waived,
    # the structural checks are not
    assert check_fused_budget(r, _fused_budget(), smoke=True) == []


def test_superbatch_bench_reports_fused_and_passes_gate(tmp_path):
    """bench.py --smoke --superbatch 4 reports the fused detail block —
    resolved depth, dispatches/batch, scan compile counts, the in-run
    on/off equivalence — and exits 0 under --check.  Default smoke
    geometry on purpose: custom-shrunk geometries flip the sync
    calibration and cannot meet the smoke_cpu rps floor even unfused
    (the same reason the --trace smoke runs without --check)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke",
         "--superbatch", "4", "--check"],
        capture_output=True, text=True, timeout=600, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    fu = result["details"]["fused"]
    assert fu["enabled"] and fu["superbatch"] == 4
    assert fu["equivalence_ok"] is True
    assert fu["staged_batches"] > 0 and fu["flushes"] > 0
    assert fu["dispatches_per_batch"] <= 1.0


def test_budget_file_shape():
    with open(os.path.join(REPO, "BENCH_BUDGET.json")) as f:
        budget = json.load(f)
    for tier in ("full", "smoke"):
        sec = budget[tier]
        assert sec["min_rps"] > 0
        assert sec["max_p99_ms"] > 0
        assert "probe_mirror" in sec["max_phase_ms"]
    # checkpoint-under-backpressure budget (bench.py --checkpoint-interval)
    cb = budget["checkpoint_backpressure"]
    assert cb["max_duration_ms"] > 0 and cb["min_completed"] >= 1
    # the tracing-overhead gate (bench.py --trace --check): tracing-on
    # must keep >= 95% of tracing-off throughput
    tr = budget["trace_cpu"]
    assert 0.95 <= tr["min_throughput_ratio"] <= 1.0
    # CPU-forced full runs carry the pipelined-hot-path acceptance keys
    full_cpu = budget["full_cpu"]
    assert full_cpu["min_vs_numpy"] >= 1.0
    assert 0 < full_cpu["max_probe_mirror_frac"] <= 1.0
    # the full_cpu floor must catch losing the deferred lane (~1.6M rec/s
    # measured scatter fallback on the reference host)
    assert full_cpu["min_rps"] > 2_000_000
    # the mesh gate (bench.py --mesh-devices --check on CPU)
    mesh = budget["mesh_cpu"]
    assert mesh["min_rps_pod"] > 0
    assert 0 < mesh["max_shard_probe_share"] <= 1.0
    assert "probe_mirror" in mesh["max_phase_ms"]
    # the serving-tier gate (bench.py --queryable --check)
    qs = budget["queryable_cpu"]
    assert qs["min_lookups_per_sec"] >= 100_000    # the ISSUE-13 floor
    assert qs["max_p99_ms"] > 0
    assert qs["max_replica_lag_checkpoints"] >= 1
    assert 0.90 <= qs["min_rps_under_load_frac"] < 1.0
    # the vectorized-CEP gate (bench.py --cep --check)
    cep = budget["cep_cpu"]
    assert cep["min_matches_per_sec"] > 0
    assert cep["min_speedup_vs_interpreted"] >= 3.0
    assert 0 < cep["min_speedup_smoke"] <= cep["min_speedup_vs_interpreted"]
    # the fused-megastep gate (bench.py --superbatch --check, ISSUE-11):
    # the one-dispatch claim plus the CPU-tier vs-numpy floor
    fused = budget["fused_cpu"]
    assert fused["max_dispatches_per_batch"] >= 1.0
    assert fused["min_vs_numpy"] >= budget["full_cpu"]["min_vs_numpy"]
    # the scenario-suite gates (bench.py --scenario --check, ISSUE-15):
    # every scenario must demand >= 1 autoscaler reaction; perf floors
    # exist for the full tier (exactly-once gates unconditionally in code)
    for sec in ("scenario_fraud_cpu", "scenario_session_cpu",
                "scenario_feature_cpu"):
        sc = budget[sec]
        assert sc["min_rescales"] >= 1
        assert sc["min_peak_rps"] > 0
        assert sc["max_p99_ms"] > 0
        assert sc["min_lookups_per_sec"] > 0
    # real-accelerator runs gate against the *_device sections (ROADMAP
    # item 2's second half: device rounds regress loudly, like CPU ones)
    for tier in ("full_device", "smoke_device"):
        sec = budget[tier]
        assert sec["min_rps"] > 0 and sec["max_p99_ms"] > 0
        assert 0 < sec["min_probe_hit_rate"] <= 1.0
        assert "device_probe" in sec["max_phase_ms"]
        assert "delta_sync" in sec["max_phase_ms"]


def _operator_phase_names():
    """The operator's ``_phase("...")`` names, scraped from the source —
    the profile artifact's key vocabulary."""
    import re
    src = os.path.join(REPO, "flink_tpu", "operators", "window_agg.py")
    with open(src) as f:
        names = set(re.findall(r"_phase\(\"([a-z_]+)\"\)", f.read()))
    assert names, "no _phase(...) sites found in window_agg.py"
    return names


def test_profile_artifact_produced_and_keys_match(tmp_path):
    """bench.py --profile writes the per-phase JSON artifact (VERDICT #10)
    and its phase keys are exactly the operator's ``_phase`` names (plus
    the bench-level snapshot_total rollup)."""
    out = tmp_path / "profile.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke",
         "--records", "16384", "--keys", "2048", "--batch-size", "4096",
         "--profile", str(out)],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    assert out.exists(), "--profile did not write the artifact"
    with open(out) as f:
        prof = json.load(f)
    allowed = _operator_phase_names() | {"snapshot_total"}
    for section in ("phase_ns", "phases_ms"):
        keys = set(prof[section])
        assert keys <= allowed, f"unknown phase keys: {keys - allowed}"
        assert "probe_mirror" in keys or "probe" in keys
    assert prof["phase_ns"].get("probe_mirror", 0) > 0 or \
        prof["phase_ns"].get("probe", 0) > 0
    assert prof["trace_annotation"] == "window_agg.device_step"
    assert "phase_bytes" in prof and "elapsed_ms" in prof


def test_inject_wedge_smoke_exercises_shared_recovery_path(tmp_path):
    """bench.py --inject-wedge drives the runtime/bench SHARED recovery
    path (device_health watchdog -> quarantine -> degrade -> heal ->
    checkpoint-aligned re-promotion) end-to-end on CPU and exits 0 only
    when the full cycle ran with digest-identical fires."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--inject-wedge"],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["ok"] and result["digest_match"]
    assert result["snapshot_during_quarantine"]
    hs = result["device_health"]
    assert hs["quarantines"] == 1 and hs["heals"] == 1
    assert hs["watchdog_timeouts"] == 1
    assert hs["quarantine_migrations"] == 1 and hs["repromotions"] == 1
    assert hs["state"] == "healthy" and hs["degraded"] == 0


def _incr_result(**kw):
    r = {"ok": True, "n_keys": 1_000_000, "churn_keys": 100_000,
         "incremental_checkpoints": 5, "full_snapshot_bytes": 16_000_000,
         "increment_bytes_max": 1_600_000, "bytes_ratio": 0.10,
         "increments_per_base": 5, "compactions": 0,
         "recovery_ms": 900.0, "digest_match": True}
    r.update(kw)
    return r


def _incr_budget(**kw):
    b = {"max_bytes_ratio": 0.25, "max_recovery_ms": 30000,
         "min_incremental_checkpoints": 1}
    b.update(kw)
    return b


def test_check_incremental_budget_pass():
    from bench import check_incremental_budget
    assert check_incremental_budget(_incr_result(), _incr_budget()) == []


def test_check_incremental_budget_bytes_ratio_ceiling():
    from bench import check_incremental_budget
    viol = check_incremental_budget(_incr_result(bytes_ratio=0.40),
                                    _incr_budget())
    assert len(viol) == 1 and "25%" in viol[0]


def test_check_incremental_budget_digest_always_gates():
    """Digest inequality and zero delta cuts violate even in smoke and
    even with an EMPTY budget section — a delta format that resolves to
    different state or silently re-bases every cut never exits 0."""
    from bench import check_incremental_budget
    viol = check_incremental_budget(_incr_result(digest_match=False), {},
                                    smoke=True)
    assert any("digest" in v for v in viol)
    viol = check_incremental_budget(_incr_result(incremental_checkpoints=0),
                                    {}, smoke=True)
    assert any("re-based" in v for v in viol)


def test_check_incremental_budget_recovery_ceiling_full_only():
    from bench import check_incremental_budget
    res = _incr_result(recovery_ms=90_000.0)
    assert check_incremental_budget(res, _incr_budget(), smoke=True) == []
    viol = check_incremental_budget(res, _incr_budget(), smoke=False)
    assert len(viol) == 1 and "recovery" in viol[0]


def test_checkpoint_incremental_budget_section_present():
    """BENCH_BUDGET.json carries the ISSUE-16 gate with the acceptance
    ceiling: delta bytes <= 25% of full at <=10% churn."""
    with open(os.path.join(REPO, "BENCH_BUDGET.json")) as f:
        sec = json.load(f)["checkpoint_incremental"]
    assert 0 < sec["max_bytes_ratio"] <= 0.25
    assert sec["max_recovery_ms"] > 0
    assert sec["min_incremental_checkpoints"] >= 1


def test_incremental_bench_smoke_passes_gate():
    """The real incremental leg (smoke size) must hold its own budget:
    delta cuts happen, bytes ratio inside the ceiling, chain restore
    digest-identical."""
    from bench import check_incremental_budget, \
        run_incremental_checkpoint_bench
    result = run_incremental_checkpoint_bench(smoke=True)
    with open(os.path.join(REPO, "BENCH_BUDGET.json")) as f:
        budget = json.load(f)["checkpoint_incremental"]
    assert result["ok"], result
    assert check_incremental_budget(result, budget, smoke=True) == []
    assert result["increments_per_base"] >= 1
    assert result["bytes_ratio"] <= budget["max_bytes_ratio"]


def test_checkpoint_interval_completes_within_budget_under_backpressure():
    """bench.py --checkpoint-interval injects SlowConsumer + SlowDisk
    backpressure and asserts checkpoints (aligned-with-timeout escalation
    enabled) still complete within the checkpoint_backpressure budget,
    reporting duration + persisted in-flight bytes — exits 0 only when a
    checkpoint completed in budget with exactly-once sums."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--checkpoint-interval", "50"],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["ok"] and result["exactly_once"]
    with open(os.path.join(REPO, "BENCH_BUDGET.json")) as f:
        budget_all = json.load(f)
    budget = budget_all["checkpoint_backpressure"]
    assert result["completed_checkpoints"] >= budget["min_completed"]
    assert result["max_duration_ms"] <= budget["max_duration_ms"]
    # backpressure was REAL (the chaos schedules actually persisted
    # in-flight data) — otherwise the run proves nothing
    assert result["unaligned_checkpoints"] >= 1
    assert result["persisted_inflight_bytes_total"] > 0
    # the ISSUE-16 incremental leg rides the same flag: delta cuts land,
    # chain restore is digest-identical, bytes ratio inside the ceiling
    inc = result["incremental"]
    assert inc["digest_match"] and inc["incremental_checkpoints"] >= 1
    assert inc["bytes_ratio"] <= budget_all["checkpoint_incremental"][
        "max_bytes_ratio"]


@pytest.mark.slow
def test_smoke_bench_passes_gate():
    """The committed budget must hold on this host: run the real smoke
    bench end-to-end under --check."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke",
         "--check"],
        capture_output=True, text=True, timeout=900, cwd=REPO)
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])


# ---------------------------------------------------------------------------
# --autoscale (ISSUE-14): reactive autoscaler under a diurnal load curve
# ---------------------------------------------------------------------------

def _rescale_result(state="Finished", lost=0, dup=0, rescales=2,
                    rollbacks=0, latency=1500.0, recovery=8000.0):
    return {"state": state, "records_lost": lost,
            "records_duplicated": dup, "rescales": rescales,
            "rollbacks": rollbacks, "rescale_latency_ms": latency,
            "recovery_ms": recovery}


def _rescale_budget(**kw):
    b = {"min_rescales": 1, "max_rollbacks": 0,
         "max_rescale_latency_ms": 20000, "max_recovery_ms": 60000}
    b.update(kw)
    return b


def test_check_rescale_budget_pass():
    from bench import check_rescale_budget
    assert check_rescale_budget(_rescale_result(), _rescale_budget()) == []


def test_check_rescale_budget_exactly_once_always_gates():
    """Lost/duplicated records and a non-finished job violate even with an
    EMPTY budget section — a lossy rescale must never exit 0 because no
    perf ceiling was configured."""
    from bench import check_rescale_budget
    assert any("records_lost" in v
               for v in check_rescale_budget(_rescale_result(lost=3), {}))
    assert any("records_duplicated" in v
               for v in check_rescale_budget(_rescale_result(dup=1), {}))
    assert any("did not finish" in v
               for v in check_rescale_budget(
                   _rescale_result(state="Failed"), {}))


def test_check_rescale_budget_floors_and_ceilings():
    from bench import check_rescale_budget
    b = _rescale_budget()
    assert any("rescales" in v for v in check_rescale_budget(
        _rescale_result(rescales=0), b))
    assert any("rollbacks" in v for v in check_rescale_budget(
        _rescale_result(rollbacks=1), b))
    assert any("rescale latency" in v for v in check_rescale_budget(
        _rescale_result(latency=30000.0), b))
    assert any("recovery" in v for v in check_rescale_budget(
        _rescale_result(recovery=90000.0), b))
    # recovery ceiling is full-run only (smoke streams are too short for
    # a meaningful drain measurement)
    assert check_rescale_budget(_rescale_result(recovery=90000.0), b,
                                smoke=True) == []


def _scenario_result(state="Finished", control="Finished", lost=0, dup=0,
                     digest=True, rescales=2, rollbacks=0, cross=(),
                     committed=None, peak=2500.0, p99=5000.0, lps=400.0):
    return {"scenario": "fraud_detection", "state": state,
            "control_state": control, "records_lost": lost,
            "records_duplicated": dup, "digest_match": digest,
            "rescales": rescales, "rollbacks": rollbacks,
            "cross_check_violations": list(cross),
            "committed_rows": committed if committed is not None
            else {"alerts": 575},
            "peak_records_per_sec": peak, "latency_p99_ms": p99,
            "queryable": {"lookups_per_sec": lps}}


def _scenario_budget(**kw):
    b = {"min_rescales": 1, "min_peak_rps": 1000, "max_p99_ms": 30000,
         "min_lookups_per_sec": 60}
    b.update(kw)
    return b


def test_check_scenario_budget_pass():
    from bench import check_scenario_budget
    assert check_scenario_budget(_scenario_result(),
                                 _scenario_budget()) == []


def test_check_scenario_budget_exactly_once_always_gates():
    """Lost/duplicated/digest-mismatch/cross-check/no-output violate even
    with an EMPTY budget section and in smoke — a lossy scenario must
    never exit 0 because no perf floor was configured."""
    from bench import check_scenario_budget
    assert any("records_lost" in v for v in check_scenario_budget(
        _scenario_result(lost=3), {}, smoke=True))
    assert any("records_duplicated" in v for v in check_scenario_budget(
        _scenario_result(dup=1), {}, smoke=True))
    assert any("digest" in v for v in check_scenario_budget(
        _scenario_result(digest=False), {}, smoke=True))
    assert any("did not finish" in v for v in check_scenario_budget(
        _scenario_result(state="Failed"), {}, smoke=True))
    assert any("control" in v for v in check_scenario_budget(
        _scenario_result(control="Canceled"), {}, smoke=True))
    assert any("TUMBLE" in v for v in check_scenario_budget(
        _scenario_result(cross=["SQL TUMBLE cross-check: diverged"]), {},
        smoke=True))
    assert any("no committed output" in v for v in check_scenario_budget(
        _scenario_result(committed={"alerts": 0}), {}, smoke=True))


def test_check_scenario_budget_floors_and_ceilings():
    from bench import check_scenario_budget
    b = _scenario_budget()
    assert any("rescales" in v for v in check_scenario_budget(
        _scenario_result(rescales=0), b))
    assert any("peak" in v for v in check_scenario_budget(
        _scenario_result(peak=100.0), b))
    assert any("p99" in v for v in check_scenario_budget(
        _scenario_result(p99=60000.0), b))
    assert any("queryable" in v for v in check_scenario_budget(
        _scenario_result(lps=1.0), b))
    assert any("rollbacks" in v for v in check_scenario_budget(
        _scenario_result(rollbacks=2), _scenario_budget(max_rollbacks=0)))
    # perf floors are full-run only; exactly-once still gates in smoke
    assert check_scenario_budget(
        _scenario_result(peak=100.0, p99=60000.0, lps=1.0), b,
        smoke=True) == []


@pytest.mark.slow
def test_scenario_bench_smoke_passes_gate(tmp_path):
    """bench.py --scenario fraud_detection --smoke --check end-to-end on
    CPU: the fraud scenario survives its peak nemeses exactly-once
    (digest == unfaulted control), the autoscaler reacts on the curve,
    and the committed scenario_fraud_cpu gate passes."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--scenario", "fraud_detection", "--smoke", "--records", "30000",
         "--check"],
        capture_output=True, text=True, timeout=900, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-3000:])
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["ok"]
    (s,) = result["scenarios"]
    assert s["scenario"] == "fraud_detection"
    assert s["state"] == "Finished" and s["control_state"] == "Finished"
    assert s["records_lost"] == 0 and s["records_duplicated"] == 0
    assert s["digest_match"] and s["rescales"] >= 1
    assert s["committed_rows"]["alerts"] > 0
    assert s["queryable"]["lookups"] > 0


def test_autoscale_bench_smoke_passes_gate():
    """bench.py --autoscale --smoke --check end-to-end on CPU: the
    autoscaler reacts to the diurnal curve (>= 1 rescale via an unaligned
    cut + channel-state redistribution) with ZERO records lost or
    duplicated, and the committed rescale_cpu gate passes."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--autoscale",
         "--smoke", "--records", "80000", "--check"],
        capture_output=True, text=True, timeout=600, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["ok"] and result["state"] == "Finished"
    assert result["records_lost"] == 0
    assert result["records_duplicated"] == 0
    assert result["rescales"] >= 1
    assert max(result["parallelism_path"]) >= 4
    assert result["rescale_latency_ms"] is not None


def _ha_result(state="FINISHED", control="Finished", epochs=(1, 2),
               pointer_fenced=True, commit_fenced=True, lost=0, dup=0,
               digest=True, committed=None, recovery=4000.0):
    return {"scenario": "fraud_detection", "state": state,
            "control_state": control, "leader_epochs": list(epochs),
            "stale_pointer_rejected": pointer_fenced,
            "stale_commit_fenced": commit_fenced,
            "records_lost": lost, "records_duplicated": dup,
            "digest_match": digest,
            "committed_rows": committed if committed is not None
            else {"alerts": 575},
            "recovery_ms": recovery}


def _ha_budget(**kw):
    b = {"max_recovery_ms": 30000}
    b.update(kw)
    return b


def test_check_ha_budget_pass():
    from bench import check_ha_budget
    assert check_ha_budget(_ha_result(), _ha_budget()) == []


def test_check_ha_budget_fencing_and_exactly_once_always_gate():
    """A zombie completing a checkpoint or committing a 2PC transaction,
    a non-advancing epoch, lost/duplicated rows, a digest mismatch or no
    output violate even with an EMPTY budget section and in smoke — a
    split-brain run must never exit 0 because no ceiling was
    configured."""
    from bench import check_ha_budget
    assert any("NOT fenced by the HA store" in v for v in check_ha_budget(
        _ha_result(pointer_fenced=False), {}, smoke=True))
    assert any("2PC" in v for v in check_ha_budget(
        _ha_result(commit_fenced=False), {}, smoke=True))
    assert any("leader epoch" in v for v in check_ha_budget(
        _ha_result(epochs=(1, 1)), {}, smoke=True))
    assert any("leader epoch" in v for v in check_ha_budget(
        _ha_result(epochs=(2,)), {}, smoke=True))
    assert any("records_lost" in v for v in check_ha_budget(
        _ha_result(lost=3), {}, smoke=True))
    assert any("records_duplicated" in v for v in check_ha_budget(
        _ha_result(dup=1), {}, smoke=True))
    assert any("digest" in v for v in check_ha_budget(
        _ha_result(digest=False), {}, smoke=True))
    assert any("did not finish" in v for v in check_ha_budget(
        _ha_result(state="FAILED"), {}, smoke=True))
    assert any("control" in v for v in check_ha_budget(
        _ha_result(control="Canceled"), {}, smoke=True))
    assert any("no committed output" in v for v in check_ha_budget(
        _ha_result(committed={"alerts": 0}), {}, smoke=True))


def test_check_ha_budget_recovery_ceiling_full_only():
    from bench import check_ha_budget
    b = _ha_budget(max_recovery_ms=1000)
    assert any("recovery" in v for v in check_ha_budget(
        _ha_result(recovery=5000.0), b))
    # smoke hosts jitter too much for a wall-clock gate
    assert check_ha_budget(_ha_result(recovery=5000.0), b,
                           smoke=True) == []


def test_ha_budget_section_present():
    with open(os.path.join(REPO, "BENCH_BUDGET.json")) as f:
        budget = json.load(f)
    ha = budget["ha_cpu"]
    assert ha["max_recovery_ms"] > 0


@pytest.mark.slow
def test_ha_kill_bench_smoke_passes_gate():
    """bench.py --ha-kill --smoke --check end-to-end on CPU: the leader
    is killed at the peak and runs on as a zombie, the standby takes
    over at epoch+1, both stale-epoch fences hold, and the committed
    ha_cpu gate passes with a digest identical to the control."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--ha-kill",
         "--smoke", "--check"],
        capture_output=True, text=True, timeout=600, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-3000:])
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["ok"]
    res = result["ha_kill"]
    assert res["state"] == "FINISHED"
    assert res["control_state"] == "Finished"
    assert res["leader_epochs"][1] == res["leader_epochs"][0] + 1
    assert res["stale_pointer_rejected"] and res["stale_commit_fenced"]
    assert res["records_lost"] == 0 and res["records_duplicated"] == 0
    assert res["digest_match"]
    assert res["restore_source"] == "ha-pointer"
