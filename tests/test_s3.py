"""Real S3 protocol (VERDICT r2 #4): AWS Signature V4 signing verified
against the AWS-published example vector, a path-style S3 REST client and
an S3-compatible server that any ecosystem client can point at, and the
checkpoint-storage seam over the dialect.

Environment note: this image has no third-party S3 server (no MinIO, no
boto3) and no network egress, so ground truth for protocol correctness is
(a) the AWS documentation's published signing vector (independent of this
repo's code) and (b) raw hand-constructed HTTP requests that bypass the
client class entirely.
"""

import hashlib
import urllib.error
import urllib.request

import numpy as np
import pytest

from flink_tpu.filesystems import (S3Client, S3CompatibleServer, sign_v4)


# ---------------------------------------------------------------------------
# known-answer test: the AWS documentation's SigV4 example
# ---------------------------------------------------------------------------

def test_sigv4_aws_documented_example_vector():
    """The exact worked example from the AWS 'Signature Version 4 signing
    process' documentation (IAM ListUsers, 20150830) — an independent
    ground truth for the signer."""
    headers = sign_v4(
        "GET",
        "https://iam.amazonaws.com/?Action=ListUsers&Version=2010-05-08",
        {"host": "iam.amazonaws.com",
         "content-type": "application/x-www-form-urlencoded; charset=utf-8"},
        hashlib.sha256(b"").hexdigest(),
        access_key="AKIDEXAMPLE",
        secret_key="wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY",
        region="us-east-1", service="iam",
        amz_date="20150830T123600Z")
    auth = headers["Authorization"]
    assert auth == (
        "AWS4-HMAC-SHA256 "
        "Credential=AKIDEXAMPLE/20150830/us-east-1/iam/aws4_request, "
        "SignedHeaders=content-type;host;x-amz-date, "
        "Signature=5d672d79c15b13162d9279b0855cfba6789a8edb4c82"
        "c400e06b5924a6f2b5d7")


# ---------------------------------------------------------------------------
# client <-> server over the real dialect
# ---------------------------------------------------------------------------

@pytest.fixture
def s3(tmp_path):
    srv = S3CompatibleServer(str(tmp_path / "s3"), access_key="AKIA_TEST",
                             secret_key="secret123").start()
    yield srv
    srv.stop()


def test_put_get_list_delete_roundtrip(s3):
    c = s3.client("data")
    c.put_object("a/1.bin", b"hello")
    c.put_object("a/2.bin", b"world!")
    c.put_object("b/3.bin", b"x")
    assert c.get_object("a/2.bin") == b"world!"
    objs = c.list_objects("a/")
    assert [o["key"] for o in objs] == ["a/1.bin", "a/2.bin"]
    assert [o["size"] for o in objs] == [5, 6]
    c.delete_object("a/1.bin")
    assert c.list_keys("a/") == ["a/2.bin"]
    with pytest.raises(urllib.error.HTTPError) as ei:
        c.get_object("a/1.bin")
    assert ei.value.code == 404


def test_list_objects_v2_pagination(s3):
    s3.MAX_KEYS = 7      # force continuation tokens
    c = s3.client("pager")
    for i in range(23):
        c.put_object(f"k{i:03d}", b"v")
    keys = c.list_keys("k")
    assert keys == [f"k{i:03d}" for i in range(23)]


def test_signature_rejections(s3):
    good = s3.client("sec")
    good.put_object("k", b"v")
    # wrong secret -> SignatureDoesNotMatch
    bad = S3Client(s3.url, "sec", "AKIA_TEST", "WRONG")
    with pytest.raises(urllib.error.HTTPError) as ei:
        bad.get_object("k")
    assert ei.value.code == 403
    # unknown access key
    bad2 = S3Client(s3.url, "sec", "AKIA_NOPE", "secret123")
    with pytest.raises(urllib.error.HTTPError) as ei:
        bad2.get_object("k")
    assert ei.value.code == 403
    # unsigned request
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(f"{s3.url}/sec/k", timeout=5)
    assert ei.value.code == 403
    # signed payload hash must MATCH the body (tamper detection)
    body = b"tampered"
    url = f"{s3.url}/sec/k2"
    host = url.split("//")[1].split("/")[0]
    wrong_hash = hashlib.sha256(b"original").hexdigest()
    headers = sign_v4("PUT", url,
                      {"host": host, "x-amz-content-sha256": wrong_hash},
                      wrong_hash, "AKIA_TEST", "secret123", "us-east-1")
    req = urllib.request.Request(url, data=body, method="PUT",
                                 headers=headers)
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=5)
    assert ei.value.code == 400          # XAmzContentSHA256Mismatch
    # stale x-amz-date -> RequestTimeTooSkewed
    old_hash = hashlib.sha256(b"").hexdigest()
    headers = sign_v4("GET", f"{s3.url}/sec/k",
                      {"host": host, "x-amz-content-sha256": old_hash},
                      old_hash, "AKIA_TEST", "secret123", "us-east-1",
                      amz_date="20200101T000000Z")
    req = urllib.request.Request(f"{s3.url}/sec/k", headers=headers)
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=5)
    assert ei.value.code == 403


def test_raw_http_client_independence(s3):
    """A hand-constructed request (urllib + sign_v4 only — no S3Client)
    interoperates with the server, and the server's responses parse as the
    documented XML dialect."""
    import xml.etree.ElementTree as ET

    url = f"{s3.url}/raw/path%20with%20space.txt"
    host = url.split("//")[1].split("/")[0]
    body = b"raw bytes"
    h = hashlib.sha256(body).hexdigest()
    headers = sign_v4("PUT", url, {"host": host,
                                   "x-amz-content-sha256": h},
                      h, "AKIA_TEST", "secret123", "us-east-1")
    urllib.request.urlopen(urllib.request.Request(
        url, data=body, method="PUT", headers=headers), timeout=5).read()

    lh = hashlib.sha256(b"").hexdigest()
    lurl = f"{s3.url}/raw?list-type=2&prefix="
    headers = sign_v4("GET", lurl, {"host": host,
                                    "x-amz-content-sha256": lh},
                      lh, "AKIA_TEST", "secret123", "us-east-1")
    with urllib.request.urlopen(urllib.request.Request(
            lurl, headers=headers), timeout=5) as r:
        root = ET.fromstring(r.read())
    assert root.tag.endswith("ListBucketResult")
    ns = root.tag.split("}")[0] + "}"
    keys = [c.findtext(f"{ns}Key") for c in root.findall(f"{ns}Contents")]
    assert keys == ["path with space.txt"]


# ---------------------------------------------------------------------------
# the checkpoint seam over S3
# ---------------------------------------------------------------------------

def test_s3_checkpoint_storage_roundtrip(s3):
    from flink_tpu.filesystems.s3 import S3CheckpointStorage

    st = S3CheckpointStorage(s3.url, "ckpts", "AKIA_TEST", "secret123",
                             retain=2)
    for cid in (1, 2, 3):
        st.store(cid, {"op-a": {"x": np.arange(cid)},
                       "op-b": {"y": cid}})
    assert st.checkpoint_ids() == [2, 3]         # retention pruned cid 1
    snap = st.load_latest()
    assert snap["op-b"]["y"] == 3
    assert np.array_equal(snap["op-a"]["x"], np.arange(3))


def test_s3_backs_a_streaming_job_checkpoint(s3):
    """A real pipeline checkpoints THROUGH the S3 protocol and restores
    from it — the object-store seam speaking the ecosystem dialect."""
    from flink_tpu.datastream.api import StreamExecutionEnvironment
    from flink_tpu.filesystems.s3 import S3CheckpointStorage

    st = S3CheckpointStorage(s3.url, "jobs", "AKIA_TEST", "secret123")
    env = StreamExecutionEnvironment()
    env.enable_checkpointing(20, storage=st)
    n = 4000
    res = (env.from_collection(
                columns={"k": (np.arange(n) % 5).astype(np.int64),
                         "v": np.ones(n)}, batch_size=64)
           .key_by("k").sum("v", output_column="total").collect())
    env.execute()
    finals = {}
    for r in res.rows():
        finals[int(r["k"])] = max(finals.get(int(r["k"]), 0.0),
                                  float(r["total"]))
    assert finals == {k: float(n // 5) for k in range(5)}
    assert st.checkpoint_ids(), "at least one checkpoint reached the bucket"
    snap = st.load_latest()
    assert snap


def test_path_traversal_and_head_auth_rejected(s3):
    """Security regressions: dot-segment buckets/keys are rejected (no
    escape from the served directory) and HEAD requires SigV4 like every
    other verb."""
    c = s3.client("..")
    with pytest.raises(urllib.error.HTTPError) as ei:
        c.put_object("pwn", b"outside!")
    assert ei.value.code == 400
    c2 = s3.client("ok")
    with pytest.raises(urllib.error.HTTPError) as ei:
        c2.put_object("..", b"x")
    assert ei.value.code == 400
    # unauthenticated HEAD must not disclose existence/size
    c2.put_object("secret", b"12345")
    req = urllib.request.Request(f"{s3.url}/ok/secret", method="HEAD")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=5)
    assert ei.value.code == 403
    # malformed Credential scope -> 403, never a 500
    req = urllib.request.Request(
        f"{s3.url}/ok/secret",
        headers={"Authorization": "AWS4-HMAC-SHA256 Credential=AKIA_TEST, "
                                  "SignedHeaders=host, Signature=x",
                 "x-amz-date": "20990101T000000Z"})
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=5)
    assert ei.value.code == 403


def test_tmp_suffix_keys_and_bucket_delete(s3):
    """Review regressions: keys ending in '.tmp' are first-class objects
    (no temp-file collision, listed normally); DeleteBucket follows the
    S3 contract (204 when empty, 409 BucketNotEmpty otherwise); list
    entries carry real ETags."""
    c = s3.client("edge")
    c.put_object("k.tmp", b"first")
    c.put_object("k", b"second")
    assert c.get_object("k.tmp") == b"first"
    objs = c.list_objects()
    assert [o["key"] for o in objs] == ["k", "k.tmp"]
    import hashlib as _h
    assert objs[0]["etag"] == _h.md5(b"second").hexdigest()
    with pytest.raises(urllib.error.HTTPError) as ei:
        c._request("DELETE").read()          # bucket not empty
    assert ei.value.code == 409
    c.delete_object("k")
    c.delete_object("k.tmp")
    c._request("DELETE").read()              # now empty: 204
    assert c.list_objects() == []
