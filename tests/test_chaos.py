"""Chaos suite: the fault-injection subsystem end-to-end.

Exercises ``flink_tpu.testing.chaos`` against the runtime's named fault
points — ``checkpoint.store``/``checkpoint.load`` with the
``RetryingCheckpointStorage`` + ``CheckpointFailureManager`` policy stack,
``heartbeat.deliver`` partitions, ``rpc.call`` drops, ``channel.send``
delays — plus the hardened ``FileCheckpointStorage`` commit protocol
(torn/truncated/corrupt checkpoints skipped by ``load_latest``).

Reference: ``flink-jepsen`` nemeses + ``CheckpointFailureManagerTest.java``
+ ``CheckpointCoordinatorFailureTest.java`` semantics.
"""

import os
import time

import numpy as np
import pytest

from flink_tpu.cluster.heartbeat import HeartbeatManager, HeartbeatTarget
from flink_tpu.cluster.channels import LocalChannel
from flink_tpu.cluster.rpc import Gateway, RpcEndpoint
from flink_tpu.cluster.task import TaskStates
from flink_tpu.core.batch import RecordBatch
from flink_tpu.datastream.api import StreamExecutionEnvironment
from flink_tpu.runtime.checkpoint.failure import (CheckpointFailureManager,
                                                  CheckpointFailureReason)
from flink_tpu.runtime.checkpoint.storage import (CorruptCheckpointError,
                                                  FileCheckpointStorage,
                                                  InMemoryCheckpointStorage,
                                                  RetryingCheckpointStorage)
from flink_tpu.testing import chaos
from flink_tpu.testing.chaos import (ActionSequence, CrashOnceAt, DelayBy,
                                     FailTimes, FailWithProbability,
                                     FaultInjector, InjectedFault, Partition,
                                     SlowDisk)
from flink_tpu.windowing.assigners import TumblingEventTimeWindows

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    """One test's faults must never leak into the next."""
    yield
    chaos.uninstall()


def _expected_sums(keys, vals):
    out = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        out[int(k)] = out.get(int(k), 0.0) + v
    return out


# ---------------------------------------------------------------------------
# schedules + injector determinism (fast tier)
# ---------------------------------------------------------------------------

def test_fire_is_noop_without_injector():
    assert chaos.fire("checkpoint.store") is True
    assert chaos.active() is None


def test_fail_times_then_succeed():
    inj = chaos.install(FaultInjector(seed=1))
    inj.inject("p", FailTimes(2))
    for _ in range(2):
        with pytest.raises(InjectedFault):
            chaos.fire("p")
    assert chaos.fire("p") is True
    assert inj.history("p") == ["fail", "fail", "ok"]


def test_crash_once_at_n():
    inj = chaos.install(FaultInjector())
    inj.inject("p", CrashOnceAt(3))
    assert chaos.fire("p") and chaos.fire("p")
    with pytest.raises(InjectedFault):
        chaos.fire("p")
    assert chaos.fire("p") is True
    assert inj.history("p") == ["ok", "ok", "fail", "ok"]


def test_action_sequence_script():
    inj = chaos.install(FaultInjector())
    inj.inject("p", ActionSequence(["ok", "fail"], then="ok"))
    assert chaos.fire("p")
    with pytest.raises(InjectedFault):
        chaos.fire("p")
    assert chaos.fire("p") and chaos.fire("p")


def test_seeded_probability_reproducible():
    """The determinism contract: same seed -> identical action history."""
    def run(seed):
        inj = FaultInjector(seed=seed)
        inj.inject("p", FailWithProbability(0.4))
        with chaos.installed(inj):
            for _ in range(64):
                try:
                    chaos.fire("p")
                except InjectedFault:
                    pass
        return inj.history("p")

    h1, h2 = run(seed=42), run(seed=42)
    assert h1 == h2
    assert "fail" in h1 and "ok" in h1      # p=0.4 over 64 draws
    assert run(seed=43) != h1               # a different seed diverges


def test_slow_disk_schedule_is_seeded_and_bounded():
    """SlowDisk draws jittered stall durations from the point's seeded RNG:
    same seed -> identical (firing, duration) histories; durations stay in
    [min_s, max_s]; the disk 'recovers' after ``times`` firings."""
    def history(seed):
        inj = FaultInjector(seed=seed)
        inj.inject("p", SlowDisk(max_s=0.0, min_s=0.0, p=0.5, times=20))
        with chaos.installed(inj):
            for _ in range(30):
                chaos.fire("p")
        return inj.history("p")

    h1, h2 = history(5), history(5)
    assert h1 == h2, "same seed must reproduce the exact stall sequence"
    assert h1 != history(6), "different seeds should differ"
    assert all(a == "ok" for a in h1[20:]), "past `times` the disk is healthy"
    stalls = [a for a in h1[:20] if isinstance(a, tuple)]
    assert stalls and all(a[0] == "delay" and 0.0 <= a[1] <= 0.0
                          for a in stalls)
    # the RNG stream advances identically whether a firing stalls or not:
    # truncating the flaky period must not change which firings stall
    inj3 = FaultInjector(seed=5)
    inj3.inject("p", SlowDisk(max_s=0.0, min_s=0.0, p=0.5, times=10))
    with chaos.installed(inj3):
        for _ in range(10):
            chaos.fire("p")
    assert inj3.history("p") == h1[:10]


def test_slow_disk_stalls_but_never_fails():
    inj = chaos.install(FaultInjector(seed=3))
    inj.inject("p", SlowDisk(max_s=0.01, min_s=0.005, p=1.0, times=3))
    t0 = time.monotonic()
    for _ in range(5):
        assert chaos.fire("p") is True    # delays, never raises/drops
    assert time.monotonic() - t0 >= 0.015


def test_per_point_counters_and_rngs_are_independent():
    inj = chaos.install(FaultInjector(seed=7))
    inj.inject("a", FailTimes(1))
    inj.inject("b", FailTimes(1))
    with pytest.raises(InjectedFault):
        chaos.fire("a")
    # point b has its own counter: its first firing still fails
    with pytest.raises(InjectedFault):
        chaos.fire("b")
    assert inj.fired("a") == 1 and inj.fired("b") == 1


def test_installed_context_manager_scopes_faults():
    inj = FaultInjector()
    inj.inject("p", FailTimes(100))
    with chaos.installed(inj):
        with pytest.raises(InjectedFault):
            chaos.fire("p")
    assert chaos.fire("p") is True          # uninstalled on exit


# ---------------------------------------------------------------------------
# CheckpointFailureManager policy (fast tier)
# ---------------------------------------------------------------------------

def test_failure_manager_tolerates_then_trips():
    fm = CheckpointFailureManager(tolerable_failed_checkpoints=2)
    assert fm.on_checkpoint_failure(CheckpointFailureReason.DECLINED, 1) is False
    assert fm.on_checkpoint_failure(CheckpointFailureReason.TIMEOUT, 2) is False
    assert fm.on_checkpoint_failure(CheckpointFailureReason.STORAGE, 3) is True
    assert fm.num_failed() == 3
    st = fm.status()
    assert st["continuous_failed_checkpoints"] == 3
    assert st["last_failure_reason"] == CheckpointFailureReason.STORAGE


def test_failure_manager_success_resets_continuous_window():
    fm = CheckpointFailureManager(tolerable_failed_checkpoints=1)
    assert fm.on_checkpoint_failure(CheckpointFailureReason.DECLINED, 1) is False
    fm.on_checkpoint_success(2)
    # the window restarted: one more failure is tolerated again
    assert fm.on_checkpoint_failure(CheckpointFailureReason.DECLINED, 3) is False
    assert fm.on_checkpoint_failure(CheckpointFailureReason.DECLINED, 4) is True
    assert fm.num_failed() == 3 and fm.num_completed() == 1


def test_failure_manager_unlimited_never_trips():
    fm = CheckpointFailureManager(CheckpointFailureManager.UNLIMITED)
    for cid in range(50):
        assert fm.on_checkpoint_failure(CheckpointFailureReason.STORAGE,
                                        cid) is False


def test_failure_manager_restart_resets_window():
    fm = CheckpointFailureManager(tolerable_failed_checkpoints=1)
    fm.on_checkpoint_failure(CheckpointFailureReason.STORAGE, 1)
    fm.on_job_restart()
    assert fm.continuous_failures == 0
    assert fm.num_failed() == 1             # lifetime counter survives


# ---------------------------------------------------------------------------
# RetryingCheckpointStorage (fast tier)
# ---------------------------------------------------------------------------

def test_retrying_storage_absorbs_transient_flakes():
    inj = chaos.install(FaultInjector())
    inj.inject("checkpoint.store", FailTimes(2))
    sleeps = []
    st = RetryingCheckpointStorage(InMemoryCheckpointStorage(),
                                   max_attempts=3, initial_backoff_ms=10,
                                   sleep=sleeps.append)
    st.store(1, {"op": {"total": 1.0}})     # 2 flakes absorbed by retries
    assert st.retries == 2
    assert sleeps == [0.01, 0.02]           # bounded exponential backoff
    assert st.load_latest() == {"op": {"total": 1.0}}
    assert inj.history("checkpoint.store") == ["fail", "fail", "ok"]


def test_retrying_storage_backoff_is_capped():
    inj = chaos.install(FaultInjector())
    inj.inject("checkpoint.store", FailTimes(4))
    sleeps = []
    st = RetryingCheckpointStorage(InMemoryCheckpointStorage(),
                                   max_attempts=5, initial_backoff_ms=100,
                                   multiplier=10.0, max_backoff_ms=250,
                                   sleep=sleeps.append)
    st.store(1, {"op": {}})
    assert sleeps == [0.1, 0.25, 0.25, 0.25]


def test_retrying_storage_gives_up_past_max_attempts():
    inj = chaos.install(FaultInjector())
    inj.inject("checkpoint.store", FailTimes(10))
    st = RetryingCheckpointStorage(InMemoryCheckpointStorage(),
                                   max_attempts=3, sleep=lambda s: None)
    with pytest.raises(InjectedFault):
        st.store(1, {"op": {}})
    assert inj.fired("checkpoint.store") == 3


def test_retrying_storage_never_retries_corruption(tmp_path):
    st = FileCheckpointStorage(str(tmp_path))
    st.store(1, {"op": {"x": 1}})
    meta = st.metadata(1)
    path = os.path.join(str(tmp_path), "chk-1", meta["operators"][0]["file"])
    with open(path, "r+b") as f:
        f.truncate(4)                        # torn write
    attempts = []
    wrapped = RetryingCheckpointStorage(st, max_attempts=5,
                                        sleep=attempts.append)
    with pytest.raises(CorruptCheckpointError):
        wrapped.load(1)
    assert attempts == []                    # a bad checksum never heals


# ---------------------------------------------------------------------------
# hardened FileCheckpointStorage commit protocol (fast tier)
# ---------------------------------------------------------------------------

def _file_of(st, cid, idx=0):
    return os.path.join(st.base_dir, f"chk-{cid}",
                        st.metadata(cid)["operators"][idx]["file"])


def test_torn_checkpoint_is_skipped_by_load_latest(tmp_path):
    st = FileCheckpointStorage(str(tmp_path))
    st.store(1, {"op": {"total": 1.0}})
    st.store(2, {"op": {"total": 2.0}})
    with open(_file_of(st, 2), "r+b") as f:
        f.truncate(8)                        # torn write survives a rename
    with pytest.raises(CorruptCheckpointError, match="torn write"):
        st.load(2)
    # latest INTACT checkpoint served — corrupt one silently skipped
    assert st.load_latest() == {"op": {"total": 1.0}}


def test_checksum_mismatch_detected(tmp_path):
    st = FileCheckpointStorage(str(tmp_path))
    st.store(1, {"op": {"total": 7.0}})
    path = _file_of(st, 1)
    data = bytearray(open(path, "rb").read())
    data[-1] ^= 0xFF                         # same size, flipped bits
    open(path, "wb").write(bytes(data))
    with pytest.raises(CorruptCheckpointError, match="checksum"):
        st.load(1)
    assert st.load_latest() is None


def test_unreadable_metadata_is_corrupt_not_fatal(tmp_path):
    st = FileCheckpointStorage(str(tmp_path))
    st.store(1, {"op": {"total": 1.0}})
    st.store(2, {"op": {"total": 2.0}})
    with open(os.path.join(str(tmp_path), "chk-2", "_metadata.json"),
              "w") as f:
        f.write("{ torn json")
    assert st.load_latest() == {"op": {"total": 1.0}}


def test_crash_mid_write_leaves_only_staging_dir(tmp_path):
    inj = chaos.install(FaultInjector())
    st = FileCheckpointStorage(str(tmp_path))
    st.store(1, {"op": {"total": 1.0}})
    # crash before the atomic publish: the fault point fires at store()
    # entry of checkpoint 2, so nothing of chk-2 is ever visible
    inj.inject("checkpoint.store", CrashOnceAt(1))
    with pytest.raises(InjectedFault):
        st.store(2, {"op": {"total": 2.0}})
    chaos.uninstall()
    assert st.checkpoint_ids() == [1]
    assert st.load_latest() == {"op": {"total": 1.0}}
    # a leftover chk-N.inprogress staging dir is ignored entirely
    os.makedirs(os.path.join(str(tmp_path), "chk-3.inprogress"))
    assert st.checkpoint_ids() == [1]


# ---------------------------------------------------------------------------
# control-plane fault points: heartbeat partition, rpc drop, channel delay
# ---------------------------------------------------------------------------

def test_heartbeat_partition_false_suspects_then_heals():
    inj = chaos.install(FaultInjector())
    dead = []
    hb = HeartbeatManager(interval_s=0.03, timeout_s=0.12,
                          on_timeout=dead.append)
    # the target is perfectly alive: it answers every request instantly
    hb.monitor_target("tm-1", HeartbeatTarget(
        lambda: hb.receive_heartbeat("tm-1")))
    part = inj.inject("heartbeat.deliver", Partition())
    hb.start()
    try:
        deadline = time.monotonic() + 3.0
        while "tm-1" not in dead and time.monotonic() < deadline:
            time.sleep(0.01)
        # its heartbeats were dropped on the floor -> falsely suspected
        assert dead == ["tm-1"]
        part.heal()
        hb.monitor_target("tm-1", HeartbeatTarget(
            lambda: hb.receive_heartbeat("tm-1")))
        time.sleep(0.3)                      # several timeout periods
        assert dead == ["tm-1"]              # healed link: no new suspicion
    finally:
        hb.stop()


def test_heartbeat_asymmetric_partition_drops_one_direction_only():
    """Partition(direction="response"): the monitor's heartbeat REQUESTS
    keep reaching the target (it demonstrably keeps answering) while the
    answers vanish — the target is falsely suspected by a one-direction
    link, the sharpest false-suspect shape.  The request direction keeps
    firing (and counting) untouched."""
    inj = chaos.install(FaultInjector())
    dead = []
    answered = []
    hb = HeartbeatManager(interval_s=0.03, timeout_s=0.12,
                          on_timeout=dead.append)

    def _answer():
        answered.append(1)
        hb.receive_heartbeat("tm-1")

    hb.monitor_target("tm-1", HeartbeatTarget(_answer))
    inj.inject("heartbeat.deliver", Partition(direction="response"))
    hb.start()
    try:
        deadline = time.monotonic() + 3.0
        while "tm-1" not in dead and time.monotonic() < deadline:
            time.sleep(0.01)
        assert dead == ["tm-1"], "responses dropped -> false suspect"
        assert len(answered) >= 2, \
            "requests must have kept flowing (the partition is one-way)"
        # deterministic history: only matching (response) firings counted
        assert all(a == chaos.DROP for a in inj.history("heartbeat.deliver"))
        assert inj.fired("heartbeat.deliver") == len(answered)
    finally:
        hb.stop()


def test_rpc_drop_loses_message_fail_raises():
    class Echo(RpcEndpoint):
        def ping(self, x):
            return x

    ep = Echo("echo")
    ep.start()
    try:
        gw = Gateway(ep)
        inj = chaos.install(FaultInjector())
        inj.inject("rpc.call", ActionSequence([chaos.DROP, chaos.OK]))
        lost = gw.ping(1)                   # dropped: never reaches mailbox
        assert gw.ping(2).result(timeout=5) == 2
        assert not lost.done()              # the lost-message model
        # the point's firing counter survives schedule replacement: the
        # next (third) firing is the one to target
        inj.inject("rpc.call", CrashOnceAt(3))
        with pytest.raises(InjectedFault):
            gw.ping(3)                      # fail schedules raise at call
    finally:
        ep.stop()


def test_channel_delay_schedule_slows_put():
    inj = chaos.install(FaultInjector())
    inj.inject("channel.send", DelayBy(0.05, times=1))
    ch = LocalChannel(capacity=4, name="c0")
    t0 = time.monotonic()
    ch.put(RecordBatch({"v": np.asarray([1.0])}))
    assert time.monotonic() - t0 >= 0.05    # first put delayed
    t1 = time.monotonic()
    ch.put(RecordBatch({"v": np.asarray([2.0])}))
    assert time.monotonic() - t1 < 0.05     # schedule exhausted


def test_channel_partition_stalls_until_closed():
    inj = chaos.install(FaultInjector())
    part = inj.inject("channel.send", Partition())
    ch = LocalChannel(capacity=4, name="c0")
    import threading
    done = []
    th = threading.Thread(
        target=lambda: done.append(
            ch.put(RecordBatch({"v": np.asarray([1.0])}))))
    th.start()
    time.sleep(0.05)
    assert not done                          # bytes neither flow nor error
    part.heal()
    th.join(timeout=5)
    assert done == [True]                    # healed link delivers
    # determinism contract: the stall fired the point exactly ONCE no
    # matter how long the partition lasted (the stall loop polls
    # blocked(), it does not re-fire)
    assert inj.fired("channel.send") == 1
    assert inj.history("channel.send") == [chaos.DROP]


def test_channel_partition_honors_put_timeout():
    inj = chaos.install(FaultInjector())
    inj.inject("channel.send", Partition())
    ch = LocalChannel(capacity=4, name="c0")
    t0 = time.monotonic()
    ok = ch.put(RecordBatch({"v": np.asarray([1.0])}), timeout_s=0.1)
    assert ok is False                       # bounded put gave up
    assert 0.1 <= time.monotonic() - t0 < 2.0


def test_latest_restore_survives_load_failure():
    """A checkpoint.load fault during restart-recovery degrades to
    no-restore instead of escaping the restart machinery."""
    from flink_tpu.cluster.minicluster import MiniCluster

    storage = InMemoryCheckpointStorage()
    storage.store(1, {"op": {"x": 1}})
    cluster = MiniCluster(checkpoint_storage=storage)
    inj = chaos.install(FaultInjector())
    inj.inject("checkpoint.load", FailTimes(1))
    assert cluster.latest_restore() is None   # swallowed, not raised
    assert cluster.latest_restore() == {"op": {"x": 1}}  # flake passed


def test_job_checkpoint_metrics_exported():
    """The failure manager's counters are registered on the cluster's
    job-scope metric group (reporters attached to the registry see them)."""
    from flink_tpu.cluster.minicluster import MiniCluster
    from flink_tpu.metrics.groups import (NUM_COMPLETED_CHECKPOINTS,
                                          NUM_FAILED_CHECKPOINTS,
                                          NUM_RESTARTS)

    cluster = MiniCluster()
    names = {k.split(".")[-1]
             for k in cluster.metrics_registry.all_metrics()}
    assert {NUM_COMPLETED_CHECKPOINTS, NUM_FAILED_CHECKPOINTS,
            NUM_RESTARTS} <= names
    cluster.failure_manager.on_checkpoint_failure(
        CheckpointFailureReason.STORAGE, 1)
    metrics = cluster.metrics_registry.all_metrics()
    failed = next(m for k, m in metrics.items()
                  if k.endswith(NUM_FAILED_CHECKPOINTS))
    assert failed.get_count() == 1


# ---------------------------------------------------------------------------
# end-to-end: MiniCluster under chaos (slow tier)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_transient_storage_flakes_absorbed_no_restart():
    """Storage fails twice, the retry wrapper absorbs both: the job
    finishes with ZERO restarts and ZERO failed checkpoints."""
    inj = FaultInjector(seed=11)
    inj.inject("checkpoint.store", FailTimes(2))
    storage = RetryingCheckpointStorage(InMemoryCheckpointStorage(retain=10),
                                        max_attempts=3, sleep=lambda s: None)
    n = 30_000
    keys = np.arange(n) % 13
    vals = np.ones(n)
    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    sink = (env.from_collection(columns={"k": keys, "v": vals},
                                batch_size=128)
            .key_by("k").sum("v").collect())
    with chaos.installed(inj):
        res = env.execute_cluster(storage=storage, checkpoint_interval_ms=5,
                                  tolerable_failed_checkpoints=0)
    assert res.state == TaskStates.FINISHED
    assert res.restarts == 0
    assert storage.retries >= 2
    cluster = env._last_cluster
    assert cluster.failure_manager.num_failed() == 0
    assert res.completed_checkpoints
    assert inj.history("checkpoint.store")[:3] == ["fail", "fail", "ok"]
    final = _expected_sums(keys, vals)
    got = {int(r["k"]): r["v"] for r in sink.rows()}
    assert got == final


@pytest.mark.slow
def test_persistent_storage_failure_fails_over_and_recovers():
    """Storage failures past tolerable_failed_checkpoints fail the job
    over; the restart strategy recovers it from the last good checkpoint
    (or from scratch) and final sums stay exactly-once."""
    inj = FaultInjector(seed=12)
    inj.inject("checkpoint.store", FailTimes(3))
    storage = InMemoryCheckpointStorage(retain=10)     # no retry wrapper
    n = 30_000
    keys = np.arange(n) % 13
    vals = np.ones(n)
    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    sink = (env.from_collection(columns={"k": keys, "v": vals},
                                batch_size=128)
            .key_by("k").sum("v").collect())
    with chaos.installed(inj):
        res = env.execute_cluster(storage=storage, checkpoint_interval_ms=5,
                                  restart_attempts=8,
                                  tolerable_failed_checkpoints=0)
    assert res.state == TaskStates.FINISHED
    assert res.restarts >= 1, "budget exhaustion did not fail the job over"
    cluster = env._last_cluster
    status = cluster.job_status()
    assert status["checkpoints"]["failed_checkpoints"] >= 1
    assert status["checkpoints"]["tolerable_failed_checkpoints"] == 0
    assert status["restarts"] == res.restarts
    got = {int(r["k"]): r["v"] for r in sink.rows()}
    assert got == _expected_sums(keys, vals)


def test_slow_disk_checkpoint_stalls_liveness_and_exactly_once():
    """Nemesis variety (VERDICT weak #6): a degrading disk stalls
    checkpoint-storage WRITES (bursty seeded jitter, no errors).  The job
    must stay LIVE — stalled stores run outside the coordinator lock, so
    acks/triggers keep flowing and the job finishes — with exactly-once
    sums, zero failed checkpoints and zero restarts."""
    inj = FaultInjector(seed=21)
    inj.inject("checkpoint.store", SlowDisk(max_s=0.08, min_s=0.02, p=0.6,
                                            times=12))
    storage = InMemoryCheckpointStorage(retain=10)
    n = 20_000
    keys = np.arange(n) % 11
    vals = np.ones(n)
    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    sink = (env.from_collection(columns={"k": keys, "v": vals},
                                batch_size=128)
            .key_by("k").sum("v").collect())
    with chaos.installed(inj):
        res = env.execute_cluster(storage=storage, checkpoint_interval_ms=5,
                                  tolerable_failed_checkpoints=0)
    assert res.state == TaskStates.FINISHED, "job lost liveness under stalls"
    assert res.restarts == 0
    cluster = env._last_cluster
    assert cluster.failure_manager.num_failed() == 0, \
        "a stall is not a failure: the budget must not be charged"
    assert res.completed_checkpoints, "stalled storage still checkpoints"
    stalls = [a for a in inj.history("checkpoint.store")
              if isinstance(a, tuple) and a[0] == "delay"]
    assert stalls, "the schedule never actually stalled a write"
    got = {int(r["k"]): r["v"] for r in sink.rows()}
    assert got == _expected_sums(keys, vals)


def _run_acceptance_scenario(seed):
    """Transient storage flakes + a subtask crash mid-window; returns
    (result, window-sum total, status, fail positions per point)."""
    inj = FaultInjector(seed=seed)
    inj.inject("checkpoint.store", FailTimes(2))
    inj.inject("subtask.run", CrashOnceAt(60))
    storage = InMemoryCheckpointStorage(retain=10)
    rng = np.random.default_rng(seed)
    n = 40_000
    keys = rng.integers(0, 21, n)
    vals = np.ones(n, dtype=np.float64)
    ts = np.sort(rng.integers(0, 4000, n))
    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    sink = (env.from_collection(columns={"k": keys, "v": vals, "t": ts},
                                batch_size=128)
            .assign_timestamps_and_watermarks(0, timestamp_column="t")
            .key_by("k")
            .window(TumblingEventTimeWindows.of(1000))
            .sum("v").collect())
    with chaos.installed(inj):
        res = env.execute_cluster(storage=storage, checkpoint_interval_ms=5,
                                  restart_attempts=4,
                                  tolerable_failed_checkpoints=10)
    total = sum(r["v"] for r in sink.rows())
    fails = {p: [i for i, a in enumerate(h) if a == "fail"]
             for p, h in inj.history().items()}
    return res, total, env._last_cluster.job_status(), fails, float(vals.sum())


@pytest.mark.slow
def test_acceptance_storage_flake_then_crash_midwindow_exactly_once():
    """The ISSUE acceptance scenario: checkpoint storage fails
    transiently, then a subtask crashes mid-window; automatic failover
    still yields exactly-once window sums, job_status() reports the
    failed-checkpoint and restart counts, and the fault schedules are
    deterministic under a fixed seed."""
    res, total, status, fails, expect = _run_acceptance_scenario(seed=99)
    assert res.state == TaskStates.FINISHED
    assert res.restarts >= 1, "the injected crash did not trigger failover"
    assert abs(total - expect) < 0.05, "window sums not exactly-once"
    assert status["checkpoints"]["failed_checkpoints"] >= 1
    assert status["restarts"] >= 1
    assert status["failed_checkpoints"] == \
        status["checkpoints"]["failed_checkpoints"]

    # determinism: a second run with the same seed produces the same
    # failure positions at every fault point
    res2, total2, _status2, fails2, _ = _run_acceptance_scenario(seed=99)
    assert res2.state == TaskStates.FINISHED
    assert abs(total2 - expect) < 0.05
    assert fails["checkpoint.store"] == fails2["checkpoint.store"] == [0, 1]
    assert fails["subtask.run"] == fails2["subtask.run"] == [59]


@pytest.mark.slow
def test_snapshot_failure_declines_checkpoint_not_task():
    """A snapshot error at a subtask DECLINES the checkpoint (charged to
    the failure budget) instead of killing the task: with enough
    tolerance the job still finishes without any restart."""
    inj = FaultInjector(seed=13)
    inj.inject("subtask.snapshot", FailTimes(1))
    storage = InMemoryCheckpointStorage(retain=10)
    n = 30_000
    keys = np.arange(n) % 13
    vals = np.ones(n)
    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    sink = (env.from_collection(columns={"k": keys, "v": vals},
                                batch_size=128)
            .key_by("k").sum("v").collect())
    with chaos.installed(inj):
        res = env.execute_cluster(storage=storage, checkpoint_interval_ms=5,
                                  tolerable_failed_checkpoints=10)
    assert res.state == TaskStates.FINISHED
    assert res.restarts == 0
    cluster = env._last_cluster
    assert cluster.failure_manager.num_failed() >= 1
    assert cluster.failure_manager.status()["last_failure_reason"] == \
        CheckpointFailureReason.DECLINED
    got = {int(r["k"]): r["v"] for r in sink.rows()}
    assert got == _expected_sums(keys, vals)
