"""Dashboard parity-lite (VERDICT r3 next #8): the four views — job DAG
SVG, per-subtask backpressure bars, checkpoint drill-down table, flame
graph SVG — render server-side from REST data and are asserted as DOM here
(SVG parsed with ElementTree, fragments with html.parser; no browser in
this image).  Reference: ``flink-runtime-web/web-dashboard``."""

import threading
import urllib.request
import xml.etree.ElementTree as ET
from html.parser import HTMLParser

import numpy as np
import pytest

from flink_tpu.cluster.minicluster import MiniCluster
from flink_tpu.datastream.api import StreamExecutionEnvironment
from flink_tpu.rest.server import JobRegistry, RestServer
from flink_tpu.runtime.checkpoint.storage import InMemoryCheckpointStorage

SVG = "{http://www.w3.org/2000/svg}"


def _get_text(url):
    with urllib.request.urlopen(url, timeout=15) as r:
        return r.read().decode(), r.headers.get_content_type()


@pytest.fixture
def job(tmp_path):
    registry = JobRegistry()
    server = RestServer(registry).start()
    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    n = 400_000
    keys = np.arange(n) % 97
    (env.from_collection(columns={"k": keys, "v": np.ones(n)},
                         batch_size=256)
     .key_by("k").sum("v").collect())
    plan = env.get_stream_graph("dash-job").to_plan()
    mc = MiniCluster(checkpoint_storage=InMemoryCheckpointStorage(),
                     checkpoint_interval_ms=10)
    job_id = registry.register("dash-job", mc)
    th = threading.Thread(target=lambda: mc.execute(plan, timeout_s=120))
    th.start()
    base = f"{server.url}/jobs/{job_id}"
    # wait until every vertex deployed (the views read live task state)
    import json
    import time
    deadline = time.time() + 30
    while time.time() < deadline:
        with urllib.request.urlopen(base, timeout=10) as r:
            st = json.loads(r.read())
        if len(st["vertices"]) >= len(plan.vertices):
            break
        time.sleep(0.05)
    try:
        yield base, plan
    finally:
        th.join(timeout=120)
        server.stop()


class _Frag(HTMLParser):
    def __init__(self):
        super().__init__()
        self.tags = []

    def handle_starttag(self, tag, attrs):
        self.tags.append((tag, dict(attrs)))


def test_dag_svg_renders_plan(job):
    base, plan = job
    body, ctype = _get_text(base + "/plan.svg")
    assert ctype == "image/svg+xml"
    root = ET.fromstring(body)
    assert root.tag == f"{SVG}svg"
    groups = root.findall(f"{SVG}g")
    vertex_groups = [g for g in groups
                     if g.get("class") == "dag-vertex"]
    assert len(vertex_groups) == len(plan.vertices)
    # every vertex renders its name and parallelism
    texts = [t.text for g in vertex_groups for t in g.findall(f"{SVG}text")]
    for v in plan.vertices:
        assert any(v.name in (t or "") for t in texts), v.name
    # edges drawn with arrowheads
    edges = [p for p in root.findall(f"{SVG}path")
             if p.get("class") == "dag-edge"]
    want_edges = sum(len(v.out_edges) for v in plan.vertices)
    assert len(edges) == want_edges
    # partitioning labels present (HASH edge from key_by)
    labels = [t.text for t in root.findall(f"{SVG}text")
              if t.get("class") == "dag-edge-label"]
    assert any("HASH" in (l or "").upper() for l in labels), labels


def test_backpressure_fragment_has_per_subtask_bars(job):
    base, plan = job
    body, ctype = _get_text(base + "/backpressure.html")
    assert ctype == "text/html"
    p = _Frag()
    p.feed(body)
    subtasks = [a for t, a in p.tags
                if a.get("class") == "bp-subtask"]
    # parallelism 2: at least one vertex shows 2 subtask rows
    by = {}
    for t, a in p.tags:
        if a.get("class") == "bp-vertex":
            by[a.get("data-vertex-id")] = 0
    assert len(by) == len(plan.vertices)
    assert len(subtasks) >= 2
    bars = [a for t, a in p.tags if a.get("class") in
            ("bp-busy", "bp-backpressured", "bp-idle")]
    assert len(bars) == 3 * len(subtasks)
    for a in bars:
        assert "width:" in a.get("style", "")


def test_checkpoint_drilldown_table(job):
    base, _plan = job
    import json
    import time
    import urllib.request as _u
    deadline = time.time() + 30
    while time.time() < deadline:
        with _u.urlopen(base + "/checkpoints", timeout=10) as r:
            ck = json.loads(r.read())
        if ck["count"] >= 1:
            break
        time.sleep(0.1)
    assert ck["count"] >= 1, "no checkpoint completed in time"
    body, ctype = _get_text(base + "/checkpoints.html")
    assert ctype == "text/html"
    p = _Frag()
    p.feed(body)
    rows = [a for t, a in p.tags if a.get("class") == "ckpt-row"]
    assert rows and all("data-checkpoint-id" in a for a in rows)
    assert any(t == "table" for t, _a in p.tags)
    assert body.count("<th>") == 5          # id/state/duration/size/kind
    # the state-size column renders real sizes, not the placeholder
    assert "state_size_bytes" not in body
    assert any(c.isdigit() for c in body.split("</td><td>")[3])


def test_flamegraph_svg_renders_samples(job):
    base, _plan = job
    body, ctype = _get_text(base + "/flamegraph.svg")
    assert ctype == "image/svg+xml"
    root = ET.fromstring(body)
    frames = [g for g in root.findall(f"{SVG}g")
              if g.get("class") == "flame-frame"]
    assert frames, "no stack frames sampled"
    # root frame spans the full width; every frame carries a tooltip title
    rects = [g.find(f"{SVG}rect") for g in frames]
    widths = [float(r.get("width")) for r in rects]
    assert max(widths) == pytest.approx(1000.0, abs=1.0)
    titles = [r.find(f"{SVG}title") for r in rects]
    assert all(t is not None and "samples" in t.text for t in titles)
    # depth attribute increases monotonically from the root
    depths = sorted(int(g.get("data-depth")) for g in frames)
    assert depths[0] == 0 and depths[-1] >= 1


def test_plan_json_topology(job):
    base, plan = job
    import json
    with urllib.request.urlopen(base + "/plan", timeout=10) as r:
        view = json.loads(r.read())
    assert {v["id"] for v in view["vertices"]} == {v.id
                                                   for v in plan.vertices}
    assert all({"source", "target", "partitioning"} <= set(e)
               for e in view["edges"])
