"""Jepsen-flavored end-to-end exactly-once: durable log source -> keyed
aggregation -> TRANSACTIONAL log sink, with injected failures and automatic
restarts.  The final output log must contain every input's effect exactly
once — the full chain: source offset replay + state restore + two-phase
sink commit."""

import numpy as np
import pytest

from flink_tpu import formats
from flink_tpu.cluster.task import TaskStates
from flink_tpu.connectors.partitioned_log import (LogSink, LogSource,
                                                  PartitionedLog)
from flink_tpu.core.batch import RecordBatch
from flink_tpu.datastream.api import StreamExecutionEnvironment
from flink_tpu.runtime.checkpoint.storage import InMemoryCheckpointStorage

pytestmark = pytest.mark.slow


def _fill_input_log(directory: str, n: int, keys: int,
                    partitions: int = 2) -> None:
    log = PartitionedLog(directory, num_partitions=partitions)
    per = n // partitions
    for p in range(partitions):
        lo = p * per
        for start in range(lo, lo + per, 512):
            stop = min(start + 512, lo + per)
            log.append(p, RecordBatch({
                "k": np.arange(start, stop) % keys,
                "v": np.ones(stop - start)}))


def test_log_to_log_exactly_once_with_chaos(tmp_path):
    n, keys = 60_000, 23
    in_dir = str(tmp_path / "in")
    out_dir = str(tmp_path / "out")
    _fill_input_log(in_dir, n, keys)

    boom = {"count": 0, "fails": 0}

    def poison(cols):
        boom["count"] += 1
        # fail twice at different points of the stream
        if boom["count"] in (25, 110):
            boom["fails"] += 1
            raise RuntimeError(f"chaos #{boom['fails']}")
        return cols

    storage = InMemoryCheckpointStorage(retain=5)
    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    (env.from_source(LogSource(in_dir, bounded=True))
     .map(poison)
     .key_by("k").sum("v")
     .add_sink(LogSink(out_dir, num_partitions=1)))
    res = env.execute_cluster(storage=storage, checkpoint_interval_ms=10,
                              restart_attempts=4)
    assert res.state == TaskStates.FINISHED
    assert res.restarts >= 1, "chaos did not trigger any restart"

    # the output log holds running sums; per key the LAST committed value
    # must equal the exact total — and no value may EXCEED it (overshoot
    # would prove double-processing)
    out_log = PartitionedLog(out_dir)
    last = {}
    over = {}
    for batch, _off in out_log.read_from(0, 0):
        for r in batch.to_rows():
            last[r["k"]] = r["v"]
            over[r["k"]] = max(over.get(r["k"], 0.0), r["v"])
    expect = {}
    for k in (np.arange(n) % keys).tolist():
        expect[k] = expect.get(k, 0.0) + 1.0
    assert last.keys() == expect.keys()
    for k in expect:
        assert last[k] == expect[k], (k, last[k], expect[k])
        assert over[k] <= expect[k], f"key {k} overshot: double-processing"


def test_log_to_log_unaligned_checkpoints(tmp_path):
    """Same chain under UNALIGNED barriers."""
    n, keys = 30_000, 11
    in_dir = str(tmp_path / "in")
    out_dir = str(tmp_path / "out")
    _fill_input_log(in_dir, n, keys)

    boom = {"count": 0}

    def poison(cols):
        boom["count"] += 1
        if boom["count"] == 40:
            raise RuntimeError("chaos")
        return cols

    storage = InMemoryCheckpointStorage(retain=5)
    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    (env.from_source(LogSource(in_dir, bounded=True))
     .map(poison)
     .key_by("k").sum("v")
     .add_sink(LogSink(out_dir, num_partitions=1)))
    res = env.execute_cluster(storage=storage, checkpoint_interval_ms=10,
                              unaligned=True, restart_attempts=3)
    assert res.state == TaskStates.FINISHED

    out_log = PartitionedLog(out_dir)
    last = {}
    for batch, _off in out_log.read_from(0, 0):
        for r in batch.to_rows():
            last[r["k"]] = r["v"]
    expect = {}
    for k in (np.arange(n) % keys).tolist():
        expect[k] = expect.get(k, 0.0) + 1.0
    assert last == expect


def test_commit_crash_window_not_truncated_by_new_attempt(tmp_path):
    """Regression: txn committed (sidecar written) but intent file left
    behind by a crash must NOT be truncated by a recovering instance with a
    different attempt id — recovery reads the union of all sidecars."""
    import json as _json
    import os

    out_dir = str(tmp_path / "out")
    s1 = LogSink(out_dir, num_partitions=1)
    s1.write_batch(RecordBatch({"v": np.arange(5.0)}))
    snap = s1.snapshot_state()
    cid = snap["counter"]
    s1.notify_checkpoint_complete(1)       # fully committed
    assert sum(len(b) for b, _ in PartitionedLog(out_dir).read_from(0, 0)) == 5
    # simulate the crash window: recreate the intent file as if os.remove
    # never ran, pointing at PRE-commit offsets
    with open(s1._intent_path(cid), "w") as f:
        _json.dump({"key": s1._commit_key(cid), "offsets": {"0": 0}}, f)
    # a NEW instance (fresh attempt) recovers: must SEE the commit in the
    # old attempt's sidecar and keep the rows
    s2 = LogSink(out_dir, num_partitions=1)
    assert sum(len(b) for b, _ in PartitionedLog(out_dir).read_from(0, 0)) == 5


def test_finished_snapshot_restore_emits_only_eoi():
    """Regression: a task restored from a FINAL snapshot replays only the
    channel-termination signal, never its data or end_input effects."""
    from flink_tpu.cluster.channels import LocalChannel
    from flink_tpu.cluster.task import SourceSubtask, TaskListener, TaskStates
    from flink_tpu.core.batch import EndOfInput
    from flink_tpu.core.functions import RuntimeContext
    from flink_tpu.operators.base import StreamOperator

    seen = []

    class _Out:
        channels = []

        def emit(self, el):
            seen.append(el)

    class _Id(StreamOperator):
        def process_batch(self, b):
            return [b]

    class _Split:
        def read(self):
            raise AssertionError("finished task must not re-read its split")

    t = SourceSubtask("src", 0, _Id(), [_Out()], RuntimeContext(),
                      TaskListener(), _Split())
    t.start({"operator": {}, "source_offset": 99, "finished": True})
    t.join()
    assert t.state == TaskStates.FINISHED
    assert len(seen) == 1 and isinstance(seen[0], EndOfInput)
    assert t.final_snapshot["finished"] is True
