"""REST API + observability: job views, backpressure gauges, latency
markers, savepoint trigger, cancel, flame graphs, dashboard."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from flink_tpu.cluster.minicluster import MiniCluster
from flink_tpu.datastream.api import StreamExecutionEnvironment
from flink_tpu.rest.server import JobRegistry, RestServer
from flink_tpu.runtime.checkpoint.storage import InMemoryCheckpointStorage


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def _req(url, method):
    req = urllib.request.Request(url, method=method)
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read()), r.status


@pytest.fixture
def stack(tmp_path):
    registry = JobRegistry()
    server = RestServer(registry).start()
    yield registry, server
    server.stop()


def _run_job(registry, n=200_000, storage=None, name="rest-job"):
    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    keys = np.arange(n) % 97
    (env.from_collection(columns={"k": keys, "v": np.ones(n)}, batch_size=256)
     .key_by("k").sum("v").collect())
    plan = env.get_stream_graph(name).to_plan()
    mc = MiniCluster(checkpoint_storage=storage,
                     checkpoint_interval_ms=10 if storage else 0)
    job_id = registry.register(name, mc)
    th = threading.Thread(target=lambda: mc.execute(plan, timeout_s=120))
    th.start()
    return job_id, mc, th


def test_rest_job_lifecycle(stack):
    registry, server = stack
    storage = InMemoryCheckpointStorage(retain=5)
    job_id, mc, th = _run_job(registry, storage=storage)
    try:
        time.sleep(0.2)
        jobs = _get(f"{server.url}/jobs")["jobs"]
        assert jobs[0]["id"] == job_id
        detail = _get(f"{server.url}/jobs/{job_id}")
        assert detail["state"] in ("RUNNING", "FINISHED")
        assert detail["vertices"]
        v0 = detail["vertices"][0]
        assert {"busy_ratio", "idle_ratio", "backpressure_ratio"} <= set(v0)
        bp = _get(f"{server.url}/jobs/{job_id}/backpressure")
        assert all(0 <= v["busy"] <= 1 for v in bp["vertices"])
        ov = _get(f"{server.url}/overview")
        assert ov["jobs_total"] == 1
    finally:
        th.join(timeout=120)
    # after completion
    detail = _get(f"{server.url}/jobs/{job_id}")
    assert detail["state"] == "FINISHED"
    m = _get(f"{server.url}/jobs/{job_id}/metrics")
    assert m["records_in"] > 0 and m["records_out"] > 0
    cp = _get(f"{server.url}/jobs/{job_id}/checkpoints")
    assert cp["count"] >= 1


def test_rest_savepoint_and_cancel(stack):
    registry, server = stack
    storage = InMemoryCheckpointStorage(retain=5)
    job_id, mc, th = _run_job(registry, n=3_000_000, storage=storage)
    try:
        time.sleep(0.2)
        body, status = _req(f"{server.url}/jobs/{job_id}/savepoints", "POST")
        assert status == 200 and body["status"] == "completed"
        body, status = _req(f"{server.url}/jobs/{job_id}", "PATCH")
        assert status == 202
    finally:
        th.join(timeout=120)


def test_rest_unknown_job_404(stack):
    _registry, server = stack
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(f"{server.url}/jobs/nope")
    assert e.value.code == 404


def test_dashboard_served(stack):
    _registry, server = stack
    with urllib.request.urlopen(server.url + "/", timeout=10) as r:
        html = r.read().decode()
    assert "flink-tpu dashboard" in html and "/jobs" in html
    # the dashboard is a real SPA: job actions, vertex time-share bars with
    # a legend, latency tiles, and a flame-graph renderer
    for marker in ("savepoint", "backpressured", "flame", "legend"):
        assert marker in html, marker


def test_flamegraph_sampler():
    from flink_tpu.rest.flamegraph import flamegraph, folded_to_tree, sample_stacks

    stop = threading.Event()

    def spin():
        while not stop.is_set():
            sum(range(1000))

    t = threading.Thread(target=spin, name="task-spin", daemon=True)
    t.start()
    try:
        folded = sample_stacks(duration_ms=120, interval_ms=2,
                               thread_prefix="task-")
        assert sum(folded.values()) > 0
        tree = folded_to_tree(folded)
        assert tree["value"] == sum(folded.values())
        assert tree["children"]
        # names carry frame + file:line
        flat = json.dumps(tree)
        assert "spin" in flat
    finally:
        stop.set()


def test_latency_markers_recorded():
    from flink_tpu.cluster.task import SourceSubtask

    env = StreamExecutionEnvironment()
    n = 50_000
    sink = (env.from_collection(columns={"k": np.arange(n) % 7,
                                         "v": np.ones(n)}, batch_size=128)
            .key_by("k").sum("v").collect())
    plan = env.get_stream_graph().to_plan()
    mc = MiniCluster()
    # enable markers on deploy: patch after _deploy via subclass
    orig_deploy = mc._deploy

    def deploy(plan, restore):
        orig_deploy(plan, restore)
        for t in mc._tasks:
            if isinstance(t, SourceSubtask):
                t.latency_marker_interval = 10

    mc._deploy = deploy
    res = mc.execute(plan, timeout_s=120)
    assert res.state == "FINISHED"
    lats = mc.sink_latencies_ms()
    assert lats, "no latency samples recorded at the sink"
    assert all(l >= 0 for l in lats)


def test_cli_cluster_commands(stack):
    import subprocess
    import sys

    registry, server = stack
    job_id, mc, th = _run_job(registry, n=2_000_000,
                              storage=InMemoryCheckpointStorage())
    try:
        time.sleep(0.2)

        import os
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

        def cli(*args):
            return subprocess.run(
                [sys.executable, "-m", "flink_tpu", *args, "--url", server.url],
                capture_output=True, text=True, timeout=120, cwd=repo)

        out = cli("list")
        assert job_id in out.stdout
        out = cli("status", job_id)
        assert '"state"' in out.stdout
        # each CLI call is a fresh subprocess (~1s): with warm jit caches
        # the 2M-record job can FINISH before the savepoint lands — that
        # race is legitimate, so a failed savepoint is acceptable ONLY when
        # the job is no longer running
        out = cli("savepoint", job_id)
        if "completed" not in out.stdout:
            status = cli("status", job_id).stdout
            assert "RUNNING" not in status, out.stdout + out.stderr + status
        out = cli("cancel", job_id)
        assert "cancelling" in out.stdout or "FINISHED" in \
            cli("status", job_id).stdout
    finally:
        th.join(timeout=120)


def test_stop_with_savepoint(stack):
    """`flink stop` analog: savepoint + cancel; the savepoint restores a
    successor run exactly where the stopped one left off."""
    registry, server = stack
    storage = InMemoryCheckpointStorage(retain=10)
    job_id, mc, th = _run_job(registry, n=4_000_000, storage=storage,
                              name="stop-job")
    try:
        time.sleep(0.3)
        req = urllib.request.Request(f"{server.url}/jobs/{job_id}/stop",
                                     method="POST")
        try:
            with urllib.request.urlopen(req, timeout=120) as r:
                status, body = r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            status, body = e.code, json.loads(e.fp.read())
        th.join(timeout=120)
        if status == 200:
            assert body["status"] == "stopped"
            cid = body["checkpoint_id"]
            assert cid in storage.checkpoint_ids()
            assert mc.job_status()["state"] in ("CANCELED", "FINISHED")
            # exactly-once across the stop boundary: a successor restored
            # from the stop-savepoint must land on the clean-run totals
            # (sources paused BEFORE the savepoint, so nothing was
            # processed past the barrier)
            n = 4_000_000
            env2 = StreamExecutionEnvironment()
            env2.set_parallelism(2)
            keys = np.arange(n) % 97
            sink = (env2.from_collection(columns={"k": keys,
                                                  "v": np.ones(n)},
                                         batch_size=256)
                    .key_by("k").sum("v").collect())
            plan2 = env2.get_stream_graph("stop-successor").to_plan()
            mc2 = MiniCluster()
            res2 = mc2.execute(plan2, timeout_s=120,
                               restore=storage.load(cid))
            assert res2.state == "FINISHED"
            final = {r["k"]: r["v"] for r in sink.rows()}
            expect = {i: float(len(range(i, n, 97))) for i in range(97)}
            assert final == expect
        else:
            # the job finished before the stop landed — legitimate race
            assert mc.job_status()["state"] == "FINISHED"
    finally:
        th.join(timeout=120)


def test_rest_checkpoint_stats_watermarks_and_exception_history(stack):
    """The three operator views (VERDICT r2 #8): per-checkpoint stats
    (duration/size), per-vertex watermarks, and exception history."""
    registry, server = stack
    storage = InMemoryCheckpointStorage(retain=10)
    job_id, mc, th = _run_job(registry, storage=storage)
    try:
        deadline = time.time() + 60
        while time.time() < deadline:
            ck = _get(f"{server.url}/jobs/{job_id}/checkpoints")
            if ck.get("history"):
                break
            time.sleep(0.05)
        assert ck["history"], "no checkpoint stats collected"
        st = ck["history"][0]
        assert {"id", "duration_ms", "state_size_bytes",
                "completed_at_ms", "acked_subtasks"} <= set(st)
        assert st["state_size_bytes"] > 0 and st["duration_ms"] >= 0
        wm = _get(f"{server.url}/jobs/{job_id}/watermarks")
        assert {v["id"] for v in wm["vertices"]}
        assert all("watermark" in v for v in wm["vertices"])
    finally:
        th.join(timeout=120)
    ex = _get(f"{server.url}/jobs/{job_id}/exceptions")
    assert ex["root_exception"] is None and ex["history"] == []


def test_metrics_history_sampled(tmp_path):
    """The background sampler feeds /metrics/history with per-vertex
    series over time — the MetricStore behind the dashboard's
    per-operator throughput graphs."""
    registry = JobRegistry()
    server = RestServer(registry, sample_interval_s=0.05).start()
    try:
        job_id, mc, th = _run_job(registry, n=400_000,
                                  name="history-job")
        th.join(timeout=120)
        time.sleep(0.3)                 # a few post-completion samples
        h = _get(f"{server.url}/jobs/{job_id}/metrics/history")
        series = h["series"]
        assert len(series) >= 2
        last = series[-1]
        assert last["ts"] > 0
        assert last["vertices"], last
        v = next(iter(last["vertices"].values()))
        assert {"records_in", "records_out", "busy_ratio",
                "backpressure_ratio"} <= set(v)
        # cumulative counters are monotone across samples
        for vid in last["vertices"]:
            vals = [s["vertices"][vid]["records_in"] for s in series
                    if vid in s["vertices"]]
            assert vals == sorted(vals)
        # the dashboard page embeds the throughput panel
        with urllib.request.urlopen(server.url, timeout=10) as r:
            page = r.read().decode()
        assert "metrics/history" in page and "renderTput" in page
    finally:
        server.stop()
