"""Shuffle SPI: pluggable result-partition services (``runtime/shuffle.py``).

Covers the SPI contract, the sort-merge blocking implementation's region
format and lifecycle (``SortMergeResultPartition.java:65`` analog), the
pipelined concurrent service, and the ``partition_by_hash``/
``map_partition`` DataSet exchange that rides the SPI.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from flink_tpu.config.config_option import Configuration
from flink_tpu.config.options import ShuffleOptions
from flink_tpu.core.batch import RecordBatch
from flink_tpu.runtime.shuffle import (
    PipelinedShuffleService, ShuffleService, SortMergeShuffleService,
    hash_subpartition, register_shuffle_service, shuffle_service_for)


def make_batch(lo: int, hi: int) -> RecordBatch:
    return RecordBatch({"k": np.arange(lo, hi, dtype=np.int64),
                        "v": np.arange(lo, hi, dtype=np.float64) * 0.5})


class TestSortMergeService:
    def test_write_finish_read_round_trip(self, tmp_path):
        svc = SortMergeShuffleService(str(tmp_path), memory_budget_bytes=1 << 20)
        w = svc.create_partition("p1", 3)
        w.emit(0, make_batch(0, 10))
        w.emit(2, make_batch(10, 15))
        w.emit(0, make_batch(20, 25))
        w.finish()
        sub0 = [np.asarray(b.column("k")) for b in svc.open_reader("p1", 0)]
        assert np.concatenate(sub0).tolist() == list(range(0, 10)) + \
            list(range(20, 25))
        assert list(svc.open_reader("p1", 1)) == []
        sub2 = [np.asarray(b.column("k")) for b in svc.open_reader("p1", 2)]
        assert np.concatenate(sub2).tolist() == list(range(10, 15))

    def test_small_budget_spills_many_regions(self, tmp_path):
        """A tiny clustering budget forces a region per emit — readers must
        stitch every region's ranges back together, in emit order."""
        svc = SortMergeShuffleService(str(tmp_path), memory_budget_bytes=64)
        w = svc.create_partition("p", 2)
        for i in range(12):
            w.emit(i % 2, make_batch(i * 10, i * 10 + 5))
        w.finish()
        assert len(w._regions) >= 6      # genuinely multi-region
        got = [int(np.asarray(b.column("k"))[0])
               for b in svc.open_reader("p", 0)]
        assert got == [0, 20, 40, 60, 80, 100]

    def test_blocking_contract_and_release(self, tmp_path):
        svc = SortMergeShuffleService(str(tmp_path))
        assert svc.blocking
        w = svc.create_partition("p", 1)
        w.emit(0, make_batch(0, 4))
        with pytest.raises(ValueError, match="not finished"):
            list(svc.open_reader("p", 0))
        w.finish()
        assert svc.is_finished("p")
        with pytest.raises(ValueError, match="already finished"):
            svc.create_partition("p", 1)
        svc.release_partition("p")
        assert not svc.is_finished("p")
        assert list(tmp_path.iterdir()) == []

    def test_abort_leaves_no_files(self, tmp_path):
        svc = SortMergeShuffleService(str(tmp_path))
        w = svc.create_partition("p", 2)
        w.emit(1, make_batch(0, 100))
        w.abort()
        assert list(tmp_path.iterdir()) == []

    def test_partition_outlives_producer_service(self, tmp_path):
        """Blocking partitions are plain files: a different service
        instance (another process's, a restarted consumer's) reads them —
        the decoupled-lifetime property batch shuffles exist for."""
        svc1 = SortMergeShuffleService(str(tmp_path))
        w = svc1.create_partition("p", 2)
        w.emit(0, make_batch(0, 50))
        w.finish()
        del svc1
        svc2 = SortMergeShuffleService(str(tmp_path))
        got = list(svc2.open_reader("p", 0))
        assert sum(len(b) for b in got) == 50
        # re-read (consumer restart) sees identical data
        again = list(svc2.open_reader("p", 0))
        assert sum(len(b) for b in again) == 50


class TestPipelinedService:
    def test_concurrent_producer_consumer(self):
        svc = PipelinedShuffleService()
        assert not svc.blocking
        w = svc.create_partition("p", 1)
        got = []

        def consume():
            for b in svc.open_reader("p", 0):
                got.append(len(b))

        t = threading.Thread(target=consume)
        t.start()
        for i in range(5):
            w.emit(0, make_batch(i, i + 3))
        w.finish()
        t.join(timeout=10)
        assert got == [3] * 5


class TestRegistry:
    def test_configured_service_resolution(self, tmp_path):
        cfg = Configuration()
        cfg.set(ShuffleOptions.SERVICE, "sort-merge")
        cfg.set(ShuffleOptions.DIRECTORY, str(tmp_path))
        cfg.set(ShuffleOptions.MEMORY_BUDGET_BYTES, 123)
        svc = shuffle_service_for(cfg)
        assert isinstance(svc, SortMergeShuffleService)
        assert svc.directory == str(tmp_path)
        assert svc.memory_budget_bytes == 123
        cfg.set(ShuffleOptions.SERVICE, "pipelined")
        assert isinstance(shuffle_service_for(cfg), PipelinedShuffleService)

    def test_third_party_registration(self):
        class Custom(ShuffleService):
            pass

        register_shuffle_service("custom-test", lambda **kw: Custom())
        assert isinstance(shuffle_service_for(name="custom-test"), Custom)
        with pytest.raises(ValueError, match="unknown shuffle.service"):
            shuffle_service_for(name="no-such")

    def test_hash_routing_matches_keygroup_spread(self):
        keys = np.arange(10_000, dtype=np.int64)
        sub = hash_subpartition(keys, 7)
        assert sub.min() >= 0 and sub.max() < 7
        counts = np.bincount(sub, minlength=7)
        assert counts.min() > 800             # roughly even
        assert np.array_equal(sub, hash_subpartition(keys, 7))  # stable


def _stream_rows(ds):
    """Rows via the STREAMED executor (``stream_batches``) — the path that
    actually rides the shuffle SPI (``_stream_map_partition``); ``collect``
    uses the in-memory materialized driver."""
    rows = []
    for b in ds.stream_batches():
        rows.extend(b.to_rows())
    return rows


class TestDataSetExchange:
    def _env(self, config=None):
        from flink_tpu.dataset.api import ExecutionEnvironment

        return ExecutionEnvironment.get_execution_environment(config)

    def test_map_partition_over_hash_exchange_streamed(self):
        env = self._env()
        n = 5000
        keys = np.arange(n, dtype=np.int64) % 100

        def dedup_count(part: RecordBatch) -> RecordBatch:
            k = np.asarray(part.column("k"))
            uniq, cnt = np.unique(k, return_counts=True)
            return RecordBatch({"k": uniq, "cnt": cnt.astype(np.int64)})

        ds = (env.from_columns({"k": keys})
              .partition_by_hash("k", num_partitions=6)
              .map_partition(dedup_count))
        for rows in (_stream_rows(ds), ds.collect()):
            got = {r["k"]: r["cnt"] for r in rows}
            assert len(got) == 100       # co-partitioned: no split keys
            assert all(c == n // 100 for c in got.values())

    def test_map_partition_without_exchange_is_one_partition(self):
        env = self._env()
        calls = []

        def fn(part: RecordBatch) -> RecordBatch:
            calls.append(len(part))
            return part

        rows = _stream_rows(
            env.from_columns({"k": np.arange(10, dtype=np.int64)})
            .map_partition(fn))
        assert len(rows) == 10
        assert calls == [10]

    def test_exchange_through_pipelined_service_override(self):
        from flink_tpu.runtime import shuffle as shuffle_mod

        env = self._env()
        created = []
        orig = shuffle_mod.PipelinedShuffleService

        class Tracking(orig):
            def __init__(self):
                super().__init__()
                created.append(self)

        shuffle_mod._FACTORIES["pipelined"] = lambda **kw: Tracking()
        try:
            rows = _stream_rows(
                env.from_columns({"k": np.arange(64, dtype=np.int64)})
                .partition_by_hash("k", num_partitions=4,
                                   service="pipelined")
                .map_partition(lambda p: p))
        finally:
            shuffle_mod._FACTORIES["pipelined"] = lambda **kw: orig()
        assert sorted(r["k"] for r in rows) == list(range(64))
        assert len(created) == 1         # the override service really ran

    def test_shuffle_options_govern_the_exchange(self, tmp_path):
        """ShuffleOptions set on the environment's Configuration reach the
        exchange: the spilled partitions land in shuffle.directory."""
        cfg = Configuration()
        cfg.set(ShuffleOptions.DIRECTORY, str(tmp_path))
        cfg.set(ShuffleOptions.MEMORY_BUDGET_BYTES, 128)  # spill a lot
        env = self._env(cfg)
        seen_files = []

        def fn(part: RecordBatch) -> RecordBatch:
            seen_files.append(len(list(tmp_path.iterdir())))
            return part

        rows = _stream_rows(
            env.from_columns({"k": np.arange(500, dtype=np.int64)})
            .partition_by_hash("k", num_partitions=3)
            .map_partition(fn))
        assert len(rows) == 500
        assert max(seen_files) > 0       # partitions lived in our directory
        assert list(tmp_path.iterdir()) == []  # and were released after

    def test_default_partition_count_agrees_across_executors(self):
        """num_partitions=0 must derive the SAME count in the streamed and
        materialized drivers — fn observes partition composition."""
        env = self._env()

        def tag_max(part: RecordBatch) -> RecordBatch:
            k = np.asarray(part.column("k"))
            return RecordBatch({"k": k, "part_max": np.full(
                len(k), k.max(), np.int64)})

        ds = (env.from_columns({"k": np.arange(40, dtype=np.int64) % 7})
              .partition_by_hash("k")
              .map_partition(tag_max))
        streamed = sorted((r["k"], r["part_max"]) for r in _stream_rows(ds))
        collected = sorted((r["k"], r["part_max"]) for r in ds.collect())
        assert streamed == collected

    def test_materialized_path_agrees_with_streamed(self):
        """A diamond reference forces the memoized/materialized driver —
        both paths must produce the same partitioned-call semantics."""
        env = self._env()

        def tag_max(part: RecordBatch) -> RecordBatch:
            k = np.asarray(part.column("k"))
            return RecordBatch({"k": k, "part_max": np.full(
                len(k), k.max(), np.int64)})

        ds = (env.from_columns({"k": np.arange(40, dtype=np.int64)})
              .partition_by_hash("k", num_partitions=4)
              .map_partition(tag_max))
        doubled = ds.union(ds)               # diamond: ds consumed twice
        rows = doubled.collect()
        assert len(rows) == 80
        assert sorted(r["k"] for r in rows) == sorted(
            list(range(40)) * 2)
        for r in rows:
            assert r["part_max"] >= r["k"]
