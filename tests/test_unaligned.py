"""Unaligned checkpoints (ISSUE-5): barrier overtake, aligned-with-timeout
escalation, persisted in-flight channel state, recovery replay, bounded
alignment queues, and the backpressure observability that rides along.

Reference semantics: Carbone et al. "Lightweight Asynchronous Snapshots for
Distributed Dataflows" + FLIP-76 unaligned checkpoints (barrier overtaking,
``ChannelStateWriterImpl``) and FLIP-182 aligned-checkpoint timeout.
"""

import time

import numpy as np
import pytest

from flink_tpu.cluster.channels import LocalChannel, element_bytes
from flink_tpu.cluster.task import (AlignmentBufferOverflowError, Subtask,
                                    TaskListener, TaskStates)
from flink_tpu.core.batch import CheckpointBarrier, EndOfInput, RecordBatch
from flink_tpu.core.functions import RuntimeContext
from flink_tpu.datastream.api import StreamExecutionEnvironment
from flink_tpu.runtime.checkpoint.storage import InMemoryCheckpointStorage
from flink_tpu.state.redistribute import (ChannelStateRescaleError,
                                          reject_channel_state)
from flink_tpu.testing import chaos
from flink_tpu.testing.chaos import (CrashOnceAt, FaultInjector, SlowConsumer,
                                     SlowDisk)
from flink_tpu.windowing.assigners import TumblingEventTimeWindows

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    yield
    chaos.uninstall()


def _batch(*vals):
    return RecordBatch({"v": np.asarray(vals, np.float64)})


class _SumOp:
    """Minimal stateful operator: sums the v column, records batch order."""

    name = "sum"
    forwards_watermarks = True
    is_stateless = False
    is_two_input = False

    def open(self, ctx):
        self.total = 0.0
        self.seen = []

    def process_batch(self, batch):
        vals = np.asarray(batch.column("v"))
        self.total += float(vals.sum())
        self.seen.extend(float(v) for v in vals)
        return []

    def process_watermark(self, wm):
        return []

    def on_processing_time(self, ts):
        return []

    def end_input(self):
        return [RecordBatch({"total": np.asarray([self.total])})]

    def snapshot_state(self):
        return {"total": self.total}

    def restore_state(self, snap):
        self.total = snap["total"]

    def notify_checkpoint_complete(self, cid):
        pass

    def close(self):
        pass


class _Recorder(TaskListener):
    def __init__(self):
        self.acks = {}
        self.declines = []
        self.states = []

    def task_state_changed(self, uid, idx, state, error):
        self.states.append((state, error))

    def acknowledge_checkpoint(self, cid, uid, idx, snap):
        self.acks[cid] = snap

    def decline_checkpoint(self, cid, uid, idx, error):
        self.declines.append((cid, error))


class _Out:
    def __init__(self):
        self.elements = []
        self.channels = []

    def emit(self, el):
        self.elements.append(el)


# ---------------------------------------------------------------------------
# SlowConsumer schedule (chaos satellite)
# ---------------------------------------------------------------------------

def test_slow_consumer_is_seeded_and_bursty():
    """Same seed => identical action sequence; stalls come in bursts of
    the configured length; the flaky period is bounded by times."""
    def actions(seed):
        inj = FaultInjector(seed=seed)
        inj.inject("p", SlowConsumer(max_s=0.0, min_s=0.0, p=0.2, burst=4,
                                     times=60))
        with chaos.installed(inj):
            for _ in range(80):
                inj.fire("p")
        return inj.history("p")

    h1, h2 = actions(5), actions(5)
    assert h1 == h2, "same seed must reproduce the exact stall sequence"
    assert actions(6) != h1
    stalls = [i for i, a in enumerate(h1) if isinstance(a, tuple)]
    assert stalls, "schedule never stalled"
    # every stall belongs to a run of at least min(burst, remaining) length
    runs, cur = [], []
    for i, a in enumerate(h1[:60]):
        if isinstance(a, tuple):
            cur.append(i)
        elif cur:
            runs.append(cur)
            cur = []
    if cur:
        runs.append(cur)
    assert all(len(r) >= 4 for r in runs[:-1] or runs), \
        f"stalls not bursty: run lengths {[len(r) for r in runs]}"
    # bounded flaky period: nothing stalls past times
    assert all(a == "ok" for a in h1[64:])


def test_slow_consumer_channel_filter_scopes_stalls():
    """A channel-scoped schedule only advances on matching channels —
    other channels neither stall nor consume the firing counter."""
    inj = FaultInjector(seed=3)
    inj.inject("channel.recv", SlowConsumer(max_s=0.0, p=1.0, burst=2,
                                            channel="a->b"))
    with chaos.installed(inj):
        inj.fire("channel.recv", channel="x->y")
        inj.fire("channel.recv", channel="x->y")
        assert inj.fired("channel.recv") == 0
        inj.fire("channel.recv", channel="a->b[0]")
        assert inj.fired("channel.recv") == 1


def test_slow_consumer_stalls_local_channel_poll():
    inj = FaultInjector(seed=4)
    inj.inject("channel.recv", SlowConsumer(max_s=0.06, min_s=0.04, p=1.0,
                                            burst=1, times=1))
    ch = LocalChannel(4, name="a->b")
    ch.put(_batch(1.0))
    ch.put(_batch(2.0))
    with chaos.installed(inj):
        t0 = time.monotonic()
        assert ch.poll() is not None      # firing 1: stalled
        stalled = time.monotonic() - t0
        t0 = time.monotonic()
        assert ch.poll() is not None      # past times: fast
        fast = time.monotonic() - t0
    assert stalled >= 0.03
    assert fast < 0.03


# ---------------------------------------------------------------------------
# channel-level barrier overtake + backpressure accounting
# ---------------------------------------------------------------------------

def test_take_until_barrier_extracts_prebarrier_elements():
    ch = LocalChannel(16, name="c")
    a, b, c = _batch(1.0), _batch(2.0), _batch(3.0)
    ch.put(a)
    ch.put(b)
    ch.put(CheckpointBarrier(5, 0))
    ch.put(c)
    els, bar = ch.take_until_barrier(5)
    # the consumed BARRIER element comes back (its is_savepoint flag
    # matters to the caller), not just a found-bool
    assert bar is not None and bar.checkpoint_id == 5
    assert els == [a, b]
    assert ch.depth() == 1 and ch.poll() is c
    assert ch.announced_barrier() is None


def test_take_until_barrier_without_barrier_takes_all_queued():
    ch = LocalChannel(16, name="c")
    a = _batch(1.0)
    ch.put(a)
    ch.put(EndOfInput())
    els, bar = ch.take_until_barrier(5)
    assert bar is None and els == [a]
    assert isinstance(ch.poll(), EndOfInput)   # never extracts past EOI


def test_channel_backpressured_time_accumulates():
    ch = LocalChannel(1, name="c")
    assert ch.put(_batch(1.0))
    assert not ch.put(_batch(2.0), timeout_s=0.05)   # full: blocks, times out
    assert ch.backpressured_ns >= 40_000_000
    assert ch.depth() == 1 and ch.queued_bytes() > 0


# ---------------------------------------------------------------------------
# subtask-level: aligned-with-timeout escalation
# ---------------------------------------------------------------------------

def test_alignment_timeout_escalates_and_persists_inflight():
    """Aligned start; the timer (clock seam) expires; the barrier overtakes:
    snapshot at escalation, blocked-queue elements process post-snapshot,
    later pre-barrier data on the laggard channel lands in channel state."""
    ch0, ch1 = LocalChannel(16, "c0"), LocalChannel(16, "c1")
    out = _Out()
    rec = _Recorder()
    op = _SumOp()
    t = Subtask("v1", 0, op, [out], RuntimeContext(), rec, [ch0, ch1],
                alignment_timeout_ms=80)
    t.start()
    ch0.put(_batch(1.0))
    ch1.put(_batch(2.0))
    time.sleep(0.1)
    ch0.put(CheckpointBarrier(1, 0))     # alignment starts, ch0 blocks
    time.sleep(0.03)                     # < timeout: still aligned
    ch0.put(_batch(3.0))                 # post-barrier: alignment queue
    time.sleep(0.25)                     # timer expired -> escalated
    ch1.put(_batch(10.0))                # pre-barrier in-flight on ch1
    time.sleep(0.1)
    ch1.put(CheckpointBarrier(1, 0))     # alignment completes -> ack
    time.sleep(0.1)
    ch0.put(EndOfInput())
    ch1.put(EndOfInput())
    t.join()
    assert t.state == TaskStates.FINISHED

    snap = rec.acks[1]
    # snapshot at ESCALATION: 1+2 only — the queued post-barrier 3.0 and
    # the in-flight 10.0 are post-snapshot effects
    assert snap["operator"]["total"] == 3.0
    cs = snap["channel_state"]
    # v2 write format (ISSUE-14): elements + per-input routing metadata
    assert cs["version"] == 2 and cs["unaligned"]
    els = cs["elements"]
    assert [i for i, _ in els] == [1]
    assert float(np.asarray(els[0][1].column("v"))[0]) == 10.0
    assert cs["persisted_bytes"] > 0
    assert cs["overtaken_bytes"] >= element_bytes(_batch(3.0))
    assert cs["alignment_ms"] >= 50
    # everything was still processed exactly once by the RUNNING job
    assert op.total == 16.0
    # the barrier reached downstream (forwarded at escalation, before the
    # laggard channel delivered its own)
    kinds = [type(e).__name__ for e in out.elements]
    assert "CheckpointBarrier" in kinds
    # subtask-side accounting surfaces the same numbers
    st = t.last_checkpoint_stats
    assert st["unaligned"] and st["persisted_inflight_bytes"] > 0
    assert t.alignment_queue_peak >= 1


def test_pure_unaligned_mode_still_overtakes_at_first_arrival():
    """Back-compat: unaligned=True == alignment_timeout_ms=0 — snapshot
    and forward at FIRST barrier arrival."""
    ch0, ch1 = LocalChannel(16, "c0"), LocalChannel(16, "c1")
    out = _Out()
    rec = _Recorder()
    t = Subtask("v1", 0, _SumOp(), [out], RuntimeContext(), rec, [ch0, ch1],
                unaligned=True)
    assert t.alignment_timeout_ms == 0
    t.start()
    ch0.put(_batch(1.0))
    time.sleep(0.05)
    ch0.put(CheckpointBarrier(1, 0))
    time.sleep(0.05)
    ch1.put(_batch(10.0))
    time.sleep(0.05)
    ch1.put(CheckpointBarrier(1, 0))
    ch0.put(EndOfInput())
    ch1.put(EndOfInput())
    t.join()
    snap = rec.acks[1]
    assert snap["operator"]["total"] == 1.0
    assert snap["channel_state"]["unaligned"]
    assert len(snap["channel_state"]["elements"]) == 1


def test_escalation_extracts_barrier_queued_behind_backlog():
    """The laggard channel's barrier is already QUEUED behind a backlog the
    consumer has not drained: the overtake extracts the backlog into
    channel state and consumes the barrier without waiting — checkpoint
    completion independent of the backpressure."""
    ch0, ch1 = LocalChannel(16, "c0"), LocalChannel(16, "c1")
    out = _Out()
    rec = _Recorder()
    op = _SumOp()
    t = Subtask("v1", 0, op, [out], RuntimeContext(), rec, [ch0, ch1],
                alignment_timeout_ms=60)
    # pre-fill ch1 BEFORE starting: backlog + barrier already queued
    for v in (5.0, 6.0, 7.0, 8.0):
        ch1.put(_batch(v))
    ch1.put(CheckpointBarrier(1, 0))
    # stall ch1's drain so the subtask cannot reach the barrier by polling
    inj = FaultInjector(seed=9)
    inj.inject("channel.recv", SlowConsumer(max_s=0.3, min_s=0.2, p=1.0,
                                            burst=1000, channel="c1"))
    with chaos.installed(inj):
        t.start()
        ch0.put(_batch(1.0))
        time.sleep(0.1)
        ch0.put(CheckpointBarrier(1, 0))   # alignment starts
        deadline = time.monotonic() + 5
        while 1 not in rec.acks and time.monotonic() < deadline:
            time.sleep(0.01)
        assert 1 in rec.acks, "overtake did not complete the checkpoint"
        ch0.put(EndOfInput())
        ch1.put(EndOfInput())
        t.join()
    snap = rec.acks[1]
    cs = snap["channel_state"]
    assert cs["unaligned"]
    vals = [float(np.asarray(el.column("v"))[0]) for _i, el in cs["elements"]]
    # the consistent-cut invariant: every pre-barrier element is EITHER in
    # the operator snapshot or persisted as channel state, exactly once
    assert snap["operator"]["total"] + sum(vals) == 27.0
    assert op.total == 27.0                 # still processed exactly once


def test_savepoint_barrier_never_escalates():
    """A savepoint must stay ALIGNED even with escalation configured —
    its snapshot has to remain rescalable/rewritable, and channel state is
    neither (the drain-then-rescale contract depends on this)."""
    ch0, ch1 = LocalChannel(16, "c0"), LocalChannel(16, "c1")
    rec = _Recorder()
    op = _SumOp()
    t = Subtask("v1", 0, op, [_Out()], RuntimeContext(), rec, [ch0, ch1],
                alignment_timeout_ms=50)
    t.start()
    ch0.put(_batch(1.0))
    time.sleep(0.05)
    ch0.put(CheckpointBarrier(1, 0, is_savepoint=True))
    time.sleep(0.3)                      # far past the 50ms timeout
    ch1.put(_batch(2.0))                 # still pre-barrier on ch1
    time.sleep(0.05)
    ch1.put(CheckpointBarrier(1, 0, is_savepoint=True))
    time.sleep(0.1)
    ch0.put(EndOfInput())
    ch1.put(EndOfInput())
    t.join()
    snap = rec.acks[1]
    cs = snap["channel_state"]
    assert not cs["unaligned"] and cs["elements"] == [], \
        "a savepoint escalated to unaligned"
    # aligned semantics: ch1's pre-barrier element is IN the snapshot
    assert snap["operator"]["total"] == 3.0


def test_stale_barrier_does_not_abort_newer_alignment():
    """The review-found supersession bug: checkpoint 1 escalates but its
    laggard channel is so backpressured that 1 expires and the coordinator
    triggers 2; the fast channel delivers barrier 2 (genuine supersession
    of 1), and THEN the laggard finally drains its buried barrier 1.  The
    stale barrier must be DROPPED — treating any id mismatch as
    supersession would abort the healthy alignment of 2 and cascade
    spurious declines."""
    ch0, ch1 = LocalChannel(16, "c0"), LocalChannel(16, "c1")
    rec = _Recorder()
    op = _SumOp()
    t = Subtask("v1", 0, op, [_Out()], RuntimeContext(), rec, [ch0, ch1],
                alignment_timeout_ms=100)
    t.start()
    ch0.put(CheckpointBarrier(1, 0))     # alignment on 1 starts
    time.sleep(0.3)                      # timer expires -> 1 ESCALATES
    ch0.put(CheckpointBarrier(2, 0))     # coordinator expired 1 -> 2:
    time.sleep(0.1)                      # genuine supersession aborts 1
    assert [cid for cid, _ in rec.declines] == [1]
    ch1.put(_batch(5.0))                 # pre-barrier data for 2 on ch1
    ch1.put(CheckpointBarrier(1, 0))     # STALE barrier finally drains
    ch1.put(CheckpointBarrier(2, 0))     # the real one completes 2
    deadline = time.monotonic() + 10
    while 2 not in rec.acks and time.monotonic() < deadline:
        time.sleep(0.01)
    assert 2 in rec.acks, "stale barrier killed the healthy alignment"
    # exactly one decline ever (the genuine supersession of 1) — the
    # stale barrier caused no second abort
    assert [cid for cid, _ in rec.declines] == [1]
    # consistent cut for 2: the 5.0 is either in the operator snapshot or
    # persisted as channel state, exactly once
    snap = rec.acks[2]
    cs_sum = sum(float(np.asarray(el.column("v")).sum())
                 for _i, el in snap["channel_state"]["elements"])
    assert snap["operator"]["total"] + cs_sum == 5.0
    ch0.put(EndOfInput())
    ch1.put(EndOfInput())
    t.join()
    assert t.state == TaskStates.FINISHED
    assert op.total == 5.0


def test_cluster_savepoint_stays_aligned_under_escalation():
    """MiniCluster.savepoint() marks its barriers: even a job running with
    alignment_timeout_ms produces an ALIGNED savepoint (empty channel
    state) that the rescale guard accepts."""
    env, sink, _ = _window_job(n=4000, batch_size=64)
    storage = InMemoryCheckpointStorage(retain=5)
    import threading as _threading

    from flink_tpu.cluster.minicluster import MiniCluster
    plan = env.get_stream_graph("sp-job").to_plan()
    cluster = MiniCluster(checkpoint_storage=storage,
                          alignment_timeout_ms=0)   # pure unaligned mode
    result = {}

    def run():
        result["res"] = cluster.execute(plan, timeout_s=120)

    th = _threading.Thread(target=run)
    th.start()
    time.sleep(0.15)
    sp = cluster.savepoint()
    th.join(timeout=120)
    if sp is None:
        pytest.skip("job finished before the savepoint could complete")
    snap = storage.load(sp)
    for uid, entry in snap.items():
        if uid.startswith("__"):
            continue
        for sub in entry.get("subtasks", []):
            cs = (sub or {}).get("channel_state")
            if isinstance(cs, dict):
                assert not cs["unaligned"] and cs["elements"] == []
    reject_channel_state(snap, "rescale")   # must not raise


# ---------------------------------------------------------------------------
# bounded alignment queues
# ---------------------------------------------------------------------------

def test_alignment_queue_overflow_raises_classified_error():
    """Cap hit while escalation is DISABLED: loud classified failure, not
    unbounded growth; the pending checkpoint is declined first."""
    ch0, ch1 = LocalChannel(32, "c0"), LocalChannel(32, "c1")
    rec = _Recorder()
    t = Subtask("v1", 0, _SumOp(), [_Out()], RuntimeContext(), rec,
                [ch0, ch1], alignment_queue_max=4)
    assert t.alignment_timeout_ms is None   # aligned, no escalation
    t.start()
    ch0.put(CheckpointBarrier(1, 0))        # ch0 blocks
    time.sleep(0.05)
    for k in range(8):                      # flood the blocked channel
        ch0.put(_batch(float(k)))
    t.join(timeout_s=10)
    assert t.state == TaskStates.FAILED
    err = next(e for s, e in rec.states if s == TaskStates.FAILED)
    assert "AlignmentBufferOverflowError" in err
    assert "alignment queue overflow" in err
    assert rec.declines and rec.declines[0][0] == 1


def test_alignment_queue_overflow_escalates_when_enabled():
    """Same flood with a (long) alignment timeout configured: cap pressure
    escalates to unaligned instead of failing (FLIP-182 size trigger)."""
    ch0, ch1 = LocalChannel(32, "c0"), LocalChannel(32, "c1")
    rec = _Recorder()
    op = _SumOp()
    t = Subtask("v1", 0, op, [_Out()], RuntimeContext(), rec,
                [ch0, ch1], alignment_timeout_ms=60_000,
                alignment_queue_max=4)
    t.start()
    ch0.put(CheckpointBarrier(1, 0))
    time.sleep(0.05)
    for k in range(8):
        ch0.put(_batch(1.0))
    time.sleep(0.2)
    ch1.put(CheckpointBarrier(1, 0))
    time.sleep(0.1)
    ch0.put(EndOfInput())
    ch1.put(EndOfInput())
    t.join()
    assert t.state == TaskStates.FINISHED
    assert 1 in rec.acks and rec.acks[1]["channel_state"]["unaligned"]
    assert op.total == 8.0


def test_savepoint_queue_overflow_declines_savepoint_not_task():
    """A user-triggered savepoint hitting the alignment-queue cap must not
    kill the job: only the savepoint is declined (savepoint() reports
    None); the task keeps running and a later checkpoint still works."""
    ch0, ch1 = LocalChannel(32, "c0"), LocalChannel(32, "c1")
    rec = _Recorder()
    op = _SumOp()
    t = Subtask("v1", 0, op, [_Out()], RuntimeContext(), rec,
                [ch0, ch1], alignment_timeout_ms=100,
                alignment_queue_max=4)
    t.start()
    ch0.put(CheckpointBarrier(1, 0, is_savepoint=True))
    time.sleep(0.05)
    for k in range(8):                      # flood the blocked channel
        ch0.put(_batch(1.0))
    time.sleep(0.3)
    assert t.state == TaskStates.RUNNING, \
        "savepoint overflow must not fail the task"
    assert rec.declines and rec.declines[0][0] == 1
    assert "savepoint" in rec.declines[0][1]
    # a later (regular) checkpoint completes normally
    ch0.put(CheckpointBarrier(2, 0))
    ch1.put(CheckpointBarrier(2, 0))
    deadline = time.monotonic() + 5
    while 2 not in rec.acks and time.monotonic() < deadline:
        time.sleep(0.01)
    assert 2 in rec.acks
    ch0.put(EndOfInput())
    ch1.put(EndOfInput())
    t.join()
    assert t.state == TaskStates.FINISHED
    assert op.total == 8.0                  # released queue fully processed


# ---------------------------------------------------------------------------
# recovery: channel state replays before new input
# ---------------------------------------------------------------------------

def test_restore_replays_v1_channel_state_before_new_input():
    ch = LocalChannel(16, "c0")
    rec = _Recorder()
    op = _SumOp()
    restore = {"operator": {"total": 3.0},
               "channel_state": {"version": 1,
                                 "elements": [(0, _batch(10.0)),
                                              (0, _batch(11.0))],
                                 "persisted_bytes": 64,
                                 "overtaken_bytes": 64,
                                 "alignment_ms": 1.0, "unaligned": True},
               "valve": [0]}
    t = Subtask("v1", 0, op, [_Out()], RuntimeContext(), rec, [ch])
    t.start(restore)
    ch.put(_batch(4.0))
    ch.put(EndOfInput())
    t.join()
    assert t.state == TaskStates.FINISHED
    # replay ORDER: persisted in-flight elements strictly before new input
    assert op.seen == [10.0, 11.0, 4.0]
    assert op.total == 3.0 + 10.0 + 11.0 + 4.0


def test_unknown_channel_state_version_fails_loudly():
    ch = LocalChannel(16, "c0")
    rec = _Recorder()
    restore = {"operator": {"total": 0.0},
               "channel_state": {"version": 99, "elements": []}}
    t = Subtask("v1", 0, _SumOp(), [_Out()], RuntimeContext(), rec, [ch])
    t.start(restore)
    t.join(timeout_s=10)
    assert t.state == TaskStates.FAILED
    err = next(e for s, e in rec.states if s == TaskStates.FAILED)
    assert "channel-state" in err and "99" in err


# ---------------------------------------------------------------------------
# rescale: the keyed rescale path now REDISTRIBUTES v2 channel state
# (tests/test_rescale_under_fire.py); only redistribution-incapable paths
# (and legacy v1 sections with elements) still fail loudly
# ---------------------------------------------------------------------------

def test_reject_helper_rejects_nonempty_channel_state():
    snap = {"__job__": {"checkpoint_id": 7},
            "win": {"subtasks": [
                {"operator": {}, "channel_state": {
                    "version": 1, "elements": [(0, _batch(1.0))],
                    "persisted_bytes": 24, "overtaken_bytes": 24,
                    "alignment_ms": 5.0, "unaligned": True}}]}}
    with pytest.raises(ChannelStateRescaleError, match="drain-then-rescale"):
        reject_channel_state(snap, "offline merge")


def test_rescale_accepts_aligned_checkpoints():
    # aligned checkpoints carry the v1 section with EMPTY elements — and
    # legacy snapshots carry none at all; both must pass
    snap = {"win": {"subtasks": [
        {"operator": {}, "channel_state": {
            "version": 1, "elements": [], "persisted_bytes": 0,
            "overtaken_bytes": 0, "alignment_ms": 0.2,
            "unaligned": False}},
        {"operator": {}}]}}
    reject_channel_state(snap, "rescale")   # no raise


# ---------------------------------------------------------------------------
# observability: job_status / gauges / REST panel
# ---------------------------------------------------------------------------

def _window_job(env_parallelism=2, n=6000, batch_size=64):
    rng = np.random.default_rng(17)
    keys = rng.integers(0, 13, n)
    vals = np.ones(n, np.float64)
    ts = np.sort(rng.integers(0, 3000, n))
    env = StreamExecutionEnvironment()
    env.set_parallelism(env_parallelism)
    sink = (env.from_collection(columns={"k": keys, "v": vals, "t": ts},
                                batch_size=batch_size)
            .assign_timestamps_and_watermarks(0, timestamp_column="t")
            .key_by("k")
            .window(TumblingEventTimeWindows.of(1000))
            .sum("v").collect())
    return env, sink, float(vals.sum())


def _fire_digest(sink):
    return sorted(tuple(sorted((k, float(v)) for k, v in r.items()))
                  for r in sink.rows())


def test_job_status_and_rest_panel_surface_backpressure():
    env, sink, _total = _window_job()
    res = env.execute_cluster(storage=InMemoryCheckpointStorage(retain=5),
                              checkpoint_interval_ms=10,
                              alignment_timeout_ms=5000)
    assert res.state == TaskStates.FINISHED
    status = env._last_cluster.job_status()
    ck = status["checkpoints"]
    for key in ("last_alignment_duration_ms", "last_overtaken_bytes",
                "last_persisted_inflight_bytes", "unaligned_checkpoints"):
        assert key in ck
    # per-checkpoint history carries the alignment accounting
    assert res.completed_checkpoints
    st = status["checkpoint_stats"][-1]
    for key in ("alignment_ms", "overtaken_bytes",
                "persisted_inflight_bytes", "unaligned"):
        assert key in st
    # channel-consuming subtasks expose per-channel gauges
    win = next(v for v in status["vertices"]
               if not v["name"].startswith("collection-source"))
    s0 = win["subtasks"][0]
    assert s0["channels"] and {"name", "depth", "queued_bytes",
                               "backpressured_ms"} <= set(s0["channels"][0])
    assert "alignment_queued" in s0
    # job-scope gauges registered (backpressure.* + lastCheckpoint*)
    names = {k.split(".", 1)[1] if k.startswith("jobmanager.") else k
             for k in env._last_cluster.metrics_registry.all_metrics()}
    assert {"backpressure.total_backpressured_ms",
            "backpressure.max_queue_depth",
            "backpressure.alignment_queued_elements",
            "lastCheckpointAlignmentTime",
            "lastCheckpointPersistedInFlightBytes"} <= names
    # the server-rendered panel renders channel rows + alignment summary
    from flink_tpu.rest.views import backpressure_html
    html = backpressure_html(status["vertices"], ck)
    assert "bp-chan-table" in html and "bp-align-item" in html
    assert 'data-metric="last_persisted_inflight_bytes"' in html


# ---------------------------------------------------------------------------
# acceptance: exactly-once under backpressure, aligned vs unaligned
# ---------------------------------------------------------------------------

def _run_backpressured(unfaulted=False, alignment_timeout_ms=None,
                       checkpoint_timeout_s=60.0, seed=23,
                       crash_at=None, restart_attempts=0):
    """One keyed windowed run; SlowConsumer stalls source-0's channels into
    the window subtasks and SlowDisk stalls the checkpoint store (unless
    unfaulted).  Returns (result, digest, status, cluster, storage).

    Timing margins (CI-safe by construction, not by luck): the stalled
    channel drains one element per ~30-60ms sweep and holds a full
    32-element credit queue, so an ALIGNED barrier needs >=1.4s of drain
    to be reached — while the unaligned path acks in ~0.3s (100ms
    announcement timeout + a few sweeps + the source's barrier-emit lag).
    A 0.8s checkpoint timeout therefore separates the two modes with >=2x
    margin on both sides."""
    env, sink, _ = _window_job(n=12_000, batch_size=64)
    inj = FaultInjector(seed=seed)
    if not unfaulted:
        # bursty drain stalls on ONE source's output channels: its barrier
        # crawls behind the backlog while the sibling's arrives promptly
        inj.inject("channel.recv",
                   SlowConsumer(max_s=0.06, min_s=0.03, p=0.3, burst=40,
                                channel="timestamps[0]->"))
        inj.inject("checkpoint.store",
                   SlowDisk(max_s=0.05, min_s=0.01, p=0.5, times=20))
    if crash_at is not None:
        inj.inject("subtask.run", CrashOnceAt(crash_at))
    storage = InMemoryCheckpointStorage(retain=10)
    with chaos.installed(inj):
        res = env.execute_cluster(
            storage=storage, checkpoint_interval_ms=30,
            checkpoint_timeout_s=checkpoint_timeout_s,
            alignment_timeout_ms=alignment_timeout_ms,
            restart_attempts=restart_attempts,
            tolerable_failed_checkpoints=-1, timeout_s=180)
    return res, _fire_digest(sink), env._last_cluster.job_status(), \
        env._last_cluster, storage


def test_acceptance_unaligned_completes_where_aligned_expires():
    """The ISSUE acceptance scenario: under SlowConsumer + SlowDisk
    backpressure an unaligned-enabled job completes checkpoints that a
    fully-aligned control run (same short timeout) expires — with fire
    digests and job_status counters identical to an unfaulted aligned
    run."""
    # 1. unfaulted aligned baseline
    res_base, digest_base, status_base, _c, _s = _run_backpressured(
        unfaulted=True)
    assert res_base.state == TaskStates.FINISHED

    # 2. aligned CONTROL under backpressure: alignment stalls behind the
    # slow-drained backlog, the short timeout expires the checkpoint
    res_ctl, digest_ctl, status_ctl, _c2, _s2 = _run_backpressured(
        alignment_timeout_ms=None, checkpoint_timeout_s=0.8)
    assert res_ctl.state == TaskStates.FINISHED
    assert status_ctl["checkpoints"]["failed_checkpoints"] >= 1, \
        "the aligned control never expired a checkpoint"
    assert status_ctl["checkpoints"]["last_failure_reason"] == "expired"

    # 3. unaligned run, same timeout: the barrier overtakes the backlog
    res_un, digest_un, status_un, cluster, storage = _run_backpressured(
        alignment_timeout_ms=100, checkpoint_timeout_s=0.8)
    assert res_un.state == TaskStates.FINISHED
    assert res_un.completed_checkpoints, \
        "unaligned run completed no checkpoint under backpressure"
    stats = status_un["checkpoint_stats"]
    assert any(s["unaligned"] for s in stats), \
        "no checkpoint actually escalated to unaligned"
    assert status_un["checkpoints"]["unaligned_checkpoints"] >= 1

    # exactly-once: fire digests identical across all three runs
    assert digest_un == digest_base
    assert digest_ctl == digest_base

    # job_status counters identical to the unfaulted aligned run
    def counters(status):
        return {v["name"]: (v["records_in"], v["records_out"])
                for v in status["vertices"]}

    assert counters(status_un) == counters(status_base)


def test_acceptance_recovery_from_unaligned_checkpoint_exactly_once():
    """Crash mid-run while unaligned checkpoints (with persisted in-flight
    channel state) are the restore source: recovery replays the channel
    state before new input and the fire digests still match the unfaulted
    aligned run."""
    res_base, digest_base, _st, _c, _s = _run_backpressured(unfaulted=True)
    assert res_base.state == TaskStates.FINISHED

    res, digest, status, cluster, storage = _run_backpressured(
        alignment_timeout_ms=100, checkpoint_timeout_s=0.8,
        crash_at=60, restart_attempts=4)
    assert res.state == TaskStates.FINISHED
    assert res.restarts >= 1, "the injected crash did not trigger failover"
    # at least one STORED checkpoint carried persisted in-flight elements
    # (so recovery exercised the channel-state replay path)
    persisted = 0
    for cid in res.completed_checkpoints:
        snap = storage.load(cid)
        if snap is None:
            continue
        for uid, entry in snap.items():
            if uid.startswith("__"):
                continue
            for sub in entry.get("subtasks", []):
                cs = (sub or {}).get("channel_state")
                if isinstance(cs, dict):
                    persisted += len(cs.get("elements", []))
    assert persisted > 0, \
        "no completed checkpoint persisted in-flight channel state"
    assert digest == digest_base, "recovery broke exactly-once fire digests"
