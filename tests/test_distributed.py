"""Cross-process cluster: ProcessCluster coordinator + worker processes,
TCP data plane between workers, distributed checkpoints, restore.

The multi-process analog of ``TaskExecutor.submitTask`` deployment — every
subtask runs in a real separate OS process, cross-process edges ride the
credit-controlled TCP channels of ``cluster/net.py``.
"""

import os
import sys
import textwrap

import numpy as np
import pytest

from flink_tpu.cluster.distributed import (ProcessCluster, assign_subtasks,
                                           build_plan, plan_structure_digest,
                                           subtask_counts_of)
from flink_tpu.runtime.checkpoint.storage import FileCheckpointStorage

pytestmark = pytest.mark.slow

JOB_MODULE = textwrap.dedent('''
    """Deterministic job: keyed sum over 2 source splits, parallelism 2."""
    import numpy as np
    from flink_tpu.datastream.api import StreamExecutionEnvironment

    N = 20_000
    K = 13

    def build():
        env = StreamExecutionEnvironment()
        env.set_parallelism(2)
        keys = (np.arange(N) % K).astype(np.int64)
        vals = np.ones(N)
        (env.from_collection(columns={"k": keys, "v": vals}, batch_size=512)
            .key_by("k").sum("v").collect())
        return env.get_stream_graph("dist-job")
''')


@pytest.fixture
def job_path(tmp_path):
    mod = tmp_path / "dist_job_mod.py"
    mod.write_text(JOB_MODULE)
    sys.path.insert(0, str(tmp_path))
    try:
        yield str(tmp_path), "dist_job_mod:build"
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("dist_job_mod", None)


def test_assignment_is_deterministic_and_total(job_path):
    path, job = job_path
    plan = build_plan(job)
    counts, _ = subtask_counts_of(plan)
    a1 = assign_subtasks(plan, counts, 3)
    a2 = assign_subtasks(build_plan(job), counts, 3)
    assert a1 == a2
    assert set(a1.values()) <= {0, 1, 2}
    assert len(a1) == sum(counts.values())


def test_plan_structure_digest_stable_and_sensitive(job_path):
    """The deploy-time digest is a pure function of plan STRUCTURE: two
    rebuilds of a deterministic job agree; a structural change (different
    record count -> different source split count) does not go unnoticed."""
    path, job = job_path
    d1 = plan_structure_digest(build_plan(job))
    d2 = plan_structure_digest(build_plan(job))
    assert d1 == d2

    from flink_tpu.datastream.api import StreamExecutionEnvironment

    def mini_plan(parallelism):
        env = StreamExecutionEnvironment()
        env.set_parallelism(parallelism)
        keys = (np.arange(1000) % 7).astype(np.int64)
        (env.from_collection(columns={"k": keys, "v": np.ones(1000)},
                             batch_size=256)
            .key_by("k").sum("v").collect())
        return env.get_stream_graph("mini").to_plan()

    assert plan_structure_digest(mini_plan(2)) == \
        plan_structure_digest(mini_plan(2))
    assert plan_structure_digest(mini_plan(2)) != \
        plan_structure_digest(mini_plan(3))


NONDET_JOB_MODULE = textwrap.dedent('''
    """NONDETERMINISTIC job builder: the plan depends on the building
    process (the bug class the deploy digest exists to catch)."""
    import os
    import numpy as np
    from flink_tpu.datastream.api import StreamExecutionEnvironment

    def build():
        env = StreamExecutionEnvironment()
        env.set_parallelism(2)
        keys = (np.arange(2000) % 7).astype(np.int64)
        (env.from_collection(columns={"k": keys, "v": np.ones(2000)},
                             batch_size=512)
            .key_by("k").sum("v")
            .map(lambda cols: cols, name=f"m-{os.getpid()}")
            .collect())
        return env.get_stream_graph("nondet-job")
''')


def test_nondeterministic_builder_rejected_at_deploy(tmp_path):
    """A worker that rebuilds a DIFFERENT plan (per-process operator name
    here) must be rejected at deploy — the job fails fast with a digest
    mismatch instead of silently deploying divergent jobs."""
    mod = tmp_path / "dist_job_nondet.py"
    mod.write_text(NONDET_JOB_MODULE)
    sys.path.insert(0, str(tmp_path))
    try:
        pc = ProcessCluster("dist_job_nondet:build", n_workers=2,
                            extra_sys_path=(str(tmp_path),))
        res = pc.run(timeout_s=120)
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("dist_job_nondet", None)
    assert res["state"] == "FAILED"
    assert "nondeterministic" in res["error"]
    assert "digest" in res["error"]


def test_two_process_job(job_path):
    path, job = job_path
    pc = ProcessCluster(job, n_workers=2, extra_sys_path=(path,))
    res = pc.run(timeout_s=180)
    assert res["state"] == "FINISHED", res["error"]
    totals = {}
    for r in res["rows"]:
        totals[r["k"]] = r["v"]  # running sums: last value wins per key
    n, k = 20_000, 13
    expect = {i: float(len(range(i, n, k))) for i in range(k)}
    assert totals == expect


SLOW_JOB_MODULE = JOB_MODULE.replace("N = 20_000", "N = 60_000").replace(
    "batch_size=512", "batch_size=128")


@pytest.fixture
def slow_job_path(tmp_path):
    mod = tmp_path / "dist_job_slow.py"
    mod.write_text(SLOW_JOB_MODULE)
    sys.path.insert(0, str(tmp_path))
    try:
        yield str(tmp_path), "dist_job_slow:build"
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("dist_job_slow", None)


def _mid_run_checkpoint(store, n_records):
    """Earliest stored checkpoint whose sources had NOT finished."""
    for cid in sorted(store.checkpoint_ids()):
        snap = store.load(cid)
        offsets = [s.get("source_offset", 0)
                   for uid, entry in snap.items() if uid != "__job__"
                   for s in entry.get("subtasks", [])
                   if s is not None and "source_offset" in s]
        if offsets and not all(s.get("finished") for uid, entry in snap.items()
                               if uid != "__job__"
                               for s in entry.get("subtasks", [])
                               if s is not None and "source_offset" in s):
            return cid, snap
    return None, None


def test_two_process_checkpoint_and_restore(slow_job_path, tmp_path):
    path, job = slow_job_path
    store = FileCheckpointStorage(str(tmp_path / "ckpt"))
    pc = ProcessCluster(job, n_workers=2, checkpoint_storage=store,
                        checkpoint_interval_ms=100, extra_sys_path=(path,))
    res = pc.run(timeout_s=300)
    assert res["state"] == "FINISHED", res["error"]
    assert res["completed_checkpoints"], "no checkpoints completed"
    cid, snap = _mid_run_checkpoint(store, 60_000)
    assert snap is not None, "job finished before the first checkpoint"
    assert "__job__" in snap

    # restore the MID-RUN checkpoint in a fresh cluster at a DIFFERENT
    # worker count: sources replay from their offsets, keyed state resumes
    pc2 = ProcessCluster(job, n_workers=3, extra_sys_path=(path,))
    res2 = pc2.run(timeout_s=300, restore=snap)
    assert res2["state"] == "FINISHED", res2["error"]
    totals = {}
    for r in res2["rows"]:
        totals[r["k"]] = max(r["v"], totals.get(r["k"], 0.0))
    n, k = 60_000, 13
    expect = {i: float(len(range(i, n, k))) for i in range(k)}
    # exactly-once across restore: final per-key totals identical
    assert totals == expect


def test_worker_crash_restart_from_checkpoint(tmp_path):
    """Worker-loss recovery: attempt 0 kills one worker mid-run (poison
    pill); the coordinator restarts every worker from the LATEST completed
    checkpoint and the job completes with exactly-once keyed totals."""
    import textwrap

    mod = tmp_path / "crash_job_mod.py"
    mod.write_text(textwrap.dedent('''
        import os
        import numpy as np
        from flink_tpu.datastream.api import StreamExecutionEnvironment

        N = 60_000
        K = 11

        def poison(cols):
            # attempt 0 dies once records past the midpoint flow; later
            # attempts (restored from a checkpoint) run clean
            if os.environ.get("FLINK_TPU_ATTEMPT") == "0" and \\
                    float(np.max(cols["v_total"])) > N // (2 * K):
                os.kill(os.getpid(), 9)   # hard worker loss, no cleanup
            return cols

        def build():
            env = StreamExecutionEnvironment()
            env.set_parallelism(2)
            keys = (np.arange(N) % K).astype(np.int64)
            (env.from_collection(columns={"k": keys, "v": np.ones(N)},
                                 batch_size=128)
                .key_by("k").sum("v", output_column="v_total")
                .map(poison)
                .collect())
            return env.get_stream_graph("crash-job")
    '''))
    sys.path.insert(0, str(tmp_path))
    try:
        store = FileCheckpointStorage(str(tmp_path / "ckpt"))
        pc = ProcessCluster("crash_job_mod:build", n_workers=2,
                            checkpoint_storage=store,
                            checkpoint_interval_ms=100,
                            restart_attempts=2,
                            extra_sys_path=(str(tmp_path),))
        res = pc.run(timeout_s=300)
        assert res["state"] == "FINISHED", res["error"]
        # the poison pill must have fired: recovered either in place
        # (surviving-worker recovery) or via a full restart
        assert res["attempts"] + res.get("recoveries", 0) >= 2
        totals = {}
        for r in res["rows"]:
            totals[r["k"]] = max(r["v_total"], totals.get(r["k"], 0.0))
        n, k = 60_000, 11
        expect = {i: float(len(range(i, n, k))) for i in range(k)}
        assert totals == expect
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("crash_job_mod", None)


def test_surviving_worker_recovery_keeps_other_processes(tmp_path):
    """VERDICT r1 #7: killing 1 of 3 workers recovers WITHOUT restarting
    the other two processes — the dead worker respawns, tasks redeploy
    from the latest checkpoint, surviving PIDs are unchanged."""
    import signal
    import textwrap
    import threading
    import time

    mod = tmp_path / "survive_job_mod.py"
    mod.write_text(textwrap.dedent('''
        import numpy as np
        from flink_tpu.datastream.api import StreamExecutionEnvironment

        N = 60_000
        K = 9

        def build():
            env = StreamExecutionEnvironment()
            env.set_parallelism(3)
            keys = (np.arange(N) % K).astype(np.int64)
            (env.from_collection(columns={"k": keys, "v": np.ones(N)},
                                 batch_size=64)
                .key_by("k").sum("v", output_column="v_total")
                .collect())
            return env.get_stream_graph("survive-job")
    '''))
    sys.path.insert(0, str(tmp_path))
    try:
        store = FileCheckpointStorage(str(tmp_path / "ckpt"))
        pc = ProcessCluster("survive_job_mod:build", n_workers=3,
                            checkpoint_storage=store,
                            checkpoint_interval_ms=50,
                            restart_attempts=2,
                            extra_sys_path=(str(tmp_path),))
        killed = {"pids": None, "victim": None}

        def chaos():
            # wait for the first completed checkpoint, then kill worker 2
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if pc._completed_ids and getattr(pc, "_procs", None):
                    procs = pc._procs
                    if all(p.poll() is None for p in procs):
                        killed["pids"] = [p.pid for p in procs]
                        killed["victim"] = 2
                        os.kill(procs[2].pid, signal.SIGKILL)
                        return
                time.sleep(0.02)

        th = threading.Thread(target=chaos)
        th.start()
        res = pc.run(timeout_s=300)
        th.join()
        assert killed["pids"] is not None, "chaos thread never fired"
        assert res["state"] == "FINISHED", res["error"]
        assert res.get("recoveries", 0) >= 1, res
        assert res["attempts"] == 1, "survivors must not full-restart"
        # the two surviving worker PROCESSES are the original ones
        final_pids = [p.pid for p in pc._procs]
        assert final_pids[0] == killed["pids"][0]
        assert final_pids[1] == killed["pids"][1]
        assert final_pids[2] != killed["pids"][2]
        n, k = 60_000, 9
        totals = {}
        for r in res["rows"]:
            totals[r["k"]] = max(r["v_total"], totals.get(r["k"], 0.0))
        expect = {i: float(len(range(i, n, k))) for i in range(k)}
        assert totals == expect
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("survive_job_mod", None)


def test_subtask_regions_forward_vs_keyed():
    """Region computation: forward chains at equal parallelism are
    per-subtask-index regions; any keyed/all-to-all edge fuses everything
    (RestartPipelinedRegionFailoverStrategy region semantics)."""
    import numpy as np

    from flink_tpu.cluster.failover import subtask_regions
    from flink_tpu.datastream.api import StreamExecutionEnvironment

    env = StreamExecutionEnvironment()
    env.set_parallelism(3)
    (env.from_collection(columns={"v": np.arange(30.)}, batch_size=4)
        .map(lambda c: {"v": np.asarray(c["v"]) * 2}).collect())
    plan = env.get_stream_graph("regions").to_plan()
    counts = {v.uid: v.parallelism for v in plan.vertices}
    regions = subtask_regions(plan, counts)
    # forward pipelines: one region per subtask column
    assert len(regions) == 3
    assert all(len({i for _, i in r}) == 1 for r in regions)

    env2 = StreamExecutionEnvironment()
    env2.set_parallelism(3)
    (env2.from_collection(columns={"k": np.arange(30) % 3,
                                   "v": np.ones(30)}, batch_size=4)
         .key_by("k").sum("v").collect())
    plan2 = env2.get_stream_graph("keyed").to_plan()
    counts2 = {v.uid: v.parallelism for v in plan2.vertices}
    assert len(subtask_regions(plan2, counts2)) == 1  # all-to-all fuses


def test_region_scoped_recovery_survivor_regions_never_restart(tmp_path):
    """VERDICT r2 #6: a 3-worker job of DISJOINT forward pipelines loses
    one worker; only the dead worker's region redeploys — the other two
    regions' tasks never leave RUNNING (no second RUNNING transition),
    and the recovery path is region-scoped, not full."""
    import signal
    import textwrap
    import threading
    import time

    mod = tmp_path / "region_job_mod.py"
    mod.write_text(textwrap.dedent('''
        import numpy as np
        from flink_tpu.datastream.api import StreamExecutionEnvironment

        N = 90_000

        def build():
            env = StreamExecutionEnvironment()
            env.set_parallelism(3)
            (env.from_collection(columns={"v": np.arange(float(N))},
                                 batch_size=32)
                .map(lambda c: {"v2": np.asarray(c["v"]) * 2.0})
                .collect())
            return env.get_stream_graph("region-job")
    '''))
    sys.path.insert(0, str(tmp_path))
    try:
        store = FileCheckpointStorage(str(tmp_path / "ckpt"))
        pc = ProcessCluster("region_job_mod:build", n_workers=3,
                            checkpoint_storage=store,
                            checkpoint_interval_ms=50,
                            restart_attempts=2,
                            extra_sys_path=(str(tmp_path),))
        killed = {"pids": None}

        def chaos():
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if pc._completed_ids and getattr(pc, "_procs", None):
                    procs = pc._procs
                    if all(p.poll() is None for p in procs):
                        killed["pids"] = [p.pid for p in procs]
                        os.kill(procs[2].pid, signal.SIGKILL)
                        return
                time.sleep(0.02)

        th = threading.Thread(target=chaos)
        th.start()
        res = pc.run(timeout_s=300)
        th.join()
        assert killed["pids"] is not None, "chaos thread never fired"
        assert res["state"] == "FINISHED", res["error"]
        assert res.get("recoveries", 0) >= 1, res
        assert res["attempts"] == 1
        assert pc._last_recovery == "region", pc._last_recovery
        # survivors kept their PIDs
        final_pids = [p.pid for p in pc._procs]
        assert final_pids[0] == killed["pids"][0]
        assert final_pids[1] == killed["pids"][1]
        # the dead worker's region redeployed; UNAFFECTED subtasks have
        # exactly ONE RUNNING transition in the whole run
        running_counts = {}
        for uid, i, st in pc._state_log:
            if st == "RUNNING":
                running_counts[(uid, i)] = running_counts.get((uid, i),
                                                              0) + 1
        from flink_tpu.cluster.distributed import (assign_subtasks,
                                                   build_plan,
                                                   subtask_counts_of)
        plan = build_plan("region_job_mod:build")
        counts, _ = subtask_counts_of(plan)
        assign = assign_subtasks(plan, counts, 3)
        for key, w in assign.items():
            if w != 2:
                assert running_counts.get(key, 0) == 1, (key, running_counts)
            else:
                assert running_counts.get(key, 0) >= 2, (key, running_counts)
        # every record accounted for exactly once (exactly-once collect)
        vals = sorted(r["v2"] for r in res["rows"])
        assert len(vals) == 90_000
        assert vals == [2.0 * i for i in range(90_000)]
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("region_job_mod", None)


def test_local_recovery_zero_remote_reads_on_same_worker_restart(tmp_path):
    """Local recovery (TaskLocalStateStoreImpl.java:54): with a local
    recovery dir configured, a crash-and-restore restores EVERY subtask
    from its worker-local snapshot copy — zero subtask states are read
    from the coordinator-shipped remote checkpoint."""
    import textwrap

    mod = tmp_path / "localrec_job_mod.py"
    mod.write_text(textwrap.dedent('''
        import os
        import numpy as np
        from flink_tpu.datastream.api import StreamExecutionEnvironment

        N = 60_000
        K = 11

        def poison(cols):
            if os.environ.get("FLINK_TPU_ATTEMPT") == "0" and \\
                    float(np.max(cols["v_total"])) > N // (2 * K):
                os.kill(os.getpid(), 9)
            return cols

        def build():
            env = StreamExecutionEnvironment()
            env.set_parallelism(2)
            keys = (np.arange(N) % K).astype(np.int64)
            (env.from_collection(columns={"k": keys, "v": np.ones(N)},
                                 batch_size=128)
                .key_by("k").sum("v", output_column="v_total")
                .map(poison)
                .collect())
            return env.get_stream_graph("localrec-job")
    '''))
    sys.path.insert(0, str(tmp_path))
    try:
        store = FileCheckpointStorage(str(tmp_path / "ckpt"))
        pc = ProcessCluster("localrec_job_mod:build", n_workers=2,
                            checkpoint_storage=store,
                            checkpoint_interval_ms=100,
                            restart_attempts=2,
                            local_recovery_dir=str(tmp_path / "local"),
                            extra_sys_path=(str(tmp_path),))
        res = pc.run(timeout_s=300)
        assert res["state"] == "FINISHED", res["error"]
        assert res["attempts"] + res.get("recoveries", 0) >= 2
        # recovery happened, and every restored subtask came from the
        # LOCAL store: zero remote (shipped-state) reads
        assert pc.recovery_stats, "no recovery stats reported"
        total_local = sum(s[1] for s in pc.recovery_stats)
        total_remote = sum(s[2] for s in pc.recovery_stats)
        assert total_local > 0
        assert total_remote == 0, pc.recovery_stats
        # and correctness held (exactly-once totals)
        totals = {}
        for r in res["rows"]:
            totals[r["k"]] = max(r["v_total"], totals.get(r["k"], 0.0))
        n, k = 60_000, 11
        expect = {i: float(len(range(i, n, k))) for i in range(k)}
        assert totals == expect
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("localrec_job_mod", None)


def test_unaligned_checkpoints_thread_through_process_cluster(slow_job_path,
                                                              tmp_path):
    """ISSUE-5: the unaligned-checkpoint policy ships with the deploy
    message (ckpt_opts); worker Subtasks overtake at the first barrier,
    acks carry the versioned channel-state section, the coordinator
    aggregates the alignment accounting, and a restore from an unaligned
    checkpoint replays channel state with exactly-once totals."""
    path, job = slow_job_path
    store = FileCheckpointStorage(str(tmp_path / "ckpt"))
    pc = ProcessCluster(job, n_workers=2, checkpoint_storage=store,
                        checkpoint_interval_ms=100, extra_sys_path=(path,),
                        alignment_timeout_ms=0)
    assert pc.ckpt_opts["alignment_timeout_ms"] == 0
    res = pc.run(timeout_s=300)
    assert res["state"] == "FINISHED", res["error"]
    assert res["completed_checkpoints"], "no checkpoints completed"
    stats = res["checkpoint_stats"]
    assert stats, "coordinator collected no per-checkpoint stats"
    assert any(s["unaligned"] for s in stats), \
        "no checkpoint recorded a barrier overtake"
    for s in stats:
        assert {"alignment_ms", "overtaken_bytes",
                "persisted_inflight_bytes"} <= set(s)
    totals = {}
    for r in res["rows"]:
        totals[r["k"]] = max(r["v"], totals.get(r["k"], 0.0))
    n, k = 60_000, 13
    expect = {i: float(len(range(i, n, k))) for i in range(k)}
    assert totals == expect

    cid, snap = _mid_run_checkpoint(store, n)
    if snap is None:
        pytest.skip("job finished before a mid-run checkpoint completed")
    # worker acks persisted the VERSIONED channel-state section
    sections = [sub["channel_state"]
                for uid, entry in snap.items() if not uid.startswith("__")
                for sub in entry.get("subtasks", [])
                if isinstance(sub, dict)
                and isinstance(sub.get("channel_state"), dict)]
    assert sections and all(cs["version"] == 1 for cs in sections)
    assert any(cs["unaligned"] for cs in sections)

    # restore at a DIFFERENT worker count: channel state replays into the
    # same subtasks (placement changes, parallelism does not)
    pc2 = ProcessCluster(job, n_workers=3, extra_sys_path=(path,))
    res2 = pc2.run(timeout_s=300, restore=snap)
    assert res2["state"] == "FINISHED", res2["error"]
    totals2 = {}
    for r in res2["rows"]:
        totals2[r["k"]] = max(r["v"], totals2.get(r["k"], 0.0))
    assert totals2 == expect
