"""PostgreSQL wire protocol v3: server, client, and the JDBC-analog seams.

Byte-level frames are hand-built against the spec (not via the client) so
the server's dialect is validated independently of this repo's own
frontend — the same methodology as the Kafka v0/v2 wire tests.  Reference
anchors: ``flink-connector-jdbc/.../JdbcSink.java:37`` (batched sink),
``JdbcSink.exactlyOnceSink:101`` + ``JdbcXaSinkFunction.java`` (2PC),
``JdbcNumericBetweenParametersProvider.java:42`` (partitioned reads).
"""

from __future__ import annotations

import hashlib
import socket
import struct

import numpy as np
import pytest

from flink_tpu.connectors.postgres import (
    PROTOCOL_V3, PostgresError, PostgresSink, PostgresSource,
    PostgresWireClient, PostgresWireServer, md5_password, read_message)
from flink_tpu.core.batch import RecordBatch


@pytest.fixture
def server():
    srv = PostgresWireServer()
    yield srv
    srv.close()


def connect(srv, **kw) -> PostgresWireClient:
    return PostgresWireClient(srv.host, srv.port, **kw)


def seed(srv, n=100):
    with connect(srv) as c:
        c.execute("CREATE TABLE t (id int8 PRIMARY KEY, v float8, "
                  "name text)")
        rows = ", ".join(f"({i}, {i * 0.5!r}, 'n{i}')" for i in range(n))
        c.execute(f"INSERT INTO t (id, v, name) VALUES {rows}")


# ---------------------------------------------------------------------------
# byte-level protocol (hand-built frames, no client involved)
# ---------------------------------------------------------------------------


class TestWireBytes:
    def _startup(self, sock, user="alice", database="db"):
        payload = struct.pack(">i", PROTOCOL_V3)
        payload += b"user\0" + user.encode() + b"\0"
        payload += b"database\0" + database.encode() + b"\0\0"
        sock.sendall(struct.pack(">i", len(payload) + 4) + payload)

    def test_trust_handshake_and_query_cycle(self, server):
        with connect(server) as c:
            c.execute("CREATE TABLE w (a int4, b text)")
            c.execute("INSERT INTO w (a, b) VALUES (7, 'x'), (8, NULL)")
        sock = socket.create_connection((server.host, server.port))
        try:
            self._startup(sock)
            # AuthenticationOk: 'R' with int32 code 0
            t, body = read_message(sock)
            assert t == b"R" and struct.unpack(">i", body)[0] == 0
            # ParameterStatus* / BackendKeyData until ReadyForQuery 'Z' 'I'
            while True:
                t, body = read_message(sock)
                if t == b"Z":
                    assert body == b"I"
                    break
                assert t in (b"S", b"K")
            # simple Query: 'Q' + cstring
            q = b"SELECT a, b FROM w ORDER BY a\0"
            sock.sendall(b"Q" + struct.pack(">i", len(q) + 4) + q)
            t, body = read_message(sock)
            assert t == b"T"
            nfields = struct.unpack(">h", body[:2])[0]
            assert nfields == 2
            # first field: name cstring 'a', oid int4=23 at bytes +6..10
            end = body.index(b"\0", 2)
            assert body[2:end] == b"a"
            oid = struct.unpack(">i", body[end + 7:end + 11])[0]
            assert oid == 23
            t, body = read_message(sock)
            assert t == b"D"
            ncols = struct.unpack(">h", body[:2])[0]
            assert ncols == 2
            l0 = struct.unpack(">i", body[2:6])[0]
            assert body[6:6 + l0] == b"7"
            t, body = read_message(sock)   # second row: b is NULL (-1 len)
            assert t == b"D"
            off = 2
            l0 = struct.unpack(">i", body[off:off + 4])[0]
            off += 4 + l0
            l1 = struct.unpack(">i", body[off:off + 4])[0]
            assert l1 == -1
            t, body = read_message(sock)
            assert t == b"C" and body.rstrip(b"\0") == b"SELECT 2"
            t, body = read_message(sock)
            assert t == b"Z" and body == b"I"
        finally:
            sock.close()

    def test_md5_auth_bytes(self):
        srv = PostgresWireServer(users={"alice": "secret"})
        try:
            sock = socket.create_connection((srv.host, srv.port))
            self._startup(sock, user="alice")
            t, body = read_message(sock)
            assert t == b"R" and struct.unpack(">i", body[:4])[0] == 5
            salt = body[4:8]
            # spec: md5( hex(md5(password+user)) + salt )
            inner = hashlib.md5(b"secretalice").hexdigest()
            digest = "md5" + hashlib.md5(inner.encode() + salt).hexdigest()
            pw = digest.encode() + b"\0"
            sock.sendall(b"p" + struct.pack(">i", len(pw) + 4) + pw)
            t, body = read_message(sock)
            assert t == b"R" and struct.unpack(">i", body)[0] == 0
            sock.close()
        finally:
            srv.close()

    def test_md5_auth_rejects_wrong_password(self):
        srv = PostgresWireServer(users={"alice": "secret"})
        try:
            with pytest.raises(PostgresError, match="authentication"):
                connect(srv, user="alice", password="wrong")
            # and the right password connects fine via the client
            with connect(srv, user="alice", password="secret") as c:
                c.execute("CREATE TABLE ok (x int4)")
        finally:
            srv.close()

    def test_error_response_fields(self, server):
        with connect(server) as c:
            with pytest.raises(PostgresError) as ei:
                c.query("SELECT * FROM missing")
            assert ei.value.fields["S"] == "ERROR"
            assert "missing" in ei.value.fields["M"]
            # connection stays usable after an error
            c.execute("CREATE TABLE after_err (x int4)")


# ---------------------------------------------------------------------------
# client/server SQL surface
# ---------------------------------------------------------------------------


class TestSqlSurface:
    def test_types_round_trip(self, server):
        with connect(server) as c:
            c.execute("CREATE TABLE ty (i int4, l bigint, f real, "
                      "d double precision, s text, b bool)")
            c.execute("INSERT INTO ty (i, l, f, d, s, b) VALUES "
                      "(1, 5000000000, 1.5, 2.25, 'it''s', TRUE)")
            cols = c.query_columns("SELECT * FROM ty")
        assert cols["i"].dtype == np.int32 and cols["i"][0] == 1
        assert cols["l"].dtype == np.int64 and cols["l"][0] == 5000000000
        assert cols["f"].dtype == np.float32
        assert cols["d"][0] == 2.25
        assert cols["s"][0] == "it's"
        assert cols["b"][0] == np.True_

    def test_where_order_limit_and_aggregates(self, server):
        seed(server, 50)
        with connect(server) as c:
            cols = c.query_columns(
                "SELECT id FROM t WHERE id >= 10 AND id < 13 ORDER BY id")
            assert cols["id"].tolist() == [10, 11, 12]
            cols = c.query_columns(
                "SELECT id FROM t ORDER BY id DESC LIMIT 3")
            assert cols["id"].tolist() == [49, 48, 47]
            agg = c.query_columns(
                "SELECT MIN(id), MAX(id), COUNT(*) FROM t WHERE id > 40")
            assert agg["min"][0] == 41 and agg["max"][0] == 49
            assert agg["count"][0] == 9

    def test_upsert_on_primary_key(self, server):
        with connect(server) as c:
            c.execute("CREATE TABLE u (k int4 PRIMARY KEY, v text)")
            c.execute("INSERT INTO u (k, v) VALUES (1, 'a')")
            with pytest.raises(PostgresError, match="duplicate key"):
                c.execute("INSERT INTO u (k, v) VALUES (1, 'b')")
            c.execute("INSERT INTO u (k, v) VALUES (1, 'b') "
                      "ON CONFLICT (k) DO UPDATE SET v = EXCLUDED.v")
            cols = c.query_columns("SELECT v FROM u WHERE k = 1")
            assert cols["v"].tolist() == ["b"]

    def test_transactions_and_rollback(self, server):
        with connect(server) as c:
            c.execute("CREATE TABLE tx (x int4)")
            c.execute("BEGIN")
            c.execute("INSERT INTO tx (x) VALUES (1)")
            c.execute("ROLLBACK")
            assert c.query_columns("SELECT COUNT(*) FROM tx")["count"][0] == 0
            c.execute("BEGIN")
            c.execute("INSERT INTO tx (x) VALUES (2)")
            c.execute("COMMIT")
            assert c.query_columns("SELECT COUNT(*) FROM tx")["count"][0] == 1

    def test_semicolon_inside_string_literal(self, server):
        with connect(server) as c:
            c.execute("CREATE TABLE semi (s text)")
            c.execute("INSERT INTO semi (s) VALUES ('a;b'); "
                      "INSERT INTO semi (s) VALUES ('c')")
            cols = c.query_columns("SELECT s FROM semi ORDER BY s")
            assert cols["s"].tolist() == ["a;b", "c"]

    def test_nan_and_infinity_literals(self, server):
        with connect(server) as c:
            c.execute("CREATE TABLE fl (x float8)")
            c.execute("INSERT INTO fl (x) VALUES (NaN), (Infinity), "
                      "(-Infinity), (1.5)")
            cols = c.query_columns("SELECT x FROM fl WHERE x >= 1")
        vals = cols["x"]
        assert np.isinf(vals).sum() == 1 and (vals == 1.5).sum() == 1

    def test_unparseable_literal_errors_not_drops(self, server):
        """A VALUES tuple the server cannot parse must ERROR — silently
        skipping it would lose rows inside a committed transaction."""
        with connect(server) as c:
            c.execute("CREATE TABLE strict (x int4)")
            with pytest.raises(PostgresError, match="literal|VALUES"):
                c.execute("INSERT INTO strict (x) VALUES (1), (oops), (3)")
            assert c.query_columns(
                "SELECT COUNT(*) FROM strict")["count"][0] == 0

    def test_order_by_with_nulls_and_bad_where_keep_connection(self, server):
        with connect(server) as c:
            c.execute("CREATE TABLE nl (a int4, b int4)")
            c.execute("INSERT INTO nl (a, b) VALUES (1, 10), (2, NULL), "
                      "(3, 5)")
            cols = c.query_columns("SELECT a FROM nl ORDER BY b")
            assert cols["a"].tolist() == [3, 1, 2]  # NULL sorts last
            # a type-confused WHERE returns an error, not a dead socket
            c.execute("CREATE TABLE tw (s text)")
            c.execute("INSERT INTO tw (s) VALUES ('x')")
            with pytest.raises(PostgresError):
                c.query("SELECT * FROM tw WHERE s < 5")
            assert c.query_columns(
                "SELECT COUNT(*) FROM tw")["count"][0] == 1

    def test_multi_statement_result_is_last_statement(self, server):
        with connect(server) as c:
            c.execute("CREATE TABLE m1 (a int4)")
            c.execute("CREATE TABLE m2 (b text)")
            c.execute("INSERT INTO m1 (a) VALUES (1), (2)")
            c.execute("INSERT INTO m2 (b) VALUES ('z')")
            fields, rows = c.query("SELECT a FROM m1; SELECT b FROM m2")
            assert [f[0] for f in fields] == ["b"]
            assert rows == [["z"]]  # not m1's rows under m2's fields

    def test_failed_commit_prepared_is_atomic(self, server):
        """COMMIT PREPARED hitting a constraint violation must leave the
        txn prepared and the table untouched (retry-able), not half-applied
        with the gid lost."""
        with connect(server) as c:
            c.execute("CREATE TABLE at (k int4 PRIMARY KEY)")
            c.execute("INSERT INTO at (k) VALUES (7)")
            c.execute("BEGIN")
            c.execute("INSERT INTO at (k) VALUES (6)")
            c.execute("INSERT INTO at (k) VALUES (7)")  # will conflict
            c.execute("PREPARE TRANSACTION 'atomic-1'")
            with pytest.raises(PostgresError, match="duplicate key"):
                c.execute("COMMIT PREPARED 'atomic-1'")
            # nothing applied, txn still prepared (could be rolled back)
            assert c.query_columns(
                "SELECT COUNT(*) FROM at")["count"][0] == 1
            assert server.list_prepared() == ["atomic-1"]
            c.execute("ROLLBACK PREPARED 'atomic-1'")

    def test_two_phase_commit(self, server, tmp_path):
        with connect(server) as c:
            c.execute("CREATE TABLE p2 (x int4)")
            c.execute("BEGIN")
            c.execute("INSERT INTO p2 (x) VALUES (1)")
            c.execute("PREPARE TRANSACTION 'gid-1'")
            # prepared but not committed: invisible
            assert c.query_columns("SELECT COUNT(*) FROM p2")["count"][0] == 0
        assert server.list_prepared() == ["gid-1"]
        # a DIFFERENT connection can commit it (that is the point of 2PC)
        with connect(server) as c:
            c.execute("COMMIT PREPARED 'gid-1'")
            assert c.query_columns("SELECT COUNT(*) FROM p2")["count"][0] == 1
            # replayed commit is idempotent; unknown gid errors
            c.execute("COMMIT PREPARED 'gid-1'")
            with pytest.raises(PostgresError, match="does not exist"):
                c.execute("COMMIT PREPARED 'never-prepared'")
            # rollback of an absent gid errors, matching real PostgreSQL —
            # recovery code must enumerate pg_prepared_xacts instead
            with pytest.raises(PostgresError, match="does not exist"):
                c.execute("ROLLBACK PREPARED 'never-prepared'")

    def test_pg_prepared_xacts_view(self, server):
        with connect(server) as c:
            c.execute("CREATE TABLE px (x int4)")
            for gid in ("view-b", "view-a"):
                c.execute("BEGIN")
                c.execute("INSERT INTO px (x) VALUES (1)")
                c.execute(f"PREPARE TRANSACTION '{gid}'")
            cols = c.query_columns("SELECT gid FROM pg_prepared_xacts")
            assert list(cols["gid"]) == ["view-a", "view-b"]
            c.execute("ROLLBACK PREPARED 'view-a'")
            c.execute("ROLLBACK PREPARED 'view-b'")
            assert list(c.query_columns(
                "SELECT gid FROM pg_prepared_xacts")["gid"]) == []

    def test_plain_commit_is_atomic(self, server):
        """A constraint violation inside COMMIT must roll back the WHOLE
        txn — not leave the rows staged before the offending one applied."""
        with connect(server) as c:
            c.execute("CREATE TABLE ac (k int4 PRIMARY KEY)")
            c.execute("INSERT INTO ac (k) VALUES (7)")
            c.execute("BEGIN")
            c.execute("INSERT INTO ac (k) VALUES (1)")
            c.execute("INSERT INTO ac (k) VALUES (7)")   # will collide
            c.execute("INSERT INTO ac (k) VALUES (2)")
            with pytest.raises(PostgresError, match="duplicate key"):
                c.execute("COMMIT")
            assert c.query_columns(
                "SELECT COUNT(*) FROM ac")["count"][0] == 1


# ---------------------------------------------------------------------------
# connector seams
# ---------------------------------------------------------------------------


class TestSourceSeam:
    def test_partitioned_splits_cover_exactly(self, server):
        seed(server, 100)
        src = PostgresSource(server.host, server.port, "t",
                             partition_column="id", batch_size=16)
        splits = src.create_splits(3)
        assert len(splits) == 3
        seen = []
        for sp in splits:
            for el in sp.read():
                seen.extend(np.asarray(el.column("id")).tolist())
        assert sorted(seen) == list(range(100))

    def test_float_partition_column_no_gaps(self, server):
        """Fractional values must not fall between splits (integer-rounded
        inclusive ranges would silently drop them)."""
        with connect(server) as c:
            c.execute("CREATE TABLE ft (x float8, tag int4)")
            vals = ", ".join(f"({i * 0.7!r}, {i})" for i in range(30))
            c.execute(f"INSERT INTO ft (x, tag) VALUES {vals}")
        src = PostgresSource(server.host, server.port, "ft",
                             partition_column="x", batch_size=8)
        seen = []
        for sp in src.create_splits(4):
            for el in sp.read():
                seen.extend(np.asarray(el.column("tag")).tolist())
        assert sorted(seen) == list(range(30))

    def test_int8_splits_beyond_float53_cover_exactly(self, server):
        """int8 partition bounds beyond 2^53: float() rounding would push
        split boundaries past true MIN/MAX and silently drop boundary rows;
        integer arithmetic must keep every row in exactly one split."""
        with connect(server) as c:
            c.execute("CREATE TABLE big (id int8, v int4)")
            base = 2 ** 60 + 1
            vals = ", ".join(f"({base + i * 997}, {i})" for i in range(20))
            c.execute(f"INSERT INTO big (id, v) VALUES {vals}")
        src = PostgresSource(server.host, server.port, "big",
                             partition_column="id", batch_size=8)
        seen = []
        for sp in src.create_splits(4):
            for el in sp.read():
                seen.extend(np.asarray(el.column("v")).tolist())
        assert sorted(seen) == list(range(20))

    def test_positioned_reader_resumes_mid_split(self, server):
        seed(server, 40)
        src = PostgresSource(server.host, server.port, "t",
                             partition_column="id", batch_size=8)
        (split,) = src.create_splits(1)
        reader = src.open_split(split, None)
        first = next(reader)
        assert reader.position == 8
        # resume a fresh reader from the checkpointed position
        resumed = src.open_split(split, reader.position)
        rest = [np.asarray(b.column("id")) for b in resumed]
        got = np.concatenate([np.asarray(first.column("id"))] + rest)
        assert got.tolist() == list(range(40))

    def test_source_in_pipeline(self, server):
        seed(server, 60)
        from flink_tpu.datastream.api import StreamExecutionEnvironment

        env = StreamExecutionEnvironment.get_execution_environment()
        rows = (env.from_source(
            PostgresSource(server.host, server.port, "t",
                           partition_column="id", columns=["id", "v"]),
            "pg")
            .key_by("id")
            .sum("v", output_column="total")
            .execute_and_collect())
        assert len(rows) == 60
        total = sum(r["total"] for r in rows)
        assert total == pytest.approx(sum(i * 0.5 for i in range(60)))


class TestSinkSeam:
    def test_at_least_once_buffered_insert(self, server):
        with connect(server) as c:
            c.execute("CREATE TABLE out1 (k int8, v float8)")
        sink = PostgresSink(server.host, server.port, "out1",
                            columns=["k", "v"], buffer_rows=8)
        sink.write_batch(RecordBatch({
            "k": np.arange(20, dtype=np.int64),
            "v": np.arange(20, dtype=np.float64) * 2.0}))
        sink.flush()
        sink.close()
        with connect(server) as c:
            cols = c.query_columns("SELECT k, v FROM out1 ORDER BY k")
        assert cols["k"].tolist() == list(range(20))
        assert cols["v"][3] == 6.0

    def test_upsert_sink_idempotent_rewrites(self, server):
        """upsert=True emits the full PostgreSQL ON CONFLICT ... DO UPDATE
        SET form (valid against real servers); re-writing the same keys
        converges instead of erroring — the reference's idempotent
        at-least-once shape."""
        with connect(server) as c:
            c.execute("CREATE TABLE up (k int8 PRIMARY KEY, v float8)")
        sink = PostgresSink(server.host, server.port, "up",
                            columns=["k", "v"], upsert=True,
                            conflict_column="k")
        sink.write_batch(RecordBatch({"k": np.asarray([1, 2], np.int64),
                                      "v": np.asarray([1.0, 2.0])}))
        sink.flush()
        sink.write_batch(RecordBatch({"k": np.asarray([2, 3], np.int64),
                                      "v": np.asarray([20.0, 3.0])}))
        sink.flush()
        sink.close()
        with connect(server) as c:
            cols = c.query_columns("SELECT k, v FROM up ORDER BY k")
        assert cols["k"].tolist() == [1, 2, 3]
        assert cols["v"].tolist() == [1.0, 20.0, 3.0]

    def test_exactly_once_2pc_commit_on_notify(self, server):
        with connect(server) as c:
            c.execute("CREATE TABLE out2 (k int8 PRIMARY KEY, v float8)")
        sink = PostgresSink(server.host, server.port, "out2",
                            columns=["k", "v"], exactly_once=True,
                            sink_id="xo")
        sink.write_batch(RecordBatch({"k": np.asarray([1, 2], np.int64),
                                      "v": np.asarray([.5, .25])}))
        snap = sink.snapshot_state()
        with connect(server) as c:   # staged, not visible yet
            assert c.query_columns(
                "SELECT COUNT(*) FROM out2")["count"][0] == 0
        sink.notify_checkpoint_complete(1)
        with connect(server) as c:
            assert c.query_columns(
                "SELECT COUNT(*) FROM out2")["count"][0] == 2
        assert [g for g, _cid in snap["staged"]] == ["xo-s0-0"]
        sink.close()

    def test_notify_skips_epochs_of_later_checkpoints(self, server):
        """TwoPhaseCommitSinkFunction contract: notify(N) must not commit
        an epoch staged for checkpoint N+1 — a restore to N would replay
        its rows and duplicate them."""
        from flink_tpu.operators.base import snapshot_scope

        with connect(server) as c:
            c.execute("CREATE TABLE outn (k int8)")
        sink = PostgresSink(server.host, server.port, "outn",
                            columns=["k"], exactly_once=True, sink_id="nf")
        sink.write_batch(RecordBatch({"k": np.asarray([1], np.int64)}))
        with snapshot_scope(1):
            sink.snapshot_state()
        sink.write_batch(RecordBatch({"k": np.asarray([2], np.int64)}))
        with snapshot_scope(2):
            sink.snapshot_state()
        sink.notify_checkpoint_complete(1)
        with connect(server) as c:
            assert c.query_columns(
                "SELECT COUNT(*) FROM outn")["count"][0] == 1
        assert server.list_prepared() == ["nf-s0-1"]  # ckpt-2 epoch staged
        sink.notify_checkpoint_complete(2)
        with connect(server) as c:
            assert c.query_columns(
                "SELECT COUNT(*) FROM outn")["count"][0] == 2
        sink.close()

    def test_exactly_once_restore_no_dups_no_loss(self, server):
        """Kill-and-restore: epoch staged at the checkpoint commits exactly
        once via the restore replay; the epoch staged AFTER the restored
        checkpoint (its rows will be replayed by upstream) rolls back."""
        with connect(server) as c:
            c.execute("CREATE TABLE out3 (k int8, v float8)")

        sink = PostgresSink(server.host, server.port, "out3",
                            columns=["k", "v"], exactly_once=True,
                            sink_id="xo3")
        sink.write_batch(RecordBatch({"k": np.asarray([1], np.int64),
                                      "v": np.asarray([1.0])}))
        snap = sink.snapshot_state()          # epoch 0 staged @ checkpoint 1
        # ... checkpoint 1's notification is LOST, job keeps running ...
        sink.write_batch(RecordBatch({"k": np.asarray([2], np.int64),
                                      "v": np.asarray([2.0])}))
        sink.snapshot_state()                 # epoch 1 staged @ checkpoint 2
        del sink                              # crash before checkpoint 2 completes

        restored = PostgresSink(server.host, server.port, "out3",
                                columns=["k", "v"], exactly_once=True,
                                sink_id="xo3")
        restored.restore_state(snap)
        # epoch 0 committed by the restore replay; epoch 1 rolled back
        with connect(server) as c:
            cols = c.query_columns("SELECT k FROM out3 ORDER BY k")
        assert cols["k"].tolist() == [1]
        assert server.list_prepared() == []
        # upstream replays the post-checkpoint rows; next epoch commits them
        restored.write_batch(RecordBatch({"k": np.asarray([2], np.int64),
                                          "v": np.asarray([2.0])}))
        restored.snapshot_state()
        restored.notify_checkpoint_complete(2)
        with connect(server) as c:
            cols = c.query_columns("SELECT k FROM out3 ORDER BY k")
        assert cols["k"].tolist() == [1, 2]
        restored.close()

    def test_restore_far_behind_crash_cleans_all_danglers(self, server):
        """Restoring to a checkpoint arbitrarily far behind the crash must
        still find and roll back every dangling epoch: the restore path
        enumerates pg_prepared_xacts instead of probing a bounded gid
        window (70 dangling epochs > the old 64-epoch probe)."""
        with connect(server) as c:
            c.execute("CREATE TABLE deep (k int8)")
        sink = PostgresSink(server.host, server.port, "deep",
                            columns=["k"], exactly_once=True, sink_id="dp")
        sink.write_batch(RecordBatch({"k": np.asarray([0], np.int64)}))
        snap = sink.snapshot_state()          # epoch 0 @ checkpoint 1
        for i in range(1, 71):                # 70 epochs past the checkpoint
            sink.write_batch(RecordBatch({"k": np.asarray([i], np.int64)}))
            sink.snapshot_state()
        del sink                              # crash; none ever notified

        restored = PostgresSink(server.host, server.port, "deep",
                                columns=["k"], exactly_once=True,
                                sink_id="dp")
        restored.restore_state(snap)
        assert server.list_prepared() == []   # every dangler rolled back
        with connect(server) as c:
            cols = c.query_columns("SELECT k FROM deep ORDER BY k")
        assert cols["k"].tolist() == [0]      # only the restored epoch
        restored.close()

    def test_prepared_txns_survive_server_restart(self, tmp_path):
        d = str(tmp_path / "pgdata")
        srv = PostgresWireServer(persist_dir=d)
        try:
            with connect(srv) as c:
                c.execute("CREATE TABLE r (x int4)")
                c.execute("BEGIN")
                c.execute("INSERT INTO r (x) VALUES (9)")
                c.execute("PREPARE TRANSACTION 'boot-1'")
                c.execute("COMMIT PREPARED 'boot-1'")
        finally:
            srv.close()
        srv2 = PostgresWireServer(persist_dir=d)
        try:
            # committed-gid set survived: the replayed commit is a no-op,
            # not an error (sink restore may replay it after ANY restart)
            with connect(srv2) as c:
                c.execute("COMMIT PREPARED 'boot-1'")
        finally:
            srv2.close()

    def test_sink_in_pipeline_end_to_end(self, server):
        with connect(server) as c:
            c.execute("CREATE TABLE out4 (w text, n float8)")
        from flink_tpu.datastream.api import StreamExecutionEnvironment

        env = StreamExecutionEnvironment.get_execution_environment()
        words = ["a", "b", "a", "c", "b", "a"]
        (env.from_collection(columns={"w": np.asarray(words, object),
                                      "one": np.ones(len(words))})
            .key_by("w")
            .sum("one", output_column="n")
            .add_sink(PostgresSink(server.host, server.port, "out4",
                                   columns=["w", "n"])))
        env.execute("pg-sink-job")
        with connect(server) as c:
            cols = c.query_columns("SELECT w, n FROM out4")
        # running keyed sums: the LAST row per key carries the final count
        final = {}
        for w, n in zip(cols["w"].tolist(), cols["n"].tolist()):
            final[w] = n
        assert final == {"a": 3.0, "b": 2.0, "c": 1.0}


class TestExtendedProtocol:
    """Parse/Bind/Describe/Execute/Sync — the JDBC PreparedStatement
    flow over the wire."""

    def test_prepared_select_with_params(self, server):
        with connect(server) as c:
            c.execute("CREATE TABLE ep (id int8, name text, score float8)")
            c.execute("INSERT INTO ep (id, name, score) VALUES "
                      "(1, 'ada', 9.5), (2, 'bob', 7.0), (3, 'cat', 8.25)")
            cols = c.query_prepared(
                "SELECT name, score FROM ep WHERE id = $1", [2])
            assert cols["name"].tolist() == ["bob"]
            assert cols["score"].tolist() == [7.0]
            # strings quote; embedded quotes escape
            c.execute_prepared("INSERT INTO ep (id, name) VALUES ($1, $2)",
                               [4, "o'hara"])
            cols = c.query_prepared(
                "SELECT name FROM ep WHERE id = $1", [4])
            assert cols["name"].tolist() == ["o'hara"]

    def test_prepared_null_and_bool(self, server):
        with connect(server) as c:
            c.execute("CREATE TABLE epn (id int8, ok bool, note text)")
            c.execute_prepared(
                "INSERT INTO epn (id, ok, note) VALUES ($1, $2, $3)",
                [1, True, None])
            cols = c.query_prepared("SELECT ok, note FROM epn")
            assert cols["ok"].tolist() == [True]
            assert cols["note"].tolist() == [None]

    def test_error_aborts_until_sync_connection_survives(self, server):
        with connect(server) as c:
            with pytest.raises(PostgresError, match="does not exist"):
                c.execute_prepared("SELECT x FROM missing_table")
            # the connection recovered at Sync: next cycle works
            c.execute("CREATE TABLE eps (id int8)")
            c.execute_prepared("INSERT INTO eps (id) VALUES ($1)", [7])
            assert c.query_prepared(
                "SELECT id FROM eps")["id"].tolist() == [7]

    def test_unbound_parameter_rejected(self, server):
        with connect(server) as c:
            c.execute("CREATE TABLE epu (id int8)")
            with pytest.raises(PostgresError, match="not bound"):
                c.execute_prepared("SELECT id FROM epu WHERE id = $2", [1])


class TestScramAuth:
    def test_scram_handshake_and_queries(self):
        srv = PostgresWireServer(users={"alice": "s3cret"},
                                 auth="scram-sha-256")
        try:
            c = PostgresWireClient(srv.host, srv.port, user="alice",
                                   password="s3cret")
            c.execute("CREATE TABLE s (x int4)")
            c.execute("INSERT INTO s (x) VALUES (5)")
            assert c.query_columns("SELECT x FROM s")["x"].tolist() == [5]
            c.close()
        finally:
            srv.close()

    def test_scram_wrong_password_rejected(self):
        srv = PostgresWireServer(users={"alice": "s3cret"},
                                 auth="scram-sha-256")
        try:
            with pytest.raises(PostgresError, match="authentication"):
                PostgresWireClient(srv.host, srv.port, user="alice",
                                   password="wrong")
            with pytest.raises(PostgresError, match="authentication"):
                PostgresWireClient(srv.host, srv.port, user="mallory",
                                   password="s3cret")
        finally:
            srv.close()


def test_params_inside_string_literals_untouched(server):
    with connect(server) as c:
        c.execute("CREATE TABLE lit (id int8, note text)")
        # a '$1' INSIDE a string literal is data, not a placeholder
        c.execute_prepared(
            "INSERT INTO lit (id, note) VALUES ($1, 'worth $1')", [9])
        cols = c.query_prepared("SELECT note FROM lit WHERE id = $1", [9])
        assert cols["note"].tolist() == ["worth $1"]
        # numeric-LOOKING text params stay strings ('1_0', 'infinity')
        c.execute_prepared(
            "INSERT INTO lit (id, note) VALUES ($1, $2)", [10, "1_0"])
        c.execute_prepared(
            "INSERT INTO lit (id, note) VALUES ($1, $2)",
            [11, "infinity"])
        cols = c.query_prepared(
            "SELECT note FROM lit WHERE id >= $1 ORDER BY id", [10])
        assert cols["note"].tolist() == ["1_0", "infinity"]


def test_binary_format_rejected_not_misread(server):
    import socket as _socket
    from flink_tpu.connectors.postgres import _cstr, _msg
    with connect(server) as c:
        c.execute("CREATE TABLE bf (id int8)")
        # hand-build a Bind with param format code 1 (binary)
        parse = _cstr("") + _cstr("INSERT INTO bf (id) VALUES ($1)") \
            + struct.pack(">h", 0)
        bind = (_cstr("") + _cstr("") + struct.pack(">hh", 1, 1)
                + struct.pack(">h", 1)
                + struct.pack(">i", 8) + struct.pack(">q", 7)
                + struct.pack(">h", 0))
        c.sock.sendall(_msg(b"P", parse) + _msg(b"B", bind)
                       + _msg(b"S", b""))
        with pytest.raises(PostgresError, match="binary-format"):
            c._read_until_ready()
        # connection recovered at Sync
        assert c.query_columns("SELECT COUNT(*) FROM bf")["count"][0] == 0


def test_malformed_scram_gets_error_not_dropped_socket():
    import socket as _socket
    srv = PostgresWireServer(users={"a": "pw"}, auth="scram-sha-256")
    try:
        sock = _socket.create_connection((srv.host, srv.port), timeout=5)
        payload = struct.pack(">i", PROTOCOL_V3) + b"user\0a\0\0"
        sock.sendall(struct.pack(">i", len(payload) + 4) + payload)
        t, body = read_message(sock)
        assert t == b"R" and struct.unpack(">i", body[:4])[0] == 10
        # garbage SASLInitialResponse (no NUL, no length)
        bad = b"\xff\xfe"
        sock.sendall(b"p" + struct.pack(">i", len(bad) + 4) + bad)
        t, body = read_message(sock)
        assert t == b"E"                    # ErrorResponse, not a RST
        sock.close()
    finally:
        srv.close()
