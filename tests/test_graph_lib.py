"""Graph library (Gelly analog): PageRank, components, SSSP, triangles,
scatter-gather, DataSet interop."""

import jax.numpy as jnp
import numpy as np
import pytest

from flink_tpu.graph_lib import Graph


def test_degrees():
    g = Graph.from_edges([(0, 1), (0, 2), (1, 2)])
    assert g.out_degrees().tolist() == [2, 1, 0]
    assert g.in_degrees().tolist() == [0, 1, 2]


def test_pagerank_star():
    # hub-and-spoke: all point to 0 -> vertex 0 dominates
    g = Graph.from_edges([(1, 0), (2, 0), (3, 0)])
    pr = g.pagerank(num_iterations=50)
    assert pr[0] > pr[1] == pytest.approx(pr[2], rel=1e-5)
    assert pr.sum() == pytest.approx(1.0, abs=1e-3)


def test_pagerank_matches_power_iteration():
    rng = np.random.default_rng(3)
    n, m = 30, 120
    edges = rng.integers(0, n, (m, 2))
    g = Graph.from_edges(edges, num_vertices=n)
    pr = g.pagerank(num_iterations=100)
    # dense-matrix ground truth with dangling redistribution
    A = np.zeros((n, n))
    for s, d in edges:
        A[d, s] += 1
    deg = A.sum(axis=0)
    P = np.where(deg > 0, A / np.maximum(deg, 1), 1.0 / n)
    r = np.full(n, 1.0 / n)
    for _ in range(100):
        r = (1 - 0.85) / n + 0.85 * P @ r
    np.testing.assert_allclose(pr, r, atol=1e-3)


def test_connected_components():
    g = Graph.from_edges([(0, 1), (1, 2), (3, 4)], num_vertices=6)
    cc = g.connected_components()
    assert cc.tolist() == [0, 0, 0, 3, 3, 5]


def test_sssp_weighted():
    # 0 ->(1) 1 ->(1) 2 ; 0 ->(5) 2
    g = Graph.from_edges([(0, 1), (1, 2), (0, 2)],
                         weights=[1.0, 1.0, 5.0])
    d = g.sssp(0)
    assert d[0] == 0 and d[1] == 1.0 and d[2] == 2.0


def test_sssp_unreachable_is_inf():
    g = Graph.from_edges([(0, 1)], num_vertices=3)
    d = g.sssp(0)
    assert np.isinf(d[2])


def test_triangle_count_dense_and_sparse_agree():
    rng = np.random.default_rng(7)
    edges = set()
    while len(edges) < 60:
        a, b = rng.integers(0, 20, 2)
        if a != b:
            edges.add((min(a, b), max(a, b)))
    e = np.asarray(sorted(edges))
    g = Graph.from_edges(e, num_vertices=20)
    dense = g.triangle_count()
    # brute force
    adj = {i: set() for i in range(20)}
    for a, b in e.tolist():
        adj[a].add(b)
        adj[b].add(a)
    brute = sum(1 for a in range(20) for b in adj[a] if b > a
                for c in (adj[a] & adj[b]) if c > b)
    assert dense == brute > 0


def test_triangle_known():
    g = Graph.from_edges([(0, 1), (1, 2), (2, 0), (2, 3)])
    assert g.triangle_count() == 1


def test_label_propagation():
    # two cliques connected weakly; labels converge within each clique
    g = Graph.from_edges([(0, 1), (1, 2), (0, 2), (3, 4), (4, 5), (3, 5)])
    labels = g.label_propagation(np.arange(6), num_iterations=10)
    assert labels[0] == labels[1] == labels[2]
    assert labels[3] == labels[4] == labels[5]
    assert labels[0] != labels[3]


def test_scatter_gather_custom():
    import jax.numpy as jnp

    # sum of neighbor values, one superstep
    g = Graph.from_edges([(0, 2), (1, 2)])
    vals = g.scatter_gather(
        np.array([1.0, 2.0, 0.0], np.float32),
        lambda sv, w: sv, "sum",
        lambda v, c: v + c, max_supersteps=1)
    assert vals.tolist() == [1.0, 2.0, 3.0]


def test_dataset_interop():
    from flink_tpu.dataset import ExecutionEnvironment

    env = ExecutionEnvironment()
    edges = env.from_columns({"src": [0, 1], "dst": [1, 2],
                              "w": [1.0, 2.0]})
    g = Graph.from_dataset(edges, weight_column="w")
    assert g.num_edges == 2 and g.n == 3
    back = g.as_dataset().collect()
    assert len(back) == 2 and back[0]["weight"] == 1.0


def test_empty_graph():
    g = Graph.from_edges([])
    assert g.n == 0 and g.num_edges == 0


def test_k_core():
    # a 4-clique plus a pendant chain: the 3-core is exactly the clique
    edges = [(0, 1), (0, 2), (0, 3), (1, 2), (1, 3), (2, 3),
             (3, 4), (4, 5)]
    g = Graph.from_edges(edges, num_vertices=6)
    core3 = g.k_core(3)
    assert core3.tolist() == [True, True, True, True, False, False]
    assert g.k_core(1).tolist() == [True] * 6


def test_clustering_coefficient():
    # triangle 0-1-2 plus vertex 3 attached to 0 only
    g = Graph.from_edges([(0, 1), (1, 2), (0, 2), (0, 3)], num_vertices=4)
    cc = g.clustering_coefficient()
    assert cc[1] == 1.0 and cc[2] == 1.0    # their 2 neighbors connect
    assert abs(cc[0] - 1 / 3) < 1e-9        # 1 of 3 neighbor pairs
    assert cc[3] == 0.0


def test_bfs_levels_multi_source():
    # path 0-1-2-3-4 and isolated 5
    g = Graph.from_edges([(0, 1), (1, 2), (2, 3), (3, 4)], num_vertices=6)
    lv = g.bfs_levels(0)
    assert lv.tolist() == [0, 1, 2, 3, 4, -1]
    lv2 = g.bfs_levels(np.array([0, 4]))
    assert lv2.tolist() == [0, 1, 2, 1, 0, -1]


def test_k_core_bidirectional_edge_list():
    """Regression: an already-bidirectional edge list must not double
    degrees — the 2-core of path 0-1-2 is empty."""
    g = Graph.from_edges([(0, 1), (1, 0), (1, 2), (2, 1)], num_vertices=3)
    assert g.k_core(2).tolist() == [False, False, False]
    assert g.k_core(1).tolist() == [True, True, True]


def test_bfs_levels_directed_flag():
    g = Graph.from_edges([(1, 0)], num_vertices=2)
    assert g.bfs_levels(0).tolist() == [0, 1]               # undirected
    assert g.bfs_levels(0, directed=True).tolist() == [0, -1]


# ---------------------------------------------------------------------------
# round-4 additions: mesh-sharded supersteps, HITS, Jaccard
# ---------------------------------------------------------------------------

def test_mesh_pagerank_matches_single_device():
    from flink_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(6)
    n, e = 300, 2_000
    g = Graph.from_edges(np.stack([rng.integers(0, n, e),
                                   rng.integers(0, n, e)], 1),
                         num_vertices=n)
    single = g.pagerank(num_iterations=25)
    mesh = g.pagerank(num_iterations=25, mesh=make_mesh(8))
    np.testing.assert_allclose(mesh, single, rtol=1e-5, atol=1e-7)


def test_mesh_connected_components_matches():
    from flink_tpu.parallel.mesh import make_mesh

    # two components + an isolated vertex
    edges = [(0, 1), (1, 2), (3, 4)]
    g = Graph.from_edges(edges, num_vertices=6)
    want = g.connected_components()
    mesh = make_mesh(8)

    def msg(vals, _w):
        return vals

    def update(vals, combined):
        return jnp.minimum(vals, combined)

    got = g.undirected().scatter_gather(
        jnp.arange(6, dtype=jnp.int32), msg, "min", update, 6, mesh=mesh)
    np.testing.assert_array_equal(got, want)


def test_mesh_weighted_sssp_matches():
    from flink_tpu.parallel.mesh import make_mesh

    edges = [(0, 1), (1, 2), (0, 2)]
    w = np.asarray([1.0, 1.0, 5.0], np.float32)
    g0 = Graph.from_edges(edges, num_vertices=3, weights=w)
    want = g0.sssp(0)
    mesh = make_mesh(8)
    inf = np.float32(np.inf)

    def msg(vals, weights):
        return vals + weights

    def update(vals, combined):
        return jnp.minimum(vals, combined)

    init = jnp.asarray([0.0, inf, inf], jnp.float32)
    got = g0.scatter_gather(init, msg, "min", update, 4, mesh=mesh)
    np.testing.assert_allclose(got, want)


def test_hits_hub_authority():
    # 0 and 1 both point at 2: vertex 2 is the authority, 0/1 equal hubs
    g = Graph.from_edges([(0, 2), (1, 2)])
    hubs, auth = g.hits(num_iterations=10)
    assert auth.argmax() == 2
    assert hubs[0] == pytest.approx(hubs[1])
    assert hubs[2] == pytest.approx(0.0, abs=1e-6)
    assert auth[2] == pytest.approx(1.0, rel=1e-5)


def test_jaccard_similarity_hand_computed():
    # triangle 0-1-2 plus pendant 3 on 2
    g = Graph.from_edges([(0, 1), (1, 2), (0, 2), (2, 3)])
    sim = g.jaccard_similarity()
    # edge (0,1): N(0)={1,2}, N(1)={0,2} -> inter {2}=1, union {0,1,2}=3
    assert sim[0] == pytest.approx(1 / 3)
    # edge (2,3): N(2)={0,1,3}, N(3)={2} -> inter 0
    assert sim[3] == pytest.approx(0.0)


def test_jaccard_dense_and_sparse_agree():
    rng = np.random.default_rng(3)
    e = np.stack([rng.integers(0, 60, 300), rng.integers(0, 60, 300)], 1)
    g = Graph.from_edges(e, num_vertices=60)
    dense = g.jaccard_similarity()
    # independent sparse mirror (the >4096-vertex branch's algorithm)
    adj = {}
    for s_, d in zip(np.asarray(g.src).tolist(), np.asarray(g.dst).tolist()):
        if s_ == d:
            continue
        adj.setdefault(s_, set()).add(d)
        adj.setdefault(d, set()).add(s_)
    sparse = []
    for s_, d in zip(np.asarray(g.src).tolist(), np.asarray(g.dst).tolist()):
        ns, nd = adj.get(s_, set()), adj.get(d, set())
        u = len(ns | nd)
        sparse.append(len(ns & nd) / u if u else 0.0)
    np.testing.assert_allclose(dense, sparse, rtol=1e-5, atol=1e-6)


def test_mesh_vector_values_match_single_device():
    """Regression: vector vertex values must work identically on the mesh
    path (the edge mask broadcasts over trailing dims)."""
    from flink_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(9)
    n, e, k = 40, 160, 3
    g = Graph.from_edges(np.stack([rng.integers(0, n, e),
                                   rng.integers(0, n, e)], 1),
                         num_vertices=n)
    init = rng.random((n, k)).astype(np.float32)

    def msg(vals, _w):
        return vals * 0.5

    def update(vals, combined):
        return vals * 0.1 + combined

    single = g.scatter_gather(init, msg, "sum", update, 3)
    mesh = g.scatter_gather(init, msg, "sum", update, 3,
                            mesh=make_mesh(8))
    np.testing.assert_allclose(mesh, single, rtol=1e-5, atol=1e-6)


def test_adamic_adar_hand_computed():
    # triangle 0-1-2 plus pendant 3 on 2: deg 0=2, 1=2, 2=3, 3=1
    g = Graph.from_edges([(0, 1), (1, 2), (0, 2), (2, 3)])
    aa = g.adamic_adar()
    # edge (0,1): common neighbor {2}, deg(2)=3 -> 1/log(3)
    assert aa[0] == pytest.approx(1 / np.log(3), rel=1e-5)
    # edge (2,3): no common neighbors
    assert aa[3] == pytest.approx(0.0)


def test_adamic_adar_dense_and_sparse_agree():
    rng = np.random.default_rng(5)
    e = np.stack([rng.integers(0, 50, 200), rng.integers(0, 50, 200)], 1)
    g = Graph.from_edges(e, num_vertices=50)
    dense = g.adamic_adar()
    adj = {}
    for s_, d in zip(np.asarray(g.src).tolist(), np.asarray(g.dst).tolist()):
        if s_ != d:
            adj.setdefault(s_, set()).add(d)
            adj.setdefault(d, set()).add(s_)
    sparse = []
    for s_, d in zip(np.asarray(g.src).tolist(), np.asarray(g.dst).tolist()):
        commons = adj.get(s_, set()) & adj.get(d, set())
        sparse.append(sum(1.0 / np.log(len(adj[w]))
                          for w in commons if len(adj[w]) > 1))
    np.testing.assert_allclose(dense, sparse, rtol=1e-4, atol=1e-5)


def test_summarize_contracts_by_label():
    # two groups: {0,1} label 10, {2,3} label 20; edges within and across
    g = Graph.from_edges([(0, 1), (0, 2), (1, 3), (2, 3), (3, 2)])
    summary, labels, sizes = g.summarize(np.asarray([10, 10, 20, 20]))
    assert labels.tolist() == [10, 20]
    assert sizes.tolist() == [2, 2]
    edges = {(int(s), int(d)): float(w) for s, d, w in
             zip(np.asarray(summary.src), np.asarray(summary.dst),
                 np.asarray(summary.weights))}
    # (10->10): edge (0,1); (10->20): (0,2),(1,3); (20->20): (2,3),(3,2)
    assert edges == {(0, 0): 1.0, (0, 1): 2.0, (1, 1): 2.0}


def test_bipartite_projections():
    # left {0,1,2}, right {3,4}: 0-3, 1-3, 1-4, 2-4
    g = Graph.from_edges([(0, 3), (1, 3), (1, 4), (2, 4)], num_vertices=5)
    left = g.bipartite_projection(left_size=3, onto_left=True)
    le = {(int(s), int(d)): float(w) for s, d, w in
          zip(np.asarray(left.src), np.asarray(left.dst),
              np.asarray(left.weights))}
    assert le == {(0, 1): 1.0, (1, 2): 1.0}   # share 3; share 4
    right = g.bipartite_projection(left_size=3, onto_left=False)
    re_ = {(int(s), int(d)): float(w) for s, d, w in
           zip(np.asarray(right.src), np.asarray(right.dst),
               np.asarray(right.weights))}
    assert re_ == {(0, 1): 1.0}               # 3 and 4 share vertex 1
    assert right.n == 2


def test_vertex_metrics():
    g = Graph.from_edges([(0, 1), (1, 2)], num_vertices=4)
    m = g.vertex_metrics()
    assert m["vertices"] == 4 and m["edges"] == 2
    assert m["vertices_with_edges"] == 3       # vertex 3 is isolated
    assert m["max_degree"] == 2                # vertex 1: in 1 + out 1
    assert m["average_degree"] == pytest.approx(1.0)


def test_similarity_sparse_branch_matches_dense():
    """The n > 4096 sparse fallbacks must agree with the dense kernels on
    the SAME edges (padding the vertex count flips the branch)."""
    rng = np.random.default_rng(9)
    e = np.stack([rng.integers(0, 50, 200), rng.integers(0, 50, 200)], 1)
    small = Graph.from_edges(e, num_vertices=50)           # dense branch
    big = Graph.from_edges(e, num_vertices=5000)           # sparse branch
    np.testing.assert_allclose(big.adamic_adar(), small.adamic_adar(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(big.jaccard_similarity(),
                               small.jaccard_similarity(),
                               rtol=1e-5, atol=1e-6)


def test_bipartite_dense_and_sparse_paths_agree():
    rng = np.random.default_rng(11)
    left, right, m = 30, 12, 150
    e = np.stack([rng.integers(0, left, m),
                  left + rng.integers(0, right, m)], 1)
    dense = Graph.from_edges(e, num_vertices=left + right)
    sparse = Graph.from_edges(e, num_vertices=left + 5000)  # big right side
    for onto in (True, False):
        a = dense.bipartite_projection(left, onto_left=onto)
        b = sparse.bipartite_projection(left, onto_left=onto)
        ea = {(int(s), int(d)): float(w) for s, d, w in
              zip(np.asarray(a.src), np.asarray(a.dst),
                  np.asarray(a.weights))}
        eb = {(int(s), int(d)): float(w) for s, d, w in
              zip(np.asarray(b.src), np.asarray(b.dst),
                  np.asarray(b.weights))}
        assert ea == eb, onto


def test_empty_projection_has_typed_weights():
    # no two left vertices share a right neighbor
    g = Graph.from_edges([(0, 2), (1, 3)], num_vertices=4 + 5000)
    p = g.bipartite_projection(left_size=2)
    assert p.num_edges == 0
    assert p.weights is not None and np.asarray(p.weights).shape == (0,)


def test_all_pairs_distances_and_eccentricity():
    """Path 0-1-2-3 plus isolated 4: the [n,n] simultaneous-BFS matrix,
    eccentricity, and diameter/radius match hand computation."""
    g = Graph.from_edges([(0, 1), (1, 2), (2, 3)], num_vertices=5)
    d = g.all_pairs_distances()
    assert d[0].tolist() == [0, 1, 2, 3, -1]
    assert d[3].tolist() == [3, 2, 1, 0, -1]
    assert d[4].tolist() == [-1, -1, -1, -1, 0]
    assert g.eccentricity().tolist() == [3, 2, 2, 3, 0]
    assert g.diameter_radius() == {"diameter": 3, "radius": 2}
    # directed orientation: row-source d[i, j] = i -> j
    dd = g.all_pairs_distances(directed=True)
    assert dd[0, 3] == 3 and dd[3, 0] == -1


def test_closeness_centrality():
    # star: the hub is closest to everything
    g = Graph.from_edges([(0, 1), (0, 2), (0, 3), (0, 4)], num_vertices=5)
    c = g.closeness_centrality()
    assert c[0] == max(c)
    assert np.allclose(c[1:], c[1])          # leaves tie
    # hub closeness = (n-1)/sum(d) = 4/4 = 1.0 (full Wasserman-Faust
    # scale since everything is reachable)
    assert c[0] == pytest.approx(1.0)
    # the component correction keeps disconnected graphs comparable
    g2 = Graph.from_edges([(0, 1), (2, 3)], num_vertices=4)
    c2 = g2.closeness_centrality()
    assert np.allclose(c2, c2[0])            # symmetric pairs tie
    assert 0 < c2[0] < 1.0                   # penalized vs a full graph


def test_all_pairs_on_mesh():
    import jax
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:4])
    if devs.size < 2:
        pytest.skip("needs a multi-device mesh")
    mesh = Mesh(devs, ("d",))
    g = Graph.from_edges([(0, 1), (1, 2), (2, 3), (3, 4), (4, 5)],
                         num_vertices=6)
    d = g.all_pairs_distances(mesh=mesh)
    assert d[0].tolist() == [0, 1, 2, 3, 4, 5]
    assert g.eccentricity(mesh=mesh).tolist() == [5, 4, 3, 3, 4, 5]


def test_diameter_ignores_self_loops_and_shares_distances():
    g = Graph.from_edges([(0, 1), (2, 2)], num_vertices=3)
    # vertex 2 only has a self-loop: excluded from diameter/radius
    assert g.diameter_radius() == {"diameter": 1, "radius": 1}
    # one BFS shared across the family
    g2 = Graph.from_edges([(0, 1), (1, 2)], num_vertices=3)
    d = g2.all_pairs_distances()
    assert g2.eccentricity(distances=d).tolist() == [2, 1, 2]
    assert g2.diameter_radius(distances=d) == {"diameter": 2, "radius": 1}
    c = g2.closeness_centrality(distances=d)
    assert c[1] == max(c)
