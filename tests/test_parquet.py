"""Parquet from first principles (VERDICT r3 next #7).

The golden-fixture test hand-assembles a tiny Parquet file with an
INDEPENDENT thrift-compact encoder written here (the codec is validated
against the spec, not against itself — the Kafka-frame test pattern);
round-trips cover every type, dictionary encoding, gzip, multiple row
groups, and the FileSink/FileSource integration."""

import os
import struct

import numpy as np
import pytest

from flink_tpu import formats
from flink_tpu.core.batch import RecordBatch
from flink_tpu.formats.parquet import read_parquet, write_parquet


# --------------------------------------------------------------------------
# independent minimal thrift-compact encoder (test-local, for the fixture)
# --------------------------------------------------------------------------

def uv(n):
    out = b""
    while n >= 0x80:
        out += bytes([(n & 0x7F) | 0x80])
        n >>= 7
    return out + bytes([n])


def zz(n):
    return uv((n << 1) ^ (n >> 63) if n < 0 else n << 1)


def fld(delta, ftype):
    return bytes([(delta << 4) | ftype])


def golden_file_bytes():
    """One INT64 REQUIRED column 'v' with values [7, 9]: PLAIN,
    uncompressed, one row group — every byte derived from the spec."""
    values = struct.pack("<qq", 7, 9)
    # PageHeader{type=DATA(0), uncomp=16, comp=16,
    #            data_page_header{num=2, enc=PLAIN, def=RLE, rep=RLE}}
    page_hdr = (
        fld(1, 5) + zz(0) +          # 1: i32 type = DATA_PAGE
        fld(1, 5) + zz(16) +         # 2: i32 uncompressed_size
        fld(1, 5) + zz(16) +         # 3: i32 compressed_size
        fld(2, 12) +                 # 5: struct data_page_header (delta 2)
        fld(1, 5) + zz(2) +          #   1: num_values
        fld(1, 5) + zz(0) +          #   2: encoding PLAIN
        fld(1, 5) + zz(3) +          #   3: def-level enc RLE
        fld(1, 5) + zz(3) +          #   4: rep-level enc RLE
        b"\x00" +                    # end data_page_header
        b"\x00")                     # end PageHeader
    body = b"PAR1" + page_hdr + values
    data_off = 4                     # page starts right after the magic
    chunk_total = len(page_hdr) + len(values)
    # ColumnMetaData
    cmd = (
        fld(1, 5) + zz(2) +                    # 1: type INT64
        fld(1, 9) + bytes([(1 << 4) | 5]) + zz(0) +   # 2: encodings [PLAIN]
        fld(1, 9) + bytes([(1 << 4) | 8]) + uv(1) + b"v" +  # 3: path ["v"]
        fld(1, 5) + zz(0) +                    # 4: codec UNCOMPRESSED
        fld(1, 6) + zz(2) +                    # 5: num_values
        fld(1, 6) + zz(chunk_total) +          # 6: total_uncompressed
        fld(1, 6) + zz(chunk_total) +          # 7: total_compressed
        fld(2, 6) + zz(data_off) +             # 9: data_page_offset
        b"\x00")
    chunk = (fld(2, 6) + zz(data_off) +        # 2: file_offset
             fld(1, 12) + cmd +                # 3: meta_data
             b"\x00")
    row_group = (
        fld(1, 9) + bytes([(1 << 4) | 12]) + chunk +  # 1: columns
        fld(1, 6) + zz(chunk_total) +                 # 2: total_byte_size
        fld(1, 6) + zz(2) +                           # 3: num_rows
        b"\x00")
    schema_root = fld(4, 8) + uv(6) + b"schema" + fld(1, 5) + zz(1) + b"\x00"
    schema_v = (fld(1, 5) + zz(2) +            # 1: type INT64
                fld(2, 5) + zz(0) +            # 3: repetition REQUIRED
                fld(1, 8) + uv(1) + b"v" +     # 4: name
                b"\x00")
    created = "flink-tpu parquet 1.0".encode()
    footer = (
        fld(1, 5) + zz(1) +                            # 1: version
        fld(1, 9) + bytes([(2 << 4) | 12]) + schema_root + schema_v,  # 2
    )[0] + (
        fld(1, 6) + zz(2) +                            # 3: num_rows
        fld(1, 9) + bytes([(1 << 4) | 12]) + row_group +  # 4: row_groups
        fld(2, 8) + uv(len(created)) + created +       # 6: created_by
        b"\x00")
    return body + footer + struct.pack("<I", len(footer)) + b"PAR1"


def test_reader_decodes_spec_golden_fixture(tmp_path):
    p = str(tmp_path / "golden.parquet")
    with open(p, "wb") as f:
        f.write(golden_file_bytes())
    [batch] = list(read_parquet(p))
    assert list(batch.columns) == ["v"]
    assert np.asarray(batch.column("v")).tolist() == [7, 9]


def test_writer_emits_exact_golden_bytes(tmp_path):
    """Byte-level: the writer's output for the golden case is IDENTICAL to
    the hand-derived fixture."""
    p = str(tmp_path / "w.parquet")
    write_parquet([RecordBatch({"v": np.array([7, 9], np.int64)})], p)
    got = open(p, "rb").read()
    assert got == golden_file_bytes()


@pytest.mark.parametrize("compression", [None, "gzip"])
def test_roundtrip_all_types(tmp_path, compression):
    rng = np.random.default_rng(4)
    n = 2_000
    cols = {
        "i64": rng.integers(-2**40, 2**40, n),
        "i32": rng.integers(-2**30, 2**30, n).astype(np.int32),
        "f32": rng.random(n).astype(np.float32),
        "f64": rng.random(n),
        "flag": rng.integers(0, 2, n).astype(bool),
        "name": np.asarray([f"user-{i % 97}" for i in range(n)], object),
    }
    p = str(tmp_path / "t.parquet")
    write_parquet([RecordBatch(cols)], p, compression=compression)
    out = RecordBatch.concat(list(read_parquet(p)))
    assert list(out.columns) == list(cols)
    for c, v in cols.items():
        got = np.asarray(out.column(c))
        if v.dtype.kind == "O":
            assert got.tolist() == [str(x) for x in v.tolist()]
        else:
            np.testing.assert_array_equal(got, v)


def test_dictionary_encoding_small_cardinality(tmp_path):
    n = 5_000
    vals = np.asarray([f"city-{i % 7}" for i in range(n)], object)
    p = str(tmp_path / "d.parquet")
    write_parquet([RecordBatch({"city": vals})], p, dictionary="always")
    raw = open(p, "rb").read()
    # the 7 distinct strings appear ONCE (dictionary page), not 5000 times
    assert raw.count(b"city-3") == 1
    [out] = list(read_parquet(p))
    assert np.asarray(out.column("city")).tolist() == vals.tolist()
    # auto mode picks dictionary here too (7 << 5000)
    p2 = str(tmp_path / "d2.parquet")
    write_parquet([RecordBatch({"city": vals})], p2)
    assert open(p2, "rb").read().count(b"city-3") == 1


def test_multiple_row_groups(tmp_path):
    p = str(tmp_path / "rg.parquet")
    write_parquet([RecordBatch({"v": np.arange(10_000, dtype=np.int64)})],
                  p, row_group_rows=3_000)
    parts = list(read_parquet(p))
    assert [len(b) for b in parts] == [3_000, 3_000, 3_000, 1_000]
    got = np.concatenate([np.asarray(b.column("v")) for b in parts])
    np.testing.assert_array_equal(got, np.arange(10_000))


def test_rle_run_decoding(tmp_path):
    """The hybrid reader must accept RLE runs too (a foreign writer may
    emit them): splice an RLE-run index page into a dictionary file."""
    from flink_tpu.formats.parquet import _rle_bitpack_read

    # header (run=5)<<1, bit width 3, value 5 -> one byte 0b00000101
    data = bytes([5 << 1, 0b101])
    out = _rle_bitpack_read(data, 3, 5)
    assert out.tolist() == [5] * 5
    # mixed: bit-packed group then RLE run
    from flink_tpu.formats.parquet import _rle_bitpack_write
    bp = _rle_bitpack_write(np.asarray([1, 2, 3, 4, 5, 6, 7, 0]), 3)
    mixed = bp + bytes([4 << 1, 0b010])
    out2 = _rle_bitpack_read(mixed, 3, 12)
    assert out2.tolist() == [1, 2, 3, 4, 5, 6, 7, 0, 2, 2, 2, 2]


def test_file_sink_and_source_speak_parquet(tmp_path):
    from flink_tpu.connectors.file_source import FileSink, FileSource
    from flink_tpu.operators.base import snapshot_scope

    d = str(tmp_path / "out")
    sink = FileSink(d, format="parquet")
    sink.write_batch(RecordBatch({"v": np.arange(100, dtype=np.int64)}))
    with snapshot_scope(1):
        sink.snapshot_state()
    sink.notify_checkpoint_complete(1)
    [f] = sink.committed_files()
    src = FileSource(f, format="parquet")
    [split] = src.create_splits(1)
    got = np.concatenate([np.asarray(b.column("v")) for b in split.read()
                          if hasattr(b, "columns")])
    np.testing.assert_array_equal(got, np.arange(100))


def test_corrupt_magic_rejected(tmp_path):
    p = str(tmp_path / "bad.parquet")
    with open(p, "wb") as f:
        f.write(b"NOPE" + b"\x00" * 64)
    with pytest.raises(ValueError, match="magic"):
        list(read_parquet(p))


def test_unsigned_roundtrip_bit_exact(tmp_path):
    """Regression: uint32/uint64 store as signed physical bits with UINT
    converted types — values above the signed range must round-trip."""
    cols = {
        "u32": np.array([0, 3_000_000_000, 2**32 - 1], np.uint32),
        "u64": np.array([1, 2**63 + 5, 2**64 - 1], np.uint64),
    }
    p = str(tmp_path / "u.parquet")
    write_parquet([RecordBatch(cols)], p)
    [out] = list(read_parquet(p))
    for c, v in cols.items():
        got = np.asarray(out.column(c))
        assert got.dtype == v.dtype
        np.testing.assert_array_equal(got, v)


def test_multi_page_chunk_fully_decoded(tmp_path):
    """A chunk holding several data pages (foreign writers page at ~1MB)
    must decode completely — the reader loops to the declared value count."""
    from flink_tpu.formats.parquet import (_encode_plain, _page_header,
                                           _file_metadata, T_INT64, MAGIC,
                                           CODEC_UNCOMPRESSED)
    import io

    vals = np.arange(10, dtype=np.int64)
    buf = io.BytesIO()
    buf.write(MAGIC)
    first_off = buf.tell()
    data_off = buf.tell()
    uncomp = 0
    for lo in (0, 4, 8):               # three pages: 4 + 4 + 2 values
        chunk = vals[lo:lo + 4]
        raw = _encode_plain(chunk, T_INT64)
        hdr = _page_header(0, len(raw), len(raw), num_values=len(chunk))
        buf.write(hdr)
        buf.write(raw)
        uncomp += len(hdr) + len(raw)
    end = buf.tell()
    meta = [{"columns": [{
        "name": "v", "type": T_INT64, "encodings": [0],
        "codec": CODEC_UNCOMPRESSED, "num_values": 10,
        "data_off": data_off, "dict_off": None,
        "total_comp": end - first_off, "total_uncomp": uncomp,
        "file_off": first_off}], "bytes": end - first_off, "rows": 10}]
    footer = _file_metadata(["v"], {"v": (T_INT64, None)}, 10, meta)
    buf.write(footer)
    buf.write(struct.pack("<I", len(footer)))
    buf.write(MAGIC)
    p = str(tmp_path / "mp.parquet")
    open(p, "wb").write(buf.getvalue())
    [out] = list(read_parquet(p))
    np.testing.assert_array_equal(np.asarray(out.column("v")), vals)


def test_bytes_values_dictionary_safe(tmp_path):
    """Regression: bytes cells must not be str()-mangled by the dictionary
    path (b'x' previously became the string \"b'x'\")."""
    vals = np.asarray([b"x", b"y", b"x", b"x"] * 30, object)
    for mode in ("always", "never"):
        p = str(tmp_path / f"b-{mode}.parquet")
        write_parquet([RecordBatch({"k": vals})], p, dictionary=mode)
        [out] = list(read_parquet(p))
        assert np.asarray(out.column("k")).tolist() == ["x", "y", "x", "x"] * 30, mode


def test_streaming_writer_bounded_groups(tmp_path):
    """Many input batches with small row groups: the writer slices groups
    exactly and never needs the whole input at once."""
    p = str(tmp_path / "s.parquet")
    batches = [RecordBatch({"v": np.arange(i * 100, (i + 1) * 100,
                                           dtype=np.int64)})
               for i in range(50)]
    write_parquet(batches, p, row_group_rows=1_234)
    parts = list(read_parquet(p))
    assert sum(len(b) for b in parts) == 5_000
    got = np.concatenate([np.asarray(b.column("v")) for b in parts])
    np.testing.assert_array_equal(got, np.arange(5_000))
    assert all(len(b) == 1_234 for b in parts[:-1])
