"""Pytest marker audit (ISSUE-4 CI satellite).

Two invariants keep the two-tier test scheme honest:

1. Every marker used anywhere under ``tests/`` is DECLARED in
   ``pyproject.toml`` (or a pytest builtin) — an unknown marker silently
   selects nothing, so a typo like ``choas`` would quietly drop a test
   from every ``-m`` expression.
2. The ``chaos`` suite stays visible to the tier-1 command
   (``-m 'not slow'``): at least a meaningful share of chaos-marked
   tests must NOT also be slow-marked, or fault-injection coverage
   silently migrates out of the gate everyone runs.
"""

import re
import sys
from pathlib import Path

TESTS = Path(__file__).parent
REPO = TESTS.parent

#: pytest's own marks — always legal without declaration
BUILTIN_MARKS = {"parametrize", "skip", "skipif", "xfail", "usefixtures",
                 "filterwarnings", "tryfirst", "trylast"}


def _marker_entries():
    """The declared marker lines from pyproject.toml (`name: description`
    strings), parsed with tomllib when available (3.11+), regex on 3.10."""
    text = (REPO / "pyproject.toml").read_text()
    try:
        import tomllib
    except ImportError:          # py310: stdlib tomllib is 3.11+
        block = re.search(r"markers\s*=\s*\[(.*?)\]", text, re.S).group(1)
        return [a or b for a, b in
                re.findall(r"\"([^\"]+)\"|'([^']+)'", block)]
    return tomllib.loads(text)["tool"]["pytest"]["ini_options"]["markers"]


def _declared_markers():
    return {ln.split(":", 1)[0].strip() for ln in _marker_entries()}


def _marks_used():
    """marker name -> set of files using it, scraped from the suite."""
    used = {}
    for path in sorted(TESTS.glob("*.py")):
        src = path.read_text()
        for m in re.finditer(r"pytest\.mark\.([A-Za-z_][A-Za-z0-9_]*)", src):
            used.setdefault(m.group(1), set()).add(path.name)
    return used


def test_every_used_marker_is_declared():
    declared = _declared_markers()
    unknown = {name: sorted(files)
               for name, files in _marks_used().items()
               if name not in declared and name not in BUILTIN_MARKS}
    assert not unknown, (
        f"markers used but not declared in pyproject.toml: {unknown} — "
        f"declare them under [tool.pytest.ini_options].markers or fix the "
        f"typo (an unknown marker silently drops tests from -m selections)")


def test_chaos_suite_collects_under_tier1():
    """Every chaos-suite FILE must contribute tests to the tier-1 run:
    a file whose chaos tests are all slow-marked has silently left the
    gate.  Verified by real collection, not regex: collect with the
    tier-1 expression and require chaos tests from each chaos file."""
    import subprocess

    mark_re = re.compile(r"^pytestmark\s*=.*\bchaos\b|^@pytest\.mark\.chaos",
                         re.M)
    chaos_files = sorted(p.name for p in TESTS.glob("*.py")
                         if mark_re.search(p.read_text()))
    assert chaos_files, "no chaos-marked files found at all"
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "--collect-only", "-q",
         "-m", "chaos and not slow", "-p", "no:cacheprovider",
         *[str(TESTS / f) for f in chaos_files]],
        capture_output=True, text=True, timeout=300, cwd=str(REPO))
    assert proc.returncode == 0, proc.stderr[-2000:]
    collected = proc.stdout
    for f in chaos_files:
        assert f"{f}::" in collected, \
            (f"{f} contributes no tests to the tier-1 chaos selection "
             f"(-m 'chaos and not slow') — its whole chaos coverage is "
             f"slow-gated")


def test_mesh_suite_collects_under_tier1():
    """The mesh-sharded hot path's suites (ISSUE-6) must contribute tests
    to the tier-1 run under ``JAX_PLATFORMS=cpu``: the conftest forces an
    8-device virtual CPU mesh, so multi-device sharding is exercised by
    the gate everyone runs — a slow-mark or cpu-skip sweep that silently
    drops them fails here.  Verified by real collection, not regex."""
    import subprocess

    mesh_files = ["test_mesh_invariance.py", "test_mesh_runtime.py",
                  "test_parallel.py"]
    for f in mesh_files:
        assert (TESTS / f).exists(), f
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "--collect-only", "-q",
         "-m", "not slow", "-p", "no:cacheprovider",
         *[str(TESTS / f) for f in mesh_files]],
        capture_output=True, text=True, timeout=300, cwd=str(REPO),
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    for f in mesh_files:
        assert f"{f}::" in proc.stdout, \
            (f"{f} contributes no tests to the tier-1 selection "
             f"(-m 'not slow' under JAX_PLATFORMS=cpu) — mesh sharding "
             f"coverage left the gate")


def test_device_probe_suite_collects_under_tier1():
    """The device-resident key probe suite (ISSUE-7) must contribute tests
    to the tier-1 run under ``JAX_PLATFORMS=cpu`` — the pure-lax probe
    fallback exists precisely so this coverage never leaves the gate."""
    import subprocess

    f = "test_device_keyindex.py"
    assert (TESTS / f).exists(), f
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "--collect-only", "-q",
         "-m", "not slow", "-p", "no:cacheprovider", str(TESTS / f)],
        capture_output=True, text=True, timeout=300, cwd=str(REPO),
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert f"{f}::" in proc.stdout, \
        (f"{f} contributes no tests to the tier-1 selection — the device "
         f"probe's digest-equality coverage left the gate")


def test_cep_vectorized_suite_collects_under_tier1():
    """The vectorized CEP suite (ISSUE-8) must contribute tests to the
    tier-1 run under ``JAX_PLATFORMS=cpu`` — the numpy kernel is the
    bit-identical portable path, so the equivalence corpus never leaves
    the gate."""
    import subprocess

    f = "test_cep_vectorized.py"
    assert (TESTS / f).exists(), f
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "--collect-only", "-q",
         "-m", "not slow", "-p", "no:cacheprovider", str(TESTS / f)],
        capture_output=True, text=True, timeout=300, cwd=str(REPO),
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert f"{f}::" in proc.stdout, \
        (f"{f} contributes no tests to the tier-1 selection — the "
         f"vectorized CEP equivalence corpus left the gate")


def test_queryable_suite_collects_under_tier1():
    """The queryable serving tier's suite (ISSUE-9) must contribute tests
    to the tier-1 run under ``JAX_PLATFORMS=cpu`` — live-read bit-equality
    (mesh 1v2 included), replica staleness/chaos, and the wire protocol
    all run on the CPU backend, so a slow-mark sweep that silently drops
    them fails here."""
    import subprocess

    f = "test_queryable_serving.py"
    assert (TESTS / f).exists(), f
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "--collect-only", "-q",
         "-m", "not slow", "-p", "no:cacheprovider", str(TESTS / f)],
        capture_output=True, text=True, timeout=300, cwd=str(REPO),
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert f"{f}::" in proc.stdout, \
        (f"{f} contributes no tests to the tier-1 selection — the serving "
         f"tier's read-path coverage left the gate")


def test_queryable_scale_suite_collects_under_tier1():
    """The production-QPS serving suite (ISSUE-13) must contribute tests
    to the tier-1 run under ``JAX_PLATFORMS=cpu`` — binary codec
    round-trips, routing-table correctness, cache invalidation,
    per-worker serving e2e and protocol negotiation all run on the CPU
    backend, so a slow-mark sweep that silently drops them fails here."""
    import subprocess

    f = "test_queryable_scale.py"
    assert (TESTS / f).exists(), f
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "--collect-only", "-q",
         "-m", "not slow", "-p", "no:cacheprovider", str(TESTS / f)],
        capture_output=True, text=True, timeout=300, cwd=str(REPO),
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert f"{f}::" in proc.stdout, \
        (f"{f} contributes no tests to the tier-1 selection — the "
         f"production-QPS read-path coverage left the gate")


def test_tracing_suite_collects_under_tier1():
    """The end-to-end tracing suite (ISSUE-10) must contribute tests to
    the tier-1 run under ``JAX_PLATFORMS=cpu`` — span-journal semantics,
    marker→histogram plumbing and the ProcessCluster merged timeline all
    run on the CPU backend, so a slow-mark sweep that silently drops
    them fails here."""
    import subprocess

    f = "test_tracing.py"
    assert (TESTS / f).exists(), f
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "--collect-only", "-q",
         "-m", "not slow", "-p", "no:cacheprovider", str(TESTS / f)],
        capture_output=True, text=True, timeout=300, cwd=str(REPO),
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert f"{f}::" in proc.stdout, \
        (f"{f} contributes no tests to the tier-1 selection — the "
         f"observability suite left the gate")


def test_fused_step_suite_collects_under_tier1():
    """The one-dispatch fused megastep suite (ISSUE-11) must contribute
    tests to the tier-1 run under ``JAX_PLATFORMS=cpu`` — the fused
    on/off digest+snapshot+counter equality, the compile-once smoke, and
    the mid-scan quarantine salvage all run on the CPU backend (the lax
    scan lane needs no TPU), so a slow-mark sweep that silently drops
    them fails here."""
    import subprocess

    f = "test_fused_step.py"
    assert (TESTS / f).exists(), f
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "--collect-only", "-q",
         "-m", "not slow", "-p", "no:cacheprovider", str(TESTS / f)],
        capture_output=True, text=True, timeout=300, cwd=str(REPO),
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert f"{f}::" in proc.stdout, \
        (f"{f} contributes no tests to the tier-1 selection — the fused "
         f"megastep's bit-identity coverage left the gate")


def test_rescale_under_fire_suite_collects_under_tier1():
    """The rescale-under-fire suite (ISSUE-14) must contribute tests to
    the tier-1 run under ``JAX_PLATFORMS=cpu`` — channel-state
    redistribution route-by-key correctness, the autoscaler's hysteresis
    and the chaos-proof rescale lifecycle (kill / rollback / re-trigger)
    all run on the CPU backend, so a slow-mark sweep that silently drops
    them fails here."""
    import subprocess

    f = "test_rescale_under_fire.py"
    assert (TESTS / f).exists(), f
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "--collect-only", "-q",
         "-m", "not slow", "-p", "no:cacheprovider", str(TESTS / f)],
        capture_output=True, text=True, timeout=300, cwd=str(REPO),
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert f"{f}::" in proc.stdout, \
        (f"{f} contributes no tests to the tier-1 selection — the rescale "
         f"lifecycle's exactly-once coverage left the gate")


def test_scenarios_suite_collects_under_tier1():
    """The scenario suite (ISSUE-15) must contribute tests to the tier-1
    run under ``JAX_PLATFORMS=cpu`` — the per-scenario exactly-once-
    under-kill acceptances vs the unfaulted control, the CEP/session
    rescale split/merge units, the two-phase-commit sink lifecycle and
    the SQL-vs-datastream cross-check all run on the CPU backend, so a
    slow-mark sweep that silently drops them fails here."""
    import subprocess

    f = "test_scenarios.py"
    assert (TESTS / f).exists(), f
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "--collect-only", "-q",
         "-m", "not slow", "-p", "no:cacheprovider", str(TESTS / f)],
        capture_output=True, text=True, timeout=300, cwd=str(REPO),
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert f"{f}::" in proc.stdout, \
        (f"{f} contributes no tests to the tier-1 selection — the "
         f"scenario suite's exactly-once coverage left the gate")


def test_incremental_checkpoint_suite_collects_under_tier1():
    """The incremental-checkpoint suite (ISSUE-16) must contribute tests
    to the tier-1 run under ``JAX_PLATFORMS=cpu`` — the digest-identical
    chain-restore acceptances per state tier, the bytes-scale-with-churn
    budget, the storage chain/compaction/retention semantics and the
    MiniCluster sub-second end-to-end all run on the CPU backend, so a
    slow-mark sweep that silently drops them fails here."""
    import subprocess

    f = "test_incremental_checkpoints.py"
    assert (TESTS / f).exists(), f
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "--collect-only", "-q",
         "-m", "not slow", "-p", "no:cacheprovider", str(TESTS / f)],
        capture_output=True, text=True, timeout=300, cwd=str(REPO),
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert f"{f}::" in proc.stdout, \
        (f"{f} contributes no tests to the tier-1 selection — the "
         f"incremental-checkpoint restore coverage left the gate")


def test_ha_suite_collects_under_tier1():
    """The coordinator-HA suite (ISSUE-20) must contribute tests to the
    tier-1 run under ``JAX_PLATFORMS=cpu`` — the lease/epoch units, the
    store/worker/data-plane/2PC stale-epoch fences, the pinned-retention
    and resolve_restore recovery semantics and the kill-the-leader
    scenario acceptance all run on the CPU backend, so a slow-mark sweep
    that silently drops them fails here."""
    import subprocess

    f = "test_ha.py"
    assert (TESTS / f).exists(), f
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "--collect-only", "-q",
         "-m", "not slow", "-p", "no:cacheprovider", str(TESTS / f)],
        capture_output=True, text=True, timeout=300, cwd=str(REPO),
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert f"{f}::" in proc.stdout, \
        (f"{f} contributes no tests to the tier-1 selection — the "
         f"coordinator-HA fencing coverage left the gate")


def test_marker_declarations_have_descriptions():
    """Each declared marker carries a description (the `name: text` form)
    so `pytest --markers` documents the tiers."""
    entries = _marker_entries()
    assert entries
    for entry in entries:
        assert ":" in entry and entry.split(":", 1)[1].strip(), \
            f"marker {entry!r} lacks a description"
