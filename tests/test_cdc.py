"""CDC changelog formats (debezium/canal/maxwell JSON) and the
retraction-consuming group aggregate they feed —
``DebeziumJsonDeserializationSchema.java:56`` analog end-to-end.
"""

import json

import numpy as np
import pytest

from flink_tpu.core.batch import RecordBatch
from flink_tpu.formats.cdc import (cdc_decoder, decode_canal,
                                   decode_debezium, decode_maxwell,
                                   encode_canal, encode_debezium)
from flink_tpu.operators.sql_ops import ChangelogGroupAggOperator


# ---------------------------------------------------------------------------
# decoders: spec-shaped payload fixtures
# ---------------------------------------------------------------------------


def test_debezium_envelopes():
    c = {"before": None, "after": {"id": 1, "v": 10}, "op": "c",
         "ts_ms": 1}
    assert decode_debezium(json.dumps(c)) == [{"id": 1, "v": 10,
                                               "op": "+I"}]
    r = {"before": None, "after": {"id": 2, "v": 5}, "op": "r"}
    assert decode_debezium(r)[0]["op"] == "+I"
    u = {"before": {"id": 1, "v": 10}, "after": {"id": 1, "v": 20},
         "op": "u"}
    assert decode_debezium(u) == [{"id": 1, "v": 10, "op": "-U"},
                                  {"id": 1, "v": 20, "op": "+U"}]
    d = {"before": {"id": 1, "v": 20}, "after": None, "op": "d"}
    assert decode_debezium(d) == [{"id": 1, "v": 20, "op": "-D"}]
    # schema-included envelope unwraps
    wrapped = {"schema": {"type": "struct"}, "payload": u}
    assert decode_debezium(wrapped)[0]["op"] == "-U"
    with pytest.raises(ValueError, match="unknown debezium op"):
        decode_debezium({"op": "x"})


def test_canal_envelopes():
    ins = {"data": [{"id": 1, "v": 10}, {"id": 2, "v": 20}], "old": None,
           "type": "INSERT"}
    assert [r["op"] for r in decode_canal(ins)] == ["+I", "+I"]
    # canal 'old' carries ONLY the changed columns
    upd = {"data": [{"id": 1, "v": 30}], "old": [{"v": 10}],
           "type": "UPDATE"}
    assert decode_canal(upd) == [{"id": 1, "v": 10, "op": "-U"},
                                 {"id": 1, "v": 30, "op": "+U"}]
    dele = {"data": [{"id": 2, "v": 20}], "old": None, "type": "DELETE"}
    assert decode_canal(dele) == [{"id": 2, "v": 20, "op": "-D"}]


def test_maxwell_envelopes():
    ins = {"database": "d", "table": "t", "type": "insert",
           "data": {"id": 1, "v": 10}}
    assert decode_maxwell(ins) == [{"id": 1, "v": 10, "op": "+I"}]
    upd = {"type": "update", "data": {"id": 1, "v": 30}, "old": {"v": 10}}
    assert decode_maxwell(upd) == [{"id": 1, "v": 10, "op": "-U"},
                                   {"id": 1, "v": 30, "op": "+U"}]
    dele = {"type": "delete", "data": {"id": 1, "v": 30}}
    assert decode_maxwell(dele) == [{"id": 1, "v": 30, "op": "-D"}]


def test_encode_decode_round_trip():
    changelog = [{"k": "a", "v": 1, "op": "+I"},
                 {"k": "a", "v": 1, "op": "-U"},
                 {"k": "a", "v": 2, "op": "+U"},
                 {"k": "a", "v": 2, "op": "-D"}]
    # debezium round trip
    envs = encode_debezium(changelog)
    assert [e["op"] for e in envs] == ["c", "u", "d"]
    back = [r for e in envs for r in decode_debezium(e)]
    assert back == changelog
    # canal round trip
    envs = encode_canal(changelog)
    assert [e["type"] for e in envs] == ["INSERT", "UPDATE", "DELETE"]
    back = [r for e in envs for r in decode_canal(e)]
    assert back == changelog


# ---------------------------------------------------------------------------
# retraction-consuming group aggregate
# ---------------------------------------------------------------------------


def batch(rows):
    cols = {c: np.asarray([r[c] for r in rows], object) for c in rows[0]}
    return RecordBatch(cols)


def collect_rows(elements):
    out = []
    for el in elements:
        arrs = {c: np.asarray(el.column(c)) for c in el.columns}
        for i in range(len(el)):
            out.append({c: arrs[c][i] for c in arrs})
    return out


def test_group_agg_consumes_retractions():
    op = ChangelogGroupAggOperator(
        "k", {"total": ("v", "sum"), "n": (None, "count")},
        consume_retractions=True)
    r1 = collect_rows(op.process_batch(batch(
        [{"k": "a", "v": 10.0, "op": "+I"},
         {"k": "a", "v": 5.0, "op": "+I"}])))
    assert r1 == [{"op": "+I", "k": "a", "total": 15.0, "n": 2.0}]
    # an update arrives as -U old / +U new
    r2 = collect_rows(op.process_batch(batch(
        [{"k": "a", "v": 5.0, "op": "-U"},
         {"k": "a", "v": 7.0, "op": "+U"}])))
    assert r2 == [{"op": "-U", "k": "a", "total": 15.0, "n": 2.0},
                  {"op": "+U", "k": "a", "total": 17.0, "n": 2.0}]
    # deleting every row of the group retracts the group itself
    r3 = collect_rows(op.process_batch(batch(
        [{"k": "a", "v": 10.0, "op": "-D"},
         {"k": "a", "v": 7.0, "op": "-D"}])))
    assert r3 == [{"op": "-D", "k": "a", "total": 17.0, "n": 2.0}]
    # re-insertion after deletion is a fresh +I
    r4 = collect_rows(op.process_batch(batch(
        [{"k": "a", "v": 1.0, "op": "+I"}])))
    assert r4 == [{"op": "+I", "k": "a", "total": 1.0, "n": 1.0}]


def test_group_agg_rejects_non_invertible_retraction():
    with pytest.raises(ValueError, match="cannot consume retractions"):
        ChangelogGroupAggOperator("k", {"m": ("v", "min")},
                                  consume_retractions=True)


def test_debezium_kafka_to_retracting_agg_end_to_end(tmp_path):
    """A Kafka topic of debezium envelopes drives a retracting group
    aggregate: the materialized result equals the source table's final
    state aggregated."""
    from flink_tpu.connectors.kafka import (KafkaWireBroker,
                                            KafkaWireClient,
                                            KafkaWireSource)

    broker = KafkaWireBroker(directory=str(tmp_path / "kafka")).start()
    try:
        broker.create_topic("cdc", partitions=1)
        envelopes = [
            {"before": None, "after": {"k": "a", "v": 10}, "op": "c"},
            {"before": None, "after": {"k": "b", "v": 1}, "op": "c"},
            {"before": None, "after": {"k": "a", "v": 5}, "op": "c"},
            {"before": {"k": "a", "v": 5}, "after": {"k": "a", "v": 7},
             "op": "u"},
            {"before": {"k": "b", "v": 1}, "after": None, "op": "d"},
        ]
        c = KafkaWireClient(broker.host, broker.port)
        c.produce("cdc", 0, [(None, json.dumps(e).encode())
                             for e in envelopes])
        c.close()

        src = KafkaWireSource(broker.host, broker.port, "cdc",
                              value_decoder=cdc_decoder("debezium-json"))
        agg = ChangelogGroupAggOperator(
            "k", {"total": ("v", "sum")}, consume_retractions=True)
        out = []
        for split in src.create_splits(1):
            for el in split.read():
                if isinstance(el, RecordBatch):
                    out.extend(collect_rows(agg.process_batch(el)))
        # materialize the emitted changelog
        state = {}
        for r in out:
            if r["op"] in ("+I", "+U"):
                state[r["k"]] = r["total"]
            elif r["op"] == "-D":
                state.pop(r["k"], None)
        # final source state: a has rows 10 and 7; b deleted
        assert state == {"a": 17.0}
    finally:
        broker.stop()


def test_table_api_select_changelog_over_cdc_table(tmp_path):
    """Table API: group aggregation over a DDL-declared CDC table folds
    the retractions automatically (the op column marks the input as a
    changelog)."""
    from flink_tpu.connectors.kafka import KafkaWireBroker, KafkaWireClient
    from flink_tpu.sql.table_env import TableEnvironment

    broker = KafkaWireBroker(directory=str(tmp_path / "kafka")).start()
    try:
        broker.create_topic("cdc2", partitions=1)
        envs = [
            {"before": None, "after": {"k": "a", "v": 10}, "op": "c"},
            {"before": None, "after": {"k": "a", "v": 5}, "op": "c"},
            {"before": {"k": "a", "v": 5}, "after": {"k": "a", "v": 7},
             "op": "u"},
        ]
        c = KafkaWireClient(broker.host, broker.port)
        c.produce("cdc2", 0, [(None, json.dumps(e).encode())
                              for e in envs])
        c.close()
        tenv = TableEnvironment()
        tenv.execute_sql(f"""
            CREATE TABLE cdc2 (k STRING, v BIGINT) WITH (
              'connector' = 'kafka', 'topic' = 'cdc2',
              'properties.bootstrap.servers' =
                '{broker.host}:{broker.port}',
              'format' = 'debezium-json')
        """)
        res = tenv.sql_query("SELECT * FROM cdc2").group_by("k") \
            .select_changelog("k, SUM(v) AS total")
        rows = res.collect()
        # materialize: the final total reflects the UPDATE (10 + 7)
        state = {}
        for r in rows:
            if r["op"] in ("+I", "+U"):
                state[r["k"]] = r["total"]
            elif r["op"] == "-D":
                state.pop(r["k"], None)
        assert state == {"a": 17.0}
    finally:
        broker.stop()
