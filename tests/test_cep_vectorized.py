"""Vectorized CEP engine (ISSUE-8 tentpole): the batched NFA
state-transition kernel must be BIT-identical to the interpreted matcher —
same matches, same order, same counters, same snapshots — on every
eligible pattern, fall back (plan-time and mid-job) everywhere else, and
keep event rows columnar until a match actually references them."""

import os

import numpy as np
import pytest

from flink_tpu.cep import (AfterMatchSkipStrategy, CepOperator, Pattern,
                           classify_pattern)
from flink_tpu.cep.vectorized import _reset_calibration
from flink_tpu.core.batch import RecordBatch, Watermark


def _is(kind):
    return lambda cols: np.asarray(cols["kind"]) == kind


def _sel(m):
    return {"sig": "|".join(f"{n}:{','.join(r['kind'] for r in rs)}"
                            for n, rs in sorted(m.items())),
            "k": next(iter(m.values()))[0]["k"]}


def _stream(seed, n=90, n_keys=6):
    """Seeded event stream staged into uneven batches with jittery
    watermarks (some events held across drains)."""
    rng = np.random.default_rng(seed)
    kinds = ["a", "b", "c", "m", "s", "e", "x"]
    evs = [(int(rng.integers(0, n_keys)), kinds[rng.integers(0, len(kinds))],
            t) for t in range(n)]
    chunks, wms = [], []
    t = 0
    while t < n:
        sz = int(rng.integers(0, 7))
        chunks.append(evs[t:t + sz])
        t += sz
        wms.append(int(rng.integers(max(0, t - 8), t + 3)))
    return chunks, wms


def _run(mode, pattern, chunks, wms, snap_at=(), select=_sel):
    op = CepOperator(pattern, "k", select, vectorized=mode)
    out, snaps = [], []
    for j, (chunk, wm) in enumerate(zip(chunks, wms)):
        if chunk:
            ks = np.asarray([e[0] for e in chunk], np.int64)
            kk = np.asarray([e[1] for e in chunk], object)
            ts = np.asarray([e[2] for e in chunk], np.int64)
            out += op.process_batch(RecordBatch({"k": ks, "kind": kk},
                                                timestamps=ts))
        out += op.process_watermark(Watermark(wm))
        if j in snap_at:
            snaps.append(op.snapshot_state())
    out += op.end_input()
    rows = [tuple(sorted((c, str(b.columns[c][i])) for c in b.columns))
            + (int(np.asarray(b.timestamps)[i]),)
            for b in out for i in range(len(b))]
    return rows, op, snaps


def _snap_eq(a, b):
    if isinstance(a, dict) and isinstance(b, dict):
        return (list(a.keys()) == list(b.keys())
                and all(_snap_eq(a[k], b[k]) for k in a))
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return len(a) == len(b) and all(_snap_eq(x, y)
                                        for x, y in zip(a, b))
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        return np.array_equal(np.asarray(a), np.asarray(b))
    return a == b


def _corpus(skip):
    return {
        "followed_by": Pattern.begin("a", skip_strategy=skip)
        .where(_is("a")).followed_by("b").where(_is("b")),
        "next_strict": Pattern.begin("a", skip_strategy=skip)
        .where(_is("a")).next("b").where(_is("b")),
        "times_1_3": Pattern.begin("a", skip_strategy=skip)
        .where(_is("a")).times(1, 3).followed_by("b").where(_is("b")),
        "times_2_strict": Pattern.begin("a", skip_strategy=skip)
        .where(_is("a")).times(2).next("b").where(_is("b")),
        "one_or_more_within": Pattern.begin("a", skip_strategy=skip)
        .where(_is("a")).one_or_more().followed_by("b").where(_is("b"))
        .within(7),
        "optional_chain": Pattern.begin("a", skip_strategy=skip)
        .where(_is("a")).followed_by("m").where(_is("m")).optional()
        .followed_by("m2").where(_is("s")).optional()
        .followed_by("b").where(_is("b")),
        "not_next": Pattern.begin("a", skip_strategy=skip)
        .where(_is("a")).not_next("nb").where(_is("b"))
        .next("c").where(_is("c")),
        "not_next_end": Pattern.begin("a", skip_strategy=skip)
        .where(_is("a")).not_next("nb").where(_is("b")),
        "not_followed_by": Pattern.begin("a", skip_strategy=skip)
        .where(_is("a")).not_followed_by("nb").where(_is("b"))
        .followed_by("c").where(_is("c")).within(15),
        "trailing_negation": Pattern.begin("a", skip_strategy=skip)
        .where(_is("a")).times(1, 2).not_followed_by("nb").where(_is("b"))
        .within(6),
        "until_loop": Pattern.begin("a", skip_strategy=skip)
        .where(_is("a")).one_or_more().until(_is("s"))
        .followed_by("e").where(_is("e")).within(20),
    }


@pytest.mark.parametrize("skip", [AfterMatchSkipStrategy.NO_SKIP,
                                  AfterMatchSkipStrategy.SKIP_PAST_LAST_EVENT])
@pytest.mark.parametrize("name", sorted(_corpus(
    AfterMatchSkipStrategy.NO_SKIP)))
def test_equivalence_corpus(skip, name):
    """The corpus acceptance: quantifiers, strict/relaxed contiguity,
    not-patterns (incl. trailing under within), until, optional, both
    skip strategies — matches, order, counters, AND mid-stream snapshots
    bit-identical vectorized vs interpreted across 3 seeds."""
    pattern = _corpus(skip)[name]
    for seed in (0, 7, 11):
        chunks, wms = _stream(seed)
        snap_at = {len(chunks) // 2}
        r_on, op_on, sn_on = _run("on", pattern, chunks, wms, snap_at)
        r_off, op_off, sn_off = _run("off", pattern, chunks, wms, snap_at)
        assert r_on == r_off, f"seed {seed}: match rows diverge"
        s1, s2 = op_on.cep_stats(), op_off.cep_stats()
        assert s1["matches"] == s2["matches"]
        assert s1["partials_high_water"] == s2["partials_high_water"]
        assert all(_snap_eq(a, b) for a, b in zip(sn_on, sn_off)), \
            f"seed {seed}: snapshots diverge"


def test_jit_kernel_matches_numpy_kernel():
    """The jax.jit kernel leg produces the numpy kernel's exact results
    (its dup/overflow flags replay on the numpy path, so bit-identity
    never rests on a hash)."""
    pattern = _corpus(AfterMatchSkipStrategy.SKIP_PAST_LAST_EVENT)[
        "times_1_3"]
    chunks, wms = _stream(3, n=60)

    def run_kernel(kernel):
        op = CepOperator(pattern, "k", _sel, vectorized="on")
        op._resolve_engine()
        op._vec.kernel = kernel
        out = []
        for chunk, wm in zip(chunks, wms):
            if chunk:
                ks = np.asarray([e[0] for e in chunk], np.int64)
                kk = np.asarray([e[1] for e in chunk], object)
                ts = np.asarray([e[2] for e in chunk], np.int64)
                out += op.process_batch(
                    RecordBatch({"k": ks, "kind": kk}, timestamps=ts))
            out += op.process_watermark(Watermark(wm))
        out += op.end_input()
        return [tuple(sorted((c, str(b.columns[c][i]))
                             for c in b.columns))
                for b in out for i in range(len(b))]

    assert run_kernel("jit") == run_kernel("numpy")


# ---------------------------------------------------------------------------
# plan-time classifier
# ---------------------------------------------------------------------------

def test_classifier_rejects_followed_by_any():
    p = (Pattern.begin("a").where(_is("a"))
         .followed_by_any("b").where(_is("b")))
    ok, reasons = classify_pattern(p)
    assert not ok and any("relaxed_any" in r for r in reasons)


def test_classifier_rejects_greedy():
    p = (Pattern.begin("a").where(_is("a")).one_or_more().greedy()
         .followed_by("b").where(_is("b")))
    ok, reasons = classify_pattern(p)
    assert not ok and any("greedy" in r for r in reasons)


def test_classifier_accepts_full_eligible_surface():
    p = (Pattern.begin("a").where(_is("a")).times(1, 3)
         .not_followed_by("nb").where(_is("b"))
         .followed_by("c").where(_is("c")).optional()
         .followed_by("d").where(_is("e")).within(100))
    ok, reasons = classify_pattern(p)
    assert ok and reasons == []


def test_vectorized_on_raises_for_ineligible_pattern():
    p = (Pattern.begin("a").where(_is("a"))
         .followed_by_any("b").where(_is("b")))
    with pytest.raises(ValueError, match="not eligible"):
        CepOperator(p, "k", _sel, vectorized="on")


def test_deferred_conditions_fall_back_interpreted():
    """MATCH_RECOGNIZE-style drain-time/PREV conditions are ineligible at
    first cut: the operator resolves to the interpreted engine and says
    why."""
    p = Pattern.begin("a").where(_is("a")).followed_by("b").where(_is("b"))
    op = CepOperator(p, "k", _sel, defer_conditions=True, vectorized="auto")
    op._resolve_engine()
    st = op.cep_stats()
    assert st["engine"] == "interpreted"
    assert any("deferred" in r or "PREV" in r
               for r in st["fallback_reasons"])


def test_ineligible_pattern_auto_falls_back():
    p = (Pattern.begin("a").where(_is("a")).one_or_more().greedy()
         .followed_by("b").where(_is("b")))
    chunks, wms = _stream(2, n=40)
    rows, op, _ = _run("auto", p, chunks, wms)
    assert op.cep_stats()["engine"] == "interpreted"
    r_off, _op2, _ = _run("off", p, chunks, wms)
    assert rows == r_off


def test_env_override_forces_engine(monkeypatch):
    monkeypatch.setenv("FLINK_TPU_CEP_VECTORIZED", "off")
    _reset_calibration()
    try:
        p = (Pattern.begin("a").where(_is("a"))
             .followed_by("b").where(_is("b")))
        op = CepOperator(p, "k", _sel, vectorized="auto")
        op._resolve_engine()
        assert op.cep_stats()["engine"] == "interpreted"
    finally:
        _reset_calibration()


# ---------------------------------------------------------------------------
# snapshots across engines + sticky growth + lazy rows
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("first,second", [("on", "off"), ("off", "on")])
def test_cross_engine_restore(first, second):
    """A mid-stream snapshot from either engine restores into the OTHER
    and continues with identical matches — one logical state, two
    executions."""
    pattern = _corpus(AfterMatchSkipStrategy.NO_SKIP)["one_or_more_within"]
    chunks, wms = _stream(5, n=80)
    half = len(chunks) // 2
    ref_rows, _op, _ = _run("off", pattern, chunks, wms)

    op1 = CepOperator(pattern, "k", _sel, vectorized=first)
    out = []
    for chunk, wm in zip(chunks[:half], wms[:half]):
        if chunk:
            ks = np.asarray([e[0] for e in chunk], np.int64)
            kk = np.asarray([e[1] for e in chunk], object)
            ts = np.asarray([e[2] for e in chunk], np.int64)
            out += op1.process_batch(RecordBatch({"k": ks, "kind": kk},
                                                 timestamps=ts))
        out += op1.process_watermark(Watermark(wm))
    snap = op1.snapshot_state()

    op2 = CepOperator(pattern, "k", _sel, vectorized=second)
    op2.restore_state(snap)
    for chunk, wm in zip(chunks[half:], wms[half:]):
        if chunk:
            ks = np.asarray([e[0] for e in chunk], np.int64)
            kk = np.asarray([e[1] for e in chunk], object)
            ts = np.asarray([e[2] for e in chunk], np.int64)
            out += op2.process_batch(RecordBatch({"k": ks, "kind": kk},
                                                 timestamps=ts))
        out += op2.process_watermark(Watermark(wm))
    out += op2.end_input()
    rows = [tuple(sorted((c, str(b.columns[c][i])) for c in b.columns))
            + (int(np.asarray(b.timestamps)[i]),)
            for b in out for i in range(len(b))]
    assert rows == ref_rows


def test_sticky_growth_from_tiny_caps():
    """Long oneOrMore runs overflow the initial partial/event-ring caps;
    the sticky pow2 growth must preserve bit-identity."""
    p = (Pattern.begin("a").where(_is("a")).one_or_more()
         .followed_by("b").where(_is("b")))
    evs = [(1, "a", t) for t in range(9)] + [(1, "b", 9)]
    chunks, wms = [evs], [100]
    r_on, op_on, _ = _run("on", p, chunks, wms)
    r_off, _op, _ = _run("off", p, chunks, wms)
    # oneOrMore branches on every sub-run ending at the 'b'
    assert r_on == r_off and len(r_on) == 45
    # growth actually happened (caps start at 4/4)
    assert op_on._vec.m_cap > 4 and op_on._vec.e_cap > 4


def test_process_batch_never_materializes_rows_upfront():
    """ISSUE-8 satellite: ``batch.to_rows()`` must not run at ingest —
    rows materialize lazily from the columnar store at emit time."""
    p = Pattern.begin("a").where(_is("a")).followed_by("b").where(_is("b"))
    for mode in ("on", "off"):
        op = CepOperator(p, "k", _sel, vectorized=mode)
        class NoRows(RecordBatch):
            def to_rows(self):
                raise AssertionError("to_rows called on the ingest path")

        b = NoRows(
            {"k": np.zeros(4, np.int64),
             "kind": np.asarray(["a", "x", "b", "x"], object)},
            timestamps=np.arange(4, dtype=np.int64))
        op.process_batch(b)
        out = op.process_watermark(Watermark(100))
        assert sum(len(x) for x in out) == 1


def test_row_store_prunes_unreferenced_batches():
    """The columnar row store drops whole batches once nothing references
    them — host memory must not grow with total events processed."""
    p = Pattern.begin("a").where(_is("a")).next("b").where(_is("b"))
    for mode in ("on", "off"):
        op = CepOperator(p, "k", _sel, vectorized=mode)
        for lo in range(0, 500, 50):
            kk = np.asarray(["x"] * 50, object)   # never matches a stage
            b = RecordBatch({"k": np.zeros(50, np.int64), "kind": kk},
                            timestamps=np.arange(lo, lo + 50,
                                                 dtype=np.int64))
            op.process_batch(b)
            op.process_watermark(Watermark(lo + 49))
        assert op.cep_stats()["batches"] == 0, mode
        snap = op.snapshot_state()
        assert sum(len(r) for _p, _s, r in snap["nfas"].values()) == 0


def test_pattern_stream_threads_vectorized():
    """``.pattern(...).select(vectorized=...)`` reaches the operator."""
    from flink_tpu.datastream.api import StreamExecutionEnvironment
    from flink_tpu.cep import CEP

    env = StreamExecutionEnvironment()
    rows = [{"k": 1, "kind": "a", "ts": 1}, {"k": 1, "kind": "b", "ts": 2}]
    p = Pattern.begin("a").where(_is("a")).followed_by("b").where(_is("b"))
    stream = (env.from_collection(rows, timestamp_column="ts")
              .assign_timestamps_and_watermarks(0, timestamp_column="ts")
              .key_by("k"))
    sink = CEP.pattern(stream, p).select(
        lambda m: {"n": len(m)}, vectorized="on").collect()
    env.execute("cep-vec")
    assert len(sink.rows()) == 1


def test_match_recognize_threads_vectorized_mode():
    """The SQL MATCH_RECOGNIZE lowering threads the planner's
    ``cep_vectorized`` mode into the CepOperator; deferred (PREV-capable)
    conditions keep it on the interpreted engine at first cut."""
    from flink_tpu.sql.table_env import TableEnvironment

    cols = {"k": np.asarray([1, 1, 1], np.int64),
            "v": np.asarray([1.0, 9.0, 2.0]),
            "ts": np.asarray([1, 2, 3], np.int64)}
    tenv = TableEnvironment(cep_vectorized="auto")
    tenv.register_collection("t", columns=cols, rowtime="ts")
    rows = tenv.execute_sql(
        "SELECT k, n FROM t MATCH_RECOGNIZE (PARTITION BY k ORDER BY ts "
        "MEASURES COUNT(*) AS n AFTER MATCH SKIP PAST LAST ROW "
        "PATTERN (A B) DEFINE A AS v < 5, B AS v > 5)").collect()
    assert len(rows) == 1 and int(rows[0]["n"]) == 2


# ---------------------------------------------------------------------------
# chaos: mid-job quarantine degrades to the interpreted path
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_wedged_kernel_degrades_digest_identical():
    """A WedgedDevice schedule hangs the vectorized drain dispatch; the
    watchdog quarantines, the operator decodes its array state into
    per-key NFAs MID-JOB and re-drains the identical pending events
    interpreted — matches digest-identical to an unfaulted pass."""
    from flink_tpu.runtime import device_health as dh
    from flink_tpu.testing import chaos

    pattern = (Pattern.begin("a").where(_is("a"))
               .followed_by("b").where(_is("b")).within(30))
    rng = np.random.default_rng(9)
    kinds = ["a", "b", "x"]
    evs = [(int(rng.integers(0, 8)), kinds[rng.integers(0, 3)], t)
           for t in range(80)]

    def one_pass(inject):
        prev = dh.get_monitor(create=False)
        dh.set_monitor(dh.DeviceHealthMonitor(
            dh.WatchdogConfig(deadline_floor_s=0.5), heal_async=False))
        inj = chaos.FaultInjector(seed=3)
        if inject:
            inj.inject("device.dispatch", chaos.WedgedDevice(at=4))
        op = CepOperator(pattern, "k", _sel, vectorized="on")
        out = []
        try:
            with chaos.installed(inj):
                for lo in range(0, len(evs), 8):
                    ch = evs[lo:lo + 8]
                    ks = np.asarray([e[0] for e in ch], np.int64)
                    kk = np.asarray([e[1] for e in ch], object)
                    ts = np.asarray([e[2] for e in ch], np.int64)
                    out += op.process_batch(
                        RecordBatch({"k": ks, "kind": kk}, timestamps=ts))
                    out += op.process_watermark(Watermark(int(ts.max())))
                out += op.end_input()
            stats = op.cep_stats()
        finally:
            dh.set_monitor(prev)
        rows = [tuple(sorted((c, str(b.columns[c][i]))
                             for c in b.columns))
                + (int(np.asarray(b.timestamps)[i]),)
                for b in out for i in range(len(b))]
        return rows, stats

    clean, s_clean = one_pass(False)
    wedged, s_wedged = one_pass(True)
    assert clean == wedged, "degraded pass diverged from unfaulted pass"
    assert s_clean["engine"] == "vectorized" and s_clean["degraded"] == 0
    assert s_wedged["engine"] == "interpreted"
    assert s_wedged["degraded"] == 1
    assert s_wedged["matches"] == s_clean["matches"]


def test_quarantined_monitor_degrades_before_dispatch():
    """An already-quarantined process-wide monitor sends the next drain
    straight to the interpreted engine (no dispatch attempt)."""
    from flink_tpu.runtime import device_health as dh

    prev = dh.get_monitor(create=False)
    mon = dh.DeviceHealthMonitor(dh.WatchdogConfig(deadline_floor_s=0.5),
                                 heal_async=False)
    dh.set_monitor(mon)
    try:
        mon.quarantine("test")
        p = (Pattern.begin("a").where(_is("a"))
             .followed_by("b").where(_is("b")))
        op = CepOperator(p, "k", _sel, vectorized="on")
        b = RecordBatch(
            {"k": np.zeros(2, np.int64),
             "kind": np.asarray(["a", "b"], object)},
            timestamps=np.arange(2, dtype=np.int64))
        op.process_batch(b)
        out = op.process_watermark(Watermark(10))
        assert sum(len(x) for x in out) == 1
        assert op.cep_stats()["engine"] == "interpreted"
        assert op.cep_stats()["degraded"] == 1
    finally:
        dh.set_monitor(prev)


def test_partial_set_tripling_in_one_step():
    """Regression: a step that nearly triples one hot key's partial set
    forces the compaction width past the candidate count (M_out > 3M+1
    after pow2 growth) — the kernel must pad, not crash, and must stay
    bit-identical (found by review fuzz: 4 hot keys, until-loop)."""
    skip = AfterMatchSkipStrategy.NO_SKIP
    for name in ("until_loop", "one_or_more_within", "times_1_3"):
        pattern = _corpus(skip)[name]
        for seed in (37, 41):
            chunks, wms = _stream(seed, n=120, n_keys=4)
            r_on, op_on, _ = _run("on", pattern, chunks, wms)
            r_off, op_off, _ = _run("off", pattern, chunks, wms)
            assert r_on == r_off, (name, seed)
            assert (op_on.cep_stats()["matches"]
                    == op_off.cep_stats()["matches"])


def test_cep_stats_never_runs_calibration():
    """Regression: a monitoring read on a fresh auto-mode operator must
    not block on the engine calibration A/B."""
    p = Pattern.begin("a").where(_is("a")).followed_by("b").where(_is("b"))
    op = CepOperator(p, "k", _sel, vectorized="auto")
    st = op.cep_stats()               # no batch processed yet
    assert st["engine"] == "unresolved"
