"""WindowAggOperator golden tests.

Modeled on the reference's ``WindowOperatorTest.java`` (SURVEY §4.2): push
elements + watermarks through a harness, assert emitted (key, value,
timestamp) tuples per window — tumbling, sliding (pane combine), lateness /
late re-fire / beyond-lateness drop, count windows, snapshot/restore.
"""

import numpy as np
import pytest

from flink_tpu.core.functions import (
    AvgAggregator,
    CountAggregator,
    LambdaReduce,
    MaxAggregator,
    MinAggregator,
    SumAggregator,
    TupleAggregator,
)
from flink_tpu.operators.window_agg import WindowAggOperator
from flink_tpu.testing import KeyedOneInputOperatorHarness
from flink_tpu.testing.harness import sorted_rows
from flink_tpu.windowing import (
    CountTrigger,
    GlobalWindows,
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
    TumblingProcessingTimeWindows,
)


def make_op(assigner=None, agg=None, **kw):
    return WindowAggOperator(
        assigner or TumblingEventTimeWindows.of(100),
        agg or SumAggregator(np.float32),
        key_column="key",
        value_column="v",
        **kw,
    )


def rows(*kv_ts):
    rws, ts = [], []
    for k, v, t in kv_ts:
        rws.append({"key": k, "v": np.float32(v)})
        ts.append(t)
    return rws, ts


class TestTumbling:
    def test_basic_sum(self):
        h = KeyedOneInputOperatorHarness(make_op())
        r, t = rows((1, 1.0, 10), (1, 2.0, 20), (2, 5.0, 30), (1, 4.0, 150))
        h.process_elements(r, t)
        h.process_watermark(99)
        out = sorted_rows(h.extract_output_rows(), ("key",))
        assert [(o["key"], o["result"]) for o in out] == [(1, 3.0), (2, 5.0)]
        assert all(o["__ts__"] == 99 for o in out)          # window.maxTimestamp
        assert all(o["window_start"] == 0 and o["window_end"] == 100 for o in out)
        h.clear_output()
        h.process_watermark(199)
        out = h.extract_output_rows()
        assert [(o["key"], o["result"]) for o in out] == [(1, 4.0)]
        assert out[0]["window_start"] == 100

    def test_empty_windows_not_emitted(self):
        h = KeyedOneInputOperatorHarness(make_op())
        r, t = rows((1, 1.0, 10))
        h.process_elements(r, t)
        h.process_watermark(5000)  # many empty windows passed
        out = h.extract_output_rows()
        assert len(out) == 1

    def test_watermark_is_exclusive_boundary(self):
        # element AT window end belongs to the next window; watermark == end-1 fires
        h = KeyedOneInputOperatorHarness(make_op())
        r, t = rows((1, 1.0, 99), (1, 10.0, 100))
        h.process_elements(r, t)
        h.process_watermark(98)
        assert h.extract_output_rows() == []
        h.process_watermark(99)
        out = h.extract_output_rows()
        assert [(o["key"], o["result"]) for o in out] == [(1, 1.0)]

    def test_multiple_batches_accumulate(self):
        h = KeyedOneInputOperatorHarness(make_op())
        for v in (1.0, 2.0, 3.0):
            r, t = rows((7, v, 50))
            h.process_elements(r, t)
        h.process_watermark(99)
        out = h.extract_output_rows()
        assert [(o["key"], o["result"]) for o in out] == [(7, 6.0)]

    def test_offset(self):
        h = KeyedOneInputOperatorHarness(
            make_op(TumblingEventTimeWindows.of(100, offset_ms=30)))
        r, t = rows((1, 1.0, 20), (1, 2.0, 40))
        h.process_elements(r, t)
        h.process_watermark(29)  # window [-70,30) ends
        out = h.extract_output_rows()
        assert [(o["key"], o["result"], o["window_end"]) for o in out] == [(1, 1.0, 30)]


class TestAggregators:
    def _run(self, agg, vals, expect, value_column="v"):
        h = KeyedOneInputOperatorHarness(make_op(agg=agg))
        r, t = rows(*[(1, v, 10) for v in vals])
        h.process_elements(r, t)
        h.process_watermark(99)
        out = h.extract_output_rows()
        assert len(out) == 1
        assert out[0]["result"] == pytest.approx(expect)

    def test_min(self):
        self._run(MinAggregator(np.float32), [3.0, 1.0, 2.0], 1.0)

    def test_max(self):
        self._run(MaxAggregator(np.float32), [3.0, 1.0, 2.0], 3.0)

    def test_count(self):
        self._run(CountAggregator(), [3.0, 1.0, 2.0], 3)

    def test_avg(self):
        self._run(AvgAggregator(np.float32), [3.0, 1.0, 2.0], 2.0)

    def test_generic_reduce_no_scatter_kind(self):
        # LambdaReduce declares no scatter kind → generic segmented-scan path
        agg = LambdaReduce(lambda a, b: a + b, np.float32(0.0))
        assert agg.scatter_kind_leaves() is None
        self._run(agg, [1.0, 2.0, 4.0], 7.0)

    def test_multi_field_tuple_aggregate(self):
        agg = TupleAggregator({
            "total": ("v", SumAggregator(np.float32)),
            "lo": ("v", MinAggregator(np.float32)),
            "n": ("v", CountAggregator()),
        })
        op = WindowAggOperator(TumblingEventTimeWindows.of(100), agg,
                               key_column="key", value_selector=lambda c: c)
        h = KeyedOneInputOperatorHarness(op)
        r, t = rows((1, 5.0, 10), (1, 3.0, 20))
        h.process_elements(r, t)
        h.process_watermark(99)
        out = h.extract_output_rows()
        assert len(out) == 1
        assert out[0]["total"] == 8.0 and out[0]["lo"] == 3.0 and out[0]["n"] == 2


class TestSliding:
    def test_pane_combine(self):
        # size 100, slide 50 → pane 50; element in 2 windows
        h = KeyedOneInputOperatorHarness(
            make_op(SlidingEventTimeWindows.of(100, 50)))
        r, t = rows((1, 1.0, 60), (1, 2.0, 120))
        h.process_elements(r, t)
        h.process_watermark(250)
        out = h.extract_output_rows()
        got = {(o["window_start"], o["window_end"]): o["result"] for o in out}
        # ts=60 in windows [0,100) and [50,150); ts=120 in [50,150) and [100,200)
        assert got[(0, 100)] == 1.0
        assert got[(50, 150)] == 3.0
        assert got[(100, 200)] == 2.0

    def test_uneven_pane_count(self):
        # size 60, slide 20 → 3 panes/window
        h = KeyedOneInputOperatorHarness(
            make_op(SlidingEventTimeWindows.of(60, 20)))
        r, t = rows((1, 1.0, 5), (1, 2.0, 25), (1, 4.0, 45))
        h.process_elements(r, t)
        h.process_watermark(300)
        out = h.extract_output_rows()
        got = {(o["window_start"], o["window_end"]): o["result"] for o in out}
        assert got[(0, 60)] == 7.0
        assert got[(-40, 20)] == 1.0
        assert got[(20, 80)] == 6.0
        assert got[(40, 100)] == 4.0


class TestLateness:
    def test_beyond_lateness_dropped(self):
        op = make_op(allowed_lateness_ms=0)
        h = KeyedOneInputOperatorHarness(op)
        r, t = rows((1, 1.0, 10))
        h.process_elements(r, t)
        h.process_watermark(99)
        h.clear_output()
        r, t = rows((1, 100.0, 50))  # late beyond lateness: window fired+cleaned
        h.process_elements(r, t)
        h.process_watermark(199)
        assert h.extract_output_rows() == []
        assert op.late_dropped == 1

    def test_late_within_lateness_refires(self):
        op = make_op(allowed_lateness_ms=200)
        h = KeyedOneInputOperatorHarness(op)
        r, t = rows((1, 1.0, 10))
        h.process_elements(r, t)
        h.process_watermark(99)
        h.clear_output()
        # late but within lateness → accumulates and re-fires immediately
        r, t = rows((1, 2.0, 20))
        h.process_elements(r, t)
        out = h.extract_output_rows()
        assert [(o["key"], o["result"]) for o in out] == [(1, 3.0)]
        assert op.late_dropped == 0
        # past end+lateness → cleanup, further late data dropped
        h.process_watermark(400)
        h.clear_output()
        r, t = rows((1, 50.0, 30))
        h.process_elements(r, t)
        assert h.extract_output_rows() == []
        assert op.late_dropped == 1


class TestCountWindows:
    def test_count_trigger_fire_and_purge(self):
        op = WindowAggOperator(
            GlobalWindows.create(), SumAggregator(np.float32),
            key_column="key", value_column="v",
            trigger=CountTrigger.of(2, purge=True),
            emit_window_bounds=False)
        h = KeyedOneInputOperatorHarness(op)
        r, t = rows((1, 1.0, 0), (1, 2.0, 0), (2, 5.0, 0))
        h.process_elements(r, t)
        out = h.extract_output_rows()
        assert [(o["key"], o["result"]) for o in out] == [(1, 3.0)]
        h.clear_output()
        r, t = rows((1, 10.0, 0), (2, 1.0, 0), (1, 20.0, 0))
        h.process_elements(r, t)
        out = sorted_rows(h.extract_output_rows(), ("key",))
        # key 1 purged after first fire → 10+20; key 2 reaches 2 elements → 5+1
        assert [(o["key"], o["result"]) for o in out] == [(1, 30.0), (2, 6.0)]


class TestProcessingTime:
    def test_proc_time_window(self):
        op = make_op(TumblingProcessingTimeWindows.of(100))
        h = KeyedOneInputOperatorHarness(op)
        h.time_service.advance_to(10)
        r, t = rows((1, 1.0, 0), (1, 2.0, 0))
        h.process_elements(r, t)
        h.set_processing_time(98)
        assert h.extract_output_rows() == []
        # ProcessingTimeTrigger registers a timer at window.maxTimestamp (99)
        h.set_processing_time(99)
        out = h.extract_output_rows()
        assert [(o["key"], o["result"]) for o in out] == [(1, 3.0)]


class TestSnapshotRestore:
    def test_mid_window_snapshot_restore(self):
        op = make_op()
        h = KeyedOneInputOperatorHarness(op)
        r, t = rows((1, 1.0, 10), (2, 7.0, 20), (1, 2.0, 110))
        h.process_elements(r, t)
        snap = h.snapshot()

        op2 = make_op()
        h2 = KeyedOneInputOperatorHarness.restored(op2, snap)
        h2.process_elements(*rows((1, 4.0, 30)))
        h2.process_watermark(199)
        out = sorted_rows(
            [o for o in h2.extract_output_rows() if o["window_end"] == 100], ("key",))
        assert [(o["key"], o["result"]) for o in out] == [(1, 5.0), (2, 7.0)]
        out2 = [o for o in h2.extract_output_rows() if o["window_end"] == 200]
        assert [(o["key"], o["result"]) for o in out2] == [(1, 2.0)]

    def test_restore_preserves_fired_horizon(self):
        op = make_op()
        h = KeyedOneInputOperatorHarness(op)
        h.process_elements(*rows((1, 1.0, 10)))
        h.process_watermark(99)
        snap = h.snapshot()
        op2 = make_op()
        h2 = KeyedOneInputOperatorHarness.restored(op2, snap)
        h2.process_watermark(99)  # same watermark again must not re-fire
        assert h2.extract_output_rows() == []


class TestStringKeys:
    def test_object_key_index(self):
        h = KeyedOneInputOperatorHarness(make_op())
        h.process_elements([{"key": "alpha", "v": np.float32(1.0)},
                            {"key": "beta", "v": np.float32(2.0)},
                            {"key": "alpha", "v": np.float32(3.0)}], [10, 20, 30])
        h.process_watermark(99)
        out = sorted_rows(h.extract_output_rows(), ("key",))
        assert [(o["key"], o["result"]) for o in out] == [("alpha", 4.0), ("beta", 2.0)]


class TestGrowth:
    def test_key_capacity_doubling(self):
        op = make_op(initial_key_capacity=4)
        h = KeyedOneInputOperatorHarness(op)
        n = 100
        r = [{"key": k, "v": np.float32(k)} for k in range(n)]
        h.process_elements(r, [10] * n)
        h.process_watermark(99)
        out = sorted_rows(h.extract_output_rows(), ("key",))
        assert len(out) == n
        assert all(o["result"] == float(o["key"]) for o in out)

    def test_pane_ring_growth_on_time_jump(self):
        op = make_op(initial_panes=2)
        h = KeyedOneInputOperatorHarness(op)
        h.process_elements(*rows((1, 1.0, 10)))
        h.process_elements(*rows((1, 2.0, 100 * 40)))  # 40 windows ahead
        h.process_watermark(100 * 41)
        out = h.extract_output_rows()
        got = {o["window_start"]: o["result"] for o in out}
        assert got[0] == 1.0 and got[4000] == 2.0


def test_async_fire_same_results_one_call_later():
    """async_fire defers emission to the next operator call but must emit
    IDENTICAL rows overall (terminal-sink pipelining mode)."""
    import jax.numpy as jnp

    from flink_tpu.core.batch import RecordBatch, Watermark
    from flink_tpu.core.functions import RuntimeContext, SumAggregator
    from flink_tpu.operators.window_agg import WindowAggOperator
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    rng = np.random.default_rng(21)
    n = 5000
    keys = rng.integers(0, 37, n)
    vals = rng.random(n).astype(np.float32)
    ts = np.sort(rng.integers(0, 5000, n))

    def run(async_fire):
        op = WindowAggOperator(TumblingEventTimeWindows.of(1000),
                               SumAggregator(jnp.float32), key_column="k",
                               value_column="v", async_fire=async_fire)
        op.open(RuntimeContext())
        out = []
        for lo in range(0, n, 512):
            hi = min(lo + 512, n)
            out += op.process_batch(RecordBatch(
                {"k": keys[lo:hi], "v": vals[lo:hi]}, timestamps=ts[lo:hi]))
            out += op.process_watermark(Watermark(int(ts[hi - 1]) - 1))
        out += op.end_input()
        rows = {}
        for b in out:
            for r in b.to_rows():
                rows[(r["k"], r["window_start"])] = r["result"]
        return rows

    sync_rows = run(False)
    async_rows = run(True)
    assert sync_rows.keys() == async_rows.keys()
    for k in sync_rows:
        assert abs(sync_rows[k] - async_rows[k]) < 1e-3


def test_count_trigger_over_tumbling_windows():
    """CountTrigger.of(n) on tumbling event-time windows: a (key, window)
    fires when its count crosses n and purges (FIRE_AND_PURGE)."""
    import jax.numpy as jnp

    from flink_tpu.core.batch import RecordBatch, Watermark
    from flink_tpu.core.functions import RuntimeContext, SumAggregator
    from flink_tpu.operators.window_agg import WindowAggOperator
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows
    from flink_tpu.windowing.triggers import CountTrigger

    op = WindowAggOperator(TumblingEventTimeWindows.of(1000),
                           SumAggregator(jnp.float32), key_column="k",
                           value_column="v", trigger=CountTrigger.of(3, purge=True))
    op.open(RuntimeContext())
    # key 1 gets 3 records in window [0,1000) -> fires on the third
    out = op.process_batch(RecordBatch(
        {"k": np.array([1, 1, 2]), "v": np.array([1., 2., 9.])},
        timestamps=np.array([10, 20, 30])))
    assert out == []
    out = op.process_batch(RecordBatch(
        {"k": np.array([1])}, timestamps=np.array([40])).with_columns(
            {"k": np.array([1]), "v": np.array([4.])}))
    rows = [r for b in out for r in b.to_rows()]
    assert len(rows) == 1 and rows[0]["k"] == 1 and rows[0]["result"] == 7.0
    # purged: three MORE records fire again with a fresh count
    out = op.process_batch(RecordBatch(
        {"k": np.array([1, 1, 1]), "v": np.array([1., 1., 1.])},
        timestamps=np.array([50, 60, 70])))
    rows = [r for b in out for r in b.to_rows()]
    assert len(rows) == 1 and rows[0]["result"] == 3.0


def test_count_trigger_over_sliding_windows():
    """Non-purging CountTrigger over a SLIDING assigner: each overlapping
    (key, window) fires independently when n elements have arrived since its
    last fire; pane state is shared and never purged."""
    import jax.numpy as jnp

    from flink_tpu.core.batch import RecordBatch
    from flink_tpu.core.functions import RuntimeContext, SumAggregator
    from flink_tpu.operators.window_agg import WindowAggOperator
    from flink_tpu.windowing.assigners import SlidingEventTimeWindows
    from flink_tpu.windowing.triggers import CountTrigger

    # size 2000 / slide 1000 -> 2 panes per window
    op = WindowAggOperator(SlidingEventTimeWindows.of(2000, 1000),
                           SumAggregator(jnp.float32), key_column="k",
                           value_column="v",
                           trigger=CountTrigger.of(2, purge=False))
    op.open(RuntimeContext())
    # two elements at t=1100,1200: panes -> both covered by windows
    # [0,2000) and [1000,3000) -> both windows hit count 2 and fire
    out = op.process_batch(RecordBatch(
        {"k": np.array([7, 7]), "v": np.array([1., 2.])},
        timestamps=np.array([1100, 1200])))
    rows = [r for b in out for r in b.to_rows()]
    assert sorted((r["window_start"], r["result"]) for r in rows) == \
        [(0, 3.0), (1000, 3.0)]
    # one more element in the same panes: count 3 < 2+2 -> no fire yet
    out = op.process_batch(RecordBatch(
        {"k": np.array([7])}, timestamps=np.array([1300])).with_columns(
            {"k": np.array([7]), "v": np.array([10.])}))
    assert [r for b in out for r in b.to_rows()] == []
    # a fourth element: both windows fire again with the FULL running sum
    out = op.process_batch(RecordBatch(
        {"k": np.array([7])}, timestamps=np.array([1400])).with_columns(
            {"k": np.array([7]), "v": np.array([20.])}))
    rows = [r for b in out for r in b.to_rows()]
    assert sorted((r["window_start"], r["result"]) for r in rows) == \
        [(0, 33.0), (1000, 33.0)]


def test_count_trigger_sliding_window_isolation():
    """An element in a NON-shared pane advances only its own windows."""
    import jax.numpy as jnp

    from flink_tpu.core.batch import RecordBatch
    from flink_tpu.core.functions import RuntimeContext, SumAggregator
    from flink_tpu.operators.window_agg import WindowAggOperator
    from flink_tpu.windowing.assigners import SlidingEventTimeWindows
    from flink_tpu.windowing.triggers import CountTrigger

    op = WindowAggOperator(SlidingEventTimeWindows.of(2000, 1000),
                           SumAggregator(jnp.float32), key_column="k",
                           value_column="v",
                           trigger=CountTrigger.of(2, purge=False))
    op.open(RuntimeContext())
    out = op.process_batch(RecordBatch(
        {"k": np.array([1, 1]), "v": np.array([1., 2.])},
        timestamps=np.array([100, 2100])))
    # pane 0 (win -1, 0) and pane 2 (win 1, 2); only window [1000,3000)?
    # windows: [0,2000) has 1 elem, [1000,3000) has 1, [-1000,1000) has 1,
    # [2000,4000) has 1 -> nothing reaches 2
    assert [r for b in out for r in b.to_rows()] == []
    out = op.process_batch(RecordBatch(
        {"k": np.array([1])}, timestamps=np.array([1100])).with_columns(
            {"k": np.array([1]), "v": np.array([10.])}))
    rows = [r for b in out for r in b.to_rows()]
    # t=1100 joins [0,2000) (now 1+10) and [1000,3000) (now 2+10)
    assert sorted((r["window_start"], r["result"]) for r in rows) == \
        [(0, 11.0), (1000, 12.0)]


def test_count_trigger_purging_sliding_non_invertible_rejected():
    """Min/max cannot retract: FIRE_AND_PURGE over pane-shared windows
    stays rejected for them (sum/count/avg work via value baselines)."""
    import jax.numpy as jnp

    from flink_tpu.core.functions import MinAggregator
    from flink_tpu.operators.window_agg import WindowAggOperator
    from flink_tpu.windowing.assigners import SlidingEventTimeWindows
    from flink_tpu.windowing.triggers import CountTrigger

    with pytest.raises(NotImplementedError, match="INVERTIBLE"):
        WindowAggOperator(SlidingEventTimeWindows.of(2000, 1000),
                          MinAggregator(jnp.float32), key_column="k",
                          value_column="v",
                          trigger=CountTrigger.of(2, purge=True))


def test_count_trigger_purging_sliding_value_baselines():
    """FIRE_AND_PURGE over a SLIDING assigner (the r4 documented gap,
    closed): each fired (key, window) logically purges — the next fire
    emits ONLY contents accumulated since — while the shared pane cells
    of overlapping neighbours stay intact."""
    import jax.numpy as jnp

    from flink_tpu.core.batch import RecordBatch
    from flink_tpu.core.functions import RuntimeContext, SumAggregator
    from flink_tpu.operators.window_agg import WindowAggOperator
    from flink_tpu.windowing.assigners import SlidingEventTimeWindows
    from flink_tpu.windowing.triggers import CountTrigger

    def mk():
        op = WindowAggOperator(SlidingEventTimeWindows.of(2000, 1000),
                               SumAggregator(jnp.float32), key_column="k",
                               value_column="v",
                               trigger=CountTrigger.of(2, purge=True))
        op.open(RuntimeContext())
        return op

    op = mk()
    out = op.process_batch(RecordBatch(
        {"k": np.array([7, 7]), "v": np.array([1., 2.])},
        timestamps=np.array([1100, 1200])))
    rows = [r for b in out for r in b.to_rows()]
    assert sorted((r["window_start"], r["result"]) for r in rows) == \
        [(0, 3.0), (1000, 3.0)]
    snap = op.snapshot_state()            # baselines survive checkpoints
    op2 = mk()
    op2.restore_state(snap)
    # two more elements in the same panes: the purged windows re-fire with
    # ONLY the new contents (10+20), not the running total 33
    out = op2.process_batch(RecordBatch(
        {"k": np.array([7, 7]), "v": np.array([10., 20.])},
        timestamps=np.array([1300, 1400])))
    rows = [r for b in out for r in b.to_rows()]
    assert sorted((r["window_start"], r["result"]) for r in rows) == \
        [(0, 30.0), (1000, 30.0)]


def test_count_trigger_nonpurging_tumbling_running_total():
    """purge=False over tumbling windows: fires every n elements with the
    running window total (the reference's raw CountTrigger semantics)."""
    import jax.numpy as jnp

    from flink_tpu.core.batch import RecordBatch
    from flink_tpu.core.functions import RuntimeContext, SumAggregator
    from flink_tpu.operators.window_agg import WindowAggOperator
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows
    from flink_tpu.windowing.triggers import CountTrigger

    op = WindowAggOperator(TumblingEventTimeWindows.of(10_000),
                           SumAggregator(jnp.float32), key_column="k",
                           value_column="v",
                           trigger=CountTrigger.of(2, purge=False))
    op.open(RuntimeContext())
    out = op.process_batch(RecordBatch(
        {"k": np.array([1, 1]), "v": np.array([1., 2.])},
        timestamps=np.array([10, 20])))
    rows = [r for b in out for r in b.to_rows()]
    assert [(r["k"], r["result"]) for r in rows] == [(1, 3.0)]
    out = op.process_batch(RecordBatch(
        {"k": np.array([1, 1]), "v": np.array([3., 4.])},
        timestamps=np.array([30, 40])))
    rows = [r for b in out for r in b.to_rows()]
    # running total, not purged: 1+2+3+4
    assert [(r["k"], r["result"]) for r in rows] == [(1, 10.0)]


def test_count_trigger_nonpurging_global_windows():
    import jax.numpy as jnp

    from flink_tpu.core.batch import RecordBatch
    from flink_tpu.core.functions import RuntimeContext, SumAggregator
    from flink_tpu.operators.window_agg import WindowAggOperator
    from flink_tpu.windowing.assigners import GlobalWindows
    from flink_tpu.windowing.triggers import CountTrigger

    op = WindowAggOperator(GlobalWindows.create(), SumAggregator(jnp.float32),
                           key_column="k", value_column="v",
                           trigger=CountTrigger.of(2, purge=False),
                           emit_window_bounds=False)
    op.open(RuntimeContext())
    out = op.process_batch(RecordBatch(
        {"k": np.array([1, 1]), "v": np.array([1., 2.])},
        timestamps=np.array([0, 0])))
    rows = [r for b in out for r in b.to_rows()]
    assert [(r["k"], r["result"]) for r in rows] == [(1, 3.0)]
    out = op.process_batch(RecordBatch(
        {"k": np.array([1])}, timestamps=np.array([0])).with_columns(
            {"k": np.array([1]), "v": np.array([5.])}))
    assert [r for b in out for r in b.to_rows()] == []  # only 1 new element
    out = op.process_batch(RecordBatch(
        {"k": np.array([1])}, timestamps=np.array([0])).with_columns(
            {"k": np.array([1]), "v": np.array([7.])}))
    rows = [r for b in out for r in b.to_rows()]
    assert [(r["k"], r["result"]) for r in rows] == [(1, 15.0)]


def test_count_trigger_sliding_snapshot_restore():
    """Baselines ride snapshots: a restored operator does not re-fire
    windows that already fired."""
    import jax.numpy as jnp

    from flink_tpu.core.batch import RecordBatch
    from flink_tpu.core.functions import RuntimeContext, SumAggregator
    from flink_tpu.operators.window_agg import WindowAggOperator
    from flink_tpu.windowing.assigners import SlidingEventTimeWindows
    from flink_tpu.windowing.triggers import CountTrigger

    def mk():
        op = WindowAggOperator(SlidingEventTimeWindows.of(2000, 1000),
                               SumAggregator(jnp.float32), key_column="k",
                               value_column="v",
                               trigger=CountTrigger.of(2, purge=False))
        op.open(RuntimeContext())
        return op

    op = mk()
    out = op.process_batch(RecordBatch(
        {"k": np.array([7, 7]), "v": np.array([1., 2.])},
        timestamps=np.array([1100, 1200])))
    assert len([r for b in out for r in b.to_rows()]) == 2
    snap = op.snapshot_state()

    op2 = mk()
    op2.restore_state(snap)
    out = op2.process_batch(RecordBatch(
        {"k": np.array([7])}, timestamps=np.array([1300])).with_columns(
            {"k": np.array([7]), "v": np.array([10.])}))
    assert [r for b in out for r in b.to_rows()] == []  # baseline restored
    out = op2.process_batch(RecordBatch(
        {"k": np.array([7])}, timestamps=np.array([1400])).with_columns(
            {"k": np.array([7]), "v": np.array([20.])}))
    rows = [r for b in out for r in b.to_rows()]
    assert sorted((r["window_start"], r["result"]) for r in rows) == \
        [(0, 33.0), (1000, 33.0)]


class TestSlidingLateness:
    """WindowOperatorTest-style scenarios: sliding assigners crossed with
    allowed lateness, late re-fires, and mid-stream snapshot/restore."""

    def _op(self, lateness=0):
        import jax.numpy as jnp

        from flink_tpu.core.functions import RuntimeContext

        op = WindowAggOperator(SlidingEventTimeWindows.of(2000, 1000),
                               SumAggregator(jnp.float32), key_column="k",
                               value_column="v",
                               allowed_lateness_ms=lateness)
        op.open(RuntimeContext())
        return op

    @staticmethod
    def _feed(op, keys, vals, ts):
        from flink_tpu.core.batch import RecordBatch

        return op.process_batch(RecordBatch(
            {"k": np.asarray(keys, np.int64),
             "v": np.asarray(vals, np.float64)},
            timestamps=np.asarray(ts, np.int64)))

    def test_late_record_refires_all_covering_windows(self):
        from flink_tpu.core.batch import Watermark

        op = self._op(lateness=5000)
        self._feed(op, [1, 1], [1., 2.], [500, 1500])
        fired = op.process_watermark(Watermark(3000))
        pre = sorted((r["window_start"], r["result"])
                     for b in fired for r in b.to_rows())
        # windows [-1000,1000)=1, [0,2000)=3, [1000,3000)=2 all fired
        assert pre == [(-1000, 1.0), (0, 3.0), (1000, 2.0)]
        # a late record at 700 (within lateness) re-fires BOTH its windows
        out = self._feed(op, [1], [10.], [700])
        refired = sorted((r["window_start"], r["result"])
                         for b in out for r in b.to_rows())
        assert refired == [(-1000, 11.0), (0, 13.0)]

    def test_beyond_lateness_sliding_drops_all_windows(self):
        from flink_tpu.core.batch import Watermark

        op = self._op(lateness=1000)
        self._feed(op, [1], [1.], [500])
        op.process_watermark(Watermark(10_000))   # far past retention
        out = self._feed(op, [1], [9.], [600])
        assert [r for b in out for r in b.to_rows()] == []
        assert op.late_dropped == 1

    def test_snapshot_restore_mid_sliding_with_lateness(self):
        from flink_tpu.core.batch import Watermark

        op = self._op(lateness=5000)
        self._feed(op, [1, 2], [1., 2.], [500, 1500])
        op.process_watermark(Watermark(1200))     # fires window [-1000,1000)
        snap = op.snapshot_state()

        op2 = self._op(lateness=5000)
        op2.restore_state(snap)
        # restored operator continues: remaining windows fire once, with
        # the pre-snapshot contributions intact
        self._feed(op2, [1], [4.], [1600])
        fired = op2.process_watermark(Watermark(4000))
        got = sorted((r["k"], r["window_start"], r["result"])
                     for b in fired for r in b.to_rows())
        # [0,2000): restored 1.0 + post-restore 4.0@1600; [1000,3000):
        # the 4.0 alone; key 2's restored 2.0@1500 covers both windows —
        # and the already-fired [-1000,1000) must NOT re-fire (exact set)
        assert got == [(1, 0, 5.0), (1, 1000, 4.0),
                       (2, 0, 2.0), (2, 1000, 2.0)]

    def test_watermark_jump_fires_windows_in_order(self):
        from flink_tpu.core.batch import Watermark

        op = self._op()
        self._feed(op, [1, 1, 1], [1., 2., 4.], [500, 2500, 4500])
        fired = op.process_watermark(Watermark(100_000))  # one giant jump
        starts = [r["window_start"]
                  for b in fired for r in b.to_rows()]
        assert starts == sorted(starts)    # ascending window order
        got = {(r["window_start"], r["result"])
               for b in fired for r in b.to_rows()}
        # 2500 and 4500 never share a window (size 2000): the COMPLETE
        # fire set — missing or spurious windows both fail
        assert got == {(-1000, 1.0), (0, 1.0), (1000, 2.0),
                       (2000, 2.0), (3000, 4.0), (4000, 4.0)}


def test_out_of_order_first_batches_extend_ring_downward():
    """Regression (parallel-source race): when the FIRST batch to arrive is
    high-timestamped (another source racing ahead), later low-timestamped
    batches must extend retention downward — lateness is judged by the
    watermark (isElementLate), never by arrival order."""
    from flink_tpu.core.batch import RecordBatch
    op = WindowAggOperator(TumblingEventTimeWindows.of(1000),
                           SumAggregator(np.float32), key_column="key",
                           value_column="v")
    h = KeyedOneInputOperatorHarness(op)
    # batch from the "fast" source: panes ~ window 3
    h.process_batch(RecordBatch({"key": np.array([1, 2]),
                                 "v": np.array([10.0, 20.0], np.float32)},
                                timestamps=np.array([3500, 3600])))
    # batch from the "slow" source: window 0 — must NOT be dropped
    h.process_batch(RecordBatch({"key": np.array([1]),
                                 "v": np.array([5.0], np.float32)},
                                timestamps=np.array([100])))
    assert op.late_dropped == 0
    h.process_watermark(999)
    out0 = h.extract_output_rows()
    assert [(o["key"], o["result"]) for o in out0] == [(1, 5.0)]
    h.clear_output()
    h.process_watermark(3999)
    out1 = {(o["key"]): o["result"] for o in h.extract_output_rows()}
    assert out1 == {1: 10.0, 2: 20.0}
    # AFTER expiry the gate is real: a record behind the cleared panes drops
    h.process_batch(RecordBatch({"key": np.array([1]),
                                 "v": np.array([1.0], np.float32)},
                                timestamps=np.array([50])))
    assert op.late_dropped == 1


def test_watermark_gate_drops_below_initial_pane_base():
    """The late gate is the WATERMARK formula even for panes below the
    initial pane_base: a record whose window's cleanup time passed the
    watermark drops (no spurious refire of a long-closed window)."""
    from flink_tpu.core.batch import RecordBatch

    op = WindowAggOperator(TumblingEventTimeWindows.of(1000),
                           SumAggregator(np.float32), key_column="key",
                           value_column="v")
    h = KeyedOneInputOperatorHarness(op)
    h.process_batch(RecordBatch({"key": np.array([1]),
                                 "v": np.array([1.0], np.float32)},
                                timestamps=np.array([5500])))
    h.process_watermark(5000)
    h.clear_output()
    # window 0 (cleanup 999) is far behind the watermark: must drop even
    # though pane 0 was never stored/expired here
    h.process_batch(RecordBatch({"key": np.array([1]),
                                 "v": np.array([9.0], np.float32)},
                                timestamps=np.array([100])))
    assert op.late_dropped == 1
    assert h.extract_output_rows() == []


# ---------------------------------------------------------------------------
# Host emit tier (VERDICT r2 #1): fires served from the write-through host
# value mirror with zero device->host traffic; device state stays equal.
# ---------------------------------------------------------------------------

def _run_workload(op, n_batches=6, seed=11, window_ms=100, n_keys=40):
    """Randomized multi-window workload incl. a late-but-within-lateness
    record; returns emitted (key, result, ts) tuples."""
    from flink_tpu.core.batch import RecordBatch, Watermark

    rng = np.random.default_rng(seed)
    out = []
    for i in range(n_batches):
        b = 64
        keys = rng.integers(0, n_keys, b).astype(np.int64)
        vals = rng.integers(0, 100, b).astype(np.float32)
        ts = i * window_ms + np.sort(rng.integers(0, window_ms, b))
        out.extend(op.process_batch(
            RecordBatch({"key": keys, "v": vals}, timestamps=ts)))
        out.extend(op.process_watermark(Watermark((i + 1) * window_ms - 1)))
    out.extend(op.end_input())
    rows = []
    for b in out:
        if hasattr(b, "columns"):
            rows.extend(b.to_rows())
    return sorted((int(r["key"]), round(float(r["result"]), 3),
                   int(r["window_start"])) for r in rows)


def _run_tuple_workload(op, n_batches=6, seed=11, window_ms=100, n_keys=40):
    """Like _run_workload but emits every non-meta output column (multi-field
    aggregates) as the comparison tuple."""
    from flink_tpu.core.batch import RecordBatch, Watermark

    rng = np.random.default_rng(seed)
    out = []
    for i in range(n_batches):
        b = 64
        keys = rng.integers(0, n_keys, b).astype(np.int64)
        vals = rng.integers(0, 100, b).astype(np.float32)
        ts = i * window_ms + np.sort(rng.integers(0, window_ms, b))
        out.extend(op.process_batch(
            RecordBatch({"key": keys, "v": vals}, timestamps=ts)))
        out.extend(op.process_watermark(Watermark((i + 1) * window_ms - 1)))
    out.extend(op.end_input())
    rows = []
    for b in out:
        if hasattr(b, "columns"):
            rows.extend(b.to_rows())
    meta = ("key", "window_start", "window_end")
    return sorted((int(r["key"]), int(r["window_start"]),
                   *(float(r[c]) for c in sorted(r) if c not in meta))
                  for r in rows)


class TestHostEmitTier:
    def _pair(self, assigner=None, agg=None, **kw):
        mk = lambda tier: make_op(  # noqa: E731
            assigner=assigner, agg=agg, emit_tier=tier, **kw)
        return mk("device"), mk("host")

    def test_tumbling_equivalence_and_mirror_consistency(self):
        from flink_tpu.core.functions import RuntimeContext

        dev, host = self._pair(allowed_lateness_ms=100)
        dev.open(RuntimeContext())
        host.open(RuntimeContext())
        assert _run_workload(dev) == _run_workload(host)
        assert host.verify_mirror()

    def test_sliding_pane_combine_equivalence(self):
        from flink_tpu.core.functions import RuntimeContext

        dev, host = self._pair(SlidingEventTimeWindows.of(300, 100))
        dev.open(RuntimeContext())
        host.open(RuntimeContext())
        assert _run_workload(dev, window_ms=100) == \
            _run_workload(host, window_ms=100)
        assert host.verify_mirror()

    def test_avg_and_tuple_aggregates_host_tier(self):
        from flink_tpu.core.functions import RuntimeContext

        tuple_agg = TupleAggregator({"s": ("v", SumAggregator(np.float32)),
                                     "m": ("v", MaxAggregator(np.float32)),
                                     "c": ("v", CountAggregator())})
        for agg, sel in ((AvgAggregator(np.float32), None),
                         (tuple_agg, lambda c: c)):
            mk = lambda tier: WindowAggOperator(  # noqa: E731
                TumblingEventTimeWindows.of(100), agg, key_column="key",
                value_column=None if sel else "v", value_selector=sel,
                emit_tier=tier)
            dev, host = mk("device"), mk("host")
            dev.open(RuntimeContext())
            host.open(RuntimeContext())
            d = _run_tuple_workload(dev)
            hh = _run_tuple_workload(host)
            assert len(d) == len(hh) and len(d) > 0
            # avg divides: compare with tolerance (mirror is f64)
            for drow, hrow in zip(d, hh):
                assert drow[:2] == hrow[:2]
                for dvv, hv in zip(drow[2:], hrow[2:]):
                    assert dvv == pytest.approx(hv, rel=1e-5)

    def test_host_tier_requires_capability(self):
        with pytest.raises(ValueError, match="host"):
            make_op(agg=LambdaReduce(lambda a, b: np.maximum(a, b),
                                     np.float32(0)),
                    emit_tier="host")
        with pytest.raises(ValueError, match="host"):
            make_op(assigner=GlobalWindows(), trigger=CountTrigger.of(2),
                    emit_tier="host")

    def test_mirror_snapshot_restore_roundtrip(self):
        """snapshot_source='mirror' serializes the host mirror; a DEVICE-tier
        operator restores it identically (format parity)."""
        from flink_tpu.core.functions import RuntimeContext

        host = make_op(emit_tier="host", snapshot_source="mirror",
                       allowed_lateness_ms=100)
        host.open(RuntimeContext())
        full = make_op(emit_tier="device")
        full.open(RuntimeContext())
        ref = _run_workload(full, n_batches=6)

        # first half on the host-tier op, snapshot mid-window, restore into
        # BOTH tiers, finish — all three transcripts must agree
        from flink_tpu.core.batch import RecordBatch, Watermark
        rng = np.random.default_rng(11)
        pre = []
        for i in range(3):
            keys = rng.integers(0, 40, 64).astype(np.int64)
            vals = rng.integers(0, 100, 64).astype(np.float32)
            ts = i * 100 + np.sort(rng.integers(0, 100, 64))
            pre.extend(host.process_batch(
                RecordBatch({"key": keys, "v": vals}, timestamps=ts)))
            pre.extend(host.process_watermark(Watermark((i + 1) * 100 - 1)))
        snap = host.snapshot_state()

        for tier in ("host", "device"):
            op2 = make_op(emit_tier=tier, allowed_lateness_ms=0)
            op2.open(RuntimeContext())
            op2.restore_state(snap)
            out = list(pre)
            for i in range(3, 6):
                keys = rng.integers(0, 40, 64).astype(np.int64)
                vals = rng.integers(0, 100, 64).astype(np.float32)
                ts = i * 100 + np.sort(rng.integers(0, 100, 64))
                out.extend(op2.process_batch(
                    RecordBatch({"key": keys, "v": vals}, timestamps=ts)))
                out.extend(op2.process_watermark(Watermark((i + 1) * 100 - 1)))
            out.extend(op2.end_input())
            rows = []
            for b in out:
                if hasattr(b, "columns"):
                    rows.extend(b.to_rows())
            got = sorted((int(r["key"]), round(float(r["result"]), 3),
                          int(r["window_start"])) for r in rows)
            assert got == ref, tier
            rng = np.random.default_rng(11)
            for _ in range(3):  # rewind rng to post-half state
                rng.integers(0, 40, 64), rng.integers(0, 100, 64)
                rng.integers(0, 100, 64)

    def test_mirror_panes_grow_with_key_capacity(self):
        """A retained pane untouched after key-capacity growth must still
        serve fires, snapshots and verify_mirror at the new key count."""
        from flink_tpu.core.batch import RecordBatch, Watermark
        from flink_tpu.core.functions import RuntimeContext

        op = make_op(emit_tier="host", snapshot_source="mirror",
                     allowed_lateness_ms=1000, initial_key_capacity=1024)
        op.open(RuntimeContext())
        op.process_batch(RecordBatch(
            {"key": np.arange(10), "v": np.ones(10, np.float32)},
            timestamps=np.full(10, 50)))
        op.process_watermark(Watermark(99))   # fires pane 0, retained (lateness)
        # 2000 NEW keys in pane 1: capacity grows 1024 -> 2048+
        op.process_batch(RecordBatch(
            {"key": np.arange(100, 2100), "v": np.ones(2000, np.float32)},
            timestamps=np.full(2000, 150)))
        snap = op.snapshot_state()            # must not broadcast-crash
        assert snap["counts"].shape[0] == 2010
        assert op.verify_mirror()
        out = op.process_watermark(Watermark(199))
        assert sum(len(b) for b in out if hasattr(b, "columns")) == 2000

    def test_phase_accounting_populated(self):
        from flink_tpu.core.functions import RuntimeContext

        op = make_op(emit_tier="host")
        op.open(RuntimeContext())
        _run_workload(op, n_batches=3)
        # fused native path reports "probe_mirror"; numpy fallback reports
        # separate "probe" + "mirror" phases
        host_ns = (op.phase_ns.get("probe_mirror", 0)
                   or min(op.phase_ns.get("probe", 0),
                          op.phase_ns.get("mirror", 0)))
        assert host_ns > 0
        assert op.phase_ns.get("device_dispatch", 0) > 0
        assert op.phase_ns.get("fire", 0) > 0
        assert op.phase_bytes.get("h2d", 0) > 0


def test_async_fire_prepare_snapshot_pre_barrier():
    """async_fire is checkpoint-compatible: the pre-barrier drain surfaces
    pending emissions, after which snapshot_state succeeds (the reference
    drains external bundles the same way)."""
    from flink_tpu.core.batch import RecordBatch, Watermark

    op = make_op(async_fire=True, emit_tier="device")
    from flink_tpu.core.functions import RuntimeContext
    op.open(RuntimeContext())
    op.process_batch(RecordBatch(
        {"key": np.arange(8), "v": np.ones(8, np.float32)},
        timestamps=np.full(8, 50)))
    out = op.process_watermark(Watermark(99))    # starts an async fire
    drained = op.prepare_snapshot_pre_barrier()
    total = sum(len(b) for b in list(out) + drained if hasattr(b, "columns"))
    assert total == 8                            # all fires surfaced
    snap = op.snapshot_state()                   # no longer refuses
    assert snap["watermark"] == 99
