"""OVER aggregations (StreamExecOverAggregate analog): unbounded running
aggregates, ROWS / RANGE bounded frames, peer semantics, ROW_NUMBER.

Reference: flink-table-planner-blink
``plan/nodes/exec/stream/StreamExecOverAggregate.java`` with runtime
``RowTime{Range,Rows}{Unbounded,Bounded}PrecedingFunction``.
"""

import numpy as np
import pytest

from flink_tpu.sql.planner import PlanError
from flink_tpu.sql.table_env import TableEnvironment


def make_env():
    te = TableEnvironment()
    te.register_collection("t", columns={
        "k": np.array([1, 1, 1, 2, 2, 1], np.int64),
        "ts": np.array([1000, 2000, 3000, 1000, 4000, 5000], np.int64),
        "v": np.array([10., 20., 30., 5., 7., 40.])},
        rowtime="ts")
    return te


def by_key(rows, k):
    return sorted((r for r in rows if r["k"] == k), key=lambda r: r["ts"])


def test_over_unbounded_running_sum():
    rows = make_env().execute_sql(
        "SELECT k, ts, v, SUM(v) OVER (PARTITION BY k ORDER BY ts) AS rs "
        "FROM t").collect()
    assert [r["rs"] for r in by_key(rows, 1)] == [10., 30., 60., 100.]
    assert [r["rs"] for r in by_key(rows, 2)] == [5., 12.]


def test_over_multiple_aggs_share_window():
    rows = make_env().execute_sql(
        "SELECT k, ts, COUNT(*) OVER (PARTITION BY k ORDER BY ts) AS c, "
        "AVG(v) OVER (PARTITION BY k ORDER BY ts) AS a, "
        "MAX(v) OVER (PARTITION BY k ORDER BY ts) AS mx, "
        "MIN(v) OVER (PARTITION BY k ORDER BY ts) AS mn FROM t").collect()
    k1 = by_key(rows, 1)
    assert [r["c"] for r in k1] == [1, 2, 3, 4]
    assert [r["a"] for r in k1] == [10., 15., 20., 25.]
    assert [r["mx"] for r in k1] == [10., 20., 30., 40.]
    assert [r["mn"] for r in k1] == [10., 10., 10., 10.]


def test_over_rows_frame():
    rows = make_env().execute_sql(
        "SELECT k, ts, SUM(v) OVER (PARTITION BY k ORDER BY ts "
        "ROWS BETWEEN 1 PRECEDING AND CURRENT ROW) AS s FROM t").collect()
    assert [r["s"] for r in by_key(rows, 1)] == [10., 30., 50., 70.]
    assert [r["s"] for r in by_key(rows, 2)] == [5., 12.]


def test_over_rows_frame_min_count():
    rows = make_env().execute_sql(
        "SELECT k, ts, MIN(v) OVER (PARTITION BY k ORDER BY ts "
        "ROWS BETWEEN 2 PRECEDING AND CURRENT ROW) AS mn, "
        "COUNT(*) OVER (PARTITION BY k ORDER BY ts "
        "ROWS BETWEEN 2 PRECEDING AND CURRENT ROW) AS c FROM t").collect()
    k1 = by_key(rows, 1)
    assert [r["mn"] for r in k1] == [10., 10., 10., 20.]
    assert [r["c"] for r in k1] == [1, 2, 3, 3]


def test_over_range_frame():
    # 2-second range: at ts=3000 the frame is [1000,3000]; at ts=5000 it is
    # [3000,5000] (only ts=3000 and ts=5000 rows for key 1)
    rows = make_env().execute_sql(
        "SELECT k, ts, SUM(v) OVER (PARTITION BY k ORDER BY ts RANGE BETWEEN "
        "INTERVAL '2' SECOND PRECEDING AND CURRENT ROW) AS s FROM t").collect()
    assert [r["s"] for r in by_key(rows, 1)] == [10., 30., 60., 70.]
    assert [r["s"] for r in by_key(rows, 2)] == [5., 7.]


def test_over_range_unbounded_peers_share():
    te = TableEnvironment()
    te.register_collection("p", columns={
        "k": np.array([1, 1, 1], np.int64),
        "ts": np.array([1000, 1000, 2000], np.int64),
        "v": np.array([3., 4., 5.])}, rowtime="ts")
    rows = te.execute_sql(
        "SELECT k, ts, SUM(v) OVER (PARTITION BY k ORDER BY ts) AS s "
        "FROM p").collect()
    # default frame = RANGE UNBOUNDED: the two ts=1000 peers both see 7
    assert sorted(r["s"] for r in rows if r["ts"] == 1000) == [7., 7.]
    assert [r["s"] for r in rows if r["ts"] == 2000] == [12.]


def test_over_rows_unbounded_no_peer_sharing():
    te = TableEnvironment()
    te.register_collection("p", columns={
        "k": np.array([1, 1, 1], np.int64),
        "ts": np.array([1000, 1000, 2000], np.int64),
        "v": np.array([3., 4., 5.])}, rowtime="ts")
    rows = te.execute_sql(
        "SELECT k, ts, SUM(v) OVER (PARTITION BY k ORDER BY ts "
        "ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS s "
        "FROM p").collect()
    assert sorted(r["s"] for r in rows if r["ts"] == 1000) == [3., 7.]
    assert [r["s"] for r in rows if r["ts"] == 2000] == [12.]


def test_over_row_number_plain():
    rows = make_env().execute_sql(
        "SELECT k, ts, ROW_NUMBER() OVER (PARTITION BY k ORDER BY ts) AS rn "
        "FROM t").collect()
    assert [r["rn"] for r in by_key(rows, 1)] == [1, 2, 3, 4]
    assert [r["rn"] for r in by_key(rows, 2)] == [1, 2]


def test_over_global_partition():
    rows = make_env().execute_sql(
        "SELECT ts, COUNT(*) OVER (ORDER BY ts) AS c FROM t").collect()
    assert max(r["c"] for r in rows) == 6


def test_over_in_expression_and_where():
    rows = make_env().execute_sql(
        "SELECT k, ts, SUM(v) OVER (PARTITION BY k ORDER BY ts) * 2 AS d "
        "FROM t WHERE v > 5").collect()
    assert [r["d"] for r in by_key(rows, 1)] == [20., 60., 120., 200.]
    assert [r["d"] for r in by_key(rows, 2)] == [14.]  # v=5 filtered out


def test_over_errors():
    te = make_env()
    with pytest.raises(PlanError, match="ORDER BY"):
        te.execute_sql("SELECT SUM(v) OVER (PARTITION BY k) FROM t").collect()
    with pytest.raises(PlanError, match="GROUP BY"):
        te.execute_sql(
            "SELECT SUM(v) OVER (PARTITION BY k ORDER BY ts), SUM(v) "
            "FROM t GROUP BY k").collect()
    with pytest.raises(PlanError, match="rowtime"):
        te.execute_sql(
            "SELECT SUM(v) OVER (PARTITION BY k ORDER BY v) FROM t").collect()


def test_over_snapshot_restore_roundtrip():
    from flink_tpu.operators.sql_ops import (OverAggregateOperator,
                                             OverAggSpec)
    from flink_tpu.core.batch import RecordBatch, Watermark

    def mk(keys, ts, vals):
        return RecordBatch({"k": np.asarray(keys, np.int64),
                            "v": np.asarray(vals, np.float64)},
                           timestamps=np.asarray(ts, np.int64))

    specs = [OverAggSpec("s", "SUM", "v"),
             OverAggSpec("r2", "SUM", "v", rows=1)]
    op = OverAggregateOperator(specs, "k")
    op.process_batch(mk([1, 1], [1000, 2000], [1., 2.]))
    out1 = op.process_watermark(Watermark(2000))
    snap = op.snapshot_state()

    op2 = OverAggregateOperator(specs, "k")
    op2.restore_state(snap)
    op2.process_batch(mk([1], [3000], [4.]))
    out2 = op2.process_watermark(Watermark(3000))
    got = np.concatenate([np.asarray(b.columns["s"]) for b in out1 + out2])
    assert got.tolist() == [1., 3., 7.]
    got2 = np.concatenate([np.asarray(b.columns["r2"]) for b in out1 + out2])
    assert got2.tolist() == [1., 3., 6.]


def test_over_late_rows_dropped():
    from flink_tpu.operators.sql_ops import (OverAggregateOperator,
                                             OverAggSpec)
    from flink_tpu.core.batch import RecordBatch, Watermark

    op = OverAggregateOperator([OverAggSpec("s", "SUM", "v")], None)
    b = RecordBatch({"v": np.array([1.])},
                    timestamps=np.array([1000], np.int64))
    op.process_batch(b)
    op.process_watermark(Watermark(2000))
    late = RecordBatch({"v": np.array([9.])},
                       timestamps=np.array([1500], np.int64))
    assert op.process_batch(late) == []
    assert op._dropped_late == 1


def test_over_distinct_unbounded():
    """agg(DISTINCT x) OVER an unbounded frame: only each value's first
    occurrence per partition contributes (closes the PARITY r2 gap)."""
    te = TableEnvironment()
    te.register_collection("d", columns={
        "k": np.array([1, 1, 1, 1], np.int64),
        "ts": np.array([1000, 2000, 3000, 4000], np.int64),
        "v": np.array([10., 10., 20., 10.])}, rowtime="ts")
    rows = te.execute_sql(
        "SELECT ts, SUM(DISTINCT v) OVER (PARTITION BY k ORDER BY ts) AS s, "
        "COUNT(DISTINCT v) OVER (PARTITION BY k ORDER BY ts) AS c, "
        "SUM(v) OVER (PARTITION BY k ORDER BY ts) AS plain "
        "FROM d").collect()
    by_ts = {r["ts"]: r for r in rows}
    assert [by_ts[t]["s"] for t in (1000, 2000, 3000, 4000)] == \
        [10., 10., 30., 30.]
    assert [by_ts[t]["c"] for t in (1000, 2000, 3000, 4000)] == [1, 1, 2, 2]
    assert [by_ts[t]["plain"] for t in (1000, 2000, 3000, 4000)] == \
        [10., 20., 40., 50.]


def test_over_distinct_bounded_rows_frame():
    """SUM/COUNT(DISTINCT) OVER ROWS n PRECEDING (r3 rejection, now
    implemented): each frame dedupes ITS OWN rows — a value leaving the
    frame re-counts while another copy remains inside."""
    te = TableEnvironment()
    te.register_collection("dbr", columns={
        "k": np.zeros(6, np.int64),
        "ts": np.array([1, 2, 3, 4, 5, 6], np.int64) * 1000,
        "v": np.array([5.0, 5.0, 3.0, 5.0, 3.0, 7.0])}, rowtime="ts")
    rows = te.execute_sql(
        "SELECT ts, SUM(DISTINCT v) OVER (PARTITION BY k ORDER BY ts "
        "ROWS BETWEEN 2 PRECEDING AND CURRENT ROW) AS s, "
        "COUNT(DISTINCT v) OVER (PARTITION BY k ORDER BY ts "
        "ROWS BETWEEN 2 PRECEDING AND CURRENT ROW) AS c FROM dbr").collect()
    rows.sort(key=lambda r: r["ts"])
    # frames: [5],[5,5],[5,5,3],[5,3,5],[3,5,3],[5,3,7]
    assert [r["s"] for r in rows] == [5.0, 5.0, 8.0, 8.0, 8.0, 15.0]
    assert [r["c"] for r in rows] == [1, 1, 2, 2, 2, 3]


def test_over_distinct_bounded_range_frame():
    te = TableEnvironment()
    te.register_collection("dgr", columns={
        "k": np.zeros(5, np.int64),
        "ts": np.array([0, 1000, 2000, 3000, 10_000], np.int64),
        "v": np.array([2.0, 2.0, 4.0, 2.0, 6.0])}, rowtime="ts")
    rows = te.execute_sql(
        "SELECT ts, SUM(DISTINCT v) OVER (PARTITION BY k ORDER BY ts "
        "RANGE BETWEEN INTERVAL '2' SECOND PRECEDING AND CURRENT ROW) AS s "
        "FROM dgr").collect()
    rows.sort(key=lambda r: r["ts"])
    # frames by ts-2000: [2],[2,2],[2,2,4],[2,4,2],[6]
    assert [r["s"] for r in rows] == [2.0, 2.0, 6.0, 6.0, 6.0]


def test_frame_words_stay_usable_as_columns():
    # ROWS/RANGE/PRECEDING/... are contextual, not reserved: a table with
    # such column names keeps working
    te = TableEnvironment()
    te.register_collection("t", columns={
        "row": np.array([1, 2], np.int64),
        "range": np.array([10., 20.]),
        "current": np.array([5., 6.])})
    rows = te.execute_sql(
        "SELECT row, range, current FROM t ORDER BY row").collect()
    assert [(r["row"], r["range"], r["current"]) for r in rows] == \
        [(1, 10.0, 5.0), (2, 20.0, 6.0)]


def test_branch_merge_snapshot_restore():
    from flink_tpu.core.batch import RecordBatch
    from flink_tpu.operators.sql_ops import BranchMergeOperator

    def mk_batch(keys, vals, extra=None):
        karr = np.empty(len(keys), object)
        karr[:] = [tuple([k]) for k in keys]
        cols = {"__merge": karr, "k": np.asarray(keys, np.int64)}
        if extra is not None:
            cols["d"] = np.asarray(extra)
        else:
            cols["s"] = np.asarray(vals)
        return RecordBatch(cols)

    op = BranchMergeOperator("__merge", ["d"])
    # left fires keys 1,2; right fires key 1 only -> key 2 stays pending
    assert op.process_batch2(mk_batch([1, 2], [10., 20.]), 0) == []
    out = op.process_batch2(mk_batch([1], None, extra=[7.]), 1)
    merged = [r for b in out for r in b.to_rows()]
    assert len(merged) == 1 and merged[0]["s"] == 10.0 and merged[0]["d"] == 7.0

    snap = op.snapshot_state()
    op2 = BranchMergeOperator("__merge", ["d"])
    op2.restore_state(snap)
    out = op2.process_batch2(mk_batch([2], None, extra=[9.]), 1)
    merged = [r for b in out for r in b.to_rows()]
    assert len(merged) == 1 and merged[0]["s"] == 20.0 and merged[0]["d"] == 9.0


def test_over_in_subquery_bare():
    # a bare OVER aggregate inside a derived table (not the Top-N shape)
    rows = make_env().execute_sql(
        "SELECT * FROM (SELECT k, ts, SUM(v) OVER (PARTITION BY k "
        "ORDER BY ts) AS s FROM t) WHERE s > 20").collect()
    assert sorted((r["k"], r["s"]) for r in rows) == \
        [(1, 30.0), (1, 60.0), (1, 100.0)]


def test_over_over_projection_subquery():
    # OVER planned on TOP of a subquery: the rowtime must propagate through
    # the inner projection for the outer ORDER BY to be a time attribute
    rows = make_env().execute_sql(
        "SELECT k, ts, SUM(v) OVER (PARTITION BY k ORDER BY ts) + 0 AS s "
        "FROM (SELECT k, ts, v FROM t)").collect()
    assert [r["s"] for r in by_key(rows, 1)] == [10., 30., 60., 100.]


def test_over_subquery_dropped_rowtime_rejected():
    # the subquery drops ts -> outer OVER has no time attribute
    te = make_env()
    with pytest.raises(PlanError, match="time attribute"):
        te.execute_sql(
            "SELECT SUM(v) OVER (PARTITION BY k ORDER BY ts) "
            "FROM (SELECT k, v FROM t)").collect()


def test_over_multiple_partitionings_one_select():
    """Distinct (PARTITION BY, ORDER BY) groups in one SELECT: the
    over_partition_split rule nests the SELECT so each level carries one
    group (closes the PARITY r2 'multiple OVER partitionings' gap)."""
    rows = make_env().execute_sql(
        "SELECT k, ts, v, "
        "SUM(v) OVER (PARTITION BY k ORDER BY ts) AS per_k, "
        "SUM(v) OVER (ORDER BY ts) AS global_rs "
        "FROM t").collect()
    assert [r["per_k"] for r in by_key(rows, 1)] == [10., 30., 60., 100.]
    assert [r["per_k"] for r in by_key(rows, 2)] == [5., 12.]
    # global running sum over ALL rows in ts order (ties share the peer
    # frame: RANGE semantics at equal timestamps)
    by_ts = sorted(rows, key=lambda r: (r["ts"], r["k"]))
    got = {(r["k"], r["ts"]): r["global_rs"] for r in by_ts}
    assert got[(1, 1000)] == got[(2, 1000)] == 15.0    # peers at ts=1000
    assert got[(1, 2000)] == 35.0 and got[(1, 3000)] == 65.0
    assert got[(2, 4000)] == 72.0 and got[(1, 5000)] == 112.0


def test_explain_shows_rewrites():
    te = make_env()
    txt = te.explain_sql(
        "SELECT k, SUM(v) OVER (PARTITION BY k ORDER BY ts) AS a, "
        "SUM(v) OVER (ORDER BY ts) AS b FROM t")
    assert "Logical Rewrites Applied" in txt
    assert "over_partition_split" in txt


def test_filter_not_pushed_below_over_subquery():
    """Regression: an outer WHERE must NOT push below a subquery computing
    OVER aggregates — it would change the window input rows."""
    te = TableEnvironment()
    te.register_collection("t", columns={
        "k": np.array([1, 2, 1, 2], np.int64),
        "ts": np.array([1000, 2000, 3000, 4000], np.int64),
        "v": np.array([10., 20., 30., 40.])}, rowtime="ts")
    rows = te.execute_sql(
        "SELECT k, ts, rs FROM (SELECT k, ts, SUM(v) OVER (ORDER BY ts) "
        "AS rs FROM t) WHERE k = 1").collect()
    got = {r["ts"]: r["rs"] for r in rows}
    assert got == {1000: 10.0, 3000: 60.0}   # running sum saw k=2 rows


def test_over_multiple_partitionings_with_alias():
    rows = make_env().execute_sql(
        "SELECT a.k, a.ts, SUM(a.v) OVER (PARTITION BY a.k ORDER BY a.ts) "
        "AS x, SUM(a.v) OVER (ORDER BY a.ts) AS y FROM t a").collect()
    assert [r["x"] for r in by_key(rows, 1)] == [10., 30., 60., 100.]
    assert max(r["y"] for r in rows) == 112.0


def test_over_split_preserves_having_rejection():
    te = make_env()
    with pytest.raises(PlanError, match="HAVING"):
        te.execute_sql(
            "SELECT SUM(v) OVER (PARTITION BY k ORDER BY ts) AS a, "
            "SUM(v) OVER (ORDER BY ts) AS b FROM t HAVING 1 = 0").collect()
