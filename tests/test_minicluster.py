"""MiniCluster: parallel subtasks over channels, checkpoint coordination,
aligned + unaligned barriers, failure restart from checkpoint."""

import threading
import time

import numpy as np
import pytest

from flink_tpu.cluster.minicluster import MiniCluster
from flink_tpu.cluster.task import Subtask, TaskListener, TaskStates
from flink_tpu.cluster.channels import LocalChannel
from flink_tpu.core.batch import (CheckpointBarrier, EndOfInput, RecordBatch,
                                  Watermark)
from flink_tpu.core.functions import RuntimeContext
from flink_tpu.datastream.api import StreamExecutionEnvironment
from flink_tpu.runtime.checkpoint.storage import InMemoryCheckpointStorage
from flink_tpu.windowing.assigners import TumblingEventTimeWindows

pytestmark = pytest.mark.slow


def _expected_sums(keys, vals):
    out = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        out[int(k)] = out.get(int(k), 0.0) + v
    return out


def test_parallel_keyed_sum_matches_serial():
    rng = np.random.default_rng(5)
    n = 5000
    keys = rng.integers(0, 37, n)
    vals = rng.random(n)

    env = StreamExecutionEnvironment()
    env.set_parallelism(3)
    sink = (env.from_collection(columns={"k": keys, "v": vals}, batch_size=256)
            .key_by("k").sum("v").collect())
    res = env.execute_cluster()
    assert res.state == TaskStates.FINISHED
    final = {}
    for r in sink.rows():
        final[int(r["k"])] = r["v"]
    expect = _expected_sums(keys, vals)
    assert final.keys() == expect.keys()
    for k in expect:
        assert abs(final[k] - expect[k]) < 1e-3


def test_parallel_window_aggregate():
    # The round-1 "spurious failure (delta ~153)" here was root-caused in
    # round 2: pane_base initialized from the FIRST batch to arrive, so a
    # parallel source racing ahead made lower panes drop as late.  Fixed by
    # gating drops on expired panes only (window_agg._expired_through) with
    # a deterministic regression test in test_window_agg.py.
    rng = np.random.default_rng(6)
    n = 4000
    keys = rng.integers(0, 21, n)
    vals = rng.random(n).astype(np.float32)
    ts = np.sort(rng.integers(0, 4000, n))

    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    sink = (env.from_collection(columns={"k": keys, "v": vals, "t": ts},
                                batch_size=512)
            .assign_timestamps_and_watermarks(0, timestamp_column="t")
            .key_by("k")
            .window(TumblingEventTimeWindows.of(1000))
            .sum("v").collect())
    res = env.execute_cluster()
    assert res.state == TaskStates.FINISHED
    total = sum(r["v"] for r in sink.rows())
    assert abs(total - float(vals.sum())) < 0.05


def test_periodic_checkpoints_complete():
    storage = InMemoryCheckpointStorage(retain=10)
    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    n = 60_000
    keys = np.arange(n) % 101
    vals = np.ones(n)
    sink = (env.from_collection(columns={"k": keys, "v": vals}, batch_size=512)
            .key_by("k").sum("v").collect())
    res = env.execute_cluster(storage=storage, checkpoint_interval_ms=20)
    assert res.state == TaskStates.FINISHED
    assert res.completed_checkpoints, "no checkpoint completed during the run"
    snap = storage.load_latest()
    assert "__job__" in snap
    # every vertex contributed all its subtask snapshots
    for uid, entry in snap.items():
        if uid == "__job__":
            continue
        assert all(s is not None for s in entry["subtasks"])


def test_failure_restart_from_checkpoint_resumes():
    """A map that fails once mid-stream; restart resumes from the latest
    checkpoint + source offsets, final sums stay correct (exactly-once state)."""
    storage = InMemoryCheckpointStorage(retain=10)
    n = 30_000
    keys = np.arange(n) % 13
    vals = np.ones(n)
    fail_once = {"armed": True}

    def poison(row_cols):
        # fail the FIRST attempt once records flow; later attempts pass
        if fail_once["armed"] and poison.count > 40:
            fail_once["armed"] = False
            raise RuntimeError("injected failure")
        poison.count += 1
        return row_cols
    poison.count = 0

    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    sink = (env.from_collection(columns={"k": keys, "v": vals}, batch_size=128)
            .map(poison)
            .key_by("k").sum("v").collect())
    res = env.execute_cluster(storage=storage, checkpoint_interval_ms=5,
                              restart_attempts=2)
    assert res.state == TaskStates.FINISHED
    assert res.restarts >= 1, "failure did not trigger a restart"
    final = {}
    for r in sink.rows():
        final[int(r["k"])] = r["v"]
    expect = _expected_sums(keys, vals)
    for k in expect:
        assert final[k] == expect[k], (k, final[k], expect[k])


def test_savepoint_and_resume():
    storage = InMemoryCheckpointStorage()
    rng = np.random.default_rng(8)
    n = 20_000
    keys = rng.integers(0, 7, n)
    vals = np.ones(n)

    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    sink = (env.from_collection(columns={"k": keys, "v": vals}, batch_size=64)
            .key_by("k").sum("v").collect())
    plan = env.get_stream_graph().to_plan()
    mc = MiniCluster(checkpoint_storage=storage)
    done = {}

    def run():
        done["res"] = mc.execute(plan, timeout_s=60)

    th = threading.Thread(target=run)
    th.start()
    time.sleep(0.15)
    sp = mc.savepoint()
    th.join(timeout=60)
    if sp is None:
        pytest.skip("job finished before savepoint could complete")
    snap = storage.load(sp)
    offsets = [s["source_offset"] for uid, entry in snap.items()
               if uid != "__job__" for s in entry["subtasks"]
               if s and "source_offset" in s]
    assert offsets and all(o >= 0 for o in offsets)


# ---------------------------------------------------------------------------
# unaligned barriers (subtask-level)
# ---------------------------------------------------------------------------

class _SumOp:
    """Minimal stateful operator: sums v column."""

    name = "sum"
    forwards_watermarks = True
    is_stateless = False
    is_two_input = False

    def open(self, ctx):
        self.total = 0.0

    def process_batch(self, batch):
        self.total += float(np.asarray(batch.column("v")).sum())
        return []

    def process_watermark(self, wm):
        return []

    def on_processing_time(self, ts):
        return []

    def end_input(self):
        return [RecordBatch({"total": np.asarray([self.total])})]

    def snapshot_state(self):
        return {"total": self.total}

    def restore_state(self, snap):
        self.total = snap["total"]

    def notify_checkpoint_complete(self, cid):
        pass

    def close(self):
        pass


class _Recorder(TaskListener):
    def __init__(self):
        self.acks = {}
        self.states = []

    def task_state_changed(self, uid, idx, state, error):
        self.states.append((state, error))

    def acknowledge_checkpoint(self, cid, uid, idx, snap):
        self.acks[cid] = snap


def _batch(v):
    return RecordBatch({"v": np.asarray([v], np.float64)})


def test_failed_task_still_closes_operator():
    """A FAILED subtask must release operator resources (managed-memory
    reservations, spill files): the slot's memory pool is reused across
    pipelined-region restarts, so a leaked reservation compounds until
    reserve_managed fails permanently inside open()."""

    class _Boom(_SumOp):
        def open(self, ctx):
            super().open(ctx)
            self.closed = 0

        def process_batch(self, batch):
            raise RuntimeError("induced failure")

        def close(self):
            self.closed += 1

    class _Out:
        channels = []

        def emit(self, el):
            pass

    op = _Boom()
    ch = LocalChannel(16)
    rec = _Recorder()
    t = Subtask("v1", 0, op, [_Out()], RuntimeContext(), rec, [ch])
    t.start()
    ch.put(_batch(1.0))
    t.join()
    assert ("FAILED", "RuntimeError: induced failure") in [
        (s, e) for s, e in rec.states]
    assert op.closed == 1


def test_canceled_task_still_closes_operator():
    class _Slow(_SumOp):
        def open(self, ctx):
            super().open(ctx)
            self.closed = 0

        def close(self):
            self.closed += 1

    class _Out:
        channels = []

        def emit(self, el):
            pass

    op = _Slow()
    ch = LocalChannel(16)
    rec = _Recorder()
    t = Subtask("v1", 0, op, [_Out()], RuntimeContext(), rec, [ch])
    t.start()
    time.sleep(0.05)
    t.cancel()
    t.join()
    assert any(s == "CANCELED" for s, _ in rec.states)
    assert op.closed == 1


def test_unaligned_barrier_overtakes_and_records_channel_state():
    ch0, ch1 = LocalChannel(16), LocalChannel(16)
    out = LocalChannel(64)

    class _Out:
        channels = [out]

        def emit(self, el):
            out.put(el)

    rec = _Recorder()
    t = Subtask("v1", 0, _SumOp(), [_Out()], RuntimeContext(), rec,
                [ch0, ch1], unaligned=True)
    t.start()
    ch0.put(_batch(1.0))
    ch1.put(_batch(2.0))
    time.sleep(0.05)
    ch0.put(CheckpointBarrier(1, 0))      # barrier on ch0 first
    time.sleep(0.05)
    ch1.put(_batch(10.0))                 # in-flight pre-barrier data on ch1
    time.sleep(0.05)
    ch1.put(CheckpointBarrier(1, 0))      # alignment completes
    time.sleep(0.05)
    ch0.put(EndOfInput())
    ch1.put(EndOfInput())
    t.join()

    snap = rec.acks[1]
    # operator snapshot taken at FIRST barrier: only 1+2 counted
    assert snap["operator"]["total"] == 3.0
    # the overtaken element is in the VERSIONED channel-state section
    cs = snap["channel_state"]
    assert cs["version"] == 1 and cs["unaligned"]
    els = cs["elements"]
    assert len(els) == 1 and els[0][0] == 1
    assert float(np.asarray(els[0][1].column("v"))[0]) == 10.0
    assert cs["persisted_bytes"] > 0
    assert cs["alignment_ms"] >= 0.0
    # barrier must have been forwarded BEFORE the in-flight data was processed
    seen = []
    while True:
        el = out.poll(0.01)
        if el is None:
            break
        seen.append(el)
    kinds = [type(e).__name__ for e in seen]
    assert "CheckpointBarrier" in kinds


def test_unaligned_restore_reprocesses_channel_state():
    rec = _Recorder()
    ch = LocalChannel(16)

    class _Out:
        channels = []

        def emit(self, el):
            if isinstance(el, RecordBatch) and "total" in el.columns:
                rec.final = float(np.asarray(el.column("total"))[0])

    restore = {"operator": {"total": 3.0},
               "channel_state": [(0, _batch(10.0))],
               "valve": [0]}
    t = Subtask("v1", 0, _SumOp(), [_Out()], RuntimeContext(), rec, [ch],
                unaligned=True)
    t.start(restore)
    ch.put(_batch(4.0))
    ch.put(EndOfInput())
    t.join()
    assert rec.final == 3.0 + 10.0 + 4.0
