"""Clock seam + ClockSkew nemesis (ISSUE-4 satellite, VERDICT next #8).

The runtime reads time through ``flink_tpu/utils/clock.py``; a chaos
``ClockSkew`` schedule offsets every reading deterministically (seeded
backward steps, forward jumps, drift).  These tests assert the monotone
boundaries hold: processing-time timers never fire early on a backward
step and never stick on a forward jump; state TTL never expires early;
session gaps never close early.
"""

import numpy as np
import pytest

from flink_tpu.testing import chaos
from flink_tpu.testing.chaos import ClockSkew, FaultInjector
from flink_tpu.utils import clock

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    yield
    chaos.uninstall()


def test_clock_skew_is_seeded_and_deterministic():
    def offsets(seed):
        inj = FaultInjector(seed=seed)
        inj.inject("clock.wall", ClockSkew(jumps=[(3, -5000), (6, 60000)],
                                           drift_ms_per_read=1.5,
                                           jitter_ms=10.0))
        with chaos.installed(inj):
            return [chaos.skew("clock.wall") for _ in range(10)]

    o1, o2 = offsets(5), offsets(5)
    assert o1 == o2, "same seed must reproduce the exact skew sequence"
    assert offsets(6) != o1
    # jumps apply from their reading on; drift accumulates; jitter bounded
    assert o1[0] == pytest.approx(1.5, abs=10.0)
    assert o1[3] == pytest.approx(-5000 + 1.5 * 4, abs=10.0)
    assert o1[7] == pytest.approx(55000 + 1.5 * 8, abs=10.0)


def test_clock_reads_through_skew():
    import time as _time
    inj = FaultInjector(seed=1)
    inj.inject("clock.wall", ClockSkew(jumps=[(1, -600_000)]))
    with chaos.installed(inj):
        skewed = clock.now_ms()
    real = int(_time.time() * 1000)
    assert 500_000 < real - skewed < 700_000
    # no injector: exact wall clock, zero offset
    assert abs(clock.now_ms() - int(_time.time() * 1000)) < 5_000


def test_timer_service_monotone_under_backward_steps():
    """Processing-time timers: a backward-stepped clock neither re-fires
    popped timers nor fires pending ones early; a forward jump fires
    everything due at once (no stuck timers)."""
    from flink_tpu.runtime.timers import InternalTimerService

    svc = InternalTimerService()
    svc.register_processing_time([1], [1000])
    svc.register_processing_time([2], [5000])
    s, _, _ = svc.advance_processing_time(500)
    assert s.size == 0
    s, _, _ = svc.advance_processing_time(2000)
    assert s.tolist() == [1]
    # backward step: service time stays at its high-water mark
    s, _, _ = svc.advance_processing_time(100)
    assert s.size == 0 and svc.current_processing_time == 2000
    # a timer registered in the (stepped-back) past fires at the next
    # advance, not early and not never
    svc.register_processing_time([3], [1500])
    s, _, _ = svc.advance_processing_time(300)   # still behind high-water
    assert s.tolist() == [3]
    # forward jump: everything due fires at once
    s, _, _ = svc.advance_processing_time(1_000_000)
    assert s.tolist() == [2]
    # snapshot round-trips the monotone high-water mark
    snap = svc.snapshot()
    svc2 = InternalTimerService()
    svc2.restore(snap)
    assert svc2.current_processing_time == 1_000_000


def test_executor_processing_tick_monotone_under_skew():
    """The LocalExecutor's ProcessingTimeService tick clamps monotone at
    the clock seam: operators observe non-decreasing processing time even
    while ClockSkew steps the wall clock backward."""
    from flink_tpu.runtime.executor import LocalExecutor

    seen = []

    class _Probe:
        def on_processing_time(self, ts):
            seen.append(ts)
            return []

    ex = LocalExecutor()
    running = {0: type("RV", (), {"operator": _Probe()})()}
    inj = FaultInjector(seed=2)
    # every second reading steps 10 minutes back, then recovers
    inj.inject("clock.wall", ClockSkew(jumps=[(2, -600_000), (3, 600_000),
                                              (4, -600_000), (5, 600_000)]))
    with chaos.installed(inj):
        for _ in range(5):
            ex._advance_processing_time(running)
    assert seen == sorted(seen), f"processing time regressed: {seen}"


def test_ttl_no_premature_expiry_on_backward_step():
    """State TTL under ClockSkew: a backward step must not expire live
    state (cutoff moves back too); a forward jump past the TTL does."""
    from flink_tpu.state.api import StateTtlConfig
    from flink_tpu.state.heap import HeapKeyedStateBackend

    backend = HeapKeyedStateBackend()
    st = backend.value_state("v", dtype=np.float64,
                             ttl=StateTtlConfig(ttl_ms=60_000))
    slots = backend.key_slots(np.asarray([7]))
    st.put_rows(slots, [1.0])        # touch at real wall time (no skew)
    inj = FaultInjector(seed=3)
    # skewed readings 2..3: 10 min BACKWARD; reading 4+: net +10 min
    inj.inject("clock.wall", ClockSkew(jumps=[(2, -600_000),
                                              (4, 1_200_000)]))
    with chaos.installed(inj):
        _vals, alive = st.get_rows(slots)          # reading 1 (no skew)
        assert alive[0]
        _vals, alive = st.get_rows(slots)          # reading 2 (backward)
        assert alive[0], "backward step expired live state"
        _vals, alive = st.get_rows(slots)          # reading 3 (backward)
        assert alive[0]
        _vals, alive = st.get_rows(slots)          # reading 4: +10 min
        assert not alive[0], "TTL past its horizon must expire"


def test_session_gap_monotone_under_skew():
    """Processing-time session windows: a backward step neither closes a
    session early nor reopens gap progress; the session closes exactly
    when (monotone) processing time passes last-activity + gap."""
    from flink_tpu.core.batch import RecordBatch
    from flink_tpu.core.functions import RuntimeContext, SumAggregator
    from flink_tpu.operators.session_window import SessionWindowOperator
    from flink_tpu.windowing.assigners import ProcessingTimeSessionWindows
    import jax.numpy as jnp

    op = SessionWindowOperator(ProcessingTimeSessionWindows(gap_ms=100),
                               SumAggregator(jnp.float64), key_column="k",
                               value_column="v")
    op.open(RuntimeContext())
    assert op.on_processing_time(1000) == []
    op.process_batch(RecordBatch({"k": np.asarray([1, 1]),
                                  "v": np.asarray([2.0, 3.0])}))
    # gap not yet passed
    assert op.on_processing_time(1050) == []
    # BACKWARD step: must not close the session, must not rewind progress
    assert op.on_processing_time(200) == []
    assert op._proc_time == 1050
    # gap passes on monotone time: exactly one fire with the full sum
    fired = op.on_processing_time(1200)
    rows = [b for b in fired if hasattr(b, "columns")]
    assert len(rows) == 1 and len(rows[0]) == 1
    assert float(np.asarray(rows[0].column("result"))[0]) == 5.0
    # no refire after another backward step + recovery
    assert op.on_processing_time(100) == []
    assert op.on_processing_time(1300) == []


def test_heartbeat_clock_seam_injectable():
    """HeartbeatManager's default clock reads the seam (a monotonic skew
    can falsely age heartbeats — the local-clock-jump false suspect)."""
    from flink_tpu.cluster.heartbeat import HeartbeatManager

    hb = HeartbeatManager()
    inj = FaultInjector(seed=4)
    inj.inject("clock.monotonic", ClockSkew(jumps=[(1, 50_000)]))
    import time as _time
    with chaos.installed(inj):
        skewed = hb._clock()
    assert skewed - _time.monotonic() > 40.0
