"""Clock seam + ClockSkew nemesis (ISSUE-4 satellite, VERDICT next #8).

The runtime reads time through ``flink_tpu/utils/clock.py``; a chaos
``ClockSkew`` schedule offsets every reading deterministically (seeded
backward steps, forward jumps, drift).  These tests assert the monotone
boundaries hold: processing-time timers never fire early on a backward
step and never stick on a forward jump; state TTL never expires early;
session gaps never close early.
"""

import numpy as np
import pytest

from flink_tpu.testing import chaos
from flink_tpu.testing.chaos import ClockSkew, FaultInjector
from flink_tpu.utils import clock

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    yield
    chaos.uninstall()


def test_clock_skew_is_seeded_and_deterministic():
    def offsets(seed):
        inj = FaultInjector(seed=seed)
        inj.inject("clock.wall", ClockSkew(jumps=[(3, -5000), (6, 60000)],
                                           drift_ms_per_read=1.5,
                                           jitter_ms=10.0))
        with chaos.installed(inj):
            return [chaos.skew("clock.wall") for _ in range(10)]

    o1, o2 = offsets(5), offsets(5)
    assert o1 == o2, "same seed must reproduce the exact skew sequence"
    assert offsets(6) != o1
    # jumps apply from their reading on; drift accumulates; jitter bounded
    assert o1[0] == pytest.approx(1.5, abs=10.0)
    assert o1[3] == pytest.approx(-5000 + 1.5 * 4, abs=10.0)
    assert o1[7] == pytest.approx(55000 + 1.5 * 8, abs=10.0)


def test_clock_reads_through_skew():
    import time as _time
    inj = FaultInjector(seed=1)
    inj.inject("clock.wall", ClockSkew(jumps=[(1, -600_000)]))
    with chaos.installed(inj):
        skewed = clock.now_ms()
    real = int(_time.time() * 1000)
    assert 500_000 < real - skewed < 700_000
    # no injector: exact wall clock, zero offset
    assert abs(clock.now_ms() - int(_time.time() * 1000)) < 5_000


def test_timer_service_monotone_under_backward_steps():
    """Processing-time timers: a backward-stepped clock neither re-fires
    popped timers nor fires pending ones early; a forward jump fires
    everything due at once (no stuck timers)."""
    from flink_tpu.runtime.timers import InternalTimerService

    svc = InternalTimerService()
    svc.register_processing_time([1], [1000])
    svc.register_processing_time([2], [5000])
    s, _, _ = svc.advance_processing_time(500)
    assert s.size == 0
    s, _, _ = svc.advance_processing_time(2000)
    assert s.tolist() == [1]
    # backward step: service time stays at its high-water mark
    s, _, _ = svc.advance_processing_time(100)
    assert s.size == 0 and svc.current_processing_time == 2000
    # a timer registered in the (stepped-back) past fires at the next
    # advance, not early and not never
    svc.register_processing_time([3], [1500])
    s, _, _ = svc.advance_processing_time(300)   # still behind high-water
    assert s.tolist() == [3]
    # forward jump: everything due fires at once
    s, _, _ = svc.advance_processing_time(1_000_000)
    assert s.tolist() == [2]
    # snapshot round-trips the monotone high-water mark
    snap = svc.snapshot()
    svc2 = InternalTimerService()
    svc2.restore(snap)
    assert svc2.current_processing_time == 1_000_000


def test_executor_processing_tick_monotone_under_skew():
    """The LocalExecutor's ProcessingTimeService tick clamps monotone at
    the clock seam: operators observe non-decreasing processing time even
    while ClockSkew steps the wall clock backward."""
    from flink_tpu.runtime.executor import LocalExecutor

    seen = []

    class _Probe:
        def on_processing_time(self, ts):
            seen.append(ts)
            return []

    ex = LocalExecutor()
    running = {0: type("RV", (), {"operator": _Probe()})()}
    inj = FaultInjector(seed=2)
    # every second reading steps 10 minutes back, then recovers
    inj.inject("clock.wall", ClockSkew(jumps=[(2, -600_000), (3, 600_000),
                                              (4, -600_000), (5, 600_000)]))
    with chaos.installed(inj):
        for _ in range(5):
            ex._advance_processing_time(running)
    assert seen == sorted(seen), f"processing time regressed: {seen}"


def test_ttl_no_premature_expiry_on_backward_step():
    """State TTL under ClockSkew: a backward step must not expire live
    state (cutoff moves back too); a forward jump past the TTL does."""
    from flink_tpu.state.api import StateTtlConfig
    from flink_tpu.state.heap import HeapKeyedStateBackend

    backend = HeapKeyedStateBackend()
    st = backend.value_state("v", dtype=np.float64,
                             ttl=StateTtlConfig(ttl_ms=60_000))
    slots = backend.key_slots(np.asarray([7]))
    st.put_rows(slots, [1.0])        # touch at real wall time (no skew)
    inj = FaultInjector(seed=3)
    # skewed readings 2..3: 10 min BACKWARD; reading 4+: net +10 min
    inj.inject("clock.wall", ClockSkew(jumps=[(2, -600_000),
                                              (4, 1_200_000)]))
    with chaos.installed(inj):
        _vals, alive = st.get_rows(slots)          # reading 1 (no skew)
        assert alive[0]
        _vals, alive = st.get_rows(slots)          # reading 2 (backward)
        assert alive[0], "backward step expired live state"
        _vals, alive = st.get_rows(slots)          # reading 3 (backward)
        assert alive[0]
        _vals, alive = st.get_rows(slots)          # reading 4: +10 min
        assert not alive[0], "TTL past its horizon must expire"


def test_session_gap_monotone_under_skew():
    """Processing-time session windows: a backward step neither closes a
    session early nor reopens gap progress; the session closes exactly
    when (monotone) processing time passes last-activity + gap."""
    from flink_tpu.core.batch import RecordBatch
    from flink_tpu.core.functions import RuntimeContext, SumAggregator
    from flink_tpu.operators.session_window import SessionWindowOperator
    from flink_tpu.windowing.assigners import ProcessingTimeSessionWindows
    import jax.numpy as jnp

    op = SessionWindowOperator(ProcessingTimeSessionWindows(gap_ms=100),
                               SumAggregator(jnp.float64), key_column="k",
                               value_column="v")
    op.open(RuntimeContext())
    assert op.on_processing_time(1000) == []
    op.process_batch(RecordBatch({"k": np.asarray([1, 1]),
                                  "v": np.asarray([2.0, 3.0])}))
    # gap not yet passed
    assert op.on_processing_time(1050) == []
    # BACKWARD step: must not close the session, must not rewind progress
    assert op.on_processing_time(200) == []
    assert op._proc_time == 1050
    # gap passes on monotone time: exactly one fire with the full sum
    fired = op.on_processing_time(1200)
    rows = [b for b in fired if hasattr(b, "columns")]
    assert len(rows) == 1 and len(rows[0]) == 1
    assert float(np.asarray(rows[0].column("result"))[0]) == 5.0
    # no refire after another backward step + recovery
    assert op.on_processing_time(100) == []
    assert op.on_processing_time(1300) == []


def test_monotone_elapsed_never_regresses_under_skew():
    """MonotoneElapsed (checkpoint expiry + alignment timers): a backward
    monotonic step must not shrink an elapsed reading — once a deadline is
    passed it stays passed; a forward jump advances it immediately."""
    from flink_tpu.utils.clock import MonotoneElapsed

    inj = FaultInjector(seed=7)
    # reading 1 = construction (unskewed); 2: +30s; 3: -60s (net -30s);
    # 4: +120s more (net +90s)
    inj.inject("clock.monotonic", ClockSkew(jumps=[(2, 30_000),
                                                   (3, -90_000),
                                                   (4, 150_000)]))
    with chaos.installed(inj):
        t = MonotoneElapsed()
        a = t.seconds()          # +30s skew
        b = t.seconds()          # -30s skew: must NOT regress
        c = t.seconds()          # +90s skew: advances
    assert a >= 29.0
    assert b >= a, f"elapsed regressed under backward skew: {a} -> {b}"
    assert c >= 89.0


def test_checkpoint_expiry_monotone_under_skew():
    """The MiniCluster coordinator's checkpoint-timeout path reads the
    clock seam: a ClockSkew forward jump past the timeout expires the
    pending checkpoint (charged as 'expired'), raw wall time regardless."""
    from flink_tpu.cluster.minicluster import MiniCluster, _PendingCheckpoint
    from flink_tpu.utils.clock import MonotoneElapsed

    cluster = MiniCluster(checkpoint_timeout_s=60.0,
                          tolerable_failed_checkpoints=-1)

    class _T:
        vertex_uid, subtask_index = "v", 0
        state = "RUNNING"

    cluster._tasks = [_T()]
    cluster._source_tasks = []
    cluster._finished = set()
    inj = FaultInjector(seed=8)
    # reading 1 = the pending timer's construction; every later reading
    # jumps 10 minutes forward — far past the 60s timeout
    inj.inject("clock.monotonic", ClockSkew(jumps=[(2, 600_000)]))
    with chaos.installed(inj):
        cluster._pending = _PendingCheckpoint(1, expected=1,
                                              timer=MonotoneElapsed())
        cid, reason = cluster._trigger_checkpoint()
    assert cid is not None and reason == "ok", \
        "expired pending must be aborted and a new checkpoint started"
    st = cluster.failure_manager.status()
    assert st["last_failure_reason"] == "expired"
    assert st["last_failure_checkpoint_id"] == 1


def test_alignment_timer_reads_clock_seam():
    """A Subtask's aligned-with-timeout escalation runs off the injectable
    clock: a forward monotonic jump expires a 60s alignment timeout
    immediately — the barrier overtakes without any wall-clock wait."""
    import time as _time

    from flink_tpu.cluster.channels import LocalChannel
    from flink_tpu.cluster.task import Subtask, TaskListener
    from flink_tpu.core.batch import CheckpointBarrier, EndOfInput, RecordBatch
    from flink_tpu.core.functions import RuntimeContext

    class _Op:
        name = "op"
        forwards_watermarks = True
        is_stateless = False
        is_two_input = False

        def open(self, ctx):
            self.total = 0.0

        def process_batch(self, b):
            self.total += float(np.asarray(b.column("v")).sum())
            return []

        def process_watermark(self, wm):
            return []

        def on_processing_time(self, ts):
            return []

        def end_input(self):
            return []

        def snapshot_state(self):
            return {"total": self.total}

        def restore_state(self, s):
            self.total = s["total"]

        def notify_checkpoint_complete(self, cid):
            pass

        def close(self):
            pass

    class _Rec(TaskListener):
        def __init__(self):
            self.acks = {}

        def acknowledge_checkpoint(self, cid, uid, idx, snap):
            self.acks[cid] = snap

    class _Out:
        channels = []

        def emit(self, el):
            pass

    ch0, ch1 = LocalChannel(16, "c0"), LocalChannel(16, "c1")
    rec = _Rec()
    t = Subtask("v1", 0, _Op(), [_Out()], RuntimeContext(), rec,
                [ch0, ch1], alignment_timeout_ms=60_000)
    inj = FaultInjector(seed=9)
    # every monotonic reading from the 3rd on jumps +10 minutes: the
    # alignment timer (started on the barrier) expires at once
    inj.inject("clock.monotonic", ClockSkew(jumps=[(3, 600_000)]))
    with chaos.installed(inj):
        t.start()
        ch0.put(CheckpointBarrier(1, 0))
        deadline = _time.monotonic() + 10
        while 1 not in rec.acks and _time.monotonic() < deadline:
            _time.sleep(0.01)
        # the OTHER channel never delivered its barrier: an ack can only
        # come from the escalated (overtaken) path completing after ch1's
        # barrier — send it now that escalation must have fired
        ch1.put(CheckpointBarrier(1, 0))
        deadline = _time.monotonic() + 10
        while 1 not in rec.acks and _time.monotonic() < deadline:
            _time.sleep(0.01)
        ch0.put(EndOfInput())
        ch1.put(EndOfInput())
        t.join()
    assert 1 in rec.acks
    assert rec.acks[1]["channel_state"]["unaligned"], \
        "the skew-expired alignment timer did not escalate"


def test_heartbeat_clock_seam_injectable():
    """HeartbeatManager's default clock reads the seam (a monotonic skew
    can falsely age heartbeats — the local-clock-jump false suspect)."""
    from flink_tpu.cluster.heartbeat import HeartbeatManager

    hb = HeartbeatManager()
    inj = FaultInjector(seed=4)
    inj.inject("clock.monotonic", ClockSkew(jumps=[(1, 50_000)]))
    import time as _time
    with chaos.installed(inj):
        skewed = hb._clock()
    assert skewed - _time.monotonic() > 40.0
