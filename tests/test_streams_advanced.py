"""Advanced stream operations: connected streams, broadcast state, interval
join, window join/cogroup, side outputs, async I/O."""

import numpy as np
import pytest

from flink_tpu.cluster.task import TaskStates
from flink_tpu.core.batch import OutputTag, RecordBatch, Watermark
from flink_tpu.datastream.api import StreamExecutionEnvironment
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


def _env():
    return StreamExecutionEnvironment()


def test_connect_co_map():
    env = _env()
    a = env.from_collection(columns={"x": np.arange(5, dtype=np.int64)})
    b = env.from_collection(columns={"x": np.arange(5, dtype=np.int64)})
    out = (a.connect(b)
           .map(lambda c: {"y": np.asarray(c["x"]) * 10},
                lambda c: {"y": np.asarray(c["x"]) * 100})
           .execute_and_collect())
    ys = sorted(r["y"] for r in out)
    assert ys == sorted([x * 10 for x in range(5)] + [x * 100 for x in range(5)])


def test_broadcast_state_pattern():
    from flink_tpu.operators.co import BroadcastProcessFunction

    class Rules(BroadcastProcessFunction):
        def process_broadcast_batch(self, cols, state, ctx):
            for k, v in zip(np.asarray(cols["key"]).tolist(),
                            np.asarray(cols["mult"]).tolist()):
                state[int(k)] = v

        def process_batch(self, cols, state, ctx):
            x = np.asarray(cols["k"])
            mult = np.asarray([state.get(int(k), 0) for k in x])
            return {"k": x, "scaled": np.asarray(cols["v"]) * mult}

    env = _env()
    rules = env.from_collection(columns={"key": np.array([0, 1]),
                                         "mult": np.array([10.0, 100.0])})
    main = env.from_collection(columns={"k": np.array([0, 1, 0]),
                                        "v": np.array([1.0, 2.0, 3.0])})
    out = main.connect_broadcast(rules, Rules()).execute_and_collect()
    got = sorted(r["scaled"] for r in out)
    assert got == [10.0, 30.0, 200.0]


def test_interval_join():
    env = _env()
    left = (env.from_collection(columns={"k": np.array([1, 1, 2]),
                                         "lv": np.array([10., 20., 30.]),
                                         "t": np.array([100, 200, 100])})
            .assign_timestamps_and_watermarks(0, timestamp_column="t")
            .key_by("k"))
    right = (env.from_collection(columns={"k": np.array([1, 1, 2]),
                                          "rv": np.array([1., 2., 3.]),
                                          "t": np.array([105, 350, 190])})
             .assign_timestamps_and_watermarks(0, timestamp_column="t")
             .key_by("k"))
    out = (left.interval_join(right).between(-50, 50).process()
           .execute_and_collect())
    pairs = sorted((r["lv"], r["rv"]) for r in out)
    # k=1: (10,t100)x(1,t105) in window; (20,t200) matches nothing within 50
    # k=2: (30,t100)x(3,t190) outside +50
    assert pairs == [(10.0, 1.0)]


def test_window_join():
    env = _env()
    left = (env.from_collection(columns={"k": np.array([1, 1, 2]),
                                         "lv": np.array([1., 2., 3.]),
                                         "t": np.array([10, 150, 20])})
            .assign_timestamps_and_watermarks(0, timestamp_column="t"))
    right = (env.from_collection(columns={"k": np.array([1, 2, 2]),
                                          "rv": np.array([5., 6., 7.]),
                                          "t": np.array([40, 30, 160])})
             .assign_timestamps_and_watermarks(0, timestamp_column="t"))
    out = (left.join(right).where("k").equal_to("k")
           .window(TumblingEventTimeWindows.of(100))
           .apply().execute_and_collect())
    pairs = sorted((r["lv"], r["rv"]) for r in out)
    # window [0,100): k=1 -> (1,5); k=2 -> (3,6). window [100,200): no match
    assert pairs == [(1.0, 5.0), (3.0, 6.0)]
    assert all(r["window_end"] % 100 == 0 for r in out)


def test_window_cogroup_fires_one_sided():
    env = _env()
    left = (env.from_collection(columns={"k": np.array([1]),
                                         "lv": np.array([1.]),
                                         "t": np.array([10])})
            .assign_timestamps_and_watermarks(0, timestamp_column="t"))
    right = (env.from_collection(columns={"k": np.array([2]),
                                          "rv": np.array([5.]),
                                          "t": np.array([20])})
             .assign_timestamps_and_watermarks(0, timestamp_column="t"))

    def fold(key, window, lrows, rrows):
        return {"k": key, "nl": len(lrows), "nr": len(rrows)}

    out = (left.co_group(right).where("k").equal_to("k")
           .window(TumblingEventTimeWindows.of(100))
           .apply(fold).execute_and_collect())
    got = {r["k"]: (r["nl"], r["nr"]) for r in out}
    assert got == {1: (1, 0), 2: (0, 1)}


def test_window_join_parallel_cluster():
    rng = np.random.default_rng(12)
    n = 400
    lk = rng.integers(0, 11, n)
    rk = rng.integers(0, 11, n)
    lts = np.sort(rng.integers(0, 1000, n))
    rts = np.sort(rng.integers(0, 1000, n))

    def build(env):
        left = (env.from_collection(columns={"k": lk, "lv": np.ones(n), "t": lts})
                .assign_timestamps_and_watermarks(0, timestamp_column="t"))
        right = (env.from_collection(columns={"k": rk, "rv": np.ones(n), "t": rts})
                 .assign_timestamps_and_watermarks(0, timestamp_column="t"))
        return (left.join(right).where("k").equal_to("k")
                .window(TumblingEventTimeWindows.of(250)).apply())

    env1 = _env()
    serial = build(env1).collect()
    env1.execute()

    env2 = _env()
    env2.set_parallelism(2)
    par = build(env2).collect()
    res = env2.execute_cluster()
    assert res.state == TaskStates.FINISHED
    assert len(par.rows()) == len(serial.rows()) > 0


def test_side_outputs():
    from flink_tpu.operators.process import KeyedProcessFunction

    late = OutputTag("big")

    class Splitter(KeyedProcessFunction):
        def process_batch(self, ctx, batch):
            v = np.asarray(batch.column("v"))
            big = v >= 10
            if big.any():
                ctx.side_output(late, {"v": v[big]})
            return [batch.select(~big)]

    env = _env()
    main = (env.from_collection(columns={"k": np.zeros(6, np.int64),
                                         "v": np.array([1., 20., 2., 30., 3., 4.])})
            .key_by("k").process(Splitter()))
    main_sink = main.collect()
    side_sink = main.get_side_output(late).collect()
    env.execute()
    assert sorted(r["v"] for r in main_sink.rows()) == [1., 2., 3., 4.]
    assert sorted(r["v"] for r in side_sink.rows()) == [20., 30.]


def test_async_io_ordered():
    env = _env()
    calls = []

    def lookup(cols):
        calls.append(len(cols["x"]))
        return {"x": cols["x"], "y": np.asarray(cols["x"]) * 2}

    out = (env.from_collection(columns={"x": np.arange(100, dtype=np.int64)},
                               batch_size=10)
           .async_wait(lookup, capacity=4, ordered=True)
           .execute_and_collect())
    xs = [r["x"] for r in out]
    assert xs == list(range(100))          # ordered mode preserves order
    assert all(r["y"] == r["x"] * 2 for r in out)
    assert len(calls) == 10


def test_async_io_unordered_with_watermark_fence():
    import time

    from flink_tpu.operators.async_io import AsyncWaitOperator

    def slow_first(cols):
        if cols["x"][0] == 0:
            time.sleep(0.05)
        return {"x": cols["x"]}

    op = AsyncWaitOperator(slow_first, capacity=8, ordered=False)
    from flink_tpu.core.functions import RuntimeContext
    op.open(RuntimeContext())
    out = []
    out += op.process_batch(RecordBatch({"x": np.array([0])}))
    out += op.process_batch(RecordBatch({"x": np.array([1])}))
    out += op.process_watermark(Watermark(100))
    out += op.process_batch(RecordBatch({"x": np.array([2])}))
    out += op.end_input()
    op.close()
    kinds = [(type(e).__name__, (np.asarray(e.column("x"))[0]
                                 if isinstance(e, RecordBatch) else e.timestamp))
             for e in out]
    xs = [v for k, v in kinds if k == "RecordBatch"]
    wm_pos = [i for i, (k, _) in enumerate(kinds) if k == "Watermark"][0]
    # both pre-fence batches emit before the watermark, in ANY order
    assert sorted(xs[:wm_pos]) == [0, 1]
    assert xs[wm_pos:] == [2]


def test_async_io_timeout_replacement():
    import time

    from flink_tpu.core.functions import RuntimeContext
    from flink_tpu.operators.async_io import AsyncFunction, AsyncWaitOperator

    class Slow(AsyncFunction):
        def invoke(self, cols):
            time.sleep(1.0)
            return cols

        def timeout(self, cols):
            return {"x": cols["x"], "timed_out": np.ones(len(cols["x"]), bool)}

    op = AsyncWaitOperator(Slow(), timeout_ms=30, ordered=True)
    op.open(RuntimeContext())
    out = op.process_batch(RecordBatch({"x": np.array([7])}))
    out += op.end_input()
    op.close()
    assert any("timed_out" in e.columns for e in out)


def test_evicting_window_count_evictor():
    from flink_tpu.windowing.evictors import CountEvictor

    env = _env()
    out = (env.from_collection(columns={"k": np.zeros(6, np.int64),
                                        "v": np.array([1., 2., 3., 4., 5., 6.]),
                                        "t": np.array([10, 20, 30, 40, 50, 60])})
           .assign_timestamps_and_watermarks(0, timestamp_column="t")
           .key_by("k")
           .window(TumblingEventTimeWindows.of(100))
           .evictor(CountEvictor.of(2))
           .apply(lambda k, w, rows: {"k": k, "s": sum(r["v"] for r in rows)})
           .execute_and_collect())
    assert [r["s"] for r in out] == [11.0]   # last 2 rows: 5+6


def test_window_apply_without_evictor():
    env = _env()
    out = (env.from_collection(columns={"k": np.array([1, 1, 2]),
                                        "v": np.array([1., 2., 5.]),
                                        "t": np.array([10, 20, 30])})
           .assign_timestamps_and_watermarks(0, timestamp_column="t")
           .key_by("k")
           .window(TumblingEventTimeWindows.of(100))
           .apply(lambda k, w, rows: {"k": k, "n": len(rows),
                                      "start": w.start})
           .execute_and_collect())
    got = {r["k"]: r["n"] for r in out}
    assert got == {1: 2, 2: 1}
    assert all(r["start"] == 0 for r in out)


def test_time_evictor():
    from flink_tpu.windowing.evictors import TimeEvictor

    env = _env()
    out = (env.from_collection(columns={"k": np.zeros(4, np.int64),
                                        "v": np.array([1., 2., 4., 8.]),
                                        "t": np.array([0, 50, 80, 90])})
           .assign_timestamps_and_watermarks(0, timestamp_column="t")
           .key_by("k")
           .window(TumblingEventTimeWindows.of(100))
           .evictor(TimeEvictor.of(15))
           .apply(lambda k, w, rows: {"s": sum(r["v"] for r in rows)})
           .execute_and_collect())
    assert [r["s"] for r in out] == [12.0]   # ts in [75, 90]: 4+8


def test_streaming_iteration():
    """Collatz-ish loop: halve evens, feed odds*3+1 back until all reach 1."""
    env = _env()
    start = env.from_collection(columns={"x": np.array([5, 6, 7], np.int64)})
    it = start.iterate(max_wait_ms=300)

    def step(cols):
        x = np.asarray(cols["x"])
        nxt = np.where(x % 2 == 0, x // 2, 3 * x + 1)
        return {"x": nxt}

    body = it.map(step)
    not_done = body.filter(lambda c: np.asarray(c["x"]) != 1)
    done = body.filter(lambda c: np.asarray(c["x"]) == 1)
    it.close_with(not_done)
    sink = done.collect()
    env.execute()
    assert sorted(r["x"] for r in sink.rows()) == [1, 1, 1]


# ---------------------------------------------------------------------------
# review regressions
# ---------------------------------------------------------------------------

def test_interval_join_intermediate_watermark_keeps_right_rows():
    """Regression: a watermark landing between a matching pair must not
    evict the right row before the left row fires."""
    from flink_tpu.core.functions import RuntimeContext
    from flink_tpu.operators.joins import IntervalJoinOperator

    op = IntervalJoinOperator("k", "k", 0, 10)
    op.open(RuntimeContext())
    op.process_batch2(RecordBatch({"k": np.array([1]), "lv": np.array([1.0])},
                                  timestamps=np.array([95])), 0)
    op.process_batch2(RecordBatch({"k": np.array([1]), "rv": np.array([2.0])},
                                  timestamps=np.array([96])), 1)
    out = op.process_watermark(Watermark(100))   # left not yet complete
    out += op.process_watermark(Watermark(110))  # now it fires
    pairs = [(r["lv"], r["rv"]) for b in out for r in b.to_rows()]
    assert pairs == [(1.0, 2.0)]


def test_async_does_not_forward_watermarks_early():
    from flink_tpu.operators.async_io import AsyncWaitOperator
    assert AsyncWaitOperator(lambda c: c).forwards_watermarks is False


def test_async_unordered_timeout_replacement():
    import time

    from flink_tpu.core.functions import RuntimeContext
    from flink_tpu.operators.async_io import AsyncFunction, AsyncWaitOperator

    class Slow(AsyncFunction):
        def invoke(self, cols):
            time.sleep(1.0)
            return cols

        def timeout(self, cols):
            return {"x": cols["x"], "timed_out": np.ones(len(cols["x"]), bool)}

    op = AsyncWaitOperator(Slow(), timeout_ms=30, ordered=False)
    op.open(RuntimeContext())
    out = op.process_batch(RecordBatch({"x": np.array([7])}))
    out += op.end_input()
    op.close()
    assert any(isinstance(e, RecordBatch) and "timed_out" in e.columns
               for e in out)


def test_side_output_parallel_cluster_no_duplicates():
    from flink_tpu.operators.process import KeyedProcessFunction

    tag = OutputTag("big")

    class Splitter(KeyedProcessFunction):
        def process_batch(self, ctx, batch):
            v = np.asarray(batch.column("v"))
            big = v >= 10
            if big.any():
                ctx.side_output(tag, {"v": v[big]})
            return [batch.select(~big)]

    env = _env()
    env.set_parallelism(2)
    main = (env.from_collection(columns={"k": np.arange(6, dtype=np.int64),
                                         "v": np.array([1., 20., 2., 30., 3., 4.])})
            .key_by("k").process(Splitter()))
    side_sink = main.get_side_output(tag).collect()
    main.collect()
    res = env.execute_cluster()
    assert res.state == TaskStates.FINISHED
    assert sorted(r["v"] for r in side_sink.rows()) == [20., 30.]


def test_evicting_window_allowed_lateness_refire():
    from flink_tpu.core.functions import RuntimeContext
    from flink_tpu.operators.evicting_window import EvictingWindowOperator

    op = EvictingWindowOperator(TumblingEventTimeWindows.of(100), None, "k",
                                lambda k, w, rows: {"n": len(rows)},
                                allowed_lateness_ms=50)
    op.open(RuntimeContext())
    op.process_batch(RecordBatch({"k": np.array([1])},
                                 timestamps=np.array([10])))
    out = op.process_watermark(Watermark(100))
    assert [r["n"] for b in out for r in b.to_rows()] == [1]
    # late element within lateness: window refires with updated contents
    out = op.process_batch(RecordBatch({"k": np.array([1])},
                                       timestamps=np.array([20])))
    assert [r["n"] for b in out for r in b.to_rows()] == [2]
    # beyond lateness: dropped silently
    op.process_watermark(Watermark(200))
    out = op.process_batch(RecordBatch({"k": np.array([1])},
                                       timestamps=np.array([30])))
    assert out == []


def test_async_snapshot_preserves_fenced_watermark():
    """Regression: a watermark queued behind in-flight work must survive a
    checkpoint (this operator is its only forwarder)."""
    import time as _t

    from flink_tpu.core.functions import RuntimeContext
    from flink_tpu.operators.async_io import AsyncWaitOperator

    def slow(cols):
        _t.sleep(0.2)
        return cols

    op = AsyncWaitOperator(slow, ordered=True)
    op.open(RuntimeContext())
    op.process_batch(RecordBatch({"x": np.array([1])},
                                 timestamps=np.array([5])))
    op.process_watermark(Watermark(50))
    snap = op.snapshot_state()
    op.close()
    op2 = AsyncWaitOperator(lambda c: c, ordered=True)
    op2.open(RuntimeContext())
    op2.restore_state(snap)
    out = op2.end_input()
    op2.close()
    assert any(isinstance(e, Watermark) and e.timestamp == 50 for e in out)
    assert any(isinstance(e, RecordBatch) for e in out)


def test_broadcast_connect_row_filtering_keeps_working():
    """Regression: a broadcast fn that changes the row count must not crash
    on timestamp re-attachment."""
    from flink_tpu.operators.co import BroadcastProcessFunction

    class Allow(BroadcastProcessFunction):
        def process_broadcast_batch(self, cols, state, ctx):
            state["allowed"] = set(np.asarray(cols["k"]).tolist())

        def process_batch(self, cols, state, ctx):
            k = np.asarray(cols["k"])
            keep = np.isin(k, list(state.get("allowed", ())))
            return {"k": k[keep]}

    env = _env()
    rules = env.from_collection(columns={"k": np.array([2])})
    main = (env.from_collection(columns={"k": np.array([1, 2, 3]),
                                         "t": np.array([10, 20, 30])})
            .assign_timestamps_and_watermarks(0, timestamp_column="t"))
    out = main.connect_broadcast(rules, Allow()).execute_and_collect()
    assert [r["k"] for r in out] == [2]


def test_cogroup_without_fn_raises_eagerly():
    env = _env()
    a = env.from_collection(columns={"k": np.array([1]), "t": np.array([1])})
    b = env.from_collection(columns={"k": np.array([1]), "t": np.array([1])})
    with pytest.raises(ValueError, match="co_group"):
        (a.co_group(b).where("k").equal_to("k")
         .window(TumblingEventTimeWindows.of(10)).apply())


def test_delta_evictor_via_rows_protocol():
    from flink_tpu.windowing.evictors import DeltaEvictor

    env = _env()
    out = (env.from_collection(columns={"k": np.zeros(4, np.int64),
                                        "v": np.array([1., 9., 10., 11.]),
                                        "t": np.array([10, 20, 30, 40])})
           .assign_timestamps_and_watermarks(0, timestamp_column="t")
           .key_by("k")
           .window(TumblingEventTimeWindows.of(100))
           .evictor(DeltaEvictor.of(2.0, "v"))
           .apply(lambda k, w, rows: {"s": sum(r["v"] for r in rows)})
           .execute_and_collect())
    assert [r["s"] for r in out] == [30.0]   # 9+10+11 within delta of last=11


# ---------------------------------------------------------------------------
# watermark idleness (StreamStatus / StatusWatermarkValve.markIdle analog)
# ---------------------------------------------------------------------------

def test_valve_idle_channel_excluded():
    from flink_tpu.core.batch import LONG_MIN
    from flink_tpu.runtime.executor import WatermarkValve

    v = WatermarkValve(2)
    assert v.input_watermark(0, 100) is None     # ch1 still at LONG_MIN
    # ch1 goes idle -> excluded -> min jumps to ch0's 100
    assert v.input_status(1, True) == 100
    assert v.input_watermark(0, 200) == 200      # advances on ch0 alone
    # ch1 reactivates behind the current watermark: no regression
    assert v.input_status(1, False) is None
    assert v.input_watermark(1, 150) is None     # still behind
    assert v.input_watermark(1, 300) is None     # min is ch0's 200
    assert v.input_watermark(0, 400) == 300
    # all idle: nothing can be proven
    v2 = WatermarkValve(2)
    v2.input_status(0, True)
    assert v2.input_status(1, True) is None


def test_idle_input_does_not_stall_windows():
    """A silent second input marked idle must not freeze event time: the
    window fires from the active input's watermarks alone."""
    import jax.numpy as jnp

    import time

    from flink_tpu.cluster.channels import LocalChannel, OutputDispatcher
    from flink_tpu.cluster.task import Subtask, TaskListener
    from flink_tpu.core.batch import (EndOfInput, RecordBatch, StreamStatus,
                                      Watermark)
    from flink_tpu.core.functions import RuntimeContext, SumAggregator
    from flink_tpu.operators.window_agg import WindowAggOperator
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    ch_active = LocalChannel(64)
    ch_idle = LocalChannel(64)
    out = LocalChannel(256)
    op = WindowAggOperator(TumblingEventTimeWindows.of(1000),
                           SumAggregator(jnp.float32), key_column="k",
                           value_column="v")
    t = Subtask("win", 0, op,
                [OutputDispatcher("forward", [out])],
                RuntimeContext(), TaskListener(), [ch_active, ch_idle])
    t.start()
    ch_active.put(RecordBatch({"k": np.array([1, 1]),
                               "v": np.array([2., 3.])},
                              timestamps=np.array([10, 20], np.int64)))
    ch_idle.put(StreamStatus(idle=True))
    ch_active.put(Watermark(2000))
    # drain the output until the window fire arrives
    fired = []
    deadline = time.time() + 20
    while time.time() < deadline and not fired:
        el = out.poll(timeout_s=0.2)
        if isinstance(el, RecordBatch) and len(el):
            fired.extend(el.to_rows())
    ch_active.put(EndOfInput())
    ch_idle.put(EndOfInput())
    t.join(timeout_s=20)
    assert fired and fired[0]["result"] == 5.0


def test_valve_idle_survives_snapshot_restore():
    """Regression: a checkpoint taken while a channel is idle must restore
    WITH the idle flag — nothing re-sends StreamStatus after recovery, so
    losing it would freeze event time forever."""
    from flink_tpu.runtime.executor import WatermarkValve

    v = WatermarkValve(2)
    v.input_watermark(0, 1000)
    v.input_status(1, True)      # min jumps to 1000
    assert v.current == 1000
    snap = v.snapshot()

    v2 = WatermarkValve(2)
    v2.restore(snap)
    assert v2.current == 1000 and v2.idle == [False, True]
    assert v2.input_watermark(0, 2000) == 2000   # still advances alone

    # legacy list-only snapshot stays restorable
    v3 = WatermarkValve(2)
    v3.restore([500, 700])
    assert v3.current == 500


def test_valve_idle_refoward_after_reactivation():
    """Regression: a watermark reactivating an all-idle valve must reset
    the combined-status memory, or the NEXT all-idle transition would
    compare equal and never forward downstream."""
    from flink_tpu.runtime.executor import WatermarkValve

    v = WatermarkValve(2)
    v.status_update(0, True)
    _, combined, changed = v.status_update(1, True)
    assert combined and changed
    v.input_watermark(0, 100)            # reactivates channel 0
    _, combined, changed = v.status_update(0, True)
    assert combined and changed          # must re-forward idle


def test_evicting_sliding_windows_share_pane_buffers():
    """Sliding assigners on the raw-element path: each row is buffered once
    per pane yet appears in every covering window's apply()."""
    import numpy as np
    from flink_tpu.core.batch import RecordBatch, Watermark
    from flink_tpu.core.functions import RuntimeContext
    from flink_tpu.operators.evicting_window import EvictingWindowOperator
    from flink_tpu.windowing.assigners import SlidingEventTimeWindows

    op = EvictingWindowOperator(
        SlidingEventTimeWindows.of(100, 50), None, "k",
        lambda k, w, rows: {"k": k, "n": len(rows),
                            "ws": w.start, "s": sum(r["v"] for r in rows)})
    op.open(RuntimeContext())
    out = op.process_batch(RecordBatch(
        {"k": np.array([1, 1, 1]), "v": np.array([1.0, 2.0, 4.0])},
        timestamps=np.array([10, 60, 110])))
    out += op.process_watermark(Watermark(250))
    rows = sorted((int(r["ws"]), int(r["n"]), float(r["s"]))
                  for b in out if hasattr(b, "columns") for r in b.to_rows())
    # windows [-50,50): v=1; [0,100): 1+2; [50,150): 2+4; [100,200): 4
    assert rows == [(-50, 1, 1.0), (0, 2, 3.0), (50, 2, 6.0), (100, 1, 4.0)]
    # one buffered copy per pane: 3 rows total across pane chunks
    assert sum(c[0].size for chunks in op._panes.values()
               for c in chunks) <= 3


def test_evicting_window_late_refire_and_beyond_lateness_drop():
    import numpy as np
    from flink_tpu.core.batch import RecordBatch, Watermark
    from flink_tpu.core.functions import RuntimeContext
    from flink_tpu.operators.evicting_window import EvictingWindowOperator
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    op = EvictingWindowOperator(
        TumblingEventTimeWindows.of(100), None, "k",
        lambda k, w, rows: {"k": k, "s": sum(r["v"] for r in rows)},
        allowed_lateness_ms=100)
    op.open(RuntimeContext())
    op.process_batch(RecordBatch({"k": np.array([1]),
                                  "v": np.array([5.0])},
                                 timestamps=np.array([10])))
    out = op.process_watermark(Watermark(120))      # window 0 fires
    assert [float(r["s"]) for b in out if hasattr(b, "columns")
            for r in b.to_rows()] == [5.0]
    # late within lateness: window 0 RE-fires with the merged content
    out = op.process_batch(RecordBatch({"k": np.array([1]),
                                        "v": np.array([2.0])},
                                       timestamps=np.array([50])))
    assert [float(r["s"]) for b in out if hasattr(b, "columns")
            for r in b.to_rows()] == [7.0]
    # beyond lateness (cleanup = 99 + 100 <= wm): dropped + counted
    op.process_watermark(Watermark(250))
    op.process_batch(RecordBatch({"k": np.array([1]),
                                  "v": np.array([9.0])},
                                 timestamps=np.array([20])))
    assert op.late_dropped == 1


def test_evicting_window_snapshot_restore_and_keygroup_rescale():
    import numpy as np
    from flink_tpu.core.batch import RecordBatch, Watermark
    from flink_tpu.core.functions import RuntimeContext
    from flink_tpu.operators.evicting_window import EvictingWindowOperator
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    def mk():
        op = EvictingWindowOperator(
            TumblingEventTimeWindows.of(100), None, "k",
            lambda k, w, rows: {"k": k, "s": sum(r["v"] for r in rows)})
        op.open(RuntimeContext())
        return op

    op = mk()
    keys = np.arange(20)
    op.process_batch(RecordBatch({"k": keys,
                                  "v": np.ones(20)},
                                 timestamps=np.full(20, 10)))
    snap = op.snapshot_state()

    # plain restore finishes the window
    op2 = mk()
    op2.restore_state(snap)
    out = op2.process_watermark(Watermark(150))
    got = sorted(int(r["k"]) for b in out if hasattr(b, "columns")
                 for r in b.to_rows())
    assert got == sorted(int(k) for k in keys)

    # rescale: split into 4, every row lands in exactly one part
    parts = EvictingWindowOperator.split_snapshot(snap, 128, 4)
    total = sum(p0["seq"].size for part in parts
                for p0 in part["panes"].values())
    assert total == 20
    merged = EvictingWindowOperator.merge_snapshots(parts)
    op3 = mk()
    op3.restore_state(merged)
    out = op3.process_watermark(Watermark(150))
    got = sorted(int(r["k"]) for b in out if hasattr(b, "columns")
                 for r in b.to_rows())
    assert got == sorted(int(k) for k in keys)
