"""Per-slot managed memory accounting (``runtime/memory.py`` —
``MemoryManager.java`` analog): reservations, fail-fast over-commit,
fraction splitting, slot sizing, and the spill-backend integration."""

from __future__ import annotations

import numpy as np
import pytest

from flink_tpu.config.config_option import Configuration
from flink_tpu.config.options import TaskManagerOptions
from flink_tpu.runtime.memory import (
    MemoryManager, MemoryReservationError, memory_manager_for,
    slot_memory_managers)


class TestAccounting:
    def test_reserve_release_cycle(self):
        mm = MemoryManager(100)
        r1 = mm.reserve("sort", 60)
        assert mm.available() == 40 and mm.used() == 60
        r2 = mm.reserve("hash", 40)
        assert mm.available() == 0
        r1.release()
        assert mm.available() == 60
        r1.release()                      # idempotent
        assert mm.available() == 60
        r2.release()
        assert mm.usage_by_owner() == {}

    def test_over_commit_fails_fast(self):
        mm = MemoryManager(100)
        mm.reserve("a", 80)
        with pytest.raises(MemoryReservationError, match="requested 30"):
            mm.reserve("b", 30)
        # the failed attempt must not leak accounting
        assert mm.available() == 20
        mm.reserve("b", 20)

    def test_release_all_for_owner(self):
        mm = MemoryManager(100)
        mm.reserve("op", 30)
        mm.reserve("op", 20)
        mm.reserve("other", 10)
        assert mm.release_all("op") == 50
        assert mm.available() == 90
        assert mm.usage_by_owner() == {"other": 10}

    def test_context_manager_releases(self):
        mm = MemoryManager(64)
        with mm.reserve("tmp", 64):
            assert mm.available() == 0
        assert mm.available() == 64

    def test_operator_share_weights(self):
        mm = MemoryManager(1000)
        w = {"sort": 3.0, "hash": 1.0}
        assert mm.compute_operator_share(w, "sort") == 750
        assert mm.compute_operator_share(w, "hash") == 250
        assert mm.compute_operator_share(w, "absent") == 0

    def test_slot_split(self):
        slots = slot_memory_managers(100, 4)
        assert [s.total for s in slots] == [25] * 4
        cfg = Configuration()
        cfg.set(TaskManagerOptions.MANAGED_MEMORY_SIZE, 128)
        assert memory_manager_for(cfg, num_slots=2).total == 64
        # num_slots defaults from taskmanager.numberOfTaskSlots
        cfg.set(TaskManagerOptions.NUM_TASK_SLOTS, 4)
        assert memory_manager_for(cfg).total == 32
        assert memory_manager_for(None).total == 256 << 20  # default

    def test_release_after_release_all_does_not_double_free(self):
        """A reservation's own release after release_all(owner) must be a
        no-op — a negative balance would void the over-commit invariant."""
        mm = MemoryManager(100)
        r = mm.reserve("op", 60)
        assert mm.release_all("op") == 60
        r.release()
        assert mm.used() == 0 and mm.available() == 100
        mm.reserve("later", 100)             # exactly full, no phantom room
        with pytest.raises(MemoryReservationError):
            mm.reserve("later", 1)

    def test_slot_pool_bounds_aggregate_memory(self):
        """Subtask launches (and relaunches) round-robin over a FIXED slot
        pool: total managed memory stays bounded by the executor's size."""
        from flink_tpu.runtime.memory import SlotMemoryPool

        cfg = Configuration()
        cfg.set(TaskManagerOptions.MANAGED_MEMORY_SIZE, 100)
        cfg.set(TaskManagerOptions.NUM_TASK_SLOTS, 2)
        pool = SlotMemoryPool(cfg)
        assigned = [pool.assign() for _ in range(10)]
        assert len({id(m) for m in assigned}) == 2     # reused, not grown
        assert sum(m.total for m in pool.slots) == 100


class TestSpillBackendIntegration:
    def test_spill_backend_reserves_and_releases(self, tmp_path):
        from flink_tpu.state.spill import SpillKeyedStateBackend

        mm = MemoryManager(64 << 20)
        b = SpillKeyedStateBackend(str(tmp_path), mem_budget=16 << 20)
        b.reserve_managed(mm, owner="proc[0]")
        assert mm.used() == 16 << 20
        b.reserve_managed(mm, owner="proc[0]")   # idempotent rebind
        assert mm.used() == 16 << 20
        b.close()
        assert mm.used() == 0

    def test_over_committed_slot_fails_at_open(self, tmp_path):
        """Two backends whose budgets exceed the slot's share: the second
        open fails LOUDLY at reserve time — the mid-job-OOM prevention the
        reference's managed memory exists for."""
        from flink_tpu.state.spill import SpillKeyedStateBackend

        mm = MemoryManager(20 << 20)
        b1 = SpillKeyedStateBackend(str(tmp_path / "a"), mem_budget=16 << 20)
        b1.reserve_managed(mm, owner="a")
        b2 = SpillKeyedStateBackend(str(tmp_path / "b"), mem_budget=16 << 20)
        with pytest.raises(MemoryReservationError):
            b2.reserve_managed(mm, owner="b")
        b1.close()
        b2.reserve_managed(mm, owner="b")        # freed share is reusable
        b2.close()

    def test_pipeline_process_function_reserves_slot_memory(self):
        """End to end: a keyed process function over the spill backend
        claims managed memory from the executor slot's manager."""
        from flink_tpu.datastream.api import StreamExecutionEnvironment
        from flink_tpu.config.options import StateOptions

        cfg = Configuration()
        cfg.set(StateOptions.BACKEND, "spill")
        env = StreamExecutionEnvironment(config=cfg)

        class CountFn:
            def open(self, ctx):
                self._seen_manager = ctx.memory_manager
                self.used_at_open = (ctx.memory_manager.used()
                                     if ctx.memory_manager else -1)

            def process_batch(self, ctx, batch):
                return []

            def close(self):
                pass

        fn = CountFn()
        from flink_tpu.operators.process import KeyedProcessOperator
        from flink_tpu.state import make_keyed_backend
        from flink_tpu.core.functions import RuntimeContext

        backend = make_keyed_backend(cfg)
        op = KeyedProcessOperator(fn, "k", "proc", backend=backend)
        mm = MemoryManager(256 << 20)
        op.open(RuntimeContext(task_name="proc", memory_manager=mm))
        if hasattr(backend, "mem_budget"):
            assert mm.used() == backend.mem_budget
        op.close()
        assert mm.used() == 0               # teardown returned the claim

    def test_changelog_wrapper_forwards_reservation(self, tmp_path):
        """changelog-spill must enforce the same contract as plain spill:
        the wrapper forwards reserve_managed/close to the inner backend."""
        from flink_tpu.state.changelog import ChangelogKeyedStateBackend
        from flink_tpu.state.spill import SpillKeyedStateBackend

        inner = SpillKeyedStateBackend(str(tmp_path), mem_budget=8 << 20)
        wrapped = ChangelogKeyedStateBackend(inner)
        mm = MemoryManager(16 << 20)
        wrapped.reserve_managed(mm, owner="w")
        assert mm.used() == 8 << 20
        wrapped.close()
        assert mm.used() == 0
