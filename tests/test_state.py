"""State API + heap backend tests (analog of HeapStateBackendTest /
StateBackendTestBase and TTL tests in runtime/state/ttl/)."""

import numpy as np
import pytest

from flink_tpu.core.functions import AvgAggregator, SumAggregator
from flink_tpu.state.api import (AggregatingStateDescriptor,
                                 ListStateDescriptor, MapStateDescriptor,
                                 ReducingStateDescriptor, StateTtlConfig,
                                 UpdateType, ValueStateDescriptor)
from flink_tpu.state.heap import HeapKeyedStateBackend
from flink_tpu.state.redistribute import (merge_keyed_snapshots,
                                          split_keyed_snapshot)


def make_backend(clock=None):
    if clock is None:
        return HeapKeyedStateBackend()
    return HeapKeyedStateBackend(clock=clock)


def test_value_state_scalar_roundtrip():
    b = make_backend()
    st = b.get_state(ValueStateDescriptor("v", dtype=np.float64, default=0.0))
    b.set_current_key(7)
    assert st.value() == 0.0
    st.update(3.5)
    assert st.value() == 3.5
    b.set_current_key(8)
    assert st.value() == 0.0
    b.set_current_key(7)
    st.clear()
    assert st.value() == 0.0


def test_value_state_batched_rows():
    b = make_backend()
    st = b.get_state(ValueStateDescriptor("v", dtype=np.int64, default=-1))
    slots = b.key_slots(np.array([10, 20, 30, 10]))
    st.put_rows(slots, np.array([1, 2, 3, 4]))
    vals, alive = st.get_rows(slots)
    assert alive.all()
    # duplicate slot: last write wins
    np.testing.assert_array_equal(vals, [4, 2, 3, 4])
    other = b.key_slots(np.array([99]))
    vals, alive = st.get_rows(other)
    assert not alive[0] and vals[0] == -1


def test_value_state_object_dtype():
    b = make_backend()
    st = b.get_state(ValueStateDescriptor("v"))  # dtype=None -> objects
    b.set_current_key("alice")
    st.update({"nested": [1, 2]})
    assert st.value() == {"nested": [1, 2]}
    b.set_current_key("bob")
    assert st.value() is None


def test_list_state_batched_append_groups_by_slot():
    b = make_backend()
    st = b.get_state(ListStateDescriptor("l"))
    slots = b.key_slots(np.array([1, 2, 1, 1, 2]))
    st.add_rows(slots, ["a", "b", "c", "d", "e"])
    lists = st.get_rows(b.key_slots(np.array([1, 2])))
    assert lists[0] == ["a", "c", "d"]
    assert lists[1] == ["b", "e"]
    b.set_current_key(1)
    st.add("z")
    assert st.get() == ["a", "c", "d", "z"]
    st.update(["only"])
    assert st.get() == ["only"]
    st.clear()
    assert st.get() == []


def test_map_state():
    b = make_backend()
    st = b.get_state(MapStateDescriptor("m"))
    b.set_current_key(5)
    assert st.is_empty()
    st.put("x", 1)
    st.put("y", 2)
    assert st.get("x") == 1 and st.contains("y")
    assert sorted(st.keys()) == ["x", "y"]
    st.remove("x")
    assert not st.contains("x")
    b.set_current_key(6)
    assert st.is_empty()  # per-key isolation


def test_reducing_state_batched_fold():
    import jax.numpy as jnp

    b = make_backend()
    st = b.get_state(ReducingStateDescriptor("r", SumAggregator(jnp.float64)))
    slots = b.key_slots(np.array([1, 2, 1, 1]))
    st.add_rows(slots, np.array([1.0, 10.0, 2.0, 3.0]))
    res, alive = st.get_rows(b.key_slots(np.array([1, 2])))
    assert alive.all()
    np.testing.assert_allclose(res, [6.0, 10.0])
    b.set_current_key(2)
    st.add(5.0)
    assert st.get() == 15.0


def test_aggregating_state_nontrivial_acc():
    import jax.numpy as jnp

    b = make_backend()
    st = b.get_state(AggregatingStateDescriptor("a", AvgAggregator(jnp.float64)))
    slots = b.key_slots(np.array([1, 1, 2]))
    st.add_rows(slots, np.array([2.0, 4.0, 9.0]))
    res, alive = st.get_rows(b.key_slots(np.array([1, 2])))
    np.testing.assert_allclose(res, [3.0, 9.0])


def test_snapshot_restore_roundtrip():
    import jax.numpy as jnp

    b = make_backend()
    v = b.get_state(ValueStateDescriptor("v", dtype=np.float32, default=0.0))
    l = b.get_state(ListStateDescriptor("l"))
    r = b.get_state(ReducingStateDescriptor("r", SumAggregator(jnp.float32)))
    slots = b.key_slots(np.array([100, 200, 300]))
    v.put_rows(slots, np.array([1.0, 2.0, 3.0]))
    l.add_rows(slots, ["a", "b", "c"])
    r.add_rows(np.array([slots[0], slots[0]]), np.array([5.0, 6.0]))
    snap = b.snapshot()

    b2 = make_backend()
    b2.get_state(ValueStateDescriptor("v", dtype=np.float32, default=0.0))
    b2.get_state(ListStateDescriptor("l"))
    b2.get_state(ReducingStateDescriptor("r", SumAggregator(jnp.float32)))
    b2.restore(snap)
    b2.set_current_key(200)
    assert b2._states["v"].value() == pytest.approx(2.0)
    assert b2._states["l"].get() == ["b"]
    b2.set_current_key(100)
    assert b2._states["r"].get() == pytest.approx(11.0)


def test_snapshot_splits_by_key_group_for_rescale():
    b = make_backend()
    v = b.get_state(ValueStateDescriptor("v", dtype=np.int64, default=0))
    keys = np.arange(1000, dtype=np.int64)
    slots = b.key_slots(keys)
    v.put_rows(slots, keys * 2)
    snap = b.snapshot()
    parts = split_keyed_snapshot(snap, HeapKeyedStateBackend.row_fields(snap),
                                 max_parallelism=128, new_parallelism=4)
    assert len(parts) == 4
    total = 0
    for p in parts:
        b2 = make_backend()
        b2.get_state(ValueStateDescriptor("v", dtype=np.int64, default=0))
        b2.restore(p)
        n = b2.num_keys
        total += n
        if n:
            ks = np.asarray(b2._index.reverse_keys())
            vals, alive = b2._states["v"].get_rows(
                b2.key_slots(ks))
            assert alive.all()
            np.testing.assert_array_equal(vals, ks * 2)
    assert total == 1000
    # and merge back (scale-down)
    merged = merge_keyed_snapshots(parts, HeapKeyedStateBackend.row_fields(snap))
    b3 = make_backend()
    b3.get_state(ValueStateDescriptor("v", dtype=np.int64, default=0))
    b3.restore(merged)
    assert b3.num_keys == 1000


def test_ttl_expiry_and_snapshot_cleanup():
    now = [1000]
    b = make_backend(clock=lambda: now[0])
    ttl = StateTtlConfig.new_builder(ttl_ms=100).build()
    st = b.get_state(ValueStateDescriptor("v", dtype=np.int64, default=-1,
                                          ttl=ttl))
    b.set_current_key(1)
    st.update(42)
    assert st.value() == 42
    now[0] = 1050
    assert st.value() == 42  # not yet expired
    now[0] = 1200
    assert st.value() == -1  # expired -> default (NeverReturnExpired)
    # full-snapshot cleanup: expired rows dropped on restore
    b.set_current_key(2)
    st.update(7)  # fresh at t=1200
    snap = b.snapshot()
    b2 = make_backend(clock=lambda: now[0])
    st2 = b2.get_state(ValueStateDescriptor("v", dtype=np.int64, default=-1,
                                            ttl=ttl))
    b2.restore(snap)
    b2.set_current_key(1)
    assert st2.value() == -1
    b2.set_current_key(2)
    assert st2.value() == 7


def test_ttl_read_refresh():
    now = [0]
    b = make_backend(clock=lambda: now[0])
    ttl = (StateTtlConfig.new_builder(ttl_ms=100)
           .set_update_type(UpdateType.OnReadAndWrite).build())
    st = b.get_state(ValueStateDescriptor("v", dtype=np.int64, default=-1,
                                          ttl=ttl))
    b.set_current_key(1)
    st.update(1)
    now[0] = 80
    assert st.value() == 1  # read refreshes the timestamp
    now[0] = 160
    assert st.value() == 1  # still alive because of the read at t=80
    now[0] = 300
    assert st.value() == -1


def test_string_keys_use_object_index():
    b = make_backend()
    st = b.get_state(ValueStateDescriptor("v", dtype=np.float64, default=0.0))
    slots = b.key_slots(np.array(["a", "b", "a"], dtype=object))
    st.put_rows(slots, np.array([1.0, 2.0, 3.0]))
    b.set_current_key("a")
    assert st.value() == 3.0
    np.testing.assert_array_equal(
        np.sort(b.slot_keys(b.key_slots(np.array(["a", "b"], dtype=object)))),
        ["a", "b"])


def test_restore_then_snapshot_preserves_unregistered_state():
    """Restored-but-not-yet-registered states must survive a checkpoint
    (lazy descriptor binding must not lose state)."""
    b = make_backend()
    st = b.get_state(ValueStateDescriptor("v", dtype=np.int64, default=0))
    b.set_current_key(1)
    st.update(42)
    snap = b.snapshot()

    b2 = make_backend()
    b2.restore(snap)          # no descriptor registered yet
    snap2 = b2.snapshot()     # checkpoint before first use
    b3 = make_backend()
    b3.restore(snap2)
    st3 = b3.get_state(ValueStateDescriptor("v", dtype=np.int64, default=0))
    b3.set_current_key(1)
    assert st3.value() == 42


def test_ttl_append_does_not_resurrect_expired_content():
    import jax.numpy as jnp

    now = [0]
    b = make_backend(clock=lambda: now[0])
    ttl = StateTtlConfig.new_builder(ttl_ms=100).build()
    lst = b.get_state(ListStateDescriptor("l", ttl=ttl))
    red = b.get_state(ReducingStateDescriptor("r", SumAggregator(jnp.float64),
                                              ttl=ttl))
    mp = b.get_state(MapStateDescriptor("m", ttl=ttl))
    b.set_current_key(1)
    lst.add("old")
    red.add(10.0)
    mp.put("old", 1)
    now[0] = 500  # everything expired
    lst.add("new")
    assert lst.get() == ["new"]
    red.add(5.0)
    assert red.get() == 5.0
    mp.put("new", 2)
    assert dict(mp.items()) == {"new": 2}


def test_state_backend_selectable_via_config():
    """state.backend config picks the keyed backend for process functions
    (heap / native spill / changelog) with identical results."""
    import numpy as np

    from flink_tpu.config.config_option import Configuration
    from flink_tpu.config.options import StateOptions
    from flink_tpu.datastream.api import StreamExecutionEnvironment
    from flink_tpu.operators.process import KeyedProcessFunction
    from flink_tpu.state.changelog import ChangelogKeyedStateBackend
    from flink_tpu.state.spill import SpillKeyedStateBackend

    class Count(KeyedProcessFunction):
        def process_batch(self, ctx, batch):
            st = ctx.state(ValueStateDescriptor("n", default=0))
            got = st.get_rows(batch.key_ids)
            cur = got[0] if isinstance(got, tuple) else got
            vals = np.asarray([0 if c is None else int(c) for c in cur]) + 1
            st.put_rows(batch.key_ids, vals)
            return [batch.with_columns({"k": batch.column("k"),
                                        "n": vals})]

    def run(backend_name):
        cfg = Configuration()
        cfg.set(StateOptions.BACKEND, backend_name)
        env = StreamExecutionEnvironment(config=cfg)
        # batch_size == #keys: one occurrence per key per batch (duplicate
        # slots within one put_rows overwrite — last write wins)
        sink = (env.from_collection(columns={"k": np.arange(50) % 5},
                                    batch_size=5)
                .key_by("k").process(Count()).collect())
        env.execute()
        final = {}
        for r in sink.rows():
            final[r["k"]] = r["n"]
        return final

    expect = run("hbm")
    assert expect == {k: 10 for k in range(5)}
    assert run("spill") == expect
    assert run("changelog") == expect


def test_unknown_backend_rejected():
    from flink_tpu.state import make_keyed_backend
    from flink_tpu.config.config_option import Configuration
    from flink_tpu.config.options import StateOptions

    cfg = Configuration()
    cfg.set(StateOptions.BACKEND, "rocksdb")
    with pytest.raises(ValueError, match="unknown state.backend"):
        make_keyed_backend(cfg)
