"""Unbounded streaming SQL join (StreamingJoinOperator).

Golden property: at EVERY input prefix, materializing the emitted changelog
(+I/+U add a row, -D/-U remove one) must equal a bounded recompute of the
join over the rows seen so far — the defining contract of the reference's
``StreamingJoinOperator`` (``flink-table-runtime-blink/.../join/stream/
StreamingJoinOperator.java:36``).
"""

from collections import Counter

import numpy as np
import pytest

from flink_tpu.core.batch import RecordBatch
from flink_tpu.operators.sql_ops import SqlJoinOperator, StreamingJoinOperator

LCOLS = ["k", "x"]
RCOLS = ["k2", "y"]
RENAME = {"k2": "k2", "y": "y"}


def lbatch(rows):
    return RecordBatch({"k": np.asarray([r[0] for r in rows], object),
                        "x": np.asarray([r[1] for r in rows], object)})


def rbatch(rows):
    return RecordBatch({"k2": np.asarray([r[0] for r in rows], object),
                        "y": np.asarray([r[1] for r in rows], object)})


def changelog_rows(elements):
    out = []
    for el in elements:
        if isinstance(el, RecordBatch):
            cols = list(el.columns)
            arrs = [np.asarray(el.column(c)) for c in cols]
            for i in range(len(el)):
                out.append({c: a[i] for c, a in zip(cols, arrs)})
    return out


def materialize(view: Counter, rows):
    """Apply changelog rows to the materialized multiset view."""
    for r in rows:
        op = r["op"]
        key = tuple((c, r[c]) for c in sorted(r) if c != "op")
        if op in ("+I", "+U"):
            view[key] += 1
        elif op in ("-D", "-U"):
            view[key] -= 1
            if view[key] == 0:
                del view[key]
        else:  # pragma: no cover
            raise AssertionError(f"bad op {op}")
    return view


def bounded_recompute(how, lrows, rrows):
    """Oracle: the bounded SqlJoinOperator over the same accumulated rows."""
    op = SqlJoinOperator("k", "k2", how, dict(RENAME),
                         left_columns=LCOLS, right_columns=RCOLS)
    if lrows:
        op.process_batch2(lbatch(lrows), 0)
    if rrows:
        op.process_batch2(rbatch(rrows), 1)
    out = Counter()
    for r in changelog_rows(op.end_input()):
        key = tuple((c, r[c]) for c in sorted(r))
        out[key] += 1
    return out


def strip_op_counter(view: Counter):
    return Counter(dict(view))


@pytest.mark.parametrize("how", ["inner", "left", "right", "full"])
def test_prefix_equivalence_append_only(how):
    """Interleaved append-only batches: after every batch the materialized
    changelog equals the bounded recompute of the prefix."""
    op = StreamingJoinOperator("k", "k2", how, dict(RENAME),
                               left_columns=LCOLS, right_columns=RCOLS)
    feed = [
        (0, [("a", 1), ("b", 2)]),
        (1, [("a", 10)]),
        (1, [("a", 11), ("c", 30)]),
        (0, [("a", 3), ("c", 4), ("c", 5)]),
        (1, [("b", 20), ("b", 21)]),
        (0, [("d", 6)]),
        (1, [("a", 12)]),
    ]
    view = Counter()
    lrows, rrows = [], []
    for side, rows in feed:
        (lrows if side == 0 else rrows).extend(rows)
        emitted = op.process_batch2(lbatch(rows) if side == 0
                                    else rbatch(rows), side)
        materialize(view, changelog_rows(emitted))
        assert view == bounded_recompute(how, lrows, rrows), \
            f"{how}: prefix mismatch after {side}:{rows}"


@pytest.mark.parametrize("how", ["inner", "left", "right", "full"])
def test_prefix_equivalence_with_retractions(how):
    """Changelog INPUT (op column with -D rows): the view tracks the net
    rows — retracting a row removes its joined rows and restores padding."""
    op = StreamingJoinOperator("k", "k2", how, dict(RENAME),
                               left_columns=LCOLS, right_columns=RCOLS)

    def lb(rows, ops):
        b = lbatch(rows)
        cols = dict(b.columns)
        cols["op"] = np.asarray(ops, object)
        return RecordBatch(cols)

    def rb(rows, ops):
        b = rbatch(rows)
        cols = dict(b.columns)
        cols["op"] = np.asarray(ops, object)
        return RecordBatch(cols)

    feed = [
        (0, [("a", 1), ("a", 2)], ["+I", "+I"]),
        (1, [("a", 10), ("b", 20)], ["+I", "+I"]),
        (0, [("a", 1)], ["-D"]),              # retract one left row
        (1, [("a", 10)], ["-D"]),             # retract its match
        (0, [("b", 3), ("a", 2)], ["+I", "-D"]),  # mixed batch
        (1, [("b", 20)], ["-U"]),             # -U folds to retract
        (1, [("c", 40)], ["+U"]),             # +U folds to accumulate
    ]
    view = Counter()
    net_l, net_r = Counter(), Counter()
    for side, rows, ops in feed:
        tgt = net_l if side == 0 else net_r
        for row, o in zip(rows, ops):
            if o in ("+I", "+U"):
                tgt[row] += 1
            else:
                tgt[row] -= 1
        emitted = op.process_batch2(lb(rows, ops) if side == 0
                                    else rb(rows, ops), side)
        materialize(view, changelog_rows(emitted))
        lrows = [r for r, c in net_l.items() for _ in range(c)]
        rrows = [r for r, c in net_r.items() for _ in range(c)]
        assert view == bounded_recompute(how, lrows, rrows), \
            f"{how}: mismatch after {side}:{list(zip(rows, ops))}"


def test_outer_padding_upgrade_downgrade_ops():
    """The null-padding transitions ride -U/+U: first match upgrades the
    padded row to a joined row; losing the last match downgrades back."""
    op = StreamingJoinOperator("k", "k2", "left", dict(RENAME),
                               left_columns=LCOLS, right_columns=RCOLS)
    first = changelog_rows(op.process_batch2(lbatch([("a", 1)]), 0))
    assert [r["op"] for r in first] == ["+I"]
    assert first[0]["y"] is None              # padded
    up = changelog_rows(op.process_batch2(rbatch([("a", 10)]), 1))
    assert [r["op"] for r in up] == ["-U", "+U"]
    assert up[0]["y"] is None and up[1]["y"] == 10
    down = changelog_rows(op.process_batch2(
        RecordBatch({"k2": np.asarray(["a"], object),
                     "y": np.asarray([10], object),
                     "op": np.asarray(["-D"], object)}), 1))
    assert [r["op"] for r in down] == ["-U", "+U"]
    assert down[0]["y"] == 10 and down[1]["y"] is None


def test_snapshot_restore_mid_join():
    """Kill-and-restore mid-stream: the restored operator continues the
    changelog exactly where the snapshot left off."""
    how = "full"
    op = StreamingJoinOperator("k", "k2", how, dict(RENAME),
                               left_columns=LCOLS, right_columns=RCOLS)
    view = Counter()
    materialize(view, changelog_rows(
        op.process_batch2(lbatch([("a", 1), ("b", 2)]), 0)))
    materialize(view, changelog_rows(
        op.process_batch2(rbatch([("a", 10)]), 1)))
    snap = op.snapshot_state()

    restored = StreamingJoinOperator("k", "k2", how, dict(RENAME),
                                     left_columns=LCOLS, right_columns=RCOLS)
    restored.restore_state(snap)
    materialize(view, changelog_rows(
        restored.process_batch2(rbatch([("b", 20), ("a", 11)]), 1)))
    materialize(view, changelog_rows(
        restored.process_batch2(lbatch([("a", 3)]), 0)))
    expected = bounded_recompute(
        how, [("a", 1), ("b", 2), ("a", 3)],
        [("a", 10), ("b", 20), ("a", 11)])
    assert view == expected


def test_state_ttl_expires_silently():
    op = StreamingJoinOperator("k", "k2", "inner", dict(RENAME),
                               left_columns=LCOLS, right_columns=RCOLS,
                               state_ttl_ms=10_000)
    op.process_batch2(lbatch([("a", 1)]), 0)
    # age the stored left row past the TTL
    op._left.ts = [t - 60_000 for t in op._left.ts]
    out = changelog_rows(op.process_batch2(rbatch([("a", 10)]), 1))
    assert out == []                      # expired row no longer joins
    out2 = changelog_rows(op.process_batch2(lbatch([("a", 2)]), 0))
    assert [r["op"] for r in out2] == ["+I"]  # fresh rows still join


# ---------------------------------------------------------------------------
# SQL-level wiring
# ---------------------------------------------------------------------------


def _collect_changelog(sql, bounded_left, bounded_right):
    from flink_tpu.sql.table_env import TableEnvironment
    tenv = TableEnvironment()
    tenv.register_collection(
        "orders", columns={"k": np.asarray(["a", "b", "a"], object),
                           "x": np.asarray([1, 2, 3], object)},
        batch_size=2, bounded=bounded_left)
    tenv.register_collection(
        "rates", columns={"k2": np.asarray(["a", "c"], object),
                          "y": np.asarray([10, 30], object)},
        batch_size=1, bounded=bounded_right)
    return tenv, tenv.execute_sql(sql)


def test_sql_unbounded_join_emits_changelog():
    tenv, res = _collect_changelog(
        "SELECT o.k, o.x, r.y FROM orders o JOIN rates r ON o.k = r.k2",
        bounded_left=False, bounded_right=False)
    rows = res.collect()
    assert all(r["op"] in ("+I", "-U", "+U", "-D") for r in rows)
    view = Counter()
    materialize(view, rows)
    final = {tuple(sorted(dict(k).items())) for k in view}
    assert final == {(("k", "a"), ("x", 1), ("y", 10)),
                     (("k", "a"), ("x", 3), ("y", 10))}
    assert res.output_columns[0] == "op"


def test_sql_unbounded_left_join_materializes_like_bounded():
    sql = ("SELECT o.k, o.x, r.y FROM orders o "
           "LEFT JOIN rates r ON o.k = r.k2")
    _, stream_res = _collect_changelog(sql, False, False)
    view = Counter()
    materialize(view, stream_res.collect())
    _, bounded_res = _collect_changelog(sql, True, True)
    bview = Counter()
    for r in bounded_res.collect():
        key = tuple((c, r[c]) for c in sorted(r))
        bview[key] += 1
    final = Counter()
    for k, c in view.items():
        final[k] += c
    assert final == bview


def test_sql_bounded_join_keeps_batch_path():
    _, res = _collect_changelog(
        "SELECT o.k, o.x, r.y FROM orders o JOIN rates r ON o.k = r.k2",
        bounded_left=True, bounded_right=True)
    rows = res.collect()
    assert "op" not in res.output_columns
    assert sorted((r["k"], r["x"], r["y"]) for r in rows) == \
        [("a", 1, 10), ("a", 3, 10)]


def test_sql_unbounded_join_rejects_aggregates_and_order():
    from flink_tpu.sql.planner import PlanError
    with pytest.raises(PlanError, match="aggregates over an unbounded"):
        _collect_changelog(
            "SELECT SUM(o.x) FROM orders o JOIN rates r ON o.k = r.k2",
            False, False)[1].collect()
    with pytest.raises(PlanError, match="ORDER BY / LIMIT"):
        _collect_changelog(
            "SELECT o.k FROM orders o JOIN rates r ON o.k = r.k2 "
            "ORDER BY o.k", False, False)[1].collect()


def _tenv_three_tables(bounded):
    from flink_tpu.sql.table_env import TableEnvironment
    tenv = TableEnvironment()
    tenv.register_collection(
        "orders", columns={"k": np.asarray(["a", "b", "a"], object),
                           "x": np.asarray([1, 2, 3], object)},
        batch_size=2, bounded=bounded)
    tenv.register_collection(
        "rates", columns={"k2": np.asarray(["a", "c"], object),
                          "y": np.asarray([10, 30], object)},
        batch_size=1, bounded=bounded)
    tenv.register_collection(
        "m", columns={"k3": np.asarray(["a", "b"], object),
                      "z": np.asarray([100, 200], object)})
    return tenv


def test_union_branch_does_not_leak_changelog_flag():
    """A changelog branch planned before a plain branch must not poison the
    plain branch's planning (the _changelog_join flag is per-plan state)."""
    from flink_tpu.sql.planner import PlanError
    tenv = _tenv_three_tables(bounded=False)
    # changelog branch emits op + 3 cols, plain branch 3 cols: the honest
    # error is the column-count mismatch, NOT an 'unknown column op' crash
    with pytest.raises(PlanError, match="column count"):
        tenv.execute_sql(
            "SELECT o.k, o.x, r.y FROM orders o JOIN rates r ON o.k = r.k2 "
            "UNION ALL SELECT k3, z, z FROM m").collect()
    # and a plain query planned AFTER a changelog one stays plain
    rows = tenv.execute_sql("SELECT k3, z FROM m").collect()
    assert sorted(r["k3"] for r in rows) == ["a", "b"]


def test_subquery_preserves_unboundedness():
    """An unbounded changelog subquery joined again must plan a second
    STREAMING join that folds the inner retractions — not the end-of-input
    batch join (which would treat -U rows as data and never emit)."""
    sql = ("SELECT s.k, s.x, s.y, m.z FROM "
           "(SELECT o.k, o.x, r.y FROM orders o "
           "LEFT JOIN rates r ON o.k = r.k2) s "
           "JOIN m ON s.k = m.k3")
    stream_rows = _tenv_three_tables(False).execute_sql(sql).collect()
    assert stream_rows and all("op" in r for r in stream_rows)
    view = Counter()
    materialize(view, stream_rows)
    bounded_rows = _tenv_three_tables(True).execute_sql(sql).collect()
    bview = Counter()
    for r in bounded_rows:
        bview[tuple((c, r[c]) for c in sorted(r))] += 1
    assert view == bview


def test_aggregate_over_changelog_subquery_rejected():
    from flink_tpu.sql.planner import PlanError
    tenv = _tenv_three_tables(bounded=False)
    with pytest.raises(PlanError, match="unbounded streaming JOIN"):
        tenv.execute_sql(
            "SELECT SUM(x) FROM (SELECT o.k, o.x, r.y FROM orders o "
            "JOIN rates r ON o.k = r.k2) s").collect()


def test_view_preserves_changelog_trait():
    from flink_tpu.sql.planner import PlanError
    tenv = _tenv_three_tables(bounded=False)
    tenv.create_temporary_view(
        "joined", tenv.sql_query(
            "SELECT o.k, o.x, r.y FROM orders o JOIN rates r ON o.k = r.k2"))
    assert tenv._catalog["joined"].changelog
    assert not tenv._catalog["joined"].bounded
    with pytest.raises(PlanError, match="unbounded streaming JOIN"):
        tenv.execute_sql("SELECT SUM(x) FROM joined").collect()
    rows = tenv.execute_sql("SELECT k, x, y FROM joined").collect()
    view = Counter()
    materialize(view, rows)
    final = {tuple(sorted(dict(k).items())) for k in view}
    assert final == {(("k", "a"), ("x", 1), ("y", 10)),
                     (("k", "a"), ("x", 3), ("y", 10))}


def test_sql_explain_shows_streaming_join():
    from flink_tpu.sql.table_env import TableEnvironment
    tenv = TableEnvironment()
    tenv.register_collection("l", columns={"k": np.asarray([1, 2])},
                             bounded=False)
    tenv.register_collection("r", columns={"k2": np.asarray([1, 3])})
    plan = tenv.explain_sql("SELECT l.k FROM l JOIN r ON l.k = r.k2")
    assert "sql-streaming-join" in plan
