"""count_window(size, slide) — the r3 documented rejection, now
implemented (WindowedStream.countWindow(size, slide) analog: CountTrigger
+ CountEvictor as a per-key value ring with mini-batch fires)."""

import numpy as np
import pytest

from flink_tpu.core.batch import RecordBatch
from flink_tpu.core.functions import (AvgAggregator, MaxAggregator,
                                      RuntimeContext, SumAggregator)
from flink_tpu.operators.count_window import CountSlideWindowOperator


def _mk(agg=None, size=4, slide=2):
    op = CountSlideWindowOperator(agg or SumAggregator(np.float64),
                                  key_column="k", value_column="v",
                                  size=size, slide=slide)
    op.open(RuntimeContext())
    return op


def _feed(op, keys, vals):
    return op.process_batch(RecordBatch(
        {"k": np.asarray(keys, np.int64),
         "v": np.asarray(vals, np.float64)}))


def _rows(out):
    rows = []
    for b in out:
        if hasattr(b, "columns"):
            for i in range(len(b)):
                rows.append((int(np.asarray(b.column("k"))[i]),
                             float(np.asarray(b.column("result"))[i])))
    return sorted(rows)


def test_fires_every_slide_over_last_size():
    op = _mk(size=4, slide=2)
    # key 1 arrivals one per batch (per-record fire granularity)
    outs = []
    for v in [1, 2, 3, 4, 5, 6]:
        outs.append(_rows(_feed(op, [1], [v])))
    # fires at counts 2, 4, 6 with sum of last min(count,4) values
    assert outs == [[], [(1, 3.0)], [], [(1, 10.0)], [], [(1, 18.0)]]


def test_ring_laps_within_one_batch():
    # 7 values for one key in ONE batch with size 3: ring holds last 3
    op = _mk(size=3, slide=7)
    out = _rows(_feed(op, [1] * 7, [1, 2, 3, 4, 5, 6, 7]))
    assert out == [(1, 5.0 + 6.0 + 7.0)]


def test_multiple_keys_vectorized():
    rng = np.random.default_rng(5)
    op = _mk(size=5, slide=5)
    keys = rng.integers(0, 10, 500)
    vals = rng.random(500)
    got = []
    for lo in range(0, 500, 50):
        got += _rows(_feed(op, keys[lo:lo + 50], vals[lo:lo + 50]))
    # oracle: per key, every 5th arrival (at mini-batch boundaries it can
    # fire once covering several multiples) sums the last 5 values — check
    # the FINAL fire per key against the last-5 oracle at its fired count
    per_key = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        per_key.setdefault(k, []).append(v)
    # weaker invariant robust to mini-batch coalescing: every emitted sum
    # equals the sum of SOME contiguous 5-suffix of the key's prefix
    for k, s in got:
        seq = per_key[k]
        suffixes = {round(sum(seq[max(0, i - 5):i]), 6)
                    for i in range(1, len(seq) + 1)}
        assert round(s, 6) in suffixes, (k, s)
    assert got, "no fires"


def test_avg_and_max():
    op = _mk(agg=AvgAggregator(np.float32), size=3, slide=3)
    out = _rows(_feed(op, [2] * 3, [3, 6, 9]))
    assert out == [(2, 6.0)]
    op2 = _mk(agg=MaxAggregator(np.float64), size=2, slide=2)
    out2 = _rows(_feed(op2, [1] * 2, [5, 1]))     # fire: max(5, 1)
    out2 += _rows(_feed(op2, [1] * 2, [2, 3]))    # fire: max(2, 3)
    assert out2 == [(1, 5.0), (1, 3.0)]
    # mini-batch coalescing: both multiples in ONE batch fire once with
    # the latest ring (documented semantics)
    op3 = _mk(agg=MaxAggregator(np.float64), size=2, slide=2)
    assert _rows(_feed(op3, [1] * 4, [5, 1, 2, 3])) == [(1, 3.0)]


def test_snapshot_restore():
    op = _mk(size=4, slide=2)
    _feed(op, [1, 1, 1], [1, 2, 3])      # fired at 2; count 3
    snap = op.snapshot_state()
    op2 = _mk(size=4, slide=2)
    op2.restore_state(snap)
    out = _rows(_feed(op2, [1], [4]))    # count 4 -> fire sum(1..4)
    assert out == [(1, 10.0)]


def test_api_end_to_end():
    from flink_tpu.datastream import StreamExecutionEnvironment

    env = StreamExecutionEnvironment()
    n = 1000
    rng = np.random.default_rng(2)
    rows = (env.from_collection(columns={
        "k": rng.integers(0, 7, n), "v": np.ones(n)})
        .key_by("k").count_window(10, 5).sum("v")
        .execute_and_collect())
    assert rows
    # every fire sums at most the last 10 ones
    assert all(0 < float(r["v"]) <= 10.0 for r in rows)


def test_requires_host_twins():
    from flink_tpu.core.functions import LambdaReduce
    with pytest.raises(ValueError, match="numpy twins"):
        CountSlideWindowOperator(LambdaReduce(lambda a, b: a + b, 0.0),
                                 key_column="k", value_column="v",
                                 size=3, slide=1)


def test_lambda_reduce_rejected_eagerly():
    """API-call-time rejection (not execute-time): a bare lambda reduce has
    no numpy twins for the ring combine."""
    from flink_tpu.datastream import StreamExecutionEnvironment

    env = StreamExecutionEnvironment()
    ks = (env.from_collection(columns={"k": np.zeros(1, np.int64),
                                       "v": np.zeros(1)})
          .key_by("k"))
    with pytest.raises(ValueError, match="numpy twins"):
        ks.count_window(4, 2).reduce(lambda a, b: a + b, 0.0,
                                     value_column="v")
    with pytest.raises(ValueError, match="positive"):
        ks.count_window(4, 0).sum("v")
