"""Transport-adaptive device sync (``WindowAggOperator(device_sync=...)``).

On taxed transports (tunneled devices where executing a dispatched update
step costs the host tens of CPU-ms per uploaded MB) the host emit tier
defers per-batch device syncs and refreshes the replica at sync points
instead (``utils/transport.py``).  These tests pin the contract:

- deferred and scatter cadences produce IDENTICAL fires and snapshots
  (the mirror is the same; only the replica's freshness differs);
- ``device_refresh`` rebuilds the replica exactly (verified by the same
  download-and-compare as scatter mode's continuous check);
- snapshots taken under deferred sync restore into either cadence;
- the auto cadence is deterministic on the CPU backend (scatter — there
  is no transport to dodge) and the calibration verdict is min-filtered
  (compile noise cannot tip it).

Reference role: the HeapKeyedStateBackend never mirrors to an accelerator
at all; the deferred cadence is the TPU-native analog of its
"authoritative host state + periodic materialization" shape, with the
device engaged per-batch only where the link makes that free.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from flink_tpu.core.batch import RecordBatch, Watermark
from flink_tpu.core.functions import RuntimeContext, SumAggregator
from flink_tpu.operators.window_agg import WindowAggOperator
from flink_tpu.utils import transport
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


@pytest.fixture(autouse=True)
def _isolate_transport_calibration():
    transport.reset()
    yield
    transport.reset()


def make_op(device_sync: str, **kw):
    op = WindowAggOperator(
        TumblingEventTimeWindows.of(100), SumAggregator(jnp.float32),
        key_column="k", value_column="v", emit_tier="host",
        snapshot_source="mirror", device_sync=device_sync, **kw)
    op.open(RuntimeContext())
    return op


def batches_for(seed: int, nbatches: int = 8, nkeys: int = 300,
                b: int = 400):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(nbatches):
        keys = rng.integers(0, nkeys, b).astype(np.int64)
        vals = rng.random(b).astype(np.float32)
        ts = np.sort(rng.integers(i * 60, i * 60 + 60, b)).astype(np.int64)
        out.append((keys, vals, ts))
    return out


def feed(op, batches):
    fired = []
    for keys, vals, ts in batches:
        fired += op.process_batch(
            RecordBatch({"k": keys, "v": vals}, timestamps=ts))
        fired += op.process_watermark(Watermark(int(ts.max()) - 1))
    fired += op.end_input()
    return fired


def fires_table(fired):
    """(window_start, key) -> result, for order-insensitive comparison."""
    table = {}
    for fb in fired:
        ws = np.asarray(fb.column("window_start"))
        ks = np.asarray(fb.column("k"))
        rs = np.asarray(fb.column("result"), np.float64)
        for w, k, r in zip(ws.tolist(), ks.tolist(), rs.tolist()):
            table[(w, k)] = table.get((w, k), 0.0) + r
    return table


def assert_same_fires(a, b):
    ta, tb = fires_table(a), fires_table(b)
    assert ta.keys() == tb.keys()
    for k in ta:
        assert ta[k] == pytest.approx(tb[k], rel=1e-5), k


class TestDeferredSync:
    def test_deferred_equals_scatter(self):
        batches = batches_for(7)
        scatter = feed(make_op("scatter"), batches)
        deferred = feed(make_op("deferred"), batches)
        assert len(deferred) > 0
        assert_same_fires(scatter, deferred)

    def test_deferred_equals_scatter_numpy_mirror(self):
        # native_emit=False pins the numpy mirror: same cadence contract
        batches = batches_for(11)
        scatter = feed(make_op("scatter", native_emit=False), batches)
        deferred = feed(make_op("deferred", native_emit=False), batches)
        assert_same_fires(scatter, deferred)

    def test_refresh_then_verify(self):
        op = make_op("deferred")
        batches = batches_for(3, nbatches=4)
        for keys, vals, ts in batches:
            op.process_batch(RecordBatch({"k": keys, "v": vals},
                                         timestamps=ts))
            op.process_watermark(Watermark(int(ts.max()) - 1))
        assert op._device_stale          # replica lags between sync points
        assert op.verify_mirror()        # refreshes, downloads, compares
        assert not op._device_stale
        assert op.phase_bytes.get("h2d_refresh", 0) > 0
        # idempotent: a second refresh is a no-op
        before = op.phase_bytes["h2d_refresh"]
        op.device_refresh()
        assert op.phase_bytes["h2d_refresh"] == before

    def test_refresh_with_negative_panes_straddling_zero(self):
        """Regression: ``max_pane == 0`` with a negative ``pane_base`` must
        refresh every pane — a falsy-zero guard used to skip panes
        pane_base+1..0, leaving the replica wrong after refresh."""
        op = make_op("deferred")
        rng = np.random.default_rng(3)
        keys = rng.integers(0, 50, 300).astype(np.int64)
        vals = rng.random(300).astype(np.float32)
        ts = np.sort(rng.integers(-300, 50, 300)).astype(np.int64)
        op.process_batch(RecordBatch({"k": keys, "v": vals}, timestamps=ts))
        assert op.pane_base < 0 and op.max_pane == 0
        assert op.verify_mirror()

    def test_refresh_covers_expirations(self):
        """Pane expiry under deferred sync skips the in-line device clear;
        the refresh must still produce an identity ring slot for it."""
        op = make_op("deferred")
        batches = batches_for(5, nbatches=10)
        feed(op, batches[:-1])  # end_input not called; plenty expired
        assert op.verify_mirror()

    def test_snapshot_restore_across_cadences(self):
        batches = batches_for(13)
        cut = 4
        # reference: uninterrupted run, capturing only post-cut fires
        ref = make_op("deferred")
        for keys, vals, ts in batches[:cut]:
            ref.process_batch(RecordBatch({"k": keys, "v": vals},
                                          timestamps=ts))
            ref.process_watermark(Watermark(int(ts.max()) - 1))
        post = fires_table(feed(ref, batches[cut:]))

        src = make_op("deferred")
        for keys, vals, ts in batches[:cut]:
            src.process_batch(RecordBatch({"k": keys, "v": vals},
                                          timestamps=ts))
            src.process_watermark(Watermark(int(ts.max()) - 1))
        snap = src.snapshot_state()
        for target_mode in ("deferred", "scatter"):
            op = make_op(target_mode)
            op.restore_state(snap)
            got = fires_table(feed(op, batches[cut:]))
            assert got.keys() == post.keys()
            for k in got:
                assert got[k] == pytest.approx(post[k], rel=1e-5), \
                    (target_mode, k)
            assert op.verify_mirror()

    def test_deferred_requires_host_tier(self):
        with pytest.raises(ValueError, match="host emit"):
            WindowAggOperator(
                TumblingEventTimeWindows.of(100),
                SumAggregator(jnp.float32), key_column="k",
                value_column="v", emit_tier="device",
                device_sync="deferred")
        with pytest.raises(ValueError, match="snapshot_source"):
            WindowAggOperator(
                TumblingEventTimeWindows.of(100),
                SumAggregator(jnp.float32), key_column="k",
                value_column="v", emit_tier="host",
                snapshot_source="device", device_sync="deferred")
        with pytest.raises(ValueError, match="auto|scatter|deferred"):
            make_op("sometimes")


class TestAutoResolution:
    def test_auto_on_cpu_backend_small_batches_settle_scatter(self):
        """The CPU backend calibrates like any other (its XLA dispatch
        compute IS the transport cost), but unit-sized batches never yield
        a sample (transport.MIN_SAMPLE_MB) — auto must settle on scatter
        after the bounded probe, keeping small-traffic CPU behavior
        deterministic."""
        op = make_op("auto")
        for keys, vals, ts in batches_for(1, nbatches=10):
            op.process_batch(RecordBatch({"k": keys, "v": vals},
                                         timestamps=ts))
            op.process_watermark(Watermark(int(ts.max()) - 1))
        assert transport.dispatch_taxed() is None
        assert op.device_sync_mode == "scatter"

    def test_calibration_gives_up_to_scatter(self, monkeypatch):
        """Sub-MB batches can never produce a calibration sample; auto must
        settle on plain scatter after a bounded number of measured batches
        instead of blocking the pipeline on until-ready forever."""
        import jax
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        transport.reset()
        op = make_op("auto")
        for keys, vals, ts in batches_for(4, nbatches=10, b=300):
            op.process_batch(RecordBatch({"k": keys, "v": vals},
                                         timestamps=ts))
            op.process_watermark(Watermark(int(ts.max()) - 1))
        assert transport.dispatch_taxed() is None  # tiny uploads: no sample
        assert op.device_sync_mode == "scatter"

    def test_pinned_verdict_resolves_auto(self, monkeypatch):
        # simulate an accelerator backend with a taxed-link verdict
        import jax
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        transport.reset(verdict=True)
        op = make_op("auto")
        keys, vals, ts = batches_for(2, nbatches=1)[0]
        op.process_batch(RecordBatch({"k": keys, "v": vals}, timestamps=ts))
        assert op.device_sync_mode == "deferred"
        assert op._device_stale
        transport.reset(verdict=False)
        op2 = make_op("auto")
        op2.process_batch(RecordBatch({"k": keys, "v": vals},
                                      timestamps=ts))
        assert op2.device_sync_mode == "scatter"


class TestCalibration:
    def test_verdict_uses_min_sample(self):
        # first sample carries compile time (slow); the min must win
        transport.reset()
        transport.record_dispatch_cost(1.0, 5.0)      # 5000 ms/MB: compile
        transport.record_dispatch_cost(1.0, 0.001)    # 1 ms/MB
        assert transport.dispatch_taxed() is None     # needs 3 samples
        transport.record_dispatch_cost(1.0, 0.002)
        assert transport.dispatch_taxed() is False
        assert transport.dispatch_ms_per_mb() == pytest.approx(1.0)

    def test_taxed_verdict(self):
        transport.reset()
        for _ in range(3):
            transport.record_dispatch_cost(2.0, 0.08)  # 40 ms/MB
        assert transport.dispatch_taxed() is True

    def test_tiny_samples_never_calibrate(self):
        """Sub-MB uploads read fixed dispatch latency as per-MB cost; they
        must not freeze a false taxed verdict (tiny-batch workloads keep
        the safe scatter default instead)."""
        transport.reset()
        for _ in range(10):
            transport.record_dispatch_cost(0.001, 0.001)  # "1000 ms/MB"
        assert transport.dispatch_taxed() is None
