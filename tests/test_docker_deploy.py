"""Container glue rendering (``deploy/docker.py`` — flink-container
analog).  The docker daemon is absent here, so the contract is the
rendered artifacts: structurally valid, role dispatch correct (the
entrypoint runs under sh), compose parses as YAML-shaped config."""

from __future__ import annotations

import os
import subprocess

from flink_tpu.deploy.docker import (render_compose, render_dockerfile,
                                     render_entrypoint, write_context)


class TestRendering:
    def test_dockerfile_structure(self):
        df = render_dockerfile(python="3.12", extras=["pyarrow"])
        assert df.startswith("FROM python:3.12-slim")
        assert "COPY flink_tpu ./flink_tpu" in df
        assert "COPY native ./native" in df          # C++ sources ship
        assert "pip install --no-cache-dir pyarrow" in df
        assert "USER flink" in df                    # non-root
        assert 'ENTRYPOINT ["/docker-entrypoint.sh"]' in df

    def test_entrypoint_dispatches_roles(self, tmp_path):
        """Run the REAL script under sh with a stubbed python on PATH:
        each role must exec the right module invocation."""
        script = tmp_path / "docker-entrypoint.sh"
        script.write_text(render_entrypoint())
        script.chmod(0o755)
        stub = tmp_path / "python"
        stub.write_text("#!/bin/sh\necho ARGS:$@\n")
        stub.chmod(0o755)
        env = dict(os.environ, PATH=f"{tmp_path}:{os.environ['PATH']}")

        def run(*args):
            return subprocess.run(["sh", str(script), *args], env=env,
                                  capture_output=True, text=True).stdout

        assert "ARGS:-m flink_tpu coordinate --port 9"\
            in run("coordinate", "--port", "9")
        assert "ARGS:-m flink_tpu worker --coordinator c:1" \
            in run("worker", "--coordinator", "c:1")
        assert "ARGS:-m flink_tpu sql" in run("sql")
        # arbitrary command passthrough (debug shells)
        assert "hello" in run("echo", "hello")

    def test_compose_structure(self):
        text = render_compose("examples.job:build", n_workers=3,
                              environment={"TPU_CHIPS": "0"})
        # one service per worker index (compose replicas can't vary args)
        for i in range(3):
            assert f"worker-{i}:" in text
            assert f'"--index", "{i}"' in text
        assert 'command: ["coordinate", "--job", "examples.job:build"' in text
        assert 'TPU_CHIPS: "0"' in text
        assert 'FLINK_TPU_ALLOW_INSECURE: "1"' in text  # non-loopback guard
        assert text.count("checkpoints:/checkpoints") == 4  # shared volume
        try:
            import yaml  # noqa: F401
        except ImportError:
            return
        parsed = yaml.safe_load(text)
        assert set(parsed["services"]) == {"coordinator", "worker-0",
                                           "worker-1", "worker-2"}

    def test_rendered_commands_parse_with_the_real_cli(self):
        """The role commands must be valid for flink_tpu.__main__'s actual
        argparse surface — spelling-level assertions let invalid flags
        ship green."""
        from flink_tpu.deploy.docker import (coordinator_command,
                                             worker_command)
        from flink_tpu.__main__ import build_parser

        parser = build_parser()
        c = coordinator_command("my.job:build", 3, 6123, "/checkpoints")
        args = parser.parse_args(c)
        assert args.job == "my.job:build" and args.workers == 3
        assert args.listen == "0.0.0.0:6123"
        w = worker_command(1, "my.job:build", 3, "coordinator:6123")
        args = parser.parse_args(w)
        assert args.index == 1 and args.coordinator == "coordinator:6123"
        assert args.advertise == "worker-1"

    def test_write_context_is_self_contained(self, tmp_path):
        """Every path the Dockerfile COPYs must exist in the context —
        otherwise ``docker build <dir>`` fails at the first COPY."""
        ctx = str(tmp_path / "ctx")
        write_context(ctx, job="my.job:build")
        df = open(os.path.join(ctx, "Dockerfile")).read()
        import re
        for line in re.findall(r"^COPY (.+?) (?:\./|/)", df, re.M):
            for src in line.split():
                assert os.path.exists(os.path.join(ctx, src)), \
                    f"Dockerfile COPYs {src} but the context lacks it"
        assert os.path.isfile(os.path.join(ctx, "flink_tpu",
                                           "__init__.py"))
        assert os.path.isfile(os.path.join(ctx, "native",
                                           "flink_native.cc"))
        assert os.access(os.path.join(ctx, "docker-entrypoint.sh"),
                         os.X_OK)

    def test_compose_worker_waits_for_healthy_coordinator(self):
        text = render_compose("j:build", n_workers=1)
        assert "condition: service_healthy" in text
        assert "restart: on-failure" in text

    def test_yaml_escaping(self):
        text = render_compose('we"ird:build', n_workers=1,
                              environment={"OPTS": 'x"y\\z'})
        assert '"we\\"ird:build"' in text
        assert 'OPTS: "x\\"y\\\\z"' in text

    def test_entrypoint_covers_every_cli_subcommand(self, tmp_path):
        """Each real subcommand must dispatch through python -m flink_tpu,
        not fall into the arbitrary-exec arm."""
        from flink_tpu.__main__ import build_parser

        subs = build_parser()._subparsers._group_actions[0].choices
        script = tmp_path / "ep.sh"
        script.write_text(render_entrypoint())
        stub = tmp_path / "python"
        stub.write_text("#!/bin/sh\necho VIA_MODULE:$@\n")
        stub.chmod(0o755)
        env = dict(os.environ, PATH=f"{tmp_path}:{os.environ['PATH']}")
        for name in subs:
            out = subprocess.run(["sh", str(script), name], env=env,
                                 capture_output=True, text=True).stdout
            assert f"VIA_MODULE:-m flink_tpu {name}" in out, name
