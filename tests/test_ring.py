"""Ring combine over the device mesh (sequence/context-parallel window
fires — the ring-attention communication pattern)."""

import numpy as np
import pytest

from flink_tpu.parallel.mesh import make_mesh
from flink_tpu.parallel.ring import (make_ring_all_reduce_sum,
                                     make_ring_combine,
                                     sharded_pane_window_total)


def _mesh8():
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    return make_mesh(8)


def test_ring_combine_sum_monoid():
    import jax.numpy as jnp

    mesh = _mesh8()
    D = mesh.devices.size

    def combine(a, b):
        return tuple(x + y for x, y in zip(a, b))

    ring = make_ring_combine(mesh, combine, num_leaves=1)
    # one partial row per device: [D, K] sharded over devices
    parts = np.arange(D * 4, dtype=np.float32).reshape(D, 4)
    (out,) = ring(jnp.asarray(parts))
    # every device row holds the SUM over all partials
    expect = parts.sum(axis=0)
    for d in range(D):
        np.testing.assert_allclose(np.asarray(out)[d], expect, rtol=1e-6)


def test_ring_combine_max_monoid():
    """A second commutative monoid (max) beyond sum; NOTE the ring requires
    commutativity (AggregateFunction.combine contract) — partials arrive in
    per-device cyclic order, so order-sensitive combines are unsupported."""
    import jax.numpy as jnp

    mesh = _mesh8()
    D = mesh.devices.size

    def combine(a, b):
        return tuple(np.maximum(x, y) if isinstance(x, np.ndarray)
                     else jnp.maximum(x, y) for x, y in zip(a, b))

    ring = make_ring_combine(mesh, combine, num_leaves=1)
    rng = np.random.default_rng(3)
    parts = rng.random((D, 5)).astype(np.float32)
    (out,) = ring(jnp.asarray(parts))
    np.testing.assert_allclose(np.asarray(out)[0], parts.max(axis=0),
                               rtol=1e-6)


def test_ring_all_reduce_sum():
    import jax.numpy as jnp

    mesh = _mesh8()
    D = mesh.devices.size
    f = make_ring_all_reduce_sum(mesh)
    x = np.ones((D, 3), np.float32)
    out = np.asarray(f(jnp.asarray(x)))
    np.testing.assert_allclose(out, np.full((D, 3), D, np.float32))


def test_sequence_parallel_window_total():
    """Pane axis sharded across chips: the window total equals the
    single-chip combine (blockwise partials + ring)."""
    import jax.numpy as jnp

    mesh = _mesh8()
    D = mesh.devices.size
    K, panes_per_dev = 16, 4

    def combine(a, b):
        return tuple(x + y for x, y in zip(a, b))

    fire = sharded_pane_window_total(mesh, combine, num_leaves=1)
    rng = np.random.default_rng(4)
    # [D, K, panes_local]: each device owns a slice of the window's panes
    state = rng.random((D, K, panes_per_dev)).astype(np.float32)
    (out,) = fire(jnp.asarray(state))
    # expected: sum over ALL D*panes_per_dev panes per key
    expect = state.sum(axis=(0, 2))
    for d in range(D):
        np.testing.assert_allclose(np.asarray(out)[d], expect, rtol=1e-5)
