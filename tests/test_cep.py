"""CEP tests, modeled on the reference's NFA/CEP ITCases
(``flink-libraries/flink-cep/src/test/.../NFAITCase.java``): feed keyed
event streams through patterns, assert the matched event sets."""

import numpy as np
import pytest

from flink_tpu.cep import CEP, AfterMatchSkipStrategy, Pattern
from flink_tpu.datastream.api import StreamExecutionEnvironment


def run_pattern(pattern, rows, select_fn, key="k"):
    env = StreamExecutionEnvironment()
    stream = (env.from_collection(rows, timestamp_column="ts")
              .assign_timestamps_and_watermarks(0, timestamp_column="ts")
              .key_by(key))
    sink = CEP.pattern(stream, pattern).select(select_fn).collect()
    env.execute("cep")
    return [{k: v for k, v in r.items() if k != "__ts__"}
            for r in sink.rows()]


def test_followed_by_basic():
    pat = (Pattern.begin("start")
           .where(lambda c: np.asarray(c["type"]) == "a")
           .followed_by("end")
           .where(lambda c: np.asarray(c["type"]) == "b"))
    rows = [
        {"k": "u", "type": "a", "v": 1, "ts": 1},
        {"k": "u", "type": "x", "v": 2, "ts": 2},
        {"k": "u", "type": "b", "v": 3, "ts": 3},
        {"k": "w", "type": "b", "v": 9, "ts": 4},  # no 'a' before: no match
    ]
    out = run_pattern(pat, rows, lambda m: {
        "k": m["start"][0]["k"],
        "sv": m["start"][0]["v"], "ev": m["end"][0]["v"]})
    assert out == [{"k": "u", "sv": 1, "ev": 3}]


def test_next_strict_contiguity():
    pat = (Pattern.begin("a").where(lambda c: np.asarray(c["t"]) == "a")
           .next("b").where(lambda c: np.asarray(c["t"]) == "b"))
    rows = [
        {"k": 1, "t": "a", "ts": 1}, {"k": 1, "t": "x", "ts": 2},
        {"k": 1, "t": "b", "ts": 3},   # NOT adjacent to the 'a': no match
        {"k": 1, "t": "a", "ts": 4}, {"k": 1, "t": "b", "ts": 5},  # match
    ]
    out = run_pattern(pat, rows, lambda m: {
        "at": m["a"][0]["ts"], "bt": m["b"][0]["ts"]})
    assert out == [{"at": 4, "bt": 5}]


def test_times_quantifier():
    pat = (Pattern.begin("a").where(lambda c: np.asarray(c["t"]) == "a")
           .times(2)
           .followed_by("b").where(lambda c: np.asarray(c["t"]) == "b"))
    rows = [{"k": 0, "t": "a", "ts": 1}, {"k": 0, "t": "a", "ts": 2},
            {"k": 0, "t": "b", "ts": 3}]
    out = run_pattern(pat, rows, lambda m: {
        "n_a": len(m["a"]), "bt": m["b"][0]["ts"]})
    assert {"n_a": 2, "bt": 3} in out


def test_one_or_more():
    pat = (Pattern.begin("a").where(lambda c: np.asarray(c["t"]) == "a")
           .one_or_more()
           .followed_by("b").where(lambda c: np.asarray(c["t"]) == "b"))
    rows = [{"k": 0, "t": "a", "ts": 1}, {"k": 0, "t": "a", "ts": 2},
            {"k": 0, "t": "b", "ts": 3}]
    out = run_pattern(pat, rows, lambda m: {"n_a": len(m["a"])})
    # 'a'@1, 'a'@2, and 'a a' can each be followed by b
    assert sorted(r["n_a"] for r in out) == [1, 1, 2]


def test_optional_stage():
    pat = (Pattern.begin("a").where(lambda c: np.asarray(c["t"]) == "a")
           .followed_by("mid").where(lambda c: np.asarray(c["t"]) == "m")
           .optional()
           .followed_by("b").where(lambda c: np.asarray(c["t"]) == "b"))
    rows = [{"k": 0, "t": "a", "ts": 1}, {"k": 0, "t": "b", "ts": 2}]
    out = run_pattern(pat, rows, lambda m: {
        "has_mid": "mid" in m})
    assert {"has_mid": False} in out


def test_within_window():
    pat = (Pattern.begin("a").where(lambda c: np.asarray(c["t"]) == "a")
           .followed_by("b").where(lambda c: np.asarray(c["t"]) == "b")
           .within(10))
    rows = [{"k": 0, "t": "a", "ts": 0}, {"k": 0, "t": "b", "ts": 50},
            {"k": 0, "t": "a", "ts": 60}, {"k": 0, "t": "b", "ts": 65}]
    out = run_pattern(pat, rows, lambda m: {
        "at": m["a"][0]["ts"], "bt": m["b"][0]["ts"]})
    assert out == [{"at": 60, "bt": 65}]


def test_skip_past_last_event():
    pat = (Pattern.begin("a", skip_strategy=AfterMatchSkipStrategy.SKIP_PAST_LAST_EVENT)
           .where(lambda c: np.asarray(c["t"]) == "a")
           .followed_by("b").where(lambda c: np.asarray(c["t"]) == "b"))
    rows = [{"k": 0, "t": "a", "ts": 1}, {"k": 0, "t": "a", "ts": 2},
            {"k": 0, "t": "b", "ts": 3}, {"k": 0, "t": "b", "ts": 4}]
    out = run_pattern(pat, rows, lambda m: {
        "at": m["a"][0]["ts"], "bt": m["b"][0]["ts"]})
    # NO_SKIP would give 3 matches (a1-b3, a2-b3 under relaxed_any? no —
    # followedBy gives a1-b3, a2-b3); skip-past-last keeps only the first fire
    assert out == [{"at": 1, "bt": 3}] or out == [{"at": 1, "bt": 3}, {"at": 2, "bt": 3}]


def test_keyed_isolation():
    pat = (Pattern.begin("a").where(lambda c: np.asarray(c["t"]) == "a")
           .next("b").where(lambda c: np.asarray(c["t"]) == "b"))
    # 'a' on key 1 and 'b' on key 2 must NOT match
    rows = [{"k": 1, "t": "a", "ts": 1}, {"k": 2, "t": "b", "ts": 2},
            {"k": 2, "t": "a", "ts": 3}, {"k": 2, "t": "b", "ts": 4}]
    out = run_pattern(pat, rows, lambda m: {"k": m["a"][0]["k"]})
    assert out == [{"k": 2}]


def test_followed_by_any():
    pat = (Pattern.begin("a").where(lambda c: np.asarray(c["t"]) == "a")
           .followed_by_any("b").where(lambda c: np.asarray(c["t"]) == "b"))
    rows = [{"k": 0, "t": "a", "ts": 1}, {"k": 0, "t": "b", "ts": 2},
            {"k": 0, "t": "b", "ts": 3}]
    out = run_pattern(pat, rows, lambda m: {"bt": m["b"][0]["ts"]})
    assert sorted(r["bt"] for r in out) == [2, 3]


def test_cep_rows_pruned_no_unbounded_growth():
    """Regression: the operator must not retain every event row forever
    (SharedBuffer pruning analog) — checkpoints would grow without bound."""
    import numpy as np
    from flink_tpu.cep.operator import CepOperator
    from flink_tpu.cep.pattern import Pattern
    from flink_tpu.core.batch import RecordBatch, Watermark

    pat = (Pattern.begin("a").where(lambda c: np.asarray(c["v"]) == 1)
           .next("b").where(lambda c: np.asarray(c["v"]) == 2))
    op = CepOperator(pat, key_column="k", select_fn=lambda m: {"ok": 1})
    n = 500
    for lo in range(0, n, 50):
        v = np.zeros(50, np.int64) + 7   # never matches any stage
        b = RecordBatch({"k": np.zeros(50, np.int64), "v": v},
                        timestamps=np.arange(lo, lo + 50, dtype=np.int64))
        op.process_batch(b)
        op.process_watermark(Watermark(lo + 49))
    total_rows = sum(len(nfa._rows) for nfa in op._nfas.values())
    assert total_rows == 0, f"rows retained: {total_rows}"
    snap = op.snapshot_state()
    assert sum(len(r) for _, _, r in snap["nfas"].values()) == 0


# ---------------------------------------------------------------------------
# VERDICT r1 #10: not-patterns, greedy, until — NFA.java scenario parity
# ---------------------------------------------------------------------------

def _run_events(pattern, events):
    """events: list of (key, kind, ts); returns list of matched kind-lists."""
    from flink_tpu.cep.operator import CepOperator
    from flink_tpu.core.batch import RecordBatch, Watermark

    got = []

    def sel(m):
        flat = [r["kind"] for rows in m.values() for r in rows]
        got.append((sorted(m.keys()), flat))
        return {"n": len(flat)}

    op = CepOperator(pattern, "k", sel)
    ks = np.asarray([e[0] for e in events], np.int64)
    kinds = np.asarray([e[1] for e in events], object)
    ts = np.asarray([e[2] for e in events], np.int64)
    op.process_batch(RecordBatch({"k": ks, "kind": kinds}, timestamps=ts))
    op.process_watermark(Watermark(1 << 40))
    op.end_input()
    return got


def _is(kind):
    return lambda cols: np.asarray(cols["kind"]) == kind


def test_not_next_blocks_immediate_match():
    """a notNext(b) followedBy(c): 'a b c' fails (b immediately follows),
    'a x c' matches."""
    p = (Pattern.begin("a").where(_is("a"))
         .not_next("nb").where(_is("b"))
         .followed_by("c").where(_is("c")))
    assert _run_events(p, [(1, "a", 1), (1, "b", 2), (1, "c", 3)]) == []
    got = _run_events(p, [(1, "a", 1), (1, "x", 2), (1, "c", 3)])
    assert len(got) == 1 and got[0][1] == ["a", "c"]


def test_not_next_same_event_can_match_following_stage():
    """The clean event after notNext may itself match the next stage:
    'a c' matches a notNext(b) followedBy(c)."""
    p = (Pattern.begin("a").where(_is("a"))
         .not_next("nb").where(_is("b"))
         .followed_by("c").where(_is("c")))
    got = _run_events(p, [(1, "a", 1), (1, "c", 2)])
    assert len(got) == 1 and got[0][1] == ["a", "c"]


def test_not_followed_by_kills_on_forbidden_event():
    """a notFollowedBy(b) followedBy(c): 'a x b c' fails, 'a x x c' matches
    (any b between a and c poisons the match, NFA.java NotFollow)."""
    p = (Pattern.begin("a").where(_is("a"))
         .not_followed_by("nb").where(_is("b"))
         .followed_by("c").where(_is("c")))
    assert _run_events(p, [(1, "a", 1), (1, "x", 2), (1, "b", 3),
                           (1, "c", 4)]) == []
    got = _run_events(p, [(1, "a", 1), (1, "x", 2), (1, "x", 3),
                          (1, "c", 4)])
    assert len(got) == 1 and got[0][1] == ["a", "c"]


def test_not_followed_by_last_requires_within():
    from flink_tpu.cep.operator import CepOperator

    p = (Pattern.begin("a").where(_is("a"))
         .not_followed_by("nb").where(_is("b")))
    with pytest.raises(ValueError, match="within"):
        CepOperator(p, "k", lambda m: m)


def test_trailing_not_followed_by_completes_on_window_close():
    """a notFollowedBy(b) within 10: match completes when the window closes
    clean; a 'b' inside the window kills it."""
    p = (Pattern.begin("a").where(_is("a"))
         .not_followed_by("nb").where(_is("b"))
         .within(10))
    got = _run_events(p, [(1, "a", 1), (1, "x", 5), (1, "x", 100)])
    assert len(got) == 1 and got[0][1] == ["a"]
    assert _run_events(p, [(1, "a", 1), (1, "b", 5), (1, "x", 100)]) == []


def test_greedy_loop_consumes_ambiguous_events():
    """a+ greedy followedBy(end) where the loop condition overlaps the end
    condition: greedy keeps extending, yielding only the LONGEST match per
    start (Quantifier.greedy semantics)."""
    is_num = lambda cols: np.char.isdigit(  # noqa: E731
        np.asarray(cols["kind"], str))

    base = Pattern.begin("nums").where(is_num).one_or_more()
    greedy = base.greedy().followed_by("end").where(_is("x"))
    lazy = base.followed_by("end").where(_is("x"))
    ev = [(1, "1", 1), (1, "2", 2), (1, "3", 3), (1, "x", 4)]
    got_greedy = _run_events(greedy, ev)
    got_lazy = _run_events(lazy, ev)
    # non-greedy branches on every prefix: 1|12|123|2|23|3 (+x each)
    assert len(got_lazy) == 6
    # greedy: only the maximal runs survive (one per distinct start)
    lens = sorted(len(m[1]) for m in got_greedy)
    assert len(got_greedy) == 3 and lens == [2, 3, 4]
    assert ["1", "2", "3", "x"] in [m[1] for m in got_greedy]


def test_until_closes_the_loop():
    """one_or_more().until(stop): events after the stop event never extend
    the loop (Pattern.until)."""
    p = (Pattern.begin("a").where(_is("a")).one_or_more()
         .until(_is("s"))
         .followed_by("end").where(_is("e")))
    # a a s a e -> loops of only the first two a's; the post-stop 'a'
    # must not appear in any match
    got = _run_events(p, [(1, "a", 1), (1, "a", 2), (1, "s", 3),
                          (1, "a", 4), (1, "e", 5)])
    assert got, "until must still allow completion via the advanced state"
    for _names, flat in got:
        a_count = sum(1 for x in flat if x == "a")
        assert a_count <= 2


def test_quantified_not_stage_rejected():
    p = Pattern.begin("a").where(_is("a")).not_next("nb")
    with pytest.raises(ValueError, match="quantified"):
        p.times(2)
    with pytest.raises(ValueError, match="optional"):
        p.optional()


def test_not_patterns_across_keys_are_independent():
    """A forbidden event on key 2 must not poison key 1's match."""
    p = (Pattern.begin("a").where(_is("a"))
         .not_followed_by("nb").where(_is("b"))
         .followed_by("c").where(_is("c")))
    got = _run_events(p, [(1, "a", 1), (2, "b", 2), (1, "c", 3)])
    assert len(got) == 1 and got[0][1] == ["a", "c"]


def test_not_followed_by_first_match_retires_watcher():
    """Regression: a notFollowedBy(b) followedBy(c) on 'a c c' matches ONCE
    (plain followedBy semantics, not followedByAny)."""
    p = (Pattern.begin("a").where(_is("a"))
         .not_followed_by("nb").where(_is("b"))
         .followed_by("c").where(_is("c")))
    got = _run_events(p, [(1, "a", 1), (1, "c", 2), (1, "c", 3)])
    assert len(got) == 1 and got[0][1] == ["a", "c"]


def test_greedy_until_closing_event_completes():
    """Regression: greedy + until — the closing event may match the loop
    condition; the advanced branch must survive to complete the match."""
    is_num = lambda cols: np.char.isdigit(  # noqa: E731
        np.asarray(cols["kind"], str))
    p = (Pattern.begin("nums").where(is_num).one_or_more().greedy()
         .until(_is("9"))
         .followed_by("end").where(_is("x")))
    got = _run_events(p, [(1, "1", 1), (1, "2", 2), (1, "9", 3),
                          (1, "x", 4)])
    assert got, "greedy+until must still complete"
    assert ["1", "2", "x"] in [m[1] for m in got]
    for _n, flat in got:
        assert "9" not in flat


def test_trailing_negation_match_timestamped_at_window_close():
    """Regression: the trailing-notFollowedBy match carries the window-close
    event time (first_ts + within), not the draining watermark."""
    from flink_tpu.cep.operator import CepOperator
    from flink_tpu.core.batch import RecordBatch, Watermark

    p = (Pattern.begin("a").where(_is("a"))
         .not_followed_by("nb").where(_is("b"))
         .within(10))
    op = CepOperator(p, "k", lambda m: {"ok": 1})
    op.process_batch(RecordBatch(
        {"k": np.array([1], np.int64), "kind": np.asarray(["a"], object)},
        timestamps=np.array([1], np.int64)))
    out = op.process_watermark(Watermark(1 << 40))
    assert out and int(np.asarray(out[0].timestamps)[0]) == 11
