"""CEP tests, modeled on the reference's NFA/CEP ITCases
(``flink-libraries/flink-cep/src/test/.../NFAITCase.java``): feed keyed
event streams through patterns, assert the matched event sets."""

import numpy as np

from flink_tpu.cep import CEP, AfterMatchSkipStrategy, Pattern
from flink_tpu.datastream.api import StreamExecutionEnvironment


def run_pattern(pattern, rows, select_fn, key="k"):
    env = StreamExecutionEnvironment()
    stream = (env.from_collection(rows, timestamp_column="ts")
              .assign_timestamps_and_watermarks(0, timestamp_column="ts")
              .key_by(key))
    sink = CEP.pattern(stream, pattern).select(select_fn).collect()
    env.execute("cep")
    return [{k: v for k, v in r.items() if k != "__ts__"}
            for r in sink.rows()]


def test_followed_by_basic():
    pat = (Pattern.begin("start")
           .where(lambda c: np.asarray(c["type"]) == "a")
           .followed_by("end")
           .where(lambda c: np.asarray(c["type"]) == "b"))
    rows = [
        {"k": "u", "type": "a", "v": 1, "ts": 1},
        {"k": "u", "type": "x", "v": 2, "ts": 2},
        {"k": "u", "type": "b", "v": 3, "ts": 3},
        {"k": "w", "type": "b", "v": 9, "ts": 4},  # no 'a' before: no match
    ]
    out = run_pattern(pat, rows, lambda m: {
        "k": m["start"][0]["k"],
        "sv": m["start"][0]["v"], "ev": m["end"][0]["v"]})
    assert out == [{"k": "u", "sv": 1, "ev": 3}]


def test_next_strict_contiguity():
    pat = (Pattern.begin("a").where(lambda c: np.asarray(c["t"]) == "a")
           .next("b").where(lambda c: np.asarray(c["t"]) == "b"))
    rows = [
        {"k": 1, "t": "a", "ts": 1}, {"k": 1, "t": "x", "ts": 2},
        {"k": 1, "t": "b", "ts": 3},   # NOT adjacent to the 'a': no match
        {"k": 1, "t": "a", "ts": 4}, {"k": 1, "t": "b", "ts": 5},  # match
    ]
    out = run_pattern(pat, rows, lambda m: {
        "at": m["a"][0]["ts"], "bt": m["b"][0]["ts"]})
    assert out == [{"at": 4, "bt": 5}]


def test_times_quantifier():
    pat = (Pattern.begin("a").where(lambda c: np.asarray(c["t"]) == "a")
           .times(2)
           .followed_by("b").where(lambda c: np.asarray(c["t"]) == "b"))
    rows = [{"k": 0, "t": "a", "ts": 1}, {"k": 0, "t": "a", "ts": 2},
            {"k": 0, "t": "b", "ts": 3}]
    out = run_pattern(pat, rows, lambda m: {
        "n_a": len(m["a"]), "bt": m["b"][0]["ts"]})
    assert {"n_a": 2, "bt": 3} in out


def test_one_or_more():
    pat = (Pattern.begin("a").where(lambda c: np.asarray(c["t"]) == "a")
           .one_or_more()
           .followed_by("b").where(lambda c: np.asarray(c["t"]) == "b"))
    rows = [{"k": 0, "t": "a", "ts": 1}, {"k": 0, "t": "a", "ts": 2},
            {"k": 0, "t": "b", "ts": 3}]
    out = run_pattern(pat, rows, lambda m: {"n_a": len(m["a"])})
    # 'a'@1, 'a'@2, and 'a a' can each be followed by b
    assert sorted(r["n_a"] for r in out) == [1, 1, 2]


def test_optional_stage():
    pat = (Pattern.begin("a").where(lambda c: np.asarray(c["t"]) == "a")
           .followed_by("mid").where(lambda c: np.asarray(c["t"]) == "m")
           .optional()
           .followed_by("b").where(lambda c: np.asarray(c["t"]) == "b"))
    rows = [{"k": 0, "t": "a", "ts": 1}, {"k": 0, "t": "b", "ts": 2}]
    out = run_pattern(pat, rows, lambda m: {
        "has_mid": "mid" in m})
    assert {"has_mid": False} in out


def test_within_window():
    pat = (Pattern.begin("a").where(lambda c: np.asarray(c["t"]) == "a")
           .followed_by("b").where(lambda c: np.asarray(c["t"]) == "b")
           .within(10))
    rows = [{"k": 0, "t": "a", "ts": 0}, {"k": 0, "t": "b", "ts": 50},
            {"k": 0, "t": "a", "ts": 60}, {"k": 0, "t": "b", "ts": 65}]
    out = run_pattern(pat, rows, lambda m: {
        "at": m["a"][0]["ts"], "bt": m["b"][0]["ts"]})
    assert out == [{"at": 60, "bt": 65}]


def test_skip_past_last_event():
    pat = (Pattern.begin("a", skip_strategy=AfterMatchSkipStrategy.SKIP_PAST_LAST_EVENT)
           .where(lambda c: np.asarray(c["t"]) == "a")
           .followed_by("b").where(lambda c: np.asarray(c["t"]) == "b"))
    rows = [{"k": 0, "t": "a", "ts": 1}, {"k": 0, "t": "a", "ts": 2},
            {"k": 0, "t": "b", "ts": 3}, {"k": 0, "t": "b", "ts": 4}]
    out = run_pattern(pat, rows, lambda m: {
        "at": m["a"][0]["ts"], "bt": m["b"][0]["ts"]})
    # NO_SKIP would give 3 matches (a1-b3, a2-b3 under relaxed_any? no —
    # followedBy gives a1-b3, a2-b3); skip-past-last keeps only the first fire
    assert out == [{"at": 1, "bt": 3}] or out == [{"at": 1, "bt": 3}, {"at": 2, "bt": 3}]


def test_keyed_isolation():
    pat = (Pattern.begin("a").where(lambda c: np.asarray(c["t"]) == "a")
           .next("b").where(lambda c: np.asarray(c["t"]) == "b"))
    # 'a' on key 1 and 'b' on key 2 must NOT match
    rows = [{"k": 1, "t": "a", "ts": 1}, {"k": 2, "t": "b", "ts": 2},
            {"k": 2, "t": "a", "ts": 3}, {"k": 2, "t": "b", "ts": 4}]
    out = run_pattern(pat, rows, lambda m: {"k": m["a"][0]["k"]})
    assert out == [{"k": 2}]


def test_followed_by_any():
    pat = (Pattern.begin("a").where(lambda c: np.asarray(c["t"]) == "a")
           .followed_by_any("b").where(lambda c: np.asarray(c["t"]) == "b"))
    rows = [{"k": 0, "t": "a", "ts": 1}, {"k": 0, "t": "b", "ts": 2},
            {"k": 0, "t": "b", "ts": 3}]
    out = run_pattern(pat, rows, lambda m: {"bt": m["b"][0]["ts"]})
    assert sorted(r["bt"] for r in out) == [2, 3]


def test_cep_rows_pruned_no_unbounded_growth():
    """Regression: the operator must not retain every event row forever
    (SharedBuffer pruning analog) — checkpoints would grow without bound."""
    import numpy as np
    from flink_tpu.cep.operator import CepOperator
    from flink_tpu.cep.pattern import Pattern
    from flink_tpu.core.batch import RecordBatch, Watermark

    pat = (Pattern.begin("a").where(lambda c: np.asarray(c["v"]) == 1)
           .next("b").where(lambda c: np.asarray(c["v"]) == 2))
    op = CepOperator(pat, key_column="k", select_fn=lambda m: {"ok": 1})
    n = 500
    for lo in range(0, n, 50):
        v = np.zeros(50, np.int64) + 7   # never matches any stage
        b = RecordBatch({"k": np.zeros(50, np.int64), "v": v},
                        timestamps=np.arange(lo, lo + 50, dtype=np.int64))
        op.process_batch(b)
        op.process_watermark(Watermark(lo + 49))
    total_rows = sum(len(nfa._rows) for nfa in op._nfas.values())
    assert total_rows == 0, f"rows retained: {total_rows}"
    snap = op.snapshot_state()
    assert sum(len(r) for _, _, r in snap["nfas"].values()) == 0
