"""Network-partition nemesis (VERDICT r2 #7): blackhole the link between a
LEADER and the lease service while both sides stay alive — the lease must
expire, a standby must take over with a newer fencing token, the deposed
leader's fenced writes must bounce, and the system must carry on under the
new leader.

Reference: ``flink-jepsen/src/jepsen/flink/nemesis.clj`` (partition
nemeses) + ``checker.clj`` (availability model).  iptables-free: the
partition is a ``FreezableProxy`` (now part of the chaos library,
``flink_tpu.testing.chaos``) interposed on the leader's path.
"""

import threading
import time

import numpy as np
import pytest

from flink_tpu.cluster.ha import LeaseLeaderElection
from flink_tpu.runtime.checkpoint.objectstore import (ObjectStoreClient,
                                                      ObjectStoreServer)
from flink_tpu.testing.chaos import FreezableProxy


@pytest.fixture
def store(tmp_path):
    s = ObjectStoreServer(str(tmp_path / "os")).start()
    yield s
    s.stop()


def test_partition_nemesis_lease_expiry_fencing_and_recovery(store):
    """The full nemesis scenario: leader partitioned from the lease
    service -> lease expires -> standby takes over with a HIGHER fencing
    token -> the deposed leader steps down AND its fenced write is
    rejected -> after the partition heals, the old leader stays follower
    and the new leader keeps operating."""
    proxy = FreezableProxy(store.host, store.port)
    a = LeaseLeaderElection(proxy.url, election="jm", contender_id="A",
                            lease_ms=800, renew_ms=150)
    a.client.timeout_s = 1.0   # a partitioned campaign must fail fast
    b = LeaseLeaderElection(store.url, election="jm", contender_id="B",
                            lease_ms=800, renew_ms=150)
    try:
        a.start()
        deadline = time.monotonic() + 10
        while not a.is_leader and time.monotonic() < deadline:
            time.sleep(0.02)
        assert a.is_leader
        a_token = a.fencing_token
        assert a_token is not None

        b.start()
        time.sleep(0.5)
        assert not b.is_leader          # lease held by A

        # ---- PARTITION: A's renewals blackhole; both processes stay up
        proxy.freeze()
        deadline = time.monotonic() + 15
        while not b.is_leader and time.monotonic() < deadline:
            time.sleep(0.05)
        assert b.is_leader, "standby must take over after lease expiry"
        assert b.fencing_token > a_token   # monotone grant
        deadline = time.monotonic() + 10
        while a.is_leader and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not a.is_leader, "partitioned leader must step down"

        # ---- fencing: the deposed leader's write (stale token) bounces,
        # even via a DIRECT path around the partition
        direct = ObjectStoreClient(store.url, timeout_s=5)
        import urllib.error
        with pytest.raises(urllib.error.HTTPError) as ei:
            direct.put("jobs/job-1/latest", b"A-era-state",
                       fencing=("jm", a_token))
        assert ei.value.code == 412
        # the NEW leader's fenced write lands
        direct.put("jobs/job-1/latest", b"B-era-state",
                   fencing=("jm", b.fencing_token))
        assert direct.get("jobs/job-1/latest") == b"B-era-state"

        # ---- HEAL: the old leader reconnects but must NOT usurp; the new
        # leader keeps renewing (the system recovered under B)
        proxy.heal()
        time.sleep(1.5)
        assert b.is_leader and not a.is_leader
        st = store.lease_state("jm")
        assert st["held"] and st["holder"] == "B"
        # A's stale-token write still bounces after the heal
        with pytest.raises(urllib.error.HTTPError):
            a.client.put("jobs/job-1/latest", b"A-usurps",
                         fencing=("jm", a_token))
        assert direct.get("jobs/job-1/latest") == b"B-era-state"
    finally:
        a.stop(abdicate=False)
        b.stop()
        proxy.stop()


def test_freezable_proxy_directional_freeze(store):
    """FreezableProxy asymmetry: freezing a->b blackholes client requests
    (the call stalls) while b->a stays open; healing that one direction
    restores the link."""
    import urllib.error

    proxy = FreezableProxy(store.host, store.port)
    try:
        c = ObjectStoreClient(proxy.url, timeout_s=0.5)
        c.put("k", b"v1")
        assert c.get("k") == b"v1"
        proxy.freeze("a->b")           # requests vanish; responses would flow
        with pytest.raises((urllib.error.URLError, TimeoutError, OSError)):
            c.put("k", b"v2")
        # the value is untouched (the request never reached the store)
        direct = ObjectStoreClient(store.url, timeout_s=5)
        assert direct.get("k") == b"v1"
        proxy.heal("a->b")
        c.put("k", b"v3")
        assert direct.get("k") == b"v3"
        # the opposite direction alone: requests ARRIVE (the store mutates)
        # but the response is lost — the classic did-my-write-land ambiguity
        proxy.freeze("b->a")
        with pytest.raises((urllib.error.URLError, TimeoutError, OSError)):
            c.put("k", b"v4")
        assert direct.get("k") == b"v4"
        proxy.heal()
    finally:
        proxy.stop()


def test_asymmetric_partition_liveness_and_exactly_once(store):
    """ISSUE-4 satellite: an A→B-only partition between the worker side
    (checkpoint writes) and the coordinator-side store.  While frozen,
    every store RPC times out — the job must stay LIVE (stores run outside
    the coordinator lock; failures only charge the budget) and finish
    EXACTLY-ONCE; after the heal, checkpoints land again."""
    from flink_tpu.cluster.task import TaskStates
    from flink_tpu.datastream.api import StreamExecutionEnvironment
    from flink_tpu.runtime.checkpoint.objectstore import \
        ObjectStoreCheckpointStorage

    proxy = FreezableProxy(store.host, store.port)
    storage = ObjectStoreCheckpointStorage(
        proxy.url, prefix="jobs/asym/",
        client=ObjectStoreClient(proxy.url, timeout_s=0.3))
    n = 30_000
    keys = np.arange(n) % 13
    vals = np.ones(n)
    env = StreamExecutionEnvironment()
    env.set_parallelism(2)
    sink = (env.from_collection(columns={"k": keys, "v": vals},
                                batch_size=128)
            .key_by("k").sum("v").collect())

    # event-driven nemesis: freeze worker->store AFTER a checkpoint landed
    # cleanly, hold the sources paused until a store visibly failed during
    # the partition AND a post-heal checkpoint completed — deterministic
    # regardless of compile/oS timing
    cycle_done = threading.Event()

    def _nemesis():
        deadline = time.monotonic() + 60
        while not hasattr(env, "_last_cluster") and \
                time.monotonic() < deadline:
            time.sleep(0.005)
        cluster = env._last_cluster
        while not cluster._completed_ids and time.monotonic() < deadline:
            time.sleep(0.005)
        for t in cluster._source_tasks:      # job must outlive the cycle
            t._paused.set()
        try:
            proxy.freeze("a->b")             # requests vanish; B->A flows
            while cluster.failure_manager.num_failed() < 1 and \
                    time.monotonic() < deadline:
                time.sleep(0.005)
            before = len(cluster._completed_ids)
            proxy.heal("a->b")
            while len(cluster._completed_ids) <= before and \
                    time.monotonic() < deadline:
                time.sleep(0.005)
        finally:
            for t in cluster._source_tasks:
                t._paused.clear()
        cycle_done.set()

    t = threading.Thread(target=_nemesis, daemon=True)
    t.start()
    try:
        res = env.execute_cluster(storage=storage, checkpoint_interval_ms=5,
                                  tolerable_failed_checkpoints=-1)
    finally:
        t.join(timeout=70)
    assert cycle_done.is_set()
    assert res.state == TaskStates.FINISHED, \
        "one-way partition cost the job its liveness"
    assert res.restarts == 0
    got = {int(r["k"]): r["v"] for r in sink.rows()}
    expect = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        expect[int(k)] = expect.get(int(k), 0.0) + v
    assert got == expect, "sums not exactly-once under the partition"
    cluster = env._last_cluster
    status = cluster.job_status()
    # the partitioned window charged storage failures but never the job
    assert status["checkpoints"]["failed_checkpoints"] >= 1
    # after the heal at least one checkpoint landed durably
    assert storage.load_latest() is not None or res.completed_checkpoints


def test_fenced_put_without_any_grant_rejects_unknown_tokens(store):
    """Fencing sanity: tokens never granted are rejected; the latest
    granted token works even after its lease lapsed (no newer grant)."""
    import urllib.error

    c = ObjectStoreClient(store.url, timeout_s=5)
    with pytest.raises(urllib.error.HTTPError):
        c.put("k", b"x", fencing=("nope", 7))
    r = store.lease_acquire("e2", "w", ttl_ms=50)
    time.sleep(0.1)                       # lease lapses, no new grant
    c.put("k", b"y", fencing=("e2", r["token"]))   # still newest token
    assert c.get("k") == b"y"
    r2 = store.lease_acquire("e2", "w2", ttl_ms=5000)
    with pytest.raises(urllib.error.HTTPError):
        c.put("k", b"z", fencing=("e2", r["token"]))  # superseded now
    c.put("k", b"z2", fencing=("e2", r2["token"]))
    assert c.get("k") == b"z2"
