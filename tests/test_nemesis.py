"""Network-partition nemesis (VERDICT r2 #7): blackhole the link between a
LEADER and the lease service while both sides stay alive — the lease must
expire, a standby must take over with a newer fencing token, the deposed
leader's fenced writes must bounce, and the system must carry on under the
new leader.

Reference: ``flink-jepsen/src/jepsen/flink/nemesis.clj`` (partition
nemeses) + ``checker.clj`` (availability model).  iptables-free: the
partition is a ``FreezableProxy`` (now part of the chaos library,
``flink_tpu.testing.chaos``) interposed on the leader's path.
"""

import time

import pytest

from flink_tpu.cluster.ha import LeaseLeaderElection
from flink_tpu.runtime.checkpoint.objectstore import (ObjectStoreClient,
                                                      ObjectStoreServer)
from flink_tpu.testing.chaos import FreezableProxy


@pytest.fixture
def store(tmp_path):
    s = ObjectStoreServer(str(tmp_path / "os")).start()
    yield s
    s.stop()


def test_partition_nemesis_lease_expiry_fencing_and_recovery(store):
    """The full nemesis scenario: leader partitioned from the lease
    service -> lease expires -> standby takes over with a HIGHER fencing
    token -> the deposed leader steps down AND its fenced write is
    rejected -> after the partition heals, the old leader stays follower
    and the new leader keeps operating."""
    proxy = FreezableProxy(store.host, store.port)
    a = LeaseLeaderElection(proxy.url, election="jm", contender_id="A",
                            lease_ms=800, renew_ms=150)
    a.client.timeout_s = 1.0   # a partitioned campaign must fail fast
    b = LeaseLeaderElection(store.url, election="jm", contender_id="B",
                            lease_ms=800, renew_ms=150)
    try:
        a.start()
        deadline = time.monotonic() + 10
        while not a.is_leader and time.monotonic() < deadline:
            time.sleep(0.02)
        assert a.is_leader
        a_token = a.fencing_token
        assert a_token is not None

        b.start()
        time.sleep(0.5)
        assert not b.is_leader          # lease held by A

        # ---- PARTITION: A's renewals blackhole; both processes stay up
        proxy.freeze()
        deadline = time.monotonic() + 15
        while not b.is_leader and time.monotonic() < deadline:
            time.sleep(0.05)
        assert b.is_leader, "standby must take over after lease expiry"
        assert b.fencing_token > a_token   # monotone grant
        deadline = time.monotonic() + 10
        while a.is_leader and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not a.is_leader, "partitioned leader must step down"

        # ---- fencing: the deposed leader's write (stale token) bounces,
        # even via a DIRECT path around the partition
        direct = ObjectStoreClient(store.url, timeout_s=5)
        import urllib.error
        with pytest.raises(urllib.error.HTTPError) as ei:
            direct.put("jobs/job-1/latest", b"A-era-state",
                       fencing=("jm", a_token))
        assert ei.value.code == 412
        # the NEW leader's fenced write lands
        direct.put("jobs/job-1/latest", b"B-era-state",
                   fencing=("jm", b.fencing_token))
        assert direct.get("jobs/job-1/latest") == b"B-era-state"

        # ---- HEAL: the old leader reconnects but must NOT usurp; the new
        # leader keeps renewing (the system recovered under B)
        proxy.heal()
        time.sleep(1.5)
        assert b.is_leader and not a.is_leader
        st = store.lease_state("jm")
        assert st["held"] and st["holder"] == "B"
        # A's stale-token write still bounces after the heal
        with pytest.raises(urllib.error.HTTPError):
            a.client.put("jobs/job-1/latest", b"A-usurps",
                         fencing=("jm", a_token))
        assert direct.get("jobs/job-1/latest") == b"B-era-state"
    finally:
        a.stop(abdicate=False)
        b.stop()
        proxy.stop()


def test_fenced_put_without_any_grant_rejects_unknown_tokens(store):
    """Fencing sanity: tokens never granted are rejected; the latest
    granted token works even after its lease lapsed (no newer grant)."""
    import urllib.error

    c = ObjectStoreClient(store.url, timeout_s=5)
    with pytest.raises(urllib.error.HTTPError):
        c.put("k", b"x", fencing=("nope", 7))
    r = store.lease_acquire("e2", "w", ttl_ms=50)
    time.sleep(0.1)                       # lease lapses, no new grant
    c.put("k", b"y", fencing=("e2", r["token"]))   # still newest token
    assert c.get("k") == b"y"
    r2 = store.lease_acquire("e2", "w2", ttl_ms=5000)
    with pytest.raises(urllib.error.HTTPError):
        c.put("k", b"z", fencing=("e2", r["token"]))  # superseded now
    c.put("k", b"z2", fencing=("e2", r2["token"]))
    assert c.get("k") == b"z2"
