"""Connectors & formats: FLIP-27 file source with positioned resume,
two-phase-commit file sink, partitioned log (Kafka analog) with exactly-once
source offsets and transactional sink."""

import os

import numpy as np
import pytest

from flink_tpu import formats
from flink_tpu.connectors.file_source import FileSink, FileSource
from flink_tpu.connectors.partitioned_log import (LogSink, LogSource,
                                                  PartitionedLog)
from flink_tpu.core.batch import RecordBatch


def _mkbatch(lo, hi):
    return RecordBatch({"k": np.arange(lo, hi) % 7,
                        "v": np.arange(lo, hi, dtype=np.float64)},
                       timestamps=np.arange(lo, hi, dtype=np.int64))


# ---------------------------------------------------------------------------
# formats
# ---------------------------------------------------------------------------

def test_csv_roundtrip(tmp_path):
    p = str(tmp_path / "x.csv")
    n = formats.write_csv([_mkbatch(0, 100)], p)
    assert n == 100
    got = list(formats.read_csv(p, batch_size=30))
    assert sum(len(b) for b in got) == 100
    assert np.asarray(got[0].column("v"))[3] == 3.0


def test_csv_skip_rows_resume(tmp_path):
    p = str(tmp_path / "x.csv")
    formats.write_csv([_mkbatch(0, 50)], p)
    got = list(formats.read_csv(p, skip_rows=40))
    assert sum(len(b) for b in got) == 10
    assert np.asarray(got[0].column("v"))[0] == 40.0


def test_jsonl_roundtrip(tmp_path):
    p = str(tmp_path / "x.jsonl")
    formats.write_jsonl([_mkbatch(0, 25)], p)
    got = list(formats.read_jsonl(p))
    assert sum(len(b) for b in got) == 25


def test_ftb_roundtrip_preserves_dtypes_and_ts(tmp_path):
    p = str(tmp_path / "x.ftb")
    formats.write_ftb([_mkbatch(0, 64), _mkbatch(64, 100)], p)
    got = list(formats.read_ftb(p))
    assert len(got) == 2
    assert got[0].column("v").dtype == np.float64
    assert got[1].timestamps is not None
    np.testing.assert_array_equal(np.asarray(got[1].timestamps),
                                  np.arange(64, 100))


def test_ftb_torn_tail_ignored(tmp_path):
    p = str(tmp_path / "x.ftb")
    formats.write_ftb([_mkbatch(0, 10)], p)
    with open(p, "ab") as f:
        f.write(b"\x99\x00\x00\x00garbage")  # torn partial frame
    got = list(formats.read_ftb(p))
    assert sum(len(b) for b in got) == 10


def test_all_columnar_formats_registered():
    # parquet AND orc are implemented natively since round 4
    for fmt in ("parquet", "orc", "avro", "ftb", "csv", "jsonl"):
        assert formats.reader_for(fmt) is not None
        assert formats.writer_for(fmt) is not None
    with pytest.raises(ValueError, match="unknown format"):
        formats.reader_for("xml")


# ---------------------------------------------------------------------------
# file source / sink
# ---------------------------------------------------------------------------

def test_file_source_splits_one_per_file(tmp_path):
    for i in range(3):
        formats.write_csv([_mkbatch(i * 10, i * 10 + 10)],
                          str(tmp_path / f"f{i}.csv"))
    src = FileSource(str(tmp_path), format="csv")
    splits = src.create_splits(parallelism=2)
    assert len(splits) == 3
    total = 0
    for s in splits:
        for b in s.read():
            total += len(b)
    assert total == 30


def test_file_source_positioned_resume(tmp_path):
    formats.write_csv([_mkbatch(0, 100)], str(tmp_path / "f.csv"))
    src = FileSource(str(tmp_path / "f.csv"), format="csv", batch_size=30)
    [split] = src.create_splits(1)
    r = src.open_split(split, None)
    first = next(r)
    assert len(first) == 30 and r.position == 30
    # resume from the checkpointed position in a fresh reader
    r2 = src.open_split(split, r.position)
    rest = sum(len(b) for b in r2)
    assert rest == 70
    assert r2.position == 100


def test_file_source_ftb_mid_batch_resume(tmp_path):
    formats.write_ftb([_mkbatch(0, 40), _mkbatch(40, 80)],
                      str(tmp_path / "f.ftb"))
    src = FileSource(str(tmp_path / "f.ftb"), format="ftb")
    [split] = src.create_splits(1)
    r = src.open_split(split, 55)   # mid second batch
    vals = np.concatenate([np.asarray(b.column("v")) for b in r])
    np.testing.assert_array_equal(vals, np.arange(55, 80, dtype=np.float64))


def test_file_sink_two_phase_commit(tmp_path):
    d = str(tmp_path / "out")
    sink = FileSink(d, format="csv")
    sink.write_batch(_mkbatch(0, 10))
    snap = sink.snapshot_state()           # pre-commit: rolled to .pending
    assert not sink.committed_files()
    assert any(f.endswith(".pending") for f in os.listdir(d))
    sink.notify_checkpoint_complete(1)     # commit
    assert len(sink.committed_files()) == 1
    got = list(formats.read_csv(sink.committed_files()[0]))
    assert sum(len(b) for b in got) == 10


def test_file_sink_restore_discards_orphans_commits_pending(tmp_path):
    d = str(tmp_path / "out")
    sink = FileSink(d, format="csv")
    sink.write_batch(_mkbatch(0, 5))
    snap = sink.snapshot_state()
    # crash before notify: a new sink restores from snap
    sink2 = FileSink(d, format="csv")
    sink2.write_batch(_mkbatch(99, 104))   # uncheckpointed epoch -> orphan
    sink2._roll()
    sink2.restore_state(snap)
    files = sink2.committed_files()
    assert len(files) == 1                 # pending committed
    assert not any(f.endswith(".pending") for f in os.listdir(d))  # orphan gone
    got = list(formats.read_csv(files[0]))
    assert np.asarray(got[0].column("v"))[0] == 0.0


# ---------------------------------------------------------------------------
# partitioned log (Kafka analog)
# ---------------------------------------------------------------------------

def test_log_append_read_offsets(tmp_path):
    log = PartitionedLog(str(tmp_path / "log"), num_partitions=2)
    off1 = log.append(0, _mkbatch(0, 10))
    off2 = log.append(0, _mkbatch(10, 20))
    assert off2 > off1
    got = [(len(b), off) for b, off in log.read_from(0, 0)]
    assert [g[0] for g in got] == [10, 10]
    # resume from mid-log offset reads only the second batch
    got2 = [len(b) for b, _ in log.read_from(0, off1)]
    assert got2 == [10]


def test_log_source_bounded_and_resume(tmp_path):
    d = str(tmp_path / "log")
    log = PartitionedLog(d, num_partitions=3)
    for p in range(3):
        log.append(p, _mkbatch(p * 10, p * 10 + 10))
    src = LogSource(d, bounded=True)
    splits = src.create_splits(1)
    assert len(splits) == 3
    readers = [src.open_split(s, None) for s in splits]
    total = sum(len(b) for r in readers for b in r)
    assert total == 30
    # checkpointed offsets: new data after the offset is all a resume sees
    positions = {s.split_id: r.position for s, r in zip(splits, readers)}
    log.append(1, _mkbatch(100, 105))
    r2 = src.open_split(splits[1], positions[splits[1].split_id])
    vals = np.concatenate([np.asarray(b.column("v")) for b in r2])
    np.testing.assert_array_equal(vals, np.arange(100, 105, dtype=np.float64))


def test_log_sink_exactly_once_no_double_commit(tmp_path):
    d = str(tmp_path / "log")
    sink = LogSink(d, num_partitions=1)
    sink.write_batch(_mkbatch(0, 10))
    snap = sink.snapshot_state()
    sink.notify_checkpoint_complete(1)
    assert sum(len(b) for b, _ in PartitionedLog(d).read_from(0, 0)) == 10
    # crash + restore from the same snapshot: txn already committed -> no dup
    sink2 = LogSink(d, num_partitions=1)
    sink2.restore_state(snap)
    assert sum(len(b) for b, _ in PartitionedLog(d).read_from(0, 0)) == 10


def test_log_sink_restore_commits_uncommitted_txn(tmp_path):
    d = str(tmp_path / "log")
    sink = LogSink(d, num_partitions=1)
    sink.write_batch(_mkbatch(0, 10))
    snap = sink.snapshot_state()
    # crash BEFORE notify: restore must publish the staged transaction once
    sink2 = LogSink(d, num_partitions=1)
    sink2.restore_state(snap)
    assert sum(len(b) for b, _ in PartitionedLog(d).read_from(0, 0)) == 10
    sink3 = LogSink(d, num_partitions=1)
    sink3.restore_state(snap)   # double restore: still exactly once
    assert sum(len(b) for b, _ in PartitionedLog(d).read_from(0, 0)) == 10


def test_log_sink_key_partitioning(tmp_path):
    d = str(tmp_path / "log")
    sink = LogSink(d, num_partitions=4, key_column="k")
    sink.write_batch(_mkbatch(0, 100))
    sink.flush()
    log = PartitionedLog(d)
    seen = {}
    for p in range(4):
        for b, _ in log.read_from(p, 0):
            for k in np.asarray(b.column("k")).tolist():
                seen.setdefault(k, set()).add(p)
    assert sum(len(v) for v in seen.values()) == len(seen)  # one partition/key
    total = sum(len(b) for p in range(4) for b, _ in log.read_from(p, 0))
    assert total == 100


# ---------------------------------------------------------------------------
# end-to-end: checkpointed pipeline resumes source exactly-once
# ---------------------------------------------------------------------------

def test_pipeline_source_position_checkpoint_resume(tmp_path):
    """Stop a job mid-stream, checkpoint, restore: every record processed
    exactly once across the two runs (FLIP-27 position + heap state resume)."""
    from flink_tpu.datastream.api import StreamExecutionEnvironment
    from flink_tpu.runtime.checkpoint.storage import InMemoryCheckpointStorage

    formats.write_csv([_mkbatch(0, 200)], str(tmp_path / "in.csv"))
    storage = InMemoryCheckpointStorage()

    def build(env):
        return (env.from_source(
                    FileSource(str(tmp_path / "in.csv"), format="csv",
                               batch_size=20))
                .key_by("k").sum("v"))

    # run 1: stop after 60 records without draining, checkpoint at stop
    env = StreamExecutionEnvironment()
    sink1 = build(env).collect()
    env.execute(max_records=60, drain=False)
    snap = env._last_executor.trigger_checkpoint(1)
    storage.store(1, snap)
    consumed = snap.get("__sources__", {})
    assert consumed, "source positions missing from checkpoint"
    [positions] = consumed.values()
    assert list(positions.values()) == [60]

    # run 2: restore, read the rest
    env2 = StreamExecutionEnvironment()
    sink2 = build(env2).collect()
    env2.execute(restore=storage.load_latest())

    # running sum per key: the last emission per key must equal the global sum
    final = {}
    for r in sink1.rows() + sink2.rows():
        final[r["k"]] = r["v"]         # running sum: last wins
    expect = {}
    for k, v in zip(np.arange(200) % 7, np.arange(200, dtype=np.float64)):
        expect[int(k)] = expect.get(int(k), 0.0) + v
    assert {int(k): float(v) for k, v in final.items()} == expect


# ---------------------------------------------------------------------------
# review regressions
# ---------------------------------------------------------------------------

def test_log_reader_idle_partition_yields_control(tmp_path):
    """Regression: an unbounded reader on an idle partition must return
    control (empty batches) so round-robin/budgets keep running."""
    d = str(tmp_path / "log")
    PartitionedLog(d, num_partitions=1)
    src = LogSource(d, bounded=False, poll_interval_ms=1)
    [split] = src.create_splits(1)
    r = src.open_split(split, None)
    el = next(r)           # no data: must yield an empty batch, not block
    assert len(el) == 0


def test_log_sink_crash_mid_commit_truncate_recovery(tmp_path):
    """Regression: crash between txn append and commit record -> recovery
    truncates the partial append; restore re-appends exactly once."""
    import json as _json

    d = str(tmp_path / "log")
    sink = LogSink(d, num_partitions=1)
    sink.write_batch(_mkbatch(0, 10))
    snap = sink.snapshot_state()
    # simulate crash mid-commit: intent written, batches appended, NO sidecar
    cid = snap["counter"]
    offsets = {0: sink.log.end_offset(0)}
    with open(sink._intent_path(cid), "w") as f:
        _json.dump({"key": sink._commit_key(cid), "offsets": offsets}, f)
    for b in snap["staged"][cid]:
        sink._append(b)
    assert sum(len(b) for b, _ in PartitionedLog(d).read_from(0, 0)) == 10
    # restore: partial append rolled back, txn re-applied exactly once
    sink2 = LogSink(d, num_partitions=1)
    sink2.restore_state(snap)
    assert sum(len(b) for b, _ in PartitionedLog(d).read_from(0, 0)) == 10


def test_file_sink_restore_spares_other_prefixes(tmp_path):
    d = str(tmp_path / "out")
    a = FileSink(d, format="csv", prefix="a")
    b = FileSink(d, format="csv", prefix="b")
    b.write_batch(_mkbatch(0, 5))
    b_snap = b.snapshot_state()            # b's pending part on disk
    a2 = FileSink(d, format="csv", prefix="a")
    a2.restore_state({"pending": [], "counter": 0})
    # b's pending must survive a's orphan cleanup
    b2 = FileSink(d, format="csv", prefix="b")
    b2.restore_state(b_snap)
    assert len(b2.committed_files()) == 1


def test_jsonl_sparse_fields_and_blank_line_resume(tmp_path):
    import json as _json
    p = str(tmp_path / "x.jsonl")
    with open(p, "w") as f:
        f.write(_json.dumps({"a": 1}) + "\n")
        f.write("\n")                                  # blank line
        f.write(_json.dumps({"a": 2, "b": 30}) + "\n")
        f.write(_json.dumps({"a": 3}) + "\n")
    [batch] = list(formats.read_jsonl(p))
    assert "b" in batch.columns                        # union of fields
    # skip_rows counts data rows: resume at 2 yields exactly the third record
    [rest] = list(formats.read_jsonl(p, skip_rows=2))
    assert len(rest) == 1 and np.asarray(rest.column("a"))[0] == 3


def test_file_source_missing_path_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        FileSource(str(tmp_path / "nope.csv"), format="csv").create_splits(1)


def test_log_sink_stable_string_key_partitioning(tmp_path):
    d = str(tmp_path / "log")
    keys = np.asarray(["alpha", "beta", "gamma", "delta"] * 5, object)
    b = RecordBatch({"k": keys, "v": np.arange(20, dtype=np.float64)})
    sink = LogSink(d, num_partitions=3, key_column="k")
    sink.write_batch(b)
    sink.flush()
    # partition assignment must match the framework's stable hash
    from flink_tpu.core.keygroups import hash_keys
    expect_parts = (np.abs(hash_keys(keys).astype(np.int64)) % 3)
    log = PartitionedLog(d)
    for p in range(3):
        for bb, _ in log.read_from(p, 0):
            got = np.asarray(bb.column("k"))
            for k in got.tolist():
                idx = keys.tolist().index(k)
                assert expect_parts[idx] == p


def test_log_sink_fresh_job_ignores_stale_sidecar(tmp_path):
    """Regression: a NEW job writing to a directory with a surviving commit
    sidecar must not mistake its own txn ids for already-committed ones."""
    d = str(tmp_path / "log")
    s1 = LogSink(d, num_partitions=1)
    s1.write_batch(_mkbatch(0, 10))
    s1.snapshot_state()
    s1.notify_checkpoint_complete(1)
    # fresh job, same directory, no restore
    s2 = LogSink(d, num_partitions=1)
    s2.write_batch(_mkbatch(10, 20))
    s2.snapshot_state()
    s2.notify_checkpoint_complete(1)
    assert sum(len(b) for b, _ in PartitionedLog(d).read_from(0, 0)) == 20


def test_log_source_missing_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        LogSource(str(tmp_path / "nope")).create_splits(1)
    assert not os.path.exists(str(tmp_path / "nope" / "_meta.json"))


def test_file_sink_sibling_subtasks_share_directory(tmp_path):
    """Regression: subtask 0's restore cleanup must not delete subtask 1's
    live pending part."""
    class _Ctx:
        subtask_index = 0

    d = str(tmp_path / "out")
    a = FileSink(d, format="csv")
    a.open(_Ctx())
    b = FileSink(d, format="csv")
    ctx1 = _Ctx()
    ctx1.subtask_index = 1
    b.open(ctx1)
    b.write_batch(_mkbatch(0, 5))
    b_snap = b.snapshot_state()            # b's pending part on disk
    a.restore_state({"pending": [], "counter": 0})   # a restores
    b.notify_checkpoint_complete(1)        # b commits: part must still exist
    assert len(b.committed_files()) == 1


# ---------------------------------------------------------------------------
# Avro object container format (flink-avro analog, pure Python)
# ---------------------------------------------------------------------------

def test_avro_roundtrip(tmp_path):
    from flink_tpu.core.batch import RecordBatch
    from flink_tpu.formats.avro import read_avro, write_avro

    path = str(tmp_path / "t.avro")
    b1 = RecordBatch({"k": np.arange(5, dtype=np.int64),
                      "v": np.linspace(0, 1, 5).astype(np.float64),
                      "f": np.arange(5, dtype=np.float32),
                      "b": np.array([True, False, True, False, True]),
                      "s": np.asarray(["a", "bb", "ccc", "", "é"], object)})
    b2 = RecordBatch({"k": np.arange(5, 8, dtype=np.int64),
                      "v": np.zeros(3),
                      "f": np.zeros(3, np.float32),
                      "b": np.zeros(3, bool),
                      "s": np.asarray(["x", "y", "z"], object)})
    n = write_avro([b1, b2], path)
    assert n == 8
    got = RecordBatch.concat(list(read_avro(path)))
    assert len(got) == 8
    np.testing.assert_array_equal(np.asarray(got.column("k")), np.arange(8))
    np.testing.assert_allclose(np.asarray(got.column("v"))[:5],
                               np.linspace(0, 1, 5))
    assert np.asarray(got.column("b"))[:3].tolist() == [True, False, True]
    assert np.asarray(got.column("s")).tolist()[:5] == ["a", "bb", "ccc", "", "é"]


def test_avro_nullable_strings(tmp_path):
    from flink_tpu.core.batch import RecordBatch
    from flink_tpu.formats.avro import read_avro, write_avro

    path = str(tmp_path / "n.avro")
    col = np.empty(3, object)
    col[:] = ["a", None, "c"]
    write_avro([RecordBatch({"s": col, "k": np.arange(3, dtype=np.int64)})],
               path)
    got = RecordBatch.concat(list(read_avro(path)))
    assert np.asarray(got.column("s")).tolist() == ["a", None, "c"]


def test_avro_null_codec_and_magic(tmp_path):
    from flink_tpu.core.batch import RecordBatch
    from flink_tpu.formats.avro import read_avro, write_avro

    path = str(tmp_path / "u.avro")
    write_avro([RecordBatch({"x": np.arange(4, dtype=np.int64)})], path,
               codec="null")
    with open(path, "rb") as f:
        assert f.read(4) == b"Obj\x01"   # standard container magic
    got = RecordBatch.concat(list(read_avro(path)))
    np.testing.assert_array_equal(np.asarray(got.column("x")), np.arange(4))


def test_avro_format_registry(tmp_path):
    from flink_tpu.core.batch import RecordBatch
    from flink_tpu.formats import reader_for, writer_for

    path = str(tmp_path / "r.avro")
    writer_for("avro")([RecordBatch({"x": np.arange(3, dtype=np.int64)})],
                       path)
    got = RecordBatch.concat(list(reader_for("avro")(path)))
    assert len(got) == 3


def test_avro_null_in_non_nullable_rejected(tmp_path):
    from flink_tpu.core.batch import RecordBatch
    from flink_tpu.formats.avro import write_avro

    # first batch has no Nones -> derived schema is non-nullable; a later
    # None must fail loudly, never serialize as the string "None"
    c1 = np.asarray(["a", "b"], object)
    c2 = np.empty(2, object)
    c2[:] = ["c", None]
    with pytest.raises(ValueError, match="non-nullable"):
        write_avro([RecordBatch({"s": c1}), RecordBatch({"s": c2})],
                   str(tmp_path / "bad.avro"))
