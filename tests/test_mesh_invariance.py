"""Shard-count invariance of the mesh-sharded hot path (ISSUE 6).

One logical window operator across the chip mesh: fire digests and operator
counters must be BIT-identical at mesh sizes 1 vs 2 vs 4 on every tier
(host mirror / device / deferred), with cold-key paging riding per-shard,
snapshots rescaling across mesh sizes in both directions, and the pjit'd
update step compiling exactly once per (mesh size, batch geometry) — a
resharding-induced recompile fails the smoke.  Runs on the 8-device
virtual CPU mesh the conftest forces (``JAX_PLATFORMS=cpu`` +
``--xla_force_host_platform_device_count=8``), so tier-1 exercises real
multi-device sharding.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from flink_tpu.core.batch import RecordBatch, Watermark
from flink_tpu.core.functions import RuntimeContext, SumAggregator
from flink_tpu.operators.window_agg import WindowAggOperator
from flink_tpu.parallel.mesh import make_mesh
from flink_tpu.parallel.mesh_runtime import MeshWindowAggOperator
from flink_tpu.state.paging import PagingConfig
from flink_tpu.state.shard_layout import (ShardLayout, densify_keyed_snapshot,
                                          has_shard_slices, slice_manifest)
from flink_tpu.windowing.assigners import TumblingEventTimeWindows

WINDOW_MS = 1000


def _digests(out):
    """Exact per-fired-batch fingerprint: window, row count, raw BYTES of
    the emitted key and result columns (order included)."""
    return [(int(np.asarray(b.column("window_start"))[0]), len(b),
             np.asarray(b.column("k")).tobytes(),
             np.asarray(b.column("result")).tobytes())
            for b in out if hasattr(b, "columns") and "result" in b.columns]


def _counters(op):
    """The per-operator counters ``job_status()`` surfaces."""
    c = {
        "late_dropped": op.late_dropped,
        "num_keys": op.key_index.num_keys if op.key_index else 0,
        "watermark": op.watermark,
        "last_fired_window": op.last_fired_window,
        "device_health": op.device_health_stats(),
    }
    if op.paging_stats() is not None:
        p = op.paging_stats()
        # residency split is a per-shard-run scheduling detail; the key
        # population and capacity are the invariants
        c["paging"] = {"capacity": p["capacity"],
                       "total_keys": p["resident_keys"] + p["spilled_keys"]}
    return c


def _mk(D, emit_tier="host", device_sync="scatter", paging=None, **kw):
    if paging is not None:
        emit_tier = "device"
    kw.setdefault("key_column", "k")
    kw.setdefault("value_column", "v")
    kw.update(emit_tier=emit_tier,
              snapshot_source="mirror" if emit_tier == "host" else "device",
              device_sync=device_sync if emit_tier == "host" else "scatter",
              paging=paging)
    if D == 1:
        op = WindowAggOperator(TumblingEventTimeWindows.of(WINDOW_MS),
                               SumAggregator(jnp.float32), **kw)
    else:
        op = MeshWindowAggOperator(TumblingEventTimeWindows.of(WINDOW_MS),
                                   SumAggregator(jnp.float32),
                                   mesh=make_mesh(D), **kw)
    op.open(RuntimeContext())
    return op


def _run(op, seed=3, n_batches=6, nk=3000, B=4096, snap_at=None,
         late_every=0):
    """Seeded feed with per-batch watermarks (and optional late records),
    an optional mid-run snapshot, ending with end_input."""
    rng = np.random.default_rng(seed)
    out, snap = [], None
    for i in range(n_batches):
        k = rng.integers(0, nk, B).astype(np.int64)
        v = rng.random(B).astype(np.float32)
        ts = i * 500 + np.sort(rng.integers(0, 500, B)).astype(np.int64)
        if late_every and i and i % late_every == 0:
            ts[: B // 8] -= 2500          # beyond-lateness drops
        out += op.process_batch(RecordBatch({"k": k, "v": v}, timestamps=ts))
        out += op.process_watermark(Watermark(int(ts.max()) - 1))
        if snap_at == i:
            op.prepare_snapshot_pre_barrier()
            snap = op.snapshot_state()
    out += op.end_input()
    return _digests(out), snap, _counters(op)


# ---------------------------------------------------------------------------
# tier invariance: mesh sizes 1 vs 2 vs 4, bit-identical digests + counters
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("tier,sync", [("host", "scatter"),
                                       ("host", "deferred"),
                                       ("device", "scatter")])
def test_mesh_size_invariance_by_tier(tier, sync):
    ref, _, ref_counters = _run(_mk(1, tier, sync), late_every=3)
    assert len(ref) >= 3
    for D in (2, 4):
        got, _, counters = _run(_mk(D, tier, sync), late_every=3)
        assert got == ref, f"digests diverge at mesh size {D} ({tier}/{sync})"
        assert counters == ref_counters, f"counters diverge at D={D}"


def test_mesh_deferred_refresh_keeps_state_pre_partitioned():
    """``device_refresh`` (deferred sync's sync point) must hand back
    PRE-partitioned state: its out shardings equal the update step's in
    shardings, so chained dispatches never reshard."""
    op = _mk(4, "host", "deferred")
    rng = np.random.default_rng(0)
    for i in range(3):
        k = rng.integers(0, 2000, 4096).astype(np.int64)
        op.process_batch(RecordBatch(
            {"k": k, "v": np.ones(4096, np.float32)},
            timestamps=np.full(4096, i * 300, np.int64)))
        op.process_watermark(Watermark(i * 300))
    assert op._device_stale
    assert op.verify_mirror()          # refresh + round-trip compare
    assert not op._device_stale
    assert len(op._leaves[0].sharding.device_set) == 4


def test_mesh_paging_invariance_64k_cap_256k_keys():
    """The PR-2 acceptance shape on the mesh: 256k keys through a 64k-row
    resident ring, digest- and counter-identical at mesh sizes 1 vs 2."""
    kw = dict(seed=5, n_batches=10, nk=1 << 18, B=1 << 15)
    ref, _, ref_counters = _run(
        _mk(1, paging=PagingConfig(capacity=1 << 16)), **kw)
    got, _, counters = _run(
        _mk(2, paging=PagingConfig(capacity=1 << 16)), **kw)
    assert got == ref
    assert counters == ref_counters
    # the key population genuinely exceeded the resident capacity
    assert ref_counters["paging"]["total_keys"] > 1 << 16


# ---------------------------------------------------------------------------
# snapshot rescale: N shards -> M shards, both directions
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("d_from,d_to", [(4, 2), (2, 4), (4, 1), (1, 4)])
def test_mesh_snapshot_rescales_between_mesh_sizes(d_from, d_to):
    _, snap, _ = _run(_mk(d_from), snap_at=3)
    assert snap is not None
    if d_from > 1:
        assert has_shard_slices(snap)
        man = slice_manifest(snap)
        assert [m["shard"] for m in man] == list(range(d_from))
        lo = 0
        for m in man:          # slices tile [0, n) in shard order
            assert m["row_range"][0] == lo
            lo = m["row_range"][1]
    # reference tail: restore at the WRITER's size and replay
    ref_op = _mk(d_from)
    ref_op.restore_state(snap)
    ref_tail, _, _ = _run(ref_op, seed=99, n_batches=3)
    # rescaled tail must be bit-identical
    op2 = _mk(d_to)
    op2.restore_state(snap)
    tail, _, _ = _run(op2, seed=99, n_batches=3)
    assert tail == ref_tail


@pytest.mark.parametrize("d_from,d_to", [(1, 2), (2, 1)])
def test_mesh_paged_snapshot_rescales(d_from, d_to):
    """Paged snapshots (dense gid-indexed: the gid space exceeds K_cap, so
    slices don't apply) restore across mesh sizes in both directions."""
    cap = PagingConfig(capacity=2048)
    kw = dict(seed=5, n_batches=6, nk=6000, B=1024)
    _, snap, _ = _run(_mk(d_from, paging=cap), snap_at=3, **kw)
    assert snap is not None and not has_shard_slices(snap)
    ref_op = _mk(d_from, paging=PagingConfig(capacity=2048))
    ref_op.restore_state(snap)
    ref_tail, _, _ = _run(ref_op, seed=99, n_batches=2, nk=6000, B=1024)
    op2 = _mk(d_to, paging=PagingConfig(capacity=2048))
    op2.restore_state(snap)
    tail, _, _ = _run(op2, seed=99, n_batches=2, nk=6000, B=1024)
    assert tail == ref_tail


def test_densify_round_trip_and_validation():
    layout = ShardLayout(4, 64)
    counts = np.arange(50 * 2, dtype=np.int32).reshape(50, 2)
    leaves = [np.random.default_rng(0).random((50, 2)).astype(np.float32)]
    from flink_tpu.state.shard_layout import split_to_shard_slices
    snap = split_to_shard_slices({"counts": counts, "leaves": leaves},
                                 layout)
    assert has_shard_slices(snap)
    dense = densify_keyed_snapshot(snap)
    assert np.array_equal(dense["counts"], counts)
    assert np.array_equal(dense["leaves"][0], leaves[0])
    # a tampered manifest (gap) fails loudly instead of silently dropping
    bad = dict(snap)
    bad["shard_slices"] = [s for s in snap["shard_slices"]
                           if s["shard"] != 1]
    with pytest.raises(ValueError, match="tile"):
        densify_keyed_snapshot(bad)


# ---------------------------------------------------------------------------
# compile-once: the pjit'd step never recompiles at fixed geometry
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("D", [2, 4])
def test_mesh_step_compiles_once_per_geometry(D):
    """Driving many batches of one geometry through the sharded step adds
    EXACTLY one compiled variant — an implicit reshard (out_shardings !=
    next in_shardings) or a geometry leak would mint more."""
    op = _mk(D, "device")
    if op.mesh_step_cache_size() < 0:
        pytest.skip("jax build without the jit cache probe")
    rng = np.random.default_rng(0)
    nk, B = 1500, 2048
    # insert every key first so K never grows mid-measurement
    warm_k = np.pad(np.arange(nk, dtype=np.int64), (0, B - nk),
                    mode="edge")
    op.process_batch(RecordBatch(
        {"k": warm_k, "v": np.zeros(B, np.float32)},
        timestamps=np.zeros(B, np.int64)))
    steady_k = rng.integers(0, nk, B).astype(np.int64)
    op.process_batch(RecordBatch(
        {"k": steady_k, "v": np.ones(B, np.float32)},
        timestamps=np.full(B, 10, np.int64)))
    size_after_warm = op.mesh_step_cache_size()
    for i in range(5):
        # random VALUES, fixed geometry and key set: the exchange capacity
        # high-water is already established, so zero recompiles are legal
        op.process_batch(RecordBatch(
            {"k": steady_k, "v": rng.random(B).astype(np.float32)},
            timestamps=np.full(B, 20 + i, np.int64)))
    assert op.mesh_step_cache_size() == size_after_warm, \
        "sharded update step recompiled at fixed geometry (reshard leak?)"


def test_mesh_per_shard_probe_breakdown_populated():
    """The host tier's fused probe reports per-shard wall times aligned
    with the mesh (the probe_mirror wall decomposed into D independent
    probes).  Requires the native mirror (sharded C pass)."""
    from flink_tpu.native import native_available
    if not native_available():
        pytest.skip("native library unavailable")
    op = _mk(2, "host")
    rng = np.random.default_rng(0)
    B = 1 << 15   # >= the C pass's parallel threshold
    for i in range(3):
        op.process_batch(RecordBatch(
            {"k": rng.integers(0, 5000, B).astype(np.int64),
             "v": np.ones(B, np.float32)},
            timestamps=np.full(B, i, np.int64)))
    op.flush_pipeline()
    assert "probe_mirror" in op.phase_shard_ns
    per_shard = op.phase_shard_ns["probe_mirror"]
    assert per_shard.size >= 2 and int(per_shard.sum()) > 0


# ---------------------------------------------------------------------------
# device-lane health on the mesh: whole-mesh degrade, bit-exact
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_mesh_quarantine_degrades_whole_mesh_bit_exactly():
    """PR-4's WedgedDevice nemesis at mesh size 2: a watchdog quarantine
    mid-run degrades the WHOLE mesh to the host tier (state materializes
    shard-by-shard into the host value mirror), fires continue without a
    dropped record, a checkpoint completes DURING quarantine, and the
    healed device re-promotes at the checkpoint-aligned safe point — with
    fire digests value-identical to an unfaulted pass (the degraded tier
    emits the mirror's f64 twins, so digests compare exact f64 sums, the
    PR-4 acceptance fingerprint)."""
    from flink_tpu.runtime import device_health as dh
    from flink_tpu.testing import chaos

    def vdigests(out):
        return [(int(np.asarray(b.column("window_start"))[0]), len(b),
                 np.asarray(b.column("k")).tobytes(),
                 float(np.asarray(b.column("result"), np.float64).sum()))
                for b in out if hasattr(b, "columns")
                and "result" in b.columns]

    def one_pass(inject):
        prev = dh.get_monitor(create=False)
        dh.set_monitor(dh.DeviceHealthMonitor(
            dh.WatchdogConfig(deadline_floor_s=0.5), heal_async=False))
        inj = chaos.FaultInjector(seed=3)
        sched = (inj.inject("device.dispatch", chaos.WedgedDevice(at=8))
                 if inject else None)
        op = _mk(2, "device")
        rng = np.random.default_rng(7)
        out = []
        snap_degraded = False
        try:
            with chaos.installed(inj):
                for i in range(24):
                    k = rng.integers(0, 64, 512).astype(np.int64)
                    v = np.ones(512, np.float32)
                    ts = i * 500 + np.sort(
                        rng.integers(0, 500, 512)).astype(np.int64)
                    out += op.process_batch(
                        RecordBatch({"k": k, "v": v}, timestamps=ts))
                    out += op.process_watermark(Watermark(int(ts.max()) - 1))
                    if inject and i == 12:
                        op.prepare_snapshot_pre_barrier()
                        snap = op.snapshot_state()
                        snap_degraded = op._degraded
                        assert "counts" in densify_keyed_snapshot(snap)
                        sched.heal()
                        dh.get_monitor().probe_now()
                    if inject and i == 16:
                        out += op.prepare_snapshot_pre_barrier()
                out += op.end_input()
            stats = op.device_health_stats()
            mon = dh.get_monitor().status()
            op.close()
        finally:
            dh.set_monitor(prev)
        return vdigests(out), stats, mon, snap_degraded

    clean, _, _, _ = one_pass(False)
    wedged, stats, mon, snap_degraded = one_pass(True)
    assert clean == wedged and len(clean) >= 10
    assert snap_degraded, "checkpoint during quarantine did not run degraded"
    assert mon["quarantines"] == 1 and mon["heals"] == 1
    assert stats["quarantine_migrations"] == 1
    assert stats["repromotions"] == 1 and stats["degraded"] == 0


@pytest.mark.slow
def test_mesh_1m_key_tumbling_sum_identical_to_single_chip():
    """The acceptance run at north-star cardinality: the sharded hot path
    at mesh size 2 produces fire digests BIT-identical to the single-chip
    run on the 1M-key tumbling sum."""
    kw = dict(seed=7, n_batches=12, nk=1 << 20, B=1 << 17)
    ref, _, ref_counters = _run(
        _mk(1, "host", initial_key_capacity=1 << 20), **kw)
    got, _, counters = _run(
        _mk(2, "host", initial_key_capacity=1 << 20), **kw)
    assert got == ref and len(ref) >= 5
    assert counters == ref_counters
    # ~1.57M draws over the 2^20 key space: ~0.8M distinct keys live
    assert ref_counters["num_keys"] > 800_000
