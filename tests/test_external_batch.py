"""Out-of-core batch runtime (VERDICT r1 missing #7): external merge sort
+ grace hash join — the ``ExternalSorter`` / ``MutableHashTable`` analogs
(``flink-runtime/.../operators/sort/``, ``operators/hash/``).

Tests force a TINY memory budget so the spill paths run on small data,
then assert results identical to the in-memory kernels.
"""

import numpy as np
import pytest

from flink_tpu.core.batch import RecordBatch
from flink_tpu.dataset.external import ExternalSorter, GraceHashJoin


def test_external_sort_many_runs_matches_inmemory():
    rng = np.random.default_rng(7)
    keys = rng.integers(0, 10_000, 50_000).astype(np.int64)
    vals = rng.random(50_000)
    s = ExternalSorter(["k"], budget_rows=3_000)   # ~17 spilled runs
    for lo in range(0, 50_000, 1_000):
        s.add(RecordBatch({"k": keys[lo:lo + 1_000],
                           "v": vals[lo:lo + 1_000]}))
    out = s.sorted_batch()
    got = np.asarray(out.column("k"))
    assert len(out) == 50_000
    np.testing.assert_array_equal(got, np.sort(keys))
    # payload stays aligned with its key: the (k, v) PAIR multiset is
    # preserved, not just each column's value multiset
    got_pairs = sorted(zip(got.tolist(),
                           np.asarray(out.column("v")).tolist()))
    want_pairs = sorted(zip(keys.tolist(), vals.tolist()))
    assert got_pairs == want_pairs


def test_external_sort_descending_and_streamed_batches():
    keys = np.arange(9_000, dtype=np.int64)
    s = ExternalSorter(["k"], ascending=False, budget_rows=2_000,
                       emit_batch_rows=1_000)
    s.add(RecordBatch({"k": keys}))
    chunks = list(s.merged())
    assert all(len(c) <= 1_000 for c in chunks)
    got = np.concatenate([np.asarray(c.column("k")) for c in chunks])
    np.testing.assert_array_equal(got, keys[::-1])


def test_external_sort_in_memory_tail_only():
    s = ExternalSorter(["k"], budget_rows=1_000_000)
    s.add(RecordBatch({"k": np.array([3, 1, 2], np.int64)}))
    out = s.sorted_batch()
    assert np.asarray(out.column("k")).tolist() == [1, 2, 3]


def test_grace_hash_join_matches_inmemory():
    from flink_tpu.operators.joins import _join_pairs

    rng = np.random.default_rng(9)
    lk = rng.integers(0, 500, 20_000).astype(np.int64)
    rk = rng.integers(0, 500, 5_000).astype(np.int64)
    gj = GraceHashJoin("k", "k", budget_rows=4_000)  # forces bucketing
    gj.add(0, RecordBatch({"k": lk, "lv": np.arange(20_000)}))
    gj.add(1, RecordBatch({"k": rk, "rv": np.arange(5_000)}))
    pairs = []
    for lb, li, rb, ri in gj.join_pairs():
        lks = np.asarray(lb.column("k"))[li]
        lvs = np.asarray(lb.column("lv"))[li]
        rvs = np.asarray(rb.column("rv"))[ri]
        assert (lks == np.asarray(rb.column("k"))[ri]).all()
        pairs.extend(zip(lvs.tolist(), rvs.tolist()))
    li0, ri0 = _join_pairs(lk, rk)
    want = sorted(zip(li0.tolist(), ri0.tolist()))
    assert sorted(pairs) == want


def test_dataset_sort_and_join_use_spill_paths(monkeypatch):
    """The dataset drivers switch to the out-of-core paths above the
    budget; results stay identical to the in-memory kernels."""
    from flink_tpu.dataset.api import ExecutionEnvironment

    rng = np.random.default_rng(3)
    n = 30_000
    keys = rng.integers(0, 2_000, n).astype(np.int64)

    def run():
        env = ExecutionEnvironment()
        ds = env.from_columns({"k": keys, "v": np.arange(n)})
        sorted_rows = ds.sort_partition("k").collect()
        other = env.from_columns({"k": np.arange(0, 2_000, 2),
                                  "w": np.arange(1_000)})
        joined = (env.from_columns({"k": keys, "v": np.arange(n)})
                  .join(other).where("k").equal_to("k").apply().collect())
        return sorted_rows, joined

    in_mem_sorted, in_mem_joined = run()
    monkeypatch.setenv("FLINK_TPU_BATCH_MEMORY_ROWS", "4000")
    sp_sorted, sp_joined = run()
    assert [r["k"] for r in sp_sorted] == [r["k"] for r in in_mem_sorted]
    key_of = lambda r: tuple(sorted(r.items()))  # noqa: E731
    assert sorted(map(key_of, sp_joined)) == sorted(map(key_of,
                                                        in_mem_joined))


def test_grace_hash_join_aliasing_and_skew():
    """Regression: reuse after join_pairs() must not alias sides; a hot key
    (unsplittable skew) still joins correctly via recursive repartition's
    depth cap."""
    from flink_tpu.operators.joins import _join_pairs

    lk = np.zeros(9_000, np.int64)              # ONE hot key
    rk = np.zeros(50, np.int64)
    gj = GraceHashJoin("k", "k", budget_rows=1_000)
    gj.add(0, RecordBatch({"k": lk, "lv": np.arange(9_000)}))
    gj.add(1, RecordBatch({"k": rk, "rv": np.arange(50)}))
    n_pairs = sum(len(li) for _l, li, _r, _ri in gj.join_pairs())
    assert n_pairs == 9_000 * 50
    # reuse: sides must be independent lists
    gj.add(0, RecordBatch({"k": np.array([1], np.int64),
                           "lv": np.array([0])}))
    assert len(gj._right) == 0


def test_external_sort_string_keys_fall_back_to_rowheap():
    s = ExternalSorter(["k"], budget_rows=100)
    words = np.asarray([f"w{i:03d}" for i in range(500)][::-1], object)
    for lo in range(0, 500, 50):
        s.add(RecordBatch({"k": words[lo:lo + 50]}))
    out = s.sorted_batch()
    got = [str(x) for x in np.asarray(out.column("k"))]
    assert got == sorted(str(w) for w in words)


def test_external_sort_descending_uint64_and_int64_min():
    """Regression: the descending gallop merge must not negate keys
    (uint64 overflow; INT64_MIN wraparound)."""
    vals = np.array([5, 2, 9, 2**63 + 7, 0, 13], np.uint64)
    s = ExternalSorter(["k"], ascending=False, budget_rows=2)
    for v in vals:
        s.add(RecordBatch({"k": np.array([v], np.uint64)}))
    out = np.asarray(s.sorted_batch().column("k"))
    np.testing.assert_array_equal(out, np.sort(vals)[::-1])

    imin = np.iinfo(np.int64).min
    vals2 = np.array([3, imin, 7, -5], np.int64)
    s2 = ExternalSorter(["k"], ascending=False, budget_rows=2)
    for v in vals2:
        s2.add(RecordBatch({"k": np.array([v], np.int64)}))
    out2 = np.asarray(s2.sorted_batch().column("k"))
    np.testing.assert_array_equal(out2, np.sort(vals2)[::-1])


def test_grace_join_fast_path_resets_and_cleans(tmp_path):
    import glob
    import tempfile

    gj = GraceHashJoin("k", "k", budget_rows=1_000_000)
    gj.add(0, RecordBatch({"k": np.array([1], np.int64)}))
    gj.add(1, RecordBatch({"k": np.array([1], np.int64)}))
    assert sum(len(li) for _l, li, _r, _ri in gj.join_pairs()) == 1
    # fast path resets sides (reuse must not re-join stale inputs)
    assert gj._left == [] and gj._right == [] and gj._rows == [0, 0]
